"""Device batched optimal-ate pairing for BLS12-381 — the north-star
kernel: N Miller loops run data-parallel over the set axis, their product
tree-reduces on device, and ONE final exponentiation (host native, a
single Fq12 predicate) yields the batch verdict.

This is the TPU-shaped decomposition of `verify_signature_sets`'
N+1-pairing product (crypto/bls.py): the O(N·bits) Miller work — line
evaluations, sparse Fq12 multiplies, accumulator doubling — is
embarrassingly data-parallel across pairs and runs as ONE jitted scan
over the 63 static bits of |x| (add steps fire under `lax.cond` on the
static bit pattern — no data-dependent control flow). The O(1)
exponentiation that follows is scalar, branchy, and latency-bound — the
wrong shape for the device — so it stays on the native C++ backend
(bls12_381.cpp final_exp_for_verdict) behind a 576-byte Fq12 handoff.

Formulas mirror native/bls12_381.cpp's fused Miller steps (same line
slots, same subfield scaling killed by the final exponentiation), so
device and native Miller values agree exactly on canonical export — the
parity anchor in tests/test_ops_pairing.py. Field arithmetic is the
bound-tracked lazy layer (ops/fql.py): all correctness-critical
column/value bounds are asserted at trace time.

Reference role: blst's pairing engine under crypto/bls.rs (C6); design
per SURVEY.md §2.5 (batch axes as mesh axes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import device as _obs
from . import fq2, fq12, fql
from .fql import LV

__all__ = [
    "BLS_X_ABS",
    "g1_affine_from_raw",
    "g2_affine_from_raw",
    "miller_loop_batched",
    "fp12_product",
    "miller_product_device",
    "g2_sum_points",
    "g1_mul_batched",
    "g2_mul_batched",
    "batch_verify_device",
    "finalize_verdict",
]

BLS_X_ABS = 0xD201000000010000
# bits below the MSB, MSB-first — the static Miller schedule
_X_BITS = np.array([int(b) for b in bin(BLS_X_ABS)[3:]], dtype=np.bool_)

# scan/tree carry envelopes (trace-time asserted fixpoints)
_ENV_V = 1 << 392
_ENV_C = 1 << 26


def _env(arr) -> LV:
    return LV(arr, _ENV_V, _ENV_C)


def _clamp(a: LV):
    return fql.lv_assert_within(a, _ENV_V, _ENV_C).arr


# ---------------------------------------------------------------------------
# marshalling (raw affine big-endian bytes <-> R'-Montgomery columns)
# ---------------------------------------------------------------------------

def g1_affine_from_raw(raws: "list[bytes]") -> tuple[LV, LV]:
    """Affine raw96 G1 points → ((N, 24), (N, 24)) R'-Montgomery x, y.
    Callers must exclude infinity (the Miller loop skips such pairs)."""
    n = len(raws)
    words = np.frombuffer(b"".join(raws), dtype=">u2").reshape(n, 48)
    x = np.ascontiguousarray(words[:, :24][:, ::-1]).astype(np.uint64)
    y = np.ascontiguousarray(words[:, 24:][:, ::-1]).astype(np.uint64)
    xy = fql.to_mont_device(
        _obs.h2d("ops.pairing.g1_affine_from_raw", np.concatenate([x, y]))
    )
    return fql.lv_canon(xy[:n]), fql.lv_canon(xy[n:])


def g2_affine_from_raw(raws: "list[bytes]") -> tuple[LV, LV]:
    """Affine raw192 G2 points (x.c0||x.c1||y.c0||y.c1, 48-byte BE each,
    the native backend's format) → ((N, 2, 24), (N, 2, 24)) LVs."""
    n = len(raws)
    words = np.frombuffer(b"".join(raws), dtype=">u2").reshape(n, 4, 24)
    limbs = np.ascontiguousarray(words[:, :, ::-1]).astype(np.uint64)
    m = fql.to_mont_device(
        _obs.h2d("ops.pairing.g2_affine_from_raw", limbs.reshape(n * 4, 24))
    ).reshape(n, 4, 24)
    x = fql.lv_canon(jnp.stack([m[:, 0], m[:, 1]], axis=-2))
    y = fql.lv_canon(jnp.stack([m[:, 2], m[:, 3]], axis=-2))
    return x, y


# ---------------------------------------------------------------------------
# G1 point arithmetic on the lazy field (Jacobian, branchless)
# ---------------------------------------------------------------------------

def _fq_comp(p: LV, i: int) -> LV:
    return LV(p.arr[..., i, :], p.vmax, p.cmax)


def _g1_pack(x: LV, y: LV, z: LV) -> LV:
    return LV(
        jnp.stack([x.arr, y.arr, z.arr], axis=-2),
        max(x.vmax, y.vmax, z.vmax),
        max(x.cmax, y.cmax, z.cmax),
    )


def _fq_is_zero(a: LV):
    return fql.is_zero_any(a.arr)


def _lv_row(t: LV, k: int) -> LV:
    return LV(t.arr[k], t.vmax, t.cmax)


def _g1_double(p: LV) -> LV:
    """dbl-2009-l over the lazy scalar field; infinity (z ≡ 0) stays
    infinity through the algebra (z3 = 2yz ≡ 0)."""
    x, y, z = (_fq_comp(p, i) for i in range(3))
    s = fql.lv_mont(fql.lv_stack([x, y, z]), fql.lv_stack([x, y, z]))
    a, b, zz = _lv_row(s, 0), _lv_row(s, 1), _lv_row(s, 2)  # x², y², z²
    s2 = fql.lv_mont(
        fql.lv_stack([b, fql.lv_add(x, b), y]),
        fql.lv_stack([b, fql.lv_add(x, b), z]),
    )
    c, xb2, yz = _lv_row(s2, 0), _lv_row(s2, 1), _lv_row(s2, 2)
    d = fql.lv_sub(fql.lv_sub(xb2, a), c)
    d = fql.lv_add(d, d)
    e = fql.lv_add(fql.lv_add(a, a), a)
    f = fql.lv_mont(e, e)
    x3 = fql.lv_sub(f, fql.lv_add(d, d))
    c8 = fql.lv_add(c, c)
    c8 = fql.lv_add(c8, c8)
    c8 = fql.lv_add(c8, c8)
    y3m = fql.lv_mont(e, fql.lv_sub(d, x3))
    y3 = fql.lv_sub(y3m, c8)
    z3 = fql.lv_add(yz, yz)
    return _g1_pack(x3, y3, z3)


def _g1_add(p: LV, q: LV) -> LV:
    """Branchless add-2007-bl with infinity / P==Q / P==-Q selects."""
    x1, y1, z1 = (_fq_comp(p, i) for i in range(3))
    x2, y2, z2 = (_fq_comp(q, i) for i in range(3))
    s = fql.lv_mont(fql.lv_stack([z1, z2]), fql.lv_stack([z1, z2]))
    z1z1, z2z2 = _lv_row(s, 0), _lv_row(s, 1)
    s = fql.lv_mont(
        fql.lv_stack([x1, x2, y1, y2]),
        fql.lv_stack([z2z2, z1z1, z2, z1]),
    )
    u1, u2, s1p, s2p = (_lv_row(s, i) for i in range(4))
    s = fql.lv_mont(fql.lv_stack([s1p, s2p]), fql.lv_stack([z2z2, z1z1]))
    s1, s2 = _lv_row(s, 0), _lv_row(s, 1)
    h = fql.lv_sub(u2, u1)
    r = fql.lv_sub(s2, s1)
    h_zero = _fq_is_zero(h)
    r_zero = _fq_is_zero(r)
    hh = fql.lv_add(h, h)
    s = fql.lv_mont(fql.lv_stack([hh, z1]), fql.lv_stack([hh, z2]))
    i4, zz = _lv_row(s, 0), _lv_row(s, 1)  # (2h)², z1z2
    s = fql.lv_mont(fql.lv_stack([h, u1]), fql.lv_stack([i4, i4]))
    j, v = _lv_row(s, 0), _lv_row(s, 1)
    r2 = fql.lv_add(r, r)
    s = fql.lv_mont(
        fql.lv_stack([r2, s1, fql.lv_add(zz, zz)]),
        fql.lv_stack([r2, j, h]),
    )
    r2sq, s1j, z3 = _lv_row(s, 0), _lv_row(s, 1), _lv_row(s, 2)
    x3 = fql.lv_sub(fql.lv_sub(r2sq, j), fql.lv_add(v, v))
    y3m = fql.lv_mont(r2, fql.lv_sub(v, x3))
    y3 = fql.lv_sub(y3m, fql.lv_add(s1j, s1j))
    added = _g1_pack(x3, y3, z3)

    doubled = _g1_double(p)
    p_inf = _fq_is_zero(z1)
    q_inf = _fq_is_zero(z2)
    both = ~p_inf & ~q_inf
    same = both & h_zero & r_zero
    negat = both & h_zero & ~r_zero

    sel = lambda m: m[..., None, None]  # noqa: E731
    out = added.arr
    out = jnp.where(sel(same), doubled.arr, out)
    out = jnp.where(sel(negat), jnp.zeros_like(out), out)
    out = jnp.where(sel(p_inf), q.arr, out)
    out = jnp.where(sel(q_inf), p.arr, out)
    vmax = max(added.vmax, doubled.vmax, p.vmax, q.vmax)
    cmax = max(added.cmax, doubled.cmax, p.cmax, q.cmax)
    return LV(out, vmax, cmax)


# ---------------------------------------------------------------------------
# G2 point arithmetic over fq2 (Jacobian, branchless)
# ---------------------------------------------------------------------------

def _g2_comp(p: LV, i: int) -> LV:
    return LV(p.arr[..., i, :, :], p.vmax, p.cmax)


def _g2_pack(x: LV, y: LV, z: LV) -> LV:
    return LV(
        jnp.stack([x.arr, y.arr, z.arr], axis=-3),
        max(x.vmax, y.vmax, z.vmax),
        max(x.cmax, y.cmax, z.cmax),
    )


def g2_point_double(p: LV) -> LV:
    x, y, z = (_g2_comp(p, i) for i in range(3))
    a, b, zz = fq2.square_many([x, y, z])
    c, xb2 = fq2.square_many([b, fq2.add(x, b)])
    d = fq2.sub(fq2.sub(xb2, a), c)
    d = fq2.add(d, d)
    e = fq2.add(fq2.add(a, a), a)
    f, = fq2.square_many([e])
    x3 = fq2.sub(f, fq2.add(d, d))
    c8 = fq2.dbl(fq2.dbl(fq2.dbl(c)))
    em, yzm = fq2.mul_many([(e, fq2.sub(d, x3)), (y, z)])
    y3 = fq2.sub(em, c8)
    z3 = fq2.add(yzm, yzm)
    return _g2_pack(x3, y3, z3)


def g2_point_add(p: LV, q: LV) -> LV:
    x1, y1, z1 = (_g2_comp(p, i) for i in range(3))
    x2, y2, z2 = (_g2_comp(q, i) for i in range(3))
    z1z1, z2z2 = fq2.square_many([z1, z2])
    u1, u2, s1p, s2p = fq2.mul_many(
        [(x1, z2z2), (x2, z1z1), (y1, z2), (y2, z1)]
    )
    s1, s2 = fq2.mul_many([(s1p, z2z2), (s2p, z1z1)])
    h = fq2.sub(u2, u1)
    r = fq2.sub(s2, s1)
    h_zero = fq2.is_zero(h)
    r_zero = fq2.is_zero(r)
    hh = fq2.add(h, h)
    i4, = fq2.square_many([hh])
    j, v, zz = fq2.mul_many([(h, i4), (u1, i4), (z1, z2)])
    r2 = fq2.add(r, r)
    r2sq, = fq2.square_many([r2])
    s1j, z3 = fq2.mul_many([(s1, j), (fq2.add(zz, zz), h)])
    x3 = fq2.sub(fq2.sub(r2sq, j), fq2.add(v, v))
    y3m, = fq2.mul_many([(r2, fq2.sub(v, x3))])
    y3 = fq2.sub(y3m, fq2.add(s1j, s1j))
    added = _g2_pack(x3, y3, z3)

    doubled = g2_point_double(p)
    p_inf = fq2.is_zero(z1)
    q_inf = fq2.is_zero(z2)
    both = ~p_inf & ~q_inf
    same = both & h_zero & r_zero
    negat = both & h_zero & ~r_zero

    sel = lambda m: m[..., None, None, None]  # noqa: E731
    out = added.arr
    out = jnp.where(sel(same), doubled.arr, out)
    out = jnp.where(sel(negat), jnp.zeros_like(out), out)
    out = jnp.where(sel(p_inf), q.arr, out)
    out = jnp.where(sel(q_inf), p.arr, out)
    vmax = max(added.vmax, doubled.vmax, p.vmax, q.vmax)
    cmax = max(added.cmax, doubled.cmax, p.cmax, q.cmax)
    return LV(out, vmax, cmax)


@functools.partial(jax.jit, static_argnames=("levels",))
def _g2_tree_reduce(points, levels: int):
    """(2^levels, 3, 2, 24) → (3, 2, 24) XOR-fold point sum (one compile
    for all levels — same trick as ops/g1._tree_reduce)."""
    width = points.shape[0]
    idx = jnp.arange(width)

    def level(k, pts):
        bit = jnp.left_shift(jnp.int32(1), k)
        summed = g2_point_add(_env(pts), _env(pts[idx ^ bit]))
        keep = (idx & bit) == 0
        return jnp.where(
            keep[:, None, None, None], _clamp(summed), jnp.zeros_like(pts)
        )

    return jax.lax.fori_loop(0, levels, level, points)[0]


def g2_sum_points(points: LV) -> LV:
    """Sum an (N, 3, 2, 24) batch of Jacobian G2 points on device."""
    n = points.arr.shape[0]
    width = 1 << (n - 1).bit_length() if n > 1 else 1
    arr = points.arr
    if width != n:
        pad = jnp.zeros((width - n, 3, 2, 24), jnp.uint64)
        arr = jnp.concatenate([arr, pad], axis=0)
    return _env(_g2_tree_reduce(arr, (width - 1).bit_length()))


# ---------------------------------------------------------------------------
# batched scalar multiplication (per-element scalars — the RLC blinders)
# ---------------------------------------------------------------------------

def _scalars_to_bits(scalars: "list[int]", bits: int) -> np.ndarray:
    out = np.zeros((len(scalars), bits), dtype=np.bool_)
    for i, s in enumerate(scalars):
        for b in range(bits):
            out[i, b] = (s >> (bits - 1 - b)) & 1
    return out


@jax.jit
def _mul_scan_g1(points, bits):  # observed below
    """points (N, 3, 24) Jacobian, bits (N, B) MSB-first →
    (N, 3, 24) [scalar]·P, double-and-add with per-element selects."""
    acc0 = jnp.zeros_like(points)

    def step(acc, bit_col):
        a = _g1_double(_env(acc))
        added = _g1_add(a, _env(points))
        out = jnp.where(bit_col[:, None, None], _clamp(added), _clamp(a))
        return out, None

    acc, _ = jax.lax.scan(step, acc0, jnp.moveaxis(bits, 1, 0))
    return acc


@jax.jit
def _mul_scan_g2(points, bits):  # observed below
    acc0 = jnp.zeros_like(points)

    def step(acc, bit_col):
        a = g2_point_double(_env(acc))
        added = g2_point_add(a, _env(points))
        out = jnp.where(bit_col[:, None, None, None], _clamp(added), _clamp(a))
        return out, None

    acc, _ = jax.lax.scan(step, acc0, jnp.moveaxis(bits, 1, 0))
    return acc


_mul_scan_g1 = _obs.observe_jit(_mul_scan_g1, "ops.pairing._mul_scan_g1")
_mul_scan_g2 = _obs.observe_jit(_mul_scan_g2, "ops.pairing._mul_scan_g2")


def g1_mul_batched(points: LV, scalars: "list[int]", bits: int = 128) -> LV:
    """(N, 3, 24) Jacobian × per-element scalars → (N, 3, 24)."""
    return _env(_mul_scan_g1(points.arr, jnp.asarray(_scalars_to_bits(scalars, bits))))


def g2_mul_batched(points: LV, scalars: "list[int]", bits: int = 128) -> LV:
    return _env(_mul_scan_g2(points.arr, jnp.asarray(_scalars_to_bits(scalars, bits))))


# ---------------------------------------------------------------------------
# the batched Miller loop
# ---------------------------------------------------------------------------

def _double_step(f: LV, t: LV, xp: LV, yp: LV):
    """Fused tangent-line + doubling (bls12_381.cpp miller_double_step)."""
    x, y, z = (_g2_comp(t, i) for i in range(3))
    a, b, zz = fq2.square_many([x, y, z])
    c, xb2 = fq2.square_many([b, fq2.add(x, b)])
    z3c, x3c, yz = fq2.mul_many([(zz, z), (a, x), (y, z)])
    line_l = fq2.dbl(fq2.mul_many([(y, z3c)])[0])
    e = fq2.add(fq2.add(a, a), a)
    ez2, = fq2.mul_many([(e, zz)])
    c00 = fq2.neg(fq2.mul_by_xi(fq2.scalar_mul(line_l, yp)))
    c11 = fq2.sub(fq2.dbl(b), fq2.add(fq2.add(x3c, x3c), x3c))
    c12 = fq2.scalar_mul(ez2, xp)
    f = fq12.fp12_mul_by_line(f, c00, c11, c12)
    # T ← 2T reusing a, b, c, e
    d = fq2.sub(fq2.sub(xb2, a), c)
    d = fq2.add(d, d)
    fsq, = fq2.square_many([e])
    x3 = fq2.sub(fsq, fq2.add(d, d))
    c8 = fq2.dbl(fq2.dbl(fq2.dbl(c)))
    em, = fq2.mul_many([(e, fq2.sub(d, x3))])
    y3 = fq2.sub(em, c8)
    z3 = fq2.add(yz, yz)
    return f, _g2_pack(x3, y3, z3)


def _add_step(f: LV, t: LV, xp: LV, yp: LV, xq: LV, yq: LV):
    """Fused secant-line + mixed addition (bls12_381.cpp miller_add_step).
    T == ±Q is unreachable inside the loop (T = [k]Q, 1 < k << r)."""
    x, y, z = (_g2_comp(t, i) for i in range(3))
    z2, = fq2.square_many([z])
    z3c, u2 = fq2.mul_many([(z2, z), (xq, z2)])
    s2, = fq2.mul_many([(yq, z3c)])
    lam_n = fq2.sub(y, s2)
    lam_d, = fq2.mul_many([(fq2.sub(x, u2), z)])
    c00 = fq2.neg(fq2.mul_by_xi(fq2.scalar_mul(lam_d, yp)))
    t1m, t2m = fq2.mul_many([(yq, lam_d), (lam_n, xq)])
    c11 = fq2.sub(t1m, t2m)
    c12 = fq2.scalar_mul(lam_n, xp)
    f = fq12.fp12_mul_by_line(f, c00, c11, c12)
    # T ← T + Q (madd-2007-bl) reusing z2, z3c, u2, s2
    h = fq2.sub(u2, x)
    hh, = fq2.square_many([h])
    i4 = fq2.dbl(fq2.dbl(hh))
    j, v = fq2.mul_many([(h, i4), (x, i4)])
    rr = fq2.dbl(fq2.sub(s2, y))
    rrsq, zh2 = fq2.square_many([rr, fq2.add(z, h)])
    x3 = fq2.sub(fq2.sub(rrsq, j), fq2.dbl(v))
    ym, yj = fq2.mul_many([(rr, fq2.sub(v, x3)), (y, j)])
    y3 = fq2.sub(ym, fq2.dbl(yj))
    z3 = fq2.sub(fq2.sub(zh2, z2), hh)
    return f, _g2_pack(x3, y3, z3)


@jax.jit
def miller_loop_batched(xp, yp, xq, yq):
    """N Miller loops f_{|x|,Q_i}(P_i), conjugated for the negative BLS x.

    xp, yp: (N, 24) R'-Montgomery G1 affine; xq, yq: (N, 2, 24) G2
    affine (raw arrays — mont outputs). Returns a raw (N, 2, 3, 2, 24)
    Fq12 batch whose canonical export is bit-identical to the native
    backend's per-pair Miller values."""
    n = xp.shape[0]
    xp_lv, yp_lv = fql.lv_canon(xp), fql.lv_canon(yp)
    xq_lv, yq_lv = fql.lv_canon(xq), fql.lv_canon(yq)
    f0 = fq12.fp12_one((n,))
    one2 = jnp.broadcast_to(
        jnp.asarray(np.stack([fql.to_mont_cols(1), np.zeros(24, np.uint64)])),
        yq.shape,
    )
    t0 = jnp.stack([xq, yq, one2], axis=-3)

    def step(carry, bit):
        f_arr, t_arr = carry
        f, t = _env(f_arr), _env(t_arr)
        f = fq12.fp12_sqr(f)
        f, t = _double_step(f, t, xp_lv, yp_lv)

        def with_add(args):
            fa, ta = args
            f2, t2 = _add_step(_env(fa), _env(ta), xp_lv, yp_lv, xq_lv, yq_lv)
            return _clamp(f2), _clamp(t2)

        f_arr, t_arr = jax.lax.cond(
            bit, with_add, lambda args: args, (_clamp(f), _clamp(t))
        )
        return (f_arr, t_arr), None

    (f_arr, _), _ = jax.lax.scan(step, (f0.arr, t0), jnp.asarray(_X_BITS))
    return fq12.fp12_conj(_env(f_arr)).arr


@functools.partial(jax.jit, static_argnames=("levels",))
def _fp12_tree(fs, levels: int):
    """(2^levels, 2, 3, 2, 24) → (2, 3, 2, 24) XOR-fold product."""
    width = fs.shape[0]
    idx = jnp.arange(width)
    one = fq12.fp12_one((width,)).arr

    def level(k, vals):
        bit = jnp.left_shift(jnp.int32(1), k)
        prod = fq12.fp12_mul(_env(vals), _env(vals[idx ^ bit]))
        keep = (idx & bit) == 0
        return jnp.where(keep[:, None, None, None, None], _clamp(prod), one)

    return jax.lax.fori_loop(0, levels, level, fs)[0]


miller_loop_batched = _obs.observe_jit(
    miller_loop_batched, "ops.pairing.miller_loop_batched"
)


def fp12_product(fs) -> jax.Array:
    """Product of an (N, 2, 3, 2, 24) raw batch of Fq12 values."""
    n = fs.shape[0]
    if n == 1:
        return fs[0]
    width = 1 << (n - 1).bit_length()
    if width != n:
        fs = jnp.concatenate([fs, fq12.fp12_one((width - n,)).arr], axis=0)
    return _fp12_tree(fs, (width - 1).bit_length())


_CHUNK = 8192  # pairs per device dispatch (bounds peak HBM for the f batch)


def _generator_raws() -> "tuple[bytes, bytes]":
    from ..native import bls as native_bls

    return native_bls.g1_generator_raw(), native_bls.g2_generator_raw()


def _pad_pow2(items: list, filler) -> list:
    n = len(items)
    width = 1 << (n - 1).bit_length() if n > 1 else 1
    return items + [filler] * (width - n)


def miller_product_device(g1_raws: "list[bytes]", g2_raws: "list[bytes]") -> "list[int]":
    """Π_i miller(P_i, Q_i) over raw affine inputs, as 12 canonical-int
    Fq12 coefficients (the native backend's final-exp handoff format).
    Inputs must be finite points (callers skip infinity pairs).

    Batches are padded to the next power of two with generator pairs —
    the padding lanes' Miller values are sliced off before the product —
    so the jitted kernels compile for at most log2(_CHUNK) shapes instead
    of one shape per distinct set count."""
    assert len(g1_raws) == len(g2_raws) and g1_raws
    n_total = len(g1_raws)
    g1f, g2f = _generator_raws()
    chunks = []
    for lo in range(0, n_total, _CHUNK):
        g1c = g1_raws[lo:lo + _CHUNK]
        g2c = g2_raws[lo:lo + _CHUNK]
        n = len(g1c)
        xp, yp = g1_affine_from_raw(_pad_pow2(g1c, g1f))
        xq, yq = g2_affine_from_raw(_pad_pow2(g2c, g2f))
        fs = miller_loop_batched(xp.arr, yp.arr, xq.arr, yq.arr)[:n]
        chunks.append(fp12_product(fs))
    total = fp12_product(jnp.stack(chunks)) if len(chunks) > 1 else chunks[0]
    return fq12.fp12_to_ints(total)


# ---------------------------------------------------------------------------
# the full RLC batch verdict, device-shaped
# ---------------------------------------------------------------------------

@jax.jit
def _g1_jacobian_to_affine(jac):
    """(N, 3, 24) Jacobian raw columns → ((N, 24), (N, 24)) affine; one
    batched Fermat inversion scan. Callers exclude infinity."""
    # canonicalize z so the inversion scan carries stay mont outputs
    z = fql.mont(jac[..., 2, :], jnp.asarray(fql._ONE_COLS))
    z = fql.mont(z, jnp.asarray(fql.R2_COLS))
    zinv = fq2.fq_inv_raw(z)
    zinv2 = fql.mont(zinv, zinv)
    x = fql.mont(jac[..., 0, :], zinv2)
    y = fql.mont(jac[..., 1, :], fql.mont(zinv2, zinv))
    return x, y


def _g2_point_to_raw(point: LV) -> "tuple[bytes, bool]":
    """One (3, 2, 24) Jacobian G2 LV → (raw192 affine, is_inf); the O(1)
    affine conversion runs host-side big-int."""
    canon = np.asarray(point.arr).reshape(3, 2, 24)
    x0, x1 = fq2.from_lv_ints(fql.lv_canon(jnp.asarray(canon[0])))
    y0, y1 = fq2.from_lv_ints(fql.lv_canon(jnp.asarray(canon[1])))
    z0, z1 = fq2.from_lv_ints(fql.lv_canon(jnp.asarray(canon[2])))
    if z0 == 0 and z1 == 0:
        return b"\x00" * 192, True
    p = fql.P_INT
    norm_inv = pow((z0 * z0 + z1 * z1) % p, -1, p)
    zi0, zi1 = (z0 * norm_inv) % p, (-z1 * norm_inv) % p
    s0 = (zi0 * zi0 - zi1 * zi1) % p
    s1 = (2 * zi0 * zi1) % p
    c0 = (s0 * zi0 - s1 * zi1) % p
    c1 = (s0 * zi1 + s1 * zi0) % p
    ax0 = (x0 * s0 - x1 * s1) % p
    ax1 = (x0 * s1 + x1 * s0) % p
    ay0 = (y0 * c0 - y1 * c1) % p
    ay1 = (y0 * c1 + y1 * c0) % p
    return (ax0.to_bytes(48, "big") + ax1.to_bytes(48, "big")
            + ay0.to_bytes(48, "big") + ay1.to_bytes(48, "big")), False


_NEG_G1_GEN_RAW = None


def _neg_g1_generator_raw() -> bytes:
    global _NEG_G1_GEN_RAW
    if _NEG_G1_GEN_RAW is None:
        from ..native import bls as native_bls

        raw = native_bls.g1_generator_raw()
        x = int.from_bytes(raw[:48], "big")
        y = (fql.P_INT - int.from_bytes(raw[48:], "big")) % fql.P_INT
        _NEG_G1_GEN_RAW = x.to_bytes(48, "big") + y.to_bytes(48, "big")
    return _NEG_G1_GEN_RAW


def _g1_jac_from_affine_raws(raws: "list[bytes]") -> LV:
    x, y = g1_affine_from_raw(raws)
    one = jnp.broadcast_to(jnp.asarray(fql.to_mont_cols(1)), x.arr.shape)
    return _env(jnp.stack([x.arr, y.arr, one], axis=-2))


# ---------------------------------------------------------------------------
# lazy-field G1 set aggregation (the verify_signature_sets batch boundary)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("levels",))
def _g1_tree_reduce_segmented(points, levels: int):
    """(S, 2^levels, 3, 24) → (S, 3, 24): the XOR-fold point sum along
    axis 1 over the LAZY field — S independent aggregations in one
    program. The fast-compiling twin of ops/g1._tree_reduce_segmented:
    the strict-field fold costs ~130s of cold XLA compile (its
    compare-and-subtract canonicalization chains are what fql exists to
    avoid); this one reuses the pairing's lazy adds and compiles in
    seconds."""
    width = points.shape[1]
    idx = jnp.arange(width)

    def level(k, pts):
        bit = jnp.left_shift(jnp.int32(1), k)
        summed = _g1_add(_env(pts), _env(pts[:, idx ^ bit]))
        keep = (idx & bit) == 0
        return jnp.where(
            keep[None, :, None, None], _clamp(summed), jnp.zeros_like(pts)
        )

    return jax.lax.fori_loop(0, levels, level, points)[:, 0]


def g1_sum_sets(
    raw_sets: "list[list[bytes]]", sharding=None
) -> "list[tuple[bytes, bool]]":
    """S independent G1 point sums on device over the lazy field:
    raw96 affine inputs (all-zero = infinity), (raw96, is_inf) outputs.
    Sets pad to the widest set (power of two) with infinity lanes; pass
    ``sharding`` (a NamedSharding over the set axis) to distribute the
    batch over a mesh before the fold."""
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)
    if not raw_sets:
        return []
    widest = max(max(len(s) for s in raw_sets), 1)
    width = 1 << (widest - 1).bit_length() if widest > 1 else 1
    flat: list[bytes] = []
    live = np.zeros((len(raw_sets), width), np.bool_)
    for i, s in enumerate(raw_sets):
        flat.extend(s)
        flat.extend([b"\x00" * 96] * (width - len(s)))
        for j, raw in enumerate(s):
            live[i, j] = any(raw)
    x, y = g1_affine_from_raw(flat)
    one = np.asarray(fql.to_mont_cols(1))
    z = jnp.asarray(
        live.reshape(-1)[:, None] * one[None, :]
    )  # z=1 live, z=0 infinity
    batch = jnp.stack([x.arr, y.arr, z], axis=-2).reshape(
        len(raw_sets), width, 3, 24
    )
    if sharding is not None:
        (batch,) = _obs.h2d_put("ops.pairing.g1_sum_sets", (batch,), sharding)
    sums = _g1_tree_reduce_segmented(batch, (width - 1).bit_length())
    # host export: R'-Montgomery columns → canonical ints → affine bytes
    ints = fql.from_mont_ints(np.asarray(sums).reshape(len(raw_sets) * 3, 24))
    out: "list[tuple[bytes, bool]]" = []
    p = fql.P_INT
    for s in range(len(raw_sets)):
        xi, yi, zi = ints[3 * s], ints[3 * s + 1], ints[3 * s + 2]
        if zi == 0:
            out.append((b"\x00" * 96, True))
            continue
        z_inv = pow(zi, -1, p)
        z2 = (z_inv * z_inv) % p
        ax = (xi * z2) % p
        ay = (yi * z2 * z_inv) % p
        out.append((ax.to_bytes(48, "big") + ay.to_bytes(48, "big"), False))
    return out


def batch_verify_device(
    pk_raws: "list[bytes]",
    h_raws: "list[bytes]",
    sig_raws: "list[bytes]",
    scalars: "list[int]",
) -> bool:
    """The RLC batch verdict, device-shaped:

        Π e([r_i]·pk_i, H_i) · e(−G, Σ [r_i]·sig_i)  ==  1

    pk_raws: per-set aggregated pubkeys (raw96 affine, non-identity —
    the caller rejects identity aggregates, as the host batch does);
    h_raws: per-set message hash points (raw192 affine, hash_to_g2
    output — never infinity); sig_raws: per-set signatures (raw192
    affine); scalars: per-set nonzero 128-bit blinders.

    All O(N) group work — blinder multiplications, the signature sum,
    the N Miller loops, the Fq12 product tree — runs on device; the one
    extra pair and the final exponentiation verdict are the native
    backend's."""
    from ..native import bls as native_bls

    n = len(pk_raws)
    assert n and len(h_raws) == n and len(sig_raws) == n and len(scalars) == n

    # pad to the next power of two so the jitted kernels see log2-many
    # shapes: pk/H lanes pad with generator points and blinder 1 (their
    # Miller values are sliced off before the product); sig lanes pad
    # with blinder 0, whose scalar mult is the identity — the branchless
    # sum skips it
    g1f, g2f = _generator_raws()
    pk_padded = _pad_pow2(pk_raws, g1f)
    h_padded = _pad_pow2(h_raws, g2f)
    sig_padded = _pad_pow2(sig_raws, g2f)
    pk_scalars = _pad_pow2(list(scalars), 1)
    sig_scalars = list(scalars) + [0] * (len(pk_padded) - n)

    pk_jac = _g1_jac_from_affine_raws(pk_padded)
    pk_blinded = g1_mul_batched(pk_jac, pk_scalars, bits=128)
    xp, yp = _g1_jacobian_to_affine(pk_blinded.arr)

    xq, yq = g2_affine_from_raw(h_padded)

    sx, sy = g2_affine_from_raw(sig_padded)
    one2 = jnp.broadcast_to(
        jnp.asarray(np.stack([fql.to_mont_cols(1), np.zeros(24, np.uint64)])),
        sy.arr.shape,
    )
    sig_jac = _env(jnp.stack([sx.arr, sy.arr, one2], axis=-3))
    sig_sum = g2_sum_points(g2_mul_batched(sig_jac, sig_scalars, bits=128))
    s_raw, s_inf = _g2_point_to_raw(sig_sum)

    fs = miller_loop_batched(xp, yp, xq.arr, yq.arr)[:n]
    f_total = fp12_product(fs)
    return finalize_verdict(f_total, s_raw, s_inf)


def finalize_verdict(f_total, s_raw: bytes, s_inf: bool) -> bool:
    """Close an RLC batch from its device partials: multiply the Fq12
    Miller product by the extra pair e(−G, Σ [r_i]·sig_i) and ask the
    native backend for the final-exponentiation verdict. Shared by the
    single-device route above and the mesh-sharded route
    (parallel/pairing.py)."""
    from ..native import bls as native_bls

    if not s_inf:
        f_extra_ints = fq12.fp12_to_ints(
            miller_loop_batched(
                *(v.arr for v in g1_affine_from_raw([_neg_g1_generator_raw()])),
                *(v.arr for v in g2_affine_from_raw([s_raw])),
            )[0]
        )
        # combine on host via the native fp12 handoff (one multiply's worth
        # of work either way; avoids another device dispatch)
        f_ints = fq12.fp12_to_ints(f_total)
        from ..crypto.fields import Fq, Fq2, Fq6, Fq12

        def lift(vals):
            def f2(i):
                return Fq2(Fq(vals[2 * i]), Fq(vals[2 * i + 1]))
            return Fq12(Fq6(f2(0), f2(1), f2(2)), Fq6(f2(3), f2(4), f2(5)))

        prod = lift(f_ints) * lift(f_extra_ints)
        out = []
        for c6 in (prod.c0, prod.c1):
            for c2 in (c6.c0, c6.c1, c6.c2):
                out += [c2.c0.n, c2.c1.n]
        f_final_ints = out
    else:
        f_final_ints = fq12.fp12_to_ints(f_total)
    raw576 = b"".join(v.to_bytes(48, "big") for v in f_final_ints)
    return native_bls.fp12_final_exp_is_one(raw576)