"""Device BLS12-381 G1 point arithmetic: branchless Jacobian add/double
and a log-depth batched point-sum (the pubkey-aggregation kernel).

The data-parallel piece of `fast_aggregate_verify` /
`eth_aggregate_public_keys` (crypto/bls.rs:114,135) is the sum of N G1
points. On device it runs as a **tree reduction**: level k adds N/2^k
point pairs in one vectorized Jacobian addition over the limb arrays
(ops/fq.py), so 512 pubkeys cost 9 sequential vector steps instead of 511
sequential host additions. Infinity handling and the P==Q doubling corner
are branchless `where` selects — no data-dependent control flow under jit.

Coordinates: Jacobian (X, Y, Z) over Montgomery-form limb arrays, shape
(..., 3, 24) uint32; Z == 0 encodes infinity. Cross-checked against the
native C++ backend (native/bls12_381.cpp) in tests/test_ops_bls.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import device as _obs
from . import fq

__all__ = [
    "points_from_raw",
    "point_to_raw",
    "point_add",
    "point_double",
    "sum_points",
    "sum_points_segmented",
    "aggregate_pubkeys_device",
    "aggregate_pubkey_sets_device",
]


def _is_zero(x):
    """x == 0 over (..., 24) limb arrays → (...,) bool."""
    return jnp.all(x == 0, axis=-1)


def point_double(p):
    """Jacobian doubling, a=0 curve (2009 Bernstein-Lange dbl-2009-l).
    p: (..., 3, 24) → same shape. Doubling infinity stays infinity
    (Z=0 → Z3=0)."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fq.mont_square(x)
    b = fq.mont_square(y)
    c = fq.mont_square(b)
    xb = fq.add_mod(x, b)
    d = fq.sub_mod(fq.sub_mod(fq.mont_square(xb), a), c)
    d = fq.add_mod(d, d)
    e = fq.add_mod(fq.add_mod(a, a), a)
    f = fq.mont_square(e)
    x3 = fq.sub_mod(f, fq.add_mod(d, d))
    c8 = fq.add_mod(c, c)
    c8 = fq.add_mod(c8, c8)
    c8 = fq.add_mod(c8, c8)
    y3 = fq.sub_mod(fq.mont_mul(e, fq.sub_mod(d, x3)), c8)
    yz = fq.mont_mul(y, z)
    z3 = fq.add_mod(yz, yz)
    return jnp.stack([x3, y3, z3], axis=-2)


def point_add(p, q):
    """Branchless Jacobian addition, a=0 curve (add-2007-bl shape).
    Handles P/Q at infinity, P == Q (doubling), and P == -Q (infinity)
    via selects. p, q: (..., 3, 24) → same shape."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]

    z1z1 = fq.mont_square(z1)
    z2z2 = fq.mont_square(z2)
    u1 = fq.mont_mul(x1, z2z2)
    u2 = fq.mont_mul(x2, z1z1)
    s1 = fq.mont_mul(fq.mont_mul(y1, z2), z2z2)
    s2 = fq.mont_mul(fq.mont_mul(y2, z1), z1z1)
    h = fq.sub_mod(u2, u1)
    r = fq.sub_mod(s2, s1)

    hh = fq.mont_square(h)
    hhh = fq.mont_mul(h, hh)
    v = fq.mont_mul(u1, hh)
    r2 = fq.mont_square(r)
    x3 = fq.sub_mod(fq.sub_mod(r2, hhh), fq.add_mod(v, v))
    y3 = fq.sub_mod(
        fq.mont_mul(r, fq.sub_mod(v, x3)), fq.mont_mul(s1, hhh)
    )
    z3 = fq.mont_mul(fq.mont_mul(z1, z2), h)
    added = jnp.stack([x3, y3, z3], axis=-2)

    doubled = point_double(p)

    p_inf = _is_zero(z1)
    q_inf = _is_zero(z2)
    h_zero = _is_zero(h)
    r_zero = _is_zero(r)
    both_live = ~p_inf & ~q_inf

    same_point = both_live & h_zero & r_zero      # P == Q → double
    negation = both_live & h_zero & ~r_zero       # P == -Q → infinity

    out = added
    out = jnp.where(same_point[..., None, None], doubled, out)
    out = jnp.where(negation[..., None, None], jnp.zeros_like(out), out)
    out = jnp.where(p_inf[..., None, None], q, out)
    out = jnp.where(q_inf[..., None, None], p, out)
    return out


@functools.partial(jax.jit, static_argnames=("levels",))
def _tree_reduce(points, levels: int):
    """(2^levels, 3, 24) → (3, 24): XOR-fold point-add tree.

    Every level pairs slot i with slot i^2^k at FULL width — shapes never
    change, so the whole log-depth tree is one `fori_loop` whose body
    compiles once per width (a per-level shape-halving tree would compile
    `levels` distinct point_add programs). The 2× redundant adds per level
    are noise next to the saved compiles."""
    width = points.shape[0]
    idx = jnp.arange(width)

    def level(k, pts):
        bit = jnp.left_shift(jnp.int32(1), k)
        summed = point_add(pts, pts[idx ^ bit])
        keep = (idx & bit) == 0
        return jnp.where(keep[:, None, None], summed, jnp.zeros_like(summed))

    return jax.lax.fori_loop(0, levels, level, points)[0]


_SEGMENT = 256  # phase-1 fold width for large batches


_tree_reduce = _obs.observe_jit(_tree_reduce, "ops.g1._tree_reduce")


def sum_points(points) -> jax.Array:
    """Sum an (N, 3, 24) batch of Jacobian points on device; returns the
    (3, 24) Jacobian sum. Pads to a power of two with infinity.

    Large batches reduce in two phases — a segmented fold of
    ``_SEGMENT``-point blocks, then a fold over the block sums — cutting
    the full-width XOR fold's levels×W compute to ~(log2 SEGMENT)×W."""
    n = points.shape[0]
    if n == 0:
        return jnp.zeros((3, fq.LIMBS), jnp.uint32)
    width = 1 << (n - 1).bit_length()
    if width != n:
        pad = jnp.zeros((width - n, 3, fq.LIMBS), jnp.uint32)
        points = jnp.concatenate([points, pad], axis=0)
    if width > _SEGMENT:
        blocks = points.reshape(width // _SEGMENT, _SEGMENT, 3, fq.LIMBS)
        points = _tree_reduce_segmented(blocks, (_SEGMENT - 1).bit_length())
        width //= _SEGMENT
    return _tree_reduce(points, (width - 1).bit_length())


@functools.partial(jax.jit, static_argnames=("levels",))
def _tree_reduce_segmented(points, levels: int):
    """(S, 2^levels, 3, 24) → (S, 3, 24): the XOR fold along axis 1 —
    S independent point sums in one program (the signature-set batch
    shape: one pubkey aggregation per attestation)."""
    width = points.shape[1]
    idx = jnp.arange(width)

    def level(k, pts):
        bit = jnp.left_shift(jnp.int32(1), k)
        summed = point_add(pts, pts[:, idx ^ bit])
        keep = (idx & bit) == 0
        return jnp.where(keep[None, :, None, None], summed, jnp.zeros_like(summed))

    return jax.lax.fori_loop(0, levels, level, points)[:, 0]


_tree_reduce_segmented = _obs.observe_jit(
    _tree_reduce_segmented, "ops.g1._tree_reduce_segmented"
)


def sum_points_segmented(points) -> jax.Array:
    """(S, B, 3, 24) → (S, 3, 24): S independent B-point sums on device.
    Pads B to a power of two with infinity."""
    s, b = points.shape[:2]
    if b == 0:
        return jnp.zeros((s, 3, fq.LIMBS), jnp.uint32)
    width = 1 << (b - 1).bit_length()
    if width != b:
        pad = jnp.zeros((s, width - b, 3, fq.LIMBS), jnp.uint32)
        points = jnp.concatenate([points, pad], axis=1)
    return _tree_reduce_segmented(points, (width - 1).bit_length())


# ---------------------------------------------------------------------------
# Host <-> device marshalling (affine raw96 <-> Montgomery Jacobian limbs)
# ---------------------------------------------------------------------------


def points_from_raw(raws: "list[bytes]") -> jax.Array:
    """Affine raw96 points (x||y, 48-byte big-endian each — the native
    backend's decompressed format) → (N, 3, 24) Montgomery Jacobian batch.
    All-zero raws (infinity) map to Z=0.

    The byte→limb conversion is one numpy reinterpret: a 48-byte
    big-endian coordinate IS its 24 16-bit limbs in reverse order."""
    n = len(raws)
    words = np.frombuffer(b"".join(raws), dtype=">u2").reshape(n, 48)
    limbs = np.zeros((n, 3, fq.LIMBS), np.uint32)
    limbs[:, 0] = words[:, :24][:, ::-1]
    limbs[:, 1] = words[:, 24:][:, ::-1]
    live = (limbs[:, 0].any(axis=1)) | (limbs[:, 1].any(axis=1))
    limbs[:, 2, 0] = live  # Z=1 for live points, 0 (infinity) otherwise
    dev = _obs.h2d("ops.g1.points_from_raw", limbs)
    # one batched to-Montgomery pass over all coordinates
    return fq.to_mont(dev.reshape(n * 3, fq.LIMBS)).reshape(n, 3, fq.LIMBS)


def _canonical_jacobian_to_raw(row) -> "tuple[bytes, bool]":
    """One CANONICAL-form (3, 24) limb row → (affine raw96, is_infinity).
    The modular inversion runs host-side (big-int) — O(1) per batch and
    control-flow-heavy, the wrong shape for the device."""
    z = fq.from_limbs(row[2])
    if z == 0:
        return b"\x00" * 96, True
    x = fq.from_limbs(row[0])
    y = fq.from_limbs(row[1])
    z_inv = pow(z, -1, fq.P_INT)
    z2 = (z_inv * z_inv) % fq.P_INT
    ax = (x * z2) % fq.P_INT
    ay = (y * z2 * z_inv) % fq.P_INT
    return ax.to_bytes(48, "big") + ay.to_bytes(48, "big"), False


def point_to_raw(point) -> "tuple[bytes, bool]":
    """(3, 24) Montgomery Jacobian point → (affine raw96, is_infinity)."""
    return _canonical_jacobian_to_raw(
        _obs.d2h("ops.g1.point_to_raw", fq.from_mont(point))
    )


def aggregate_pubkeys_device(raws: "list[bytes]") -> "tuple[bytes, bool]":
    """Sum N affine raw96 G1 points on device; returns (raw96, is_inf).
    The device twin of the aggregation loop inside fast_aggregate_verify
    (crypto/bls.rs:114) and eth_aggregate_public_keys (:135)."""
    if not raws:
        return b"\x00" * 96, True
    return point_to_raw(sum_points(points_from_raw(raws)))


def aggregate_pubkey_sets_device(
    raw_sets: "list[list[bytes]]",
) -> "list[tuple[bytes, bool]]":
    """S independent pubkey aggregations on device — the batch boundary of
    verify_signature_sets: one aggregation per signature set (attestation /
    sync aggregate), padded to the widest set with infinity, all folded in
    one segmented kernel.

    Runs over the LAZY field (ops/pairing.g1_sum_sets): identical sums,
    but the fold compiles in seconds where this module's strict-field
    kernels cost ~130s of cold XLA compile — the strict path stays for
    the single huge sum (sum_points), whose one compile amortizes over
    the 128k-point north-star batches."""
    from . import pairing as _lazy

    return _lazy.g1_sum_sets(raw_sets)
