"""Batched SHA-256 on device (JAX/XLA + Pallas TPU kernel).

The SSZ merkleization hot path (reference: `ssz_rs::hash_tree_root`, the #1
hot path per SURVEY.md §3.1) is millions of *independent* SHA-256 hashes of
exactly 64 bytes (two 32-byte child nodes). A 64-byte message compresses in
exactly two rounds: one over the message block, one over the constant padding
block (0x80…, bit length 512). That makes the workload a pure data-parallel
uint32 VPU problem — no MXU, no dynamic shapes.

Layout: messages are held as uint32 words with shape ``(16, N)`` (words on
the sublane axis, hash lanes on the 128-wide lane axis), outputs ``(8, N)``.
Words use SHA-256's big-endian convention; conversion from byte strings
happens host-side via numpy ``>u4`` views.

The 64 rounds run as a ``lax.fori_loop`` with a rolling 16-entry message
schedule window (W[t+16] = W[t] + σ0(W[t+1]) + W[t+9] + σ1(W[t+14])) —
constant-size graph, so tracing/compilation stays cheap at every batch size
while the VPU still sees full-width vector ops per round.

Three execution paths, all bit-identical:
  - ``sha256_64b_xla``: pure jax.numpy (reference, runs anywhere)
  - ``sha256_64b_pallas``: Pallas TPU kernel (tiled over lanes)
  - host hashlib (see ssz/hash.py)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "sha256_64b_xla",
    "sha256_64b_pallas",
    "sha256_64b",
    "hash_level_bytes",
    "install_device_hasher",
    "K",
    "H0",
]

# SHA-256 round constants (FIPS 180-4).
K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

# Initial hash state.
H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state, window, k_at):
    """One SHA-256 compression.

    ``state`` (8, N) uint32 working state; ``window`` (16, N) message block;
    ``k_at(t)`` returns the round-t constant as a scalar (an accessor so the
    Pallas path can do SMEM scalar loads while the XLA path indexes an
    array). Returns updated (8, N) state.
    """

    def round_body(t, carry):
        window, s = carry
        a, b, c, d, e, f, g, h = (s[i] for i in range(8))
        wt = jax.lax.dynamic_index_in_dim(window, t % 16, axis=0, keepdims=False)

        big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        kt = k_at(t)
        t1 = h + big_s1 + ch + kt + wt
        big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = big_s0 + maj

        # rolling schedule: this slot next holds W[t+16]
        w1 = jax.lax.dynamic_index_in_dim(window, (t + 1) % 16, axis=0, keepdims=False)
        w9 = jax.lax.dynamic_index_in_dim(window, (t + 9) % 16, axis=0, keepdims=False)
        w14 = jax.lax.dynamic_index_in_dim(window, (t + 14) % 16, axis=0, keepdims=False)
        s0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> np.uint32(3))
        s1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> np.uint32(10))
        w_next = wt + s0 + w9 + s1
        window = jax.lax.dynamic_update_index_in_dim(window, w_next, t % 16, axis=0)

        new_s = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g])
        return window, new_s

    _, out = jax.lax.fori_loop(0, 64, round_body, (window, state))
    return state + out


def _compress_unrolled(state, window):
    """One SHA-256 compression, fully unrolled (static indices only).

    Used inside the Pallas kernel: mosaic cannot lower dynamic_slice on
    loop-carried values, and the kernel has a single fixed tile shape so the
    larger graph compiles exactly once. Bit-identical to ``_compress``.
    """
    w = [window[i] for i in range(16)]
    a, b, c, d, e, f, g, h = (state[i] for i in range(8))
    for t in range(64):
        if t < 16:
            wt = w[t]
        else:
            w15 = w[(t - 15) % 16]
            w2 = w[(t - 2) % 16]
            s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
            s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
            wt = w[t % 16] + s0 + w[(t - 7) % 16] + s1
            w[t % 16] = wt
        big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + big_s1 + ch + np.uint32(int(K[t])) + wt
        big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = big_s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    return jnp.stack(
        [
            state[0] + a, state[1] + b, state[2] + c, state[3] + d,
            state[4] + e, state[5] + f, state[6] + g, state[7] + h,
        ]
    )


def _initial_state(n: int):
    """(8, N) initial state built from scalar literals (Pallas-safe)."""
    return jnp.stack([jnp.full((n,), int(v), jnp.uint32) for v in H0])


def _pad_block(n: int):
    """(16, N) padding block for 64-byte messages, from scalar literals."""
    rows = [jnp.full((n,), 0x80000000, jnp.uint32)]
    rows += [jnp.zeros((n,), jnp.uint32)] * 14
    rows += [jnp.full((n,), 512, jnp.uint32)]
    return jnp.stack(rows)


def _sha256_64b_words(msgs, k_at):
    """SHA-256 of N 64-byte messages: ``msgs`` (16, N) uint32 → (8, N)."""
    n = msgs.shape[1]
    state = _compress(_initial_state(n), msgs, k_at)
    return _compress(state, _pad_block(n), k_at)


@jax.jit
def sha256_64b_xla(msgs: jax.Array) -> jax.Array:
    """Pure-XLA batched SHA-256 of 64-byte messages. (16, N) → (8, N)."""
    k_arr = jnp.asarray(K)
    return _sha256_64b_words(msgs, lambda t: k_arr[t])


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

# Lanes per grid step. 8 sublane-tiles of 128 lanes for 32-bit data keeps the
# VPU fed while staying far under VMEM limits ((16+8)*1024*4B = 96KiB/step).
_TILE_N = 1024


def _sha256_kernel(in_ref, out_ref):
    msgs = in_ref[:]
    n = msgs.shape[1]
    state = _compress_unrolled(_initial_state(n), msgs)
    out_ref[:] = _compress_unrolled(state, _pad_block(n))


@functools.partial(jax.jit, static_argnames=("interpret",))
def sha256_64b_pallas(msgs: jax.Array, interpret: bool = False) -> jax.Array:
    """Pallas-TPU batched SHA-256 of 64-byte messages. (16, N) → (8, N).

    N must be a multiple of _TILE_N (callers pad; merkle levels are powers
    of two so this is cheap). ``interpret=True`` runs the kernel in the
    Pallas interpreter (CPU) for testing.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = msgs.shape[1]
    if n % _TILE_N != 0:
        raise ValueError(
            f"sha256_64b_pallas requires N % {_TILE_N} == 0, got {n}; "
            "pad the batch or use sha256_64b_xla"
        )
    grid = (n // _TILE_N,)
    return pl.pallas_call(
        _sha256_kernel,
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (16, _TILE_N), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (8, _TILE_N), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(msgs)


_PALLAS_BROKEN = False


def _supports_pallas() -> bool:
    return jax.default_backend() == "tpu" and not _PALLAS_BROKEN


def sha256_64b(msgs: jax.Array) -> jax.Array:
    """Batched SHA-256, Pallas on TPU (when N tiles evenly), XLA otherwise.

    A Pallas compile failure (e.g. a transient remote-compile-helper error
    on tunneled TPU setups) demotes to the bit-identical XLA kernel for
    the rest of the process instead of surfacing an internal error."""
    global _PALLAS_BROKEN
    if _supports_pallas() and msgs.shape[1] % _TILE_N == 0:
        try:
            return sha256_64b_pallas(msgs)
        except jax.errors.JaxRuntimeError:
            _PALLAS_BROKEN = True
    return sha256_64b_xla(msgs)


# ---------------------------------------------------------------------------
# Host bridge: bytes ↔ device words
# ---------------------------------------------------------------------------


def hash_level_bytes(nodes: bytes) -> bytes:
    """Device equivalent of ssz.hash.hash_level_host: ``nodes`` is 2n 32-byte
    nodes concatenated; returns n parent nodes. Bit-identical to hashlib."""
    n = len(nodes) // 64
    # (n, 16) big-endian words → (16, n) lanes-last layout
    words = np.frombuffer(nodes, dtype=">u4").astype(np.uint32).reshape(n, 16).T
    out = np.asarray(sha256_64b(jnp.asarray(words)))
    # (8, n) → (n, 8) → big-endian bytes
    return out.T.astype(">u4").tobytes()


def install_device_hasher(force: bool = False) -> None:
    """Route ssz merkleization's large levels through the device backend.

    No-op on a CPU default backend unless ``force``: the jnp compression
    there is ~30x slower than the native C++ hasher, and a degraded
    (chip-less) ``ops.install()`` was silently poisoning every
    subsequent big merkle level in the process — measured 6.3s vs 0.2s
    per 2^19-pair level, which turned the 2^20-registry cold walk from
    6s into 59s once any config had installed device routing."""
    import jax

    if jax.default_backend() == "cpu" and not force:
        return
    from ..ssz.hash import register_device_hasher

    register_device_hasher(hash_level_bytes)
