"""Device BLS12-381 Fq2 = Fq[u]/(u² + 1) on the bound-tracked lazy field.

An Fq2 element is an ``fql.LV`` whose array is (..., 2, 24) uint64
columns in R' = 2^416 Montgomery form — index 0 is c0, index 1 is c1 —
with static value/column bounds carried beside the trace (fql.py).
Multiplications STACK their independent Montgomery products into a
single `fql` mont call so the compiled graph stays small, and use
SCHOOLBOOK component formulas (c0 = a0b0 − a1b1, c1 = a0b1 + a1b0)
rather than Karatsuba: one extra product per multiply, but every
subtrahend is then a fresh mont output, which keeps the lazy-sub pad
ladder shallow — the compile-time/bound-growth tradeoff that makes the
Miller loop traceable at all.

Reference parity: the role blst's fp2 layer plays under crypto/bls.rs
(C6); canonical exports match crypto/fields.py Fq2 exactly
(tests/test_ops_pairing.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fql
from .fql import LV

__all__ = [
    "one",
    "zero_like",
    "to_lv",
    "from_lv_ints",
    "add",
    "sub",
    "neg",
    "dbl",
    "mul",
    "square",
    "scalar_mul",
    "mul_by_xi",
    "conj",
    "inv",
    "is_zero",
]


def _c0(a: LV):
    return LV(a.arr[..., 0, :], a.vmax, a.cmax)


def _c1(a: LV):
    return LV(a.arr[..., 1, :], a.vmax, a.cmax)


def _pack(c0: LV, c1: LV) -> LV:
    return LV(
        jnp.stack([c0.arr, c1.arr], axis=-2),
        max(c0.vmax, c1.vmax),
        max(c0.cmax, c1.cmax),
    )


def one(batch_shape=()) -> LV:
    base = np.stack([fql.to_mont_cols(1), np.zeros(24, np.uint64)])
    arr = jnp.broadcast_to(jnp.asarray(base), tuple(batch_shape) + base.shape)
    return fql.lv_canon(arr)


def zero_like(a: LV) -> LV:
    return fql.lv_zero_like(a)


def to_lv(c0: int, c1: int) -> LV:
    """(c0 + c1·u) canonical ints → a (2, 24) R'-Montgomery LV."""
    arr = np.stack([fql.to_mont_cols(c0), fql.to_mont_cols(c1)])
    return fql.lv_canon(jnp.asarray(arr))


def from_lv_ints(a) -> tuple:
    """LV (or raw (..., 2, 24) array) → canonical (c0, c1) ints (host)."""
    arr = np.asarray(a.arr if isinstance(a, LV) else a)
    return fql.from_mont_ints(arr[..., 0, :]), fql.from_mont_ints(arr[..., 1, :])


def add(a: LV, b: LV) -> LV:
    return fql.lv_add(a, b)


def sub(a: LV, b: LV) -> LV:
    return fql.lv_sub(a, b)


def dbl(a: LV) -> LV:
    return fql.lv_add(a, a)


def neg(a: LV) -> LV:
    return fql.lv_sub(fql.lv_zero_like(a), a)


def mul(a: LV, b: LV) -> LV:
    """Schoolbook: c0 = a0b0 − a1b1, c1 = a0b1 + a1b0 — four independent
    products in ONE stacked mont; both outputs are shallow (one sub of a
    mont output / one add)."""
    a0, a1 = _c0(a), _c1(a)
    b0, b1 = _c0(b), _c1(b)
    lhs = fql.lv_stack([a0, a1, a0, a1])
    rhs = fql.lv_stack([b0, b1, b1, b0])
    t = fql.lv_mont(lhs, rhs)
    t0 = LV(t.arr[0], t.vmax, t.cmax)
    t1 = LV(t.arr[1], t.vmax, t.cmax)
    t2 = LV(t.arr[2], t.vmax, t.cmax)
    t3 = LV(t.arr[3], t.vmax, t.cmax)
    return _pack(fql.lv_sub(t0, t1), fql.lv_add(t2, t3))


def square(a: LV) -> LV:
    """c0 = a0² − a1², c1 = 2·a0a1 — three products, one stacked mont."""
    a0, a1 = _c0(a), _c1(a)
    lhs = fql.lv_stack([a0, a1, a0])
    rhs = fql.lv_stack([a0, a1, a1])
    t = fql.lv_mont(lhs, rhs)
    t0 = LV(t.arr[0], t.vmax, t.cmax)
    t1 = LV(t.arr[1], t.vmax, t.cmax)
    t2 = LV(t.arr[2], t.vmax, t.cmax)
    return _pack(fql.lv_sub(t0, t1), fql.lv_add(t2, t2))


def mul_many(pairs: "list[tuple[LV, LV]]") -> "list[LV]":
    """All the listed Fq2 products in ONE stacked mont call (4 Fq
    products each, schoolbook) — the graph-size lever: a whole fp6/fp12
    multiply becomes a single mont instance."""
    lhs, rhs = [], []
    for a, b in pairs:
        a0, a1 = _c0(a), _c1(a)
        b0, b1 = _c0(b), _c1(b)
        lhs += [a0, a1, a0, a1]
        rhs += [b0, b1, b1, b0]
    t = fql.lv_mont(fql.lv_stack(lhs), fql.lv_stack(rhs))
    outs = []
    for k in range(len(pairs)):
        t0 = LV(t.arr[4 * k], t.vmax, t.cmax)
        t1 = LV(t.arr[4 * k + 1], t.vmax, t.cmax)
        t2 = LV(t.arr[4 * k + 2], t.vmax, t.cmax)
        t3 = LV(t.arr[4 * k + 3], t.vmax, t.cmax)
        outs.append(_pack(fql.lv_sub(t0, t1), fql.lv_add(t2, t3)))
    return outs


def square_many(items: "list[LV]") -> "list[LV]":
    """All the listed Fq2 squares in one stacked mont (3 products each)."""
    lhs, rhs = [], []
    for a in items:
        a0, a1 = _c0(a), _c1(a)
        lhs += [a0, a1, a0]
        rhs += [a0, a1, a1]
    t = fql.lv_mont(fql.lv_stack(lhs), fql.lv_stack(rhs))
    outs = []
    for k in range(len(items)):
        t0 = LV(t.arr[3 * k], t.vmax, t.cmax)
        t1 = LV(t.arr[3 * k + 1], t.vmax, t.cmax)
        t2 = LV(t.arr[3 * k + 2], t.vmax, t.cmax)
        outs.append(_pack(fql.lv_sub(t0, t1), fql.lv_add(t2, t2)))
    return outs


def scalar_mul(a: LV, k: LV) -> LV:
    """a · k with k an Fq scalar LV of shape (..., 24)."""
    lhs = fql.lv_stack([_c0(a), _c1(a)])
    rhs = fql.lv_stack([k, k])
    t = fql.lv_mont(lhs, rhs)
    return _pack(LV(t.arr[0], t.vmax, t.cmax), LV(t.arr[1], t.vmax, t.cmax))


def mul_by_xi(a: LV) -> LV:
    """a · (u + 1) = (a0 − a1) + (a0 + a1)·u."""
    a0, a1 = _c0(a), _c1(a)
    return _pack(fql.lv_sub(a0, a1), fql.lv_add(a0, a1))


def conj(a: LV) -> LV:
    a0, a1 = _c0(a), _c1(a)
    return _pack(a0, fql.lv_sub(fql.lv_zero_like(a1), a1))


def is_zero(a: LV):
    """a ≡ 0 mod p, safe for any redundant value (canonicalizing mont)."""
    t = fql.mont(
        jnp.stack([a.arr[..., 0, :], a.arr[..., 1, :]]),
        jnp.asarray(fql._ONE_COLS),
    )
    return fql.is_zero_cols(t[0]) & fql.is_zero_cols(t[1])


# p − 2 bits MSB-first (static), for the Fermat inversion scans
_P_MINUS_2_BITS = np.array(
    [int(b) for b in bin(fql.P_INT - 2)[2:]], dtype=np.bool_
)


def fq_inv_raw(a):
    """Fq inversion a^(p−2) over raw (..., 24) R'-Montgomery mont-output
    arrays (bounds are scan-stable: every carry is a mont output).
    0 maps to 0. Used in batch affine conversions only."""
    bits = jnp.asarray(_P_MINUS_2_BITS[1:])  # MSB consumed by init

    def step(acc, bit):
        acc2 = fql.mont(acc, acc)
        with_mul = fql.mont(acc2, a)
        return jnp.where(bit, with_mul, acc2), None

    out, _ = jax.lax.scan(step, a, bits)
    return out


def inv(a: LV) -> LV:
    """1 / (a0 + a1·u) = (a0 − a1·u) / (a0² + a1²)."""
    a0, a1 = _c0(a), _c1(a)
    t = fql.lv_mont(fql.lv_stack([a0, a1]), fql.lv_stack([a0, a1]))
    norm = LV(t.arr[0], t.vmax, t.cmax)
    norm = fql.lv_add(norm, LV(t.arr[1], t.vmax, t.cmax))
    # one extra mont canonicalizes the sum for the scan-stable ladder
    ninv = fq_inv_raw(fql.lv_mont(norm, fql.lv_const(1)).arr)
    ninv_lv = fql.lv_canon(ninv)
    lhs = fql.lv_stack([a0, fql.lv_sub(fql.lv_zero_like(a1), a1)])
    out = fql.lv_mont(lhs, fql.lv_stack([ninv_lv, ninv_lv]))
    return _pack(LV(out.arr[0], out.vmax, out.cmax), LV(out.arr[1], out.vmax, out.cmax))