"""Device epoch-processing sweeps — the whole-registry data-parallel loops.

Reference parity: the per-validator epoch loops the reference runs scalar
(altair flag-delta rewards ethereum-consensus/src/altair/helpers.rs:265,
inactivity updates/penalties altair/epoch_processing.rs:104, effective-
balance hysteresis phase0/epoch_processing.rs) — re-expressed as exact-u64
`jnp` vector ops over the packed registry, the "embarrassingly data-parallel
integer ops, ideal XLA material" of SURVEY.md §7. Bit-identical to the host
spec functions; cross-checked in tests.

Inputs are packed registry arrays (uint64/uint8/bool). All arithmetic is
integer; callers enable ``jax_enable_x64``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# exact u64 spec arithmetic is meaningless without real uint64 lanes
# (without x64 mode jnp silently truncates to uint32)
jax.config.update("jax_enable_x64", True)

from ..models.altair.constants import (
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)
from ..telemetry import device as _obs

from .registry_columns import pack_registry  # noqa: F401 — re-export

__all__ = [
    "pack_registry",
    "flag_deltas_device",
    "inactivity_updates_device",
    "inactivity_penalties_device",
    "effective_balance_updates_device",
]


def _isqrt_u64(x):
    """Integer sqrt of a uint64 scalar array (Newton, fixed 6 iters from a
    float seed — exact for the total-balance magnitudes involved)."""
    guess = jnp.sqrt(x.astype(jnp.float64)).astype(jnp.uint64) + jnp.uint64(1)

    def body(_, g):
        g = jnp.maximum(g, jnp.uint64(1))
        return (g + x // g) >> jnp.uint64(1)

    g = jax.lax.fori_loop(0, 6, body, guess)
    # clamp to floor(sqrt(x))
    g = jnp.where(g * g > x, g - jnp.uint64(1), g)
    return jnp.where((g + 1) * (g + 1) <= x, g + jnp.uint64(1), g)


@functools.partial(
    jax.jit,
    static_argnames=(
        "flag_index", "increment", "base_reward_factor", "weight_denominator",
        "is_leaking",
    ),
)
def _flag_deltas(
    effective_balance,
    participating,  # bool: unslashed & active & has_flag
    eligible,
    total_active_balance,
    flag_weight,
    flag_index: int,
    increment: int,
    base_reward_factor: int,
    weight_denominator: int,
    is_leaking: bool,
):
    base_reward_per_increment = (
        jnp.uint64(increment)
        * jnp.uint64(base_reward_factor)
        // _isqrt_u64(total_active_balance)
    )
    base_reward = (
        effective_balance // jnp.uint64(increment)
    ) * base_reward_per_increment

    unslashed_participating_balance = jnp.sum(
        jnp.where(participating, effective_balance, jnp.uint64(0))
    )
    unslashed_increments = unslashed_participating_balance // jnp.uint64(increment)
    # spec: max(EFFECTIVE_BALANCE_INCREMENT, total) guard is already applied
    # by the caller for total_active_balance
    active_increments = total_active_balance // jnp.uint64(increment)

    reward_numerator = base_reward * flag_weight * unslashed_increments
    rewards = jnp.where(
        participating & eligible & jnp.bool_(not is_leaking),
        reward_numerator // (active_increments * jnp.uint64(weight_denominator)),
        jnp.uint64(0),
    )
    penalize = eligible & ~participating
    if flag_index == TIMELY_HEAD_FLAG_INDEX:
        penalties = jnp.zeros_like(rewards)
    else:
        penalties = jnp.where(
            penalize,
            base_reward * flag_weight // jnp.uint64(weight_denominator),
            jnp.uint64(0),
        )
    return rewards, penalties


_flag_deltas = _obs.observe_jit(_flag_deltas, "ops.sweeps._flag_deltas")


def flag_deltas_device(packed: dict, flag_index: int, total_active_balance: int, context, is_leaking: bool):
    """Device twin of altair get_flag_index_deltas (helpers.rs:265)."""
    participating = (
        ((packed["previous_participation"] >> np.uint8(flag_index)) & 1).astype(bool)
        & ~packed["slashed"]
        & packed["active_previous"]
    )
    eff_d, part_d, elig_d = _obs.h2d(
        "ops.sweeps.flag_deltas",
        packed["effective_balance"], participating, packed["eligible"],
    )
    rewards, penalties = _flag_deltas(
        eff_d,
        part_d,
        elig_d,
        jnp.uint64(total_active_balance),
        jnp.uint64(PARTICIPATION_FLAG_WEIGHTS[flag_index]),
        flag_index,
        context.EFFECTIVE_BALANCE_INCREMENT,
        context.BASE_REWARD_FACTOR,
        WEIGHT_DENOMINATOR,
        is_leaking,
    )
    return (
        _obs.d2h("ops.sweeps.flag_deltas", rewards),
        _obs.d2h("ops.sweeps.flag_deltas", penalties),
    )


@functools.partial(jax.jit, static_argnames=("bias", "recovery_rate", "is_leaking"))
def _inactivity_updates(scores, participating, eligible, bias: int, recovery_rate: int, is_leaking: bool):
    decreased = scores - jnp.minimum(jnp.uint64(1), scores)
    increased = scores + jnp.uint64(bias)
    scores = jnp.where(
        eligible, jnp.where(participating, decreased, increased), scores
    )
    if not is_leaking:
        scores = jnp.where(
            eligible, scores - jnp.minimum(jnp.uint64(recovery_rate), scores), scores
        )
    return scores


_inactivity_updates = _obs.observe_jit(
    _inactivity_updates, "ops.sweeps._inactivity_updates"
)


def inactivity_updates_device(packed: dict, context, is_leaking: bool):
    """Device twin of altair process_inactivity_updates
    (epoch_processing.rs:104)."""
    participating = (
        ((packed["previous_participation"] >> np.uint8(1)) & 1).astype(bool)
        & ~packed["slashed"]
        & packed["active_previous"]
    )
    scores_d, part_d, elig_d = _obs.h2d(
        "ops.sweeps.inactivity_updates",
        packed["inactivity_scores"], participating, packed["eligible"],
    )
    return _obs.d2h(
        "ops.sweeps.inactivity_updates",
        _inactivity_updates(
            scores_d,
            part_d,
            elig_d,
            context.inactivity_score_bias,
            context.inactivity_score_recovery_rate,
            is_leaking,
        ),
    )


@functools.partial(jax.jit, static_argnames=("bias", "quotient"))
def _inactivity_penalties(effective_balance, scores, not_target, bias: int, quotient: int):
    numerator = effective_balance * scores
    denominator = jnp.uint64(bias) * jnp.uint64(quotient)
    return jnp.where(not_target, numerator // denominator, jnp.uint64(0))


_inactivity_penalties = _obs.observe_jit(
    _inactivity_penalties, "ops.sweeps._inactivity_penalties"
)


def inactivity_penalties_device(packed: dict, context, quotient: int):
    """Device twin of get_inactivity_penalty_deltas (per-fork quotient).

    The device kernel multiplies effective_balance * score in uint64,
    which wraps once a score exceeds 2^64 / effective_balance (~5.8e8 at
    32 ETH, ~9e6 at electra's 2048 ETH cap) — scores that large need an
    inactivity leak lasting years, but they are representable, so the
    spec's exact-bigint semantics are preserved by routing through an
    exact object-int path whenever the max products could wrap."""
    participating = (
        ((packed["previous_participation"] >> np.uint8(1)) & 1).astype(bool)
        & ~packed["slashed"]
        & packed["active_previous"]
    )
    not_target = packed["eligible"] & ~participating
    eff = packed["effective_balance"]
    scores = packed["inactivity_scores"]
    max_product = int(eff.max(initial=0)) * int(scores.max(initial=0))
    if max_product >= 1 << 64:
        denominator = context.inactivity_score_bias * quotient
        products = eff.astype(object) * scores.astype(object)
        exact = np.where(not_target, products // denominator, 0)
        return exact.astype(np.uint64)
    eff_d, scores_d, not_target_d = _obs.h2d(
        "ops.sweeps.inactivity_penalties", eff, scores, not_target
    )
    return _obs.d2h(
        "ops.sweeps.inactivity_penalties",
        _inactivity_penalties(
            eff_d,
            scores_d,
            not_target_d,
            context.inactivity_score_bias,
            quotient,
        ),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "increment", "max_effective", "quotient", "down_mult", "up_mult",
    ),
)
def _effective_balance_updates(
    balances, effective, increment: int, max_effective: int, quotient: int,
    down_mult: int, up_mult: int,
):
    hysteresis_increment = jnp.uint64(increment // quotient)
    downward = hysteresis_increment * jnp.uint64(down_mult)
    upward = hysteresis_increment * jnp.uint64(up_mult)
    candidate = jnp.minimum(
        balances - balances % jnp.uint64(increment), jnp.uint64(max_effective)
    )
    update = (balances + downward < effective) | (effective + upward < balances)
    return jnp.where(update, candidate, effective)


_effective_balance_updates = _obs.observe_jit(
    _effective_balance_updates, "ops.sweeps._effective_balance_updates"
)


def effective_balance_updates_device(packed: dict, context):
    """Device twin of phase0 process_effective_balance_updates."""
    bal_d, eff_d = _obs.h2d(
        "ops.sweeps.effective_balance_updates",
        packed["balances"], packed["effective_balance"],
    )
    return _obs.d2h(
        "ops.sweeps.effective_balance_updates",
        _effective_balance_updates(
            bal_d,
            eff_d,
            context.EFFECTIVE_BALANCE_INCREMENT,
            context.MAX_EFFECTIVE_BALANCE,
            context.HYSTERESIS_QUOTIENT,
            context.HYSTERESIS_DOWNWARD_MULTIPLIER,
            context.HYSTERESIS_UPWARD_MULTIPLIER,
        )
    )
