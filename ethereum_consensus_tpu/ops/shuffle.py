"""Vectorized swap-or-not shuffle on device.

Reference parity: the optimized whole-list shuffle behind the reference's
`shuffling` feature (ethereum-consensus/src/phase0/helpers.rs:287, "cribbed
from lighthouse") — here as a TPU-shaped kernel: the per-round pivot and
source-byte material is tiny and data-independent, so it is precomputed
host-side (SHUFFLE_ROUND_COUNT × ⌈count/256⌉ SHA-256 calls), uploaded once,
and the per-index permutation runs as a `lax.fori_loop` of pure integer
vector ops over all indices at once — no gather-scatter, no dynamic shapes.

Bit-identical to models.phase0.helpers.compute_shuffled_index(s).
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import device as _obs

__all__ = ["shuffle_sources", "shuffled_indices_device", "compute_shuffled_indices_device"]


def shuffle_sources(count: int, seed: bytes, rounds: int):
    """Host-side precompute: per-round pivots and source-byte tables.

    Returns (pivots: (rounds,) uint32, sources: (rounds, n_chunks*32) uint8)
    where sources[r] concatenates sha256(seed + r + chunk) for every 256-
    index chunk (helpers.rs:287's hash schedule).
    """
    if count == 0:
        raise ValueError("empty index list")
    n_chunks = (count + 255) // 256
    pivots = np.empty(rounds, dtype=np.uint32)
    sources = np.empty((rounds, n_chunks * 32), dtype=np.uint8)
    for r in range(rounds):
        round_byte = r.to_bytes(1, "little")
        pivots[r] = (
            int.from_bytes(
                hashlib.sha256(seed + round_byte).digest()[:8], "little"
            )
            % count
        )
        for chunk in range(n_chunks):
            digest = hashlib.sha256(
                seed + round_byte + chunk.to_bytes(4, "little")
            ).digest()
            sources[r, chunk * 32 : (chunk + 1) * 32] = np.frombuffer(
                digest, dtype=np.uint8
            )
    return pivots, sources


def _shuffle_rounds(indices, pivots, sources, count: int, forward: bool):
    """fori_loop over rounds; each round is one vectorized swap-or-not pass.

    ``forward`` applies rounds 0..R-1 (the per-index map direction of
    compute_shuffled_index); reversed order gives the inverse permutation.
    """
    count32 = jnp.uint32(count)
    rounds = pivots.shape[0]

    def body(i, idx):
        r = i if forward else rounds - 1 - i
        pivot = pivots[r]
        flip = (pivot + count32 - idx) % count32
        position = jnp.maximum(idx, flip)
        byte = sources[r, position // jnp.uint32(8)]
        bit = (byte >> (position % jnp.uint32(8)).astype(jnp.uint8)) & jnp.uint8(1)
        return jnp.where(bit == 1, flip, idx)

    return jax.lax.fori_loop(0, rounds, body, indices)


_shuffle_rounds_jit = _obs.observe_jit(
    jax.jit(_shuffle_rounds, static_argnames=("count", "forward")),
    "ops.shuffle._shuffle_rounds",
)


def shuffled_indices_device(count: int, seed: bytes, rounds: int) -> jax.Array:
    """Map every index through the swap-or-not permutation on device:
    out[i] == compute_shuffled_index(i, count, seed)."""
    pivots, sources = shuffle_sources(count, seed, rounds)
    indices = jnp.arange(count, dtype=jnp.uint32)
    pivots_d, sources_d = _obs.h2d("ops.shuffle", pivots, sources)
    return _shuffle_rounds_jit(
        indices,
        pivots_d,
        sources_d,
        count=count,
        forward=True,
    )


def compute_shuffled_indices_device(indices: list[int], seed: bytes, context) -> list[int]:
    """Drop-in device twin of helpers.compute_shuffled_indices: permutes the
    *list* so that out[i] == indices[compute_shuffled_index(i, ...)]."""
    count = len(indices)
    if count == 0:
        return []
    mapping = _obs.d2h(
        "ops.shuffle",
        shuffled_indices_device(count, seed, context.SHUFFLE_ROUND_COUNT),
    )
    arr = np.asarray(indices)
    return arr[mapping].tolist()
