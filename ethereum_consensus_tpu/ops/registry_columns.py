"""Host-side registry column extraction (jax-free).

The packed columns feed BOTH the device sweeps (ops/sweeps.py — jnp twins
of the epoch loops) and the numpy host twins
(models/altair/epoch_processing._host_deltas_vectorized); keeping the
eligibility formula and the genesis participation corner in ONE place
stops the two consumers drifting (code-review r5)."""

from __future__ import annotations

import numpy as np

__all__ = ["pack_registry", "unslashed_flag_mask", "activity_masks"]


def activity_masks(activation, exit_epoch, withdrawable, slashed, previous_epoch):
    """(active_previous, eligible) boolean columns from the epoch columns —
    THE eligibility formula (altair helpers.rs:265), shared by the
    fromiter packing below and the cached-column packing in
    models/ops_vector.py so the two can't drift."""
    prev = np.uint64(int(previous_epoch))
    active_previous = (activation <= prev) & (prev < exit_epoch)
    eligible = active_previous | (
        slashed & (prev + np.uint64(1) < withdrawable)
    )
    return active_previous, eligible


def pack_registry(state, previous_epoch: int, use_current_participation: bool = False) -> dict:
    """Host→device packing of the registry fields the sweeps touch.
    Activity/eligibility are evaluated at ``previous_epoch`` (the epoch the
    deltas reward/penalize, altair helpers.rs:265).

    ``use_current_participation`` covers the genesis corner where
    previous_epoch == current_epoch and the spec's
    get_unslashed_participating_indices reads the CURRENT epoch's flags."""
    n = len(state.validators)
    # phase0 states have no participation flags or inactivity scores — the
    # sweeps that need them are altair+; zero-fill so phase0-only sweeps
    # (effective-balance hysteresis) can share the same pack
    participation_list = getattr(
        state,
        "current_epoch_participation"
        if use_current_participation
        else "previous_epoch_participation",
        None,
    )
    if participation_list is None:
        participation_list = [0] * n
    inactivity_scores = getattr(state, "inactivity_scores", None)
    if inactivity_scores is None:
        inactivity_scores = [0] * n
    out = {
        "effective_balance": np.fromiter(
            (v.effective_balance for v in state.validators), np.uint64, n
        ),
        "slashed": np.fromiter(
            (bool(v.slashed) for v in state.validators), np.bool_, n
        ),
        "previous_participation": np.fromiter(
            (int(f) for f in participation_list), np.uint8, n
        ),
        "inactivity_scores": np.fromiter(
            (int(s) for s in inactivity_scores), np.uint64, n
        ),
        "balances": np.fromiter((int(b) for b in state.balances), np.uint64, n),
    }
    out["active_previous"], out["eligible"] = activity_masks(
        np.fromiter(
            (v.activation_epoch for v in state.validators), np.uint64, n
        ),
        np.fromiter((v.exit_epoch for v in state.validators), np.uint64, n),
        np.fromiter(
            (v.withdrawable_epoch for v in state.validators), np.uint64, n
        ),
        out["slashed"],
        previous_epoch,
    )
    return out


def unslashed_flag_mask(packed: dict, flag_index: int):
    """Boolean column: active-in-previous-epoch, unslashed, and holding
    participation ``flag_index`` — get_unslashed_participating_indices as
    a mask. Shared by the rewards and inactivity numpy twins so the flag
    semantics live in one place."""
    return (
        packed["active_previous"]
        & ~packed["slashed"]
        & (
            (packed["previous_participation"] >> np.uint8(flag_index))
            & np.uint8(1)
        ).astype(bool)
    )
