"""Device Fq6/Fq12 tower arithmetic for the batched pairing, on the
bound-tracked lazy field (ops/fql.py).

Tower (identical to crypto/fields.py and native/bls12_381.cpp):
    Fq6  = Fq2[v]/(v³ − ξ),  ξ = u + 1
    Fq12 = Fq6[w]/(w² − v)

Shapes: an Fq6 element is an LV over (..., 3, 2, 24) — v-power, the Fq2
pair, limbs; Fq12 is (..., 2, 3, 2, 24) with the w-half first. Products
use SCHOOLBOOK component formulas routed through fq2.mul_many, so one
fp6 multiply is ONE stacked Montgomery instance (36 Fq products) — the
graph-size discipline that keeps the Miller loop compilable. The lazy
pad ladder (fql.lv_sub) absorbs every subtraction with trace-time bound
checks.

Cross-checked against native/bls12_381.cpp and crypto/fields.py on
canonical exports in tests/test_ops_pairing.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import fq2, fql
from .fql import LV

__all__ = [
    "fp6_comp",
    "fp6_pack",
    "fp6_add",
    "fp6_sub",
    "fp6_neg",
    "fp6_mul",
    "fp6_mul_by_v",
    "fp12_one",
    "fp12_comp",
    "fp12_pack",
    "fp12_mul",
    "fp12_sqr",
    "fp12_conj",
    "fp12_mul_by_line",
    "fp12_to_ints",
    "fp12_from_ints",
]


def fp6_comp(a: LV, i: int) -> LV:
    return LV(a.arr[..., i, :, :], a.vmax, a.cmax)


def fp6_pack(c0: LV, c1: LV, c2: LV) -> LV:
    return LV(
        jnp.stack([c0.arr, c1.arr, c2.arr], axis=-3),
        max(c0.vmax, c1.vmax, c2.vmax),
        max(c0.cmax, c1.cmax, c2.cmax),
    )


def fp6_add(a: LV, b: LV) -> LV:
    return fql.lv_add(a, b)


def fp6_sub(a: LV, b: LV) -> LV:
    return fql.lv_sub(a, b)


def fp6_neg(a: LV) -> LV:
    return fql.lv_sub(fql.lv_zero_like(a), a)


def fp6_mul(a: LV, b: LV) -> LV:
    """Schoolbook over Fq2 — 9 products, ONE stacked mont:
    c0 = a0b0 + ξ(a1b2 + a2b1)
    c1 = a0b1 + a1b0 + ξ(a2b2)
    c2 = a0b2 + a1b1 + a2b0"""
    a0, a1, a2 = (fp6_comp(a, i) for i in range(3))
    b0, b1, b2 = (fp6_comp(b, i) for i in range(3))
    p = fq2.mul_many([
        (a0, b0), (a1, b2), (a2, b1),
        (a0, b1), (a1, b0), (a2, b2),
        (a0, b2), (a1, b1), (a2, b0),
    ])
    c0 = fq2.add(p[0], fq2.mul_by_xi(fq2.add(p[1], p[2])))
    c1 = fq2.add(fq2.add(p[3], p[4]), fq2.mul_by_xi(p[5]))
    c2 = fq2.add(fq2.add(p[6], p[7]), p[8])
    return fp6_pack(c0, c1, c2)


def fp6_mul_by_v(a: LV) -> LV:
    """(a0, a1, a2) → (ξ·a2, a0, a1)."""
    return fp6_pack(
        fq2.mul_by_xi(fp6_comp(a, 2)), fp6_comp(a, 0), fp6_comp(a, 1)
    )


# -- Fq12 -------------------------------------------------------------------

def fp12_comp(a: LV, i: int) -> LV:
    return LV(a.arr[..., i, :, :, :], a.vmax, a.cmax)


def fp12_pack(c0: LV, c1: LV) -> LV:
    return LV(
        jnp.stack([c0.arr, c1.arr], axis=-4),
        max(c0.vmax, c1.vmax),
        max(c0.cmax, c1.cmax),
    )


def fp12_one(batch_shape=()) -> LV:
    one6 = np.stack([
        np.stack([fql.to_mont_cols(1), np.zeros(24, np.uint64)]),
        np.zeros((2, 24), np.uint64),
        np.zeros((2, 24), np.uint64),
    ])
    base = np.stack([one6, np.zeros_like(one6)])
    arr = jnp.broadcast_to(jnp.asarray(base), tuple(batch_shape) + base.shape)
    return fql.lv_canon(arr)


def fp12_mul(a: LV, b: LV) -> LV:
    """Karatsuba over the w-halves — 3 fp6 multiplies."""
    a0, a1 = fp12_comp(a, 0), fp12_comp(a, 1)
    b0, b1 = fp12_comp(b, 0), fp12_comp(b, 1)
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    t2 = fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1))
    t2 = fp6_sub(fp6_sub(t2, t0), t1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    return fp12_pack(c0, t2)


def fp12_sqr(a: LV) -> LV:
    """Complex squaring — 2 fp6 multiplies."""
    a0, a1 = fp12_comp(a, 0), fp12_comp(a, 1)
    u = fp6_mul(a0, a1)
    t = fp6_mul(fp6_add(a0, a1), fp6_add(a0, fp6_mul_by_v(a1)))
    t = fp6_sub(t, u)
    c0 = fp6_sub(t, fp6_mul_by_v(u))
    c1 = fp6_add(u, u)
    return fp12_pack(c0, c1)


def fp12_conj(a: LV) -> LV:
    """f^(p^6): negate the w-half."""
    return fp12_pack(fp12_comp(a, 0), fp6_neg(fp12_comp(a, 1)))


def fp12_mul_by_line(f: LV, c00: LV, c11: LV, c12: LV) -> LV:
    """f · (A + B·w) with A = (c00, 0, 0), B = (0, c11, c12) — the sparse
    Miller-line multiply: the 9 cross products run as one stacked mont,
    the dense (f0+f1)(A+B) correction as one fp6_mul."""
    f0, f1 = fp12_comp(f, 0), fp12_comp(f, 1)
    g0, g1, g2 = (fp6_comp(f1, i) for i in range(3))
    h0, h1, h2 = (fp6_comp(f0, i) for i in range(3))
    p = fq2.mul_many([
        (h0, c00), (h1, c00), (h2, c00),      # t0 = f0 · A
        (g1, c12), (g2, c11),                 # t1 v^0 parts (×ξ)
        (g0, c11), (g2, c12),                 # t1 v^1 parts
        (g0, c12), (g1, c11),                 # t1 v^2 parts
    ])
    t0 = fp6_pack(p[0], p[1], p[2])
    t1 = fp6_pack(
        fq2.mul_by_xi(fq2.add(p[3], p[4])),
        fq2.add(p[5], fq2.mul_by_xi(p[6])),
        fq2.add(p[7], p[8]),
    )
    ab = fp6_pack(c00, c11, c12)
    t2 = fp6_mul(fp6_add(f0, f1), ab)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(t2, t0), t1)
    return fp12_pack(c0, c1)


# -- host interop -----------------------------------------------------------

def fp12_to_ints(a) -> list[int]:
    """LV (or raw (2, 3, 2, 24) array) → 12 canonical ints in
    (c0.a0.c0, c0.a0.c1, c0.a1.c0, ..., c1.a2.c1) order (host side)."""
    arr = np.asarray(a.arr if isinstance(a, LV) else a)
    return fql.from_mont_ints(arr.reshape(-1, 24))


def fp12_from_ints(vals) -> LV:
    """Inverse of fp12_to_ints: 12 ints → R'-Montgomery LV."""
    arr = fql.to_mont_cols(list(vals)).reshape(2, 3, 2, 24)
    return fql.lv_canon(jnp.asarray(arr))