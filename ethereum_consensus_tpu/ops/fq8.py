"""Experimental MXU-shaped Montgomery multiplication (8-bit limb columns).

The lazy tower (ops/fql.py) made the batched pairing COMPILE and run
correct, but on chips that emulate wide-integer lane multiplies (v5e)
its u64 column products lose to the native ADX backend. The TPU's
arithmetic actually lives in the MXU, whose integer path is
int8×int8→int32. This module re-shapes the schoolbook column product to
feed it:

    a, b in 48 8-bit limbs;   outer[n, i, j] = a8[n, i] · b8[n, j]
    cols[n, k] = Σ_{i+j=k} outer[n, i, j]
               = (outer reshaped to (n, 2304)) @ M        # one matmul
    with M[(i, j), k] = [i + j == k], a constant 0/1 (2304, 95) operand.

Every accumulation is exact in int32 (48 terms × 255² < 2^22). The
contraction as implemented is int32×int32 (outer-product values exceed
int8, and jax dot_general needs matching operand dtypes): it reshapes
the reduction into MXU-tileable matmul form but does NOT yet hit the
int8×int8→int32 fast path itself — that needs the digits as a matmul
operand, i.e. ≤7-bit limbs (55 per value) so they fit SIGNED int8, with
per-element shift matrices. This module is the first step (a correct
matmul-shaped product + byte-granular reduction); the 7-bit
reformulation is the follow-up, to be measured on hardware before any
routing. The Montgomery reduction that follows is the same
column-serial sweep as fql.mont at byte granularity (52 rounds).

STATUS: correctness-complete and cross-checked against fql.mont
(tests/test_ops_pairing.py::test_fq8_matmul_product_matches_fql); NOT
routed into the pairing yet — flipping ops/pairing.py onto this layer
(and measuring it on real hardware) is the planned path to enabling
`install(pairing_min_sets=...)` by default. See docs/DEVICE_PAIRING.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fql

__all__ = ["product_cols8", "mont8", "lv_mont8"]

L8 = 48          # 8-bit limbs per 384-bit value
COLS8 = 2 * L8 - 1

# constant anti-diagonal contraction matrix: (i*48+j, k) -> [i+j == k]
_M = np.zeros((L8 * L8, COLS8), dtype=np.int8)
for _i in range(L8):
    for _j in range(L8):
        _M[_i * L8 + _j, _i + _j] = 1


def lv_mont8(a: "fql.LV", b: "fql.LV") -> "fql.LV":
    """Bound-checked entry point: mont8 REQUIRES canonical 16-bit columns
    (mont outputs) — unlike fql.mont it does NOT accept lazily-redundant
    values (_to8 would silently drop bits 16+). The trace-time assert
    makes that precondition loud, the same discipline as fql.lv_mont."""
    assert a.cmax <= (1 << 16) and b.cmax <= (1 << 16), (
        "mont8 needs canonical 16-bit columns; canonicalize redundant "
        f"values first (got cmax {a.cmax:#x}, {b.cmax:#x})"
    )
    return fql.lv_canon(mont8(a.arr, b.arr))


def _to8(cols16):
    """(..., 24) 16-bit columns -> (..., 48) 8-bit columns (int32 lanes).
    Inputs MUST be mont outputs (exact 16-bit columns) — higher bits are
    dropped; use lv_mont8 for the checked entry point."""
    lo = (cols16 & jnp.uint64(0xFF)).astype(jnp.int32)
    hi = ((cols16 >> jnp.uint64(8)) & jnp.uint64(0xFF)).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(cols16.shape[:-1] + (L8,))


def product_cols8(a16, b16):
    """Full 95-column schoolbook product of two 16-bit-column values via
    the outer-product ⊗ constant-matrix contraction. Returns (..., 95)
    int64 columns of the exact integer product (8-bit column weights)."""
    a8 = _to8(a16)
    b8 = _to8(b16)
    outer = (a8[..., :, None] * b8[..., None, :]).reshape(
        a8.shape[:-1] + (L8 * L8,)
    )
    # the MXU-shaped contraction: (..., 2304) @ (2304, 95) with exact
    # int32 accumulation (48 terms x 255^2 < 2^22)
    cols = jax.lax.dot_general(
        outer,
        jnp.asarray(_M, jnp.int32),
        (((outer.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return cols.astype(jnp.int64)


_P8 = np.zeros(L8, dtype=np.int64)
for _i in range(L8):
    _P8[_i] = (fql.P_INT >> (8 * _i)) & 0xFF


def mont8(a16, b16):
    """Montgomery product a·b·(2^416)⁻¹ mod-ish p, MXU-product variant.

    The 95-column exact product feeds the same column-serial reduction as
    fql.mont but at 8-bit granularity (52 rounds): m = low byte × n0',
    add m·p's byte columns, shift. Output is identical to
    ``fql.mont(a16, b16)`` — 16-bit columns, value < 1.1p — verified
    column-exact in tests."""
    n0_8 = (-pow(fql.P_INT, -1, 1 << 8)) % (1 << 8)
    cols = product_cols8(a16, b16)
    batch = cols.shape[:-1]
    t = jnp.concatenate(
        [cols, jnp.zeros(batch + (5,), jnp.int64)], axis=-1
    ).astype(jnp.uint64)
    p8 = jnp.asarray(_P8.astype(np.uint64))
    mask8 = jnp.uint64(0xFF)
    rounds = 52  # R' = 2^416 = 2^(8·52)

    def step(i, t):
        m = (t[..., 0] * jnp.uint64(n0_8)) & mask8
        t = t.at[..., :L8].add(m[..., None] * p8)
        carry0 = t[..., 0] >> jnp.uint64(8)
        shifted = jnp.concatenate(
            [t[..., 1:], jnp.zeros(batch + (1,), jnp.uint64)], axis=-1
        )
        return shifted.at[..., 0].add(carry0)

    t = jax.lax.fori_loop(0, rounds, step, t)

    def carry_step(carry, col):
        v = col + carry
        return v >> jnp.uint64(8), v & mask8

    _, limbs8 = jax.lax.scan(
        carry_step, jnp.zeros(batch, jnp.uint64), jnp.moveaxis(t, -1, 0)
    )
    limbs8 = jnp.moveaxis(limbs8, 0, -1)[..., :L8]
    # back to 16-bit columns
    lo = limbs8[..., 0::2]
    hi = limbs8[..., 1::2]
    return lo | (hi << jnp.uint64(8))
