"""Experimental MXU-shaped Montgomery multiplication (8-bit limb columns).

The lazy tower (ops/fql.py) made the batched pairing COMPILE and run
correct, but on chips that emulate wide-integer lane multiplies (v5e)
its u64 column products lose to the native ADX backend. The TPU's
arithmetic actually lives in the MXU, whose integer path is
int8×int8→int32. This module re-shapes the schoolbook column product to
feed it:

    a, b in 48 8-bit limbs;   outer[n, i, j] = a8[n, i] · b8[n, j]
    cols[n, k] = Σ_{i+j=k} outer[n, i, j]
               = (outer reshaped to (n, 2304)) @ M        # one matmul
    with M[(i, j), k] = [i + j == k], a constant 0/1 (2304, 95) operand.

Every accumulation is exact in int32 (48 terms × 255² < 2^22). The
contraction as implemented is int32×int32 (outer-product values exceed
int8, and jax dot_general needs matching operand dtypes): it reshapes
the reduction into MXU-tileable matmul form but does NOT yet hit the
int8×int8→int32 fast path itself. `product_cols7`/`mont7` DO: 7-bit
digits (55 per value, fitting SIGNED int8) form per-element shifted
digit matrices, and the whole product is one batched int8 dot_general
with exact int32 accumulation (55 terms × 127² < 2^20) — the MXU's
native integer path. Hardware measurement decides routing. The Montgomery reduction that follows is the same
column-serial sweep as fql.mont at byte granularity (52 rounds).

STATUS: ROUTED (round 4). `mont7r` generalizes `mont7` to the lazy
tower's redundant operands and is a drop-in for ``fql.mont``, selected
by ``fql.set_multiplier("mxu")`` / ``EC_PAIRING_MULT=mxu``; correctness
is pinned by tests/test_ops_pairing.py (column-exact vs fql.mont on
redundant and canonical inputs, full batch-verdict parity under the mxu
multiplier). ``bench.py bench_pairing_device`` measures both
multipliers; the live-chip crossover decides the default
(`install(pairing_min_sets=...)`). See docs/DEVICE_PAIRING.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fql

__all__ = ["product_cols8", "mont8", "lv_mont8", "product_cols7", "mont7"]

L8 = 48          # 8-bit limbs per 384-bit value
COLS8 = 2 * L8 - 1

# constant anti-diagonal contraction matrix: (i*48+j, k) -> [i+j == k]
_M = np.zeros((L8 * L8, COLS8), dtype=np.int8)
for _i in range(L8):
    for _j in range(L8):
        _M[_i * L8 + _j, _i + _j] = 1


def lv_mont8(a: "fql.LV", b: "fql.LV") -> "fql.LV":
    """Bound-checked entry point: mont8 REQUIRES canonical 16-bit columns
    (mont outputs) — unlike fql.mont it does NOT accept lazily-redundant
    values (_to8 would silently drop bits 16+). The trace-time assert
    makes that precondition loud, the same discipline as fql.lv_mont."""
    assert a.cmax <= (1 << 16) and b.cmax <= (1 << 16), (
        "mont8 needs canonical 16-bit columns; canonicalize redundant "
        f"values first (got cmax {a.cmax:#x}, {b.cmax:#x})"
    )
    return fql.lv_canon(mont8(a.arr, b.arr))


def _to8(cols16):
    """(..., 24) 16-bit columns -> (..., 48) 8-bit columns (int32 lanes).
    Inputs MUST be mont outputs (exact 16-bit columns) — higher bits are
    dropped; use lv_mont8 for the checked entry point."""
    lo = (cols16 & jnp.uint64(0xFF)).astype(jnp.int32)
    hi = ((cols16 >> jnp.uint64(8)) & jnp.uint64(0xFF)).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(cols16.shape[:-1] + (L8,))


def product_cols8(a16, b16):
    """Full 95-column schoolbook product of two 16-bit-column values via
    the outer-product ⊗ constant-matrix contraction. Returns (..., 95)
    int64 columns of the exact integer product (8-bit column weights)."""
    a8 = _to8(a16)
    b8 = _to8(b16)
    outer = (a8[..., :, None] * b8[..., None, :]).reshape(
        a8.shape[:-1] + (L8 * L8,)
    )
    # the MXU-shaped contraction: (..., 2304) @ (2304, 95) with exact
    # int32 accumulation (48 terms x 255^2 < 2^22)
    cols = jax.lax.dot_general(
        outer,
        jnp.asarray(_M, jnp.int32),
        (((outer.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return cols.astype(jnp.int64)


_P8 = np.zeros(L8, dtype=np.int64)
for _i in range(L8):
    _P8[_i] = (fql.P_INT >> (8 * _i)) & 0xFF


def _reduce8(t):
    """Byte-granular Montgomery reduction of deferred uint64 byte columns
    (value weight 2^(8i)): 52 rounds for R' = 2^416, carry-normalize,
    regroup to 16-bit columns. Shared by mont8 and mont7."""
    n0_8 = (-pow(fql.P_INT, -1, 1 << 8)) % (1 << 8)
    batch = t.shape[:-1]
    p8 = jnp.asarray(_P8.astype(np.uint64))
    mask8 = jnp.uint64(0xFF)
    rounds = 52  # R' = 2^416 = 2^(8·52)

    def step(i, t):
        m = (t[..., 0] * jnp.uint64(n0_8)) & mask8
        t = t.at[..., :L8].add(m[..., None] * p8)
        carry0 = t[..., 0] >> jnp.uint64(8)
        shifted = jnp.concatenate(
            [t[..., 1:], jnp.zeros(batch + (1,), jnp.uint64)], axis=-1
        )
        return shifted.at[..., 0].add(carry0)

    t = jax.lax.fori_loop(0, rounds, step, t)

    def carry_step(carry, col):
        v = col + carry
        return v >> jnp.uint64(8), v & mask8

    _, limbs8 = jax.lax.scan(
        carry_step, jnp.zeros(batch, jnp.uint64), jnp.moveaxis(t, -1, 0)
    )
    limbs8 = jnp.moveaxis(limbs8, 0, -1)[..., :L8]
    lo = limbs8[..., 0::2]
    hi = limbs8[..., 1::2]
    return lo | (hi << jnp.uint64(8))


def mont8(a16, b16):
    """Montgomery product a·b·(2^416)⁻¹ mod-ish p, MXU-shaped product
    (int32 contraction) + byte-granular reduction. Output is identical to
    ``fql.mont(a16, b16)`` — 16-bit columns, value < 1.1p — verified
    column-exact in tests."""
    cols = product_cols8(a16, b16)
    batch = cols.shape[:-1]
    t = jnp.concatenate(
        [cols, jnp.zeros(batch + (5,), jnp.int64)], axis=-1
    ).astype(jnp.uint64)
    return _reduce8(t)


# -- the TRUE int8×int8→int32 form: 7-bit digits ---------------------------

L7 = 55          # 7-bit digits per 384-bit value (55·7 = 385)
COLS7 = 2 * L7 - 1


def _to7(cols16):
    """(..., 24) exact 16-bit columns → (..., 55) 7-bit digits as SIGNED
    int8 (digits ≤ 127 fit). Same canonical-input precondition as _to8."""
    # bits via pairwise extraction: digit d covers bits [7d, 7d+7)
    out = []
    for d in range(L7):
        lo_bit = 7 * d
        q, r = divmod(lo_bit, 16)
        v = cols16[..., q] >> jnp.uint64(r)
        if r > 9 and q + 1 < 24:  # digit straddles the column boundary
            v = v | (cols16[..., q + 1] << jnp.uint64(16 - r))
        out.append((v & jnp.uint64(0x7F)).astype(jnp.int8))
    return jnp.stack(out, axis=-1)


def product_cols7(a16, b16):
    """Exact 109-column 7-bit-weighted product via a BATCHED int8 matmul:
    cols7[n, k] = Σ_j b7[n, j] · A[n, j, k] with A[n, j, k] = a7[n, k−j]
    (shifted copies of a's digit vector). Both dot_general operands are
    int8 with int32 accumulation — the MXU's native integer path — and
    every sum is exact (55 terms × 127² < 2^20)."""
    a7 = _to7(a16)
    b7 = _to7(b16)
    batch = a7.shape[:-1]
    shifted = []
    zero = jnp.zeros(batch + (1,), jnp.int8)
    for j in range(L7):
        row = a7
        if j:
            pad = jnp.zeros(batch + (j,), jnp.int8)
            row = jnp.concatenate([pad, a7], axis=-1)
        tail = COLS7 - row.shape[-1]
        if tail > 0:
            row = jnp.concatenate(
                [row, jnp.zeros(batch + (tail,), jnp.int8)], axis=-1
            )
        shifted.append(row)
    A = jnp.stack(shifted, axis=-2)          # (..., 55, 109) int8
    del zero
    # batched (..., 1, 55) @ (..., 55, 109) int8 matmul, int32 accumulate
    nb = len(batch)
    cols = jax.lax.dot_general(
        b7[..., None, :],
        A,
        (((nb + 1,), (nb,)), (tuple(range(nb)), tuple(range(nb)))),
        preferred_element_type=jnp.int32,
    )[..., 0, :]
    return cols.astype(jnp.int64)


def mont7(a16, b16):
    """Montgomery product via the TRUE int8 MXU product (product_cols7):
    the 7-bit-weighted columns regroup into byte-weighted uint64 columns
    (static shift-adds, exact), then the shared byte-granular reduction.
    Column-exact vs fql.mont — verified in tests."""
    cols7 = product_cols7(a16, b16).astype(jnp.uint64)
    batch = cols7.shape[:-1]
    t = jnp.zeros(batch + (2 * L8 + 4,), jnp.uint64)
    for i in range(COLS7):
        lo_bit = 7 * i
        q, r = divmod(lo_bit, 8)
        t = t.at[..., q].add(cols7[..., i] << jnp.uint64(r))
    # columns now byte-weighted but with values up to ~2^27 each — the
    # deferred-carry reduction tolerates that (accumulator ≪ 2^64)
    return _reduce8(t)


# -- mont7r: the int8 MXU product for REDUNDANT inputs ----------------------
#
# fql.mont's callers (the whole pairing tower) feed lazily-redundant
# columns (< 2^24, value < ~2^397) that _to7's bit-slicing cannot take
# directly. mont7r normalizes each operand first with one carry scan
# (exact, 25 16-bit columns), then runs the digit extraction, the batched
# int8 matmul, and the byte regroup fully vectorized — a handful of XLA
# ops per multiply, so the Miller loop's thousands of monts stay
# compilable. Drop-in replacement for fql.mont (same R' = 2^416, same
# output form); routed via fql.set_multiplier / EC_PAIRING_MULT.

L7R = 58            # 7-bit digits covering 25 16-bit columns (406 ≥ 400 bits)
COLS7R = 2 * L7R - 1
NORM_COLS = 25

_D7_Q = np.array([(7 * d) // 16 for d in range(L7R)])
_D7_R = np.array([(7 * d) % 16 for d in range(L7R)], np.uint64)
# A[n, j, k] = a7[n, k - j]: one gather with an out-of-range slot -> 0
_K_MINUS_J = np.arange(COLS7R)[None, :] - np.arange(L7R)[:, None]
_KJ_IDX = np.where(
    (_K_MINUS_J >= 0) & (_K_MINUS_J < L7R), _K_MINUS_J, L7R
)  # (58, 115); index L7R hits the appended zero slot
_R7_Q = np.array([(7 * i) // 8 for i in range(COLS7R)])
_R7_S = np.array([(7 * i) % 8 for i in range(COLS7R)], np.uint64)


def carry_norm(cols):
    """Exact carry propagation: redundant (..., 24) uint64 columns
    (value < 2^400) → (..., 25) canonical 16-bit columns."""
    batch = cols.shape[:-1]
    mask = jnp.uint64(fql.MASK)

    def step(carry, col):
        v = col + carry
        return v >> jnp.uint64(16), v & mask

    carry, out = jax.lax.scan(
        step, jnp.zeros(batch, jnp.uint64), jnp.moveaxis(cols, -1, 0)
    )
    out = jnp.moveaxis(out, 0, -1)
    return jnp.concatenate([out, carry[..., None]], axis=-1)


def _to7r(cols25):
    """(..., 25) exact 16-bit columns → (..., 58) 7-bit digits as SIGNED
    int8, fully vectorized (two gathers + shifts)."""
    padded = jnp.concatenate(
        [cols25, jnp.zeros(cols25.shape[:-1] + (1,), jnp.uint64)], axis=-1
    )
    c0 = padded[..., _D7_Q]
    c1 = padded[..., _D7_Q + 1]
    r = jnp.asarray(_D7_R)
    # bits ≥ 7 are masked off, so the uniform (16 − r) splice is exact
    # for every r (at r = 0 the c1 term lands at bit 16, masked away)
    v = (c0 >> r) | (c1 << (jnp.uint64(16) - r))
    return (v & jnp.uint64(0x7F)).astype(jnp.int8)


def product_cols7r(a25, b25):
    """Exact 115-column 7-bit-weighted product of two 25-column values via
    the batched int8 matmul (int32 accumulation: 58 terms × 127² < 2^20)."""
    a7 = _to7r(a25)
    b7 = _to7r(b25)
    batch = a7.shape[:-1]
    a7p = jnp.concatenate(
        [a7, jnp.zeros(batch + (1,), jnp.int8)], axis=-1
    )
    A = a7p[..., _KJ_IDX]                      # (..., 58, 115) int8
    nb = len(batch)
    cols = jax.lax.dot_general(
        b7[..., None, :],
        A,
        (((nb + 1,), (nb,)), (tuple(range(nb)), tuple(range(nb)))),
        preferred_element_type=jnp.int32,
    )[..., 0, :]
    return cols.astype(jnp.uint64)


def mont7r(a, b):
    """Montgomery product a·b·(2^416)⁻¹ for REDUNDANT operands — the
    drop-in MXU-path replacement for ``fql.mont``: same input contract
    (uint64 columns < 2^24, values < ~2^397), same output (exact 16-bit
    columns, value < 1.1·p). Verified column-exact vs fql.mont in
    tests/test_ops_pairing.py."""
    # fql.mont broadcasts (e.g. mont(x, ONE_COLS) canonicalizes a batch
    # against one constant); the batched dot_general needs explicit
    # common batch shapes
    if a.shape != b.shape:
        shape = jnp.broadcast_shapes(a.shape, b.shape)
        a = jnp.broadcast_to(a, shape)
        b = jnp.broadcast_to(b, shape)
    cols7 = product_cols7r(carry_norm(a), carry_norm(b))
    batch = cols7.shape[:-1]
    shifted = cols7 << jnp.asarray(_R7_S)
    t = (
        jnp.zeros(batch + (2 * L8 + 4,), jnp.uint64)
        .at[..., _R7_Q]
        .add(shifted)
    )
    return _reduce8(t)
