"""Lazy-reduction device field arithmetic for the batched pairing stack.

Why a second field layer (vs ops/fq.py): the strict kernels canonicalize
after every add/sub with compare-and-subtract chains (`_geq` + borrow
propagation). Those long sequential integer chains are precisely what
XLA's optimizer chokes on — a single strict Fq2 multiply costs ~17s of
compile time, which makes a Miller loop (thousands of field ops)
uncompilable. This layer removes every comparison from the hot path:

* Elements are (..., 24) **uint64** columns of 16-bit limbs, but columns
  may exceed 16 bits between multiplications (redundant form). Values are
  bounded, never canonical: every element is ≡ its value mod p with
  columns < 2^24 and the 24-column integer < 2^397.
* Addition is a plain elementwise `+` (one XLA op). Subtraction adds a
  precomputed redistributed multiple of p (``SUB_PAD`` ≈ 2^391, every
  column ≥ 2^23 − 16) so columns never underflow: requires the
  subtrahend's columns < 2^23 − 16 — audited per formula; the deepest
  chains in fq12's line multiply stay below 2^22.5.
* Multiplication is Montgomery CIOS with **R' = 2^416** (26 rounds).
  The two extra rounds buy slack: for input VALUES up to ~2^397 (far
  beyond anything the formulas produce, pads included) the output is
  < 1.1·p with exact 16-bit columns, WITHOUT any conditional
  subtraction. An output that is ≡ 0 mod p is exactly 0 or exactly p,
  which is what `is_zero_cols` pattern-checks.
* Export to canonical integers reduces mod p on host (ints are exact).

Bit-identical parity with the strict/native backends is checked on
canonical exports (tests/test_ops_pairing.py) — the internal R' form is
invisible outside this package.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import _env
from . import fq as _strict

__all__ = [
    "P_INT",
    "LIMBS",
    "ONE_MONT",
    "to_mont_cols",
    "from_mont_ints",
    "mont",
    "add",
    "sub",
    "dbl",
    "is_zero_cols",
]

P_INT = _strict.P_INT
LIMBS = 24
MASK = (1 << 16) - 1
R_PRIME = 1 << 416
R2_PRIME = (R_PRIME * R_PRIME) % P_INT
N0_INT = (-pow(P_INT, -1, 1 << 16)) % (1 << 16)

P_COLS = np.array([(P_INT >> (16 * i)) & MASK for i in range(LIMBS)], np.uint64)


def _int_to_cols(v: int) -> np.ndarray:
    return np.array([(v >> (16 * i)) & MASK for i in range(LIMBS)], np.uint64)


def _redistribute(value: int, slack_bits: int) -> np.ndarray:
    """Rewrite ``value`` as 24 columns each ≥ 2^slack − 16 (borrowing
    across columns), preserving the integer exactly."""
    cols = []
    rem = value
    for i in range(LIMBS - 1):
        d = (rem >> (16 * i)) & MASK
        ci = d + (1 << slack_bits)
        cols.append(ci)
        rem -= ci << (16 * i)
    top = rem >> (16 * (LIMBS - 1))
    assert 0 < top < (1 << (slack_bits + 3)), hex(top)
    cols.append(top)
    assert sum(v << (16 * i) for i, v in enumerate(cols)) == value
    assert all(c >= (1 << slack_bits) - 16 for c in cols)
    return np.array(cols, np.uint64)


# ~2^391 multiple of p, every column ≥ 2^23 − 16 — covers any subtrahend
# the formulas produce (audited bound: < 2^22.5 per column)
SUB_PAD = _redistribute(((1 << 391) // P_INT + 1) * P_INT, 23)
# top column of SUB_PAD must also dominate the subtrahend's top column
assert SUB_PAD[-1] >= (1 << 23)

ONE_MONT = _int_to_cols(R_PRIME % P_INT)  # 1 in R'-Montgomery form


def add(a, b):
    return a + b


def dbl(a):
    return a + a


def sub(a, b):
    """(a − b) + SUB_PAD, columnwise nonnegative for b cols < 2^23 − 16."""
    return (a + jnp.asarray(SUB_PAD)) - b


# Which product kernel `mont` runs: "u64" = the CIOS fori_loop below
# (wide-integer lane products); "mxu" = the int8 digit matmul
# (fq8.mont7r — the MXU's native int8×int8→int32 path). Same contract
# either way; the switch exists because which one wins is a per-chip
# hardware question (v5e emulates u64 lane products; see
# docs/DEVICE_PAIRING.md and bench.py bench_pairing_device).
_MULTIPLIER = _env.raw("EC_PAIRING_MULT", "u64")


def set_multiplier(kind: str) -> None:
    """Switch the pairing-stack product kernel ("u64" | "mxu").

    Clears every jit cache: compiled pairing traces bake the multiplier
    in, so stale executables must not outlive the switch."""
    global _MULTIPLIER
    assert kind in ("u64", "mxu"), kind
    if kind != _MULTIPLIER:
        _MULTIPLIER = kind
        jax.clear_caches()


def get_multiplier() -> str:
    return _MULTIPLIER


def mont(a, b):
    """Montgomery product a·b·R'⁻¹ (mod p up to one multiple): inputs are
    redundant columns (< 2^24, value < 2^397), output has exact 16-bit
    columns and value < 1.1·p. 26 CIOS rounds under one `fori_loop`,
    carry-normalized by one scan — no comparisons, no conditional
    subtraction. With the "mxu" multiplier selected the same contract is
    served by the int8 digit matmul instead (fq8.mont7r)."""
    if _MULTIPLIER == "mxu":
        from . import fq8

        return fq8.mont7r(a, b)
    p64 = jnp.asarray(P_COLS)
    n0 = jnp.uint64(N0_INT)
    mask = jnp.uint64(MASK)
    shift = jnp.uint64(16)
    batch = a.shape[:-1]
    apad = jnp.concatenate([a, jnp.zeros(batch + (2,), jnp.uint64)], axis=-1)
    t0 = jnp.zeros(batch + (LIMBS + 2,), jnp.uint64)

    def step(i, t):
        ai = jax.lax.dynamic_index_in_dim(apad, i, axis=-1, keepdims=True)
        t = t.at[..., :LIMBS].add(ai * b)
        m = (t[..., 0] * n0) & mask
        t = t.at[..., :LIMBS].add(m[..., None] * p64)
        carry0 = t[..., 0] >> shift
        shifted = jnp.concatenate(
            [t[..., 1:], jnp.zeros(batch + (1,), jnp.uint64)], axis=-1
        )
        return shifted.at[..., 0].add(carry0)

    t = jax.lax.fori_loop(0, LIMBS + 2, step, t0)

    def carry_step(carry, col):
        v = col + carry
        return v >> shift, v & mask

    _, limbs = jax.lax.scan(
        carry_step, jnp.zeros(batch, jnp.uint64), jnp.moveaxis(t, -1, 0)
    )
    return jnp.moveaxis(limbs, 0, -1)[..., :LIMBS]


def is_zero_cols(x):
    """x ≡ 0 mod p for a MONT OUTPUT (value < 1.1·p ⇒ value ∈ {0, p})."""
    zero = jnp.all(x == 0, axis=-1)
    isp = jnp.all(x == jnp.asarray(P_COLS), axis=-1)
    return zero | isp


_ONE_COLS = _int_to_cols(1)
R2_COLS = _int_to_cols(R2_PRIME)


def is_zero_any(x):
    """x ≡ 0 mod p for ANY redundant value: one mont by the integer 1
    canonicalizes (x·R'⁻¹, value < 1.1p), then pattern-checks {0, p}."""
    return is_zero_cols(mont(x, jnp.asarray(_ONE_COLS)))


def to_mont_device(x):
    """Plain canonical columns → R'-Montgomery form, on device."""
    return mont(x, jnp.asarray(R2_COLS))


# ---------------------------------------------------------------------------
# Bound-tracked lazy values: the hand-audit of column/value growth across
# the Fq12 tower is exactly the kind of bookkeeping that silently breaks
# (round-3 lesson: the first cut wrapped uint64 columns in fp12_mul).
# LV carries STATIC Python-int bounds beside the traced array; `lv_sub`
# picks the smallest adequate pad from a ladder and `lv_mont` asserts the
# no-overflow preconditions — any violation fails loudly at TRACE time,
# with zero runtime cost.
# ---------------------------------------------------------------------------

from typing import NamedTuple  # noqa: E402


class LV(NamedTuple):
    """A lazy field element (or stack of them): uint64 columns on the last
    axis plus static value/column upper bounds (exclusive)."""

    arr: "jax.Array"
    vmax: int
    cmax: int


class _Pad(NamedTuple):
    arr: np.ndarray
    value: int
    cmin: int
    cmax: int


def _make_pad(slack_bits: int) -> _Pad:
    # smallest multiple of p whose redistributed columns all reach the
    # slack floor
    need = sum((1 << slack_bits) << (16 * i) for i in range(LIMBS))
    m = need // P_INT + 1
    cols = _redistribute(m * P_INT, slack_bits)
    return _Pad(cols, m * P_INT, int(cols.min()), int(cols.max()))


_PAD_LADDER = [_make_pad(s) for s in range(17, 31)]

# Montgomery preconditions: output must stay < 2^384 (24 columns), and
# the CIOS accumulator columns must stay < 2^64.
_MAX_AB = ((1 << 384) - 1 - P_INT) * R_PRIME
_CANON_VMAX = P_INT + (P_INT >> 8)  # < 1.004·p covers every mont output


def lv_canon(arr) -> LV:
    """Wrap a mont output (16-bit columns, value < 1.004p)."""
    return LV(arr, _CANON_VMAX, 1 << 16)


def lv_const(value: int) -> LV:
    """R'-Montgomery constant."""
    return LV(jnp.asarray(to_mont_cols(value)), _CANON_VMAX, 1 << 16)


def lv_zero_like(a: LV) -> LV:
    return LV(jnp.zeros_like(a.arr), 1, 1)


def lv_add(a: LV, b: LV) -> LV:
    return LV(a.arr + b.arr, a.vmax + b.vmax, a.cmax + b.cmax)


def lv_dbl(a: LV) -> LV:
    return lv_add(a, a)


def lv_sub(a: LV, b: LV) -> LV:
    """a − b + (smallest ladder pad covering b's columns)."""
    for pad in _PAD_LADDER:
        if pad.cmin >= b.cmax:
            return LV(
                (a.arr + jnp.asarray(pad.arr)) - b.arr,
                a.vmax + pad.value,
                a.cmax + pad.cmax,
            )
    raise AssertionError(
        f"no pad covers subtrahend columns < {b.cmax:#x}; add a bigger "
        "ladder entry or normalize the operand"
    )


def lv_mont(a: LV, b: LV) -> LV:
    assert a.vmax * b.vmax <= _MAX_AB, (
        f"mont value overflow: vmax {a.vmax.bit_length()}+"
        f"{b.vmax.bit_length()} bits"
    )
    assert 32 * a.cmax * b.cmax < (1 << 63), (
        f"mont column overflow: cmax {a.cmax:#x} * {b.cmax:#x}"
    )
    return lv_canon(mont(a.arr, b.arr))


def lv_stack(items: "list[LV]", axis: int = 0) -> LV:
    return LV(
        jnp.stack([i.arr for i in items], axis=axis),
        max(i.vmax for i in items),
        max(i.cmax for i in items),
    )


def lv_coerce(arr, like: LV) -> LV:
    """Rebrand a raw array (e.g. a scan carry) with declared bounds."""
    return LV(arr, like.vmax, like.cmax)


def lv_assert_within(a: LV, vmax: int, cmax: int) -> LV:
    """Trace-time check that actual bounds fit a declared envelope (used
    at scan-carry boundaries, where bounds must be iteration-stable)."""
    assert a.vmax <= vmax and a.cmax <= cmax, (
        f"bounds exceed declared envelope: vmax 2^{a.vmax.bit_length()}"
        f" > 2^{vmax.bit_length()} or cmax {a.cmax:#x} > {cmax:#x}"
    )
    return LV(a.arr, vmax, cmax)


def to_mont_cols(values: "int | list[int]") -> np.ndarray:
    """Canonical int(s) → R'-Montgomery columns (host side)."""
    if isinstance(values, int):
        return _int_to_cols((values * R_PRIME) % P_INT)
    return np.stack([to_mont_cols(v) for v in values])


def from_mont_ints(cols) -> "int | list[int]":
    """R'-Montgomery columns (any redundancy) → canonical int(s), host."""
    arr = np.asarray(cols)
    if arr.ndim == 1:
        v = sum(int(c) << (16 * i) for i, c in enumerate(arr))
        return (v * pow(R_PRIME, -1, P_INT)) % P_INT
    return [from_mont_ints(row) for row in arr]
