"""Light-client document production off pipeline-committed snapshots.

Bootstraps, updates, finality + optimistic updates (the altair
light-client sync protocol objects) built from ``HeadStore`` snapshots:
the committed state supplies the sync committees and the header (its
``latest_block_header`` with ``state_root`` filled is the head block's
header — the ``head_block_root`` identity the serving oracle
test-asserts), the committed signed BLOCK — retained on the snapshot by
the pipeline's state channel since this PR — supplies the
``sync_aggregate``/``signature_slot`` pair and, on capella+, the body
the ``execution_branch`` is proven over. Every branch comes off the
warm stored-levels walker (proofs/extract.py), so producing an update
against a just-committed head costs tree-depth node reads.

Branch depths are derived from the ACTUAL state type via
``get_generalized_index`` — never hardcoded — which is also what pinned
the electra container drift this PR fixes (electra's 37-field state
pushes ``finalized_checkpoint.root`` to depth 7 and the sync committees
to depth 6; the inherited deneb vectors declared 6 and 5).

Unservable requests raise ``serving.oracle.BadRequest`` (handler 400)
or ``LookupError`` (handler 404): pre-altair states, snapshots without
a retained block where one is required, unretained attested/finalized
ancestors.
"""

from __future__ import annotations

from ..fork import Fork
from ..ssz import core as _core
from ..types import FORK_SEQUENCE, fork_module
from .extract import ProofContext

__all__ = [
    "fork_of",
    "light_client_header",
    "light_client_bootstrap",
    "light_client_update",
    "light_client_finality_update",
    "light_client_optimistic_update",
    "light_client_updates",
    "sync_committee_period",
]

_ZERO32 = b"\x00" * 32

# forks carrying an execution payload header inside LightClientHeader
_EXECUTION_HEADER_FORKS = ("capella", "deneb", "electra")


def _bad_request(message: str):
    from ..serving.oracle import BadRequest

    return BadRequest(message)


def fork_of(snap) -> str:
    """The snapshot's fork name — the wrapper's version tag when the
    pipeline published it, else detected from the container class."""
    if snap.fork:
        return snap.fork
    preset = snap.context.preset
    for fork in reversed(FORK_SEQUENCE):
        try:
            if type(snap.raw) is fork_module(fork).build(preset).BeaconState:
                return fork.name.lower()
        except Exception:  # noqa: BLE001 — kind absent in fork
            continue
    raise _bad_request("snapshot state is not a known BeaconState")


def _ns(snap):
    fork = fork_of(snap)
    if fork == "phase0":
        raise _bad_request("light-client data requires an altair+ state")
    return fork_module(Fork[fork.upper()]).build(snap.context.preset), fork


def _beacon_header(snap):
    """The snapshot's own block header: ``latest_block_header`` with the
    state root filled the way ``process_slot`` fills it (the snapshot
    root is that state root — no re-hash)."""
    header = snap.raw.latest_block_header.copy()
    if bytes(header.state_root) == _ZERO32:
        header.state_root = snap.root
    return header


def light_client_header(snap, ns=None, fork=None):
    """The fork's ``LightClientHeader`` for the snapshot's head block.
    capella+ headers embed the execution payload header (the state's
    ``latest_execution_payload_header`` IS the head block's, by
    ``process_execution_payload``) plus the ``execution_branch`` proven
    over the retained block body — no body retained, no header."""
    if ns is None:
        ns, fork = _ns(snap)
    beacon = _beacon_header(snap)
    if fork not in _EXECUTION_HEADER_FORKS:
        return ns.LightClientHeader(beacon=beacon)
    block = getattr(snap, "block", None)
    if block is None:
        raise _bad_request(
            f"{fork} light-client headers need the committed block "
            "(execution_branch is proven over its body); this snapshot "
            "retained none"
        )
    body = block.message.body
    body = getattr(body, "data", body)
    body_t = type(body)
    gi = _core.get_generalized_index(body_t, "execution_payload")
    branch = ProofContext(body_t, body).proof(gi)
    return ns.LightClientHeader(
        beacon=beacon,
        execution=snap.raw.latest_execution_payload_header.copy(),
        execution_branch=branch,
    )


def light_client_bootstrap(snap):
    """Spec ``create_light_client_bootstrap`` off one snapshot: the
    header, the state's CURRENT sync committee, and its branch extracted
    warm off the stored levels."""
    ns, fork = _ns(snap)
    state_t = type(snap.raw)
    gi = _core.get_generalized_index(state_t, "current_sync_committee")
    branch = ProofContext(state_t, snap.raw).proof(gi)
    return (
        ns.LightClientBootstrap(
            header=light_client_header(snap, ns, fork),
            current_sync_committee=snap.raw.current_sync_committee,
            current_sync_committee_branch=branch,
        ),
        fork,
    )


def _attested_for(store, snap):
    """(attested snapshot, sync_aggregate, signature_slot) for the block
    committed at ``snap``: the aggregate in the block body signs the
    PARENT block's state — resolved through the store's block-root
    index."""
    block = getattr(snap, "block", None)
    if block is None:
        raise _bad_request(
            "light-client updates need the committed block (its "
            "sync_aggregate signs the attested header); this snapshot "
            "retained none"
        )
    attested = store.resolve(bytes(block.message.parent_root))
    if attested is None:
        raise LookupError(
            "attested (parent) snapshot not retained by the store"
        )
    return attested, block.message.body.sync_aggregate, int(block.message.slot)


def _header_as(header, fork, ns_to, fork_to):
    """Spec ``upgrade_lc_header_to_*``: re-type ``header`` (built in
    ``fork``) as ``fork_to``'s ``LightClientHeader``. An update's
    finalized header can lag the attested fork across a boundary, but
    the update container is declared in the ATTESTED fork — fields the
    older fork lacks stay at their defaults, exactly as the spec's
    upgrade chain leaves them."""
    if fork_to == fork:
        return header
    out = ns_to.LightClientHeader.default()
    out.beacon = header.beacon
    if fork in _EXECUTION_HEADER_FORKS:  # fork_to is newer, so capella+
        for name in type(out.execution).fields():
            if hasattr(header.execution, name):
                setattr(out.execution, name, getattr(header.execution, name))
        out.execution_branch = list(header.execution_branch)
    return out


def _finalized_parts(store, attested):
    """(finalized_header, finality_branch) proven on the ATTESTED state.
    A zero finalized root (pre-finality) serves the spec's empty header;
    a non-zero root must resolve through the block-root index."""
    ns, fork = _ns(attested)
    state_t = type(attested.raw)
    gi = _core.get_generalized_index(
        state_t, "finalized_checkpoint", "root"
    )
    branch = ProofContext(state_t, attested.raw).proof(gi)
    fin_root = bytes(attested.raw.finalized_checkpoint.root)
    if fin_root == _ZERO32:
        return ns.LightClientHeader.default(), branch
    finalized = store.resolve(fin_root)
    if finalized is None:
        raise LookupError("finalized snapshot not retained by the store")
    return (
        _header_as(light_client_header(finalized), fork_of(finalized), ns, fork),
        branch,
    )


def light_client_update(store, snap=None):
    """Spec ``create_light_client_update`` for the block committed at
    ``snap`` (default: head): attested header + NEXT sync committee and
    branch proven on the attested state, the finality pair, and the
    block's sync aggregate."""
    snap = snap if snap is not None else store.head
    if snap is None:
        raise LookupError("no snapshot published")
    attested, aggregate, signature_slot = _attested_for(store, snap)
    ns, fork = _ns(attested)
    state_t = type(attested.raw)
    gi = _core.get_generalized_index(state_t, "next_sync_committee")
    next_branch = ProofContext(state_t, attested.raw).proof(gi)
    finalized_header, finality_branch = _finalized_parts(store, attested)
    return (
        ns.LightClientUpdate(
            attested_header=light_client_header(attested, ns, fork),
            next_sync_committee=attested.raw.next_sync_committee,
            next_sync_committee_branch=next_branch,
            finalized_header=finalized_header,
            finality_branch=finality_branch,
            sync_aggregate=aggregate,
            signature_slot=signature_slot,
        ),
        fork,
    )


def light_client_finality_update(store, snap=None):
    snap = snap if snap is not None else store.head
    if snap is None:
        raise LookupError("no snapshot published")
    attested, aggregate, signature_slot = _attested_for(store, snap)
    ns, fork = _ns(attested)
    finalized_header, finality_branch = _finalized_parts(store, attested)
    return (
        ns.LightClientFinalityUpdate(
            attested_header=light_client_header(attested, ns, fork),
            finalized_header=finalized_header,
            finality_branch=finality_branch,
            sync_aggregate=aggregate,
            signature_slot=signature_slot,
        ),
        fork,
    )


def light_client_optimistic_update(store, snap=None):
    snap = snap if snap is not None else store.head
    if snap is None:
        raise LookupError("no snapshot published")
    attested, aggregate, signature_slot = _attested_for(store, snap)
    ns, fork = _ns(attested)
    return (
        ns.LightClientOptimisticUpdate(
            attested_header=light_client_header(attested, ns, fork),
            sync_aggregate=aggregate,
            signature_slot=signature_slot,
        ),
        fork,
    )


def sync_committee_period(snap) -> int:
    ctx = snap.context
    return int(snap.slot) // (
        int(ctx.EPOCHS_PER_SYNC_COMMITTEE_PERIOD) * int(ctx.SLOTS_PER_EPOCH)
    )


def light_client_updates(store, start_period: int, count: int) -> list:
    """Best-effort ``updates?start_period=&count=``: one update per
    requested sync-committee period, produced from the NEWEST retained
    snapshot of that period whose attested ancestor is also retained —
    a bounded store serves the recent periods, exactly what a following
    light client polls for."""
    if count < 1:
        return []
    wanted = range(int(start_period), int(start_period) + int(count))
    out: dict = {}
    for snap in reversed(store.snapshots()):
        if getattr(snap, "block", None) is None:
            continue
        period = sync_committee_period(snap)
        if period not in wanted or period in out:
            continue
        try:
            out[period] = light_client_update(store, snap)
        except LookupError:
            continue  # unretained ancestor: an older snapshot may serve
        except Exception as exc:  # noqa: BLE001 — BadRequest only
            from ..serving.oracle import BadRequest

            if isinstance(exc, BadRequest):
                continue
            raise
    return [out[p] for p in sorted(out)]
