"""Batched multi-index proofs: spec multiproof layout over one level-walk.

``extract_multiproof`` resolves N generalized indices in a single pass
over the stored levels: the spec ``get_helper_indices`` layout dedupes
every shared ancestor up front (two leaves under one subtree need ONE
helper above their join, not two overlapping branches), and the shared
``ProofContext`` memoizes layer providers and group subtrees, so the
batch reads each stored-level node at most once.

The sub-group work — the only hashing a warm batch pays — is gathered
columnar: a planning pass names every 4096-chunk group the batch will
touch, and the gather rebuilds ALL of them in one set of level passes
over a single concatenated buffer (each group padded to full width so
rows stay aligned), instead of one small tree walk per group. The route
is decided by ``parallel/runtime.py``'s ``proof_gather`` gate exactly
like the merkle rebuilds: a provisioned mesh + enough chunks engages the
columnar path (whose big ``hash_level`` calls ride the installed device
hasher), anything else declines — journaled, never silent — and the
groups build lazily on the host.

Verification layout (consensus-specs ``ssz/merkle-proofs.md``):
``calculate_multi_merkle_root(leaves, proof, gindices)`` must equal the
object root; tests pin every leaf and helper byte-identical to the cold
``compute_subtree_root`` walk.
"""

from __future__ import annotations

from ..ssz.hash import hash_level, hash_pair
from ..ssz.merkle import BYTES_PER_CHUNK
from ..telemetry import metrics as _metrics
from .extract import ProofContext, _SubNodes

__all__ = [
    "Multiproof",
    "get_branch_indices",
    "get_path_indices",
    "get_helper_indices",
    "calculate_multi_merkle_root",
    "extract_multiproof",
]


# -- spec multiproof helpers (ssz/merkle-proofs.md) ---------------------------


def get_branch_indices(tree_index: int) -> "list[int]":
    """Sister-node gindices along the path from ``tree_index`` to the
    root — the nodes a single-item proof consists of."""
    out = [tree_index ^ 1]
    while out[-1] > 1:
        out.append((out[-1] // 2) ^ 1)
    return out[:-1]


def get_path_indices(tree_index: int) -> "list[int]":
    """Gindices on the path from ``tree_index`` to the root itself."""
    out = [tree_index]
    while out[-1] > 1:
        out.append(out[-1] // 2)
    return out[:-1]


def get_helper_indices(indices: "list[int]") -> "list[int]":
    """The minimal helper set for a multiproof of ``indices``: every
    branch sister not itself on (or derivable from) some path —
    deduped shared ancestors, sorted descending so leaves come first."""
    all_helper: set = set()
    all_path: set = set()
    for index in indices:
        all_helper.update(get_branch_indices(index))
        all_path.update(get_path_indices(index))
    return sorted(all_helper - all_path, reverse=True)


def calculate_multi_merkle_root(
    leaves: "list[bytes]", proof: "list[bytes]", indices: "list[int]"
) -> bytes:
    """Root from a spec-layout multiproof (the verifier side)."""
    if len(leaves) != len(indices):
        raise ValueError("one leaf per index required")
    helper_indices = get_helper_indices(indices)
    if len(proof) != len(helper_indices):
        raise ValueError(
            f"expected {len(helper_indices)} helpers, got {len(proof)}"
        )
    objects = dict(zip(indices, leaves))
    objects.update(zip(helper_indices, proof))
    keys = sorted(objects, reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and k ^ 1 in objects and k // 2 not in objects:
            objects[k // 2] = hash_pair(
                objects[(k | 1) ^ 1], objects[k | 1]
            )
            keys.append(k // 2)
        pos += 1
    return objects[1]


# -- batched extraction -------------------------------------------------------


class Multiproof:
    """One batch's result: ``leaves[i]`` proves ``gindices[i]``; ``proof``
    is the helper-node list in ``get_helper_indices`` order."""

    __slots__ = ("gindices", "leaves", "proof")

    def __init__(self, gindices, leaves, proof):
        self.gindices = list(gindices)
        self.leaves = list(leaves)
        self.proof = list(proof)

    def verify(self, root: bytes) -> bool:
        return (
            calculate_multi_merkle_root(
                self.leaves, self.proof, self.gindices
            )
            == root
        )


def _columnar_group_build(pending: dict) -> None:
    """Rebuild every pending 4096-chunk group subtree in one set of
    level passes over a single concatenated buffer: each group padded to
    full width keeps rows aligned through every halving, so one
    ``hash_level`` call per level covers the whole batch (and is big
    enough for the device hasher the mesh runtime installs). Providers
    are cohorted by their tree's group shift — uniform in production,
    but the shrunk-geometry fixtures can mix widths."""
    cohorts: dict = {}  # group_shift -> (jobs, segs)
    for prov, groups in pending.items():
        gs = prov._tree.level_offset
        jobs, segs = cohorts.setdefault(gs, ([], []))
        gbytes = (1 << gs) * BYTES_PER_CHUNK
        for g in sorted(groups):
            if g in prov._groups:
                continue
            seg = prov._group_chunks(g)
            if len(seg) < gbytes:
                seg = seg + b"\x00" * (gbytes - len(seg))
            jobs.append((prov, g))
            segs.append(seg)
    for gs, (jobs, segs) in cohorts.items():
        if not jobs:
            continue
        per_level: "list[list[bytes]]" = []
        nodes = b"".join(segs)
        width = 1 << gs
        for _ in range(gs):
            per_level.append(
                [
                    nodes[i * width * 32 : (i + 1) * width * 32]
                    for i in range(len(jobs))
                ]
            )
            nodes = hash_level(nodes)
            width //= 2
        per_level.append(
            [nodes[32 * i : 32 * (i + 1)] for i in range(len(jobs))]
        )
        for at, (prov, g) in enumerate(jobs):
            prov._groups[g] = _SubNodes(
                [per_level[d][at] for d in range(gs + 1)]
            )


def _pending_chunks(pending: dict) -> int:
    return sum(
        len(groups) << prov._tree.level_offset
        for prov, groups in pending.items()
    )


def extract_multiproof(
    ctx_or_typ, value=None, gindices=None
) -> Multiproof:
    """Resolve ``gindices`` into a spec-layout multiproof in one
    level-walk. Accepts a shared ``ProofContext`` or a (typ, value)
    pair; duplicate indices are rejected (the spec layout is a set)."""
    if isinstance(ctx_or_typ, ProofContext):
        ctx = ctx_or_typ
    else:
        ctx = ProofContext(ctx_or_typ, value)
    gindices = [int(g) for g in gindices]
    if len(set(gindices)) != len(gindices):
        raise ValueError("duplicate generalized indices in a multiproof")
    for g in gindices:
        if g < 1:
            raise ValueError("generalized index must be >= 1")
    helpers = get_helper_indices(gindices)

    # planning pass: walk every index with the plan sink armed, naming
    # each sub-group subtree the batch will need — node values are
    # placeholders, the descent shape is what we are after
    ctx.pending = {}
    try:
        for g in gindices:
            ctx.node_at(g)
        for h in helpers:
            ctx.node_at(h)
        pending = {
            prov: {g for g in groups if g not in prov._groups}
            for prov, groups in ctx.pending.items()
        }
        pending = {p: gs for p, gs in pending.items() if gs}
    finally:
        ctx.pending = None

    n_chunks = _pending_chunks(pending)
    if n_chunks:
        mesh = None
        try:
            from ..parallel import runtime as _runtime

            mesh = _runtime.proof_gather(n_chunks)
        except Exception:  # noqa: BLE001 — no runtime: lazy host builds
            mesh = None
        if mesh is not None:
            _columnar_group_build(pending)
        # declined: the groups build lazily (per-group host Trees) as
        # the resolution pass touches them — the gate journaled why

    leaves = [ctx.node_at(g) for g in gindices]
    proof = [ctx.node_at(h) for h in helpers]
    _metrics.counter("proofs.batched").inc()
    return Multiproof(gindices, leaves, proof)


# re-exported for the verifier-side convenience of callers that only
# ever see (leaves, proof, indices) triples
def verify_multiproof(
    leaves: "list[bytes]", proof: "list[bytes]", indices: "list[int]",
    root: bytes,
) -> bool:
    return calculate_multi_merkle_root(leaves, proof, indices) == root


__all__.append("verify_multiproof")
