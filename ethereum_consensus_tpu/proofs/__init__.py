"""The proof & light-client plane: stateless serving off stored levels.

Fourth data plane beside balances/duties/pool (docs/PROOFS.md):

* ``extract``    — single-branch generalized-index proofs read off the
                   incremental-HTR stored levels, cold ``Tree`` walk as
                   fallback + differential oracle, every large-layer
                   decline counted and journaled.
* ``multiproof`` — spec multiproof layout (``get_helper_indices`` /
                   ``calculate_multi_merkle_root``) with batched
                   extraction over one shared context; sub-group work
                   gathered columnar behind the mesh runtime's
                   ``proof_gather`` gate.
* ``light_client`` — ``LightClientBootstrap``/``Update``/finality/
                   optimistic production off ``HeadStore`` snapshots,
                   served at ``/eth/v1/beacon/light_client/*``.
"""

from .extract import ProofContext, extract_leaf, extract_proof
from .multiproof import (
    Multiproof,
    calculate_multi_merkle_root,
    extract_multiproof,
    get_helper_indices,
    verify_multiproof,
)

__all__ = [
    "ProofContext",
    "extract_proof",
    "extract_leaf",
    "Multiproof",
    "extract_multiproof",
    "get_helper_indices",
    "calculate_multi_merkle_root",
    "verify_multiproof",
]
