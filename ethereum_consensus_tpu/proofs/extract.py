"""Branch extraction off the stored-levels tree memos.

The fourth data plane (docs/PROOFS.md): a generalized-index walker that
serves single-branch Merkle proofs by READING the incremental-HTR
machinery instead of re-merkleizing. After a warm ``hash_tree_root``
walk, the big collections of a BeaconState carry stored levels —
``CachedRootList._pack_tree`` (packed basic / Bytes32 collections) and
``CachedRootList._tree_memo`` (scalar-leaf container registries), each
an ``IncrementalPaddedTree`` of 4096-chunk group mids (ssz/core.py) —
so every sibling at or above the group layer is a 32-byte slice read,
and the handful of sub-group siblings cost one 4096-chunk subtree
rebuild, memoized per extraction context.

Layers without stored levels materialize a full ``Tree`` over their top
chunks — the cold ``compute_merkle_proof`` walk, which doubles as the
differential oracle (``ssz.core.prove`` recomputes every sibling from
values; tests pin the two byte-identical). A LARGE layer (one whose
populated chunk count clears the dirty-tracking threshold) going cold is
a routing decision, never silent: each bumps a
``proofs.fallback.{reason}`` counter, journals a
``proofs.extract``/cold entry in the device observatory when it is
armed, and fires a one-shot re-armable trace event — the
parallel/runtime.py decline idiom (PR 10/15).
"""

from __future__ import annotations

import threading

from ..ssz import core as _core
from ..ssz.core import CachedRootList
from ..ssz.merkle import (
    BYTES_PER_CHUNK,
    Tree,
    next_pow_of_two,
    pack_bytes,
    zero_hash,
)
from ..telemetry import device as _device_obs
from ..telemetry import metrics as _metrics
from ..utils import trace

__all__ = [
    "ProofContext",
    "extract_proof",
    "extract_leaf",
]

# Group geometry is shared with the memo substrate (one stored-level
# node spans one 2^_DIRTY_GROUP_SHIFT-chunk subtree; a layer only ever
# CARRIES stored levels above _DIRTY_TRACK_MIN_CHUNKS populated chunks)
# and is read DYNAMICALLY — off each tree's level_offset and the live
# core globals — because the shrunk-geometry test fixtures rebind them.

# one-shot fallback events re-arm on reason change (the mesh runtime's
# _DECLINE_LAST discipline): a soak that flips causes journals every
# transition, while the counters keep counting every occurrence
_FALLBACK_LAST: dict = {}
_FALLBACK_LOCK = threading.Lock()


def _fallback(kind: str, reason: str, **inputs) -> None:
    """Count + journal + one-shot-event one large layer served cold."""
    _metrics.counter(f"proofs.fallback.{reason}").inc()
    if _device_obs.OBSERVATORY.active:
        _device_obs.route(f"proofs.{kind}", "cold", reason, **inputs)
    if _FALLBACK_LAST.get(kind) != reason:
        with _FALLBACK_LOCK:
            if _FALLBACK_LAST.get(kind) != reason:
                _FALLBACK_LAST[kind] = reason
                trace.event(
                    "proofs.fallback", kind=kind, reason=reason, **inputs
                )


def _warm(kind: str, **inputs) -> None:
    if _device_obs.OBSERVATORY.active:
        _device_obs.route(f"proofs.{kind}", "warm", "stored_levels", **inputs)


class _ColdLayer:
    """One merkle layer fully materialized (the cold walk): top chunks
    rebuilt into a ``Tree``, every node a lookup thereafter. This is
    also the only provider for small layers — a container's field roots
    come off the instance caches, so 'cold' there is a few hashes."""

    warm = False

    __slots__ = ("depth", "n_chunks", "value", "_tree")

    def __init__(self, typ, value):
        chunks = _core._top_level_chunk_bytes(typ, value)
        limit = next_pow_of_two(_core._chunk_count_of(typ))
        self.depth = (limit - 1).bit_length()
        self.n_chunks = len(chunks) // BYTES_PER_CHUNK
        self.value = value  # pins id() for the context's layer key
        self._tree = Tree(
            [chunks[i : i + 32] for i in range(0, len(chunks), 32)], limit
        )

    def node(self, d: int, idx: int) -> bytes:
        return self._tree.node(d, idx)


class _SubNodes:
    """Interior nodes of one 4096-chunk group subtree, prebuilt by the
    batched columnar gather (proofs/multiproof.py): per-level flat byte
    strings, every group padded to full width so node(d, i) is a slice."""

    __slots__ = ("_levels",)

    def __init__(self, levels: "list[bytes]"):
        self._levels = levels

    def node(self, d: int, idx: int) -> bytes:
        level = self._levels[d]
        return level[32 * idx : 32 * (idx + 1)]


class _StoredLevels:
    """Warm provider over a pack-tree / tree-memo: siblings at or above
    the group layer read straight off ``IncrementalPaddedTree.levels``;
    sub-group siblings build (and memoize) one 4096-chunk subtree per
    touched group — for a single proof every sub-group sibling shares
    the target leaf's group, so the whole branch costs one rebuild."""

    warm = True

    __slots__ = ("depth", "n_chunks", "value", "_tree", "_group_chunks",
                 "_groups", "_ctx")

    def __init__(self, tree, group_chunks, n_chunks, value, ctx):
        self._tree = tree  # IncrementalPaddedTree, levels all fresh
        self._group_chunks = group_chunks  # g -> packed chunk segment
        self._groups: dict = {}  # g -> Tree | _SubNodes
        self._ctx = ctx
        self.depth = tree.depth + tree.level_offset
        self.n_chunks = n_chunks
        self.value = value

    def node(self, d: int, idx: int) -> bytes:
        gs = self._tree.level_offset
        if d >= gs:
            td = d - gs
            levels = self._tree.levels
            if td < len(levels):
                off = 32 * idx
                level = levels[td]
                if off < len(level):
                    return bytes(level[off : off + 32])
            return zero_hash(d)
        g = idx >> (gs - d)
        local = idx & ((1 << (gs - d)) - 1)
        sub = self._groups.get(g)
        if sub is None:
            pending = self._ctx.pending
            if pending is not None:
                # planning pass of the batched gather: record the group,
                # hand back a placeholder (node VALUES never steer the
                # descent, so the plan walk stays shape-faithful)
                pending.setdefault(self, set()).add(g)
                return zero_hash(d)
            seg = self._group_chunks(g)
            if not seg:
                return zero_hash(d)
            sub = Tree(
                [seg[i : i + 32] for i in range(0, len(seg), 32)],
                1 << gs,
            )
            self._groups[g] = sub
        return sub.node(d, local)


def _pack_provider(typ, values, key, esize, ctx):
    """Stored-levels provider off ``_pack_tree`` (packed basic / Bytes32
    collections), or (None, decline_reason)."""
    pt = values._pack_tree
    if pt is None:
        return None, "no_memo"
    if pt[0] != key:
        return None, "memo_key"
    raw, tree = pt[1], pt[2]
    if len(raw) != len(values) * esize:
        return None, "stale_buffer"
    if tree._dirty is None or tree._dirty:
        return None, "stale_tree"
    dg = values._dirty_groups
    if dg is None or dg:
        return None, "dirty_groups"
    # group width comes off the TREE, not the module constant: the
    # shrunk-geometry test fixtures rebuild memos under a smaller shift
    cbytes = BYTES_PER_CHUNK << tree.level_offset

    def group_chunks(g, raw=raw, cbytes=cbytes):
        return pack_bytes(bytes(raw[g * cbytes : (g + 1) * cbytes]))

    n_chunks = (len(raw) + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
    prov = _StoredLevels(tree, group_chunks, n_chunks, values, ctx)
    if prov.depth != (next_pow_of_two(_core._chunk_count_of(typ)) - 1).bit_length():
        return None, "depth_mismatch"
    return prov, None


def _tree_provider(typ, values, tkey, ctx):
    """Stored-levels provider off ``_tree_memo`` (scalar-leaf container
    registries: chunks are the joined element roots)."""
    tm = values._tree_memo
    if tm is None:
        return None, "no_memo"
    if tm[0] != tkey:
        return None, "memo_key"
    chunks, tree = tm[1], tm[2]
    if tree is None:
        return None, "no_levels"
    if len(chunks) != BYTES_PER_CHUNK * len(values):
        return None, "stale_buffer"
    if tree._dirty is None or tree._dirty:
        return None, "stale_tree"
    dg = values._dirty_groups
    if dg is None or dg:
        # None = tracking never armed (or lost); non-empty = sticky
        # groups whose elements refuse caching — either way the next
        # mutation would not be named, so the walker declines
        return None, "dirty_groups"
    cbytes = BYTES_PER_CHUNK << tree.level_offset

    def group_chunks(g, chunks=chunks, cbytes=cbytes):
        return bytes(chunks[g * cbytes : (g + 1) * cbytes])

    prov = _StoredLevels(
        tree, group_chunks, len(chunks) // BYTES_PER_CHUNK, values, ctx
    )
    if prov.depth != (next_pow_of_two(_core._chunk_count_of(typ)) - 1).bit_length():
        return None, "depth_mismatch"
    return prov, None


def _populated_chunks(typ, value) -> int:
    if isinstance(typ, type) and issubclass(typ, _core.Container):
        return len(typ.__ssz_fields__)
    if isinstance(typ, (_core.Vector, _core.List)):
        if _core._is_basic(typ.elem):
            size = typ.elem.fixed_size()
            return (len(value) * size + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
        return len(value)
    if isinstance(typ, (_core.Bitvector, _core.Bitlist)):
        return (len(value) + 255) // 256
    if isinstance(typ, (_core.ByteVector, _core.ByteList)):
        return (len(value) + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK
    raise TypeError(f"cannot chunk {typ!r}")


def _build_layer(typ, value, ctx):
    """Provider for one merkle layer: warm stored levels when the memo
    substrate can serve them, cold ``Tree`` otherwise — with every
    large-layer decline counted and journaled."""
    n_chunks = _populated_chunks(typ, value)
    # dynamic read (not the import-time constant): the shrunk-geometry
    # fixtures lower the threshold so small layers classify as large
    large = n_chunks > _core._DIRTY_TRACK_MIN_CHUNKS
    prov = None
    reason = None
    if isinstance(typ, (_core.Vector, _core.List)):
        elem = typ.elem
        limit_elems = (
            typ.length if isinstance(typ, _core.Vector) else typ.limit
        )
        if not isinstance(value, CachedRootList):
            reason = "untracked_list"
        elif _core._is_basic(elem):
            key = ("u", elem, typ.chunk_count())
            prov, reason = _pack_provider(
                typ, value, key, elem.fixed_size(), ctx
            )
        elif isinstance(elem, _core.ByteVector) and elem.length == BYTES_PER_CHUNK:
            key = ("b32", elem, limit_elems)
            prov, reason = _pack_provider(typ, value, key, BYTES_PER_CHUNK, ctx)
        elif (
            isinstance(elem, type)
            and getattr(elem, "__ssz_scalar_leaf__", False)
        ):
            tkey = ("tree", elem, limit_elems)
            prov, reason = _tree_provider(typ, value, tkey, ctx)
        else:
            reason = "unsupported_kind"
    elif large:
        reason = "unsupported_kind"
    if prov is not None:
        _warm("extract", chunks=n_chunks, layer=type(typ).__name__)
        return prov
    if large:
        ctx.declines.append((type(typ).__name__, reason))
        _fallback(
            "extract", reason, chunks=n_chunks, layer=type(typ).__name__
        )
    return _ColdLayer(typ, value)


class ProofContext:
    """Extraction context for one (type, value): settles the incremental
    memos with a ``hash_tree_root`` walk (warm after a committed block:
    a memo hit), then resolves generalized indices to nodes through
    per-layer providers memoized across calls — a batch of proofs pays
    each layer and each 4096-chunk group subtree at most once."""

    def __init__(self, typ, value):
        self.typ = typ
        self.value = value
        # the settle: makes every eligible memo exist and match its
        # collection, and is the root every extracted branch must verify
        # against (warm case: served from the caches this walker reads)
        self.root = _core.hash_tree_root(typ, value)
        self.declines: list = []  # (layer_kind, reason) for large layers
        self.pending: "dict | None" = None  # batched-gather plan sink
        self._layers: dict = {}

    def _layer(self, typ, value):
        key = (id(typ), id(value))
        prov = self._layers.get(key)
        if prov is None:
            prov = _build_layer(typ, value, self)
            self._layers[key] = prov
        return prov

    def node_at(self, gindex: int, typ=None, value=None) -> bytes:
        """The 32-byte node at ``gindex`` in hash_tree_root(typ, value)
        — the warm twin of ``ssz.core.compute_subtree_root``."""
        if typ is None:
            typ, value = self.typ, self.value
        gindex = int(gindex)
        if gindex < 1:
            raise ValueError("generalized index must be >= 1")
        if gindex == 1:
            return _core.hash_tree_root(typ, value)
        bits = bin(gindex)[3:]  # descent path, MSB first
        if isinstance(typ, (_core.List, _core.Bitlist, _core.ByteList)):
            if bits[0] == "1":
                if len(bits) > 1:
                    raise ValueError("cannot descend into the length mix-in")
                return len(value).to_bytes(32, "little")
            bits = bits[1:]
            if not bits:
                prov = self._layer(typ, value)
                return prov.node(prov.depth, 0)
        prov = self._layer(typ, value)
        depth = prov.depth
        if len(bits) <= depth:
            return prov.node(depth - len(bits), int(bits, 2))
        chunk_index = int(bits[:depth], 2)
        elem_typ, elem_val = _core._element_at(typ, value, chunk_index)
        return self.node_at(int("1" + bits[depth:], 2), elem_typ, elem_val)

    def leaf(self, gindex: int) -> bytes:
        return self.node_at(gindex)

    def proof(self, gindex: int) -> "list[bytes]":
        """Single-branch proof for ``gindex``, leaf-level sibling first —
        the layout ``is_valid_merkle_branch_for_generalized_index``
        consumes, byte-identical to ``ssz.core.prove``."""
        g = int(gindex)
        if g < 1:
            raise ValueError("generalized index must be >= 1")
        branch = []
        while g > 1:
            branch.append(self.node_at(g ^ 1))
            g >>= 1
        _metrics.counter("proofs.served").inc()
        return branch

    def warm(self) -> bool:
        """True while no large layer has been served cold."""
        return not self.declines


def extract_proof(typ, value, gindex: int) -> "list[bytes]":
    """One-shot single-branch extraction (callers holding several
    requests against the same value should share a ``ProofContext``)."""
    return ProofContext(typ, value).proof(gindex)


def extract_leaf(typ, value, gindex: int) -> bytes:
    return ProofContext(typ, value).node_at(gindex)
