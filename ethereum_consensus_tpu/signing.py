"""Signing-root computation and domain-signed operations.

Reference parity: ethereum-consensus/src/signing.rs:7-30 (SigningData,
compute_signing_root, sign_with_domain, verify_signed_data).
"""

from __future__ import annotations

from .crypto import bls
from .error import InvalidSignatureError
from .ssz import ByteVector, Container

__all__ = [
    "SigningData",
    "compute_signing_root",
    "sign_with_domain",
    "verify_signed_data",
]


class SigningData(Container):
    """(signing.rs:7) — also re-exported via models.phase0.containers."""

    object_root: ByteVector[32]
    domain: ByteVector[32]


def compute_signing_root(ssz_type, value, domain: bytes) -> bytes:
    """hash_tree_root(SigningData(object_root, domain)).

    ``ssz_type`` is the SSZ descriptor/container class for ``value``; pass
    a Container instance alone by giving its class as the type."""
    object_root = ssz_type.hash_tree_root(value)
    return SigningData.hash_tree_root(
        SigningData(object_root=object_root, domain=domain)
    )


def sign_with_domain(ssz_type, value, secret_key: bls.SecretKey, domain: bytes) -> bytes:
    root = compute_signing_root(ssz_type, value, domain)
    return secret_key.sign(root).to_bytes()


def verify_signed_data(
    ssz_type, value, signature: bytes, public_key: bytes, domain: bytes
) -> None:
    """Raises InvalidSignatureError unless ``signature`` over the signing
    root verifies (signing.rs verify_signed_data)."""
    root = compute_signing_root(ssz_type, value, domain)
    pk = bls.PublicKey.from_bytes(public_key)
    sig = bls.Signature.from_bytes(signature)
    if not bls.verify_signature(pk, root, sig):
        raise InvalidSignatureError("signed data does not verify")
