"""Stage-B scheduling: bounded async dispatch of coalesced flushes.

The scheduler owns the pipeline's work queue discipline — and nothing
else. It knows signature batches and futures; it does NOT know states,
forks, or rollback (engine.py's job). Three rules:

* **Coalesce**: one dispatched window carries the merged signature sets
  of up to ``FlushPolicy.window_size`` consecutive blocks; the verifier
  proves them in ONE random-linear-combination multi-pairing (N+K Miller
  loops, one shared final exponentiation) via
  ``crypto.bls.verify_signature_sets`` — which itself routes to the
  native IFMA engine or, above the ``ops`` pairing threshold, the
  device/mesh pairing kernels.
* **Bound**: at most ``FlushPolicy.max_in_flight`` windows may be queued
  or running at once. ``dispatch`` on a full scheduler is a programming
  error (the engine settles the oldest window first — that blocking wait
  IS the backpressure, so an unbounded block stream cannot pile
  unverified speculative state in memory).
* **Order**: windows settle strictly FIFO (the verifier pool is a single
  worker), so chain order and commit order agree by construction.

Hardening (the scenario harness's fault targets, docs/SCENARIOS.md):
every settle is TIMEOUT-BOUNDED (``FlushPolicy.settle_timeout_s``; a
wedged worker raises ``PipelineBrokenError`` carrying the stuck window's
attribution instead of deadlocking the submitter), a
``TransientFlushError`` from the worker is retried with bounded backoff
(``flush_retries`` × ``retry_backoff_s``), and a worker death or any
other non-verdict crash degrades THAT window to in-line host
verification — the verdicts stay exact, only the overlap is lost. Every
path is counted (``pipeline.fault.*``, ``pipeline.degraded_flushes``)
and the process-wide ``pipeline.degraded`` gauge latches once any
window degraded.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import TimeoutError as _FutureTimeout

from .. import _env
from ..crypto import bls
from ..telemetry import metrics as _metrics
from ..utils import trace
from .errors import PipelineBrokenError, TransientFlushError, WorkerKilled
from .stats import PipelineStats

__all__ = ["FlushPolicy", "VerifyScheduler", "Window", "auto_verify_lanes"]

# verifier-lane auto-sizing cap: each lane is one persistent
# single-thread pool (crypto/bls._verify_pool), so an unbounded core
# count must not spawn an unbounded worker census
_AUTO_LANES_CAP = 8


def auto_verify_lanes() -> int:
    """The lane count an unset ``FlushPolicy(verify_lanes=...)``
    resolves to: ``min(cpu_cores, mesh devices)`` when the mesh runtime
    is switched on (``ECT_MESH`` — each mesh dispatch already owns the
    device axis, so more lanes than devices just queue), plain
    ``cpu_cores`` otherwise (one GIL-released native pairing per core),
    capped at 8 lanes and floored at 1. The consult is a plain env read
    first — a mesh-off process never imports jax here."""
    cores = os.cpu_count() or 1
    lanes = cores
    # the env read duplicates runtime.requested() on purpose: importing
    # ethereum_consensus_tpu.parallel pays the jax import, so the
    # mesh-off path must decide without it (the epoch_vector idiom)
    value = _env.mode("ECT_MESH")
    if value not in ("", "off", "0", "none", "host"):
        from ..parallel import runtime as _mesh_runtime

        devices = _mesh_runtime.device_count()
        if devices:
            lanes = min(cores, devices)
    return max(1, min(lanes, _AUTO_LANES_CAP))


class FlushPolicy:
    """When to cut a window and how many may be in flight.

    * ``window_size`` — blocks coalesced per flush. 1 = per-block flushes
      (the sequential batching PR 0 shipped, just asynchronous); larger
      windows amortize the final exponentiation and per-call overheads
      across blocks, at the cost of a coarser rollback granule.
    * ``max_in_flight`` — the bounded verify queue's cap (backpressure).
    * ``checkpoint_interval`` — every Nth dispatched window carries a
      full state snapshot for the commit bookkeeping. A snapshot is the
      only O(registry) cost the pipeline adds to the success path (the
      object-graph copy; root memos travel), so it is amortized: between
      checkpoints the committed position is represented as "newest
      checkpoint + proven blocks", and a rollback (rare, terminal)
      re-derives it by deterministic replay.
    * ``flush_empty`` — whether windows whose blocks deferred zero sets
      (Validation.DISABLED replay) still pass through the scheduler; off
      by default, they commit immediately.
    * ``settle_timeout_s`` — the bound on every settle wait: a window
      whose future hasn't resolved after this long raises
      ``PipelineBrokenError`` with the window's attribution. None
      disables the bound (NOT recommended — a wedged worker then hangs
      the submitter forever, which is exactly the failure mode this
      exists to close).
    * ``flush_retries`` — how many times a ``TransientFlushError`` from
      the worker is re-dispatched before the window degrades to in-line
      verification.
    * ``retry_backoff_s`` — base backoff before retry k (linear:
      ``k * retry_backoff_s``), bounding total stall to
      ``flush_retries * (flush_retries + 1) / 2 * retry_backoff_s``.
    * ``verify_lanes`` — how many single-thread verifier workers the
      windows fan over (``crypto.bls`` keeps one FIFO pool per lane).
      1 = the historical shared worker. With N lanes, window ``seq``
      dispatches to lane ``seq % N`` — DETERMINISTIC, so a replay hits
      the same lanes — and up to N windows verify concurrently (the
      native pairing releases the GIL, so N cores prove N windows at
      once). Settle order is untouched: the engine always settles the
      OLDEST window first and blocks on its future, so commits stay in
      chain order no matter which lane finishes first. Raise
      ``max_in_flight`` to at least ``verify_lanes`` or the backpressure
      wait will idle the extra lanes. Unset (``None``) auto-sizes from
      the machine: ``min(cpu_cores, mesh devices)`` under ``ECT_MESH``,
      ``cpu_cores`` otherwise, capped at 8 (``auto_verify_lanes`` — the
      production-soak default; a single-core box resolves to the
      historical 1).
    """

    __slots__ = (
        "window_size", "max_in_flight", "checkpoint_interval", "flush_empty",
        "settle_timeout_s", "flush_retries", "retry_backoff_s",
        "verify_lanes",
    )

    def __init__(self, window_size: int = 8, max_in_flight: int = 2,
                 checkpoint_interval: int = 8, flush_empty: bool = False,
                 settle_timeout_s: "float | None" = 300.0,
                 flush_retries: int = 2, retry_backoff_s: float = 0.05,
                 verify_lanes: "int | None" = None):
        if window_size < 1:
            raise ValueError("window_size must be >= 1")
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if checkpoint_interval < 1:
            raise ValueError("checkpoint_interval must be >= 1")
        if settle_timeout_s is not None and settle_timeout_s <= 0:
            raise ValueError("settle_timeout_s must be positive or None")
        if flush_retries < 0:
            raise ValueError("flush_retries must be >= 0")
        if verify_lanes is None:
            verify_lanes = auto_verify_lanes()
        if verify_lanes < 1:
            raise ValueError("verify_lanes must be >= 1")
        self.window_size = window_size
        self.max_in_flight = max_in_flight
        self.checkpoint_interval = checkpoint_interval
        self.flush_empty = flush_empty
        self.settle_timeout_s = settle_timeout_s
        self.flush_retries = flush_retries
        self.retry_backoff_s = retry_backoff_s
        self.verify_lanes = verify_lanes

    def __repr__(self) -> str:
        return (
            f"FlushPolicy(window_size={self.window_size}, "
            f"max_in_flight={self.max_in_flight}, "
            f"checkpoint_interval={self.checkpoint_interval}, "
            f"settle_timeout_s={self.settle_timeout_s}, "
            f"verify_lanes={self.verify_lanes})"
        )


class Window:
    """One dispatched flush: consecutive block entries, their merged
    signature batch, and — on checkpoint-carrying windows — the
    post-window state snapshot the engine installs as the new checkpoint
    when the verdicts come back clean (``post_state`` is None
    otherwise; the committed position is then checkpoint + blocks).
    ``attempts`` counts dispatches (retries of transient faults).

    The timing stamps are the flight recorder's raw material
    (telemetry/flight.py): ``t_dispatch``/``t_settled`` bound the settle
    wall, ``verify_s`` accumulates the worker's busy seconds across
    attempts (plus any in-line re-verification), and ``degraded``
    latches when the window fell back to host verification. They are
    written by the scheduler/worker strictly BEFORE the engine reads
    them at commit/rollback (the future's result is the happens-before
    edge), so no lock is needed."""

    __slots__ = (
        "entries", "batch", "post_state", "snap_state", "future", "seq",
        "attempts", "t_dispatch", "t_settled", "verify_s", "degraded",
        "verify_route", "trace_ctx",
    )

    def __init__(self, entries, batch, post_state, seq: int):
        self.entries = entries
        self.batch = batch
        self.post_state = post_state
        # serving-layer copy of the post-window state, taken at dispatch
        # when the live state IS the post-window state; published on the
        # commit hook's state channel when the verdicts come back clean
        # (None unless a HeadStore is attached — HOOK.state_active)
        self.snap_state = None
        self.future = None
        self.seq = seq
        self.attempts = 0
        self.t_dispatch = None
        self.t_settled = None
        self.verify_s = 0.0
        self.degraded = False
        # which pairing route proved this window's batch ("device" /
        # "host" / None when no RLC batch ran) — written by the worker
        # via the verify route_sink (same happens-before edge as the
        # timer), folded into BlockLineage.verify_route
        self.verify_route = None
        # the causal trace the window's blocks recorded under (a
        # utils/trace TraceContext anchored at the window's first
        # stage-A span; None when tracing is off) — the handoff token
        # the verify lane and settle path adopt, and the trace_id the
        # SLO histograms exemplar against
        self.trace_ctx = None


class VerifyScheduler:
    """Bounded FIFO dispatch onto the shared background verifier."""

    def __init__(self, policy: FlushPolicy, stats: PipelineStats,
                 fault_injector=None):
        self.policy = policy
        self.stats = stats
        self.fault_injector = fault_injector
        self._in_flight: list[Window] = []

    # -- queue state ---------------------------------------------------------
    @property
    def in_flight(self) -> int:
        return len(self._in_flight)

    @property
    def full(self) -> bool:
        return len(self._in_flight) >= self.policy.max_in_flight

    @property
    def idle(self) -> bool:
        return not self._in_flight

    # -- dispatch / settle ---------------------------------------------------
    def _window_slots(self, window: Window) -> tuple:
        return tuple(
            e.slot for e in window.entries if getattr(e, "slot", None) is not None
        )

    def _submit(self, window: Window) -> None:
        """One verify dispatch of ``window`` (initial or retry). A failed
        SUBMIT (the pool itself is gone — interpreter shutdown, a test
        tore the pool down) degrades immediately: the overlap is
        unavailable, the verdicts must not be."""
        pre = None
        if self.fault_injector is not None:
            pre = self.fault_injector.hook_for(window.seq, window.attempts)
        window.attempts += 1
        stats = self.stats

        def timer(seconds, _w=window):
            # runs on the worker; the engine reads verify_s only after
            # the future resolves, so the write needs no lock
            stats.stage_b_busy(seconds)
            _w.verify_s += seconds

        def route_sink(route, _w=window):
            # same worker-side write discipline as the timer
            _w.verify_route = route

        try:
            window.future = bls.verify_signature_sets_async(
                window.batch.sets, timer=timer, pre=pre,
                route_sink=route_sink,
                # deterministic window→lane assignment: retries of one
                # window stay on its lane (FIFO with its successors),
                # consecutive windows round-robin over the lanes
                lane=window.seq % self.policy.verify_lanes,
                # causal handoff: the verify lane adopts the window's
                # trace, so its span parents across the thread seam
                trace_ctx=window.trace_ctx,
            )
        except RuntimeError:
            _metrics.counter("pipeline.fault.dispatch_failure").inc()
            window.future = _InlineFuture(self._verify_inline(window))

    def dispatch(self, window: Window) -> None:
        """Queue one window onto the verifier. The caller must have made
        room (``not full``) by settling the oldest window first."""
        if self.full:
            raise RuntimeError(
                "VerifyScheduler.dispatch on a full queue — settle the "
                "oldest window first (the engine's backpressure wait)"
            )
        n_sets = len(window.batch)
        trace.event(
            "pipeline.flush.dispatch",
            seq=window.seq,
            blocks=len(window.entries),
            sets=n_sets,
            in_flight=len(self._in_flight) + 1,
        )
        window.t_dispatch = time.perf_counter()
        self._submit(window)
        self._in_flight.append(window)
        self.stats.flush_dispatched(n_sets)
        self.stats.queue_depth(len(self._in_flight))

    def _verify_inline(self, window: Window) -> "list[bool]":
        """Graceful degradation: prove the window's sets on THIS thread
        (the same host verification the sequential path runs). Verdicts
        and attribution are exactly what the worker would have produced;
        only the stage overlap is lost — which the latched
        ``pipeline.degraded`` gauge makes visible."""
        # the stats mutator owns the pipeline.degraded_flushes registry
        # counter; only the latched gauge is set here
        _metrics.gauge("pipeline.degraded").set(1)
        window.degraded = True
        self.stats.degraded_flush()
        trace.event(
            "pipeline.degraded", seq=window.seq, sets=len(window.batch)
        )
        t0 = time.perf_counter()
        try:
            with trace.adopt(window.trace_ctx):
                with trace.span(
                    "pipeline.flush.verify_inline", seq=window.seq
                ):
                    verdicts = bls.verify_signature_sets(window.batch.sets)
            window.verify_route = bls.last_batch_route()
            return verdicts
        finally:
            window.verify_s += time.perf_counter() - t0

    @staticmethod
    def _observe_settled(window: Window) -> None:
        """Feed the window's stage-B latencies into the process-wide SLO
        histograms (bounded reservoirs, telemetry/metrics.py) — the
        production soak's p99 gates read these directly, so they observe
        unconditionally (two reservoir inserts per WINDOW, not per
        block; noise against a multi-pairing). Under tracing each
        observation carries the window's trace_id, so the histogram's
        worst-N exemplar table can name which window was the tail; the
        settled window also feeds the slow-trace ring and counts
        ``trace.windows_linked``."""
        ctx = window.trace_ctx
        tid = ctx.trace_id if ctx is not None else None
        fields = {"seq": window.seq} if tid is not None else None
        _metrics.histogram("pipeline.verify_s").observe(
            window.verify_s, trace_id=tid, fields=fields
        )
        if window.t_dispatch is not None and window.t_settled is not None:
            _metrics.histogram("pipeline.settle_s").observe(
                max(0.0, window.t_settled - window.t_dispatch),
                trace_id=tid, fields=fields,
            )
        if tid is not None:
            _metrics.counter("trace.windows_linked").inc()
            starts = [
                e.t_start
                for e in window.entries
                if getattr(e, "t_start", None) is not None
            ]
            t_begin = min(starts) if starts else window.t_dispatch
            if window.t_settled is not None and t_begin is not None:
                trace.note_trace(
                    ctx,
                    "pipeline.window",
                    max(0.0, window.t_settled - t_begin),
                    seq=window.seq,
                    blocks=len(window.entries),
                    sets=len(window.batch),
                )

    def settle_oldest(self) -> "tuple[Window, list[bool]]":
        """Block until the oldest in-flight window's verdicts are in;
        returns (window, per-set verdicts in call-site order).

        Bounded and fault-hardened: a worker stuck past
        ``settle_timeout_s`` raises ``PipelineBrokenError`` with the
        window's attribution; a ``TransientFlushError`` re-dispatches up
        to ``flush_retries`` times with linear backoff; a worker death
        (or any other non-verdict crash) falls back to in-line host
        verification on this thread."""
        if not self._in_flight:
            raise RuntimeError("settle_oldest with nothing in flight")
        window = self._in_flight.pop(0)
        policy = self.policy
        # the settle span joins the window's causal tree: the submitting
        # thread adopts the same context the verify lane did
        with trace.adopt(window.trace_ctx), \
                trace.span("pipeline.flush.settle", seq=window.seq):
            while True:
                try:
                    verdicts = window.future.result(
                        timeout=policy.settle_timeout_s
                    )
                    window.t_settled = time.perf_counter()
                    self._observe_settled(window)
                    return window, verdicts
                except (_FutureTimeout, TimeoutError):
                    _metrics.counter("pipeline.fault.settle_timeout").inc()
                    window.future.cancel()
                    slots = self._window_slots(window)
                    broken = PipelineBrokenError(
                        f"flush window {window.seq} (slots {list(slots)}, "
                        f"{len(window.batch)} sets) did not settle within "
                        f"{policy.settle_timeout_s}s — verifier wedged; "
                        "the pipeline is broken, the state is at the last "
                        "committed position",
                        window_seq=window.seq,
                        slots=slots,
                    )
                    # the engine emits `discarded` lineage for the stuck
                    # window's blocks (it was popped off the queue here,
                    # so the error is the only path that still names it)
                    broken.stuck_window = window
                    raise broken from None
                except TransientFlushError as exc:
                    _metrics.counter("pipeline.fault.transient").inc()
                    if window.attempts > policy.flush_retries:
                        # retries exhausted: the fault is persistent —
                        # degrade this window rather than fail the chain
                        trace.event(
                            "pipeline.fault.retries_exhausted",
                            seq=window.seq,
                            attempts=window.attempts,
                            error=repr(exc),
                        )
                        verdicts = self._verify_inline(window)
                        window.t_settled = time.perf_counter()
                        self._observe_settled(window)
                        return window, verdicts
                    _metrics.counter("pipeline.fault.retries").inc()
                    self.stats.fault_retry()
                    backoff = window.attempts * policy.retry_backoff_s
                    trace.event(
                        "pipeline.fault.retry",
                        seq=window.seq,
                        attempt=window.attempts,
                        backoff_s=backoff,
                    )
                    if backoff > 0:
                        time.sleep(backoff)
                    self._submit(window)
                except (WorkerKilled, Exception) as exc:  # noqa: BLE001
                    # worker death or an unexpected crash: NOT a verdict
                    # (structured consensus errors never propagate through
                    # the future — verify returns verdict lists), so the
                    # sound recovery is to re-verify in-line right here
                    _metrics.counter("pipeline.fault.worker_death").inc()
                    trace.event(
                        "pipeline.fault.worker_death",
                        seq=window.seq,
                        error=repr(exc),
                    )
                    verdicts = self._verify_inline(window)
                    window.t_settled = time.perf_counter()
                    self._observe_settled(window)
                    return window, verdicts

    def drop_all(self) -> "list[Window]":
        """Abandon every in-flight window (rollback path): the futures
        run to completion on the worker — the single-thread pool keeps
        FIFO order, and a later submit would queue behind them anyway —
        but their verdicts are no longer consulted. Returns the dropped
        windows (the engine emits ``discarded`` lineage for their
        speculative blocks)."""
        dropped = self._in_flight
        self._in_flight = []
        return dropped


class _InlineFuture:
    """A pre-resolved future for the dispatch-failure degradation path:
    quacks like ``concurrent.futures.Future`` for the one consumer
    (``settle_oldest``)."""

    __slots__ = ("_verdicts",)

    def __init__(self, verdicts):
        self._verdicts = verdicts

    def result(self, timeout=None):
        return self._verdicts

    def cancel(self) -> bool:
        return False
