"""Stage-B scheduling: bounded async dispatch of coalesced flushes.

The scheduler owns the pipeline's work queue discipline — and nothing
else. It knows signature batches and futures; it does NOT know states,
forks, or rollback (engine.py's job). Three rules:

* **Coalesce**: one dispatched window carries the merged signature sets
  of up to ``FlushPolicy.window_size`` consecutive blocks; the verifier
  proves them in ONE random-linear-combination multi-pairing (N+K Miller
  loops, one shared final exponentiation) via
  ``crypto.bls.verify_signature_sets`` — which itself routes to the
  native IFMA engine or, above the ``ops`` pairing threshold, the
  device/mesh pairing kernels.
* **Bound**: at most ``FlushPolicy.max_in_flight`` windows may be queued
  or running at once. ``dispatch`` on a full scheduler is a programming
  error (the engine settles the oldest window first — that blocking wait
  IS the backpressure, so an unbounded block stream cannot pile
  unverified speculative state in memory).
* **Order**: windows settle strictly FIFO (the verifier pool is a single
  worker), so chain order and commit order agree by construction.
"""

from __future__ import annotations

from ..crypto import bls
from ..models.signature_batch import SignatureBatch
from ..utils import trace
from .stats import PipelineStats

__all__ = ["FlushPolicy", "VerifyScheduler", "Window"]


class FlushPolicy:
    """When to cut a window and how many may be in flight.

    * ``window_size`` — blocks coalesced per flush. 1 = per-block flushes
      (the sequential batching PR 0 shipped, just asynchronous); larger
      windows amortize the final exponentiation and per-call overheads
      across blocks, at the cost of a coarser rollback granule.
    * ``max_in_flight`` — the bounded verify queue's cap (backpressure).
    * ``checkpoint_interval`` — every Nth dispatched window carries a
      full state snapshot for the commit bookkeeping. A snapshot is the
      only O(registry) cost the pipeline adds to the success path (the
      object-graph copy; root memos travel), so it is amortized: between
      checkpoints the committed position is represented as "newest
      checkpoint + proven blocks", and a rollback (rare, terminal)
      re-derives it by deterministic replay.
    * ``flush_empty`` — whether windows whose blocks deferred zero sets
      (Validation.DISABLED replay) still pass through the scheduler; off
      by default, they commit immediately.
    """

    __slots__ = (
        "window_size", "max_in_flight", "checkpoint_interval", "flush_empty"
    )

    def __init__(self, window_size: int = 8, max_in_flight: int = 2,
                 checkpoint_interval: int = 8, flush_empty: bool = False):
        if window_size < 1:
            raise ValueError("window_size must be >= 1")
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if checkpoint_interval < 1:
            raise ValueError("checkpoint_interval must be >= 1")
        self.window_size = window_size
        self.max_in_flight = max_in_flight
        self.checkpoint_interval = checkpoint_interval
        self.flush_empty = flush_empty

    def __repr__(self) -> str:
        return (
            f"FlushPolicy(window_size={self.window_size}, "
            f"max_in_flight={self.max_in_flight}, "
            f"checkpoint_interval={self.checkpoint_interval})"
        )


class Window:
    """One dispatched flush: consecutive block entries, their merged
    signature batch, and — on checkpoint-carrying windows — the
    post-window state snapshot the engine installs as the new checkpoint
    when the verdicts come back clean (``post_state`` is None
    otherwise; the committed position is then checkpoint + blocks)."""

    __slots__ = ("entries", "batch", "post_state", "future", "seq")

    def __init__(self, entries, batch: SignatureBatch, post_state, seq: int):
        self.entries = entries
        self.batch = batch
        self.post_state = post_state
        self.future = None
        self.seq = seq


class VerifyScheduler:
    """Bounded FIFO dispatch onto the shared background verifier."""

    def __init__(self, policy: FlushPolicy, stats: PipelineStats):
        self.policy = policy
        self.stats = stats
        self._in_flight: list[Window] = []

    # -- queue state ---------------------------------------------------------
    @property
    def in_flight(self) -> int:
        return len(self._in_flight)

    @property
    def full(self) -> bool:
        return len(self._in_flight) >= self.policy.max_in_flight

    @property
    def idle(self) -> bool:
        return not self._in_flight

    # -- dispatch / settle ---------------------------------------------------
    def dispatch(self, window: Window) -> None:
        """Queue one window onto the verifier. The caller must have made
        room (``not full``) by settling the oldest window first."""
        if self.full:
            raise RuntimeError(
                "VerifyScheduler.dispatch on a full queue — settle the "
                "oldest window first (the engine's backpressure wait)"
            )
        n_sets = len(window.batch)
        trace.event(
            "pipeline.flush.dispatch",
            seq=window.seq,
            blocks=len(window.entries),
            sets=n_sets,
            in_flight=len(self._in_flight) + 1,
        )
        window.future = bls.verify_signature_sets_async(
            window.batch.sets, timer=self.stats.stage_b_busy
        )
        self._in_flight.append(window)
        self.stats.flush_dispatched(n_sets)
        self.stats.queue_depth(len(self._in_flight))

    def settle_oldest(self) -> "tuple[Window, list[bool]]":
        """Block until the oldest in-flight window's verdicts are in;
        returns (window, per-set verdicts in call-site order)."""
        if not self._in_flight:
            raise RuntimeError("settle_oldest with nothing in flight")
        window = self._in_flight.pop(0)
        with trace.span("pipeline.flush.settle", seq=window.seq):
            verdicts = window.future.result()
        return window, verdicts

    def drop_all(self) -> None:
        """Abandon every in-flight window (rollback path): the futures
        run to completion on the worker — the single-thread pool keeps
        FIFO order, and a later submit would queue behind them anyway —
        but their verdicts are no longer consulted."""
        self._in_flight.clear()
