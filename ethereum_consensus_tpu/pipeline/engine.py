"""ChainPipeline — the streaming block-application engine.

The one-shot ``Executor`` (executor.rs:113 parity) applies a block and
verifies its signatures synchronously, one block at a time. Serving
heavy sync/replay traffic wants the shape every inference-serving stack
uses instead: a bounded two-stage pipeline that keeps the pairing engine
busy while the host mutates state.

Stage A (the submitting thread) runs the full state transition for each
block — slot advance, operation processing, incremental hash-tree-root,
state-root check — but with every signature claim *collected*, not
verified: the transition's per-block batch flushes into a cross-block
window (``signature_batch.defer_flushes``) instead of pairing. The state
mutation is therefore **speculative**: structurally validated, signatures
pending. Deferred registry-key parses (``PublicKey.from_validated_bytes``)
keep the G1 decompression off this stage too.

Stage B (the background verifier, ``scheduler.py``) receives windows of
up to K consecutive blocks' merged sets and proves each window in ONE
random-linear-combination multi-pairing — N+K Miller loops, one shared
final exponentiation — preceded by the eight-wide bulk decompression of
any cold keys, on the native IFMA engine (ctypes releases the GIL for
the whole call, so the overlap is real parallelism) or, above the
``ops`` pairing threshold, the device/mesh pairing route.

Commit protocol: a full state snapshot is the only O(registry) cost the
pipeline adds to the success path, so snapshots are **checkpoints**,
taken on every ``checkpoint_interval``-th window (at dispatch, when the
live state IS the post-window state; root memos travel with the copy —
docs/INCREMENTAL_HTR.md — so a checkpoint costs an object-graph walk,
never a rehash). Between checkpoints the committed position is
represented as "newest checkpoint + the proven blocks since", which a
(rare, terminal) failure re-derives by deterministic replay.

Rollback: when a window's verdicts come back dirty, the verifier's
per-set fallback has already re-verified the window sequentially in
call-site order, naming the first failing set and therefore the failing
block and operation. The engine discards the speculative state, rebuilds
the committed position, re-applies the proven prefix of the failed
window (signatures already proven, so no re-pairing), and raises the
failing set's structured error — the same exception the sequential path
raises. Semantics match the sequential Executor observably: identical
final state bit-for-bit on success, the same structured error and a
coherent last-committed state on failure.

Fault hardening (docs/SCENARIOS.md): every settle wait is bounded by
``FlushPolicy.settle_timeout_s`` — a wedged verifier raises
``PipelineBrokenError`` with the stuck window's attribution and the
state restored to the last committed position, never a deadlock.
Transient flush faults retry with bounded backoff; a dead worker
degrades the window to in-line host verification (scheduler.py). An
optional ``fault_injector`` (faults.FaultInjector) drives these paths
deterministically for the scenario harness.
"""

from __future__ import annotations

import time

from ..error import Error
from ..models.signature_batch import SignatureBatch, defer_flushes
from ..models.transition import Validation
from ..utils import trace
from .errors import PipelineBrokenError
from .scheduler import FlushPolicy, VerifyScheduler, Window
from .stats import PipelineStats

__all__ = ["ChainPipeline", "PipelineBrokenError"]


class _Entry:
    """One speculatively applied block: the block itself (kept for the
    rollback re-application) and its collected signature batch."""

    __slots__ = ("signed_block", "slot", "batch")

    def __init__(self, signed_block, slot: int, batch: SignatureBatch):
        self.signed_block = signed_block
        self.slot = slot
        self.batch = batch


class ChainPipeline:
    """Streaming chain engine over an ``Executor``.

    Usage::

        pipe = ChainPipeline(executor, policy=FlushPolicy(window_size=8))
        for signed_block in blocks:
            pipe.submit(signed_block)
        stats = pipe.close()          # settles every in-flight window

    or as a context manager (``close`` on clean exit, ``abort`` — which
    restores the last committed state — when the body raises). After a
    failed block the structured error has been raised, ``executor.state``
    is the last committed state, and the pipeline is broken (further
    ``submit`` raises ``PipelineBrokenError``).
    """

    def __init__(
        self,
        executor,
        policy: FlushPolicy | None = None,
        validation: Validation = Validation.ENABLED,
        stats: PipelineStats | None = None,
        fault_injector=None,
    ):
        self._executor = executor
        self.policy = policy or FlushPolicy()
        self._validation = validation
        self.stats = stats or PipelineStats()
        self._sched = VerifyScheduler(
            self.policy, self.stats, fault_injector=fault_injector
        )
        self._pending: list[_Entry] = []
        # committed position = checkpoint + proven blocks since it
        self._checkpoint = executor.state.copy()
        self._since_checkpoint: list = []
        self._dispatched_since_checkpoint = 0
        self._seq = 0
        self._broken: Exception | None = None
        self._closed = False

    # -- public surface ------------------------------------------------------
    @property
    def state(self):
        """The executor's (possibly speculative) head state."""
        return self._executor.state

    @property
    def committed_state(self):
        """The last signature-verified state. Free when the pipeline is
        settled (nothing speculative: the head IS committed); otherwise
        rebuilt on a scratch executor from the newest checkpoint by
        replaying the proven blocks since."""
        if not self._pending and self._sched.idle:
            return self._executor.state
        scratch = type(self._executor)(
            self._checkpoint.copy(), self._executor.context
        )
        throwaway = SignatureBatch()
        with defer_flushes(throwaway):
            for block in self._since_checkpoint:
                scratch.apply_block_with_validation(block, self._validation)
        return scratch.state

    def submit(self, signed_block) -> None:
        """Speculatively apply one block (stage A) and queue its signature
        sets for windowed verification (stage B). Raises the block's
        structured error — or an earlier queued block's, settled first —
        exactly as the sequential path would, leaving ``state`` at the
        last committed position."""
        self._check_usable()
        self.stats.start()
        t0 = time.perf_counter()
        sink = SignatureBatch()
        slot = int(signed_block.message.slot)
        try:
            with trace.span("pipeline.stage_a", slot=slot):
                with defer_flushes(sink):
                    self._executor.apply_block_with_validation(
                        signed_block, self._validation
                    )
        except Error as exc:
            self.stats.block_submitted(time.perf_counter() - t0)
            self._fail_structural(exc)  # never returns
        self._pending.append(_Entry(signed_block, slot, sink))
        self.stats.block_submitted(time.perf_counter() - t0)
        if len(self._pending) >= self.policy.window_size:
            self._dispatch_pending()

    def close(self) -> PipelineStats:
        """Flush the partial window, settle every in-flight flush, and
        return the run's stats. Idempotent; a no-op (stats only) once the
        pipeline is broken — the failure was already raised."""
        if not self._closed and self._broken is None:
            try:
                if self._pending:
                    self._dispatch_pending()
                while not self._sched.idle:
                    self._settle_oldest()
            finally:
                self._closed = True
                self.stats.stop()
        return self.stats

    def abort(self) -> None:
        """Discard all speculative work and restore the last committed
        state (the context-manager exit path when the body raised)."""
        if self._closed:
            return
        self._sched.drop_all()
        self._pending.clear()
        self._materialize_committed()
        if self._broken is None:
            self._broken = PipelineBrokenError("pipeline aborted")
        self._closed = True
        self.stats.stop()

    def __enter__(self) -> "ChainPipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abort()

    # -- internals -----------------------------------------------------------
    def _check_usable(self) -> None:
        if self._broken is not None:
            raise PipelineBrokenError(
                f"pipeline is broken ({self._broken!r}); the state is at "
                "the last committed position"
            ) from self._broken
        if self._closed:
            raise PipelineBrokenError("pipeline is closed")

    def _dispatch_pending(self) -> None:
        entries, self._pending = self._pending, []
        merged = SignatureBatch()
        for entry in entries:
            merged.merge(entry.batch)
        # checkpoint-due windows snapshot the live state, which right now
        # IS the post-window state (nothing later has been applied yet)
        self._dispatched_since_checkpoint += 1
        candidate = None
        if self._dispatched_since_checkpoint >= self.policy.checkpoint_interval:
            candidate = self._executor.state.copy()
            self._dispatched_since_checkpoint = 0
            self.stats.checkpoint()
        if not len(merged) and not self.policy.flush_empty:
            # a window that deferred zero sets has nothing to prove
            self._commit(entries, candidate)
            return
        window = Window(entries, merged, candidate, self._seq)
        self._seq += 1
        # backpressure: the bounded queue admits a new window only after
        # the oldest one settles — this wait is where an over-eager
        # producer blocks instead of piling unverified state in memory
        while self._sched.full:
            self._settle_oldest()
        self._sched.dispatch(window)

    def _settle_oldest(self) -> None:
        try:
            window, verdicts = self._sched.settle_oldest()
        except PipelineBrokenError as exc:
            # a bounded settle expired (verifier wedged): abandon every
            # in-flight window, restore the committed position, and break
            # the pipeline — the submitter gets attribution, not a hang
            self._sched.drop_all()
            self._pending.clear()
            self._materialize_committed()
            self._broken = exc
            self.stats.stop()
            raise
        if all(verdicts):
            self._commit(window.entries, window.post_state)
            return
        self._rollback(window, verdicts)  # raises

    def _commit(self, entries, checkpoint) -> None:
        if checkpoint is not None:
            self._checkpoint = checkpoint
            self._since_checkpoint = []
        else:
            self._since_checkpoint.extend(e.signed_block for e in entries)
        self.stats.blocks_were_committed(len(entries))
        trace.event(
            "pipeline.commit",
            blocks=len(entries),
            checkpoint=checkpoint is not None,
        )

    def _materialize_committed(self) -> None:
        """Point the executor at the last committed state: the newest
        checkpoint plus a deterministic replay of the proven blocks since
        (signatures already proven, so the throwaway sink skips the
        re-pairing). Failure paths only."""
        self._executor.state = self._checkpoint.copy()
        if self._since_checkpoint:
            throwaway = SignatureBatch()
            with defer_flushes(throwaway):
                for block in self._since_checkpoint:
                    self._executor.apply_block_with_validation(
                        block, self._validation
                    )

    def _rollback(self, window: Window, verdicts: "list[bool]") -> None:
        """A window failed: the verifier's per-set fallback
        (crypto/bls.verify_signature_sets) has already re-verified the
        window's sets sequentially, so the verdicts are exact and the
        first False in call-site order names the failing block and
        operation. Discard the speculative state, rebuild the committed
        position, re-apply the proven prefix to land exactly at the
        failure boundary, and raise the failing set's structured error."""
        self.stats.rollback()
        self.stats.sequential_reverify()
        fail_idx = verdicts.index(False)
        at = 0
        fail_block = 0
        local_idx = fail_idx
        for i, entry in enumerate(window.entries):
            n = len(entry.batch)
            if fail_idx < at + n:
                fail_block, local_idx = i, fail_idx - at
                break
            at += n
        error = window.entries[fail_block].batch.errors[local_idx]
        trace.event(
            "pipeline.rollback",
            seq=window.seq,
            failed_slot=window.entries[fail_block].slot,
            committed_blocks=fail_block,
            error=type(error).__name__,
        )
        self._sched.drop_all()
        self._pending.clear()
        self._materialize_committed()
        if fail_block > 0:
            proven = window.entries[:fail_block]
            throwaway = SignatureBatch()
            with defer_flushes(throwaway):
                for entry in proven:
                    self._executor.apply_block_with_validation(
                        entry.signed_block, self._validation
                    )
            self._since_checkpoint.extend(e.signed_block for e in proven)
            self.stats.blocks_were_committed(fail_block)
        self._broken = error
        self.stats.stop()
        raise error

    def _fail_structural(self, exc: Exception) -> None:
        """Stage A aborted structurally mid-block: the live state is a
        discarded partial mutation. Earlier queued blocks must settle
        FIRST — an earlier block's bad signature preempts this later
        block's error, matching sequential order. In-flight windows
        settle through their normal paths; still-pending blocks re-apply
        sequentially with INLINE verification (the terminal sequential
        re-verify). Then the structural error propagates with the state
        at the last committed position."""
        pending, self._pending = self._pending, []
        try:
            while not self._sched.idle:
                self._settle_oldest()  # an earlier window failure raises
            self._materialize_committed()
            if pending:
                self.stats.sequential_reverify()
                for entry in pending:
                    self._executor.apply_block_with_validation(
                        entry.signed_block, self._validation
                    )
                    self._since_checkpoint.append(entry.signed_block)
                    self.stats.blocks_were_committed(1)
        except Error as earlier:
            if self._broken is None:  # a pending inline re-apply failed
                self._materialize_committed()
                self._broken = earlier
                self.stats.stop()
            raise earlier
        self._broken = exc
        self.stats.stop()
        raise exc
