"""ChainPipeline — the streaming block-application engine.

The one-shot ``Executor`` (executor.rs:113 parity) applies a block and
verifies its signatures synchronously, one block at a time. Serving
heavy sync/replay traffic wants the shape every inference-serving stack
uses instead: a bounded two-stage pipeline that keeps the pairing engine
busy while the host mutates state.

Stage A (the submitting thread) runs the full state transition for each
block — slot advance, operation processing, incremental hash-tree-root,
state-root check — but with every signature claim *collected*, not
verified: the transition's per-block batch flushes into a cross-block
window (``signature_batch.defer_flushes``) instead of pairing. The state
mutation is therefore **speculative**: structurally validated, signatures
pending. Deferred registry-key parses (``PublicKey.from_validated_bytes``)
keep the G1 decompression off this stage too.

Stage B (the background verifier, ``scheduler.py``) receives windows of
up to K consecutive blocks' merged sets and proves each window in ONE
random-linear-combination multi-pairing — N+K Miller loops, one shared
final exponentiation — preceded by the eight-wide bulk decompression of
any cold keys, on the native IFMA engine (ctypes releases the GIL for
the whole call, so the overlap is real parallelism) or, above the
``ops`` pairing threshold, the device/mesh pairing route.

Commit protocol: a full state snapshot is the only O(registry) cost the
pipeline adds to the success path, so snapshots are **checkpoints**,
taken on every ``checkpoint_interval``-th window (at dispatch, when the
live state IS the post-window state; root memos travel with the copy —
docs/INCREMENTAL_HTR.md — so a checkpoint costs an object-graph walk,
never a rehash). Between checkpoints the committed position is
represented as "newest checkpoint + the proven blocks since", which a
(rare, terminal) failure re-derives by deterministic replay.

Rollback: when a window's verdicts come back dirty, the verifier's
per-set fallback has already re-verified the window sequentially in
call-site order, naming the first failing set and therefore the failing
block and operation. The engine discards the speculative state, rebuilds
the committed position, re-applies the proven prefix of the failed
window (signatures already proven, so no re-pairing), and raises the
failing set's structured error — the same exception the sequential path
raises. Semantics match the sequential Executor observably: identical
final state bit-for-bit on success, the same structured error and a
coherent last-committed state on failure.

Fault hardening (docs/SCENARIOS.md): every settle wait is bounded by
``FlushPolicy.settle_timeout_s`` — a wedged verifier raises
``PipelineBrokenError`` with the stuck window's attribution and the
state restored to the last committed position, never a deadlock.
Transient flush faults retry with bounded backoff; a dead worker
degrades the window to in-line host verification (scheduler.py). An
optional ``fault_injector`` (faults.FaultInjector) drives these paths
deterministically for the scenario harness.
"""

from __future__ import annotations

import time

from ..error import Error
from ..models.signature_batch import SignatureBatch, defer_flushes
from ..models.transition import Validation
from ..telemetry import flight as _flight
from ..telemetry import memory as _memory
from ..telemetry import metrics as _metrics
from ..telemetry import phases as _phases
from ..telemetry import spans as _spans
from ..utils import trace
from .errors import PipelineBrokenError
from .scheduler import FlushPolicy, VerifyScheduler, Window
from .stats import PipelineStats

__all__ = ["ChainPipeline", "PipelineBrokenError"]


def _snapshot_copy(state):
    """The serving layer's publication copy, with the memory
    observatory's ``pipeline.snapshot_copy`` bandwidth accounting: the
    copy's structural list traffic is attributed per list at the
    ``ssz.state_copy`` site; this site counts the publication EVENTS
    and their wall window so a profile shows what snapshot publication
    costs beside what it moves. One bool read while off."""
    obs = _memory.OBSERVATORY
    if not obs.active:
        return state.copy()
    before = obs.copy_summary()["sites"].get("ssz.state_copy", {})
    t0 = time.perf_counter()
    snap = state.copy()
    t1 = time.perf_counter()
    after = obs.copy_summary()["sites"].get("ssz.state_copy", {})
    obs.record_copy(
        "pipeline.snapshot_copy",
        after.get("bytes", 0) - before.get("bytes", 0),
        t0,
        t1,
    )
    return snap


def _state_root_hex(signed_block) -> str:
    """The block's claimed post-state root — a free field read, so the
    lineage root costs no hashing."""
    return bytes(signed_block.message.state_root).hex()


def _block_root_hex(signed_block) -> str:
    """The block's own hash_tree_root — the BLOCK-root index the serving
    duties endpoints resolve ``dependent_root`` against. An instance-
    cache hit in practice: stage A's proposer-signature check already
    merkleized the message for its signing root."""
    message = signed_block.message
    return type(message).hash_tree_root(message).hex()


class _Entry:
    """One speculatively applied block: the block itself (kept for the
    rollback re-application), its collected signature batch, and — when
    the flight-recorder hook is active — the stage-A timing stamps the
    lineage record is assembled from (telemetry/flight.py)."""

    __slots__ = (
        "signed_block", "slot", "batch",
        "t_start", "t_applied", "stage_a_s", "fork", "phases",
    )

    def __init__(self, signed_block, slot: int, batch: SignatureBatch):
        self.signed_block = signed_block
        self.slot = slot
        self.batch = batch
        self.t_start = None
        self.t_applied = None
        self.stage_a_s = None
        self.fork = None
        self.phases = None


class ChainPipeline:
    """Streaming chain engine over an ``Executor``.

    Usage::

        pipe = ChainPipeline(executor, policy=FlushPolicy(window_size=8))
        for signed_block in blocks:
            pipe.submit(signed_block)
        stats = pipe.close()          # settles every in-flight window

    or as a context manager (``close`` on clean exit, ``abort`` — which
    restores the last committed state — when the body raises). After a
    failed block the structured error has been raised, ``executor.state``
    is the last committed state, and the pipeline is broken (further
    ``submit`` raises ``PipelineBrokenError``).
    """

    def __init__(
        self,
        executor,
        policy: FlushPolicy | None = None,
        validation: Validation = Validation.ENABLED,
        stats: PipelineStats | None = None,
        fault_injector=None,
    ):
        self._executor = executor
        self.policy = policy or FlushPolicy()
        self._validation = validation
        self.stats = stats or PipelineStats()
        self._sched = VerifyScheduler(
            self.policy, self.stats, fault_injector=fault_injector
        )
        self._pending: list[_Entry] = []
        # the causal trace the current (accumulating) window records
        # under: anchored at the window's FIRST stage-A span, handed to
        # the scheduler at dispatch, None while tracing is off
        self._window_ctx = None
        # committed position = checkpoint + proven blocks since it
        self._checkpoint = executor.state.copy()
        self._since_checkpoint: list = []
        self._dispatched_since_checkpoint = 0
        self._seq = 0
        self._broken: Exception | None = None
        self._closed = False

    # -- public surface ------------------------------------------------------
    @property
    def state(self):
        """The executor's (possibly speculative) head state."""
        return self._executor.state

    @property
    def committed_state(self):
        """The last signature-verified state. Free when the pipeline is
        settled (nothing speculative: the head IS committed); otherwise
        rebuilt on a scratch executor from the newest checkpoint by
        replaying the proven blocks since."""
        if not self._pending and self._sched.idle:
            return self._executor.state
        scratch = type(self._executor)(
            self._checkpoint.copy(), self._executor.context
        )
        throwaway = SignatureBatch()
        with defer_flushes(throwaway):
            for block in self._since_checkpoint:
                scratch.apply_block_with_validation(block, self._validation)
        return scratch.state

    def submit(self, signed_block) -> None:
        """Speculatively apply one block (stage A) and queue its signature
        sets for windowed verification (stage B). Raises the block's
        structured error — or an earlier queued block's, settled first —
        exactly as the sequential path would, leaving ``state`` at the
        last committed position."""
        self._check_usable()
        self.stats.start()
        # zero-overhead guard: one bool read when no flight recorder or
        # introspection server is attached (tests/test_flight_server.py)
        hooked = _flight.HOOK.active
        mark = (
            _spans.RECORDER.mark()
            if hooked and _spans.RECORDER.enabled
            else None
        )
        t0 = time.perf_counter()
        sink = SignatureBatch()
        slot = int(signed_block.message.slot)
        try:
            # later blocks of an accumulating window adopt the context
            # anchored at the window's first stage-A span, so the whole
            # window records as ONE causal tree; the first block roots it
            with trace.adopt(self._window_ctx if self._pending else None):
                with trace.span("pipeline.stage_a", slot=slot):
                    if not self._pending:
                        self._window_ctx = trace.context()
                    with defer_flushes(sink):
                        self._executor.apply_block_with_validation(
                            signed_block, self._validation
                        )
        except Error as exc:
            t1 = time.perf_counter()
            self.stats.block_submitted(t1 - t0)
            if hooked:
                failed = self._make_entry(signed_block, slot, sink, t0, t1,
                                          mark)
                self._emit_block(failed, "rolled-back", blame=exc,
                                 trace_ctx=self._window_ctx)
                _flight.HOOK.emit(
                    "rollback",
                    {
                        "slot": slot,
                        "seq": None,
                        "structural": True,
                        "error": type(exc).__name__,
                    },
                )
            self._fail_structural(exc)  # never returns
        t1 = time.perf_counter()
        if hooked:
            entry = self._make_entry(signed_block, slot, sink, t0, t1, mark)
        else:
            entry = _Entry(signed_block, slot, sink)
        self._pending.append(entry)
        self.stats.block_submitted(t1 - t0)
        if len(self._pending) >= self.policy.window_size:
            self._dispatch_pending()

    def close(self) -> PipelineStats:
        """Flush the partial window, settle every in-flight flush, and
        return the run's stats. Idempotent; a no-op (stats only) once the
        pipeline is broken — the failure was already raised."""
        if not self._closed and self._broken is None:
            try:
                if self._pending:
                    self._dispatch_pending()
                while not self._sched.idle:
                    self._settle_oldest()
            finally:
                self._closed = True
                self.stats.stop()
        return self.stats

    def abort(self) -> None:
        """Discard all speculative work and restore the last committed
        state (the context-manager exit path when the body raised)."""
        if self._closed:
            return
        dropped = self._sched.drop_all()
        pending, self._pending = self._pending, []
        if _flight.HOOK.active:
            self._emit_discards(dropped, pending)
        self._materialize_committed()
        if self._broken is None:
            self._broken = PipelineBrokenError("pipeline aborted")
        self._closed = True
        self.stats.stop()

    def __enter__(self) -> "ChainPipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abort()

    # -- flight-recorder lineage assembly ------------------------------------
    def _make_entry(self, signed_block, slot: int, sink, t0: float,
                    t1: float, mark) -> _Entry:
        """An entry carrying the stage-A stamps the lineage record needs
        (hook-active path only): apply window, post-apply fork, and the
        span-derived phase split when the span recorder is live."""
        entry = _Entry(signed_block, slot, sink)
        entry.t_start = t0
        entry.t_applied = t1
        entry.stage_a_s = t1 - t0
        entry.fork = self._executor.state.version().name.lower()
        if mark is not None:
            entry.phases = _phases.attribution(
                _spans.RECORDER.records_since(mark)
            )
        return entry

    def _emit_block(self, entry: _Entry, outcome: str, window=None,
                    blame=None, degraded=None, trace_ctx=None) -> None:
        """Assemble one ``BlockLineage`` from the entry's stage-A stamps
        and its window's stage-B stamps, and publish it on the commit
        hook. Callers guard with ``_flight.HOOK.active``. The lineage
        names the causal trace the block recorded under (the window's
        context, or ``trace_ctx`` on windowless paths), so a lineage
        record resolves via ``/trace`` into its span tree."""
        now = time.perf_counter()
        if trace_ctx is None and window is not None:
            trace_ctx = window.trace_ctx
        queue_wait = 0.0
        settle_s = None
        if window is not None and window.t_dispatch is not None:
            if entry.t_applied is not None:
                queue_wait = max(0.0, window.t_dispatch - entry.t_applied)
            if window.t_settled is not None:
                settle_s = max(0.0, window.t_settled - window.t_dispatch)
        if degraded is None:
            degraded = bool(window.degraded) if window is not None else False
        _flight.HOOK.emit(
            "block",
            _flight.BlockLineage(
                slot=entry.slot,
                root=_state_root_hex(entry.signed_block),
                block_root=_block_root_hex(entry.signed_block),
                fork=entry.fork,
                outcome=outcome,
                stage_a_s=entry.stage_a_s,
                phases=entry.phases,
                queue_wait_s=queue_wait,
                flush_seq=window.seq if window is not None else None,
                flush_slots=(
                    tuple(e.slot for e in window.entries)
                    if window is not None
                    else ()
                ),
                flush_sets=len(window.batch) if window is not None else 0,
                verify_s=window.verify_s if window is not None else None,
                verify_route=(
                    window.verify_route if window is not None else None
                ),
                settle_s=settle_s,
                total_s=(
                    now - entry.t_start
                    if entry.t_start is not None
                    else None
                ),
                retries=(
                    max(0, window.attempts - 1) if window is not None else 0
                ),
                degraded=degraded,
                blame=(
                    {"error": type(blame).__name__, "detail": str(blame)}
                    if blame is not None
                    else None
                ),
                trace_id=(
                    trace_ctx.trace_id if trace_ctx is not None else None
                ),
            ),
        )

    def _emit_discards(self, dropped_windows, pending_entries,
                       blame=None) -> None:
        """Lineage for speculative work abandoned by someone else's
        failure: every block of every dropped in-flight window plus the
        never-dispatched pending entries."""
        for window in dropped_windows:
            for entry in window.entries:
                self._emit_block(entry, "discarded", window=window,
                                 blame=blame)
        for entry in pending_entries:
            self._emit_block(entry, "discarded", blame=blame)

    def _publish_state(self, entries, snap, seq=None) -> None:
        """Hand the serving layer an immutable snapshot of the committed
        state these entries produced (the commit hook's STATE channel —
        telemetry/flight.py). ``snap`` must be a state copy that nothing
        will mutate again: either a window's dispatch-time ``snap_state``
        or a copy taken while the live state IS the committed position.
        Callers guard with ``_flight.HOOK.state_active``."""
        last = entries[-1]
        _flight.HOOK.emit_state(
            {
                "state": snap,
                "context": self._executor.context,
                "slot": last.slot,
                "root": _state_root_hex(last.signed_block),
                "block_root": _block_root_hex(last.signed_block),
                # the committed signed block itself: the light-client
                # plane (proofs/light_client.py) reads sync_aggregate/
                # signature_slot from it and proves execution_branch
                # over its body — a reference, already immutable after
                # commit, so the channel stays copy-free
                "block": last.signed_block,
                "seq": seq,
            }
        )

    def _emit_head(self, entry: _Entry, blocks: int, seq=None,
                   trace_ctx=None) -> None:
        _flight.HOOK.emit(
            "head",
            {
                "slot": entry.slot,
                "root": _state_root_hex(entry.signed_block),
                "block_root": _block_root_hex(entry.signed_block),
                "blocks": blocks,
                "seq": seq,
                # the causal trace the head-advancing window recorded
                # under — SSE consumers can resolve it via /trace
                "trace_id": (
                    trace_ctx.trace_id if trace_ctx is not None else None
                ),
            },
        )

    # -- internals -----------------------------------------------------------
    def _check_usable(self) -> None:
        if self._broken is not None:
            raise PipelineBrokenError(
                f"pipeline is broken ({self._broken!r}); the state is at "
                "the last committed position"
            ) from self._broken
        if self._closed:
            raise PipelineBrokenError("pipeline is closed")

    def _dispatch_pending(self) -> None:
        entries, self._pending = self._pending, []
        trace_ctx, self._window_ctx = self._window_ctx, None
        merged = SignatureBatch()
        for entry in entries:
            merged.merge(entry.batch)
        # checkpoint-due windows snapshot the live state, which right now
        # IS the post-window state (nothing later has been applied yet)
        self._dispatched_since_checkpoint += 1
        candidate = None
        if self._dispatched_since_checkpoint >= self.policy.checkpoint_interval:
            candidate = self._executor.state.copy()
            self._dispatched_since_checkpoint = 0
            self.stats.checkpoint()
        if not len(merged) and not self.policy.flush_empty:
            # a window that deferred zero sets has nothing to prove
            self._commit(entries, candidate, window=None,
                         trace_ctx=trace_ctx)
            return
        window = Window(entries, merged, candidate, self._seq)
        window.trace_ctx = trace_ctx
        if _flight.HOOK.state_active:
            # serving data plane attached (telemetry/flight.py state
            # channel): copy the post-window state NOW, while the live
            # state is exactly it — the copy is published at commit and
            # never reused by the engine, so readers can't be torn by
            # later speculative applies. Deliberately NOT the checkpoint
            # object: the engine copy-shares checkpoints on failure
            # paths, which would race reader-side column syncs.
            window.snap_state = _snapshot_copy(self._executor.state)
        self._seq += 1
        # backpressure: the bounded queue admits a new window only after
        # the oldest one settles — this wait is where an over-eager
        # producer blocks instead of piling unverified state in memory
        while self._sched.full:
            self._settle_oldest()
        self._sched.dispatch(window)

    def _settle_oldest(self) -> None:
        try:
            window, verdicts = self._sched.settle_oldest()
        except PipelineBrokenError as exc:
            # a bounded settle expired (verifier wedged): abandon every
            # in-flight window, restore the committed position, and break
            # the pipeline — the submitter gets attribution, not a hang
            _metrics.gauge("pipeline.broken").set(1)
            dropped = self._sched.drop_all()
            pending, self._pending = self._pending, []
            if _flight.HOOK.active:
                stuck = getattr(exc, "stuck_window", None)
                if stuck is not None:
                    dropped = [stuck] + dropped
                self._emit_discards(dropped, pending, blame=exc)
                _flight.HOOK.emit(
                    "broken",
                    {
                        "window_seq": exc.window_seq,
                        "slots": list(exc.slots),
                        "detail": str(exc),
                    },
                )
            self._materialize_committed()
            self._broken = exc
            self.stats.stop()
            raise
        if all(verdicts):
            self._commit(window.entries, window.post_state, window=window)
            return
        self._rollback(window, verdicts)  # raises

    def _commit(self, entries, checkpoint, window=None,
                trace_ctx=None) -> None:
        if trace_ctx is None and window is not None:
            trace_ctx = window.trace_ctx
        if checkpoint is not None:
            self._checkpoint = checkpoint
            self._since_checkpoint = []
        else:
            self._since_checkpoint.extend(e.signed_block for e in entries)
        self.stats.blocks_were_committed(len(entries))
        if _flight.HOOK.state_active and entries:
            if window is None:
                # the empty-flush path commits synchronously inside
                # dispatch: the live state IS the committed position
                self._publish_state(
                    entries, _snapshot_copy(self._executor.state)
                )
            elif window.snap_state is not None:
                self._publish_state(
                    entries, window.snap_state, seq=window.seq
                )
            # a window dispatched before the store attached has no
            # snapshot (and the live state may be speculatively ahead):
            # skip — the next dispatched window publishes the new head
        if _flight.HOOK.active and entries:
            for entry in entries:
                self._emit_block(entry, "committed", window=window,
                                 trace_ctx=trace_ctx)
            self._emit_head(
                entries[-1], len(entries),
                seq=window.seq if window is not None else None,
                trace_ctx=trace_ctx,
            )
            _flight.HOOK.emit(
                "commit",
                {
                    "seq": window.seq if window is not None else None,
                    "slots": [e.slot for e in entries],
                    "sets": len(window.batch) if window is not None else 0,
                    "checkpoint": checkpoint is not None,
                    "degraded": (
                        bool(window.degraded) if window is not None else False
                    ),
                    "trace_id": (
                        trace_ctx.trace_id
                        if trace_ctx is not None
                        else None
                    ),
                },
            )
        trace.event(
            "pipeline.commit",
            blocks=len(entries),
            checkpoint=checkpoint is not None,
        )

    def _materialize_committed(self) -> None:
        """Point the executor at the last committed state: the newest
        checkpoint plus a deterministic replay of the proven blocks since
        (signatures already proven, so the throwaway sink skips the
        re-pairing). Failure paths only."""
        self._executor.state = self._checkpoint.copy()
        if self._since_checkpoint:
            throwaway = SignatureBatch()
            with defer_flushes(throwaway):
                for block in self._since_checkpoint:
                    self._executor.apply_block_with_validation(
                        block, self._validation
                    )

    def _rollback(self, window: Window, verdicts: "list[bool]") -> None:
        """A window failed: the verifier's per-set fallback
        (crypto/bls.verify_signature_sets) has already re-verified the
        window's sets sequentially, so the verdicts are exact and the
        first False in call-site order names the failing block and
        operation. Discard the speculative state, rebuild the committed
        position, re-apply the proven prefix to land exactly at the
        failure boundary, and raise the failing set's structured error."""
        self.stats.rollback()
        self.stats.sequential_reverify()
        fail_idx = verdicts.index(False)
        at = 0
        fail_block = 0
        local_idx = fail_idx
        for i, entry in enumerate(window.entries):
            n = len(entry.batch)
            if fail_idx < at + n:
                fail_block, local_idx = i, fail_idx - at
                break
            at += n
        error = window.entries[fail_block].batch.errors[local_idx]
        trace.event(
            "pipeline.rollback",
            seq=window.seq,
            failed_slot=window.entries[fail_block].slot,
            committed_blocks=fail_block,
            error=type(error).__name__,
        )
        hooked = _flight.HOOK.active
        if hooked:
            # disposition of every block the failed window carried: the
            # proven prefix commits (re-applied below without re-pairing),
            # the blamed block rolls back, the rest of the speculative
            # window is discarded
            for entry in window.entries[:fail_block]:
                self._emit_block(entry, "committed", window=window)
            self._emit_block(
                window.entries[fail_block], "rolled-back", window=window,
                blame=error,
            )
            for entry in window.entries[fail_block + 1:]:
                self._emit_block(entry, "discarded", window=window)
            _flight.HOOK.emit(
                "rollback",
                {
                    "seq": window.seq,
                    "slot": window.entries[fail_block].slot,
                    "structural": False,
                    "error": type(error).__name__,
                    "committed_blocks": fail_block,
                },
            )
        dropped = self._sched.drop_all()
        pending, self._pending = self._pending, []
        if hooked:
            self._emit_discards(dropped, pending)
        self._materialize_committed()
        if fail_block > 0:
            proven = window.entries[:fail_block]
            throwaway = SignatureBatch()
            with defer_flushes(throwaway):
                for entry in proven:
                    self._executor.apply_block_with_validation(
                        entry.signed_block, self._validation
                    )
            self._since_checkpoint.extend(e.signed_block for e in proven)
            self.stats.blocks_were_committed(fail_block)
            if _flight.HOOK.state_active:
                # the live state IS the rolled-back committed position
                # (checkpoint + proven prefix, just re-applied): publish
                # it so the serving head lands exactly at the failure
                # boundary — the rolled-back state itself is never
                # published (it was discarded above, pre-commit)
                self._publish_state(
                    proven, self._executor.state.copy(), seq=window.seq
                )
            if hooked:
                self._emit_head(proven[-1], fail_block, seq=window.seq,
                                trace_ctx=window.trace_ctx)
        self._broken = error
        self.stats.stop()
        raise error

    def _fail_structural(self, exc: Exception) -> None:
        """Stage A aborted structurally mid-block: the live state is a
        discarded partial mutation. Earlier queued blocks must settle
        FIRST — an earlier block's bad signature preempts this later
        block's error, matching sequential order. In-flight windows
        settle through their normal paths; still-pending blocks re-apply
        sequentially with INLINE verification (the terminal sequential
        re-verify). Then the structural error propagates with the state
        at the last committed position."""
        pending, self._pending = self._pending, []
        hooked = _flight.HOOK.active
        try:
            while not self._sched.idle:
                self._settle_oldest()  # an earlier window failure raises
            self._materialize_committed()
            if pending:
                self.stats.sequential_reverify()
                for entry in pending:
                    try:
                        self._executor.apply_block_with_validation(
                            entry.signed_block, self._validation
                        )
                    except Error as inline_exc:
                        if hooked:
                            self._emit_block(
                                entry, "rolled-back", blame=inline_exc
                            )
                        raise
                    self._since_checkpoint.append(entry.signed_block)
                    self.stats.blocks_were_committed(1)
                    if _flight.HOOK.state_active:
                        # each inline re-apply advances the committed
                        # position with the live state sitting exactly on
                        # it (rare path: structural abort drain)
                        self._publish_state(
                            [entry], self._executor.state.copy()
                        )
                    if hooked:
                        # committed, but verified IN-LINE on the host (the
                        # terminal sequential re-verify) — the lineage
                        # marks the lost overlap like a degraded window
                        self._emit_block(entry, "committed", degraded=True)
                if hooked:
                    self._emit_head(pending[-1], len(pending))
        except Error as earlier:
            if self._broken is None:  # a pending inline re-apply failed
                self._materialize_committed()
                self._broken = earlier
                self.stats.stop()
            raise earlier
        self._broken = exc
        self.stats.stop()
        raise exc
