"""Chain pipeline: async block application with cross-block signature
batching.

``ChainPipeline`` (engine.py) turns the one-shot ``Executor`` into a
streaming engine: stage A speculatively applies each block on the host
(state mutation + incremental HTR, signatures collected, not verified);
stage B proves up to ``FlushPolicy.window_size`` consecutive blocks'
merged signature sets in one coalesced multi-pairing on a background
verifier, with a bounded in-flight queue (backpressure), rollback to the
last committed state on a failed flush, and exact structured-error
attribution. ``PipelineStats`` is the counter surface; ``python -m
ethereum_consensus_tpu.pipeline --selfcheck`` is the smoke entry point.

Host-only by construction: importing this package never imports jax —
the device pairing route engages underneath ``crypto.bls`` exactly when
``ops.install()`` has routed it.
"""

from .engine import ChainPipeline
from .errors import PipelineBrokenError, TransientFlushError, WorkerKilled
from .faults import FaultInjector
from .scheduler import FlushPolicy, VerifyScheduler, Window, auto_verify_lanes
from .stats import PipelineStats

__all__ = [
    "ChainPipeline",
    "FaultInjector",
    "FlushPolicy",
    "PipelineBrokenError",
    "PipelineStats",
    "TransientFlushError",
    "VerifyScheduler",
    "Window",
    "WorkerKilled",
    "auto_verify_lanes",
]
