"""Pipeline error taxonomy — shared by engine.py and scheduler.py.

These live in their own module (not engine.py) because the scheduler's
hardened settle path raises ``PipelineBrokenError`` too, and importing
it from the engine would be circular (engine imports scheduler).
"""

from __future__ import annotations

__all__ = ["PipelineBrokenError", "TransientFlushError", "WorkerKilled"]


class PipelineBrokenError(RuntimeError):
    """The pipeline already failed (the structured error was raised at the
    failure point), was aborted, or a bounded wait expired on a wedged
    verifier; it accepts no further blocks. ``window_seq`` / ``slots``
    carry the stuck window's attribution when a timeout raised it."""

    def __init__(self, message: str, window_seq: "int | None" = None,
                 slots: "tuple | None" = None):
        super().__init__(message)
        self.window_seq = window_seq
        self.slots = tuple(slots) if slots else ()


class TransientFlushError(RuntimeError):
    """A flush failed for an infrastructure (non-consensus) reason that a
    retry can plausibly clear — the scheduler retries it with bounded
    backoff before degrading to in-line verification. Consensus verdicts
    are NEVER modeled as transient: an invalid signature is a verdict,
    not an error."""


class WorkerKilled(BaseException):
    """The background verifier worker died mid-flush (fault injection's
    stand-in for a crashed/OOM-killed thread). Derives from BaseException
    so nothing on the worker accidentally swallows it; the scheduler
    catches it at the settle boundary and degrades to in-line host
    verification."""
