"""``python -m ethereum_consensus_tpu.pipeline --selfcheck`` — smoke the
pipeline end-to-end without pytest.

Two tiers, best available wins:

* **chain tier** (repo checkout: ``tests/chain_utils.py`` importable) —
  build a toy minimal-preset chain, replay it pipelined vs sequential,
  require bit-identical roots; then tamper a mid-stream block signature
  and require rollback to the last committed state with the structured
  error.
* **window tier** (installed package, no test scaffolding) — drive the
  scheduler + signature-window machinery directly with real BLS keys,
  including a tampered-set rollback-attribution check.

Telemetry exports (docs/OBSERVABILITY.md):

* ``--lanes N``          — run the chain tier with N verifier lanes
  (``FlushPolicy.verify_lanes``): windows fan over N FIFO workers,
  settle order preserved — the multi-core blocks/s shape.

* ``--trace-out PATH``   — record every span/event of the selfcheck and
  write a Chrome trace-event JSON (Perfetto / ``chrome://tracing``):
  stage A and the background verifier render as separate tracks with
  flush dispatch/verify/settle windows and rollbacks visible.
* ``--metrics-out PATH`` — dump the process-wide metrics registry
  snapshot (digests, pubkey-cache hit rates, flush shapes, ...) as JSON.
* ``--memory-out PATH`` — run the memory & bandwidth observatory for
  the selfcheck and write its ledgers (census/worst table, phase RSS
  ledger, bulk-copy sites) as JSON
* ``--device-out PATH``  — run the device execution observatory
  (``telemetry/device.py``) for the selfcheck's duration and dump its
  ledgers (compile ledger + recompile sentinel, per-site host<->device
  transfer bytes, the device-vs-host routing journal) as JSON. The
  three `-out` flags together are ``make profile``'s capture artifact
  (docs/TPU_CAPTURE_PLAN.md).
* ``--serve PORT``       — run the live introspection server
  (``telemetry/server.py``: /metrics Prometheus exposition, /healthz,
  /blocks lineage, /events SSE) for the selfcheck's duration; 0 picks
  an ephemeral port. ``--hold SECONDS`` keeps it up after the checks
  finish so you can scrape/curl around (``make serve``).
* ``--serve-data``       — additionally mount the Beacon-API read data
  plane (``serving/``: validators, balances, committees, duties, ...)
  fed by the selfcheck replay's commits (``make serve-data``); requires
  ``--serve``.

Exit code 0 = all checks passed; any failure prints the reason and
exits 1.
"""

from __future__ import annotations

import sys
from pathlib import Path


def _find_chain_utils() -> bool:
    """Make tests/chain_utils importable when running from a repo
    checkout; False when only the installed package exists."""
    tests_dir = Path(__file__).resolve().parents[2] / "tests"
    if (tests_dir / "chain_utils.py").is_file():
        sys.path.insert(0, str(tests_dir))
        return True
    return False


def _selfcheck_chain(lanes: int = 1) -> None:
    from chain_utils import fresh_genesis, make_attestation, produce_block

    from ..error import InvalidBlock
    from ..executor import Executor
    from ..models.phase0.state_transition import (
        Validation as P0Validation,
        state_transition_block_in_slot as p0_transition,
    )
    from . import ChainPipeline, FlushPolicy

    state, ctx = fresh_genesis(64, "minimal")
    scratch = state.copy()
    blocks = []
    pending_atts = []
    n_blocks = 6
    for slot in range(1, n_blocks + 1):
        block = produce_block(scratch, slot, ctx, attestations=pending_atts)
        p0_transition(scratch, block, P0Validation.ENABLED, ctx)
        pending_atts = [make_attestation(scratch, slot, 0, ctx)]
        blocks.append(block)

    # pipelined replay must be bit-identical to sequential
    sequential = Executor(state.copy(), ctx)
    for block in blocks:
        sequential.apply_block(block)
    pipelined = Executor(state.copy(), ctx)
    stats = pipelined.stream(
        blocks,
        policy=FlushPolicy(
            window_size=3,
            max_in_flight=max(2, lanes),
            verify_lanes=lanes,
        ),
    )
    if pipelined.state.hash_tree_root() != sequential.state.hash_tree_root():
        raise AssertionError("pipelined root != sequential root")
    if lanes > 1:
        print(f"chain tier: {lanes} verifier lanes, settle order preserved")
    if stats.blocks_committed != n_blocks:
        raise AssertionError(f"committed {stats.blocks_committed}/{n_blocks}")
    print(
        f"chain tier: {n_blocks} blocks bit-identical; "
        f"flushes={stats.flushes} occ={stats.occupancy()}"
    )

    # mid-stream invalid proposer signature (a VALID G2 point signing the
    # wrong message, so it survives parsing and fails only at the pairing):
    # rollback + structured error
    bad = blocks[3].copy()
    bad.signature = bytes(blocks[2].signature)
    broken = Executor(state.copy(), ctx)
    pipe = ChainPipeline(broken, policy=FlushPolicy(window_size=2))
    caught = None
    try:
        for block in blocks[:3] + [bad] + blocks[4:]:
            pipe.submit(block)
        pipe.close()
    except Exception as exc:  # noqa: BLE001 — selfcheck inspects it
        caught = exc
    if not isinstance(caught, InvalidBlock):
        raise AssertionError(f"expected InvalidBlock, got {caught!r}")
    expect = Executor(state.copy(), ctx)
    for block in blocks[:3]:
        expect.apply_block(block)
    if broken.state.hash_tree_root() != expect.state.hash_tree_root():
        raise AssertionError("rollback state != last committed prefix")
    print("chain tier: mid-stream rollback + structured error OK")


def _selfcheck_window() -> None:
    from ..crypto import bls
    from ..error import InvalidAttestation
    from ..models.signature_batch import SignatureBatch
    from .scheduler import FlushPolicy, VerifyScheduler, Window
    from .stats import PipelineStats

    sks = [bls.SecretKey(i + 101) for i in range(6)]
    stats = PipelineStats()
    stats.start()
    sched = VerifyScheduler(FlushPolicy(window_size=3, max_in_flight=2), stats)

    def make_batch(tamper: bool) -> SignatureBatch:
        batch = SignatureBatch()
        for i, sk in enumerate(sks):
            msg = b"selfcheck-%d" % i
            sig = sk.sign(msg if not tamper or i != 3 else b"wrong")
            batch.defer(
                [sk.public_key()], msg, sig, InvalidAttestation(f"set {i}")
            )
        return batch

    good, bad = make_batch(False), make_batch(True)
    sched.dispatch(Window([None], good, None, 0))
    sched.dispatch(Window([None], bad, None, 1))
    if not sched.full:
        raise AssertionError("bounded queue did not fill at cap")
    _, verdicts = sched.settle_oldest()
    if not all(verdicts):
        raise AssertionError("valid window rejected")
    _, verdicts = sched.settle_oldest()
    if verdicts.index(False) != 3:
        raise AssertionError(f"bad set misattributed: {verdicts}")
    stats.stop()
    print(
        f"window tier: coalesced verify + attribution OK "
        f"(high_watermark={stats.queue_high_watermark})"
    )


def _flag_value(argv: "list[str]", flag: str) -> "str | None":
    if flag in argv:
        at = argv.index(flag)
        if at + 1 >= len(argv):
            raise SystemExit(f"{flag} requires a path argument")
        return argv[at + 1]
    return None


def main(argv: "list[str]") -> int:
    trace_out = _flag_value(argv, "--trace-out")
    metrics_out = _flag_value(argv, "--metrics-out")
    device_out = _flag_value(argv, "--device-out")
    memory_out = _flag_value(argv, "--memory-out")
    serve_port = _flag_value(argv, "--serve")
    hold_s = _flag_value(argv, "--hold")
    lanes = int(_flag_value(argv, "--lanes") or "1")
    if "--selfcheck" not in argv:
        print(__doc__)
        return 2
    from ..telemetry import device as device_obs
    from ..telemetry import memory as memory_obs
    from ..telemetry import metrics, spans

    server = None
    store = None
    if serve_port is not None:
        from ..telemetry.server import IntrospectionServer

        server = IntrospectionServer(port=int(serve_port)).start()
        print(
            f"introspection server on {server.url()} "
            "(/metrics /healthz /blocks /events)"
        )
        if "--serve-data" in argv:
            from ..serving import BeaconDataPlane, HeadStore

            store = HeadStore().attach()
            server.mount(BeaconDataPlane(store))
            print(
                f"beacon data plane mounted on {server.url('/eth/')} "
                "(validators, balances, committees, duties — fed by the "
                "selfcheck replay's commits)"
            )
    elif "--serve-data" in argv:
        raise SystemExit("--serve-data requires --serve PORT")
    if trace_out:
        spans.start_recording()
    if device_out:
        device_obs.start()
    if memory_out:
        memory_obs.start()
    try:
        if _find_chain_utils():
            _selfcheck_chain(lanes=lanes)
        _selfcheck_window()
    except Exception as exc:  # noqa: BLE001 — smoke must report, not crash
        print(f"SELFCHECK FAILED: {type(exc).__name__}: {exc}")
        if store is not None:
            store.detach()
        if server is not None:
            server.stop()
        return 1
    finally:
        if trace_out:
            spans.stop_recording()
            spans.write_chrome_trace(trace_out)
            print(f"chrome trace written: {trace_out}")
        if metrics_out:
            import json

            with open(metrics_out, "w", encoding="utf-8") as f:
                json.dump(metrics.snapshot(), f, indent=1, sort_keys=True)
            print(f"metrics snapshot written: {metrics_out}")
        if device_out:
            import json

            device_obs.stop()
            with open(device_out, "w", encoding="utf-8") as f:
                json.dump(
                    device_obs.snapshot(), f, indent=1, sort_keys=True
                )
            print(f"device ledger written: {device_out}")
        if memory_out:
            import json

            memory_obs.stop()
            with open(memory_out, "w", encoding="utf-8") as f:
                json.dump(
                    memory_obs.snapshot(), f, indent=1, sort_keys=True
                )
            print(f"memory ledger written: {memory_out}")
    print("selfcheck OK")
    if server is not None:
        if hold_s is not None and float(hold_s) > 0:
            import time as _time

            print(
                f"holding the introspection server for {hold_s}s "
                f"({server.url('/blocks')} has the selfcheck's lineage)"
            )
            if store is not None and store.head is not None:
                print(
                    f"data plane head: slot {store.head.slot} — try "
                    f"{server.url('/eth/v1/beacon/states/head/validators?id=0,1,2')}"
                )
            _time.sleep(float(hold_s))
        if store is not None:
            store.detach()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
