"""FaultInjector — deterministic infrastructure-fault injection for the
chain pipeline (the scenario harness's stage-B chaos hook).

The injector owns a per-(window seq, attempt) fault plan; the scheduler
asks it for a hook before every flush dispatch and runs that hook ON THE
VERIFIER WORKER immediately before verification (the ``pre`` parameter
of ``bls.verify_signature_sets_async``), so an injected fault surfaces
exactly where a real one would: inside the flush future.

Three fault shapes, matching the hardening they exercise
(scheduler.settle_oldest):

* ``fail_flush(seq, times)``  — ``TransientFlushError`` on the first
  ``times`` attempts of window ``seq``; the scheduler retries with
  bounded backoff (``FlushPolicy.flush_retries``) and the flush succeeds
  once the plan is exhausted.
* ``kill_worker(seq)``        — ``WorkerKilled`` from the worker
  mid-flush; the scheduler detects the death and degrades that window to
  in-line host verification (no hang, verdicts still exact).
* ``delay_flush(seq, s)``     — the worker sleeps ``s`` seconds before
  verifying; with ``s`` beyond ``FlushPolicy.settle_timeout_s`` the
  bounded settle raises ``PipelineBrokenError`` with the stuck window's
  attribution instead of deadlocking the submitter.

A fourth lane targets the MESH route (parallel/runtime.py): the injector
holds a per-kind budget of device faults (``fail_mesh("pairing"|
"epoch", times)``) consumed by ``runtime.fault_point`` inside the
sharded paths (parallel/pairing.py, parallel/epoch.py) while the
injector is installed (``install_mesh``/``uninstall_mesh``) — an
injected fault surfaces exactly where real device trouble would, the
decline is journaled (``mesh.decline.injected_fault``), and the host
fallback recovers with bit-identical results. Mesh injections land in
the same ``injected`` audit log with seq/attempt ``None`` (the mesh
seam is route-scoped, not window-scoped).

Thread-safety: the plan is written from the test/driver thread and read
from both the engine thread (hook_for) and the worker (the hook itself);
every access holds the instance lock.
"""

from __future__ import annotations

import threading
import time

from ..telemetry import metrics
from ..utils import trace
from .errors import TransientFlushError, WorkerKilled

__all__ = ["FaultInjector"]


class FaultInjector:
    """Deterministic per-window fault plan for the verify scheduler."""

    def __init__(self):
        self._lock = threading.Lock()
        self._transient: dict = {}   # seq -> remaining failures
        self._kill: set = set()      # seqs whose worker dies mid-flush
        self._delay: dict = {}       # seq -> seconds of worker stall
        self._mesh: dict = {}        # route kind -> remaining device faults
        self._injected: list = []    # (seq, attempt, kind) audit log

    # -- plan construction (driver side) -------------------------------------
    def fail_flush(self, seq: int, times: int = 1) -> "FaultInjector":
        """Raise ``TransientFlushError`` on the first ``times`` verify
        attempts of window ``seq``."""
        with self._lock:
            self._transient[seq] = times
        return self

    def kill_worker(self, seq: int) -> "FaultInjector":
        """Kill the verifier worker mid-flush on window ``seq`` (every
        attempt — a dead worker stays dead)."""
        with self._lock:
            self._kill.add(seq)
        return self

    def delay_flush(self, seq: int, seconds: float) -> "FaultInjector":
        """Stall the worker ``seconds`` before verifying window ``seq``."""
        with self._lock:
            self._delay[seq] = float(seconds)
        return self

    def fail_mesh(self, kind: str, times: int = 1) -> "FaultInjector":
        """Plan ``times`` device faults on the mesh route ``kind``
        (``"pairing"`` / ``"epoch"``), consumed by
        ``parallel.runtime.fault_point`` while this injector is
        installed (``install_mesh``)."""
        with self._lock:
            self._mesh[kind] = self._mesh.get(kind, 0) + int(times)
        return self

    def install_mesh(self) -> "FaultInjector":
        """Arm the process-wide mesh fault seam with this injector's
        plan (parallel/runtime.install_fault_hook). Callers must
        ``uninstall_mesh`` when done — the seam is process-wide."""
        from ..parallel import runtime as _mesh_runtime

        _mesh_runtime.install_fault_hook(self.mesh_hook)
        return self

    def uninstall_mesh(self) -> None:
        from ..parallel import runtime as _mesh_runtime

        _mesh_runtime.install_fault_hook(None)

    def mesh_hook(self, kind: str) -> bool:
        """The seam's consumption callback: True exactly when a planned
        mesh fault for ``kind`` exists (one is consumed and audited)."""
        with self._lock:
            remaining = self._mesh.get(kind, 0)
            if remaining <= 0:
                return False
            self._mesh[kind] = remaining - 1
            self._injected.append((None, None, f"mesh_{kind}"))
        metrics.counter(f"pipeline.fault.injected.mesh_{kind}").inc()
        trace.event("pipeline.fault.injected", kind=f"mesh_{kind}")
        return True

    @property
    def injected(self) -> list:
        """(seq, attempt, kind) tuples, in injection order."""
        with self._lock:
            return list(self._injected)

    # -- hook resolution (scheduler side) ------------------------------------
    def hook_for(self, seq: int, attempt: int):
        """The pre-verify hook to run on the worker for this (window,
        attempt), or None when no fault is planned. The hook itself
        consumes the plan entry, so a retry of the same window re-asks
        and gets the NEXT planned behavior."""
        with self._lock:
            armed = (
                seq in self._kill
                or seq in self._delay
                or self._transient.get(seq, 0) > 0
            )
        if not armed:
            return None

        def fire() -> None:
            with self._lock:
                delay = self._delay.get(seq)
                kill = seq in self._kill
                remaining = self._transient.get(seq, 0)
                if remaining > 0:
                    self._transient[seq] = remaining - 1
                kind = (
                    "delay" if delay else
                    "worker_death" if kill else
                    "transient" if remaining > 0 else None
                )
                if kind is not None:
                    self._injected.append((seq, attempt, kind))
            if kind is None:
                return
            metrics.counter(f"pipeline.fault.injected.{kind}").inc()
            trace.event(
                "pipeline.fault.injected",
                seq=seq, attempt=attempt, kind=kind,
            )
            if delay:
                time.sleep(delay)
            if kill:
                raise WorkerKilled(f"injected worker death (window {seq})")
            if remaining > 0:
                raise TransientFlushError(
                    f"injected transient flush fault (window {seq}, "
                    f"attempt {attempt})"
                )

        return fire
