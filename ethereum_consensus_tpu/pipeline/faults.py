"""FaultInjector — deterministic infrastructure-fault injection for the
chain pipeline (the scenario harness's stage-B chaos hook).

The injector owns a per-(window seq, attempt) fault plan; the scheduler
asks it for a hook before every flush dispatch and runs that hook ON THE
VERIFIER WORKER immediately before verification (the ``pre`` parameter
of ``bls.verify_signature_sets_async``), so an injected fault surfaces
exactly where a real one would: inside the flush future.

Three fault shapes, matching the hardening they exercise
(scheduler.settle_oldest):

* ``fail_flush(seq, times)``  — ``TransientFlushError`` on the first
  ``times`` attempts of window ``seq``; the scheduler retries with
  bounded backoff (``FlushPolicy.flush_retries``) and the flush succeeds
  once the plan is exhausted.
* ``kill_worker(seq)``        — ``WorkerKilled`` from the worker
  mid-flush; the scheduler detects the death and degrades that window to
  in-line host verification (no hang, verdicts still exact).
* ``delay_flush(seq, s)``     — the worker sleeps ``s`` seconds before
  verifying; with ``s`` beyond ``FlushPolicy.settle_timeout_s`` the
  bounded settle raises ``PipelineBrokenError`` with the stuck window's
  attribution instead of deadlocking the submitter.

Thread-safety: the plan is written from the test/driver thread and read
from both the engine thread (hook_for) and the worker (the hook itself);
every access holds the instance lock.
"""

from __future__ import annotations

import threading
import time

from ..telemetry import metrics
from ..utils import trace
from .errors import TransientFlushError, WorkerKilled

__all__ = ["FaultInjector"]


class FaultInjector:
    """Deterministic per-window fault plan for the verify scheduler."""

    def __init__(self):
        self._lock = threading.Lock()
        self._transient: dict = {}   # seq -> remaining failures
        self._kill: set = set()      # seqs whose worker dies mid-flush
        self._delay: dict = {}       # seq -> seconds of worker stall
        self._injected: list = []    # (seq, attempt, kind) audit log

    # -- plan construction (driver side) -------------------------------------
    def fail_flush(self, seq: int, times: int = 1) -> "FaultInjector":
        """Raise ``TransientFlushError`` on the first ``times`` verify
        attempts of window ``seq``."""
        with self._lock:
            self._transient[seq] = times
        return self

    def kill_worker(self, seq: int) -> "FaultInjector":
        """Kill the verifier worker mid-flush on window ``seq`` (every
        attempt — a dead worker stays dead)."""
        with self._lock:
            self._kill.add(seq)
        return self

    def delay_flush(self, seq: int, seconds: float) -> "FaultInjector":
        """Stall the worker ``seconds`` before verifying window ``seq``."""
        with self._lock:
            self._delay[seq] = float(seconds)
        return self

    @property
    def injected(self) -> list:
        """(seq, attempt, kind) tuples, in injection order."""
        with self._lock:
            return list(self._injected)

    # -- hook resolution (scheduler side) ------------------------------------
    def hook_for(self, seq: int, attempt: int):
        """The pre-verify hook to run on the worker for this (window,
        attempt), or None when no fault is planned. The hook itself
        consumes the plan entry, so a retry of the same window re-asks
        and gets the NEXT planned behavior."""
        with self._lock:
            armed = (
                seq in self._kill
                or seq in self._delay
                or self._transient.get(seq, 0) > 0
            )
        if not armed:
            return None

        def fire() -> None:
            with self._lock:
                delay = self._delay.get(seq)
                kill = seq in self._kill
                remaining = self._transient.get(seq, 0)
                if remaining > 0:
                    self._transient[seq] = remaining - 1
                kind = (
                    "delay" if delay else
                    "worker_death" if kill else
                    "transient" if remaining > 0 else None
                )
                if kind is not None:
                    self._injected.append((seq, attempt, kind))
            if kind is None:
                return
            metrics.counter(f"pipeline.fault.injected.{kind}").inc()
            trace.event(
                "pipeline.fault.injected",
                seq=seq, attempt=attempt, kind=kind,
            )
            if delay:
                time.sleep(delay)
            if kill:
                raise WorkerKilled(f"injected worker death (window {seq})")
            if remaining > 0:
                raise TransientFlushError(
                    f"injected transient flush fault (window {seq}, "
                    f"attempt {attempt})"
                )

        return fire
