"""Pipeline counter surface.

One ``PipelineStats`` instance rides a ``ChainPipeline`` run and is safe
to read from any thread at any time (every mutation holds one lock; the
snapshot is taken under the same lock). The counters are the operational
story of a run:

* throughput — blocks submitted/committed, wall seconds;
* flush shape — how many windowed flushes, how many sets each coalesced
  (the multi-pairing amortization the pipeline exists for);
* failure handling — rollbacks and sequential re-verifications;
* occupancy — how busy each stage was. Stage A is the host (state
  mutation + incremental HTR + signature collection, on the submitting
  thread); stage B is the verifier (the coalesced multi-pairings, on the
  background worker). Occupancies near 1.0 on BOTH stages mean the
  overlap is real; a stage near 0 is the bottleneck's complement.
"""

from __future__ import annotations

import threading
import time

__all__ = ["PipelineStats"]


class PipelineStats:
    """Counters for one pipeline run; all methods thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self.blocks_submitted = 0
        self.blocks_committed = 0
        self.flushes = 0
        self.sets_flushed = 0
        self.flush_sizes: list[int] = []
        self.rollbacks = 0
        self.sequential_reverifies = 0
        self.checkpoints = 0
        self.stage_a_s = 0.0
        self.stage_b_s = 0.0
        self.queue_high_watermark = 0
        self._t_start: float | None = None
        self._t_end: float | None = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._t_start is None:
                self._t_start = time.perf_counter()

    def stop(self) -> None:
        with self._lock:
            self._t_end = time.perf_counter()

    @property
    def wall_s(self) -> float:
        with self._lock:
            if self._t_start is None:
                return 0.0
            end = self._t_end if self._t_end is not None else time.perf_counter()
            return end - self._t_start

    # -- mutation ------------------------------------------------------------
    def block_submitted(self, stage_a_s: float) -> None:
        with self._lock:
            self.blocks_submitted += 1
            self.stage_a_s += stage_a_s

    def blocks_were_committed(self, n: int) -> None:
        with self._lock:
            self.blocks_committed += n

    def flush_dispatched(self, n_sets: int) -> None:
        with self._lock:
            self.flushes += 1
            self.sets_flushed += n_sets
            self.flush_sizes.append(n_sets)

    def stage_b_busy(self, seconds: float) -> None:
        with self._lock:
            self.stage_b_s += seconds

    def rollback(self) -> None:
        with self._lock:
            self.rollbacks += 1

    def checkpoint(self) -> None:
        with self._lock:
            self.checkpoints += 1

    def sequential_reverify(self) -> None:
        with self._lock:
            self.sequential_reverifies += 1

    def queue_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self.queue_high_watermark:
                self.queue_high_watermark = depth

    # -- reading -------------------------------------------------------------
    def occupancy(self) -> dict:
        """Per-stage busy fraction of the run's wall clock."""
        wall = self.wall_s
        with self._lock:
            if wall <= 0.0:
                return {"stage_a": 0.0, "stage_b": 0.0}
            return {
                "stage_a": min(1.0, self.stage_a_s / wall),
                "stage_b": min(1.0, self.stage_b_s / wall),
            }

    def snapshot(self) -> dict:
        """A plain-dict copy (JSON-ready) of every counter."""
        wall = self.wall_s
        with self._lock:
            sizes = list(self.flush_sizes)
            return {
                "blocks_submitted": self.blocks_submitted,
                "blocks_committed": self.blocks_committed,
                "flushes": self.flushes,
                "sets_flushed": self.sets_flushed,
                "flush_sizes": sizes,
                "max_flush_size": max(sizes) if sizes else 0,
                "mean_flush_size": (
                    sum(sizes) / len(sizes) if sizes else 0.0
                ),
                "rollbacks": self.rollbacks,
                "sequential_reverifies": self.sequential_reverifies,
                "checkpoints": self.checkpoints,
                "stage_a_s": self.stage_a_s,
                "stage_b_s": self.stage_b_s,
                "wall_s": wall,
                "stage_a_occupancy": (
                    min(1.0, self.stage_a_s / wall) if wall > 0 else 0.0
                ),
                "stage_b_occupancy": (
                    min(1.0, self.stage_b_s / wall) if wall > 0 else 0.0
                ),
                "queue_high_watermark": self.queue_high_watermark,
            }

    def __repr__(self) -> str:
        s = self.snapshot()
        return (
            f"PipelineStats(blocks={s['blocks_committed']}/"
            f"{s['blocks_submitted']}, flushes={s['flushes']}, "
            f"rollbacks={s['rollbacks']}, "
            f"occ_a={s['stage_a_occupancy']:.2f}, "
            f"occ_b={s['stage_b_occupancy']:.2f})"
        )
