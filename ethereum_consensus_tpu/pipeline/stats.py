"""Pipeline counter surface — a per-run view over the telemetry registry.

The counters themselves live in the process-wide metrics registry
(``telemetry/metrics.py``) under ``pipeline.*`` names, so any consumer
of the registry — the bench ``metrics`` block, ``--metrics-out`` dumps —
sees pipeline activity without holding a ``PipelineStats`` reference.
One ``PipelineStats`` instance rides one ``ChainPipeline`` run and reads
as the DELTA since its construction: each counter property subtracts the
baseline captured in ``__init__``, and ``stop()`` freezes the view so a
finished run's numbers stay exact even after a later run increments the
shared registry counters.

Per-run-only shapes (the exact flush-size list and the queue-depth
high-watermark, which are max/list semantics a monotonic registry
counter can't replay) are kept on the instance and mirrored to the
registry (``pipeline.flush_size`` histogram,
``pipeline.queue_depth_high_watermark`` gauge).

Concurrency: all mutation is thread-safe (every write holds a lock —
the metric's own or the instance's). The per-run VIEW is exact when
runs don't overlap in time, which the engine guarantees for its own
stats (one pipeline owns one stats instance and ``stop()`` freezes it at
close/abort/failure); two pipelines deliberately run concurrently would
fold each other's counts into their live views, while the registry
totals stay correct either way.

The counters are the operational story of a run:

* throughput — blocks submitted/committed, wall seconds;
* flush shape — how many windowed flushes, how many sets each coalesced
  (the multi-pairing amortization the pipeline exists for);
* failure handling — rollbacks and sequential re-verifications;
* occupancy — how busy each stage was. Stage A is the host (state
  mutation + incremental HTR + signature collection, on the submitting
  thread); stage B is the verifier (the coalesced multi-pairings, on the
  background worker). Occupancies near 1.0 on BOTH stages mean the
  overlap is real; a stage near 0 is the bottleneck's complement.
"""

from __future__ import annotations

import threading
import time

from ..telemetry import metrics as _metrics

__all__ = ["PipelineStats"]

# the registry counters one run's view subtracts its baseline from;
# seconds-valued entries end in _s (float increments)
_COUNTER_NAMES = (
    "blocks_submitted",
    "blocks_committed",
    "flushes",
    "sets_flushed",
    "rollbacks",
    "sequential_reverifies",
    "checkpoints",
    "fault_retries",
    "degraded_flushes",
    "stage_a_s",
    "stage_b_s",
)


class PipelineStats:
    """Per-run delta view over the ``pipeline.*`` registry counters;
    all methods thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {
            name: _metrics.counter(f"pipeline.{name}")
            for name in _COUNTER_NAMES
        }
        self._base = {
            name: c.value() for name, c in self._counters.items()
        }
        self._frozen: "dict | None" = None
        self._flush_sizes: list = []
        self._queue_high_watermark = 0
        self._flush_size_hist = _metrics.histogram("pipeline.flush_size")
        self._queue_gauge = _metrics.gauge("pipeline.queue_depth_high_watermark")
        self._t_start: "float | None" = None
        self._t_end: "float | None" = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._t_start is None:
                self._t_start = time.perf_counter()

    def stop(self) -> None:
        """Stamp the end time and freeze the per-run counter view (a
        later run's registry increments no longer show through)."""
        frozen = {
            name: c.value() - self._base[name]
            for name, c in self._counters.items()
        }
        with self._lock:
            self._t_end = time.perf_counter()
            self._frozen = frozen

    @property
    def wall_s(self) -> float:
        with self._lock:
            if self._t_start is None:
                return 0.0
            end = self._t_end if self._t_end is not None else time.perf_counter()
            return end - self._t_start

    # -- the counter view ----------------------------------------------------
    def _view(self, name: str):
        frozen = self._frozen
        if frozen is not None:
            return frozen[name]
        return self._counters[name].value() - self._base[name]

    @property
    def blocks_submitted(self) -> int:
        return self._view("blocks_submitted")

    @property
    def blocks_committed(self) -> int:
        return self._view("blocks_committed")

    @property
    def flushes(self) -> int:
        return self._view("flushes")

    @property
    def sets_flushed(self) -> int:
        return self._view("sets_flushed")

    @property
    def rollbacks(self) -> int:
        return self._view("rollbacks")

    @property
    def sequential_reverifies(self) -> int:
        return self._view("sequential_reverifies")

    @property
    def checkpoints(self) -> int:
        return self._view("checkpoints")

    @property
    def fault_retries(self) -> int:
        return self._view("fault_retries")

    @property
    def degraded_flushes(self) -> int:
        return self._view("degraded_flushes")

    @property
    def stage_a_s(self) -> float:
        return self._view("stage_a_s")

    @property
    def stage_b_s(self) -> float:
        return self._view("stage_b_s")

    @property
    def flush_sizes(self) -> list:
        with self._lock:
            return list(self._flush_sizes)

    @property
    def queue_high_watermark(self) -> int:
        return self._queue_high_watermark

    # -- mutation ------------------------------------------------------------
    def block_submitted(self, stage_a_s: float) -> None:
        self._counters["blocks_submitted"].inc()
        self._counters["stage_a_s"].inc(stage_a_s)

    def blocks_were_committed(self, n: int) -> None:
        self._counters["blocks_committed"].inc(n)

    def flush_dispatched(self, n_sets: int) -> None:
        self._counters["flushes"].inc()
        self._counters["sets_flushed"].inc(n_sets)
        self._flush_size_hist.observe(n_sets)
        with self._lock:
            self._flush_sizes.append(n_sets)

    def stage_b_busy(self, seconds: float) -> None:
        self._counters["stage_b_s"].inc(seconds)

    def rollback(self) -> None:
        self._counters["rollbacks"].inc()

    def checkpoint(self) -> None:
        self._counters["checkpoints"].inc()

    def sequential_reverify(self) -> None:
        self._counters["sequential_reverifies"].inc()

    def fault_retry(self) -> None:
        self._counters["fault_retries"].inc()

    def degraded_flush(self) -> None:
        self._counters["degraded_flushes"].inc()

    def queue_depth(self, depth: int) -> None:
        self._queue_gauge.update_max(depth)
        with self._lock:
            if depth > self._queue_high_watermark:
                self._queue_high_watermark = depth

    # -- reading -------------------------------------------------------------
    def occupancy(self) -> dict:
        """Per-stage busy fraction of the run's wall clock."""
        wall = self.wall_s
        if wall <= 0.0:
            return {"stage_a": 0.0, "stage_b": 0.0}
        return {
            "stage_a": min(1.0, self.stage_a_s / wall),
            "stage_b": min(1.0, self.stage_b_s / wall),
        }

    def snapshot(self) -> dict:
        """A plain-dict copy (JSON-ready) of every counter."""
        wall = self.wall_s
        sizes = self.flush_sizes
        stage_a = self.stage_a_s
        stage_b = self.stage_b_s
        return {
            "blocks_submitted": self.blocks_submitted,
            "blocks_committed": self.blocks_committed,
            "flushes": self.flushes,
            "sets_flushed": self.sets_flushed,
            "flush_sizes": sizes,
            "max_flush_size": max(sizes) if sizes else 0,
            "mean_flush_size": (
                sum(sizes) / len(sizes) if sizes else 0.0
            ),
            "rollbacks": self.rollbacks,
            "sequential_reverifies": self.sequential_reverifies,
            "checkpoints": self.checkpoints,
            "fault_retries": self.fault_retries,
            "degraded_flushes": self.degraded_flushes,
            "stage_a_s": stage_a,
            "stage_b_s": stage_b,
            "wall_s": wall,
            "stage_a_occupancy": (
                min(1.0, stage_a / wall) if wall > 0 else 0.0
            ),
            "stage_b_occupancy": (
                min(1.0, stage_b / wall) if wall > 0 else 0.0
            ),
            "queue_high_watermark": self.queue_high_watermark,
        }

    def __repr__(self) -> str:
        s = self.snapshot()
        return (
            f"PipelineStats(blocks={s['blocks_committed']}/"
            f"{s['blocks_submitted']}, flushes={s['flushes']}, "
            f"rollbacks={s['rollbacks']}, "
            f"occ_a={s['stage_a_occupancy']:.2f}, "
            f"occ_b={s['stage_b_occupancy']:.2f})"
        )
