"""Signing domain types.

Reference parity: ethereum-consensus/src/domains.rs:1-30. Each domain type
encodes to 4 bytes; the spec domains use the first byte as index
(e.g. DOMAIN_BEACON_ATTESTER = 0x01000000 big-endian notation = bytes
[1,0,0,0]), application domains use the last byte (mask 0x00000001 = bytes
[0,0,0,1]).
"""

from __future__ import annotations

from enum import IntEnum


class DomainType(IntEnum):
    """Values are the little-endian u32 reading of the 4-byte encoding."""

    BEACON_PROPOSER = 0
    BEACON_ATTESTER = 1
    RANDAO = 2
    DEPOSIT = 3
    VOLUNTARY_EXIT = 4
    SELECTION_PROOF = 5
    AGGREGATE_AND_PROOF = 6
    SYNC_COMMITTEE = 7
    SYNC_COMMITTEE_SELECTION_PROOF = 8
    CONTRIBUTION_AND_PROOF = 9
    BLS_TO_EXECUTION_CHANGE = 10
    CONSOLIDATION = 11
    APPLICATION_MASK = 0x01000000  # bytes [0,0,0,1]
    # DOMAIN_APPLICATION_BUILDER shares the application-mask encoding
    APPLICATION_BUILDER = 0x01000000

    def as_bytes(self) -> bytes:
        """4-byte little-endian encoding of the domain."""
        return int(self).to_bytes(4, "little")
