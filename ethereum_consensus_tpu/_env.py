"""Central readers for the package's ``EC_*``/``ECT_*`` environment flags.

Every environ read of a repo flag goes through this module — speclint's
``envflags`` analyzer enforces it (``envflags/scattered-env-read``).
Centralizing buys three things the scattered ``os.environ.get`` sites
could not:

* one truth for the parse idioms ("off"/"0"/"false" vs "1"/"on" vs
  mode strings), so a new site cannot invent a subtly different
  spelling of "disabled";
* a statically readable key registry (``KNOWN_KEYS``) that the linter
  diffs against the documented flag table in docs/OBSERVABILITY.md, so
  an undocumented flag cannot land; and
* the import-ordering guarantee stays auditable: this module imports
  NOTHING but the stdlib, so a gate check like ``flag_off(...)`` can
  never drag jax in — the "plain env read before jax import" discipline
  (a mesh-off process must never pay for jax) is preserved by
  construction at the reader layer.

Readers deliberately take the key STRING (not an enum): call sites read
``_env.flag_off(_DISABLE_ENV)`` and the linter resolves the constant to
its ``ECT_*`` value for the KNOWN_KEYS cross-check.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

# Every environment flag the PACKAGE reads, with a one-line meaning.
# The envflags analyzer checks (a) every EC_/ECT_ environ read in the
# package resolves to a key listed here, and (b) every key here has a
# row in the "Environment flags" table in docs/OBSERVABILITY.md.
# (Harness-level keys like EC_BENCH_XL / EC_SOAK_PROFILE are read by
# bench.py outside the package and live only in the doc table.)
KNOWN_KEYS = {
    "ECT_OPS_VECTOR": "=off disables every columnar path (scalar oracle mode)",
    "ECT_EPOCH_VECTOR": "=off disables just the columnar-primary epoch engine",
    "ECT_COMMITTEE_MASKS": "=off disables just the phase0 committee-mask kernel",
    "ECT_POOL_RLC": "=off forces the pool's scalar per-message admission twin",
    "ECT_MESH": "mesh size: N devices | auto | off (plain read gates jax import)",
    "ECT_MESH_EPOCH_MIN_N": "registry size below which epoch sweeps stay host-routed",
    "ECT_MESH_MERKLE_MIN_CHUNKS": "flat-tree chunk count below which merkle stays host",
    "ECT_MESH_PROOF_MIN_CHUNKS": "proof-group chunk count below which gathers stay host",
    "ECT_PAIRING_MIN_SETS": "pairing-batch size routed to device; off pins the host engine",
    "ECT_TRACEMALLOC": "=1/on adds tracemalloc deltas to the memory observatory",
    "EC_JAX_CACHE_DIR": "jax persistent compilation cache directory",
    "EC_PAIRING_MULT": "pairing product kernel: u64 (CIOS lanes) | mxu (int8 matmul)",
    "EC_BLS_BACKEND": "BLS backend pin: auto | native | python",
    "EC_NATIVE_SHA_NI": "native SHA extension toggle (build-probe cache key input)",
}


def raw(key: str, default: str = "") -> str:
    """The raw environ value (``os.environ.get`` with a default)."""
    return os.environ.get(key, default)


def raw_or_none(key: str) -> "str | None":
    """The raw environ value, or None when the key is unset — for flags
    whose unset/empty states mean different things (ECT_PAIRING_MIN_SETS:
    unset = auto threshold, "off" = host pinned)."""
    return os.environ.get(key)


def mode(key: str, default: str = "") -> str:
    """Stripped, lowercased environ value — the mode-string idiom
    (``ECT_MESH=Auto`` reads as ``"auto"``)."""
    return os.environ.get(key, default).strip().lower()


def flag_off(key: str) -> bool:
    """True when the flag explicitly disables its feature: the repo-wide
    ``=off`` idiom (off/0/false, case-insensitive). Unset is NOT off —
    features default on and are opted out."""
    return os.environ.get(key, "").strip().lower() in ("off", "0", "false")


def flag_on(key: str) -> bool:
    """True when the flag explicitly enables its feature: the opt-in
    ``=1``/``=on`` idiom (ECT_TRACEMALLOC). Unset is NOT on."""
    return os.environ.get(key, "").strip().lower() in ("1", "on")


def mesh_requested(key: str = "ECT_MESH") -> bool:
    """Is a mesh explicitly requested? The gate host layers consult
    BEFORE importing anything jax-adjacent: unset/off/0/none/host all
    mean "no mesh" and must not trigger a jax import downstream."""
    return mode(key) not in ("", "off", "0", "none", "host")


@contextmanager
def override(key: str, value: "str | None"):
    """Temporarily pin (or, with ``None``, unset) a flag for the scope,
    restoring the prior state on exit — the scenario harness's
    scalar-mode/forced-columnar save-set-restore idiom, centralized so
    environ WRITES stay on this module's surface too."""
    old = os.environ.get(key)
    if value is None:
        os.environ.pop(key, None)
    else:
        os.environ[key] = value
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old
