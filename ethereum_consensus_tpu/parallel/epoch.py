"""Mesh-sharded epoch sweeps: the production epoch hot path on devices.

The columnar-primary epoch engine (models/epoch_vector.py) runs its
per-validator math as three numeric kernels over numpy columns. This
module lifts exactly those sweeps onto the 1-D ``shard`` mesh: the
validator axis shards row-wise over the devices, the masked
effective-balance reductions the rewards formula needs become ``psum``
collectives, and the results come home bit-identical to the host kernels
(same u64 arithmetic, same floor divisions, same application order — the
bodies REUSE the epoch_vector kernel functions wherever the scalars are
static, and mirror them operation-for-operation where a per-epoch scalar
must stay dynamic to keep XLA from re-tracing every epoch).

Padding discipline: the registry length pads up to a multiple of the
mesh size with neutral rows (zero balances/scores, all-False masks) —
padded rows contribute zero to every psum, earn zero deltas, and are
sliced back off before the columns return to the host pass. Exactness:
the caller (models/epoch_vector.py ``_sync``) has already guarded every
product/sum into the u64 lane, so device sums equal host sums exactly
(u64 addition is associative) and a decline happens BEFORE any dispatch.

The overflow contract survives sharding: the apply chain counts wrapped
lanes through a ``psum`` and the host wrapper returns ``None`` when any
wrapped — the caller then falls back to the host path, whose literal
mirror raises the structured error at the exact index (the same
unreachable-under-guards terminal the host pass keeps).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..telemetry import device as _obs
from ..telemetry import memory as _mem
from ._compat import shard_map
from .mesh import SHARD_AXIS

__all__ = ["MeshEpochSweeps", "pad_to_mesh"]


def pad_to_mesh(n: int, n_dev: int) -> int:
    """Smallest multiple of ``n_dev`` covering ``n`` rows — elementwise
    sweeps need no power-of-two subtrees (unlike the merkle shards), so
    a non-power-of-two registry pads by at most ``n_dev - 1`` neutral
    rows."""
    return -(-n // n_dev) * n_dev


def _bit_mask(part, flag_index: int):
    """The kernel-side twin of epoch_vector._flag_mask (u8 column →
    bool participation mask for one flag)."""
    return ((part >> np.uint8(flag_index)) & np.uint8(1)).astype(bool)


@functools.lru_cache(maxsize=16)
def _inactivity_sharded(mesh, bias: int, recovery: int, leaking: bool):
    """Sharded twin of epoch_vector.inactivity_scores_kernel — the SAME
    kernel body, row-sharded (it is purely elementwise; bias/recovery
    are chain constants, so static args cost one compile per chain)."""
    from ..models.epoch_vector import inactivity_scores_kernel

    def body(scores, eligible, participating):
        return inactivity_scores_kernel(
            jnp, scores, eligible, participating, bias, recovery, leaking
        )

    spec = P(SHARD_AXIS)
    return _obs.observe_jit(
        jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(spec,) * 3,
                out_specs=spec,
                check_vma=False,
            )
        ),
        "parallel.epoch.inactivity_sweep",
    )


@functools.lru_cache(maxsize=16)
def _fused_sharded(
    mesh,
    bias: int,
    recovery_rate: int,
    weights: tuple,
    weight_denominator: int,
    leaking: bool,
    head_flag_index: int,
    target_flag_index: int,
):
    """The FUSED epoch kernel (ISSUE 14), mesh-sharded: the SAME
    ``epoch_vector.fused_epoch_kernel`` body the jit route runs, with
    its scalar reductions wrapped in ``psum`` — inactivity update, flag
    deltas, inactivity penalties, and in-order application in ONE
    dispatch, so the packed columns ship to the devices once and stay
    there across every stage."""
    from ..models.epoch_vector import fused_epoch_kernel

    def body(balances, eff, prev_part, slashed, active_prev, eligible,
             scores, increment, brpi, active_increments, denominator):
        return fused_epoch_kernel(
            jnp, balances, eff, prev_part, slashed, active_prev, eligible,
            scores, increment, brpi, active_increments, denominator,
            bias, recovery_rate, weights, weight_denominator, leaking,
            head_flag_index, target_flag_index,
            psum=lambda v: jax.lax.psum(v, SHARD_AXIS),
        )

    spec = P(SHARD_AXIS)
    return _obs.observe_jit(
        jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(spec,) * 7 + (P(),) * 4,
                out_specs=(spec, spec, P()),
                check_vma=False,
            )
        ),
        "parallel.epoch.fused_sweep",
    )


@functools.lru_cache(maxsize=16)
def _rewards_sharded(
    mesh,
    weights: tuple,
    weight_denominator: int,
    leaking: bool,
    head_flag_index: int,
    target_flag_index: int,
):
    """The whole altair rewards stage as ONE sharded sweep: per-flag
    masked effective-balance ``psum`` reductions, the three flag-delta
    pairs, the inactivity-penalty pair off the post-update scores, and
    the in-order saturating application — operation-for-operation the
    host stage (models/epoch_vector.py _rewards_altair), with the
    per-epoch scalars (base-reward-per-increment, active increments,
    penalty denominator) DYNAMIC so a steady-state replay compiles once.

    Returns ``(new_balances  [sharded], wrapped_lanes [replicated],
    unslashed_sums (3,) [replicated])``; a nonzero ``wrapped_lanes``
    means a u64 wrap the guards should have made unreachable — the host
    wrapper declines so the literal overflow mirror keeps its structured
    error."""

    def body(balances, eff, prev_part, slashed, active_prev, eligible,
             scores, increment, brpi, active_increments, denominator):
        zero = jnp.uint64(0)
        base_reward = (eff // increment) * brpi
        divisor = active_increments * jnp.uint64(weight_denominator)
        unslashed_all = ~slashed
        pairs = []
        sums = []
        target_unslashed = None
        for flag_index, weight in enumerate(weights):
            unslashed = (
                active_prev & unslashed_all & _bit_mask(prev_part, flag_index)
            )
            if flag_index == target_flag_index:
                target_unslashed = unslashed
            flag_sum = jax.lax.psum(
                jnp.sum(jnp.where(unslashed, eff, zero)), SHARD_AXIS
            )
            sums.append(flag_sum)
            # get_total_balance floors at one increment
            unslashed_increments = (
                jnp.maximum(increment, flag_sum) // increment
            )
            w = jnp.uint64(weight)
            if leaking:
                rewards = jnp.zeros_like(base_reward)
            else:
                rewards = jnp.where(
                    eligible & unslashed,
                    base_reward * w * unslashed_increments // divisor,
                    zero,
                )
            if flag_index == head_flag_index:
                penalties = jnp.zeros_like(base_reward)
            else:
                penalties = jnp.where(
                    eligible & ~unslashed,
                    base_reward * w // jnp.uint64(weight_denominator),
                    zero,
                )
            pairs.append((rewards, penalties))

        # inactivity penalties off the POST-UPDATE scores (spec order)
        missed = eligible & ~target_unslashed
        inactivity_penalties = jnp.where(
            missed, eff * scores // denominator, zero
        )
        pairs.append((jnp.zeros_like(base_reward), inactivity_penalties))

        # apply in spec sequence with zero saturation BETWEEN pairs —
        # apply_delta_pairs_kernel's exact ops, plus the per-pair wrap
        # census the host path keeps
        wrapped = zero
        for rewards, penalties in pairs:
            raised = balances + rewards
            wrapped = wrapped + jnp.sum(
                (raised < balances).astype(jnp.uint64)
            )
            balances = jnp.where(raised >= penalties, raised - penalties, zero)
        wrapped_total = jax.lax.psum(wrapped, SHARD_AXIS)
        return balances, wrapped_total, jnp.stack(sums)

    spec = P(SHARD_AXIS)
    return _obs.observe_jit(
        jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(spec,) * 7 + (P(),) * 4,
                out_specs=(spec, P(), P()),
                check_vma=False,
            )
        ),
        "parallel.epoch.rewards_sweep",
    )


class MeshEpochSweeps:
    """Host-facing runner: pads, ships, runs the sharded sweeps, and
    unpads — one instance per provisioned mesh (parallel/runtime.py).
    Every entry point is a drop-in for the host kernel it shadows and
    returns plain numpy (the epoch pass's working-column dtype)."""

    __slots__ = ("mesh", "n_dev")

    def __init__(self, mesh):
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)

    def _pad(self, arr, fill=0):
        n = arr.shape[0]
        padded = pad_to_mesh(n, self.n_dev)
        if padded == n:
            return np.ascontiguousarray(arr)
        out = np.full(padded, fill, dtype=arr.dtype)
        out[:n] = arr
        # bandwidth: the mesh staging copy (the upload itself is the
        # device observatory's h2d ledger; this is the host-side
        # re-materialization the padding costs)
        mem = _mem.OBSERVATORY
        if mem.active:
            mem.record_copy("parallel.pad_to_mesh", int(out.nbytes))
        return out

    def inactivity_scores(self, scores, eligible, participating, bias: int,
                          recovery_rate: int, leaking: bool):
        """Sharded ``process_inactivity_updates`` sweep; returns the new
        scores column (numpy uint64, original length)."""
        from . import runtime as _runtime

        n = scores.shape[0]
        # fault-injection seam: an injected fault raises before any
        # dispatch, and the caller's device-trouble fallback (the host
        # kernel) recovers bit-identically — blame journaled by the seam
        _runtime.fault_point(
            "epoch", stage="inactivity", validators=n, devices=self.n_dev
        )
        kernel = _inactivity_sharded(
            self.mesh, int(bias), int(recovery_rate), bool(leaking)
        )
        args = _obs.h2d(
            "parallel.epoch.inactivity",
            self._pad(scores),
            self._pad(eligible, False),
            self._pad(participating, False),
        )
        out = kernel(*args)
        return _obs.d2h("parallel.epoch.inactivity", out)[:n]

    def fused(self, balances, eff, prev_part, slashed, active_prev,
              eligible, scores, increment: int, brpi: int,
              active_increments: int, denominator: int, bias: int,
              recovery_rate: int, weights: tuple, weight_denominator: int,
              leaking: bool, head_flag_index: int,
              target_flag_index: int) -> "tuple | None":
        """Inactivity + the full rewards stage as ONE sharded dispatch;
        returns ``(new_scores, new_balances)`` as numpy columns — or
        ``None`` when a u64 wrap surfaced (caller falls back to the
        staged host path and its literal overflow mirror)."""
        from . import runtime as _runtime

        n = balances.shape[0]
        _runtime.fault_point(
            "epoch", stage="fused", validators=n, devices=self.n_dev
        )
        kernel = _fused_sharded(
            self.mesh,
            int(bias),
            int(recovery_rate),
            tuple(int(w) for w in weights),
            int(weight_denominator),
            bool(leaking),
            int(head_flag_index),
            int(target_flag_index),
        )
        sharded = _obs.h2d(
            "parallel.epoch.fused",
            self._pad(balances),
            self._pad(eff),
            self._pad(prev_part),
            self._pad(slashed, False),
            self._pad(active_prev, False),
            self._pad(eligible, False),
            self._pad(scores),
        )
        scalars = (
            jnp.uint64(increment),
            jnp.uint64(brpi),
            jnp.uint64(active_increments),
            jnp.uint64(denominator),
        )
        new_scores, new_balances, wrapped = kernel(*sharded, *scalars)
        if int(wrapped):
            return None
        return (
            _obs.d2h("parallel.epoch.fused", new_scores)[:n],
            _obs.d2h("parallel.epoch.fused", new_balances)[:n],
        )

    def rewards(self, balances, eff, prev_part, slashed, active_prev,
                eligible, scores, increment: int, brpi: int,
                active_increments: int, denominator: int, weights: tuple,
                weight_denominator: int, leaking: bool,
                head_flag_index: int, target_flag_index: int):
        """The full rewards stage, sharded; returns the new balances
        column — or ``None`` when a u64 wrap surfaced (caller falls back
        to the host path and its literal overflow mirror)."""
        from . import runtime as _runtime

        n = balances.shape[0]
        _runtime.fault_point(
            "epoch", stage="rewards", validators=n, devices=self.n_dev
        )
        kernel = _rewards_sharded(
            self.mesh,
            tuple(int(w) for w in weights),
            int(weight_denominator),
            bool(leaking),
            int(head_flag_index),
            int(target_flag_index),
        )
        sharded = _obs.h2d(
            "parallel.epoch.rewards",
            self._pad(balances),
            self._pad(eff),
            self._pad(prev_part),
            self._pad(slashed, False),
            self._pad(active_prev, False),
            self._pad(eligible, False),
            self._pad(scores),
        )
        scalars = (
            jnp.uint64(increment),
            jnp.uint64(brpi),
            jnp.uint64(active_increments),
            jnp.uint64(denominator),
        )
        new_balances, wrapped, _sums = kernel(*sharded, *scalars)
        if int(wrapped):
            return None
        return _obs.d2h("parallel.epoch.rewards", new_balances)[:n]
