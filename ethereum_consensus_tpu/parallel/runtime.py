"""Mesh runtime: one process-wide provisioned mesh behind ``ECT_MESH``.

The virtual 8-device dryrun (``__graft_entry__.dryrun_multichip``)
proved every layer shards; this module is the PRODUCTION switch that
routes the hot paths through the 1-D ``shard`` mesh:

* the columnar epoch sweeps (models/epoch_vector.py → parallel/epoch.py
  ``MeshEpochSweeps``: row-sharded kernels + psum reductions),
* the RLC flush windows of the pipeline and the operation pool
  (crypto/bls.py → parallel/pairing.py ``batch_verify_sharded``),
* large ``hash_tree_root`` rebuilds (ssz/merkle.py's mesh hook →
  parallel/merkle.py ``sharded_merkleize_chunks``),
* batched multiproof extraction (proofs/multiproof.py's columnar
  dirty-group rebuild behind the ``proof_gather`` gate — the hash
  passes themselves ride the installed merkle hook).

``ECT_MESH=N`` provisions a mesh over the first N devices (N=1 is legal
— it exercises the sharded code paths on one device), ``ECT_MESH=auto``
takes every device when there are at least two, and unset/``off``
disables the runtime entirely — the host paths then never pay a jax
import, let alone a dispatch. On a CPU-only box the devices come from
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the
virtual_mesh.py seam): a multi-core box is a mesh, no chip required.

Observability contract (the PR 10 observatory, telemetry/device.py):
every routing decision is journal-visible — engages bump ``mesh.engage``
and journal ``mesh.{epoch,pairing,merkle,proofs}``/``device`` entries with the
device count and per-device work split; EVERY decline bumps
``mesh.decline.{reason}`` and fires a one-shot ``mesh.decline`` trace
event carrying the device-count/threshold inputs (the
epoch_vector.fallback idiom — no silent declines, ever). The host paths
stay live as fallback AND differential oracle: any device trouble
returns the work to the host without changing results.

Provisioning happens ONCE per process (double-checked lock); a declined
runtime stays declined (the reasons — bad env value, devices missing,
jax unusable — do not heal mid-process).
"""

from __future__ import annotations

import threading

from .. import _env
from ..telemetry import device as _device_obs
from ..telemetry import metrics as _metrics
from ..utils import trace

__all__ = [
    "MESH_ENV",
    "EPOCH_MIN_ENV",
    "MERKLE_MIN_ENV",
    "PROOF_MIN_ENV",
    "DEFAULT_EPOCH_MIN_N",
    "DEFAULT_MERKLE_MIN_CHUNKS",
    "DEFAULT_PROOF_MIN_CHUNKS",
    "MeshFaultInjected",
    "requested",
    "mesh",
    "device_count",
    "status",
    "epoch_sweeps",
    "pairing_mesh",
    "proof_gather",
    "install_fault_hook",
    "fault_point",
    "reset",
]

MESH_ENV = "ECT_MESH"
EPOCH_MIN_ENV = "ECT_MESH_EPOCH_MIN_N"
MERKLE_MIN_ENV = "ECT_MESH_MERKLE_MIN_CHUNKS"
PROOF_MIN_ENV = "ECT_MESH_PROOF_MIN_CHUNKS"

# crossover defaults, matching the ops.install sweep thresholds: below
# these sizes the dispatch + padding overhead loses to the host path
DEFAULT_EPOCH_MIN_N = 1 << 17
DEFAULT_MERKLE_MIN_CHUNKS = 1 << 15
# batched proof extraction: total chunks across the dirty-group rebuild
# jobs a multiproof plans; below this the per-group lazy Trees win
DEFAULT_PROOF_MIN_CHUNKS = 1 << 14

_LOCK = threading.Lock()
# provisioning outcome, written once under _LOCK then read lock-free:
# None = not yet attempted; (mesh_or_None, reason) afterwards
_PROVISIONED: "tuple | None" = None

# one-shot decline events re-arm on reason CHANGE: the event marks the
# newest distinct decline cause per route kind, so a long soak that
# flips thresholds mid-run (A -> B -> back to A) journals every
# transition instead of going silent after each reason's first firing
# (the counters still count every occurrence)
_DECLINE_LAST: dict = {}
_DECLINE_LOCK = threading.Lock()


class MeshFaultInjected(RuntimeError):
    """An injected mesh-route fault (pipeline/faults.FaultInjector's
    device lane): raised from inside a sharded path so the host fallback
    recovers exactly as it would from real device trouble. ``mesh_fault``
    marks it for the catch sites that must not double-journal (the
    fault point already declined as ``injected_fault``)."""

    mesh_fault = True


def requested() -> bool:
    """Is the mesh runtime switched on at all? A plain env read — the
    off path imports no jax and journals nothing (off is a
    configuration, not a decline)."""
    return _env.mesh_requested(MESH_ENV)


def _decline(kind: str, reason: str, **inputs) -> None:
    """Count + one-shot-event + journal one declined mesh route (the
    epoch_vector.fallback idiom — a decline is a routing decision worth
    seeing, so none are silent)."""
    _metrics.counter(f"mesh.decline.{reason}").inc()
    if _device_obs.OBSERVATORY.active:
        _device_obs.route(f"mesh.{kind}", "host", reason, **inputs)
    if _DECLINE_LAST.get(kind) != reason:
        with _DECLINE_LOCK:
            if _DECLINE_LAST.get(kind) != reason:
                _DECLINE_LAST[kind] = reason
                trace.event(
                    "mesh.decline", kind=kind, reason=reason, **inputs
                )


def decline(kind: str, reason: str, **inputs) -> None:
    """Public decline seam for the routed call sites (epoch_vector's
    mesh wrappers journal their stage-local declines through this)."""
    _decline(kind, reason, **inputs)


def _engage(kind: str, **inputs) -> None:
    _metrics.counter("mesh.engage").inc()
    if _device_obs.OBSERVATORY.active:
        _device_obs.route(f"mesh.{kind}", "device", "engaged", **inputs)


def _provision() -> "tuple":
    """Resolve ECT_MESH into a provisioned Mesh (or a decline reason).
    Runs at most once per process; the first caller pays the jax import
    and mesh construction, everyone else reads the cached outcome."""
    global _PROVISIONED
    if _PROVISIONED is not None:
        return _PROVISIONED
    with _LOCK:
        if _PROVISIONED is not None:
            return _PROVISIONED
        value = _env.mode(MESH_ENV)
        outcome = _provision_locked(value)
        if outcome[0] is not None:
            # the merkle hook rides provisioning: one install, and the
            # pure-host ssz layer stays jax-free until a mesh engages
            _install_merkle_hook(outcome[0])
        _PROVISIONED = outcome
    return _PROVISIONED


def _provision_locked(value: str) -> "tuple":
    try:
        import jax

        from .mesh import chip_mesh

        jax.config.update("jax_enable_x64", True)
        devices = jax.devices()
    except Exception as exc:  # noqa: BLE001 — no usable jax: host paths
        _decline("runtime", "no_jax", error=repr(exc)[:160])
        return None, "no_jax"
    if value == "auto":
        if len(devices) < 2:
            _decline("runtime", "single_device", devices=len(devices))
            return None, "single_device"
        n = len(devices)
    else:
        try:
            n = int(value)
        except ValueError:
            _decline("runtime", "bad_value", value=value)
            return None, "bad_value"
        if n < 1:
            _decline("runtime", "bad_value", value=value)
            return None, "bad_value"
        if n > len(devices):
            _decline(
                "runtime", "devices_unavailable",
                requested=n, devices=len(devices),
            )
            return None, "devices_unavailable"
    built = chip_mesh(n)
    _metrics.gauge("mesh.devices").set(n)
    trace.event("mesh.provisioned", devices=n, backend=jax.default_backend())
    if _device_obs.OBSERVATORY.active:
        _device_obs.route(
            "mesh.runtime", "device", "provisioned",
            devices=n, backend=jax.default_backend(),
        )
    return built, "engaged"


def mesh():
    """The provisioned mesh, or None (not requested / declined)."""
    if not requested():
        return None
    return _provision()[0]


def device_count() -> int:
    m = mesh()
    return int(m.devices.size) if m is not None else 0


def status() -> dict:
    """Runtime state for /device and the bench evidence blocks."""
    value = _env.raw(MESH_ENV).strip() or "off"
    if not requested():
        return {"requested": False, "env": value, "devices": 0}
    m, reason = _provision()
    return {
        "requested": True,
        "env": value,
        "devices": int(m.devices.size) if m is not None else 0,
        "reason": reason,
    }


def _threshold(env_key: str, default: int) -> int:
    raw = _env.raw(env_key).strip()
    if not raw:
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


# -- fault injection under the mesh route ------------------------------------

# one process-wide hook, written under _FAULT_LOCK and read lock-free on
# the routed paths (a plain attribute load; None = no injector armed).
# The hook is a callable (kind: str) -> bool: True consumes one planned
# fault for that route kind (pipeline/faults.FaultInjector.mesh_hook).
_FAULT_HOOK = None
_FAULT_LOCK = threading.Lock()


def install_fault_hook(hook) -> None:
    """Arm (or with ``None`` disarm) the mesh fault-injection seam. The
    sharded paths (parallel/pairing.py, parallel/epoch.py) call
    ``fault_point`` on entry; a consumed fault raises
    ``MeshFaultInjected`` there, and the host fallback that catches real
    device trouble recovers it the same way — degrade, blame, recover,
    all journaled (``mesh.decline.injected_fault``)."""
    global _FAULT_HOOK
    with _FAULT_LOCK:
        _FAULT_HOOK = hook


def fault_point(kind: str, **inputs) -> None:
    """The injection seam the sharded paths run on entry: when an
    installed hook consumes a planned fault for ``kind``, journal the
    decline (counter + re-armable event + routing-journal entry, the
    standard no-silent-declines treatment) and raise
    ``MeshFaultInjected`` — the caller's existing device-trouble
    fallback then recovers on the host path with identical results."""
    hook = _FAULT_HOOK
    if hook is None:
        return
    if not hook(kind):
        return
    _decline(kind, "injected_fault", **inputs)
    raise MeshFaultInjected(
        f"injected mesh fault on the {kind} route"
    )


# -- the three routed hot paths ----------------------------------------------


def epoch_sweeps(n_validators: int, family: str = "altair"):
    """A ``MeshEpochSweeps`` runner for an ``n_validators`` registry, or
    None with the decline journaled. Callers treat None as 'run the
    host kernels' — the live fallback. Only the altair-family sweeps
    (inactivity + flag rewards) have sharded twins; phase0's
    pending-attestation rewards decline explicitly."""
    if not requested():
        return None
    if family != "altair":
        _decline(
            "epoch", f"{family}_family", validators=n_validators
        )
        return None
    m, reason = _provision()
    if m is None:
        _decline("epoch", reason, validators=n_validators)
        return None
    threshold = _threshold(EPOCH_MIN_ENV, DEFAULT_EPOCH_MIN_N)
    if n_validators < threshold:
        _decline(
            "epoch", "below_threshold",
            validators=n_validators, threshold=threshold,
            devices=int(m.devices.size),
        )
        return None
    try:
        from .epoch import MeshEpochSweeps

        runner = MeshEpochSweeps(m)
    except Exception as exc:  # noqa: BLE001 — device trouble: host path
        _decline("epoch", "device_unusable", error=repr(exc)[:160])
        return None
    _engage(
        "epoch",
        validators=n_validators,
        devices=runner.n_dev,
        rows_per_device=-(-n_validators // runner.n_dev),
    )
    return runner


def pairing_mesh(n_sets: int):
    """The mesh for one RLC flush window's sharded pairing, or None
    (caller keeps the single-device/native route). The pairing-size
    threshold itself lives in ops (_device_flags.pairing_enabled) — by
    the time crypto/bls.py consults this, the batch is already routed
    device-ward; this only decides single-device vs mesh-sharded."""
    if not requested():
        return None
    m, reason = _provision()
    if m is None:
        _decline("pairing", reason, sets=n_sets)
        return None
    n_dev = int(m.devices.size)
    _engage(
        "pairing",
        sets=n_sets,
        devices=n_dev,
        sets_per_device=-(-n_sets // n_dev),
    )
    return m


def proof_gather(n_chunks: int):
    """The mesh for one multiproof's columnar group rebuild, or None
    (caller builds lazy per-group Trees on the host). ``n_chunks`` is
    the TOTAL chunk count across the planned group jobs — the columnar
    build concatenates them into one buffer whose ``hash_level`` passes
    ride the installed device hasher, so the threshold gates on the
    aggregate, not per group."""
    if not requested():
        return None
    m, reason = _provision()
    if m is None:
        _decline("proofs", reason, chunks=n_chunks)
        return None
    threshold = _threshold(PROOF_MIN_ENV, DEFAULT_PROOF_MIN_CHUNKS)
    if n_chunks < threshold:
        _decline(
            "proofs", "below_threshold",
            chunks=n_chunks, threshold=threshold,
            devices=int(m.devices.size),
        )
        return None
    _engage(
        "proofs",
        chunks=n_chunks,
        devices=int(m.devices.size),
    )
    return m


def _install_merkle_hook(m) -> None:
    """Point ssz/merkle.py's mesh seam at the sharded merkleizer: large
    flat rebuilds (cold column materializations, whole-list roots)
    divide their leaf ranges over the mesh. The hook returns None on any
    trouble — the host merkleizer is always live underneath."""
    from ..ssz import merkle as ssz_merkle

    min_chunks = _threshold(MERKLE_MIN_ENV, DEFAULT_MERKLE_MIN_CHUNKS)
    n_dev = int(m.devices.size)

    def mesh_merkleize(chunks: bytes, limit: "int | None") -> "bytes | None":
        # shape pre-check BEFORE dispatch: sharded_merkleize_chunks falls
        # back to the host merkleizer for meshes that cannot own an
        # aligned subtree per device — returning None here instead keeps
        # the hook non-reentrant (the host path would re-enter the hook)
        count = len(chunks) // 32
        width = ssz_merkle.next_pow_of_two(
            count if limit is None else limit
        )
        if n_dev & (n_dev - 1) or n_dev > width:
            _decline(
                "merkle", "mesh_shape",
                chunks=count, devices=n_dev, width=width,
            )
            return None
        try:
            from .merkle import sharded_merkleize_chunks

            root = sharded_merkleize_chunks(chunks, m, limit=limit)
        except Exception as exc:  # noqa: BLE001 — host path must win
            _decline("merkle", "device_unusable", error=repr(exc)[:160])
            return None
        _engage("merkle", chunks=count, devices=n_dev)
        return root

    ssz_merkle.register_mesh_merkleizer(mesh_merkleize, min_chunks)


def reset() -> None:
    """Drop the provisioned mesh + hooks (tests only: lets one process
    exercise several ECT_MESH configurations)."""
    global _PROVISIONED
    with _LOCK:
        _PROVISIONED = None
        with _DECLINE_LOCK:
            _DECLINE_LAST.clear()
        install_fault_hook(None)
        from ..ssz import merkle as ssz_merkle

        ssz_merkle.register_mesh_merkleizer(None, None)
