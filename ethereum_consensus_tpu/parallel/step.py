"""The distributed chain step: one epoch-boundary device sweep, sharded.

This is the multi-chip "training step" of the framework: the validator
registry (the only axis at mainnet scale — VALIDATOR_REGISTRY_LIMIT = 2^40,
phase0/presets/mainnet.rs:26) is sharded row-wise over the mesh, and one
jitted step performs, entirely on device:

  1. the effective-balance hysteresis sweep
     (reference: phase0/epoch_processing.rs process_effective_balance_updates)
  2. the total-active-balance reduction (``psum`` across chips)
  3. the SSZ ``hash_tree_root`` of the balances list — per-device subtree
     reduction, one ``all_gather`` of subtree roots over ICI, replicated top
     tree + length mix-in — bit-identical to the host merkleizer.

Exact u64 spec semantics require ``jax_enable_x64`` (SURVEY.md §7 hard
parts); callers enable it before building the step (see __graft_entry__ and
tests). Sweep math is exact integer arithmetic — no floats anywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..ops.merkle import reduce_levels
from ..ops.sha256 import sha256_64b
from ..ssz.merkle import next_pow_of_two
from .mesh import SHARD_AXIS

__all__ = ["make_chain_step", "u64_to_be_words"]


def _bswap32(x):
    x = x.astype(jnp.uint32)
    return (
        (x >> np.uint32(24))
        | ((x >> np.uint32(8)) & np.uint32(0xFF00))
        | ((x << np.uint32(8)) & np.uint32(0xFF0000))
        | (x << np.uint32(24))
    )


def u64_to_be_words(values):
    """(N,) uint64 → (2N,) uint32: the big-endian-word view of the
    little-endian u64 byte serialization (SSZ basic-value packing)."""
    lo = (values & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (values >> jnp.uint64(32)).astype(jnp.uint32)
    return jnp.stack([_bswap32(lo), _bswap32(hi)], axis=1).reshape(-1)


def _length_words(length: int) -> np.ndarray:
    """(8,) uint32 word view of the SSZ length mix-in chunk."""
    chunk = length.to_bytes(8, "little") + b"\x00" * 24
    return np.frombuffer(chunk, dtype=">u4").astype(np.uint32)


def make_chain_step(
    mesh: Mesh,
    axis_name: str = SHARD_AXIS,
    registry_limit: int = 2**40,
    effective_balance_increment: int = 10**9,
    max_effective_balance: int = 32 * 10**9,
    hysteresis_quotient: int = 4,
    hysteresis_downward_multiplier: int = 1,
    hysteresis_upward_multiplier: int = 5,
):
    """Build the jitted distributed chain step over ``mesh``.

    Returns ``step(balances, effective_balances, active_mask, zero_words)``
    where the first three are (N,) arrays sharded over ``axis_name`` (N
    divisible by mesh size; N/devices divisible by 4 — one SSZ chunk packs
    four u64 balances) and ``zero_words`` is ops.merkle.zero_hash_words().
    Returns ``(new_effective_balances, total_active_balance, balances_root)``
    with the root as (8,) uint32 words, replicated.
    """
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "make_chain_step needs exact u64 semantics: enable jax_enable_x64"
        )
    n_dev = mesh.shape[axis_name]
    chunk_limit = (registry_limit + 3) // 4
    depth = (next_pow_of_two(chunk_limit) - 1).bit_length()

    increment = np.uint64(effective_balance_increment)
    hysteresis_increment = np.uint64(effective_balance_increment // hysteresis_quotient)
    downward = hysteresis_increment * np.uint64(hysteresis_downward_multiplier)
    upward = hysteresis_increment * np.uint64(hysteresis_upward_multiplier)
    max_eff = np.uint64(max_effective_balance)

    def body(balances, eff, active, zero_words):
        local_n = balances.shape[0]
        if local_n % 4:
            raise ValueError("per-device balance count must be a multiple of 4")
        # each device must own a full, aligned 2^k-chunk subtree; otherwise
        # the zero-padded local reduction computes a root over misplaced
        # leaves (chunk owned by the next device replaced by a zero chunk)
        local_chunks = local_n // 4
        if local_chunks == 0 or local_chunks & (local_chunks - 1):
            raise ValueError(
                f"per-device chunk count {local_chunks} must be a power of two"
            )

        # 1. hysteresis sweep (epoch_processing.rs process_effective_balance_updates)
        candidate = jnp.minimum(balances - balances % increment, max_eff)
        new_eff = jnp.where(
            (balances + downward < eff) | (eff + upward < balances), candidate, eff
        )

        # 2. total active balance across the whole mesh
        total = jax.lax.psum(
            jnp.sum(jnp.where(active, new_eff, jnp.uint64(0))), axis_name
        )

        # 3. hash_tree_root(balances): local subtree → all_gather → top tree
        words = u64_to_be_words(balances).reshape(local_n // 4, 8).T
        local_depth = (local_n // 4 - 1).bit_length()
        sub = reduce_levels(words, zero_words, local_depth)
        roots = jax.lax.all_gather(sub, axis_name)  # (n_dev, 8)
        merkle = reduce_levels(roots.T, zero_words, depth, start_level=local_depth)
        # SSZ List → mix_in_length(root, N)
        length = jnp.asarray(_length_words(local_n * n_dev))
        msg = jnp.concatenate([merkle, length]).reshape(16, 1)
        root = sha256_64b(msg)[:, 0]
        return new_eff, total, root

    # check_vma=False: the SHA-256 fori_loop carries a mix of unvarying
    # (padding-block literals) and device-varying lanes, which the vma type
    # system rejects; replication of the psum/top-tree outputs is guaranteed
    # by construction here.
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), P(axis_name), P(axis_name), P(None, None)),
            out_specs=(P(axis_name), P(), P(None)),
            check_vma=False,
        )
    )
