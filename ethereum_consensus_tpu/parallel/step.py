"""The distributed chain step: one epoch-boundary device sweep, sharded.

This is the multi-chip "training step" of the framework: the validator
registry (the only axis at mainnet scale — VALIDATOR_REGISTRY_LIMIT = 2^40,
phase0/presets/mainnet.rs:26) is sharded row-wise over the mesh, and one
jitted step performs, entirely on device:

  1. the effective-balance hysteresis sweep
     (reference: phase0/epoch_processing.rs process_effective_balance_updates)
  2. the total-active-balance reduction (``psum`` across chips)
  3. the SSZ ``hash_tree_root`` of the balances list — per-device subtree
     reduction, one ``all_gather`` of subtree roots over ICI, replicated top
     tree + length mix-in — bit-identical to the host merkleizer.

Exact u64 spec semantics require ``jax_enable_x64`` (SURVEY.md §7 hard
parts); callers enable it before building the step (see __graft_entry__ and
tests). Sweep math is exact integer arithmetic — no floats anywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..ops.merkle import reduce_levels
from ..ops.sha256 import sha256_64b
from ..ssz.merkle import next_pow_of_two
from ..telemetry import device as _obs
from ._compat import shard_map
from .mesh import SHARD_AXIS

__all__ = [
    "make_chain_step",
    "make_epoch_sweep_step",
    "pad_registry_for_mesh",
    "run_chain_step",
    "u64_to_be_words",
]


def _bswap32(x):
    x = x.astype(jnp.uint32)
    return (
        (x >> np.uint32(24))
        | ((x >> np.uint32(8)) & np.uint32(0xFF00))
        | ((x << np.uint32(8)) & np.uint32(0xFF0000))
        | (x << np.uint32(24))
    )


def u64_to_be_words(values):
    """(N,) uint64 → (2N,) uint32: the big-endian-word view of the
    little-endian u64 byte serialization (SSZ basic-value packing)."""
    lo = (values & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (values >> jnp.uint64(32)).astype(jnp.uint32)
    return jnp.stack([_bswap32(lo), _bswap32(hi)], axis=1).reshape(-1)


def _length_words(length: int) -> np.ndarray:
    """(8,) uint32 word view of the SSZ length mix-in chunk."""
    chunk = length.to_bytes(8, "little") + b"\x00" * 24
    return np.frombuffer(chunk, dtype=">u4").astype(np.uint32)


# lru_cache IS the staging discipline here (speclint device/jit-outside-
# staging): every distinct (mesh, constants) tuple compiles exactly once
# per process, so a driver looping over epochs re-enters the SAME jitted
# step instead of re-tracing a fresh one each call.
@functools.lru_cache(maxsize=8)
def make_chain_step(
    mesh: Mesh,
    axis_name: str = SHARD_AXIS,
    registry_limit: int = 2**40,
    effective_balance_increment: int = 10**9,
    max_effective_balance: int = 32 * 10**9,
    hysteresis_quotient: int = 4,
    hysteresis_downward_multiplier: int = 1,
    hysteresis_upward_multiplier: int = 5,
):
    """Build the jitted distributed chain step over ``mesh``.

    Returns ``step(balances, effective_balances, active_mask, zero_words,
    length_words)`` where the first three are (N,) arrays sharded over
    ``axis_name`` (N divisible by mesh size; N/devices a power-of-two
    multiple of 4 — one SSZ chunk packs four u64 balances; use
    ``run_chain_step`` for arbitrary sizes, which zero-pads and passes the
    TRUE length's mix-in words), ``zero_words`` is
    ops.merkle.zero_hash_words() and ``length_words`` is the (8,) uint32
    word view of the SSZ length mix-in chunk.
    Returns ``(new_effective_balances, total_active_balance, balances_root)``
    with the root as (8,) uint32 words, replicated.
    """
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "make_chain_step needs exact u64 semantics: enable jax_enable_x64"
        )
    n_dev = mesh.shape[axis_name]
    chunk_limit = (registry_limit + 3) // 4
    depth = (next_pow_of_two(chunk_limit) - 1).bit_length()

    increment = np.uint64(effective_balance_increment)
    hysteresis_increment = np.uint64(effective_balance_increment // hysteresis_quotient)
    downward = hysteresis_increment * np.uint64(hysteresis_downward_multiplier)
    upward = hysteresis_increment * np.uint64(hysteresis_upward_multiplier)
    max_eff = np.uint64(max_effective_balance)

    def body(balances, eff, active, zero_words, length_words):
        local_n = balances.shape[0]
        if local_n % 4:
            raise ValueError("per-device balance count must be a multiple of 4")
        # each device must own a full, aligned 2^k-chunk subtree; otherwise
        # the zero-padded local reduction computes a root over misplaced
        # leaves (chunk owned by the next device replaced by a zero chunk)
        local_chunks = local_n // 4
        if local_chunks == 0 or local_chunks & (local_chunks - 1):
            raise ValueError(
                f"per-device chunk count {local_chunks} must be a power of two"
            )

        # 1. hysteresis sweep (epoch_processing.rs process_effective_balance_updates)
        candidate = jnp.minimum(balances - balances % increment, max_eff)
        new_eff = jnp.where(
            (balances + downward < eff) | (eff + upward < balances), candidate, eff
        )

        # 2. total active balance across the whole mesh
        total = jax.lax.psum(
            jnp.sum(jnp.where(active, new_eff, jnp.uint64(0))), axis_name
        )

        # 3. hash_tree_root(balances): local subtree → all_gather → top tree
        words = u64_to_be_words(balances).reshape(local_n // 4, 8).T
        local_depth = (local_n // 4 - 1).bit_length()
        sub = reduce_levels(words, zero_words, local_depth)
        roots = jax.lax.all_gather(sub, axis_name)  # (n_dev, 8)
        merkle = reduce_levels(roots.T, zero_words, depth, start_level=local_depth)
        # SSZ List → mix_in_length(root, true length)
        msg = jnp.concatenate([merkle, length_words]).reshape(16, 1)
        root = sha256_64b(msg)[:, 0]
        return new_eff, total, root

    # check_vma=False: the SHA-256 fori_loop carries a mix of unvarying
    # (padding-block literals) and device-varying lanes, which the vma type
    # system rejects; replication of the psum/top-tree outputs is guaranteed
    # by construction here.
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(axis_name), P(axis_name), P(axis_name), P(None, None), P(None),
            ),
            out_specs=(P(axis_name), P(), P(None)),
            check_vma=False,
        )
    )


def pad_registry_for_mesh(n: int, n_dev: int) -> int:
    """Padded registry length for an arbitrary ``n`` on an ``n_dev`` mesh:
    each device owns an aligned power-of-two subtree of whole SSZ chunks
    (4 u64 per chunk). Zero-padding is exactly the merkleizer's own
    padding, so roots are unchanged as long as the TRUE length feeds the
    SSZ length mix-in."""
    per_dev_chunks = next_pow_of_two(max(1, -(-n // (4 * n_dev))))
    return n_dev * per_dev_chunks * 4


def run_chain_step(step, mesh, balances, effective, active, zero_words,
                   axis_name: str = SHARD_AXIS):
    """Host wrapper around ``make_chain_step``'s jitted step for ARBITRARY
    (non-aligned) registry sizes: zero-pads the inputs to the mesh-aligned
    width (inactive padding cannot contribute to the psum total, and zero
    chunks are the merkleizer's own padding), runs the step with the true
    length in the mix-in, and slices the padded tail back off."""
    n = len(balances)
    n_dev = mesh.shape[axis_name]
    padded = pad_registry_for_mesh(n, n_dev)
    bal = np.zeros(padded, np.uint64)
    bal[:n] = balances
    eff = np.zeros(padded, np.uint64)
    eff[:n] = effective
    act = np.zeros(padded, np.bool_)
    act[:n] = active
    bal_d, eff_d, act_d, len_d = _obs.h2d(
        "parallel.step.registry", bal, eff, act, _length_words(n)
    )
    new_eff, total, root_words = step(bal_d, eff_d, act_d, zero_words, len_d)
    return (
        _obs.d2h("parallel.step.new_effective", new_eff)[:n],
        int(total),
        _obs.d2h("parallel.step.balances_root", root_words),
    )


def make_epoch_sweep_step(
    mesh: Mesh,
    context,
    axis_name: str = SHARD_AXIS,
    is_leaking: bool = False,
    check_score_bound: bool = True,
):
    """The distributed altair epoch sweep (the real per-epoch hot loop):
    inactivity-score updates, the three participation-flag delta sweeps,
    inactivity penalties, and balance application — sharded row-wise over
    the mesh with ``psum`` totals, matching altair
    process_inactivity_updates + process_rewards_and_penalties
    (epoch_processing.rs:104,160) bit-for-bit including saturating
    decreases and application order.

    Returns ``step(balances, effective, participation, slashed,
    active_previous, active_current, eligible, scores)`` over sharded (N,)
    arrays → ``(new_balances, new_scores, total_active_balance)``.
    ``participation`` is the uint8 flag byte for the delta epoch
    (previous, or current in the genesis corner — the caller picks when
    packing, see ops.sweeps.pack_registry).

    Precondition for the bit-identical guarantee: every
    ``effective_balance * inactivity_score`` product must fit in uint64,
    i.e. max score < 2^64 / max_effective_balance (~5.8e8 at 32 ETH,
    ~9e6 at electra's 2048 ETH cap — both need a years-long leak).
    Inside jit the sweep cannot branch on data, so by default the
    returned step wraps the jitted kernel with a host-side check of
    ``max(effective) * max(scores)`` (one small device reduction + sync
    per call) and raises ``OverflowError`` when the bound is exceeded —
    that epoch must then run through the host spec path (the
    single-device twin, ops.sweeps.inactivity_penalties_device, reroutes
    itself). Pass ``check_score_bound=False`` to get the raw jitted step
    for composition inside a larger jit.

    The context object is unhashable, so this wrapper extracts the five
    scalars the sweep actually closes over and defers to the lru-cached
    factory — two epochs under the same constants share ONE compiled
    step (speclint device/jit-outside-staging)."""
    return _epoch_sweep_step(
        mesh,
        int(context.EFFECTIVE_BALANCE_INCREMENT),
        int(context.BASE_REWARD_FACTOR),
        int(context.inactivity_score_bias),
        int(context.inactivity_score_recovery_rate),
        int(context.INACTIVITY_PENALTY_QUOTIENT_ALTAIR),
        axis_name,
        is_leaking,
        check_score_bound,
    )


@functools.lru_cache(maxsize=16)
def _epoch_sweep_step(
    mesh: Mesh,
    effective_balance_increment: int,
    base_reward_factor_int: int,
    inactivity_score_bias: int,
    inactivity_score_recovery_rate: int,
    inactivity_penalty_quotient: int,
    axis_name: str,
    is_leaking: bool,
    check_score_bound: bool,
):
    from ..models.altair.constants import (
        PARTICIPATION_FLAG_WEIGHTS,
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
        WEIGHT_DENOMINATOR,
    )

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "make_epoch_sweep_step needs exact u64 semantics: enable jax_enable_x64"
        )

    increment = np.uint64(effective_balance_increment)
    base_reward_factor = np.uint64(base_reward_factor_int)
    score_bias = np.uint64(inactivity_score_bias)
    recovery_rate = np.uint64(inactivity_score_recovery_rate)
    inactivity_quotient = np.uint64(inactivity_penalty_quotient)

    def _isqrt(x):
        guess = jnp.sqrt(x.astype(jnp.float64)).astype(jnp.uint64) + jnp.uint64(1)

        def newton(_, g):
            g = jnp.maximum(g, jnp.uint64(1))
            return (g + x // g) >> jnp.uint64(1)

        g = jax.lax.fori_loop(0, 6, newton, guess)
        g = jnp.where(g * g > x, g - jnp.uint64(1), g)
        return jnp.where((g + 1) * (g + 1) <= x, g + jnp.uint64(1), g)

    def body(balances, eff, participation, slashed, active_prev, active_cur,
             eligible, scores):
        # --- process_inactivity_updates (epoch_processing.rs:104) ---
        target_participating = (
            ((participation >> np.uint8(TIMELY_TARGET_FLAG_INDEX)) & 1).astype(bool)
            & ~slashed
            & active_prev
        )
        decreased = scores - jnp.minimum(jnp.uint64(1), scores)
        increased = scores + score_bias
        new_scores = jnp.where(
            eligible,
            jnp.where(target_participating, decreased, increased),
            scores,
        )
        if not is_leaking:
            new_scores = jnp.where(
                eligible,
                new_scores - jnp.minimum(recovery_rate, new_scores),
                new_scores,
            )

        # --- totals (psum over the mesh — the ICI collectives) ---
        total_active = jax.lax.psum(
            jnp.sum(jnp.where(active_cur, eff, jnp.uint64(0))), axis_name
        )
        total_active = jnp.maximum(total_active, increment)
        base_reward_per_increment = increment * base_reward_factor // _isqrt(
            total_active
        )
        base_reward = (eff // increment) * base_reward_per_increment
        active_increments = total_active // increment

        # --- the three flag-delta sweeps (helpers.rs:265) ---
        new_balances = balances
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            w = jnp.uint64(weight)
            participating = (
                ((participation >> np.uint8(flag_index)) & 1).astype(bool)
                & ~slashed
                & active_prev
            )
            unslashed_increments = (
                jax.lax.psum(
                    jnp.sum(jnp.where(participating, eff, jnp.uint64(0))),
                    axis_name,
                )
                // increment
            )
            rewards = jnp.where(
                participating & eligible & jnp.bool_(not is_leaking),
                base_reward
                * w
                * unslashed_increments
                // (active_increments * jnp.uint64(WEIGHT_DENOMINATOR)),
                jnp.uint64(0),
            )
            if flag_index == TIMELY_HEAD_FLAG_INDEX:
                penalties = jnp.zeros_like(rewards)
            else:
                penalties = jnp.where(
                    eligible & ~participating,
                    base_reward * w // jnp.uint64(WEIGHT_DENOMINATOR),
                    jnp.uint64(0),
                )
            # spec application order: increase then saturating decrease
            new_balances = new_balances + rewards
            new_balances = new_balances - jnp.minimum(penalties, new_balances)

        # --- inactivity penalties (uses the UPDATED scores) ---
        not_target = eligible & ~target_participating
        inactivity_penalties = jnp.where(
            not_target,
            eff * new_scores // (score_bias * inactivity_quotient),
            jnp.uint64(0),
        )
        new_balances = new_balances - jnp.minimum(inactivity_penalties, new_balances)

        return new_balances, new_scores, total_active

    spec = P(axis_name)
    jitted = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(spec,) * 8,
            out_specs=(spec, spec, P()),
            check_vma=False,
        )
    )
    if not check_score_bound:
        return jitted

    def checked_step(balances, eff, participation, slashed, active_prev,
                     active_cur, eligible, scores):
        max_product = int(jnp.max(eff)) * int(jnp.max(scores))
        if max_product >= 1 << 64:
            raise OverflowError(
                "inactivity score × effective balance exceeds uint64: the "
                "device epoch sweep would wrap; route this epoch through "
                "the host spec path (see make_epoch_sweep_step docstring)"
            )
        return jitted(balances, eff, participation, slashed, active_prev,
                      active_cur, eligible, scores)

    return checked_step
