"""Device-mesh construction helpers.

One logical axis ``shard`` carries every batch axis in this framework (merkle
leaf ranges, signature batches, validator-registry rows) — the domain has no
tensor/pipeline dimension to split, so a 1-D mesh maps the whole ICI
bandwidth onto the one axis that matters. Multi-host meshes come for free:
``jax.devices()`` spans hosts under ``jax.distributed``, and the collectives
(`all_gather`/`psum`) ride ICI within a host and DCN across.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"

__all__ = ["SHARD_AXIS", "chip_mesh", "default_device_mesh"]


def chip_mesh(n_devices: int | None = None, axis_name: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (all by default)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def default_device_mesh() -> Mesh:
    return chip_mesh()
