"""Mesh-sharded RLC batch signature verification.

The signature-set axis IS the mesh axis (SURVEY.md §2.5, batch axes as
mesh axes): each device runs the blinder multiplications, Miller loops,
and local Fq12-product/G2-sum reductions for its slice of the batch
under one ``shard_map``; only the tiny per-device partials (one Fq12
value and one Jacobian G2 point per device) cross the mesh, and the O(1)
final exponentiation stays on the host native backend — the same
decomposition as the single-device route (ops/pairing.py), with the
chunk axis promoted to devices.

Padding discipline mirrors ops/pairing.batch_verify_device: lanes pad to
``n_dev × 2^k`` with generator points; padded pk/H lanes are masked out
of the local Fq12 product by a validity column (slicing cannot cross
shard boundaries), and padded signature lanes carry blinder 0, whose
scalar multiple is the identity the branchless sum skips.

Reference role: blst's pairing engine under crypto/bls.rs (C6). The
reference itself has NO distributed backend (SURVEY.md §2.5 — it is a
single-process library); this mesh decomposition is the green-field
TPU-native scale-out of its batch-verification semantics, not a port
of any reference communication layer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops import fq12, pairing as dp
from ..telemetry import device as _obs
from ._compat import shard_map
from .mesh import SHARD_AXIS, default_device_mesh

__all__ = ["batch_verify_sharded", "miller_partials_sharded"]


@functools.lru_cache(maxsize=8)
def _sharded_parts(mesh):
    """Jitted shard_map over the set axis: per-device blinder mults +
    Miller loops + local reductions → (n_dev, 2, 3, 2, 24) Fq12 partial
    products and (n_dev, 3, 2, 24) Jacobian G2 partial signature sums."""

    def body(pk_jac, pk_bits, xq, yq, sig_jac, sig_bits, valid):
        k = pk_jac.shape[0]  # lanes per device (power of two)
        pk_blinded = dp._mul_scan_g1(pk_jac, pk_bits)
        xp, yp = dp._g1_jacobian_to_affine(pk_blinded)
        fs = dp.miller_loop_batched(xp, yp, xq, yq)
        one = fq12.fp12_one((k,)).arr
        fs = jnp.where(valid[:, None, None, None, None], fs, one)
        local_f = dp.fp12_product(fs)
        sig_mul = dp._mul_scan_g2(sig_jac, sig_bits)
        local_sig = dp._g2_tree_reduce(sig_mul, (k - 1).bit_length())
        return local_f[None], local_sig[None]

    # check_vma=False: the Miller scan mixes device-varying lanes with
    # unvarying constants (same situation as parallel/step.py's SHA loop)
    return _obs.observe_jit(
        jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(P(SHARD_AXIS),) * 7,
                out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                check_vma=False,
            )
        ),
        "parallel.pairing._sharded_parts",
    )


def _pad_width(n: int, n_dev: int) -> int:
    """Lanes per device: the next power of two covering ceil(n/n_dev)."""
    per = -(-n // n_dev)
    return 1 << (per - 1).bit_length() if per > 1 else 1


def miller_partials_sharded(mesh, pk_raws, h_raws, sig_raws, scalars):
    """Shard the batch over ``mesh`` and return host-side partials:
    ``(f_total, s_raw, s_inf)`` ready for ``ops.pairing.finalize_verdict``.

    Inputs are the same raw affine byte strings + blinder ints as
    ``batch_verify_device`` (non-identity pk aggregates, hash points,
    signatures, nonzero 128-bit blinders).
    """
    n = len(pk_raws)
    n_dev = mesh.devices.size
    assert n and len(h_raws) == n and len(sig_raws) == n and len(scalars) == n
    # fault-injection seam (runtime.install_fault_hook): an injected
    # fault surfaces here exactly where real device trouble would — the
    # caller's device-unusable fallback recovers on the host engine with
    # identical verdicts, the decline journaled as injected_fault
    from . import runtime as _runtime

    _runtime.fault_point("pairing", sets=n, devices=int(n_dev))

    k = _pad_width(n, n_dev)
    width = n_dev * k
    g1f, g2f = dp._generator_raws()
    pk_padded = list(pk_raws) + [g1f] * (width - n)
    h_padded = list(h_raws) + [g2f] * (width - n)
    sig_padded = list(sig_raws) + [g2f] * (width - n)
    pk_scalars = list(scalars) + [1] * (width - n)
    sig_scalars = list(scalars) + [0] * (width - n)
    valid = np.zeros(width, np.bool_)
    valid[:n] = True

    pk_jac = dp._g1_jac_from_affine_raws(pk_padded).arr
    xq, yq = dp.g2_affine_from_raw(h_padded)
    sx, sy = dp.g2_affine_from_raw(sig_padded)
    one2 = jnp.broadcast_to(
        _obs.h2d(
            "parallel.pairing.const_one2",
            np.stack([
                np.asarray(dp.fql.to_mont_cols(1)), np.zeros(24, np.uint64),
            ]),
        ),
        sy.arr.shape,
    )
    sig_jac = jnp.stack([sx.arr, sy.arr, one2], axis=-3)
    pk_bits, sig_bits = _obs.h2d(
        "parallel.pairing.scalar_bits",
        dp._scalars_to_bits(pk_scalars, 128),
        dp._scalars_to_bits(sig_scalars, 128),
    )

    shard = NamedSharding(mesh, P(SHARD_AXIS))
    # ``valid`` rides as the host np array — the seam's device_put IS
    # its one transfer
    staged = (pk_jac, pk_bits, xq.arr, yq.arr, sig_jac, sig_bits, valid)
    args = _obs.h2d_put("parallel.pairing.shard_put", staged, shard)
    partial_fs, partial_sigs = _sharded_parts(mesh)(*args)

    # per-shard partials come back as device arrays already — reduce in
    # place, no re-wrap
    f_total = dp.fp12_product(partial_fs)
    sig_sum = dp.g2_sum_points(dp._env(partial_sigs))
    s_raw, s_inf = dp._g2_point_to_raw(sig_sum)
    return f_total, s_raw, s_inf


def batch_verify_sharded(
    pk_raws, h_raws, sig_raws, scalars, mesh=None
) -> bool:
    """The RLC batch verdict with the set axis sharded over a device mesh
    — semantics identical to ``ops.pairing.batch_verify_device``."""
    mesh = mesh if mesh is not None else default_device_mesh()
    f_total, s_raw, s_inf = miller_partials_sharded(
        mesh, pk_raws, h_raws, sig_raws, scalars
    )
    return dp.finalize_verdict(f_total, s_raw, s_inf)
