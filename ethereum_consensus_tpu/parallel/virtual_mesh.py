"""Virtual multi-device CPU platform provisioning.

One real TPU chip is the common case under the axon tunnel; multi-device
sharding is still testable by re-running in a subprocess whose JAX sees a
virtual ``n``-device CPU platform. The platform plugin registers at
interpreter startup, so this MUST happen via environment of a fresh
process — never in-process. This module holds the one canonical recipe
(used by tests/conftest.py and __graft_entry__.dryrun_multichip).

Deliberately imports neither jax nor the rest of the package.
"""

from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["cpu_mesh_env", "run_in_cpu_mesh", "REEXEC_SENTINEL"]

# Set (to the provisioned device count) in a child spawned for a specific
# request; a child provisioned for n devices that still can't see them must
# fail loudly instead of re-execing forever.
REEXEC_SENTINEL = "EC_VIRTUAL_MESH_CHILD"


def _default_repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def cpu_mesh_env(n_devices: int = 8, repo_root: str | None = None) -> dict:
    """Environment for a subprocess with an n-device virtual CPU platform.

    Preserves any pre-existing XLA_FLAGS (appends the device-count flag);
    pins PYTHONPATH to the repo root to drop sitecustomize plugin injection.
    """
    if repo_root is None:
        repo_root = _default_repo_root()
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env[REEXEC_SENTINEL] = str(n_devices)
    return env


def run_in_cpu_mesh(
    code: str,
    n_devices: int = 8,
    timeout: int = 600,
    repo_root: str | None = None,
    stream: bool = False,
) -> str:
    """Run ``code`` in a subprocess on the virtual CPU mesh; returns stdout.

    With ``stream=True`` the child inherits this process's stdout so
    per-stage progress reaches the caller's output LIVE (a kill at any
    outer timeout still leaves the stages that ran on record); the
    return value is then "". Raises RuntimeError (with captured streams
    and the timeout) on nonzero exit or timeout.
    """
    if repo_root is None:
        repo_root = _default_repo_root()
    env = cpu_mesh_env(n_devices, repo_root=repo_root)
    if stream:
        sys.stdout.flush()
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            env=env,
            stderr=subprocess.PIPE,
            text=True,
            cwd=repo_root,
        )
        try:
            _, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            raise RuntimeError(
                f"cpu-mesh subprocess exceeded {timeout}s (stages that "
                "completed are on stdout above)"
            )
        if proc.returncode != 0:
            tail = "\n".join((err or "").splitlines()[-25:])
            raise RuntimeError(
                f"cpu-mesh subprocess failed (rc={proc.returncode}):\n"
                f"stderr tail:\n{tail}"
            )
        return ""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=repo_root,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"cpu-mesh subprocess failed (rc={proc.returncode}):\n"
            f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
        )
    return proc.stdout
