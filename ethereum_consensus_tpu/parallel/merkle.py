"""Sharded SSZ merkleization over a device mesh.

The merkle tree over N leaf chunks is split by leaf range: each device
reduces its contiguous 2^k-leaf subtree locally (pure VPU work, zero
communication), then one ``all_gather`` of the 32-byte subtree roots crosses
ICI and every device finishes the top log2(D) levels redundantly (cheaper
than a log-depth halving exchange for D ≤ 256: the top tree is D hashes).

This is the ring/all-reduce-shaped pattern SURVEY.md §5 calls for ("blockwise
kernels over leaf chunks with tree reduction across chips"), replacing the
reference's single-core `ssz_rs` merkleizer. Bit-identical to
ssz/merkle.py's host merkleizer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..ops.merkle import reduce_levels, zero_hash_words
from ..ssz.merkle import BYTES_PER_CHUNK, merkleize_chunks, next_pow_of_two, zero_hash
from ..telemetry import device as _obs
from ._compat import shard_map
from .mesh import SHARD_AXIS

__all__ = ["sharded_merkle_root_words", "sharded_merkleize_chunks"]


@functools.partial(
    jax.jit, static_argnames=("depth", "mesh", "axis_name"), static_argnums=()
)
def sharded_merkle_root_words(
    nodes: jax.Array,
    zero_words: jax.Array,
    depth: int,
    mesh: Mesh,
    axis_name: str = SHARD_AXIS,
) -> jax.Array:
    """Root of a depth-``depth`` tree over ``nodes`` (8, N), N sharded.

    N must be a power of two divisible by the mesh axis size. Returns (8,)
    root words, replicated.
    """
    n = nodes.shape[1]
    n_dev = mesh.shape[axis_name]
    if n % n_dev != 0:
        raise ValueError(f"leaf count {n} not divisible by mesh size {n_dev}")
    local_n = n // n_dev
    if local_n == 0 or local_n & (local_n - 1):
        raise ValueError(f"local leaf count {local_n} must be a power of two")
    local_depth = (local_n - 1).bit_length()

    def body(local_nodes, zw):
        sub = reduce_levels(local_nodes, zw, local_depth)  # (8,)
        roots = jax.lax.all_gather(sub, axis_name)  # (n_dev, 8)
        return reduce_levels(roots.T, zw, depth, start_level=local_depth)

    # check_vma=False: see parallel/step.py — the SHA-256 fori_loop carry
    # mixes unvarying literals with varying lanes.
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, None)),
        out_specs=P(None),
        check_vma=False,
    )(nodes, zero_words)


def sharded_merkleize_chunks(
    chunks: bytes, mesh: Mesh, limit: int | None = None, axis_name: str = SHARD_AXIS
) -> bytes:
    """Mesh-sharded equivalent of ssz.merkle.merkleize_chunks (bit-identical).

    Pads the populated leaves up to a power-of-two multiple of the mesh size
    with zero chunks; the virtual tree above (up to ``limit``) chains
    zero-subtree hashes exactly like the host merkleizer.
    """
    if len(chunks) % BYTES_PER_CHUNK != 0:
        raise ValueError("chunks must be a multiple of 32 bytes")
    count = len(chunks) // BYTES_PER_CHUNK
    if limit is None:
        width = next_pow_of_two(count)
    else:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        width = next_pow_of_two(limit)
    depth = (width - 1).bit_length()
    if count == 0:
        return zero_hash(depth)

    n_dev = mesh.shape[axis_name]
    # shardable only when every device owns a full, aligned 2^k-leaf subtree
    # inside the virtual tree: mesh size a power of two and ≤ width. Anything
    # else (tiny trees, odd meshes) goes to the host merkleizer, which
    # handles every input.
    if n_dev & (n_dev - 1) or n_dev > width:
        return merkleize_chunks(chunks, limit)
    local = max(1, next_pow_of_two(count) // n_dev)
    padded = local * n_dev  # == max(next_pow_of_two(count), n_dev) ≤ width
    data = chunks + b"\x00" * ((padded - count) * BYTES_PER_CHUNK)
    words = np.ascontiguousarray(
        np.frombuffer(data, dtype=">u4").astype(np.uint32).reshape(padded, 8).T
    )
    words_d, zero_d = _obs.h2d(
        "parallel.merkle.sharded_merkleize", words, zero_hash_words()
    )
    root = sharded_merkle_root_words(
        words_d,
        zero_d,
        depth=depth,
        mesh=mesh,
        axis_name=axis_name,
    )
    return _obs.d2h(
        "parallel.merkle.sharded_merkleize", root
    ).astype(">u4").tobytes()
