"""jax API-drift shims for the sharding layer.

``shard_map`` has moved twice across the jax versions this repo meets:
``jax.experimental.shard_map.shard_map`` (with ``check_rep``) on 0.4.x,
``jax.shard_map`` (with ``check_vma``) on 0.6+. The sharded kernels in
this package are written against the NEW surface; this shim maps the
call onto whichever the installed jax provides, so the same code runs
on the baked-in toolchain and on a future chip image. Resolution happens
once per process (the first sharded trace), not per call.
"""

from __future__ import annotations

import functools

__all__ = ["shard_map"]


@functools.lru_cache(maxsize=1)
def _resolve():
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native, "check_vma"
    from jax.experimental.shard_map import shard_map as legacy

    return legacy, "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the new keyword surface on any jax.

    ``check_vma=False`` maps to ``check_rep=False`` on the legacy API —
    both disable the replication/varying-axes type check that rejects
    the SHA-256/Miller fori_loop carries mixing unvarying literals with
    device-varying lanes (see parallel/step.py).
    """
    fn, check_kw = _resolve()
    return fn(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{check_kw: check_vma},
    )
