"""Multi-chip parallelism: device meshes, sharded merkleization, and the
distributed chain step.

The reference is a single-process library (SURVEY.md §2.5); scale-out here is
green-field TPU design: batch axes of the crypto kernels (merkle leaf ranges,
signature batches, validator-registry sweeps) are sharded over a
``jax.sharding.Mesh`` with XLA collectives (``all_gather``/``psum``) riding
ICI, per the shard_map recipe.
"""

from .._jax_cache import enable as _enable_jax_cache

_enable_jax_cache()

from .mesh import chip_mesh, default_device_mesh  # noqa: E402
from .merkle import sharded_merkle_root_words, sharded_merkleize_chunks  # noqa: E402
from .step import make_chain_step  # noqa: E402

__all__ = [
    "chip_mesh",
    "default_device_mesh",
    "sharded_merkle_root_words",
    "sharded_merkleize_chunks",
    "make_chain_step",
]
