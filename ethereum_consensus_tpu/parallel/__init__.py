"""Multi-chip parallelism: device meshes, sharded merkleization, the
distributed chain step — and the PRODUCTION mesh runtime.

The reference is a single-process library (SURVEY.md §2.5); scale-out here is
green-field TPU design: batch axes of the crypto kernels (merkle leaf ranges,
signature batches, validator-registry sweeps) are sharded over a
``jax.sharding.Mesh`` with XLA collectives (``all_gather``/``psum``) riding
ICI, per the shard_map recipe.

``runtime.py`` is the production switch (``ECT_MESH=N|auto|off``): it
provisions one mesh per process and routes the columnar epoch sweeps
(``epoch.py``), the RLC flush windows (``pairing.py``), and large
``hash_tree_root`` rebuilds (``merkle.py``) through it, with every
engage/decline journaled and the host paths live as fallback +
differential oracle (docs/PARALLEL_DESIGN.md). Deliberately NOT
imported here: host-only processes consult a plain env read before
paying this package's jax import.
"""

from .._jax_cache import enable as _enable_jax_cache

_enable_jax_cache()

from .mesh import chip_mesh, default_device_mesh  # noqa: E402
from .merkle import sharded_merkle_root_words, sharded_merkleize_chunks  # noqa: E402
from .step import make_chain_step  # noqa: E402

__all__ = [
    "chip_mesh",
    "default_device_mesh",
    "sharded_merkle_root_words",
    "sharded_merkleize_chunks",
    "make_chain_step",
]
