"""Multi-chip parallelism: device meshes, sharded merkleization, and the
distributed chain step.

The reference is a single-process library (SURVEY.md §2.5); scale-out here is
green-field TPU design: batch axes of the crypto kernels (merkle leaf ranges,
signature batches, validator-registry sweeps) are sharded over a
``jax.sharding.Mesh`` with XLA collectives (``all_gather``/``psum``) riding
ICI, per the shard_map recipe.
"""

from .mesh import chip_mesh, default_device_mesh
from .merkle import sharded_merkle_root_words, sharded_merkleize_chunks
from .step import make_chain_step

__all__ = [
    "chip_mesh",
    "default_device_mesh",
    "sharded_merkle_root_words",
    "sharded_merkleize_chunks",
    "make_chain_step",
]
