"""Transition phase attribution from recorded spans.

The per-block cost split the ROADMAP quotes — signature batch / state
HTR / committees / operations — used to be computed by bench-local
monkeypatching inside ``bench.py``. It now derives from the named spans
the transition itself emits (``models/transition.py`` +
``models/phase0/helpers.py``), so ANY entry point that records a run —
bench, the pipeline CLI, the spec harness — attributes the same way.

Span name contract (docs/OBSERVABILITY.md):

* ``transition.slot_advance`` — one per ``process_slots`` call;
* ``transition.block``        — one per block-in-slot application;
* ``transition.sig_batch``    — the batched signature verification
  (≈ 0 under the pipeline's ``defer_flushes``: the work moved to the
  stage-B ``pipeline.flush.verify`` span);
* ``transition.state_htr``    — every full-state hash_tree_root (the
  per-slot root memo and the state-root check);
* ``transition.committees``   — committee/proposer machinery
  (``get_beacon_committee`` bodies, proposer-index cache misses).

``operations`` is everything else inside the transition:
``slot_advance + block − sig_batch − state_htr − committees`` — the same
residual definition the old bench plumbing used, so BENCH_*.json
trajectories stay comparable across the migration.
"""

from __future__ import annotations

__all__ = ["PHASE_SPANS", "HOT_SWEEP_SPANS", "attribution", "hot_sweep_report"]

PHASE_SPANS = {
    "slot_advance": "transition.slot_advance",
    "block": "transition.block",
    "sig_batch": "transition.sig_batch",
    "state_htr": "transition.state_htr",
    "committees": "transition.committees",
}

# The named ROADMAP hot scans. With the epoch caches and the columnar
# withdrawals path (models/ops_vector.py) engaged, NONE of these may
# appear on a warm per-block path — the columnar twin runs under
# ``ops_vector.withdrawals`` instead. Epoch-boundary occurrences (inside
# ``transition.process_epoch``) are legitimate once-per-epoch work.
HOT_SWEEP_SPANS = (
    "helpers.active_indices_sweep",
    "helpers.total_balance_sweep",
    "capella.withdrawals_sweep",
    "electra.withdrawals_sweep",
)


def _total(records, name: str) -> float:
    return sum(r.duration_s for r in records if r.name == name)


def attribution(records) -> dict:
    """Phase seconds from a list of ``SpanRecord``s (one or more recorded
    transitions). Returns the bench ``phases`` dict shape."""
    by_id = {r.span_id: r for r in records}

    def has_ancestor(rec, name: str) -> bool:
        seen = 0
        parent = by_id.get(rec.parent_id)
        while parent is not None and seen < 64:
            if parent.name == name:
                return True
            parent = by_id.get(parent.parent_id)
            seen += 1
        return False

    slots_s = _total(records, PHASE_SPANS["slot_advance"])
    block_s = _total(records, PHASE_SPANS["block"])
    sig_s = _total(records, PHASE_SPANS["sig_batch"])
    htr_s = _total(records, PHASE_SPANS["state_htr"])
    committee_s = _total(records, PHASE_SPANS["committees"])
    htr_in_slots = sum(
        r.duration_s
        for r in records
        if r.name == PHASE_SPANS["state_htr"]
        and has_ancestor(r, PHASE_SPANS["slot_advance"])
    )
    ops_s = (slots_s + block_s) - (sig_s + htr_s + committee_s)
    return {
        "slot_advance_s": round(slots_s, 4),
        "block_apply_s": round(block_s, 4),
        "sig_batch_s": round(sig_s, 4),
        "state_htr_s": round(htr_s, 4),
        "state_htr_in_slot_advance_s": round(htr_in_slots, 4),
        "committee_s": round(committee_s, 4),
        "operations_s": round(max(0.0, ops_s), 4),
    }


def hot_sweep_report(records) -> dict:
    """Occurrences of the named ROADMAP hot-scan spans over a recorded
    run, split into ``boundary`` (inside ``transition.process_epoch`` —
    legitimate once-per-epoch recomputation) and ``per_block`` (must be
    ABSENT on a warm path: the epoch caches and the columnar withdrawals
    sweep take them off it). ``per_block_absent`` is the bench
    assertion bit."""
    by_id = {r.span_id: r for r in records}

    def inside_epoch_processing(rec) -> bool:
        seen = 0
        parent = by_id.get(rec.parent_id)
        while parent is not None and seen < 64:
            if parent.name == "transition.process_epoch":
                return True
            parent = by_id.get(parent.parent_id)
            seen += 1
        return False

    per_block: dict = {}
    boundary: dict = {}
    for r in records:
        if r.name in HOT_SWEEP_SPANS:
            bucket = boundary if inside_epoch_processing(r) else per_block
            bucket[r.name] = bucket.get(r.name, 0) + 1
    return {
        "per_block": per_block,
        "boundary": boundary,
        "per_block_absent": not per_block,
    }
