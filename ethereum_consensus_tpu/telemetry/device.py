"""Device execution observatory: JAX/XLA compile, transfer, and routing
telemetry.

The host paths are instrumented exhaustively (spans, metrics, flight
lineage) but the JAX/XLA side was a black box: a TPU run would come home
with ``bls.pairing_route.{device,host}`` tallies and nothing else — no
visibility into compiles (tens of seconds per distinct shape on the
tunneled chip), silent per-shape RE-compiles (the classic TPU perf
killer: one drifting dtype and every "warm" call re-traces), host<->
device transfer volume (the epoch columns and signature batches are the
payloads that matter), or why a given call routed device vs host. This
module closes that: one process-wide ``DeviceObservatory`` recording

* a **compile ledger** — every traced-function compile observed through
  the repo's jit seams (``ops/``, ``parallel/``,
  ``models/epoch_vector.py`` kernels), with the call's shape/dtype
  signature, elapsed seconds (the compiling call's wall time — on an
  accelerator trace+compile dominates it), and a **recompile sentinel**:
  a counter plus a ONE-SHOT trace event per function naming the old and
  new signatures whenever an already-compiled kernel is re-traced for a
  drifted signature;
* a **transfer ledger** — host→device and device→host transfer counts
  and bytes aggregated per call site (``device.transfer.{h2d,d2h}_
  {count,bytes}`` registry counters + per-site totals), with
  per-transfer spans on a dedicated ``device`` virtual lane in the
  Chrome-trace export (telemetry/spans.py ``named_lane``) so Perfetto
  renders the device traffic alongside the pipeline/verifier thread
  tracks;
* a **routing journal** — every device-vs-host decision (the
  ``_device_flags`` threshold gates, the BLS pairing route, the
  ``epoch_vector`` engage/decline) with its choice, reason, and
  threshold inputs, queryable live via the introspection server's
  ``/device`` endpoint and summarized per flush window in
  ``BlockLineage.verify_route``.

Cost discipline (the spans/commit-hook contract): ``OBSERVATORY.active``
is a plain bool read — instrumented call sites check it FIRST and pay
nothing else while the observatory is off (guarded by the overhead test
in tests/test_device_observatory.py). Everything here is stdlib-only;
jax is never imported by this module (the instrumented seams already
have it).

Lock discipline (speclint-checked): every write to the observatory's
shared structures holds ``self._lock``; the hot ``active`` read and the
metrics-registry increments (locked per metric) stay outside it.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from contextlib import contextmanager

from .. import _env
from . import metrics as _metrics
from . import spans as _spans

__all__ = [
    "DeviceObservatory",
    "OBSERVATORY",
    "DEFAULT_CAPACITY",
    "observe_jit",
    "h2d",
    "d2h",
    "route",
    "signature_of",
    "start",
    "stop",
    "is_observing",
    "observing",
    "snapshot",
]

DEFAULT_CAPACITY = 1 << 12

_DEVICE_LANE = "device"


def signature_of(args: tuple, kwargs: dict) -> str:
    """A stable shape/dtype signature for one jitted call: arrays render
    as ``dtype[d0,d1]``, static scalars by value, everything else by
    type name — the same drift axes XLA re-traces on."""
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        elif isinstance(a, (bool, int, float, str, bytes)):
            parts.append(repr(a))
        else:
            parts.append(type(a).__name__)
    for k in sorted(kwargs):
        v = kwargs[k]
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{k}={dtype}[{','.join(str(d) for d in shape)}]")
        elif isinstance(v, (bool, int, float, str, bytes)):
            parts.append(f"{k}={v!r}")
        else:
            parts.append(f"{k}={type(v).__name__}")
    return "(" + ", ".join(parts) + ")"


def _jit_cache_size(jitted) -> "int | None":
    """The jitted callable's executable-cache entry count, when the jax
    version exposes it (``PjitFunction._cache_size``); None otherwise —
    the observatory then falls back to its own seen-signature table."""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 — version drift must not break calls
        return None


class DeviceObservatory:
    """Process-wide ledger of device-side execution facts; one instance
    (``OBSERVATORY``) serves the whole process, started/stopped like the
    span recorder."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._compiles: deque = deque(maxlen=capacity)
        self._routes: deque = deque(maxlen=capacity)
        self._route_tally: dict = {}      # (kind, choice) -> count
        self._transfers: dict = {}        # site -> {h2d/d2h count/bytes}
        self._signatures: dict = {}       # fn -> set of compiled signatures
        self._sentinel_seen: set = set()  # fn names whose sentinel fired
        self.active = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Begin a fresh observation (drops previous ledgers)."""
        with self._lock:
            self._compiles.clear()
            self._routes.clear()
            self._route_tally.clear()
            self._transfers.clear()
            self._signatures.clear()
            self._sentinel_seen.clear()
            self.active = True

    def stop(self) -> None:
        """Stop observing (ledgers stay readable)."""
        with self._lock:
            self.active = False

    # -- compile ledger ------------------------------------------------------
    def record_call(self, name: str, signature: str, t0: float, t1: float,
                    compiled: "bool | None", cache_size: "int | None") -> None:
        """One observed jitted call. ``compiled`` is the jit-cache
        verdict when the jax version exposes the cache size (None =
        unknown: fall back to the seen-signature table)."""
        seconds = max(0.0, t1 - t0)
        recompile_from = None
        with self._lock:
            known = self._signatures.get(name)
            if known is None:
                known = self._signatures[name] = set()
            if compiled is None:
                compiled = signature not in known
            if compiled:
                if known and signature not in known:
                    # the sentinel case: this kernel had compiled before
                    # and a drifted signature re-traced it
                    recompile_from = sorted(known)[-1]
                known.add(signature)
                self._compiles.append(
                    {
                        "fn": name,
                        "signature": signature,
                        "compile_s": seconds,
                        "recompile": recompile_from is not None,
                        "prev_signature": recompile_from,
                        "cache_size": cache_size,
                        "at": time.time(),
                    }
                )
            fire_sentinel = (
                recompile_from is not None
                and name not in self._sentinel_seen
            )
            if fire_sentinel:
                self._sentinel_seen.add(name)
        if compiled:
            _metrics.counter("device.compiles").inc()
            _metrics.histogram("device.compile_s").observe(seconds)
            _metrics.counter("device.jit_cache.misses").inc()
        else:
            _metrics.counter("device.jit_cache.hits").inc()
        if recompile_from is not None:
            _metrics.counter("device.recompiles").inc()
        if fire_sentinel:
            # one-shot per function per process (the ops_vector.fallback
            # idiom): the counter counts every recompile, the event names
            # the drift once so a trace isn't flooded by a pathological
            # shape churn
            from ..utils import trace

            trace.event(
                "device.recompile",
                fn=name,
                old_signature=recompile_from,
                new_signature=signature,
            )
        rec = _spans.RECORDER
        if rec.enabled and compiled:
            rec.add_complete(
                "device.compile",
                t0,
                t1,
                {"fn": name, "signature": signature,
                 "recompile": recompile_from is not None},
                lane=rec.named_lane(_DEVICE_LANE),
            )

    # -- transfer ledger -----------------------------------------------------
    def record_transfer(self, site: str, direction: str, count: int,
                        nbytes: int, t0: float, t1: float) -> None:
        """One host<->device transfer at ``site`` (``direction`` is
        ``h2d`` or ``d2h``)."""
        with self._lock:
            agg = self._transfers.get(site)
            if agg is None:
                agg = self._transfers[site] = {
                    "h2d_count": 0, "h2d_bytes": 0,
                    "d2h_count": 0, "d2h_bytes": 0,
                }
            agg[f"{direction}_count"] += count
            agg[f"{direction}_bytes"] += nbytes
        _metrics.counter(f"device.transfer.{direction}_count").inc(count)
        _metrics.counter(f"device.transfer.{direction}_bytes").inc(nbytes)
        rec = _spans.RECORDER
        if rec.enabled:
            rec.add_complete(
                f"device.{direction}",
                t0,
                t1,
                {"site": site, "bytes": nbytes, "count": count},
                lane=rec.named_lane(_DEVICE_LANE),
            )

    # -- routing journal -----------------------------------------------------
    def record_route(self, kind: str, choice: str, reason: str,
                     inputs: dict) -> None:
        """One device-vs-host decision: ``kind`` names the gate
        (``pairing``, ``sweeps``, ``shuffle``, ``bls_agg``,
        ``epoch_vector``), ``choice`` where it went (``device`` /
        ``host`` / ``columnar`` / ``literal``), ``reason`` why, and
        ``inputs`` the threshold arithmetic behind it."""
        with self._lock:
            key = (kind, choice)
            self._route_tally[key] = self._route_tally.get(key, 0) + 1
            self._routes.append(
                {
                    "kind": kind,
                    "choice": choice,
                    "reason": reason,
                    "inputs": dict(inputs),
                    "at": time.time(),
                }
            )
        _metrics.counter(f"device.route.{kind}.{choice}").inc()
        rec = _spans.RECORDER
        if rec.enabled:
            rec.add_instant(
                "device.route",
                time.perf_counter(),
                {"kind": kind, "choice": choice, "reason": reason},
                lane=rec.named_lane(_DEVICE_LANE),
            )

    # -- reading -------------------------------------------------------------
    def compiles(self) -> list:
        """Compile-ledger records, oldest first (consistent copy)."""
        with self._lock:
            return [dict(r) for r in self._compiles]

    def routes(self, n: "int | None" = None) -> list:
        """Routing-journal records, oldest first; newest ``n`` if
        given."""
        with self._lock:
            records = [dict(r) for r in self._routes]
        return records if n is None else records[-n:]

    def route_tallies(self) -> dict:
        """Cumulative ``{kind: {choice: count}}`` over the whole
        observation (unbounded, unlike the journal ring)."""
        with self._lock:
            items = list(self._route_tally.items())
        out: dict = {}
        for (kind, choice), count in items:
            out.setdefault(kind, {})[choice] = count
        return out

    def transfer_summary(self) -> dict:
        """Per-site transfer aggregates plus process totals."""
        with self._lock:
            sites = {site: dict(agg) for site, agg in self._transfers.items()}
        totals = {"h2d_count": 0, "h2d_bytes": 0, "d2h_count": 0,
                  "d2h_bytes": 0}
        for agg in sites.values():
            for key in totals:
                totals[key] += agg[key]
        return {"sites": sites, "totals": totals}

    def signatures(self) -> dict:
        """``{fn: sorted compiled signatures}`` — the shape census."""
        with self._lock:
            return {name: sorted(sigs)
                    for name, sigs in self._signatures.items()}

    def snapshot(self, journal_n: int = 128) -> dict:
        """The /device endpoint document: every ledger, JSON-ready."""
        from .._jax_cache import status as _jax_cache_status

        # mesh runtime state (parallel/runtime.py): imported ONLY when
        # ECT_MESH is switched on — this module stays jax-free otherwise
        mesh_env = _env.raw("ECT_MESH").strip()
        mesh_state = {
            "requested": False,
            "env": mesh_env or "off",
            "devices": 0,
        }
        if mesh_env.lower() not in ("", "off", "0", "none", "host"):
            try:
                from ..parallel import runtime as _mesh_runtime

                mesh_state = _mesh_runtime.status()
            except Exception as exc:  # noqa: BLE001 — report, not raise
                mesh_state["error"] = repr(exc)[:160]
        compiles = self.compiles()
        return {
            "observing": self.active,
            "compile_ledger": {
                "compiles": len(compiles),
                "recompiles": sum(1 for c in compiles if c["recompile"]),
                "total_compile_s": sum(c["compile_s"] for c in compiles),
                "signatures": self.signatures(),
                "recent": compiles[-journal_n:],
            },
            "transfer_ledger": self.transfer_summary(),
            "routing_journal": {
                "tallies": self.route_tallies(),
                "recent": self.routes(journal_n),
            },
            "jit_cache": {
                "hits": _metrics.counter("device.jit_cache.hits").value(),
                "misses": _metrics.counter("device.jit_cache.misses").value(),
            },
            "persistent_cache": _jax_cache_status(),
            "mesh": mesh_state,
        }


OBSERVATORY = DeviceObservatory()


# ---------------------------------------------------------------------------
# the instrumentation seams (called from ops/, parallel/, models/, crypto/)
# ---------------------------------------------------------------------------


def observe_jit(jitted, name: str):
    """Wrap an already-jitted callable so every call through it feeds
    the compile ledger while the observatory is active. The inactive
    path is one bool read + one indirection (overhead-test guarded);
    the active path derives the call's shape signature, times the call,
    and classifies it compile / cache-hit / RECOMPILE via the jit cache
    size (or the observatory's own signature table on jax versions
    without ``_cache_size``)."""

    def observed(*args, **kwargs):
        obs = OBSERVATORY
        if not obs.active:
            return jitted(*args, **kwargs)
        signature = signature_of(args, kwargs)
        before = _jit_cache_size(jitted)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        t1 = time.perf_counter()
        after = _jit_cache_size(jitted)
        compiled = None
        if before is not None and after is not None:
            compiled = after > before
        obs.record_call(name, signature, t0, t1, compiled, after)
        return out

    observed.__name__ = name.rsplit(".", 1)[-1]
    observed.__qualname__ = name
    observed.__wrapped__ = jitted
    return observed


@functools.lru_cache(maxsize=1)
def _jnp():
    """The jax.numpy module, resolved once (thread-safe via lru_cache —
    no unlocked module-global write). Call sites of ``h2d`` are device
    entry points that already imported jax, so this never triggers a
    cold jax import on a host-only process."""
    import jax.numpy

    return jax.numpy


@functools.lru_cache(maxsize=1)
def _np():
    import numpy

    return numpy


def _nbytes(a) -> int:
    n = getattr(a, "nbytes", None)
    if n is not None:
        return int(n)
    try:
        return len(a)
    except TypeError:
        return 0


def h2d(site: str, *arrays):
    """``jnp.asarray`` every argument (the repo's host→device seam),
    recording count/bytes/seconds against ``site`` while observing.
    Returns a single array for a single argument, a tuple otherwise.
    On the CPU backend the "transfer" may be a zero-copy view — the
    ledger measures the dispatch seam, which on a real accelerator IS
    the PCIe/ICI transfer."""
    jnp = _jnp()
    obs = OBSERVATORY
    if not obs.active:
        out = tuple(jnp.asarray(a) for a in arrays)
        return out[0] if len(out) == 1 else out
    nbytes = sum(_nbytes(a) for a in arrays)
    t0 = time.perf_counter()
    out = tuple(jnp.asarray(a) for a in arrays)
    t1 = time.perf_counter()
    obs.record_transfer(site, "h2d", len(out), nbytes, t0, t1)
    return out[0] if len(out) == 1 else out


def h2d_put(site: str, arrays, sharding=None):
    """``jax.device_put`` with an explicit sharding — the sharded-mesh
    twin of ``h2d``, and the ONLY sanctioned way to place host buffers
    onto a mesh (speclint's transfer-seam rule points every raw
    ``device_put`` here). Takes an iterable so one ledger entry covers
    the whole staged argument tuple; returns the placed tuple."""
    import jax

    arrays = tuple(arrays)
    obs = OBSERVATORY
    if not obs.active:
        return tuple(jax.device_put(a, sharding) for a in arrays)
    nbytes = sum(_nbytes(a) for a in arrays)
    t0 = time.perf_counter()
    out = tuple(jax.device_put(a, sharding) for a in arrays)
    t1 = time.perf_counter()
    obs.record_transfer(site, "h2d", len(out), nbytes, t0, t1)
    return out


def d2h(site: str, array):
    """``np.asarray`` the device value (the device→host seam),
    recording against ``site`` while observing."""
    np = _np()
    obs = OBSERVATORY
    if not obs.active:
        return np.asarray(array)
    t0 = time.perf_counter()
    out = np.asarray(array)
    t1 = time.perf_counter()
    obs.record_transfer(site, "d2h", 1, _nbytes(out), t0, t1)
    return out


def route(kind: str, choice: str, reason: str, **inputs) -> None:
    """Journal one device-vs-host decision (no-op while not observing;
    hot call sites pre-guard with ``OBSERVATORY.active`` so the off
    path is a single bool read)."""
    obs = OBSERVATORY
    if not obs.active:
        return
    obs.record_route(kind, choice, reason, inputs)


# -- module-level lifecycle ---------------------------------------------------


def start() -> DeviceObservatory:
    OBSERVATORY.start()
    return OBSERVATORY


def stop() -> None:
    OBSERVATORY.stop()


def is_observing() -> bool:
    return OBSERVATORY.active


@contextmanager
def observing():
    """Observe for the duration of the block; yields ``OBSERVATORY``
    (the ``spans.recording`` idiom)."""
    start()
    try:
        yield OBSERVATORY
    finally:
        stop()


def snapshot(journal_n: int = 128) -> dict:
    return OBSERVATORY.snapshot(journal_n)
