"""Unified telemetry: structured spans + process-wide metrics.

Two stdlib-only submodules (importable from any layer, including the
pure-host ``ssz``/``crypto`` paths — nothing here touches jax):

* ``spans``   — the in-process ring-buffer span recorder behind the
  ``utils/trace.py`` facade, with Chrome trace-event JSON export
  (Perfetto / ``chrome://tracing``). Off by default; near-zero cost
  while off.
* ``metrics`` — the process-wide counter/gauge/histogram registry with
  snapshot/delta semantics; the one home for operational counters
  (``ssz.digests``, ``bls.pubkey_cache.*``, ``pipeline.*``, ...).
* ``phases``  — derives the bench's per-block phase attribution
  (sig batch / state HTR / committees / operations) from recorded
  transition spans.
* ``flight``  — the chain flight recorder: a bounded ring journal of
  per-block ``BlockLineage`` records assembled by the pipeline's
  commit/rollback hook, with JSONL export and a query API.
* ``device``  — the device execution observatory: compile ledger with
  recompile sentinel, host<->device transfer ledger, and the
  device-vs-host routing journal, recorded at the repo's JAX/XLA seams
  (stdlib-only here; jax stays at the instrumented call sites).
* ``memory``  — the memory & bandwidth observatory: resident-set
  census of the repo's byte owners, phase RSS/allocation ledger
  bracketing the transition/epoch spans, and per-site bulk-copy byte
  counters at the SSZ/pipeline/mesh chokepoints.
* ``server``  — the live introspection server (``/metrics`` Prometheus
  exposition, ``/healthz``, ``/blocks``, ``/events`` SSE). NOT imported
  here: it pulls in ``http.server``, which no pure-compute layer needs
  — import ``ethereum_consensus_tpu.telemetry.server`` explicitly.

Conventions and export formats: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from . import flight, metrics, phases, spans
from . import device  # noqa: E402 — after spans/metrics (its imports)
from . import memory  # noqa: E402 — after spans/metrics (its imports)

__all__ = [
    "device", "flight", "memory", "metrics", "phases", "spans", "server",
]
