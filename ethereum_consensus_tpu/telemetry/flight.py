"""Chain flight recorder: per-block lineage off the pipeline commit hook.

Spans answer "where did the microseconds go inside one call"; the bench
answers "how fast is the hot path on average". Neither answers the
operational question a serving node gets paged for: *why was block N
slow / rolled back / late*. The flight recorder does — one bounded ring
journal of ``BlockLineage`` records, one record per block disposition,
assembled by the pipeline engine (``pipeline/engine.py``) at its
commit/rollback boundaries and published through the process-wide
``CommitHook`` this module owns.

Each record carries the block's whole trip through the two-stage
pipeline: slot, root, fork, stage-A apply seconds (with the span-derived
phase split when the span recorder is live), queue wait, the flush
window it rode (seq + membership — which blocks shared the RLC
multi-pairing), the window's verify seconds and settle wall time, the
outcome (``committed`` / ``rolled-back`` with structured blame /
``degraded-inline`` / ``retried-N`` / ``discarded``), and — when the
scenario harness drove a storm — the measured recovery latency.

The hook is also the live-event bus: the introspection server
(``telemetry/server.py``) subscribes the same ``head`` / ``commit`` /
``rollback`` / ``broken`` events onto SSE streams — the seed of the
ROADMAP's serving layer.

Cost discipline: the engine guards every assembly site with one read of
``HOOK.active`` (a plain bool — no call, no lock), so a pipeline with
neither the recorder nor a server attached pays nothing measurable
(guarded by tests/test_flight_server.py's overhead test, the same
contract as the disabled-span fast path).

Lock discipline (speclint-checked): every write to shared structures
holds the owner's ``_lock``; ``HOOK.active`` and subscriber fan-out read
an immutable tuple snapshot lock-free. No lock is ever held while
calling out (subscriber callbacks run outside the hook lock), so the
lockorder analyzer sees no cross-module edges.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = [
    "BlockLineage",
    "CommitHook",
    "FlightRecorder",
    "HOOK",
    "RECORDER",
    "DEFAULT_CAPACITY",
    "LATENCY_FIELDS",
    "start",
    "stop",
    "is_recording",
    "read_jsonl",
]

DEFAULT_CAPACITY = 1 << 12

# the queryable latency axes of a lineage record (worst-N API + docs)
LATENCY_FIELDS = (
    "stage_a_s",
    "queue_wait_s",
    "verify_s",
    "settle_s",
    "total_s",
    "recovery_s",
)

_OUTCOMES = ("committed", "rolled-back", "discarded")


class BlockLineage:
    """One block's trip through the pipeline, flattened to plain values
    (JSON-ready via ``to_dict``). Latency decomposition on the success
    path: ``stage_a_s`` (speculative application on the submitting
    thread) + ``queue_wait_s`` (applied → window dispatch) +
    ``settle_s`` (dispatch → verdicts in hand) ≈ ``total_s`` (submit →
    disposition); ``verify_s`` is the window's stage-B busy seconds and
    overlaps later blocks' stage A — it is membership-shared, not
    additive."""

    __slots__ = (
        "slot",
        "root",
        "block_root",
        "fork",
        "outcome",
        "stage_a_s",
        "phases",
        "queue_wait_s",
        "flush_seq",
        "flush_slots",
        "flush_sets",
        "verify_s",
        "verify_route",
        "settle_s",
        "total_s",
        "retries",
        "degraded",
        "blame",
        "recovery_s",
        "trace_id",
        "finished_at",
    )

    def __init__(
        self,
        slot: int,
        root: str,
        block_root: "str | None" = None,
        fork: "str | None" = None,
        outcome: str = "committed",
        stage_a_s: "float | None" = None,
        phases: "dict | None" = None,
        queue_wait_s: float = 0.0,
        flush_seq: "int | None" = None,
        flush_slots: tuple = (),
        flush_sets: int = 0,
        verify_s: "float | None" = None,
        verify_route: "str | None" = None,
        settle_s: "float | None" = None,
        total_s: "float | None" = None,
        retries: int = 0,
        degraded: bool = False,
        blame: "dict | None" = None,
        recovery_s: "float | None" = None,
        trace_id: "int | None" = None,
        finished_at: "float | None" = None,
    ):
        if outcome not in _OUTCOMES:
            raise ValueError(f"unknown outcome {outcome!r}")
        self.slot = slot
        self.root = root
        self.block_root = block_root
        self.fork = fork
        self.outcome = outcome
        self.stage_a_s = stage_a_s
        self.phases = phases
        self.queue_wait_s = queue_wait_s
        self.flush_seq = flush_seq
        self.flush_slots = tuple(flush_slots)
        self.flush_sets = flush_sets
        self.verify_s = verify_s
        # which pairing route proved this block's flush window:
        # "device" / "host" / None (no RLC batch ran — empty flush or
        # per-set fallback) — the device observatory's lineage hook
        self.verify_route = verify_route
        self.settle_s = settle_s
        self.total_s = total_s
        self.retries = retries
        self.degraded = degraded
        self.blame = blame
        self.recovery_s = recovery_s
        # the causal trace this block's flush window recorded under
        # (telemetry/spans.py TraceContext), None when tracing was off
        self.trace_id = trace_id
        self.finished_at = time.time() if finished_at is None else finished_at

    @property
    def committed(self) -> bool:
        return self.outcome == "committed"

    @property
    def disposition(self) -> str:
        """The ISSUE taxonomy string: ``committed`` / ``rolled-back`` /
        ``degraded-inline`` (committed, but verified on the host thread
        instead of the overlapped worker) / ``retried-N`` (committed
        after N transient-fault re-dispatches) / ``discarded``
        (speculative work abandoned by someone else's failure)."""
        if self.outcome != "committed":
            return self.outcome
        if self.degraded:
            return "degraded-inline"
        if self.retries:
            return f"retried-{self.retries}"
        return "committed"

    def to_dict(self) -> dict:
        d = {name: getattr(self, name) for name in self.__slots__}
        d["flush_slots"] = list(self.flush_slots)
        d["disposition"] = self.disposition
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "BlockLineage":
        kwargs = {name: d[name] for name in cls.__slots__ if name in d}
        kwargs["flush_slots"] = tuple(kwargs.get("flush_slots", ()))
        return cls(**kwargs)

    def __repr__(self) -> str:
        return (
            f"BlockLineage(slot={self.slot}, {self.disposition}, "
            f"flush_seq={self.flush_seq}, total_s={self.total_s})"
        )


class CommitHook:
    """Pub/sub fan-out for pipeline lifecycle events.

    ``emit(kind, payload)`` calls every subscriber with the event; kinds
    in flight today: ``block`` (payload: ``BlockLineage``), ``head`` /
    ``commit`` / ``rollback`` / ``broken`` (payload: JSON-ready dict).

    ``active`` is the engine's zero-overhead guard: a plain bool that is
    True exactly while at least one subscriber is attached — the hot
    path reads it without a call or a lock. Subscribers must never
    raise into the pipeline; a raising subscriber is dropped from the
    fan-out for the event and counted (``flight.hook_errors``).

    The STATE channel (``subscribe_states``/``emit_state``) is the
    serving data plane's feed and deliberately separate from the event
    channel: its payloads carry live state handles (a committed
    ``BeaconState`` copy — not JSON-ready, never put on an SSE wire),
    and its guard ``state_active`` gates an O(registry) state copy per
    flush window in the engine, a cost only a mounted ``HeadStore``
    should ever switch on. Same contracts otherwise: lock-free tuple
    snapshot fan-out, subscribers never raise into the pipeline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: tuple = ()
        self._state_subs: tuple = ()
        self.active = False
        self.state_active = False

    def subscribe(self, fn) -> None:
        with self._lock:
            if fn not in self._subs:
                self._subs = self._subs + (fn,)
            self.active = True

    def unsubscribe(self, fn) -> None:
        # equality, not identity: a bound method (RECORDER.handle) is a
        # fresh object per attribute access, but compares equal
        with self._lock:
            self._subs = tuple(s for s in self._subs if s != fn)
            self.active = bool(self._subs)

    def subscribe_states(self, fn) -> None:
        with self._lock:
            if fn not in self._state_subs:
                self._state_subs = self._state_subs + (fn,)
            self.state_active = True

    def unsubscribe_states(self, fn) -> None:
        with self._lock:
            self._state_subs = tuple(s for s in self._state_subs if s != fn)
            self.state_active = bool(self._state_subs)

    def emit(self, kind: str, payload) -> None:
        for fn in self._subs:  # tuple snapshot: safe without the lock
            try:
                fn(kind, payload)
            except Exception:  # noqa: BLE001 — never break the pipeline
                from . import metrics as _metrics

                _metrics.counter("flight.hook_errors").inc()

    def emit_state(self, payload: dict) -> None:
        """Fan a committed-state snapshot out to the data plane:
        ``payload`` carries ``state`` (an immutable-by-convention copy),
        ``context``, ``slot``, ``root`` (hex), ``seq``."""
        for fn in self._state_subs:  # tuple snapshot, same as emit
            try:
                fn(payload)
            except Exception:  # noqa: BLE001 — never break the pipeline
                from . import metrics as _metrics

                _metrics.counter("flight.hook_errors").inc()


class FlightRecorder:
    """Bounded ring journal of ``BlockLineage`` records with a small
    query API and JSONL export. Subscribe it to ``HOOK`` (via
    ``flight.start()``) to record a live pipeline."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=capacity)
        self._last_broken: "dict | None" = None

    # -- hook subscriber -----------------------------------------------------
    def handle(self, kind: str, payload) -> None:
        if kind == "block":
            dropped = False
            with self._lock:
                if len(self._records) == self._records.maxlen:
                    dropped = True  # ring full: oldest lineage evicted
                self._records.append(payload)
            if dropped:
                from . import metrics as _metrics

                _metrics.counter("flight.ring_dropped").inc()
        elif kind == "broken":
            with self._lock:
                self._last_broken = dict(payload)

    # -- recording control ---------------------------------------------------
    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._last_broken = None

    def resize(self, capacity: int) -> None:
        with self._lock:
            self._records = deque(self._records, maxlen=capacity)

    @property
    def capacity(self) -> int:
        return self._records.maxlen

    @property
    def last_broken(self) -> "dict | None":
        """Attribution of the newest ``PipelineBrokenError`` observed
        (stuck window seq + slots), or None — the /healthz detail."""
        return self._last_broken

    def __len__(self) -> int:
        return len(self._records)

    # -- query API -----------------------------------------------------------
    def records(self) -> "list[BlockLineage]":
        """Every retained record, oldest first (consistent copy)."""
        with self._lock:
            return list(self._records)

    def by_slot_range(self, lo: int, hi: int) -> "list[BlockLineage]":
        """Records with ``lo <= slot <= hi``, oldest first."""
        return [r for r in self.records() if lo <= r.slot <= hi]

    def by_outcome(self, outcome: str) -> "list[BlockLineage]":
        """Records whose ``outcome`` OR derived ``disposition`` matches
        (so both ``committed`` and ``degraded-inline`` are queryable)."""
        return [
            r
            for r in self.records()
            if r.outcome == outcome or r.disposition == outcome
        ]

    def for_slot(self, slot: int) -> "list[BlockLineage]":
        return [r for r in self.records() if r.slot == slot]

    def by_trace(self, trace_id: int) -> "list[BlockLineage]":
        """Records settled under the causal trace ``trace_id`` (the
        ``/trace`` endpoint's lineage join), oldest first."""
        return [r for r in self.records() if r.trace_id == trace_id]

    def worst(self, n: int = 5, field: str = "total_s") -> "list[BlockLineage]":
        """The ``n`` records with the largest ``field`` (any
        ``LATENCY_FIELDS`` axis), descending; records without the field
        populated sort last and are excluded."""
        if field not in LATENCY_FIELDS:
            raise ValueError(
                f"unknown latency field {field!r} (one of {LATENCY_FIELDS})"
            )
        populated = [
            r for r in self.records() if getattr(r, field) is not None
        ]
        populated.sort(key=lambda r: getattr(r, field), reverse=True)
        return populated[:n]

    # -- annotation ----------------------------------------------------------
    def annotate_recovery(self, slot: int, seconds: float) -> bool:
        """Stamp the measured rollback-recovery latency onto the NEWEST
        non-committed record for ``slot`` (the scenario harness measures
        recovery outside the engine — error caught → fresh pipeline
        ready — so it back-fills the record the rollback emitted).
        Returns whether a record was found."""
        with self._lock:
            for rec in reversed(self._records):
                if rec.slot == slot and rec.outcome != "committed":
                    rec.recovery_s = seconds
                    return True
        return False

    # -- JSONL ---------------------------------------------------------------
    def write_jsonl(self, path: str) -> int:
        """One JSON object per line, oldest first; returns the record
        count written."""
        records = self.records()
        with open(path, "w", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(rec.to_dict(), sort_keys=True))
                f.write("\n")
        return len(records)


def read_jsonl(path: str) -> "list[BlockLineage]":
    """Load a ``write_jsonl`` export back into records."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(BlockLineage.from_dict(json.loads(line)))
    return out


# -- the process-wide instances ----------------------------------------------

HOOK = CommitHook()
RECORDER = FlightRecorder()


def start(capacity: "int | None" = None) -> FlightRecorder:
    """Begin a fresh flight recording: clear the ring (resizing it if
    asked) and subscribe the process-wide recorder to the commit hook.
    Idempotent."""
    RECORDER.clear()
    if capacity is not None and capacity != RECORDER.capacity:
        RECORDER.resize(capacity)
    HOOK.subscribe(RECORDER.handle)
    return RECORDER


def stop() -> None:
    """Detach the recorder from the hook (records stay readable)."""
    HOOK.unsubscribe(RECORDER.handle)


def is_recording() -> bool:
    return HOOK.active
