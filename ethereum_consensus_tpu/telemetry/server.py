"""Live introspection server: /metrics, /healthz, /blocks, /events,
/device.

A stdlib-only threaded HTTP server over the telemetry substrate — the
read side of the ROADMAP's serving layer, landed first so every later
consumer (the Beacon-API read path, the device-pairing re-measure) ships
on instrumented ground:

* ``/metrics``  — the WHOLE metrics registry in Prometheus text
  exposition format 0.0.4: counters and gauges verbatim, histograms as
  summaries (``{quantile="..."}`` gauges from the bounded reservoir +
  ``_sum``/``_count``) with ``_min``/``_max`` companion gauges. Strict
  format 0.0.4 — no OpenMetrics constructs, so any classic scraper
  parses every line.
* ``/healthz``  — pipeline liveness: ``ok`` / ``degraded`` (the latched
  ``pipeline.degraded`` gauge) / ``broken`` (the latched
  ``pipeline.broken`` gauge, with the stuck window's seq + slots from
  the flight recorder's ``broken`` event).
* ``/blocks``   — recent ``BlockLineage`` records as JSON; filter by
  ``?outcome=``, ``?min_slot=``/``?max_slot=``, rank by
  ``?worst=<latency field>``, cap with ``?n=``.
* ``/events``   — Server-Sent Events off the pipeline commit hook:
  ``head`` / ``commit`` / ``rollback`` / ``broken`` (add ``block`` for
  full lineage records with ``?kinds=head,block``). Commit order on the
  wire IS chain order — the submitting thread emits. Idle streams carry
  a ``: ping`` keepalive comment every ``sse_keepalive_s`` (default
  15 s) so proxies and load balancers don't reap quiet subscribers.
* ``/device``   — the device execution observatory's ledgers
  (telemetry/device.py): compile ledger with recompile sentinel hits,
  per-site host<->device transfer aggregates, the device-vs-host
  routing journal, and the persistent XLA cache state. ``?n=`` caps the
  journal tails.
* ``/memory``   — the memory & bandwidth observatory's ledgers
  (telemetry/memory.py): the resident-set census with its ``worst``
  attribution table, the phase RSS ledger, and the per-site bulk-copy
  byte counters. ``?n=`` caps the worst table. The census probes run
  at request time — a scrape IS a census.
* ``/trace``    — the causal trace plane's read side. ``?id=<trace_id>``
  assembles one trace into its causal tree: the span tree (with
  cross-lane flow edges and a ``connected`` verdict), the flight
  lineage records settled under that trace, and the device span-plane
  evidence (``device.*`` route/transfer events) that landed inside the
  trace's time window. Bare ``/trace`` returns the worst-N slow-trace
  ring, the span recorder's audit (span/trace/orphan/drop counts), and
  every histogram's worst-N exemplar table — the JSON home of exemplar
  evidence (``/metrics`` stays pure text format 0.0.4: the OpenMetrics
  ``# {...}`` exemplar appendage would read as a malformed timestamp to
  classic parsers and fail the whole scrape, and even OpenMetrics only
  allows exemplars on counters/histogram buckets, not the summary
  quantiles we render). Trace ids come from the ``/trace`` exemplar
  tables, lineage records on ``/blocks``/``/events``, and the soak
  report's SLO gates.

``/metrics`` additionally carries a standard ``build_info`` gauge (git
sha, jax/numpy versions, x64 flag, backend platform as labels, value 1)
so every scrape — and every bench trend artifact built from one — is
self-describing.

Concurrency model (speclint's newest scope): the accept loop runs on a
single-worker ``ThreadPoolExecutor`` (the repo's sanctioned way to own a
background worker); per-request threads come from
``ThreadingHTTPServer`` with ``daemon_threads`` set; every
``IntrospectionServer`` state write holds its instance lock; SSE
fan-out rides the ``CommitHook``'s lock-free tuple snapshot with one
bounded ``queue.Queue`` per client (a slow client drops events rather
than backpressuring the pipeline — counted in
``flight.sse_dropped_events``).

Zero overhead when off: nothing here is imported by the pipeline; the
engine's only coupling is the ``flight.HOOK.active`` bool.
"""

from __future__ import annotations

import json
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import device as _device
from . import flight as _flight
from . import memory as _memory
from . import metrics as _metrics
from . import spans as _spans

__all__ = [
    "IntrospectionServer",
    "render_prometheus",
    "prometheus_name",
    "escape_label_value",
    "health_view",
    "build_info_labels",
    "build_info_line",
]

_QUANTILES = (0.5, 0.9, 0.99)
_SSE_DEFAULT_KINDS = ("head", "commit", "rollback", "broken")
DEFAULT_SSE_KEEPALIVE_S = 15.0


# ---------------------------------------------------------------------------
# Prometheus text exposition (format 0.0.4)
# ---------------------------------------------------------------------------


def prometheus_name(name: str) -> str:
    """The registry's dotted name as a valid Prometheus metric name:
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` — dots (and anything else outside the
    alphabet) become underscores, a leading digit gets a prefix."""
    out = [
        ch if (ch.isascii() and (ch.isalnum() or ch in "_:")) else "_"
        for ch in name
    ]
    rendered = "".join(out) or "_"
    if rendered[0].isdigit():
        rendered = "_" + rendered
    return rendered


def escape_label_value(value: str) -> str:
    """Label-value escaping per the text format: backslash, double
    quote, and newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _read_git_sha() -> str:
    """The checkout's HEAD commit, read straight from .git (no
    subprocess, no git dependency); "unknown" outside a checkout."""
    import os

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        with open(os.path.join(repo, ".git", "HEAD")) as f:
            head = f.read().strip()
        if head.startswith("ref: "):
            ref = head[len("ref: "):]
            with open(os.path.join(repo, ".git", ref)) as f:
                return f.read().strip()[:12]
        return head[:12]
    except OSError:
        return "unknown"


def _dist_version(name: str) -> str:
    """Installed version without importing the package (a /metrics
    scrape must never trigger a cold jax import)."""
    import sys

    mod = sys.modules.get(name)
    version = getattr(mod, "__version__", None)
    if version:
        return str(version)
    try:
        from importlib import metadata

        return metadata.version(name)
    except Exception:  # noqa: BLE001 — absent dependency
        return "unknown"


def build_info_labels() -> dict:
    """The ``build_info`` label set: git sha, jax/numpy versions, the
    x64 flag, and the backend platform. Platform/x64 report live values
    when jax is already imported (never importing it from here — an
    uninitialized process reports ``uninitialized``)."""
    import sys

    jax_mod = sys.modules.get("jax")
    x64 = "uninitialized"
    backend = "uninitialized"
    if jax_mod is not None:
        try:
            x64 = "1" if jax_mod.config.jax_enable_x64 else "0"
        except Exception:  # noqa: BLE001 — config drift
            x64 = "unknown"
        try:
            # default_backend() would *initialize* a backend on a fresh
            # process — only ask once something else already has
            if getattr(jax_mod._src.xla_bridge, "_backends", None):
                backend = jax_mod.default_backend()
        except Exception:  # noqa: BLE001 — internal layout drift
            backend = "unknown"
    return {
        "git_sha": _read_git_sha(),
        "jax": _dist_version("jax"),
        "numpy": _dist_version("numpy"),
        "x64": x64,
        "backend": backend,
    }


def build_info_line() -> str:
    labels = ",".join(
        f'{key}="{escape_label_value(value)}"'
        for key, value in sorted(build_info_labels().items())
    )
    return f"build_info{{{labels}}} 1"


def render_prometheus(metric_objects=None) -> str:
    """The registry (or an explicit metric-object list — the golden
    test's seam) as one exposition document, prefixed — on the
    default registry walk only — by the standard ``build_info`` gauge.
    Counters/gauges render verbatim; a ``Histogram`` renders as a
    summary — reservoir-derived ``{quantile="0.5|0.9|0.99"}`` samples
    plus exact ``_sum``/``_count`` — with ``_min``/``_max`` companion
    gauges.

    Deliberately NO exemplars here: the document is served as
    ``text/plain; version=0.0.4``, whose parser reads the OpenMetrics
    ``# {...}`` appendage as a malformed timestamp and rejects the
    line — failing the ENTIRE scrape whenever any histogram holds an
    exemplar. Even under negotiated OpenMetrics, exemplars are only
    legal on counters and histogram buckets, never on the summary
    quantiles rendered here. Exemplar evidence lives on the JSON side:
    bare ``/trace`` serves every histogram's worst-N table."""
    lines: list = []
    if metric_objects is None:
        metric_objects = _metrics.registered_metrics()
        lines.append(
            "# HELP build_info repo/toolchain identity of this process"
        )
        lines.append("# TYPE build_info gauge")
        lines.append(build_info_line())
    for metric in metric_objects:
        name = prometheus_name(metric.name)
        lines.append(f"# HELP {name} {escape_help(metric.name)}")
        if isinstance(metric, _metrics.Counter):
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {_fmt(metric.value())}")
        elif isinstance(metric, _metrics.Gauge):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(metric.value())}")
        elif isinstance(metric, _metrics.Histogram):
            summary = metric.summary()
            lines.append(f"# TYPE {name} summary")
            for q, value in sorted(metric.quantiles(_QUANTILES).items()):
                label = escape_label_value(f"{q:g}")
                lines.append(
                    f'{name}{{quantile="{label}"}} {_fmt(value)}'
                )
            lines.append(f"{name}_sum {_fmt(summary['sum'])}")
            lines.append(f"{name}_count {_fmt(summary['count'])}")
            for bound in ("min", "max"):
                if summary[bound] is not None:
                    lines.append(f"# TYPE {name}_{bound} gauge")
                    lines.append(
                        f"{name}_{bound} {_fmt(summary[bound])}"
                    )
    return "\n".join(lines) + "\n"


def escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline (the text format does
    not escape quotes there)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


# ---------------------------------------------------------------------------
# health view
# ---------------------------------------------------------------------------


def health_view() -> dict:
    """The /healthz document: pipeline alive / degraded / broken, with
    the latched gauges and the stuck-window attribution when a bounded
    settle expired."""
    degraded = bool(_metrics.gauge("pipeline.degraded").value())
    broken_gauge = bool(_metrics.gauge("pipeline.broken").value())
    stuck = _flight.RECORDER.last_broken
    broken = broken_gauge or stuck is not None
    status = "broken" if broken else ("degraded" if degraded else "ok")
    return {
        "status": status,
        "pipeline_alive": not broken,
        "degraded": degraded,
        "degraded_flushes": _metrics.counter(
            "pipeline.degraded_flushes"
        ).value(),
        "fault_retries": _metrics.counter("pipeline.fault_retries").value(),
        "blocks_committed": _metrics.counter(
            "pipeline.blocks_committed"
        ).value(),
        "rollbacks": _metrics.counter("pipeline.rollbacks").value(),
        "stuck_window": stuck,
        "flight_records": len(_flight.RECORDER),
    }


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    server_version = "ect-introspect/1"
    protocol_version = "HTTP/1.1"
    # bounded keep-alive idle: HTTP/1.1 clients (requests.Session on the
    # Beacon-API data plane) hold persistent connections, parking a
    # non-daemon handler thread in a blocking read between requests —
    # without a socket timeout, stop()'s server_close join would wait on
    # the CLIENT's goodwill. One second bounds the join; an idle-expired
    # connection just reconnects on its next request.
    timeout = 1

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr
        pass

    # -- plumbing ------------------------------------------------------------
    def _send(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, doc, status: int = 200) -> None:
        body = json.dumps(doc, sort_keys=True, indent=1).encode("utf-8")
        self._send(status, "application/json; charset=utf-8", body)

    def _query(self) -> dict:
        return parse_qs(urlparse(self.path).query)

    def _param(self, params: dict, key: str, default=None):
        values = params.get(key)
        return values[0] if values else default

    def _try_apps(self, method: str, route: str, params: dict, body) -> bool:
        """Route into a mounted app when one claims the path; apps
        return (status, JSON document) and never raise. An app claims
        with ``prefix`` (one string) or ``prefixes`` (several); the
        LONGEST matching prefix across every mounted app wins, so the
        pool plane's ``/eth/v1/beacon/pool/...`` routes past the read
        plane's broader ``/eth/`` claim regardless of mount order.
        False → no app claimed the route."""
        best = None  # (prefix length, app)
        for app in getattr(self.server, "apps", ()):
            prefixes = getattr(app, "prefixes", None) or (app.prefix,)
            for prefix in prefixes:
                if route.startswith(prefix) and (
                    best is None or len(prefix) > best[0]
                ):
                    best = (len(prefix), app)
        if best is None:
            return False
        status, doc = best[1].handle(method, route, params, body)
        self._send_json(doc, status=status)
        return True

    # -- routes --------------------------------------------------------------
    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        route = urlparse(self.path).path
        try:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            try:
                body = json.loads(raw) if raw else None
            except ValueError:
                self._send_json(
                    {"code": 400, "message": "request body is not JSON"},
                    status=400,
                )
                return
            if not self._try_apps("POST", route, self._query(), body):
                self._send_json(
                    {"error": f"no route POST {route}"}, status=404
                )
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        route = urlparse(self.path).path
        try:
            if route == "/metrics":
                self._send(
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    render_prometheus().encode("utf-8"),
                )
            elif route == "/healthz":
                view = health_view()
                self._send_json(
                    view, status=200 if view["pipeline_alive"] else 503
                )
            elif route == "/blocks":
                self._serve_blocks()
            elif route == "/device":
                params = self._query()
                try:
                    n = int(self._param(params, "n", "128"))
                except ValueError:
                    self._send_json({"error": "?n= must be an int"}, 400)
                    return
                self._send_json(_device.OBSERVATORY.snapshot(journal_n=n))
            elif route == "/memory":
                params = self._query()
                try:
                    n = int(self._param(params, "n", "12"))
                except ValueError:
                    self._send_json({"error": "?n= must be an int"}, 400)
                    return
                self._send_json(_memory.OBSERVATORY.snapshot(worst_n=n))
            elif route == "/trace":
                self._serve_trace()
            elif route == "/events":
                self._serve_events()
            elif route == "/":
                apps = getattr(self.server, "apps", ())
                self._send_json(
                    {
                        "service": "ethereum_consensus_tpu introspection",
                        "endpoints": [
                            "/metrics",
                            "/healthz",
                            "/blocks",
                            "/events",
                            "/device",
                            "/memory",
                            "/trace",
                        ]
                        + [app.prefix + "..." for app in apps],
                        "apps": [type(app).__name__ for app in apps],
                        "docs": "docs/OBSERVABILITY.md",
                    }
                )
            elif self._try_apps("GET", route, self._query(), None):
                pass
            else:
                self._send_json({"error": f"no route {route}"}, status=404)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response

    def _serve_blocks(self) -> None:
        params = self._query()
        recorder = _flight.RECORDER
        worst = self._param(params, "worst")
        outcome = self._param(params, "outcome")
        n = int(self._param(params, "n", "128"))
        try:
            if worst is not None:
                records = recorder.worst(n, field=worst)
            else:
                records = recorder.records()
                min_slot = self._param(params, "min_slot")
                max_slot = self._param(params, "max_slot")
                if min_slot is not None or max_slot is not None:
                    lo = int(min_slot) if min_slot is not None else 0
                    hi = (
                        int(max_slot)
                        if max_slot is not None
                        else (1 << 62)
                    )
                    records = [r for r in records if lo <= r.slot <= hi]
                if outcome is not None:
                    records = [
                        r
                        for r in records
                        if r.outcome == outcome or r.disposition == outcome
                    ]
                records = records[-n:]
        except ValueError as exc:
            self._send_json({"error": str(exc)}, status=400)
            return
        self._send_json(
            {
                "count": len(records),
                "recording": _flight.is_recording(),
                "capacity": recorder.capacity,
                "blocks": [r.to_dict() for r in records],
            }
        )

    def _serve_trace(self) -> None:
        """The causal-trace read side: bare → the slow-trace ring +
        recorder audit + per-histogram exemplar tables (the JSON home
        of exemplar evidence — /metrics stays strict text 0.0.4);
        ``?id=`` → one trace assembled across the three evidence planes
        (span tree, flight lineage, device events)."""
        params = self._query()
        recorder = _spans.RECORDER
        raw_id = self._param(params, "id")
        if raw_id is None:
            exemplars = {}
            for metric in _metrics.registered_metrics():
                if isinstance(metric, _metrics.Histogram):
                    table = metric.exemplars()
                    if table:
                        exemplars[metric.name] = table
            self._send_json(
                {
                    "recording": recorder.enabled,
                    "slow_traces": recorder.slow_traces(),
                    "audit": recorder.audit(),
                    "exemplars": exemplars,
                }
            )
            return
        try:
            trace_id = int(raw_id, 0)
        except ValueError:
            # the standard error envelope (code+message), so api/client.py
            # surfaces the status instead of a code-0 ApiError
            self._send_json(
                {"code": 400, "message": "?id= must be an int"}, 400
            )
            return
        tree = recorder.trace_tree(trace_id)
        if not tree["spans"]:
            self._send_json(
                {
                    "code": 404,
                    "message": f"no spans recorded for trace {trace_id}",
                    "trace_id": trace_id,
                },
                status=404,
            )
            return
        # flight lineage settled under this trace (admission→settle
        # outcome records), then the device span-plane evidence that
        # landed inside the trace's time window — routing decisions and
        # transfers share the span clock, so the join is a range scan.
        # Span t0_s values are recorder-relative, so absolute
        # perf_counter stamps rebase onto recorder.origin first; route
        # decisions are instants in the EVENTS ring, so both rings scan.
        tree["lineage"] = [
            r.to_dict() for r in _flight.RECORDER.by_trace(trace_id)
        ]
        origin = recorder.origin
        t_lo = tree["t0_s"]
        t_hi = t_lo + tree["duration_s"]
        device_events: list = []
        for rec in recorder.records():
            if not rec.name.startswith("device."):
                continue
            t0_s = rec.t0 - origin
            if t0_s < t_lo or t0_s > t_hi:
                continue
            device_events.append(
                {
                    "name": rec.name,
                    "t0_s": t0_s,
                    "duration_s": rec.duration_s,
                    "fields": rec.fields,
                }
            )
        for rec in recorder.event_records():
            if not rec.name.startswith("device."):
                continue
            t0_s = rec.ts - origin
            if t0_s < t_lo or t0_s > t_hi:
                continue
            device_events.append(
                {"name": rec.name, "t0_s": t0_s, "fields": rec.fields}
            )
        device_events.sort(key=lambda e: e["t0_s"])
        # bounded response, never a silent cap: the count survives
        tree["device_count"] = len(device_events)
        tree["device"] = device_events[:256]
        self._send_json(tree)

    def _serve_events(self) -> None:
        params = self._query()
        kinds_param = self._param(params, "kinds")
        kinds = (
            tuple(k.strip() for k in kinds_param.split(",") if k.strip())
            if kinds_param
            else _SSE_DEFAULT_KINDS
        )
        inbox: queue.Queue = queue.Queue(maxsize=1024)

        def push(kind, payload):
            if kind not in kinds:
                return
            try:
                inbox.put_nowait((kind, payload))
            except queue.Full:
                # a slow client drops events; it must never backpressure
                # the pipeline through the hook
                _metrics.counter("flight.sse_dropped_events").inc()

        _flight.HOOK.subscribe(push)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(b": ect introspection event stream\n\n")
            self.wfile.flush()
            keepalive_s = float(
                getattr(
                    self.server, "sse_keepalive_s", DEFAULT_SSE_KEEPALIVE_S
                )
            )
            import time as _time

            last_write = _time.monotonic()
            while not getattr(self.server, "stopping", False):
                try:
                    kind, payload = inbox.get(timeout=0.25)
                except queue.Empty:
                    # keepalive comment on the SSE interval (not every
                    # poll): an idle subscriber behind a proxy or LB
                    # keeps its stream alive, without the old
                    # 4-writes-per-second chatter; the 0.25 s poll still
                    # bounds stop() and dead-client discovery
                    now = _time.monotonic()
                    if now - last_write >= keepalive_s:
                        self.wfile.write(b": ping\n\n")
                        self.wfile.flush()
                        last_write = now
                    continue
                last_write = _time.monotonic()
                if isinstance(payload, _flight.BlockLineage):
                    payload = payload.to_dict()
                # default=repr: an exotic payload value (a state handle
                # would only appear here through a future event kind)
                # degrades to its repr instead of killing the stream
                data = json.dumps(payload, sort_keys=True, default=repr)
                self.wfile.write(
                    f"event: {kind}\ndata: {data}\n\n".encode("utf-8")
                )
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            _flight.HOOK.unsubscribe(push)


class IntrospectionServer:
    """Start/stoppable introspection endpoint over the process-wide
    telemetry state.

    Usage::

        server = IntrospectionServer(port=0).start()   # 0 = ephemeral
        ... replay ...
        server.stop()

    or as a context manager. ``start`` also begins a flight recording
    (``flight.start()``) unless told not to, so ``/blocks`` is live the
    moment the server is."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 sse_keepalive_s: float = DEFAULT_SSE_KEEPALIVE_S):
        self._lock = threading.Lock()
        self._host = host
        self._requested_port = port
        self._sse_keepalive_s = sse_keepalive_s
        self._httpd = None
        self._pool = None
        self._flight_started = False
        self._apps: tuple = ()

    def mount(self, app) -> "IntrospectionServer":
        """Mount a data-plane app (``.prefix`` + ``.handle(method, path,
        params, body) → (status, doc)``) — requests under the prefix
        route into it (the Beacon-API read plane, serving/handlers.py).
        Rebinds an immutable tuple, so handler threads iterate a
        consistent snapshot lock-free."""
        with self._lock:
            self._apps = self._apps + (app,)
            if self._httpd is not None:
                self._httpd.apps = self._apps
        return self

    # -- lifecycle -----------------------------------------------------------
    def start(self, start_flight: bool = True) -> "IntrospectionServer":
        with self._lock:
            if self._httpd is not None:
                return self
            httpd = ThreadingHTTPServer(
                (self._host, self._requested_port), _Handler
            )
            # non-daemon handler threads + block_on_close: server_close()
            # JOINS every in-flight handler, so stop() returns only after
            # SSE subscribers have detached from the commit hook (their
            # loops exit within one `stopping` poll, so the join is
            # bounded at ~0.25s)
            httpd.daemon_threads = False
            httpd.stopping = False
            httpd.apps = self._apps
            httpd.sse_keepalive_s = self._sse_keepalive_s
            pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="introspection-accept"
            )
            pool.submit(httpd.serve_forever, 0.1)
            self._httpd = httpd
            self._pool = pool
            self._flight_started = bool(
                start_flight and not _flight.is_recording()
            )
        if self._flight_started:
            _flight.start()
        return self

    def stop(self) -> None:
        with self._lock:
            httpd, pool = self._httpd, self._pool
            flight_started = self._flight_started
            self._httpd = None
            self._pool = None
            self._flight_started = False
        if httpd is None:
            return
        httpd.stopping = True  # SSE loops exit at their next poll
        httpd.shutdown()
        httpd.server_close()
        pool.shutdown(wait=False)
        if flight_started:
            _flight.stop()

    def __enter__(self) -> "IntrospectionServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    # -- addressing ----------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._httpd is not None

    @property
    def port(self) -> int:
        httpd = self._httpd
        if httpd is None:
            raise RuntimeError("server is not running")
        return httpd.server_address[1]

    def url(self, path: str = "/") -> str:
        return f"http://{self._host}:{self.port}{path}"

    def __repr__(self) -> str:
        if self.running:
            return f"IntrospectionServer(on {self.url()})"
        return "IntrospectionServer(stopped)"
