"""Memory & bandwidth observatory: attribute every resident byte and
every byte moved on the million-validator hot paths.

PRs 4/7/10 instrumented seconds (spans), lineage (flight), and the
device side (compile/transfer/routing ledgers) — memory was the last
black box: the ``EC_BENCH_XL=1`` 2^22 epoch stretch peaks at ~18 GB RSS
and nothing in the telemetry stack could say which structure owns it or
how many bytes each epoch phase actually moves. This module closes that
with one process-wide ``MemoryObservatory`` behind the same one-read
zero-overhead ``active`` guard as the span recorder and the device
observatory, recording THREE ledgers:

* a **resident-set census** — a registry of the repo's bounded and
  unbounded byte owners, probed ON DEMAND (never sampled in the hot
  path): the SSZ list-resident caches (column arrays, ``_root_cache``
  roots + Bitlist ``bitpack`` rows, pack/tree memos and their retained
  raw buffers — ``ssz/core.py``), the committee mask bundles
  (``models/committees.py``), the phase0 shuffle-cache slots, HeadStore
  snapshots + frozen column bundles (``serving/headstore.py``), the
  flight ring, the pool's bitfield matrices (``pool/store.py``), and
  the jit executable cache (entry counts — XLA does not expose
  executable bytes). Exposed as ``census()`` / ``worst(n)``, as
  ``memory.owner.{name}.bytes`` gauges, and on the ``/memory``
  endpoint. The soak's ``LeakSentinel`` consumes THIS census
  (``soak/sentinel.py watch_owner``) instead of keeping a second
  implementation.

* a **phase RSS/allocation ledger** — every ``transition.*`` /
  ``epoch_vector.*`` / ``committees.mask*`` span (through the
  ``utils/trace.py`` facade) and every explicit ``memory.phase(...)``
  bracket records the RSS delta across its body plus the process
  high-water-mark movement, so a bench config's ``mem`` evidence block
  can decompose a peak into named phases ("cold state build retained
  13.9 GB; the warm epoch's transient working set peaked 2.3 GB above
  its floor") instead of one scary number. With ``ECT_TRACEMALLOC=1``
  the ledger additionally records tracemalloc traced-bytes deltas per
  phase and ``top_sites(n)`` serves the top allocation sites (opt-in:
  tracemalloc roughly doubles allocation cost).

* a **bandwidth ledger** — byte counters at the repo's bulk-copy
  chokepoints, aggregated per call site exactly like the device
  observatory's transfer ledger: ``ssz.bulk_store`` adoption splices,
  ``ssz.packed_splice`` dirty-group re-serialization,
  ``ssz.column_serialize`` wire-width ``tobytes()`` packing,
  ``ssz.state_copy`` structural list copies (pointer-width bytes —
  element payloads are shared structurally), the engine's
  ``pipeline.snapshot_copy`` publication copies, and the mesh
  ``parallel.pad_to_mesh`` staging copies. Sites with a timed window
  render as complete events on a ``memory`` VIRTUAL lane in the
  Chrome trace (the device-lane idiom), so a profile shows bytes-moved
  next to seconds-spent.

Cost discipline (the spans/device contract): ``OBSERVATORY.active`` is
a plain bool read — every instrumented call site checks it FIRST and
pays nothing else while the observatory is off (guarded by the
overhead test in tests/test_memory_observatory.py). RSS reads go
through ``/proc/self/statm`` (one short read, ~10 µs) with the
``getrusage`` peak beside it; census probes run only when census() is
called. Everything here is stdlib-only; numpy objects are only ever
*measured* (``nbytes``), never created.

Lock discipline (speclint-checked): every write to the observatory's
shared structures holds ``self._lock``; the hot ``active`` read and
the metrics-registry increments (locked per metric) stay outside it.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from contextlib import contextmanager

from .. import _env
from . import metrics as _metrics
from . import spans as _spans

__all__ = [
    "MemoryObservatory",
    "OBSERVATORY",
    "TRACKED_LISTS",
    "PHASE_PREFIXES",
    "rss_mb",
    "peak_rss_mb",
    "copy",
    "phase",
    "register_owner",
    "census",
    "worst",
    "owner_entries",
    "owner_bytes",
    "start",
    "stop",
    "is_observing",
    "observing",
    "snapshot",
    "top_sites",
]

_MEMORY_LANE = "memory"
_TRACEMALLOC_ENV = "ECT_TRACEMALLOC"

# span names the trace facade brackets into the phase ledger while the
# observatory is active (the transition phase split + the epoch engine's
# stage spans + the committee-mask build); explicit memory.phase(...)
# brackets take any name
PHASE_PREFIXES = ("transition.", "epoch_vector.", "committees.mask", "mem.")

# the SSZ list census: ssz/core.py's CachedRootList.__init__ adds every
# new instance here while tracking is armed (one module-attribute read +
# None check on the off path — the list-creation hot path pays nothing
# else). A WeakValueDictionary keyed by id() because lists are
# unhashable (a dead entry's id may be reused — the weak callback
# removed the old entry first, so the slot just rebinds). None =
# tracking off; armed by start(), left in place by stop() so the census
# stays readable after an observation ends.
TRACKED_LISTS: "weakref.WeakValueDictionary | None" = None

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# guards the one-time arming of TRACKED_LISTS (module global): writes
# hold this module lock; the hot read in CachedRootList.__init__ stays
# lock-free (a torn read can only see None or the armed dict)
_TRACK_LOCK = threading.Lock()


def rss_mb() -> float:
    """Current resident set in MiB: ``/proc/self/statm`` (one short
    read — fast enough to bracket phase spans), ``getrusage`` peak as
    the degraded non-Linux fallback."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE / (1024.0 * 1024.0)
    except (OSError, IndexError, ValueError):
        return peak_rss_mb()


def peak_rss_mb() -> float:
    """Process high-water mark in MiB (``ru_maxrss`` — monotonic for
    the process lifetime)."""
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _nbytes(obj) -> int:
    """Resident bytes of a measurable buffer: numpy ``nbytes``,
    ``len()`` for bytes-likes, 0 otherwise."""
    n = getattr(obj, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    return 0


class MemoryObservatory:
    """Process-wide memory ledgers; one instance (``OBSERVATORY``)
    serves the whole process, started/stopped like the span recorder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owners: dict = {}        # name -> probe() -> (bytes, entries)
        self._phases: dict = {}        # name -> aggregate dict
        self._copies: dict = {}        # site -> {count, bytes}
        self._peak_phase: "str | None" = None  # last bracket that raised peak
        self._tracemalloc_started = False
        self.active = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Begin a fresh observation: drop the phase/bandwidth ledgers,
        arm the SSZ list census, and (``ECT_TRACEMALLOC=1``) start
        tracemalloc. Registered owners persist — they describe where
        structures LIVE, not one observation."""
        global TRACKED_LISTS
        with _TRACK_LOCK:
            if TRACKED_LISTS is None:
                TRACKED_LISTS = weakref.WeakValueDictionary()
        with self._lock:
            self._phases.clear()
            self._copies.clear()
            self._peak_phase = None
            if _env.flag_on(_TRACEMALLOC_ENV):
                import tracemalloc

                if not tracemalloc.is_tracing():
                    tracemalloc.start()
                    self._tracemalloc_started = True
            self.active = True

    def stop(self) -> None:
        """Stop observing (ledgers and the census stay readable; a
        tracemalloc WE started stops with us)."""
        with self._lock:
            if self._tracemalloc_started:
                import tracemalloc

                tracemalloc.stop()
                self._tracemalloc_started = False
            self.active = False

    # -- resident-set census -------------------------------------------------
    def register_owner(self, name: str, probe) -> None:
        """Register a byte owner: ``probe()`` returns ``(bytes,
        entries)``. Probes run only on census() — never in any hot
        path — and may raise (reported as an errored owner, which the
        sentinel's bound check treats as a trip, never a silent pass)."""
        with self._lock:
            self._owners[name] = probe

    def unregister_owner(self, name: str) -> None:
        with self._lock:
            self._owners.pop(name, None)

    def census(self) -> dict:
        """``{owner: {"bytes": int, "entries": int}}`` over every
        registered owner plus the SSZ list walk (one pass distributed
        over its per-structure owners), probed now. Sets the
        ``memory.owner.{name}.bytes`` gauges as a side effect."""
        with self._lock:
            probes = list(self._owners.items())
        out = dict(_ssz_census())
        for name, probe in probes:
            try:
                nbytes, entries = probe()
                out[name] = {"bytes": int(nbytes), "entries": int(entries)}
            except Exception as exc:  # noqa: BLE001 — a probe must not kill a census
                out[name] = {"bytes": -1, "entries": -1,
                             "error": repr(exc)[:160]}
        for name, rec in out.items():
            _metrics.gauge(f"memory.owner.{name}.bytes").set(rec["bytes"])
            _metrics.gauge(f"memory.owner.{name}.entries").set(rec["entries"])
        return out

    def worst(self, n: int = 8, census_doc: "dict | None" = None) -> list:
        """The attribution table: top-``n`` owners by resident bytes,
        ``[{"owner", "bytes", "mb", "entries"}, ...]`` largest first.
        Pass an existing ``census()`` result to avoid a second probe
        walk."""
        if census_doc is None:
            census_doc = self.census()
        rows = [
            {
                "owner": name,
                "bytes": rec["bytes"],
                "mb": round(rec["bytes"] / (1024.0 * 1024.0), 1),
                "entries": rec["entries"],
            }
            for name, rec in census_doc.items()
            if rec["bytes"] > 0
        ]
        rows.sort(key=lambda r: r["bytes"], reverse=True)
        return rows[:n]

    def owner_entries(self, name: str) -> int:
        """One owner's entry count (the LeakSentinel's census read);
        -1 on an unknown owner or a failing probe — the sentinel's
        bound check fails closed on negatives."""
        with self._lock:
            probe = self._owners.get(name)
        if probe is None:
            rec = _ssz_census().get(name)
            return int(rec["entries"]) if rec else -1
        try:
            _nb, entries = probe()
            return int(entries)
        except Exception:  # noqa: BLE001 — fail closed, never raise into a gate
            return -1

    def owner_bytes(self, name: str) -> int:
        with self._lock:
            probe = self._owners.get(name)
        if probe is None:
            rec = _ssz_census().get(name)
            return int(rec["bytes"]) if rec else -1
        try:
            nbytes, _entries = probe()
            return int(nbytes)
        except Exception:  # noqa: BLE001
            return -1

    # -- phase RSS ledger ----------------------------------------------------
    def phase_begin(self, name: str) -> "tuple | None":
        """Open one phase bracket; returns the begin token the matching
        ``phase_end`` consumes, or None when ``name`` is not a phase
        span. Caller pre-guards with ``active``."""
        if not name.startswith(PHASE_PREFIXES):
            return None
        traced = 0
        if self._tracemalloc_started:
            import tracemalloc

            traced = tracemalloc.get_traced_memory()[0]
        return (rss_mb(), peak_rss_mb(), traced, time.perf_counter())

    def phase_end(self, name: str, token: tuple) -> None:
        rss0, peak0, traced0, t0 = token
        rss1 = rss_mb()
        peak1 = peak_rss_mb()
        traced_delta = 0
        if self._tracemalloc_started:
            import tracemalloc

            traced_delta = tracemalloc.get_traced_memory()[0] - traced0
        delta = rss1 - rss0
        # the bracket's transient headroom: only meaningful when the
        # process high-water mark MOVED inside this bracket (a stale
        # peak from an earlier, bigger phase must not be attributed
        # here) — then the watermark moment was inside this bracket and
        # sat (peak1 - rss0) above the bracket's floor, of which
        # max(0, delta) was retained
        transient = 0.0
        if peak1 > peak0:
            transient = max(0.0, (peak1 - rss0) - max(0.0, delta))
        with self._lock:
            agg = self._phases.get(name)
            if agg is None:
                agg = self._phases[name] = {
                    "count": 0,
                    "rss_delta_mb": 0.0,
                    "rss_end_mb": 0.0,
                    "peak_mb": 0.0,
                    "peak_growth_mb": 0.0,
                    "transient_mb": 0.0,
                    "seconds": 0.0,
                    "traced_delta_mb": 0.0,
                }
            agg["count"] += 1
            agg["rss_delta_mb"] += delta
            agg["rss_end_mb"] = rss1
            agg["peak_mb"] = max(agg["peak_mb"], peak1)
            agg["peak_growth_mb"] += max(0.0, peak1 - peak0)
            agg["transient_mb"] = max(agg["transient_mb"], transient)
            agg["seconds"] += time.perf_counter() - t0
            agg["traced_delta_mb"] += traced_delta / (1024.0 * 1024.0)
            if peak1 > peak0:
                self._peak_phase = name
        rec = _spans.RECORDER
        if rec.enabled:
            rec.add_instant(
                "memory.phase",
                time.perf_counter(),
                {"phase": name, "rss_mb": round(rss1, 1),
                 "delta_mb": round(delta, 2)},
                lane=rec.named_lane(_MEMORY_LANE),
            )

    def phase_ledger(self) -> dict:
        """Per-phase aggregates (consistent copy), rounded for JSON."""
        with self._lock:
            out = {
                name: {
                    key: (round(value, 3) if isinstance(value, float)
                          else value)
                    for key, value in agg.items()
                }
                for name, agg in self._phases.items()
            }
        return out

    def peak_phase(self) -> "str | None":
        """The last phase bracket that raised the process high-water
        mark — the peak's home."""
        with self._lock:
            return self._peak_phase

    # -- bandwidth ledger ----------------------------------------------------
    def record_copy(self, site: str, nbytes: int,
                    t0: "float | None" = None,
                    t1: "float | None" = None) -> None:
        """One bulk copy of ``nbytes`` at ``site``. Call sites
        pre-guard with ``active``. A timed window (t0/t1) additionally
        renders on the Chrome-trace ``memory`` lane."""
        with self._lock:
            agg = self._copies.get(site)
            if agg is None:
                agg = self._copies[site] = {"count": 0, "bytes": 0}
            agg["count"] += 1
            agg["bytes"] += nbytes
        _metrics.counter("memory.copies").inc()
        _metrics.counter("memory.copy_bytes").inc(nbytes)
        if t0 is not None and t1 is not None:
            rec = _spans.RECORDER
            if rec.enabled:
                rec.add_complete(
                    "memory.copy",
                    t0,
                    t1,
                    {"site": site, "bytes": nbytes},
                    lane=rec.named_lane(_MEMORY_LANE),
                )

    def copy_summary(self) -> dict:
        """Per-site copy aggregates plus process totals (the transfer-
        ledger shape)."""
        with self._lock:
            sites = {site: dict(agg) for site, agg in self._copies.items()}
        totals = {"count": 0, "bytes": 0}
        for agg in sites.values():
            totals["count"] += agg["count"]
            totals["bytes"] += agg["bytes"]
        return {"sites": sites, "totals": totals}

    # -- the /memory document ------------------------------------------------
    def snapshot(self, worst_n: int = 12) -> dict:
        tracked = TRACKED_LISTS
        census_doc = self.census()
        doc = {
            "observing": self.active,
            "rss_mb": round(rss_mb(), 1),
            "peak_rss_mb": round(peak_rss_mb(), 1),
            "tracked_lists": len(tracked) if tracked is not None else None,
            "census": census_doc,
            "worst": self.worst(worst_n, census_doc),
            "phase_ledger": self.phase_ledger(),
            "peak_phase": self.peak_phase(),
            "bandwidth": self.copy_summary(),
            "tracemalloc": {"tracing": self._tracemalloc_started},
        }
        if self._tracemalloc_started:
            doc["tracemalloc"]["top_sites"] = top_sites(8)
        return doc


OBSERVATORY = MemoryObservatory()


# ---------------------------------------------------------------------------
# the SSZ list walk: one pass over the tracked CachedRootList instances,
# distributed over per-structure owners. Shared buffers (column arrays /
# memos travel structurally across state copies) dedup by id().
# ---------------------------------------------------------------------------

_SSZ_OWNERS = (
    "ssz.columns",
    "ssz.bitpack",
    "ssz.root_cache",
    "ssz.pack_tree",
    "ssz.tree_memo",
    "ssz.pack_memo",
)


def _tree_bytes(tree) -> int:
    """Resident bytes of an IncrementalPaddedTree: its stored levels."""
    levels = getattr(tree, "levels", None)
    if not isinstance(levels, list):
        return 0
    return sum(len(level) for level in levels)


def _ssz_census() -> dict:
    """The per-structure byte census over every tracked list (see
    TRACKED_LISTS). Zero rows (not an error) while tracking has never
    been armed."""
    out = {name: {"bytes": 0, "entries": 0} for name in _SSZ_OWNERS}
    tracked = TRACKED_LISTS
    if tracked is None:
        return out
    lists = [ref() for ref in tracked.valuerefs()]  # snapshot, GC-safe
    seen: set = set()

    def add(owner: str, obj, nbytes: "int | None" = None) -> None:
        key = id(obj)
        if key in seen:
            return
        seen.add(key)
        rec = out[owner]
        rec["bytes"] += _nbytes(obj) if nbytes is None else nbytes
        rec["entries"] += 1

    for lst in lists:
        if lst is None:
            continue
        cc = getattr(lst, "_col_cache", None)
        if isinstance(cc, tuple):
            if cc[0] == "validators" and isinstance(cc[1], dict):
                for arr in cc[1].values():
                    add("ssz.columns", arr)
            elif cc[0] == "list":
                add("ssz.columns", cc[1])
        rc = getattr(lst, "_root_cache", None)
        if isinstance(rc, dict):
            for key, value in rc.items():
                if key == "bitpack":
                    add("ssz.bitpack", value)
                elif isinstance(value, tuple):
                    # ("tree", elem, limit) -> (chunks, root)
                    for part in value:
                        if isinstance(part, (bytes, bytearray)):
                            add("ssz.root_cache", part)
                elif isinstance(value, (bytes, bytearray)):
                    add("ssz.root_cache", value)
        pt = getattr(lst, "_pack_tree", None)
        if isinstance(pt, list) and len(pt) >= 3:
            add("ssz.pack_tree", pt[1])
            add("ssz.pack_tree", pt[2], _tree_bytes(pt[2]))
        tm = getattr(lst, "_tree_memo", None)
        if isinstance(tm, (list, tuple)) and len(tm) >= 3:
            add("ssz.tree_memo", tm[1])
            add("ssz.tree_memo", tm[2], _tree_bytes(tm[2]))
        pm = getattr(lst, "_pack_memo", None)
        if isinstance(pm, tuple):
            for part in pm[1:]:
                if isinstance(part, (bytes, bytearray)):
                    add("ssz.pack_memo", part)
    return out


# ---------------------------------------------------------------------------
# built-in owners: probes over the process-wide structures the ROADMAP's
# 18-GB question names. Registered at import (probes are lazy — they
# import their subject module only when census() runs, so a process that
# never serves or pools pays nothing).
# ---------------------------------------------------------------------------


def _flight_ring_probe() -> "tuple[int, int]":
    import sys

    from . import flight as _flight

    records = _flight.RECORDER.records()
    nbytes = 0
    for rec in records[:64]:  # bounded size sample; extrapolated below
        nbytes += sys.getsizeof(rec)
        for slot_name in getattr(type(rec), "__slots__", ()):
            value = getattr(rec, slot_name, None)
            if isinstance(value, (str, bytes, dict, list, tuple)):
                nbytes += sys.getsizeof(value)
    if records:
        nbytes = nbytes * len(records) // min(len(records), 64)
    return nbytes, len(records)


def _headstore_probe() -> "tuple[int, int]":
    from ..serving import headstore as _hs

    nbytes = 0
    entries = 0
    for store in _hs.registered_stores():
        b, e = store.memory_census()
        nbytes += b
        entries += e
    return nbytes, entries


def _pool_probe() -> "tuple[int, int]":
    from ..pool import store as _pool_store

    nbytes = 0
    entries = 0
    for pool in list(_pool_store.registered_pools()):
        b, e = pool.memory_census()
        nbytes += b
        entries += e
    return nbytes, entries


def _shuffle_cache_probe() -> "tuple[int, int]":
    from ..models.phase0 import helpers as _h

    nbytes = 0
    entries = 0
    for entry in list(_h._SHUFFLE_CACHE.values()):
        entries += 1
        for part in entry:
            n = _nbytes(part)
            if n:
                nbytes += n
            elif isinstance(part, (list, tuple)):
                nbytes += len(part) * 8  # pointer-width estimate
    return nbytes, entries


def _mask_bundle_probe() -> "tuple[int, int]":
    from ..models import committees as _committees

    nbytes = 0
    entries = 0
    seen: set = set()
    for bundle in list(_committees.registered_bundles()):
        entries += 1
        for field in ("source", "target", "head", "covered",
                      "inclusion_delay", "inclusion_proposer"):
            arr = getattr(bundle, field, None)
            if arr is not None and id(arr) not in seen:
                seen.add(id(arr))
                nbytes += _nbytes(arr)
    return nbytes, entries


def _jit_cache_probe() -> "tuple[int, int]":
    """Entry counts only: XLA does not expose executable byte sizes
    (the census delegates to ``epoch_vector.kernel_cache_census``).
    ``sys.modules`` gate: a process that never built the kernels must
    not import jax from a census."""
    import sys

    ev = sys.modules.get("ethereum_consensus_tpu.models.epoch_vector")
    if ev is None:
        return 0, 0
    return ev.kernel_cache_census()


_BUILTIN_OWNERS = (
    ("flight.ring", _flight_ring_probe),
    ("serving.snapshots", _headstore_probe),
    ("pool.store", _pool_probe),
    ("phase0.shuffle_cache", _shuffle_cache_probe),
    ("committees.mask_bundles", _mask_bundle_probe),
    ("epoch_vector.jit_kernels", _jit_cache_probe),
)

for _name, _probe in _BUILTIN_OWNERS:
    OBSERVATORY.register_owner(_name, _probe)
del _name, _probe


# ---------------------------------------------------------------------------
# module-level conveniences (the device.py idiom)
# ---------------------------------------------------------------------------


def copy(site: str, nbytes: int, t0: "float | None" = None,
         t1: "float | None" = None) -> None:
    """Record one bulk copy (no-op while not observing; hot call sites
    pre-guard with ``OBSERVATORY.active`` so the off path is a single
    bool read)."""
    obs = OBSERVATORY
    if not obs.active:
        return
    obs.record_copy(site, nbytes, t0, t1)


@contextmanager
def phase(name: str):
    """Explicitly bracket a phase into the RSS ledger (the bench's
    state-build/cold/warm brackets — names outside ``PHASE_PREFIXES``
    should use the ``mem.`` prefix so the facade filter admits them)."""
    obs = OBSERVATORY
    if not obs.active:
        yield
        return
    token = obs.phase_begin(name)
    try:
        yield
    finally:
        if token is not None:
            obs.phase_end(name, token)


def register_owner(name: str, probe) -> None:
    OBSERVATORY.register_owner(name, probe)


def census() -> dict:
    return OBSERVATORY.census()


def worst(n: int = 8) -> list:
    return OBSERVATORY.worst(n)


def owner_entries(name: str) -> int:
    return OBSERVATORY.owner_entries(name)


def owner_bytes(name: str) -> int:
    return OBSERVATORY.owner_bytes(name)


def top_sites(n: int = 8) -> list:
    """tracemalloc's top allocation sites (grouped by file) while
    tracing — empty when tracing is off."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        return []
    stats = tracemalloc.take_snapshot().statistics("filename")[:n]
    return [
        {
            "site": str(stat.traceback),
            "bytes": int(stat.size),
            "mb": round(stat.size / (1024.0 * 1024.0), 2),
            "count": int(stat.count),
        }
        for stat in stats
    ]


def start() -> MemoryObservatory:
    OBSERVATORY.start()
    return OBSERVATORY


def stop() -> None:
    OBSERVATORY.stop()


def is_observing() -> bool:
    return OBSERVATORY.active


@contextmanager
def observing():
    """Observe for the duration of the block; yields ``OBSERVATORY``."""
    start()
    try:
        yield OBSERVATORY
    finally:
        stop()


def snapshot(worst_n: int = 12) -> dict:
    return OBSERVATORY.snapshot(worst_n)
