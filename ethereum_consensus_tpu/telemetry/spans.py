"""Structured span recorder: thread-aware ring buffer + Chrome-trace export.

The tracing facade (``utils/trace.py``) stays the only API call sites
use; this module is the recording sink behind it. When recording is off
(the default) the facade never calls in here beyond one attribute read,
so the disabled path costs nothing measurable (guarded by
tests/test_telemetry.py's overhead test).

When recording is on, every ``trace.span`` exit appends one fixed-size
record — name, thread lane, parent span, start/end ``perf_counter``
stamps, the call site's fields, the error repr if the body raised — into
a bounded ``deque`` (oldest spans drop first; spans-in-progress live
only on a per-thread stack). ``chrome_trace()`` renders the buffer as
Chrome trace-event JSON (the ``{"traceEvents": [...]}`` flavor), loadable
in Perfetto / ``chrome://tracing``: each recording thread becomes one
``tid`` lane with its Python thread name as metadata, spans are ``"X"``
complete events in microseconds, point events are ``"i"`` instants. A
pipelined replay therefore renders stage A (the submitting thread) and
the background verifier as separate tracks, with flush dispatch/settle/
verify windows and rollbacks visible.

Thread lanes are small sequential ints (0 = first thread to record, in
practice the main thread) rather than raw ``threading.get_ident()``
values, so the Perfetto track list stays readable; the real ident is
kept in the thread-name metadata.

Besides thread lanes there are **named virtual lanes**
(``named_lane``): tid tracks that belong to no Python thread —
the device observatory (``telemetry/device.py``) renders XLA compiles
and host<->device transfers on a dedicated ``device`` track alongside
the pipeline/verifier thread tracks, via ``add_complete``/
``add_instant`` (pre-timed records appended without touching any
thread's span stack).

Lock discipline (speclint-checked): every write to the recorder's shared
structures holds ``self._lock``; the hot ``enabled`` read and the
per-thread span stack (``threading.local``) stay lock-free.

**Causal trace plane.** Spans only parent within a thread (the TLS
stack), so causality used to die at every cross-thread handoff — pool
admission → flush-window dispatch → verify lane → settle. A
``TraceContext`` is the explicit handoff token across those seams:
``SpanRecorder.context()`` captures the current span as
``(trace_id, span_id, lane, ts)``, the receiving thread brackets its
work in ``adopt(ctx)``, and every top-of-stack span begun under an
adopted context parents to ``ctx.span_id`` and inherits
``ctx.trace_id`` — one flush window becomes one connected tree no
matter how many threads it crossed. A span with no parent and no
adopted context roots its own trace (``trace_id == span_id``).
Cross-lane adoptions additionally record a flow source, rendered by
``chrome_trace()`` as Chrome flow events (``ph:"s"``/``"f"`` arrows
across ``tid`` lanes in Perfetto). The ring drops oldest records when
full as before, but no longer silently: ``dropped`` counts evictions
and mirrors to the ``spans.dropped`` counter. Completed traces noted
via ``note_trace`` feed a bounded worst-N slow-trace ring — the
``/trace`` endpoint's index (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = [
    "SpanRecord",
    "SpanRecorder",
    "TraceContext",
    "RECORDER",
    "DEFAULT_CAPACITY",
    "SLOW_TRACE_RING",
    "is_recording",
    "start_recording",
    "stop_recording",
    "recording",
    "write_chrome_trace",
]

DEFAULT_CAPACITY = 1 << 16

# worst-N slow-trace ring size (completed traces, by duration)
SLOW_TRACE_RING = 32


class TraceContext:
    """Immutable cross-thread handoff token: ``trace_id`` names the
    causal tree, ``span_id`` the parent span the receiving side should
    link under, ``lane``/``ts`` the handoff origin (the flow-arrow
    source in the Chrome trace). Captured with ``context()`` on the
    sending thread, passed explicitly (a ticket field, a closure arg —
    never ambient), adopted with ``adopt(ctx)`` on the receiving
    thread."""

    __slots__ = ("trace_id", "span_id", "lane", "ts")

    def __init__(self, trace_id: int, span_id: int, lane: int, ts: float):
        self.trace_id = trace_id
        self.span_id = span_id
        self.lane = lane
        self.ts = ts

    def __repr__(self) -> str:
        return (
            f"TraceContext(trace={self.trace_id}, span={self.span_id}, "
            f"lane={self.lane})"
        )


class SpanRecord:
    """One completed span (or, transiently, one in progress on its
    thread's stack). ``parent_id`` is 0 for top-level spans; parents are
    resolved per thread at begin time, so cross-thread work (the
    verifier) starts its own tree."""

    __slots__ = (
        "span_id",
        "parent_id",
        "trace_id",
        "name",
        "lane",
        "t0",
        "t1",
        "fields",
        "error",
        "flow_src",
    )

    def __init__(self, span_id: int, parent_id: int, name: str, lane: int,
                 t0: float, fields: dict, trace_id: int = 0):
        self.span_id = span_id
        self.parent_id = parent_id
        # the causal tree this span belongs to: its own span_id when it
        # roots a fresh trace, the adopted/inherited trace_id otherwise
        self.trace_id = trace_id or span_id
        self.name = name
        self.lane = lane
        self.t0 = t0
        self.t1 = t0
        self.fields = fields
        self.error = None
        # (src_span_id, src_lane, src_ts) when this span was begun under
        # a context adopted from another lane — the flow-arrow source
        self.flow_src = None

    @property
    def duration_s(self) -> float:
        return max(0.0, self.t1 - self.t0)


class _EventRecord:
    __slots__ = ("name", "lane", "ts", "fields")

    def __init__(self, name: str, lane: int, ts: float, fields: dict):
        self.name = name
        self.lane = lane
        self.ts = ts
        self.fields = fields


def _json_safe(value):
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    return str(value)


class SpanRecorder:
    """In-process ring-buffer recorder; one module-level instance
    (``RECORDER``) serves the whole process."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._spans: deque = deque(maxlen=capacity)
        self._events: deque = deque(maxlen=capacity)
        self._lanes: dict = {}        # thread ident -> small lane int
        self._lane_names: dict = {}   # lane int -> thread name
        self._tls = threading.local()
        self._ids = itertools.count(1)
        self._t0 = 0.0                # perf_counter origin of the recording
        self._wall0 = 0.0             # wall-clock at start (metadata only)
        self._slow: list = []         # worst-N completed traces, ascending
        self.dropped = 0              # ring evictions (spans + events)
        self.enabled = False

    # -- lifecycle -----------------------------------------------------------
    def start(self, capacity: "int | None" = None) -> None:
        """Begin a fresh recording (drops any previous buffer)."""
        with self._lock:
            if capacity is not None and capacity != self._capacity:
                self._capacity = capacity
                self._spans = deque(maxlen=capacity)
                self._events = deque(maxlen=capacity)
            else:
                self._spans.clear()
                self._events.clear()
            self._lanes.clear()
            self._lane_names.clear()
            self._slow = []
            self.dropped = 0
            self._t0 = time.perf_counter()
            self._wall0 = time.time()
            self.enabled = True

    def stop(self) -> None:
        with self._lock:
            self.enabled = False

    # -- recording (called from the trace facade) ---------------------------
    def _lane(self) -> int:
        ident = threading.get_ident()
        lane = self._lanes.get(ident)
        if lane is None:
            with self._lock:
                lane = self._lanes.get(ident)
                if lane is None:
                    lane = len(self._lanes)
                    self._lanes[ident] = lane
                    self._lane_names[lane] = (
                        f"{threading.current_thread().name} ({ident})"
                    )
        return lane

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def begin(self, name: str, fields: dict) -> SpanRecord:
        stack = self._stack()
        lane = self._lane()
        flow_src = None
        if stack:
            # in-thread nesting wins: parent is the enclosing span
            parent_id = stack[-1].span_id
            trace_id = stack[-1].trace_id
        else:
            ctx = getattr(self._tls, "adopted", None)
            if ctx is not None:
                # cross-seam handoff: link under the sender's span
                parent_id = ctx.span_id
                trace_id = ctx.trace_id
                if ctx.lane != lane:
                    flow_src = (ctx.span_id, ctx.lane, ctx.ts)
            else:
                parent_id = 0
                trace_id = 0  # self-rooted: SpanRecord uses its span_id
        rec = SpanRecord(
            span_id=next(self._ids),
            parent_id=parent_id,
            name=name,
            lane=lane,
            t0=time.perf_counter(),
            fields=fields,
            trace_id=trace_id,
        )
        rec.flow_src = flow_src
        stack.append(rec)
        return rec

    def end(self, rec: SpanRecord, error: "str | None" = None) -> None:
        rec.t1 = time.perf_counter()
        rec.error = error
        stack = self._stack()
        # the facade pairs begin/end via try/finally, so rec is the top;
        # remove by identity anyway in case a caller misnests
        if stack and stack[-1] is rec:
            stack.pop()
        else:  # pragma: no cover - defensive
            try:
                stack.remove(rec)
            except ValueError:
                pass
        self._append_span(rec)

    def _append_span(self, rec: SpanRecord) -> None:
        dropped = False
        with self._lock:
            if len(self._spans) == self._capacity:
                self.dropped += 1
                dropped = True
            self._spans.append(rec)
        if dropped:
            from . import metrics as _metrics

            _metrics.counter("spans.dropped").inc()

    def event(self, name: str, fields: dict) -> None:
        rec = _EventRecord(name, self._lane(), time.perf_counter(), fields)
        self._append_event(rec)

    def _append_event(self, rec: _EventRecord) -> None:
        dropped = False
        with self._lock:
            if len(self._events) == self._capacity:
                self.dropped += 1
                dropped = True
            self._events.append(rec)
        if dropped:
            from . import metrics as _metrics

            _metrics.counter("spans.dropped").inc()

    # -- causal trace plane --------------------------------------------------
    def context(self) -> "TraceContext | None":
        """The current causal position as a handoff token: the top of
        this thread's span stack if one is open (the common case — call
        inside the span that should parent the downstream work), else
        the context this thread itself adopted, else None."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            top = stack[-1]
            return TraceContext(
                top.trace_id, top.span_id, top.lane, time.perf_counter()
            )
        return getattr(self._tls, "adopted", None)

    @contextmanager
    def adopt(self, ctx: "TraceContext | None"):
        """Bracket the receiving side of a handoff: top-of-stack spans
        begun inside the block parent to ``ctx.span_id`` and inherit its
        trace. Nests (the previous adoption is restored on exit); TLS
        only, so it is lock-free."""
        prev = getattr(self._tls, "adopted", None)
        self._tls.adopted = ctx
        try:
            yield ctx
        finally:
            self._tls.adopted = prev

    def note_trace(self, trace_id: int, name: str, duration_s: float,
                   fields: "dict | None" = None) -> None:
        """Feed the worst-N slow-trace ring: called once per completed
        trace (the pipeline notes each settled window, the pool each
        settled flush) with its end-to-end duration."""
        entry = {
            "trace_id": trace_id,
            "name": name,
            "duration_s": duration_s,
        }
        if fields:
            entry.update({k: _json_safe(v) for k, v in fields.items()})
        with self._lock:
            slow = self._slow
            if len(slow) < SLOW_TRACE_RING:
                slow.append(entry)
                slow.sort(key=lambda e: e["duration_s"])
            elif duration_s > slow[0]["duration_s"]:
                slow[0] = entry
                slow.sort(key=lambda e: e["duration_s"])

    def slow_traces(self) -> "list[dict]":
        """The worst-N completed traces, slowest first (consistent
        copy)."""
        with self._lock:
            return [dict(e) for e in reversed(self._slow)]

    def trace_records(self, trace_id: int) -> "list[SpanRecord]":
        """Completed spans belonging to ``trace_id`` (consistent copy,
        sorted by start time)."""
        with self._lock:
            spans = [r for r in self._spans if r.trace_id == trace_id]
        spans.sort(key=lambda r: r.t0)
        return spans

    def trace_tree(self, trace_id: int) -> dict:
        """One trace assembled as a JSON-ready causal tree: its spans
        (start-ordered), root/orphan accounting, and the wall window it
        covered. ``connected`` is the gate the tests and the ``/trace``
        endpoint assert: at least one span, exactly one root, zero
        orphans (an orphan parents to a span id absent from the
        trace)."""
        spans = self.trace_records(trace_id)
        ids = {r.span_id for r in spans}
        roots = sum(1 for r in spans if r.parent_id == 0)
        orphans = sum(
            1 for r in spans if r.parent_id and r.parent_id not in ids
        )
        t0 = self._t0
        out_spans = []
        for rec in spans:
            d = {
                "span_id": rec.span_id,
                "parent_id": rec.parent_id,
                "name": rec.name,
                "lane": rec.lane,
                "t0_s": max(0.0, rec.t0 - t0),
                "duration_s": rec.duration_s,
                "fields": {k: _json_safe(v) for k, v in rec.fields.items()},
            }
            if rec.error is not None:
                d["error"] = rec.error
            if rec.flow_src is not None:
                d["flow_from"] = {
                    "span_id": rec.flow_src[0],
                    "lane": rec.flow_src[1],
                }
            out_spans.append(d)
        return {
            "trace_id": trace_id,
            "spans": out_spans,
            "span_count": len(spans),
            "roots": roots,
            "orphans": orphans,
            "connected": bool(spans) and roots == 1 and orphans == 0,
            "t0_s": out_spans[0]["t0_s"] if out_spans else None,
            "duration_s": (
                max(r.t1 for r in spans) - min(r.t0 for r in spans)
                if spans
                else None
            ),
            "lanes": sorted({r.lane for r in spans}),
        }

    def audit(self) -> dict:
        """Whole-buffer trace health (the bench's evidence block):
        distinct traces, spans that parent to an id absent from the
        buffer (orphans), and ring evictions."""
        with self._lock:
            spans = list(self._spans)
            dropped = self.dropped
        ids = {r.span_id for r in spans}
        orphans = sum(
            1 for r in spans if r.parent_id and r.parent_id not in ids
        )
        return {
            "spans": len(spans),
            "traces": len({r.trace_id for r in spans}),
            "orphans": orphans,
            "dropped": dropped,
        }

    # -- named virtual lanes (non-thread tid tracks) -------------------------
    def named_lane(self, name: str) -> int:
        """The lane int for the virtual track ``name`` (allocated on
        first use). Virtual lanes share the tid namespace with thread
        lanes but belong to no thread — the device observatory's
        ``device`` track."""
        key = ("virtual", name)
        lane = self._lanes.get(key)
        if lane is None:
            with self._lock:
                lane = self._lanes.get(key)
                if lane is None:
                    lane = len(self._lanes)
                    self._lanes[key] = lane
                    self._lane_names[lane] = name
        return lane

    def add_complete(self, name: str, t0: float, t1: float, fields: dict,
                     lane: "int | None" = None) -> SpanRecord:
        """Append a pre-timed completed span (``perf_counter`` stamps)
        without touching any thread's span stack — the virtual-lane
        writer's API."""
        rec = SpanRecord(
            span_id=next(self._ids),
            parent_id=0,
            name=name,
            lane=self._lane() if lane is None else lane,
            t0=t0,
            fields=fields,
        )
        rec.t1 = t1
        self._append_span(rec)
        return rec

    def add_instant(self, name: str, ts: float, fields: dict,
                    lane: "int | None" = None) -> None:
        """Append a pre-timed instant event, optionally on a virtual
        lane."""
        rec = _EventRecord(
            name, self._lane() if lane is None else lane, ts, fields
        )
        self._append_event(rec)

    # -- reading -------------------------------------------------------------
    @property
    def origin(self) -> float:
        """``perf_counter`` stamp of the recording start — the zero
        point of every relative ``t0_s`` this recorder emits
        (``trace_tree``, ``chrome_trace``). Readers holding absolute
        ``perf_counter`` stamps (``records()``/``event_records()``)
        rebase with ``t - origin`` before comparing against them."""
        return self._t0

    def records(self) -> "list[SpanRecord]":
        """Completed spans, consistent copy (any order; sort by ``t0``)."""
        with self._lock:
            return list(self._spans)

    def event_records(self) -> "list[_EventRecord]":
        """Instant events (the ``event``/``add_instant`` ring),
        consistent copy (any order; sort by ``ts``)."""
        with self._lock:
            return list(self._events)

    def mark(self) -> int:
        """A watermark for ``records_since``: consumes one span id, so
        every span begun after the mark has ``span_id > mark``. Cheap
        (no lock) — the pipeline's per-block phase-split probe."""
        return next(self._ids)

    def records_since(self, mark: int) -> "list[SpanRecord]":
        """Completed spans begun after ``mark`` (consistent copy)."""
        with self._lock:
            return [r for r in self._spans if r.span_id > mark]

    def chrome_trace(self) -> dict:
        """The buffer as a Chrome trace-event JSON document
        (Perfetto / ``chrome://tracing`` loadable). Timestamps are
        microseconds relative to the recording start, strictly
        non-negative and monotonic per the ``perf_counter`` clock."""
        with self._lock:
            spans = sorted(self._spans, key=lambda r: r.t0)
            events = sorted(self._events, key=lambda r: r.ts)
            lane_names = dict(self._lane_names)
            t0 = self._t0
            wall0 = self._wall0
        pid = os.getpid()
        out = [
            {
                "ph": "M",
                "pid": pid,
                "name": "process_name",
                "args": {"name": "ethereum_consensus_tpu"},
            }
        ]
        for lane in sorted(lane_names):
            out.append(
                {
                    "ph": "M",
                    "pid": pid,
                    "tid": lane,
                    "name": "thread_name",
                    "args": {"name": lane_names[lane]},
                }
            )
        for rec in spans:
            args = {k: _json_safe(v) for k, v in rec.fields.items()}
            args["span_id"] = rec.span_id
            args["trace_id"] = rec.trace_id
            if rec.parent_id:
                args["parent_id"] = rec.parent_id
            if rec.error is not None:
                args["error"] = rec.error
            out.append(
                {
                    "ph": "X",
                    "pid": pid,
                    "tid": rec.lane,
                    "name": rec.name,
                    "cat": rec.name.split(".", 1)[0],
                    "ts": max(0.0, (rec.t0 - t0) * 1e6),
                    "dur": max(0.0, (rec.t1 - rec.t0) * 1e6),
                    "args": args,
                }
            )
            if rec.flow_src is not None:
                # cross-lane handoff: a flow-start at the sender's
                # capture point, a binding flow-finish at this span's
                # start — Perfetto draws the arrow between tid lanes
                src_span, src_lane, src_ts = rec.flow_src
                flow = {
                    "pid": pid,
                    "name": "trace.flow",
                    "cat": "flow",
                    "id": rec.span_id,
                }
                out.append(
                    dict(
                        flow,
                        ph="s",
                        tid=src_lane,
                        ts=max(0.0, (src_ts - t0) * 1e6),
                        args={"from_span": src_span},
                    )
                )
                out.append(
                    dict(
                        flow,
                        ph="f",
                        bp="e",
                        tid=rec.lane,
                        ts=max(0.0, (rec.t0 - t0) * 1e6),
                        args={"to_span": rec.span_id},
                    )
                )
        for rec in events:
            out.append(
                {
                    "ph": "i",
                    "s": "t",
                    "pid": pid,
                    "tid": rec.lane,
                    "name": rec.name,
                    "cat": rec.name.split(".", 1)[0],
                    "ts": max(0.0, (rec.ts - t0) * 1e6),
                    "args": {k: _json_safe(v) for k, v in rec.fields.items()},
                }
            )
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"recordingStartUnixTime": wall0},
        }


RECORDER = SpanRecorder()


def is_recording() -> bool:
    return RECORDER.enabled


def start_recording(capacity: "int | None" = None) -> None:
    RECORDER.start(capacity)


def stop_recording() -> None:
    RECORDER.stop()


@contextmanager
def recording(capacity: "int | None" = None):
    """Record spans for the duration of the block; yields ``RECORDER``."""
    RECORDER.start(capacity)
    try:
        yield RECORDER
    finally:
        RECORDER.stop()


def write_chrome_trace(path: str) -> None:
    """Serialize the current buffer as Chrome trace JSON at ``path``."""
    doc = RECORDER.chrome_trace()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
