"""Structured span recorder: thread-aware ring buffer + Chrome-trace export.

The tracing facade (``utils/trace.py``) stays the only API call sites
use; this module is the recording sink behind it. When recording is off
(the default) the facade never calls in here beyond one attribute read,
so the disabled path costs nothing measurable (guarded by
tests/test_telemetry.py's overhead test).

When recording is on, every ``trace.span`` exit appends one fixed-size
record — name, thread lane, parent span, start/end ``perf_counter``
stamps, the call site's fields, the error repr if the body raised — into
a bounded ``deque`` (oldest spans drop first; spans-in-progress live
only on a per-thread stack). ``chrome_trace()`` renders the buffer as
Chrome trace-event JSON (the ``{"traceEvents": [...]}`` flavor), loadable
in Perfetto / ``chrome://tracing``: each recording thread becomes one
``tid`` lane with its Python thread name as metadata, spans are ``"X"``
complete events in microseconds, point events are ``"i"`` instants. A
pipelined replay therefore renders stage A (the submitting thread) and
the background verifier as separate tracks, with flush dispatch/settle/
verify windows and rollbacks visible.

Thread lanes are small sequential ints (0 = first thread to record, in
practice the main thread) rather than raw ``threading.get_ident()``
values, so the Perfetto track list stays readable; the real ident is
kept in the thread-name metadata.

Besides thread lanes there are **named virtual lanes**
(``named_lane``): tid tracks that belong to no Python thread —
the device observatory (``telemetry/device.py``) renders XLA compiles
and host<->device transfers on a dedicated ``device`` track alongside
the pipeline/verifier thread tracks, via ``add_complete``/
``add_instant`` (pre-timed records appended without touching any
thread's span stack).

Lock discipline (speclint-checked): every write to the recorder's shared
structures holds ``self._lock``; the hot ``enabled`` read and the
per-thread span stack (``threading.local``) stay lock-free.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = [
    "SpanRecord",
    "SpanRecorder",
    "RECORDER",
    "DEFAULT_CAPACITY",
    "is_recording",
    "start_recording",
    "stop_recording",
    "recording",
    "write_chrome_trace",
]

DEFAULT_CAPACITY = 1 << 16


class SpanRecord:
    """One completed span (or, transiently, one in progress on its
    thread's stack). ``parent_id`` is 0 for top-level spans; parents are
    resolved per thread at begin time, so cross-thread work (the
    verifier) starts its own tree."""

    __slots__ = (
        "span_id",
        "parent_id",
        "name",
        "lane",
        "t0",
        "t1",
        "fields",
        "error",
    )

    def __init__(self, span_id: int, parent_id: int, name: str, lane: int,
                 t0: float, fields: dict):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.lane = lane
        self.t0 = t0
        self.t1 = t0
        self.fields = fields
        self.error = None

    @property
    def duration_s(self) -> float:
        return max(0.0, self.t1 - self.t0)


class _EventRecord:
    __slots__ = ("name", "lane", "ts", "fields")

    def __init__(self, name: str, lane: int, ts: float, fields: dict):
        self.name = name
        self.lane = lane
        self.ts = ts
        self.fields = fields


def _json_safe(value):
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    return str(value)


class SpanRecorder:
    """In-process ring-buffer recorder; one module-level instance
    (``RECORDER``) serves the whole process."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._spans: deque = deque(maxlen=capacity)
        self._events: deque = deque(maxlen=capacity)
        self._lanes: dict = {}        # thread ident -> small lane int
        self._lane_names: dict = {}   # lane int -> thread name
        self._tls = threading.local()
        self._ids = itertools.count(1)
        self._t0 = 0.0                # perf_counter origin of the recording
        self._wall0 = 0.0             # wall-clock at start (metadata only)
        self.enabled = False

    # -- lifecycle -----------------------------------------------------------
    def start(self, capacity: "int | None" = None) -> None:
        """Begin a fresh recording (drops any previous buffer)."""
        with self._lock:
            if capacity is not None and capacity != self._capacity:
                self._capacity = capacity
                self._spans = deque(maxlen=capacity)
                self._events = deque(maxlen=capacity)
            else:
                self._spans.clear()
                self._events.clear()
            self._lanes.clear()
            self._lane_names.clear()
            self._t0 = time.perf_counter()
            self._wall0 = time.time()
            self.enabled = True

    def stop(self) -> None:
        with self._lock:
            self.enabled = False

    # -- recording (called from the trace facade) ---------------------------
    def _lane(self) -> int:
        ident = threading.get_ident()
        lane = self._lanes.get(ident)
        if lane is None:
            with self._lock:
                lane = self._lanes.get(ident)
                if lane is None:
                    lane = len(self._lanes)
                    self._lanes[ident] = lane
                    self._lane_names[lane] = (
                        f"{threading.current_thread().name} ({ident})"
                    )
        return lane

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def begin(self, name: str, fields: dict) -> SpanRecord:
        stack = self._stack()
        rec = SpanRecord(
            span_id=next(self._ids),
            parent_id=stack[-1].span_id if stack else 0,
            name=name,
            lane=self._lane(),
            t0=time.perf_counter(),
            fields=fields,
        )
        stack.append(rec)
        return rec

    def end(self, rec: SpanRecord, error: "str | None" = None) -> None:
        rec.t1 = time.perf_counter()
        rec.error = error
        stack = self._stack()
        # the facade pairs begin/end via try/finally, so rec is the top;
        # remove by identity anyway in case a caller misnests
        if stack and stack[-1] is rec:
            stack.pop()
        else:  # pragma: no cover - defensive
            try:
                stack.remove(rec)
            except ValueError:
                pass
        with self._lock:
            self._spans.append(rec)

    def event(self, name: str, fields: dict) -> None:
        rec = _EventRecord(name, self._lane(), time.perf_counter(), fields)
        with self._lock:
            self._events.append(rec)

    # -- named virtual lanes (non-thread tid tracks) -------------------------
    def named_lane(self, name: str) -> int:
        """The lane int for the virtual track ``name`` (allocated on
        first use). Virtual lanes share the tid namespace with thread
        lanes but belong to no thread — the device observatory's
        ``device`` track."""
        key = ("virtual", name)
        lane = self._lanes.get(key)
        if lane is None:
            with self._lock:
                lane = self._lanes.get(key)
                if lane is None:
                    lane = len(self._lanes)
                    self._lanes[key] = lane
                    self._lane_names[lane] = name
        return lane

    def add_complete(self, name: str, t0: float, t1: float, fields: dict,
                     lane: "int | None" = None) -> SpanRecord:
        """Append a pre-timed completed span (``perf_counter`` stamps)
        without touching any thread's span stack — the virtual-lane
        writer's API."""
        rec = SpanRecord(
            span_id=next(self._ids),
            parent_id=0,
            name=name,
            lane=self._lane() if lane is None else lane,
            t0=t0,
            fields=fields,
        )
        rec.t1 = t1
        with self._lock:
            self._spans.append(rec)
        return rec

    def add_instant(self, name: str, ts: float, fields: dict,
                    lane: "int | None" = None) -> None:
        """Append a pre-timed instant event, optionally on a virtual
        lane."""
        rec = _EventRecord(
            name, self._lane() if lane is None else lane, ts, fields
        )
        with self._lock:
            self._events.append(rec)

    # -- reading -------------------------------------------------------------
    def records(self) -> "list[SpanRecord]":
        """Completed spans, consistent copy (any order; sort by ``t0``)."""
        with self._lock:
            return list(self._spans)

    def mark(self) -> int:
        """A watermark for ``records_since``: consumes one span id, so
        every span begun after the mark has ``span_id > mark``. Cheap
        (no lock) — the pipeline's per-block phase-split probe."""
        return next(self._ids)

    def records_since(self, mark: int) -> "list[SpanRecord]":
        """Completed spans begun after ``mark`` (consistent copy)."""
        with self._lock:
            return [r for r in self._spans if r.span_id > mark]

    def chrome_trace(self) -> dict:
        """The buffer as a Chrome trace-event JSON document
        (Perfetto / ``chrome://tracing`` loadable). Timestamps are
        microseconds relative to the recording start, strictly
        non-negative and monotonic per the ``perf_counter`` clock."""
        with self._lock:
            spans = sorted(self._spans, key=lambda r: r.t0)
            events = sorted(self._events, key=lambda r: r.ts)
            lane_names = dict(self._lane_names)
            t0 = self._t0
            wall0 = self._wall0
        pid = os.getpid()
        out = [
            {
                "ph": "M",
                "pid": pid,
                "name": "process_name",
                "args": {"name": "ethereum_consensus_tpu"},
            }
        ]
        for lane in sorted(lane_names):
            out.append(
                {
                    "ph": "M",
                    "pid": pid,
                    "tid": lane,
                    "name": "thread_name",
                    "args": {"name": lane_names[lane]},
                }
            )
        for rec in spans:
            args = {k: _json_safe(v) for k, v in rec.fields.items()}
            args["span_id"] = rec.span_id
            if rec.parent_id:
                args["parent_id"] = rec.parent_id
            if rec.error is not None:
                args["error"] = rec.error
            out.append(
                {
                    "ph": "X",
                    "pid": pid,
                    "tid": rec.lane,
                    "name": rec.name,
                    "cat": rec.name.split(".", 1)[0],
                    "ts": max(0.0, (rec.t0 - t0) * 1e6),
                    "dur": max(0.0, (rec.t1 - rec.t0) * 1e6),
                    "args": args,
                }
            )
        for rec in events:
            out.append(
                {
                    "ph": "i",
                    "s": "t",
                    "pid": pid,
                    "tid": rec.lane,
                    "name": rec.name,
                    "cat": rec.name.split(".", 1)[0],
                    "ts": max(0.0, (rec.ts - t0) * 1e6),
                    "args": {k: _json_safe(v) for k, v in rec.fields.items()},
                }
            )
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"recordingStartUnixTime": wall0},
        }


RECORDER = SpanRecorder()


def is_recording() -> bool:
    return RECORDER.enabled


def start_recording(capacity: "int | None" = None) -> None:
    RECORDER.start(capacity)


def stop_recording() -> None:
    RECORDER.stop()


@contextmanager
def recording(capacity: "int | None" = None):
    """Record spans for the duration of the block; yields ``RECORDER``."""
    RECORDER.start(capacity)
    try:
        yield RECORDER
    finally:
        RECORDER.stop()


def write_chrome_trace(path: str) -> None:
    """Serialize the current buffer as Chrome trace JSON at ``path``."""
    doc = RECORDER.chrome_trace()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
