"""Process-wide metrics registry: counters, gauges, histograms.

One lock-disciplined home for every operational counter the codebase
used to keep as ad-hoc module globals — the ``ssz/hash.py`` digest
count (previously an unlocked ``global`` incremented from both pipeline
threads), the ``crypto/bls.py`` pubkey-cache hits/misses/evictions and
bulk-decompress counts, the pairing-route decisions, and the
``pipeline.*`` counters ``PipelineStats`` views.

Semantics:

* **get-or-create by name** — ``counter(name)`` / ``gauge(name)`` /
  ``histogram(name)`` return the one process-wide instance for that
  name (double-checked under the registry lock); asking for an existing
  name with a different kind raises.
* **lock discipline** (speclint-checked) — every mutation holds the
  metric's own lock; reads are lock-free (a Python int/float load is
  atomic under the GIL). Counters are monotonic, so readers see a value
  that was true at some instant — exactly what delta arithmetic needs.
* **snapshot/delta** — ``snapshot()`` is a JSON-ready plain dict of
  every registered metric; ``delta(before, after)`` subtracts two
  snapshots (counters and histogram count/sum subtract; gauges report
  the ``after`` value — they are levels, not totals).

Naming convention (docs/OBSERVABILITY.md): dotted lowercase paths,
``<subsystem>.<object>.<what>`` — e.g. ``ssz.digests``,
``bls.pubkey_cache.hits``, ``pipeline.flushes``. Seconds-valued
counters end in ``_s``.
"""

from __future__ import annotations

import random
import threading
import zlib

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "counter",
    "gauge",
    "histogram",
    "registered",
    "registered_metrics",
    "snapshot",
    "delta",
]


class Counter:
    """Monotonic total (int or float increments)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def value(self):
        return self._value

    def __repr__(self) -> str:
        return f"Counter({self.name}={self._value})"


class Gauge:
    """A level: last-set value, plus a high-watermark helper."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def update_max(self, v) -> None:
        """Raise the gauge to ``v`` if larger (queue-depth high-watermark
        semantics)."""
        with self._lock:
            if v > self._value:
                self._value = v

    def value(self):
        return self._value

    def __repr__(self) -> str:
        return f"Gauge({self.name}={self._value})"


class Histogram:
    """Counted observations with exact streaming count/sum/min/max and a
    FIXED-SIZE uniform reservoir of raw values (Vitter's algorithm R):
    after ``sample_limit`` observations, each new value replaces a
    random slot with probability ``sample_limit / count``, so the sample
    stays a uniform draw over the whole stream and memory is bounded no
    matter how many observations arrive (a 2^17 replay can't grow it
    linearly the way an append-only sample would). The exact aggregates
    are never sampled — ``summary()``/``snapshot()``/``delta()`` keep
    their semantics; only ``values()``/``quantiles()`` read the
    reservoir. The per-histogram RNG is seeded from the metric name, so
    a replay's reservoir is reproducible.

    **Exemplars** (the causal trace plane, docs/OBSERVABILITY.md):
    ``observe(v, trace_id=..., fields=...)`` additionally retains the
    observation in a bounded worst-N ``(value, trace_id, fields)``
    exemplar table beside the reservoir, so a p99 SLO gate can name
    *which* trace was the tail, not just how slow it was. The table is
    value-ordered and deterministic — insertion never touches the RNG,
    so the seeded-reservoir reproducibility contract is unchanged
    whether or not call sites pass trace ids. Observations carrying a
    trace_id that don't displace a retained exemplar are counted (the
    no-silent-caps rule; ``exemplar_dropped`` per table, summed
    process-wide into the ``metrics.exemplars_dropped`` counter)."""

    __slots__ = ("name", "_lock", "_count", "_sum", "_min", "_max", "_values",
                 "_rng", "sample_limit", "_exemplars", "_exemplar_dropped",
                 "exemplar_limit")

    def __init__(self, name: str, sample_limit: int = 1 << 12,
                 exemplar_limit: int = 8):
        self.name = name
        self.sample_limit = sample_limit
        self.exemplar_limit = exemplar_limit
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0
        self._min = None
        self._max = None
        self._values: list = []
        self._exemplars: list = []   # (value, trace_id, fields), ascending
        self._exemplar_dropped = 0
        self._rng = random.Random(zlib.crc32(name.encode("utf-8")))

    def observe(self, v, trace_id=None, fields=None) -> None:
        dropped = False
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if len(self._values) < self.sample_limit:
                self._values.append(v)
            else:
                j = self._rng.randrange(self._count)
                if j < self.sample_limit:
                    self._values[j] = v
            if trace_id is not None:
                ex = self._exemplars
                if len(ex) < self.exemplar_limit:
                    ex.append((v, trace_id, fields))
                    ex.sort(key=lambda e: e[0])
                elif v > ex[0][0]:
                    dropped = True  # the displaced smallest
                    ex[0] = (v, trace_id, fields)
                    ex.sort(key=lambda e: e[0])
                else:
                    dropped = True
                if dropped:
                    self._exemplar_dropped += 1
        # mirror into the process-wide drop counter outside self._lock
        # (never nest the registry lock under a metric lock)
        if dropped:
            counter("metrics.exemplars_dropped").inc()

    def exemplars(self) -> "list[dict]":
        """The worst-N exemplar table, largest value first: JSON-ready
        ``{"value", "trace_id", "fields"}`` dicts (``fields`` omitted
        when the call site passed none)."""
        with self._lock:
            ex = list(self._exemplars)
        out = []
        for v, trace_id, fields in reversed(ex):
            d = {"value": v, "trace_id": trace_id}
            if fields:
                d["fields"] = dict(fields)
            out.append(d)
        return out

    @property
    def exemplar_dropped(self) -> int:
        """Trace-carrying observations not retained in the bounded
        exemplar table (evicted smallest, or arrived below the current
        floor)."""
        return self._exemplar_dropped

    def reset_exemplars(self) -> None:
        """Clear the exemplar table (the drop tally survives — it is an
        accounting total, not a window statistic). The soak calls this
        at run start so every exemplar it reports resolves against the
        span recording it just began; the reservoir is untouched."""
        with self._lock:
            self._exemplars = []

    def summary(self) -> dict:
        with self._lock:
            count, total = self._count, self._sum
            lo, hi = self._min, self._max
        return {
            "count": count,
            "sum": total,
            "min": lo,
            "max": hi,
            "mean": (total / count) if count else None,
        }

    def values(self) -> list:
        """The bounded reservoir sample (uniform over the stream once it
        exceeds ``sample_limit``; the full stream in arrival order
        before that)."""
        with self._lock:
            return list(self._values)

    def quantiles(self, qs=(0.5, 0.9, 0.99)) -> dict:
        """{q: value} estimated from the reservoir (nearest-rank over
        the sorted sample); empty when nothing has been observed."""
        with self._lock:
            sample = sorted(self._values)
        if not sample:
            return {}
        top = len(sample) - 1
        return {
            q: sample[min(top, max(0, round(q * top)))] for q in qs
        }

    def __repr__(self) -> str:
        return f"Histogram({self.name}, n={self._count})"


# -- the process-wide registry ------------------------------------------------

_REGISTRY: dict = {}
_REGISTRY_LOCK = threading.Lock()


def _get_or_create(name: str, kind):
    metric = _REGISTRY.get(name)
    if metric is None:
        with _REGISTRY_LOCK:
            metric = _REGISTRY.get(name)
            if metric is None:
                metric = kind(name)
                _REGISTRY[name] = metric
    if not isinstance(metric, kind):
        raise TypeError(
            f"metric {name!r} is a {type(metric).__name__}, "
            f"not a {kind.__name__}"
        )
    return metric


def counter(name: str) -> Counter:
    """The process-wide counter named ``name`` (created on first use)."""
    return _get_or_create(name, Counter)


def gauge(name: str) -> Gauge:
    return _get_or_create(name, Gauge)


def histogram(name: str) -> Histogram:
    return _get_or_create(name, Histogram)


def registered() -> "list[str]":
    """Registered metric names, sorted."""
    return sorted(_REGISTRY)


def registered_metrics() -> list:
    """The registered metric OBJECTS, sorted by name (the introspection
    server's exposition walk — ``telemetry/server.py``)."""
    return [_REGISTRY[name] for name in sorted(_REGISTRY)]


def snapshot() -> dict:
    """JSON-ready ``{name: value}`` of every registered metric (histograms
    report their ``summary()`` dict). Consistent per metric, not across
    metrics — fine for monotonic-counter deltas."""
    out = {}
    for name in sorted(_REGISTRY):
        metric = _REGISTRY[name]
        if isinstance(metric, Histogram):
            out[name] = metric.summary()
        else:
            out[name] = metric.value()
    return out


def delta(before: dict, after: "dict | None" = None) -> dict:
    """``after - before`` over two snapshots (``after`` defaults to a
    fresh ``snapshot()``). Counters subtract; histogram ``count``/``sum``
    subtract (``min``/``max``/``mean`` describe the after-window only in
    mean's case, so the delta reports count/sum/mean-of-window); gauges
    are levels and report the ``after`` value. Metrics absent from
    ``before`` count from zero."""
    if after is None:
        after = snapshot()
    out = {}
    for name, now in after.items():
        prev = before.get(name)
        if isinstance(now, dict):  # histogram summary
            prev = prev if isinstance(prev, dict) else {}
            count = now.get("count", 0) - prev.get("count", 0)
            total = (now.get("sum") or 0) - (prev.get("sum") or 0)
            out[name] = {
                "count": count,
                "sum": total,
                "mean": (total / count) if count else None,
            }
        elif isinstance(_REGISTRY.get(name), Gauge):
            out[name] = now
        else:
            out[name] = now - (prev if isinstance(prev, (int, float)) else 0)
    return out
