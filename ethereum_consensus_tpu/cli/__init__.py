"""`ec`-equivalent CLI (C25): validator mnemonic / EIP-2333-2334-2335 keys
and keystores, BLS keygen, EIP-4844 blob encode/bundle/decode.

Reference parity: ethereum-consensus/src/bin/ec/ (945 LoC).
"""

from . import blobs, keys, keystores, mnemonic  # noqa: F401
from .main import main  # noqa: F401
