"""EIP-4844 blob encoding: pack arbitrary bytes into blobs, bundle with KZG
commitments/proofs, and decode back.

Reference parity: ethereum-consensus/src/bin/ec/blobs/ — 254-bit packing
into big-endian field elements (encode.rs:15: the top two bits of each
32-byte field element are unusable), raw/sized framing (framing.rs: 1
version byte + u32 big-endian payload size), bundling via
blob_to_kzg_commitment + compute_blob_kzg_proof (bundler.rs), inverse
unpacking (decode.rs).
"""

from __future__ import annotations

from ..crypto.fields import R as BLS_MODULUS

__all__ = [
    "BYTES_PER_FIELD_ELEMENT",
    "BITS_PER_FIELD_ELEMENT",
    "SIZED_FRAMING_VERSION",
    "HEADER_SIZE",
    "pack_into_blobs",
    "unpack_from_blobs",
    "sized_header",
    "payload_from_sized",
    "encode",
    "decode",
    "bundle",
]

BYTES_PER_FIELD_ELEMENT = 32
BITS_PER_FIELD_ELEMENT = 254  # usable bits per big-endian field element
FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_BLOB = BYTES_PER_FIELD_ELEMENT * FIELD_ELEMENTS_PER_BLOB
MAX_BLOBS = 6

SIZED_FRAMING_VERSION = 0
HEADER_SIZE = 5


def pack_into_blobs(buffer: bytes) -> list[bytes]:
    """(encode.rs:29) — tightly pack a byte stream into 254-bit field
    elements across however many blobs are needed. One big-int shift/mask
    pass (no per-bit Python loop)."""
    total_bits = len(buffer) * 8
    stream = int.from_bytes(buffer, "big")
    n_elements = max(1, -(-total_bits // BITS_PER_FIELD_ELEMENT))
    blobs: list[bytes] = []
    blob = bytearray()
    for i in range(n_elements):
        start = i * BITS_PER_FIELD_ELEMENT
        width = min(BITS_PER_FIELD_ELEMENT, total_bits - start)
        if width <= 0:
            chunk = 0
        else:
            chunk = (stream >> (total_bits - start - width)) & ((1 << width) - 1)
        # bits land after the two zero top bits of the 256-bit big-endian
        # word (encode.rs:15)
        value = chunk << (256 - 2 - start % BITS_PER_FIELD_ELEMENT - width)
        if value >= BLS_MODULUS:
            raise ValueError("packed field element exceeds the BLS modulus")
        if len(blob) == BYTES_PER_BLOB:
            blobs.append(bytes(blob))
            blob.clear()
        blob.extend(value.to_bytes(32, "big"))
    blob.extend(b"\x00" * (BYTES_PER_BLOB - len(blob)))
    blobs.append(bytes(blob))
    if len(blobs) > MAX_BLOBS:
        raise ValueError(
            f"payload needs {len(blobs)} blobs, exceeding the per-block "
            f"limit of {MAX_BLOBS}"
        )
    return blobs


def unpack_from_blobs(blobs: list[bytes]) -> bytes:
    """(decode.rs:10) — inverse of pack_into_blobs (keeps padding bits;
    apply framing to recover exact payloads)."""
    out_bits = 0
    n_bits = 0
    for blob in blobs:
        if len(blob) != BYTES_PER_BLOB:
            raise ValueError(f"blob must be {BYTES_PER_BLOB} bytes")
        for start in range(0, BYTES_PER_BLOB, BYTES_PER_FIELD_ELEMENT):
            element = int.from_bytes(
                blob[start : start + BYTES_PER_FIELD_ELEMENT], "big"
            )
            out_bits = (out_bits << BITS_PER_FIELD_ELEMENT) | element
            n_bits += BITS_PER_FIELD_ELEMENT
    out_len = len(blobs) * BYTES_PER_BLOB
    # right-pad the recovered bit stream to the output byte length
    out_bits <<= out_len * 8 - n_bits if out_len * 8 > n_bits else 0
    return out_bits.to_bytes(out_len, "big")[:out_len]


def sized_header(data_byte_length: int) -> bytes:
    """(framing.rs:19)"""
    if data_byte_length >= 2**32:
        raise ValueError("payload too large for sized framing")
    return bytes([SIZED_FRAMING_VERSION]) + data_byte_length.to_bytes(4, "big")


def payload_from_sized(stream: bytes) -> bytes:
    """(framing.rs:30)"""
    if len(stream) < HEADER_SIZE:
        raise ValueError("expected header for sized framing")
    if stream[0] != SIZED_FRAMING_VERSION:
        raise ValueError("unsupported sized-framing version")
    size = int.from_bytes(stream[1:5], "big")
    if size > len(stream) - HEADER_SIZE:
        raise ValueError("invalid payload size")
    return stream[HEADER_SIZE : HEADER_SIZE + size]


def encode(data: bytes, framing: str = "sized") -> list[bytes]:
    """(encode.rs:63 from_reader)"""
    if framing == "sized":
        data = sized_header(len(data)) + data
    elif framing != "raw":
        raise ValueError(f"unknown framing {framing!r}")
    return pack_into_blobs(data)


def decode(blobs: list[bytes], framing: str = "sized") -> bytes:
    """(decode.rs:36 to_writer_from_json)"""
    stream = unpack_from_blobs(blobs)
    if framing == "sized":
        return payload_from_sized(stream)
    if framing != "raw":
        raise ValueError(f"unknown framing {framing!r}")
    return stream


def bundle(blobs: list[bytes], kzg_settings=None):
    """(bundler.rs) — per blob: commitment + proof → BlobsBundle-shaped
    dict. Uses the embedded mainnet ceremony setup unless ``kzg_settings``
    is supplied."""
    from ..crypto import kzg

    if kzg_settings is None:
        kzg_settings = kzg.KzgSettings.ceremony()
    commitments = []
    proofs = []
    for blob in blobs:
        commitment = kzg.blob_to_kzg_commitment(blob, kzg_settings)
        proof = kzg.compute_blob_kzg_proof(blob, commitment, kzg_settings)
        commitments.append(commitment)
        proofs.append(proof)
    return {
        "commitments": commitments,
        "proofs": proofs,
        "blobs": blobs,
    }
