"""EIP-2335 BLS keystores (scrypt KDF + AES-128-CTR).

Reference parity: ethereum-consensus/src/bin/ec/validator/keystores.rs:221 —
version-4 keystore JSON with scrypt kdf, sha256 checksum and aes-128-ctr
cipher; NFKD + control-character stripping of passphrases.
"""

from __future__ import annotations

import hashlib
import json
import os
import unicodedata
import uuid as uuid_module

from ..crypto import bls

__all__ = ["Keystore", "encrypt", "decrypt", "generate_passphrase"]

VERSION = 4
SCRYPT_N = 2**15  # scrypt "recommended" params (keystores.rs:97)
SCRYPT_R = 8
SCRYPT_P = 1
SCRYPT_DKLEN = 32
SALT_LEN = 16
IV_LEN = 16


def _normalize(passphrase: str) -> bytes:
    text = unicodedata.normalize("NFKD", passphrase)
    text = "".join(c for c in text if not unicodedata.category(c).startswith("C"))
    return text.encode()


def _scrypt(passphrase: str, salt: bytes) -> bytes:
    return hashlib.scrypt(
        _normalize(passphrase),
        salt=salt,
        n=SCRYPT_N,
        r=SCRYPT_R,
        p=SCRYPT_P,
        maxmem=2**27,
        dklen=SCRYPT_DKLEN,
    )


def _aes_128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


class Keystore(dict):
    """An EIP-2335 keystore document (a dict with helpers)."""

    @property
    def public_key(self) -> str:
        return self["pubkey"]

    def to_json(self) -> str:
        return json.dumps(self, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "Keystore":
        return cls(json.loads(text))


def encrypt(
    secret_key: bls.SecretKey,
    passphrase: str,
    path: str = "",
    salt: bytes | None = None,
    iv: bytes | None = None,
) -> Keystore:
    """(keystores.rs encrypt path)"""
    salt = os.urandom(SALT_LEN) if salt is None else salt
    iv = os.urandom(IV_LEN) if iv is None else iv
    decryption_key = _scrypt(passphrase, salt)
    secret_bytes = secret_key.to_bytes()
    cipher_text = _aes_128_ctr(decryption_key[:16], iv, secret_bytes)
    checksum = hashlib.sha256(decryption_key[16:32] + cipher_text).digest()
    public_key = secret_key.public_key().to_bytes()
    return Keystore(
        {
            "crypto": {
                "kdf": {
                    "function": "scrypt",
                    "params": {
                        "dklen": SCRYPT_DKLEN,
                        "n": SCRYPT_N,
                        "p": SCRYPT_P,
                        "r": SCRYPT_R,
                        "salt": salt.hex(),
                    },
                    "message": "",
                },
                "checksum": {
                    "function": "sha256",
                    "params": {},
                    "message": checksum.hex(),
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": iv.hex()},
                    "message": cipher_text.hex(),
                },
            },
            "description": "",
            "pubkey": public_key.hex(),
            "path": path,
            "uuid": str(uuid_module.uuid4()),
            "version": VERSION,
        }
    )


def decrypt(keystore: Keystore | dict, passphrase: str) -> bls.SecretKey:
    """(keystores.rs decrypt path) — verifies the checksum before
    decrypting; raises ValueError on a wrong passphrase."""
    crypto = keystore["crypto"]
    kdf = crypto["kdf"]
    if kdf["function"] != "scrypt":
        raise ValueError(f"unsupported kdf {kdf['function']!r}")
    params = kdf["params"]
    decryption_key = hashlib.scrypt(
        _normalize(passphrase),
        salt=bytes.fromhex(params["salt"]),
        n=params["n"],
        r=params["r"],
        p=params["p"],
        maxmem=2**27,
        dklen=params["dklen"],
    )
    cipher = crypto["cipher"]
    if cipher["function"] != "aes-128-ctr":
        raise ValueError(f"unsupported cipher {cipher['function']!r}")
    cipher_text = bytes.fromhex(cipher["message"])
    checksum = hashlib.sha256(decryption_key[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise ValueError("keystore checksum mismatch (wrong passphrase?)")
    secret_bytes = _aes_128_ctr(
        decryption_key[:16], bytes.fromhex(cipher["params"]["iv"]), cipher_text
    )
    return bls.SecretKey(int.from_bytes(secret_bytes, "big"))


def generate_passphrase(length: int = 32) -> str:
    """Random url-safe passphrase (keystores.rs PASSPHRASE_LEN)."""
    import secrets

    return secrets.token_urlsafe(length)[:length]
