"""EIP-2335 BLS keystores (scrypt KDF + AES-128-CTR).

Reference parity: ethereum-consensus/src/bin/ec/validator/keystores.rs:221 —
version-4 keystore JSON with scrypt kdf, sha256 checksum and aes-128-ctr
cipher; NFKD + control-character stripping of passphrases.
"""

from __future__ import annotations

import hashlib
import json
import os
import unicodedata
import uuid as uuid_module

from ..crypto import bls

__all__ = ["Keystore", "encrypt", "decrypt", "generate_passphrase"]

VERSION = 4
SCRYPT_N = 2**15  # scrypt "recommended" params (keystores.rs:97)
SCRYPT_R = 8
SCRYPT_P = 1
SCRYPT_DKLEN = 32
SALT_LEN = 16
IV_LEN = 16


def _normalize(passphrase: str) -> bytes:
    text = unicodedata.normalize("NFKD", passphrase)
    text = "".join(c for c in text if not unicodedata.category(c).startswith("C"))
    return text.encode()


def _scrypt(passphrase: str, salt: bytes) -> bytes:
    return hashlib.scrypt(
        _normalize(passphrase),
        salt=salt,
        n=SCRYPT_N,
        r=SCRYPT_R,
        p=SCRYPT_P,
        maxmem=2**27,
        dklen=SCRYPT_DKLEN,
    )


def _aes_128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    try:
        from cryptography.hazmat.primitives.ciphers import (
            Cipher,
            algorithms,
            modes,
        )
    except ImportError:
        # the container image ships no `cryptography` wheel; EIP-2335
        # payloads are 32 bytes, so the table-driven fallback below is
        # plenty (and keeps the CLI dependency-free)
        return _aes_128_ctr_py(key, iv, data)

    cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


# -- pure-python AES-128-CTR fallback -----------------------------------------
#
# FIPS-197 with the standard 256-entry S-box/xtime tables. CTR mode only
# ever ENCRYPTS the counter stream, so decrypt == encrypt and no inverse
# cipher is needed. Keystore secrets are one or two blocks; throughput is
# irrelevant, correctness is pinned by the round-trip + known-vector
# tests in tests/test_cli.py.

_SBOX = None


def _aes_tables():
    global _SBOX
    if _SBOX is not None:
        return _SBOX
    # generate the S-box from the field inverse + affine map rather than
    # inlining 256 magic numbers
    p, q, sbox = 1, 1, [0] * 256
    while True:
        # p := p * 3, q := q / 3 in GF(2^8)
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        q ^= q << 1
        q ^= q << 2
        q ^= q << 4
        q &= 0xFF
        if q & 0x80:
            q ^= 0x09
        x = (
            q
            ^ ((q << 1) | (q >> 7))
            ^ ((q << 2) | (q >> 6))
            ^ ((q << 3) | (q >> 5))
            ^ ((q << 4) | (q >> 4))
        )
        sbox[p] = (x ^ 0x63) & 0xFF
        if p == 1:
            break
    sbox[0] = 0x63
    _SBOX = sbox
    return sbox


def _xtime(a: int) -> int:
    return ((a << 1) ^ 0x1B) & 0xFF if a & 0x80 else a << 1


def _aes_128_expand_key(key: bytes) -> "list[list[int]]":
    sbox = _aes_tables()
    words = [list(key[4 * i : 4 * i + 4]) for i in range(4)]
    rcon = 1
    for i in range(4, 44):
        w = list(words[i - 1])
        if i % 4 == 0:
            w = [sbox[b] for b in w[1:] + w[:1]]
            w[0] ^= rcon
            rcon = _xtime(rcon)
        words.append([a ^ b for a, b in zip(words[i - 4], w)])
    return [sum(words[4 * r : 4 * r + 4], []) for r in range(11)]


def _aes_128_encrypt_block(round_keys, block: bytes) -> bytes:
    sbox = _aes_tables()
    s = [b ^ k for b, k in zip(block, round_keys[0])]
    for rnd in range(1, 11):
        s = [sbox[b] for b in s]
        # ShiftRows on the column-major state layout
        s = [s[(i + 4 * (i % 4)) % 16] for i in range(16)]
        if rnd < 10:
            mixed = []
            for c in range(4):
                a = s[4 * c : 4 * c + 4]
                t = a[0] ^ a[1] ^ a[2] ^ a[3]
                mixed.extend(
                    a[i] ^ t ^ _xtime(a[i] ^ a[(i + 1) % 4]) for i in range(4)
                )
            s = mixed
        s = [b ^ k for b, k in zip(s, round_keys[rnd])]
    return bytes(s)


def _aes_128_ctr_py(key: bytes, iv: bytes, data: bytes) -> bytes:
    round_keys = _aes_128_expand_key(key)
    counter = int.from_bytes(iv, "big")
    out = bytearray()
    for off in range(0, len(data), 16):
        stream = _aes_128_encrypt_block(
            round_keys, (counter & (2**128 - 1)).to_bytes(16, "big")
        )
        block = data[off : off + 16]
        out.extend(b ^ s for b, s in zip(block, stream))
        counter += 1
    return bytes(out)


class Keystore(dict):
    """An EIP-2335 keystore document (a dict with helpers)."""

    @property
    def public_key(self) -> str:
        return self["pubkey"]

    def to_json(self) -> str:
        return json.dumps(self, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "Keystore":
        return cls(json.loads(text))


def encrypt(
    secret_key: bls.SecretKey,
    passphrase: str,
    path: str = "",
    salt: bytes | None = None,
    iv: bytes | None = None,
) -> Keystore:
    """(keystores.rs encrypt path)"""
    salt = os.urandom(SALT_LEN) if salt is None else salt
    iv = os.urandom(IV_LEN) if iv is None else iv
    decryption_key = _scrypt(passphrase, salt)
    secret_bytes = secret_key.to_bytes()
    cipher_text = _aes_128_ctr(decryption_key[:16], iv, secret_bytes)
    checksum = hashlib.sha256(decryption_key[16:32] + cipher_text).digest()
    public_key = secret_key.public_key().to_bytes()
    return Keystore(
        {
            "crypto": {
                "kdf": {
                    "function": "scrypt",
                    "params": {
                        "dklen": SCRYPT_DKLEN,
                        "n": SCRYPT_N,
                        "p": SCRYPT_P,
                        "r": SCRYPT_R,
                        "salt": salt.hex(),
                    },
                    "message": "",
                },
                "checksum": {
                    "function": "sha256",
                    "params": {},
                    "message": checksum.hex(),
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": iv.hex()},
                    "message": cipher_text.hex(),
                },
            },
            "description": "",
            "pubkey": public_key.hex(),
            "path": path,
            "uuid": str(uuid_module.uuid4()),
            "version": VERSION,
        }
    )


def decrypt(keystore: Keystore | dict, passphrase: str) -> bls.SecretKey:
    """(keystores.rs decrypt path) — verifies the checksum before
    decrypting; raises ValueError on a wrong passphrase."""
    crypto = keystore["crypto"]
    kdf = crypto["kdf"]
    if kdf["function"] != "scrypt":
        raise ValueError(f"unsupported kdf {kdf['function']!r}")
    params = kdf["params"]
    decryption_key = hashlib.scrypt(
        _normalize(passphrase),
        salt=bytes.fromhex(params["salt"]),
        n=params["n"],
        r=params["r"],
        p=params["p"],
        maxmem=2**27,
        dklen=params["dklen"],
    )
    cipher = crypto["cipher"]
    if cipher["function"] != "aes-128-ctr":
        raise ValueError(f"unsupported cipher {cipher['function']!r}")
    cipher_text = bytes.fromhex(cipher["message"])
    checksum = hashlib.sha256(decryption_key[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise ValueError("keystore checksum mismatch (wrong passphrase?)")
    secret_bytes = _aes_128_ctr(
        decryption_key[:16], bytes.fromhex(cipher["params"]["iv"]), cipher_text
    )
    return bls.SecretKey(int.from_bytes(secret_bytes, "big"))


def generate_passphrase(length: int = 32) -> str:
    """Random url-safe passphrase (keystores.rs PASSPHRASE_LEN)."""
    import secrets

    return secrets.token_urlsafe(length)[:length]
