"""The `ec` CLI: validator keys/keystores, BLS utilities, blob tooling.

Reference parity: ethereum-consensus/src/bin/ec/main.rs:7-29 — subcommands
``validator`` (mnemonic/HD keys/keystores), ``bls`` (random keypair),
``blobs`` (encode/bundle/decode). Run as
``python -m ethereum_consensus_tpu.cli ...``.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["main"]


def _cmd_bls(args) -> int:
    """(bin/ec/bls.rs:14) — random keypair to stdout."""
    import secrets

    from ..crypto import bls
    from ..crypto.fields import R

    sk = bls.SecretKey(secrets.randbelow(R - 1) + 1)
    print(
        json.dumps(
            {
                "secret_key": "0x" + sk.to_bytes().hex(),
                "public_key": "0x" + sk.public_key().to_bytes().hex(),
            },
            indent=2,
        )
    )
    return 0


def _cmd_validator_mnemonic(args) -> int:
    from . import mnemonic

    if args.wordlist:
        mnemonic.load_wordlist(args.wordlist)
    print(mnemonic.generate_random_from_system_entropy())
    return 0


def _cmd_validator_keys(args) -> int:
    from . import keys, mnemonic

    if args.wordlist:
        mnemonic.load_wordlist(args.wordlist)
        phrase = mnemonic.recover_from_phrase(args.phrase)
    else:
        phrase = args.phrase  # seed derivation needs no wordlist
    seed = mnemonic.to_seed(phrase, args.passphrase)
    signing, withdrawal = keys.generate(seed, args.start, args.end, parallel=not args.serial)
    out = [
        {
            "path": s.path,
            "signing_public_key": "0x" + s.public_key.to_bytes().hex(),
            "withdrawal_path": w.path,
            "withdrawal_public_key": "0x" + w.public_key.to_bytes().hex(),
        }
        for s, w in zip(signing, withdrawal)
    ]
    print(json.dumps(out, indent=2))
    return 0


def _cmd_validator_keystores(args) -> int:
    from . import keys, keystores, mnemonic

    seed = mnemonic.to_seed(args.phrase, args.passphrase)
    signing, _ = keys.generate(seed, args.start, args.end, parallel=not args.serial)
    documents = []
    for pair in signing:
        passphrase = args.keystore_passphrase or keystores.generate_passphrase()
        store = keystores.encrypt(pair.private_key, passphrase, path=pair.path)
        documents.append({"keystore": store, "passphrase": passphrase})
    print(json.dumps(documents, indent=2))
    return 0


def _read_input(args) -> bytes:
    if args.input == "-":
        return sys.stdin.buffer.read()
    with open(args.input, "rb") as f:
        return f.read()


def _cmd_blobs_encode(args) -> int:
    """(bin/ec/blobs/encode.rs)"""
    from . import blobs

    data = _read_input(args)
    packed = blobs.encode(data, framing=args.framing)
    print(json.dumps(["0x" + b.hex() for b in packed]))
    return 0


def _cmd_blobs_decode(args) -> int:
    """(bin/ec/blobs/decode.rs)"""
    from . import blobs

    packed = [
        bytes.fromhex(b.removeprefix("0x"))
        for b in json.loads(_read_input(args).decode())
    ]
    sys.stdout.buffer.write(blobs.decode(packed, framing=args.framing))
    return 0


def _cmd_blobs_bundle(args) -> int:
    """(bin/ec/blobs/bundler.rs)"""
    from . import blobs

    packed = [
        bytes.fromhex(b.removeprefix("0x"))
        for b in json.loads(_read_input(args).decode())
    ]
    bundle = blobs.bundle(packed)
    print(
        json.dumps(
            {
                "commitments": ["0x" + bytes(c).hex() for c in bundle["commitments"]],
                "proofs": ["0x" + bytes(p).hex() for p in bundle["proofs"]],
                "blobs": ["0x" + b.hex() for b in bundle["blobs"]],
            }
        )
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="ec", description="utilities for ethereum consensus"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    validator = sub.add_parser("validator", help="validator key utilities")
    vsub = validator.add_subparsers(dest="subcommand", required=True)

    vm = vsub.add_parser("generate-mnemonic", help="random BIP-39 mnemonic")
    vm.add_argument("--wordlist", help="path to the BIP-39 english wordlist")
    vm.set_defaults(fn=_cmd_validator_mnemonic)

    vk = vsub.add_parser("keys", help="derive EIP-2334 validator keys")
    vk.add_argument("phrase", help="BIP-39 mnemonic phrase")
    vk.add_argument("--passphrase", default=None)
    vk.add_argument("--start", type=int, default=0)
    vk.add_argument("--end", type=int, default=1)
    vk.add_argument("--serial", action="store_true")
    vk.add_argument("--wordlist", help="validate the phrase against this wordlist")
    vk.set_defaults(fn=_cmd_validator_keys)

    vs = vsub.add_parser("keystores", help="derive keys into EIP-2335 keystores")
    vs.add_argument("phrase")
    vs.add_argument("--passphrase", default=None)
    vs.add_argument("--start", type=int, default=0)
    vs.add_argument("--end", type=int, default=1)
    vs.add_argument("--serial", action="store_true")
    vs.add_argument("--keystore-passphrase", default=None)
    vs.set_defaults(fn=_cmd_validator_keystores)

    blscmd = sub.add_parser("bls", help="random BLS keypair")
    blscmd.set_defaults(fn=_cmd_bls)

    blobs_cmd = sub.add_parser("blobs", help="EIP-4844 blob tooling")
    bsub = blobs_cmd.add_subparsers(dest="subcommand", required=True)
    for name, fn in (
        ("encode", _cmd_blobs_encode),
        ("decode", _cmd_blobs_decode),
        ("bundle", _cmd_blobs_bundle),
    ):
        cmd = bsub.add_parser(name)
        cmd.add_argument("--input", default="-", help="file path or - for stdin")
        cmd.add_argument("--framing", choices=("raw", "sized"), default="sized")
        cmd.set_defaults(fn=fn)

    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
