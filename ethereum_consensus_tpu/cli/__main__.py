from .main import main

raise SystemExit(main())
