"""BIP-39 mnemonics.

Reference parity: ethereum-consensus/src/bin/ec/validator/mnemonic.rs:9-22
(generate from system entropy, recover from phrase, seed derivation).

Seed derivation (PBKDF2-HMAC-SHA512, 2048 rounds, salt "mnemonic"+pass)
needs no wordlist and always works. Phrase generation/validation needs the
standard 2048-word english list, which is data this environment does not
ship — provide it via ``set_wordlist``/``load_wordlist`` (gated otherwise).
"""

from __future__ import annotations

import hashlib
import os
import unicodedata

__all__ = [
    "Seed",
    "set_wordlist",
    "load_wordlist",
    "wordlist_available",
    "generate_random_from_system_entropy",
    "entropy_to_phrase",
    "recover_from_phrase",
    "to_seed",
]

Seed = bytes  # 64 bytes

_WORDLIST: list[str] | None = None
_WORD_INDEX: dict[str, int] | None = None


def set_wordlist(words: list[str]) -> None:
    """Install the BIP-39 wordlist (2048 words, index order)."""
    global _WORDLIST, _WORD_INDEX
    if len(words) != 2048:
        raise ValueError(f"BIP-39 wordlist must have 2048 words, got {len(words)}")
    _WORDLIST = [unicodedata.normalize("NFKD", w.strip()) for w in words]
    _WORD_INDEX = {w: i for i, w in enumerate(_WORDLIST)}


def load_wordlist(path: str) -> None:
    with open(path, encoding="utf-8") as f:
        set_wordlist([line for line in f.read().split() if line])


def wordlist_available() -> bool:
    return _WORDLIST is not None


def _require_wordlist() -> None:
    if _WORDLIST is None:
        raise RuntimeError(
            "BIP-39 wordlist not installed: call load_wordlist(path) or "
            "set_wordlist(words) first (the standard english.txt, 2048 words)"
        )


def entropy_to_phrase(entropy: bytes) -> str:
    """entropy (16/20/24/28/32 bytes) → mnemonic phrase."""
    _require_wordlist()
    if len(entropy) not in (16, 20, 24, 28, 32):
        raise ValueError("entropy must be 128-256 bits in 32-bit steps")
    checksum_bits = len(entropy) * 8 // 32
    checksum = hashlib.sha256(entropy).digest()
    value = int.from_bytes(entropy, "big")
    value = (value << checksum_bits) | (checksum[0] >> (8 - checksum_bits))
    total_bits = len(entropy) * 8 + checksum_bits
    n_words = total_bits // 11
    indices = [
        (value >> (11 * (n_words - 1 - i))) & 0x7FF for i in range(n_words)
    ]
    return " ".join(_WORDLIST[i] for i in indices)


def generate_random_from_system_entropy(strength_bytes: int = 32) -> str:
    """(mnemonic.rs:9)"""
    return entropy_to_phrase(os.urandom(strength_bytes))


def recover_from_phrase(phrase: str) -> str:
    """Validate a phrase's words + checksum; returns the normalized phrase
    (mnemonic.rs:16)."""
    _require_wordlist()
    words = unicodedata.normalize("NFKD", phrase).split()
    if len(words) not in (12, 15, 18, 21, 24):
        raise ValueError(f"invalid mnemonic length {len(words)}")
    value = 0
    for word in words:
        if word not in _WORD_INDEX:
            raise ValueError(f"unknown mnemonic word {word!r}")
        value = (value << 11) | _WORD_INDEX[word]
    checksum_bits = len(words) // 3
    entropy_bits = len(words) * 11 - checksum_bits
    checksum = value & ((1 << checksum_bits) - 1)
    entropy = (value >> checksum_bits).to_bytes(entropy_bits // 8, "big")
    expected = hashlib.sha256(entropy).digest()[0] >> (8 - checksum_bits)
    if checksum != expected:
        raise ValueError("mnemonic checksum mismatch")
    return " ".join(words)


def to_seed(phrase: str, passphrase: str | None = None) -> Seed:
    """(mnemonic.rs:20) — PBKDF2-HMAC-SHA512(phrase, "mnemonic"+pass, 2048)."""
    normalized = unicodedata.normalize("NFKD", phrase)
    salt = "mnemonic" + unicodedata.normalize("NFKD", passphrase or "")
    return hashlib.pbkdf2_hmac(
        "sha512", normalized.encode(), salt.encode(), 2048, dklen=64
    )
