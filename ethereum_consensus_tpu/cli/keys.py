"""EIP-2333/2334 hierarchical BLS key derivation.

Reference parity: ethereum-consensus/src/bin/ec/validator/keys.rs:127 —
hkdf_mod_r, lamport parent→child derivation, the EIP-2334 validator paths
m/12381/3600/{i}/0 (withdrawal) and m/12381/3600/{i}/0/0 (signing), and
parallel batch generation (rayon there, a process pool here).
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

from ..crypto import bls
from ..crypto.fields import R as BLS_MODULUS

__all__ = [
    "KeyPair",
    "hkdf_mod_r",
    "derive_master_sk",
    "derive_child_key",
    "derive_validator_keys",
    "generate",
]

_SALT = b"BLS-SIG-KEYGEN-SALT-"
_L = 48
_K = 32
_LAMPORT_COUNT = 255
_LAMPORT_L = _K * _LAMPORT_COUNT


@dataclass
class KeyPair:
    private_key: bls.SecretKey
    public_key: bls.PublicKey
    path: str


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]


def _hkdf(salt: bytes, ikm: bytes, info: bytes, length: int) -> bytes:
    return _hkdf_expand(_hkdf_extract(salt, ikm), info, length)


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def hkdf_mod_r(ikm: bytes) -> int:
    """(keys.rs:68) — EIP-2333 hkdf_mod_r with re-salting on zero."""
    key = 0
    salt = _sha256(_SALT)
    key_info = bytes([0, _L])
    ikm = ikm + b"\x00"
    while key == 0:
        okm = _hkdf(salt, ikm, key_info, _L)
        key = int.from_bytes(okm, "big") % BLS_MODULUS
        salt = _sha256(salt)
    return key


def _ikm_to_lamport_secret_key(ikm: bytes, salt: bytes) -> list[bytes]:
    okm = _hkdf(salt, ikm, b"", _LAMPORT_L)
    return [okm[i * _K : (i + 1) * _K] for i in range(_LAMPORT_COUNT)]


def _parent_key_to_lamport_public_key(parent_key: int, index: int) -> bytes:
    """(keys.rs:47)"""
    salt = index.to_bytes(4, "big")
    ikm = parent_key.to_bytes(32, "big")
    lamport_0 = _ikm_to_lamport_secret_key(ikm, salt)
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    lamport_1 = _ikm_to_lamport_secret_key(not_ikm, salt)
    lamport_public_key = b"".join(_sha256(k) for k in lamport_0) + b"".join(
        _sha256(k) for k in lamport_1
    )
    return _sha256(lamport_public_key)


def derive_child_key(parent_key: int, index: int) -> int:
    """(keys.rs:96)"""
    return hkdf_mod_r(_parent_key_to_lamport_public_key(parent_key, index))


def derive_master_sk(seed: bytes) -> int:
    """(keys.rs:101)"""
    return hkdf_mod_r(seed)


def _to_key_pair(key: int, path: str) -> KeyPair:
    sk = bls.SecretKey(key)
    return KeyPair(private_key=sk, public_key=sk.public_key(), path=path)


def derive_validator_keys(root_key: int, index: int) -> tuple[KeyPair, KeyPair]:
    """(keys.rs:117) → (signing, withdrawal) at the EIP-2334 paths."""
    withdrawal_key = root_key
    for step in (12381, 3600, index, 0):
        withdrawal_key = derive_child_key(withdrawal_key, step)
    signing_key = derive_child_key(withdrawal_key, 0)
    return (
        _to_key_pair(signing_key, f"m/12381/3600/{index}/0/0"),
        _to_key_pair(withdrawal_key, f"m/12381/3600/{index}/0"),
    )


def generate(
    seed: bytes, start: int, end: int, parallel: bool = True
) -> tuple[list[KeyPair], list[KeyPair]]:
    """(keys.rs:127) — batch keygen; data-parallel like the reference's
    rayon path when ``parallel`` and the range is big enough."""
    root_key = derive_master_sk(seed)
    indices = range(start, end)
    if parallel and len(indices) > 4:
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor() as pool:
            pairs = list(pool.map(_derive_for, [(root_key, i) for i in indices]))
    else:
        pairs = [derive_validator_keys(root_key, i) for i in indices]
    signing = [p[0] for p in pairs]
    withdrawal = [p[1] for p in pairs]
    return signing, withdrawal


def _derive_for(args: tuple[int, int]) -> tuple[KeyPair, KeyPair]:
    return derive_validator_keys(*args)
