// From-scratch BLS12-381 host backend (the role blst plays for the
// reference, ethereum-consensus/src/crypto/bls.rs): Montgomery Fp,
// Fp2/Fp6/Fp12 tower, G1/G2, optimal ate pairing with a shared final
// exponentiation, RFC 9380 hash-to-G2, Pippenger MSM, and the eth BLS
// verification APIs. Semantics mirror the pure-Python oracle in
// crypto/{fields,curves,pairing,hash_to_curve}.py bit-for-bit at the API
// boundary; tests cross-check the two.
//
// Built by native/bls.py with g++ -O3 -shared; exposed via ctypes.

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__x86_64__)
#include <immintrin.h>
#define EC_FP8_COMPILED 1
#endif

#include "bls12_381_constants.h"

typedef uint64_t u64;
typedef unsigned __int128 u128;
typedef uint8_t u8;
typedef uint32_t u32;

// ---------------------------------------------------------------------------
// Fp: 6x64-bit Montgomery arithmetic
// ---------------------------------------------------------------------------

struct Fp { u64 l[6]; };

static const int NL = 6;

static u64 FP_INV;      // -p^{-1} mod 2^64
static Fp FP_R2;        // 2^768 mod p (standard-form limbs)
static Fp FP_ONE;       // 2^384 mod p == Montgomery form of 1
static Fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};
static Fp FP_TWO_INV;   // 2^{-1} (Montgomery form), for the Fp2 sqrt norm method

// big exponents, computed at init from p
static u64 EXP_P_MINUS_2[6];
static u64 EXP_P_PLUS_1_DIV_4[6];
static u64 EXP_P_MINUS_3_DIV_4[6];
static u64 EXP_P_MINUS_1_DIV_2[6];
static u64 EXP_P_MINUS_1_DIV_6[6];
static u64 P_MINUS_1_DIV_2_STD[6];  // for lexicographic-largest compares

static inline u64 adc(u64 a, u64 b, u64& carry) {
  u128 t = (u128)a + b + carry;
  carry = (u64)(t >> 64);
  return (u64)t;
}

static inline u64 sbb(u64 a, u64 b, u64& borrow) {
  u128 t = (u128)a - b - borrow;
  borrow = (u64)((t >> 64) & 1);
  return (u64)t;
}

static inline int fp_cmp_raw(const u64* a, const u64* b) {
  for (int i = NL - 1; i >= 0; i--) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

static inline bool fp_is_zero(const Fp& a) {
  u64 acc = 0;
  for (int i = 0; i < NL; i++) acc |= a.l[i];
  return acc == 0;
}

static inline bool fp_eq(const Fp& a, const Fp& b) {
  u64 acc = 0;
  for (int i = 0; i < NL; i++) acc |= a.l[i] ^ b.l[i];
  return acc == 0;
}

static inline void fp_add(Fp& out, const Fp& a, const Fp& b) {
  u64 carry = 0;
  for (int i = 0; i < NL; i++) out.l[i] = adc(a.l[i], b.l[i], carry);
  if (carry || fp_cmp_raw(out.l, P_RAW.l) >= 0) {
    u64 borrow = 0;
    for (int i = 0; i < NL; i++) out.l[i] = sbb(out.l[i], P_RAW.l[i], borrow);
  }
}

static inline void fp_sub(Fp& out, const Fp& a, const Fp& b) {
  u64 borrow = 0;
  for (int i = 0; i < NL; i++) out.l[i] = sbb(a.l[i], b.l[i], borrow);
  if (borrow) {
    u64 carry = 0;
    for (int i = 0; i < NL; i++) out.l[i] = adc(out.l[i], P_RAW.l[i], carry);
  }
}

static inline void fp_neg(Fp& out, const Fp& a) {
  if (fp_is_zero(a)) { out = a; return; }
  u64 borrow = 0;
  for (int i = 0; i < NL; i++) out.l[i] = sbb(P_RAW.l[i], a.l[i], borrow);
}

static inline void fp_dbl(Fp& out, const Fp& a) { fp_add(out, a, a); }

// Montgomery "no-carry" CIOS multiplication: out = a*b*2^-384 mod p.
// Valid because p's top limb (0x1a01..., 61 bits) leaves enough slack that
// the per-round high words never overflow a single u64 accumulator
// (requires top limb < (2^64-1)/2; the same precondition gnark documents).
// ~30% faster than the classic 8-word CIOS on this compiler.
static inline void madd1(u64 a, u64 b, u64 c, u64& hi, u64& lo) {
  u128 r = (u128)a * b + c; hi = (u64)(r >> 64); lo = (u64)r;
}
static inline void madd2(u64 a, u64 b, u64 c, u64 d, u64& hi, u64& lo) {
  u128 r = (u128)a * b + c + d; hi = (u64)(r >> 64); lo = (u64)r;
}

#if defined(__x86_64__) && defined(__ADX__) && defined(__BMI2__)
#define EC_FP_MUL_ADX 1
// ADX/BMI2 dual-carry-chain rounds: the a*b[i] row streams lo words into
// t[j] on the ADCX (CF) chain and hi words into t[j+1] on the ADOX (OF)
// chain, so the two carry chains run in parallel; the m*p reduction row
// does the same with t0 annihilated. Same no-carry invariant as the C
// path (t6 never produces a carry-out) — the chains are folded into t6
// with the zero register. ~25% faster than what the compiler emits for
// the u128 formulation.
static void fp_mul(Fp& out, const Fp& a, const Fp& b) {
  u64 t0 = 0, t1 = 0, t2 = 0, t3 = 0, t4 = 0, t5 = 0, t6 = 0;
  const u64* ap = a.l;
  const u64* pp = P_RAW.l;
  for (int i = 0; i < NL; i++) {
    u64 bi = b.l[i];
    asm volatile(
        "xor %%r15d, %%r15d\n\t"
        "movq %[bi], %%rdx\n\t"
        "mulxq 0(%[ap]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t0]\n\t"
        "adoxq %%rbx, %[t1]\n\t"
        "mulxq 8(%[ap]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t1]\n\t"
        "adoxq %%rbx, %[t2]\n\t"
        "mulxq 16(%[ap]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t2]\n\t"
        "adoxq %%rbx, %[t3]\n\t"
        "mulxq 24(%[ap]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t3]\n\t"
        "adoxq %%rbx, %[t4]\n\t"
        "mulxq 32(%[ap]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t4]\n\t"
        "adoxq %%rbx, %[t5]\n\t"
        "mulxq 40(%[ap]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t5]\n\t"
        "adoxq %%rbx, %[t6]\n\t"
        "adcxq %%r15, %[t6]\n\t"
        : [t0]"+r"(t0), [t1]"+r"(t1), [t2]"+r"(t2), [t3]"+r"(t3),
          [t4]"+r"(t4), [t5]"+r"(t5), [t6]"+r"(t6)
        : [ap]"r"(ap), [bi]"r"(bi), "m"(*(const u64(*)[6])ap)
        : "rax", "rbx", "rdx", "r15", "cc");
    u64 m = t0 * FP_INV;
    asm volatile(
        "xor %%r15d, %%r15d\n\t"
        "movq %[m], %%rdx\n\t"
        "mulxq 0(%[pp]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t0]\n\t"
        "adoxq %%rbx, %[t1]\n\t"
        "mulxq 8(%[pp]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t1]\n\t"
        "adoxq %%rbx, %[t2]\n\t"
        "mulxq 16(%[pp]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t2]\n\t"
        "adoxq %%rbx, %[t3]\n\t"
        "mulxq 24(%[pp]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t3]\n\t"
        "adoxq %%rbx, %[t4]\n\t"
        "mulxq 32(%[pp]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t4]\n\t"
        "adoxq %%rbx, %[t5]\n\t"
        "mulxq 40(%[pp]), %%rax, %%rbx\n\t"
        "adcxq %%rax, %[t5]\n\t"
        "adoxq %%rbx, %[t6]\n\t"
        "adcxq %%r15, %[t6]\n\t"
        : [t0]"+r"(t0), [t1]"+r"(t1), [t2]"+r"(t2), [t3]"+r"(t3),
          [t4]"+r"(t4), [t5]"+r"(t5), [t6]"+r"(t6)
        : [pp]"r"(pp), [m]"r"(m), "m"(*(const u64(*)[6])pp)
        : "rax", "rbx", "rdx", "r15", "cc");
    t0 = t1; t1 = t2; t2 = t3; t3 = t4; t4 = t5; t5 = t6; t6 = 0;
  }
  out.l[0] = t0; out.l[1] = t1; out.l[2] = t2;
  out.l[3] = t3; out.l[4] = t4; out.l[5] = t5;
  if (fp_cmp_raw(out.l, P_RAW.l) >= 0) {
    u64 borrow = 0;
    for (int i = 0; i < NL; i++) out.l[i] = sbb(out.l[i], P_RAW.l[i], borrow);
  }
}
#else
static void fp_mul(Fp& out, const Fp& a, const Fp& b) {
  u64 t0, t1, t2, t3, t4, t5;
  u64 A, C, m;
  {
    u128 r = (u128)a.l[0] * b.l[0]; t0 = (u64)r; A = (u64)(r >> 64);
    m = t0 * FP_INV;
    r = (u128)m * P_RAW.l[0] + t0; C = (u64)(r >> 64);
    madd1(a.l[1], b.l[0], A, A, t1); madd2(m, P_RAW.l[1], C, t1, C, t0);
    madd1(a.l[2], b.l[0], A, A, t2); madd2(m, P_RAW.l[2], C, t2, C, t1);
    madd1(a.l[3], b.l[0], A, A, t3); madd2(m, P_RAW.l[3], C, t3, C, t2);
    madd1(a.l[4], b.l[0], A, A, t4); madd2(m, P_RAW.l[4], C, t4, C, t3);
    madd1(a.l[5], b.l[0], A, A, t5); madd2(m, P_RAW.l[5], C, t5, C, t4);
    t5 = C + A;
  }
  for (int i = 1; i < NL; i++) {
    u64 bi = b.l[i];
    madd1(a.l[0], bi, t0, A, t0);
    m = t0 * FP_INV;
    { u128 r = (u128)m * P_RAW.l[0] + t0; C = (u64)(r >> 64); }
    madd2(a.l[1], bi, A, t1, A, t1); madd2(m, P_RAW.l[1], C, t1, C, t0);
    madd2(a.l[2], bi, A, t2, A, t2); madd2(m, P_RAW.l[2], C, t2, C, t1);
    madd2(a.l[3], bi, A, t3, A, t3); madd2(m, P_RAW.l[3], C, t3, C, t2);
    madd2(a.l[4], bi, A, t4, A, t4); madd2(m, P_RAW.l[4], C, t4, C, t3);
    madd2(a.l[5], bi, A, t5, A, t5); madd2(m, P_RAW.l[5], C, t5, C, t4);
    t5 = C + A;
  }
  out.l[0] = t0; out.l[1] = t1; out.l[2] = t2;
  out.l[3] = t3; out.l[4] = t4; out.l[5] = t5;
  if (fp_cmp_raw(out.l, P_RAW.l) >= 0) {
    u64 borrow = 0;
    for (int i = 0; i < NL; i++) out.l[i] = sbb(out.l[i], P_RAW.l[i], borrow);
  }
}
#endif  // EC_FP_MUL_ADX

#ifdef EC_FP_MUL_ADX
// With the ADX multiplier, mul(a, a) beats the dedicated C squaring
// (measured 36ns vs 72ns: the 12-limb stack buffer costs more than the
// saved cross products).
static inline void fp_sqr(Fp& out, const Fp& a) { fp_mul(out, a, a); }
#else
// Dedicated Montgomery squaring: full 12-limb square (cross products
// doubled by a 1-bit shift, diagonal added) + 6-round reduction.
// ~30% faster again than fp_mul(a, a).
static void fp_sqr(Fp& out, const Fp& a) {
  u64 t[12];
  u64 c;
  {
    u128 r;
    r = (u128)a.l[0] * a.l[1];            t[1] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[0] * a.l[2] + c;        t[2] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[0] * a.l[3] + c;        t[3] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[0] * a.l[4] + c;        t[4] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[0] * a.l[5] + c;        t[5] = (u64)r; t[6] = (u64)(r >> 64);
  }
  {
    u128 r;
    r = (u128)a.l[1] * a.l[2] + t[3];     t[3] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[1] * a.l[3] + t[4] + c; t[4] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[1] * a.l[4] + t[5] + c; t[5] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[1] * a.l[5] + t[6] + c; t[6] = (u64)r; t[7] = (u64)(r >> 64);
  }
  {
    u128 r;
    r = (u128)a.l[2] * a.l[3] + t[5];     t[5] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[2] * a.l[4] + t[6] + c; t[6] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[2] * a.l[5] + t[7] + c; t[7] = (u64)r; t[8] = (u64)(r >> 64);
  }
  {
    u128 r;
    r = (u128)a.l[3] * a.l[4] + t[7];     t[7] = (u64)r; c = (u64)(r >> 64);
    r = (u128)a.l[3] * a.l[5] + t[8] + c; t[8] = (u64)r; t[9] = (u64)(r >> 64);
  }
  {
    u128 r;
    r = (u128)a.l[4] * a.l[5] + t[9];     t[9] = (u64)r; t[10] = (u64)(r >> 64);
  }
  t[11] = t[10] >> 63;
  for (int i = 10; i > 1; i--) t[i] = (t[i] << 1) | (t[i - 1] >> 63);
  t[1] <<= 1;
  u64 carry = 0;
  t[0] = 0;
  for (int i = 0; i < NL; i++) {
    u128 sq = (u128)a.l[i] * a.l[i];
    u128 lo = (u128)t[2 * i] + (u64)sq + carry;
    t[2 * i] = (u64)lo;
    u128 hi = (u128)t[2 * i + 1] + (u64)(sq >> 64) + (u64)(lo >> 64);
    t[2 * i + 1] = (u64)hi;
    carry = (u64)(hi >> 64);
  }
  u64 carry2 = 0;
  for (int i = 0; i < NL; i++) {
    u64 m = t[i] * FP_INV;
    u64 cc = 0;
    for (int j = 0; j < NL; j++) {
      u128 cur = (u128)t[i + j] + (u128)m * P_RAW.l[j] + cc;
      t[i + j] = (u64)cur;
      cc = (u64)(cur >> 64);
    }
    u128 cur = (u128)t[i + 6] + cc + carry2;
    t[i + 6] = (u64)cur;
    carry2 = (u64)(cur >> 64);
  }
  for (int i = 0; i < NL; i++) out.l[i] = t[i + 6];
  if (carry2 || fp_cmp_raw(out.l, P_RAW.l) >= 0) {
    u64 borrow = 0;
    for (int i = 0; i < NL; i++) out.l[i] = sbb(out.l[i], P_RAW.l[i], borrow);
  }
}
#endif  // !EC_FP_MUL_ADX

static void fp_to_mont(Fp& out, const Fp& std_form) { fp_mul(out, std_form, FP_R2); }
static void fp_from_mont(Fp& out, const Fp& mont) {
  Fp one_std = {{1, 0, 0, 0, 0, 0}};
  fp_mul(out, mont, one_std);
}

// exponent is a little-endian limb array; 4-bit fixed window (windows are
// 4-aligned so they never straddle a limb). Halves the multiply count of
// plain square-and-multiply on the 381-bit sqrt/legendre exponents.
static void fp_pow(Fp& out, const Fp& base, const u64* exp, int exp_limbs) {
  int bits = exp_limbs * 64;
  while (bits > 0 && !((exp[(bits - 1) >> 6] >> ((bits - 1) & 63)) & 1)) bits--;
  if (bits == 0) { out = FP_ONE; return; }
  Fp tbl[15];  // base^1 .. base^15
  tbl[0] = base;
  for (int i = 1; i < 15; i++) fp_mul(tbl[i], tbl[i - 1], base);
  Fp result = FP_ONE;
  bool started = false;
  for (int w = ((bits - 1) / 4) * 4; w >= 0; w -= 4) {
    if (started) {
      fp_sqr(result, result); fp_sqr(result, result);
      fp_sqr(result, result); fp_sqr(result, result);
    }
    int d = (int)((exp[w >> 6] >> (w & 63)) & 15);
    if (d) {
      if (started) fp_mul(result, result, tbl[d - 1]);
      else { result = tbl[d - 1]; started = true; }
    }
  }
  out = result;
}

// Binary extended Euclid on standard-form limbs — ~10x faster than the
// Fermat p-2 power ladder. Variable-time is fine here: inversion inputs
// are public curve data (coordinates, pairing values), never secret keys.
static inline bool limbs6_is_zero(const u64* a) {
  return !(a[0] | a[1] | a[2] | a[3] | a[4] | a[5]);
}
static inline bool limbs6_is_one(const u64* a) {
  return a[0] == 1 && !(a[1] | a[2] | a[3] | a[4] | a[5]);
}
static inline void limbs6_shr1(u64* a) {
  for (int i = 0; i < 5; i++) a[i] = (a[i] >> 1) | (a[i + 1] << 63);
  a[5] >>= 1;
}
static inline void limbs6_add_p_shr1(u64* a) {
  // (a + p) / 2 where a + p may carry into a 7th word
  u64 carry = 0;
  u64 t[6];
  for (int i = 0; i < 6; i++) {
    u128 cur = (u128)a[i] + P_RAW.l[i] + carry;
    t[i] = (u64)cur;
    carry = (u64)(cur >> 64);
  }
  for (int i = 0; i < 5; i++) a[i] = (t[i] >> 1) | (t[i + 1] << 63);
  a[5] = (t[5] >> 1) | (carry << 63);
}
static inline void limbs6_sub(u64* a, const u64* b) {
  u64 borrow = 0;
  for (int i = 0; i < 6; i++) a[i] = sbb(a[i], b[i], borrow);
}
static inline void limbs6_sub_mod_p(u64* a, const u64* b) {
  // a = (a - b) mod p for a, b < p
  u64 borrow = 0;
  for (int i = 0; i < 6; i++) a[i] = sbb(a[i], b[i], borrow);
  if (borrow) {
    u64 carry = 0;
    for (int i = 0; i < 6; i++) {
      u128 cur = (u128)a[i] + P_RAW.l[i] + carry;
      a[i] = (u64)cur;
      carry = (u64)(cur >> 64);
    }
  }
}

static void fp_inv(Fp& out, const Fp& a) {
  if (fp_is_zero(a)) { out = FP_ZERO; return; }  // matches 0^(p-2) == 0
  Fp a_std;
  fp_from_mont(a_std, a);
  u64 u[6], v[6], x1[6] = {1, 0, 0, 0, 0, 0}, x2[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 6; i++) { u[i] = a_std.l[i]; v[i] = P_RAW.l[i]; }
  while (!limbs6_is_one(u) && !limbs6_is_one(v)) {
    while (!(u[0] & 1)) {
      limbs6_shr1(u);
      if (x1[0] & 1) limbs6_add_p_shr1(x1); else limbs6_shr1(x1);
    }
    while (!(v[0] & 1)) {
      limbs6_shr1(v);
      if (x2[0] & 1) limbs6_add_p_shr1(x2); else limbs6_shr1(x2);
    }
    if (fp_cmp_raw(u, v) >= 0) {
      limbs6_sub(u, v);
      limbs6_sub_mod_p(x1, x2);
    } else {
      limbs6_sub(v, u);
      limbs6_sub_mod_p(x2, x1);
    }
  }
  Fp inv_std;
  const u64* r = limbs6_is_one(u) ? x1 : x2;
  for (int i = 0; i < 6; i++) inv_std.l[i] = r[i];
  fp_to_mont(out, inv_std);
}

// returns false if not a square
static bool fp_sqrt(Fp& out, const Fp& a) {
  Fp cand, check;
  fp_pow(cand, a, EXP_P_PLUS_1_DIV_4, 6);
  fp_sqr(check, cand);
  if (!fp_eq(check, a)) return false;
  out = cand;
  return true;
}

static int fp_sgn0(const Fp& mont) {
  Fp std_form;
  fp_from_mont(std_form, mont);
  return (int)(std_form.l[0] & 1);
}

static bool fp_is_lex_largest(const Fp& mont) {
  Fp std_form;
  fp_from_mont(std_form, mont);
  return fp_cmp_raw(std_form.l, P_MINUS_1_DIV_2_STD) > 0;
}

// big-endian 48-byte IO (standard form)
static void fp_to_bytes(u8 out[48], const Fp& mont) {
  Fp s;
  fp_from_mont(s, mont);
  for (int i = 0; i < NL; i++) {
    u64 w = s.l[NL - 1 - i];
    for (int j = 0; j < 8; j++) out[i * 8 + j] = (u8)(w >> (56 - 8 * j));
  }
}

// returns false if value >= p
static bool fp_from_bytes(Fp& out, const u8 in[48]) {
  Fp s;
  for (int i = 0; i < NL; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[i * 8 + j];
    s.l[NL - 1 - i] = w;
  }
  if (fp_cmp_raw(s.l, P_RAW.l) >= 0) return false;
  fp_to_mont(out, s);
  return true;
}

static void fp_from_u64(Fp& out, u64 v) {
  Fp s = {{v, 0, 0, 0, 0, 0}};
  fp_to_mont(out, s);
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1)
// ---------------------------------------------------------------------------

struct Fp2 { Fp c0, c1; };

static Fp2 FP2_ZERO, FP2_ONE;

static inline bool fp2_is_zero(const Fp2& a) { return fp_is_zero(a.c0) && fp_is_zero(a.c1); }
static inline bool fp2_eq(const Fp2& a, const Fp2& b) { return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1); }

static inline void fp2_add(Fp2& o, const Fp2& a, const Fp2& b) {
  fp_add(o.c0, a.c0, b.c0); fp_add(o.c1, a.c1, b.c1);
}
static inline void fp2_sub(Fp2& o, const Fp2& a, const Fp2& b) {
  fp_sub(o.c0, a.c0, b.c0); fp_sub(o.c1, a.c1, b.c1);
}
static inline void fp2_neg(Fp2& o, const Fp2& a) { fp_neg(o.c0, a.c0); fp_neg(o.c1, a.c1); }
static inline void fp2_dbl(Fp2& o, const Fp2& a) { fp2_add(o, a, a); }

static void fp2_mul(Fp2& o, const Fp2& a, const Fp2& b) {
  Fp t0, t1, t2, s0, s1;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(s0, a.c0, a.c1);
  fp_add(s1, b.c0, b.c1);
  fp_mul(t2, s0, s1);
  fp_sub(o.c0, t0, t1);
  fp_sub(t2, t2, t0);
  fp_sub(o.c1, t2, t1);
}

static void fp2_sqr(Fp2& o, const Fp2& a) {
  Fp s, d, t;
  fp_add(s, a.c0, a.c1);
  fp_sub(d, a.c0, a.c1);
  fp_mul(t, a.c0, a.c1);
  fp_mul(o.c0, s, d);
  fp_add(o.c1, t, t);
}

static void fp2_scalar_mul(Fp2& o, const Fp2& a, const Fp& k) {
  fp_mul(o.c0, a.c0, k); fp_mul(o.c1, a.c1, k);
}

// xi = u + 1: (a + bu)(1 + u) = (a - b) + (a + b)u
static void fp2_mul_by_xi(Fp2& o, const Fp2& a) {
  Fp t0, t1;
  fp_sub(t0, a.c0, a.c1);
  fp_add(t1, a.c0, a.c1);
  o.c0 = t0; o.c1 = t1;
}

static inline void fp2_conj(Fp2& o, const Fp2& a) { o.c0 = a.c0; fp_neg(o.c1, a.c1); }

static void fp2_inv(Fp2& o, const Fp2& a) {
  Fp n0, n1, norm, inv;
  fp_sqr(n0, a.c0);
  fp_sqr(n1, a.c1);
  fp_add(norm, n0, n1);
  fp_inv(inv, norm);
  fp_mul(o.c0, a.c0, inv);
  Fp t;
  fp_mul(t, a.c1, inv);
  fp_neg(o.c1, t);
}

// 4-bit fixed window, same shape as fp_pow
static void fp2_pow(Fp2& out, const Fp2& base, const u64* exp, int exp_limbs) {
  int bits = exp_limbs * 64;
  while (bits > 0 && !((exp[(bits - 1) >> 6] >> ((bits - 1) & 63)) & 1)) bits--;
  if (bits == 0) { out = FP2_ONE; return; }
  Fp2 tbl[15];
  tbl[0] = base;
  for (int i = 1; i < 15; i++) fp2_mul(tbl[i], tbl[i - 1], base);
  Fp2 result = FP2_ONE;
  bool started = false;
  for (int w = ((bits - 1) / 4) * 4; w >= 0; w -= 4) {
    if (started) {
      fp2_sqr(result, result); fp2_sqr(result, result);
      fp2_sqr(result, result); fp2_sqr(result, result);
    }
    int d = (int)((exp[w >> 6] >> (w & 63)) & 15);
    if (d) {
      if (started) fp2_mul(result, result, tbl[d - 1]);
      else { result = tbl[d - 1]; started = true; }
    }
  }
  out = result;
}

static int fp2_sgn0(const Fp2& a) {
  Fp s0;
  fp_from_mont(s0, a.c0);
  int sign0 = (int)(s0.l[0] & 1);
  bool zero0 = fp_is_zero(a.c0);
  Fp s1;
  fp_from_mont(s1, a.c1);
  int sign1 = (int)(s1.l[0] & 1);
  return sign0 | ((zero0 ? 1 : 0) & sign1);
}

static bool fp2_is_lex_largest(const Fp2& a) {
  if (!fp_is_zero(a.c1)) return fp_is_lex_largest(a.c1);
  return fp_is_lex_largest(a.c0);
}

// Fp2 sqrt via the norm map, ~2x cheaper than the direct p≡3 mod 4 tower
// algorithm (2-3 Fp pow chains instead of 2 Fp2 pow chains, and a
// non-square input is rejected after the FIRST chain — which also makes
// the failing gx1 probe inside SSWU cheap). With z = a + b·i, i² = −1:
// z is a square in Fp2 iff N = a² + b² is a square in Fp; for s = √N,
// exactly one of (a ± s)/2 is a nonzero square in Fp (their product is
// −(b/2)², a non-residue when b ≠ 0 since χ(−1) = −1 for p ≡ 3 mod 4);
// with x² = (a ± s)/2, the root is x + (b / 2x)·i.
static bool fp2_sqrt(Fp2& out, const Fp2& a) {
  if (fp2_is_zero(a)) { out = a; return true; }
  if (fp_is_zero(a.c1)) {
    // real input: always a square in Fp2 — √a0, or i·√(−a0) when a0 is
    // a non-residue (exactly one works, again because χ(−1) = −1)
    Fp r;
    if (fp_sqrt(r, a.c0)) { out.c0 = r; out.c1 = FP_ZERO; return true; }
    Fp na;
    fp_neg(na, a.c0);
    fp_sqrt(r, na);
    out.c0 = FP_ZERO; out.c1 = r;
    return true;
  }
  Fp n, t, s, x;
  fp_sqr(n, a.c0);
  fp_sqr(t, a.c1);
  fp_add(n, n, t);
  if (!fp_sqrt(s, n)) return false;  // norm non-square => no root in Fp2
  fp_add(t, a.c0, s);
  fp_mul(t, t, FP_TWO_INV);
  if (!fp_sqrt(x, t) || fp_is_zero(x)) {
    fp_sub(t, a.c0, s);
    fp_mul(t, t, FP_TWO_INV);
    if (!fp_sqrt(x, t) || fp_is_zero(x)) return false;  // unreachable for b != 0
  }
  Fp d, y;
  fp_dbl(d, x);
  fp_inv(d, d);
  fp_mul(y, a.c1, d);
  out.c0 = x;
  out.c1 = y;
  return true;
}

static void fp2_from_raw(Fp2& out, const Fp2Raw& r) {
  Fp c0s, c1s;
  for (int i = 0; i < NL; i++) { c0s.l[i] = r.c0.l[i]; c1s.l[i] = r.c1.l[i]; }
  fp_to_mont(out.c0, c0s);
  fp_to_mont(out.c1, c1s);
}

// ---------------------------------------------------------------------------
// Batched scalar inversion (Montgomery's trick): one fp_inv plus 3(n-1)
// multiplies for n inverses. Zero inputs pass through as zero (matching
// fp_inv). Used by the eight-wide batch paths below, where per-element
// fp_inv calls would otherwise dominate the scalar epilogues.
// ---------------------------------------------------------------------------
static void fp_inv_batch(Fp* vals, int n) {
  if (n <= 0) return;
  Fp pre[64];
  Fp acc = FP_ONE;
  int nz[64];
  int m = 0;
  for (int i = 0; i < n; i++) {
    if (fp_is_zero(vals[i])) continue;
    pre[m] = acc;
    fp_mul(acc, acc, vals[i]);
    nz[m++] = i;
  }
  if (m == 0) return;
  Fp inv;
  fp_inv(inv, acc);
  for (int k = m - 1; k >= 0; k--) {
    Fp v;
    fp_mul(v, inv, pre[k]);
    fp_mul(inv, inv, vals[nz[k]]);
    vals[nz[k]] = v;
  }
}

// n Fp2 inverses via the same trick on the norms: inv(a+bi) =
// (a-bi)/(a^2+b^2), so n Fp2 inversions cost one fp_inv + O(n) muls.
static void fp2_inv_batch(Fp2* vals, int n) {
  if (n <= 0) return;
  Fp norms[64];
  for (int i = 0; i < n; i++) {
    Fp t0, t1;
    fp_sqr(t0, vals[i].c0);
    fp_sqr(t1, vals[i].c1);
    fp_add(norms[i], t0, t1);
  }
  fp_inv_batch(norms, n);
  for (int i = 0; i < n; i++) {
    fp_mul(vals[i].c0, vals[i].c0, norms[i]);
    fp_mul(vals[i].c1, vals[i].c1, norms[i]);
    fp_neg(vals[i].c1, vals[i].c1);
  }
}

// ===========================================================================
// FP8: eight-way SoA Fp arithmetic on AVX-512 IFMA (radix-2^52 Montgomery).
//
// The RLC batch-verification hot path spends most of its per-set scalar
// time in fixed-exponent Fp power chains — the norm-method Fp2 square
// roots inside hash-to-G2's SSWU maps and G2 signature decompression.
// Those chains are identical instruction sequences over independent
// data, so they vectorize losslessly: each __m512i holds limb j of
// EIGHT field elements and vpmadd52{lo,hi}uq performs eight 52x52-bit
// multiply-accumulates per instruction. The Montgomery radix here is
// 2^416 (8 limbs x 52 bits) — distinct from the scalar path's 2^384 —
// and values cross between domains through canonical limbs at batch
// boundaries only.
//
// Dispatch is at RUN time (__builtin_cpu_supports + a self-check), so a
// build cached on one machine can never execute IFMA on a host without
// it; every batch entry point falls back to the scalar routines.
// ===========================================================================

static bool FP8_READY = false;
static u64 P52[8];        // p, radix-2^52 limbs
static u64 P52_INV;       // -p^{-1} mod 2^52
static u64 R52SQ_52[8];   // 2^832 mod p (canonical radix-52): to-Montgomery multiplier
static u64 TWOINV_M52[8]; // 2^{-1} in R52-Montgomery form == 2^415 mod p
static u64 X2_448_52[8];  // 2^448 mod p: scalar-Montgomery -> R52-Montgomery
static u64 X2_384_52[8];  // 2^384 mod p: R52-Montgomery -> scalar-Montgomery
static const u64 MASK52 = (1ULL << 52) - 1;

// 384-bit value: 6x64 canonical limbs <-> 8x52 canonical limbs
static void limbs6_to_52(u64 out[8], const u64 in[6]) {
  out[0] = in[0] & MASK52;
  out[1] = ((in[0] >> 52) | (in[1] << 12)) & MASK52;
  out[2] = ((in[1] >> 40) | (in[2] << 24)) & MASK52;
  out[3] = ((in[2] >> 28) | (in[3] << 36)) & MASK52;
  out[4] = ((in[3] >> 16) | (in[4] << 48)) & MASK52;
  out[5] = (in[4] >> 4) & MASK52;
  out[6] = ((in[4] >> 56) | (in[5] << 8)) & MASK52;
  out[7] = in[5] >> 44;
}

static void limbs52_to_6(u64 out[6], const u64 in[8]) {
  out[0] = in[0] | (in[1] << 52);
  out[1] = (in[1] >> 12) | (in[2] << 40);
  out[2] = (in[2] >> 24) | (in[3] << 28);
  out[3] = (in[3] >> 36) | (in[4] << 16);
  out[4] = (in[4] >> 48) | (in[5] << 4) | (in[6] << 56);
  out[5] = (in[6] >> 8) | (in[7] << 44);
}

#ifdef EC_FP8_COMPILED
#define EC_FP8_TARGET \
  __attribute__((target("avx512f,avx512ifma,avx512vl,avx512dq,avx512bw")))

struct Fp8 { __m512i l[8]; };  // l[j] = limb j of lanes 0..7

EC_FP8_TARGET static void fp8_bcast(Fp8& o, const u64 limbs[8]) {
  for (int j = 0; j < 8; j++) o.l[j] = _mm512_set1_epi64((long long)limbs[j]);
}

// Montgomery product, CIOS over radix 2^52. Accumulator limbs live in
// 64-bit lanes with 12 bits of headroom; each physical slot receives at
// most four sub-2^52 addends per iteration across eight iterations
// (< 2^57 total), so no intra-loop carries are needed. Inputs must be
// canonical (< p, 52-bit limbs); output is canonical.
EC_FP8_TARGET static void fp8_montmul(Fp8& o, const Fp8& a, const Fp8& b) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512i pinv = _mm512_set1_epi64((long long)P52_INV);
  __m512i pv[8];
  for (int j = 0; j < 8; j++) pv[j] = _mm512_set1_epi64((long long)P52[j]);
  __m512i acc[9];
  for (int j = 0; j < 9; j++) acc[j] = zero;
  for (int i = 0; i < 8; i++) {
    const __m512i bi = b.l[i];
    for (int j = 0; j < 8; j++)
      acc[j] = _mm512_madd52lo_epu64(acc[j], a.l[j], bi);
    const __m512i m = _mm512_madd52lo_epu64(zero, acc[0], pinv);
    acc[0] = _mm512_madd52lo_epu64(acc[0], m, pv[0]);
    const __m512i carry = _mm512_srli_epi64(acc[0], 52);
    for (int j = 1; j < 8; j++)
      acc[j] = _mm512_madd52lo_epu64(acc[j], m, pv[j]);
    for (int j = 0; j < 8; j++)
      acc[j + 1] = _mm512_madd52hi_epu64(acc[j + 1], a.l[j], bi);
    for (int j = 0; j < 8; j++)
      acc[j + 1] = _mm512_madd52hi_epu64(acc[j + 1], m, pv[j]);
    acc[1] = _mm512_add_epi64(acc[1], carry);
    for (int j = 0; j < 8; j++) acc[j] = acc[j + 1];
    acc[8] = zero;
  }
  // carry-normalize to 52-bit limbs (result < 2p fits 416 bits)
  const __m512i mask = _mm512_set1_epi64((long long)MASK52);
  __m512i cr = zero;
  for (int j = 0; j < 8; j++) {
    acc[j] = _mm512_add_epi64(acc[j], cr);
    cr = _mm512_srli_epi64(acc[j], 52);
    acc[j] = _mm512_and_si512(acc[j], mask);
  }
  // conditional subtract p, lanewise
  __m512i d[8], bor = zero;
  const __m512i two52 = _mm512_set1_epi64(1LL << 52);
  for (int j = 0; j < 8; j++) {
    __m512i t = _mm512_sub_epi64(
        _mm512_add_epi64(acc[j], two52), _mm512_add_epi64(pv[j], bor));
    d[j] = _mm512_and_si512(t, mask);
    bor = _mm512_xor_si512(_mm512_srli_epi64(t, 52), _mm512_set1_epi64(1));
  }
  const __mmask8 ge_p = _mm512_cmpeq_epu64_mask(bor, zero);
  for (int j = 0; j < 8; j++)
    o.l[j] = _mm512_mask_blend_epi64(ge_p, acc[j], d[j]);
}

EC_FP8_TARGET static void fp8_sqr(Fp8& o, const Fp8& a) { fp8_montmul(o, a, a); }

// lanewise a + b mod p
EC_FP8_TARGET static void fp8_add(Fp8& o, const Fp8& a, const Fp8& b) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512i mask = _mm512_set1_epi64((long long)MASK52);
  const __m512i two52 = _mm512_set1_epi64(1LL << 52);
  __m512i acc[8], cr = zero;
  for (int j = 0; j < 8; j++) {
    acc[j] = _mm512_add_epi64(_mm512_add_epi64(a.l[j], b.l[j]), cr);
    cr = _mm512_srli_epi64(acc[j], 52);
    acc[j] = _mm512_and_si512(acc[j], mask);
  }
  __m512i pv[8];
  for (int j = 0; j < 8; j++) pv[j] = _mm512_set1_epi64((long long)P52[j]);
  __m512i d[8], bor = zero;
  for (int j = 0; j < 8; j++) {
    __m512i t = _mm512_sub_epi64(
        _mm512_add_epi64(acc[j], two52), _mm512_add_epi64(pv[j], bor));
    d[j] = _mm512_and_si512(t, mask);
    bor = _mm512_xor_si512(_mm512_srli_epi64(t, 52), _mm512_set1_epi64(1));
  }
  // note: sum < 2p always (inputs canonical), so one subtract suffices
  const __mmask8 ge_p = _mm512_cmpeq_epu64_mask(bor, zero);
  for (int j = 0; j < 8; j++)
    o.l[j] = _mm512_mask_blend_epi64(ge_p, acc[j], d[j]);
}

// lanewise a - b mod p
EC_FP8_TARGET static void fp8_sub(Fp8& o, const Fp8& a, const Fp8& b) {
  const __m512i zero = _mm512_setzero_si512();
  const __m512i mask = _mm512_set1_epi64((long long)MASK52);
  const __m512i two52 = _mm512_set1_epi64(1LL << 52);
  __m512i acc[8], bor = zero;
  for (int j = 0; j < 8; j++) {
    __m512i t = _mm512_sub_epi64(
        _mm512_add_epi64(a.l[j], two52), _mm512_add_epi64(b.l[j], bor));
    acc[j] = _mm512_and_si512(t, mask);
    bor = _mm512_xor_si512(_mm512_srli_epi64(t, 52), _mm512_set1_epi64(1));
  }
  // lanes that borrowed get +p
  const __mmask8 neg = _mm512_cmpeq_epu64_mask(bor, _mm512_set1_epi64(1));
  __m512i cr = zero;
  for (int j = 0; j < 8; j++) {
    __m512i addend = _mm512_maskz_set1_epi64(neg, (long long)P52[j]);
    acc[j] = _mm512_add_epi64(_mm512_add_epi64(acc[j], addend), cr);
    cr = _mm512_srli_epi64(acc[j], 52);
    acc[j] = _mm512_and_si512(acc[j], mask);
  }
  for (int j = 0; j < 8; j++) o.l[j] = acc[j];
}

// per-lane equality of canonical values -> bitmask
EC_FP8_TARGET static __mmask8 fp8_eq_mask(const Fp8& a, const Fp8& b) {
  __m512i diff = _mm512_setzero_si512();
  for (int j = 0; j < 8; j++)
    diff = _mm512_or_si512(diff, _mm512_xor_si512(a.l[j], b.l[j]));
  return _mm512_cmpeq_epu64_mask(diff, _mm512_setzero_si512());
}

EC_FP8_TARGET static __mmask8 fp8_is_zero_mask(const Fp8& a) {
  __m512i acc = _mm512_setzero_si512();
  for (int j = 0; j < 8; j++) acc = _mm512_or_si512(acc, a.l[j]);
  return _mm512_cmpeq_epu64_mask(acc, _mm512_setzero_si512());
}

// scalar-Montgomery Fp lanes -> R52-Montgomery SoA vector (lanes >= n
// replicate lane 0 so padding never contains surprise values). The
// scalar-Montgomery LIMBS repack directly (a*2^384 as an integer) and
// one vector multiply by 2^448 rebases them: a*2^384 * 2^448 * 2^-416 =
// a*2^416 — no per-element scalar conversion.
EC_FP8_TARGET static void fp8_load(Fp8& o, const Fp* in, int n) {
  u64 t[8][8];
  for (int k = 0; k < 8; k++) limbs6_to_52(t[k], in[k < n ? k : 0].l);
  for (int j = 0; j < 8; j++)
    o.l[j] = _mm512_setr_epi64(
        (long long)t[0][j], (long long)t[1][j], (long long)t[2][j],
        (long long)t[3][j], (long long)t[4][j], (long long)t[5][j],
        (long long)t[6][j], (long long)t[7][j]);
  Fp8 c;
  fp8_bcast(c, X2_448_52);
  fp8_montmul(o, o, c);
}

// R52-Montgomery SoA vector -> scalar-Montgomery Fp lanes: one vector
// multiply by 2^384 (a*2^416 * 2^384 * 2^-416 = a*2^384), then repack.
EC_FP8_TARGET static void fp8_store(Fp* out, const Fp8& a, int n) {
  Fp8 c, red;
  fp8_bcast(c, X2_384_52);
  fp8_montmul(red, a, c);
  u64 t[8][8];
  for (int j = 0; j < 8; j++) {
    alignas(64) u64 lane[8];
    _mm512_store_si512((__m512i*)lane, red.l[j]);
    for (int k = 0; k < 8; k++) t[k][j] = lane[k];
  }
  for (int k = 0; k < n; k++) limbs52_to_6(out[k].l, t[k]);
}

// shared-exponent windowed power (all lanes raise to the SAME public
// exponent, so the 4-bit window digit schedule is lane-independent)
EC_FP8_TARGET static void fp8_pow(Fp8& out, const Fp8& base, const u64* exp,
                                  int exp_limbs) {
  int bits = exp_limbs * 64;
  while (bits > 0 && !((exp[(bits - 1) >> 6] >> ((bits - 1) & 63)) & 1)) bits--;
  if (bits == 0) {
    // x^0 = 1 in Montgomery form: montmul(2^832, 1) = 2^416 mod p
    static const u64 ONEP[8] = {1, 0, 0, 0, 0, 0, 0, 0};
    Fp8 r2, onep;
    fp8_bcast(r2, R52SQ_52);
    fp8_bcast(onep, ONEP);
    fp8_montmul(out, r2, onep);
    return;
  }
  Fp8 tbl[15];
  tbl[0] = base;
  for (int i = 1; i < 15; i++) fp8_montmul(tbl[i], tbl[i - 1], base);
  Fp8 result;
  bool started = false;
  for (int w = ((bits - 1) / 4) * 4; w >= 0; w -= 4) {
    if (started) {
      fp8_sqr(result, result);
      fp8_sqr(result, result);
      fp8_sqr(result, result);
      fp8_sqr(result, result);
    }
    int d = (int)((exp[w >> 6] >> (w & 63)) & 15);
    if (d) {
      if (started) fp8_montmul(result, result, tbl[d - 1]);
      else { result = tbl[d - 1]; started = true; }
    }
  }
  out = result;
}

// Eight candidate square roots x_i = a_i^((p+1)/4) with per-lane
// verification (x^2 == a); returns the success bitmask.
EC_FP8_TARGET static __mmask8 fp8_sqrt(Fp8& out, const Fp8& a) {
  fp8_pow(out, a, EXP_P_PLUS_1_DIV_4, 6);
  Fp8 chk;
  fp8_sqr(chk, out);
  return fp8_eq_mask(chk, a);
}

// Batched norm-method Fp2 sqrt (the vector twin of fp2_sqrt above):
// three batched Fp power chains — norm, (a+s)/2, (a-s)/2 — cover eight
// roots, where the scalar path pays 2-3 chains EACH. Lanes with
// c1 == 0 (real inputs) take the scalar path; every produced root is
// verified per-lane, with scalar recomputation as the safety net, so
// verdict semantics cannot drift from the scalar routine.
EC_FP8_TARGET static u32 fp2_sqrt_x8_ifma(Fp2* out, const Fp2* const* in,
                                          int n) {
  u32 okbits = 0;
  Fp av[8], bv[8];
  int idx[8];
  int m = 0;
  for (int k = 0; k < n; k++) {
    if (fp_is_zero(in[k]->c1)) {
      Fp2 r;
      if (fp2_sqrt(r, *in[k])) { out[k] = r; okbits |= 1u << k; }
      continue;
    }
    av[m] = in[k]->c0;
    bv[m] = in[k]->c1;
    idx[m] = k;
    m++;
  }
  if (!m) return okbits;
  Fp8 a8, b8, n8, t, s8;
  fp8_load(a8, av, m);
  fp8_load(b8, bv, m);
  fp8_sqr(n8, a8);
  fp8_sqr(t, b8);
  fp8_add(n8, n8, t);
  const __mmask8 sq_ok = fp8_sqrt(s8, n8);   // norm must be square in Fp
  Fp8 half, t1, t2, x1, x2;
  fp8_bcast(half, TWOINV_M52);
  fp8_add(t1, a8, s8);
  fp8_montmul(t1, t1, half);
  fp8_sub(t2, a8, s8);
  fp8_montmul(t2, t2, half);
  const __mmask8 x1_ok = fp8_sqrt(x1, t1);
  const __mmask8 x1_nz = ~fp8_is_zero_mask(x1);
  fp8_sqrt(x2, t2);
  const __mmask8 use1 = x1_ok & x1_nz;
  Fp8 x;
  for (int j = 0; j < 8; j++)
    x.l[j] = _mm512_mask_blend_epi64(use1, x2.l[j], x1.l[j]);
  Fp xs[8];
  fp8_store(xs, x, m);
  // y = b / (2x): batch the lane inversions through one fp_inv
  Fp dens[8];
  for (int k = 0; k < m; k++) fp_dbl(dens[k], xs[k]);
  fp_inv_batch(dens, m);
  for (int k = 0; k < m; k++) {
    if (!((sq_ok >> k) & 1)) continue;  // non-square input: leave unset
    Fp2 r;
    r.c0 = xs[k];
    fp_mul(r.c1, bv[k], dens[k]);
    Fp2 chk;
    fp2_sqr(chk, r);
    if (fp2_eq(chk, *in[idx[k]])) {
      out[idx[k]] = r;
      okbits |= 1u << idx[k];
    } else {
      // engine disagreement: defer to the scalar routine (never expected;
      // keeps verdicts exactly equal to the scalar path by construction)
      Fp2 r2;
      if (fp2_sqrt(r2, *in[idx[k]])) { out[idx[k]] = r2; okbits |= 1u << idx[k]; }
    }
  }
  return okbits;
}
#endif  // EC_FP8_COMPILED

#ifdef EC_FP8_COMPILED
// eight Fp square roots through one batched (p+1)/4 chain
EC_FP8_TARGET static u32 fp_sqrt_x8_ifma(Fp* out, const Fp* const* in, int n) {
  Fp vals[8];
  for (int k = 0; k < 8; k++) vals[k] = *in[k < n ? k : 0];
  Fp8 a8, r8;
  fp8_load(a8, vals, 8);
  const __mmask8 okm = fp8_sqrt(r8, a8);
  Fp roots[8];
  fp8_store(roots, r8, 8);
  u32 okbits = 0;
  for (int k = 0; k < n; k++) {
    if ((okm >> k) & 1) {
      out[k] = roots[k];
      okbits |= 1u << k;
    } else {
      // engine said non-square; scalar confirm keeps verdicts pinned
      Fp r;
      if (fp_sqrt(r, *in[k])) { out[k] = r; okbits |= 1u << k; }
    }
  }
  return okbits;
}
#endif  // EC_FP8_COMPILED

// Dispatch: batched Fp sqrt over up to 8 independent inputs
static u32 fp_sqrt_x8(Fp* out, const Fp* const* in, int n) {
#ifdef EC_FP8_COMPILED
  if (FP8_READY) return fp_sqrt_x8_ifma(out, in, n);
#endif
  u32 okbits = 0;
  for (int k = 0; k < n; k++) {
    Fp r;
    if (fp_sqrt(r, *in[k])) { out[k] = r; okbits |= 1u << k; }
  }
  return okbits;
}

// Dispatch wrapper: batched Fp2 sqrt over up to 8 independent inputs
// (pointer array), scalar fallback when the IFMA engine is unavailable.
static u32 fp2_sqrt_x8(Fp2* out, const Fp2* const* in, int n) {
#ifdef EC_FP8_COMPILED
  if (FP8_READY) return fp2_sqrt_x8_ifma(out, in, n);
#endif
  u32 okbits = 0;
  for (int k = 0; k < n; k++) {
    Fp2 r;
    if (fp2_sqrt(r, *in[k])) { out[k] = r; okbits |= 1u << k; }
  }
  return okbits;
}

#ifdef EC_FP8_COMPILED
// init-time self-check: random-ish vectors must round-trip and agree
// with the scalar field on mul/add/sub/pow before FP8_READY flips on
EC_FP8_TARGET static bool fp8_selfcheck() {
  u64 seed = 0x9e3779b97f4a7c15ULL;
  Fp vals[16];
  for (int i = 0; i < 16; i++) {
    Fp s;
    for (int j = 0; j < 6; j++) {
      seed ^= seed << 13; seed ^= seed >> 7; seed ^= seed << 17;
      s.l[j] = seed;
    }
    s.l[5] &= (1ULL << 61) - 1;  // < p after reduction headroom
    // reduce below p: conditional subtract a few times
    for (int r = 0; r < 4; r++) {
      if (fp_cmp_raw(s.l, P_RAW.l) >= 0) {
        u64 borrow = 0;
        for (int j = 0; j < 6; j++) s.l[j] = sbb(s.l[j], P_RAW.l[j], borrow);
      }
    }
    fp_to_mont(vals[i], s);
  }
  vals[14] = FP_ZERO;
  vals[15] = FP_ONE;
  Fp8 a8, b8, r8;
  fp8_load(a8, vals, 8);
  fp8_load(b8, vals + 8, 8);
  // round-trip
  Fp back[8];
  fp8_store(back, a8, 8);
  for (int i = 0; i < 8; i++)
    if (!fp_eq(back[i], vals[i])) return false;
  // mul / add / sub vs scalar
  Fp want[8], got[8];
  fp8_montmul(r8, a8, b8);
  fp8_store(got, r8, 8);
  for (int i = 0; i < 8; i++) {
    fp_mul(want[i], vals[i], vals[8 + i]);
    if (!fp_eq(got[i], want[i])) return false;
  }
  fp8_add(r8, a8, b8);
  fp8_store(got, r8, 8);
  for (int i = 0; i < 8; i++) {
    fp_add(want[i], vals[i], vals[8 + i]);
    if (!fp_eq(got[i], want[i])) return false;
  }
  fp8_sub(r8, a8, b8);
  fp8_store(got, r8, 8);
  for (int i = 0; i < 8; i++) {
    fp_sub(want[i], vals[i], vals[8 + i]);
    if (!fp_eq(got[i], want[i])) return false;
  }
  fp8_pow(r8, a8, EXP_P_PLUS_1_DIV_4, 6);
  fp8_store(got, r8, 8);
  for (int i = 0; i < 8; i++) {
    fp_pow(want[i], vals[i], EXP_P_PLUS_1_DIV_4, 6);
    if (!fp_eq(got[i], want[i])) return false;
  }
  return true;
}
#endif  // EC_FP8_COMPILED

#ifdef EC_FP8_COMPILED
// randomized engine-vs-scalar cross-check (driven by ec_fp8_selftest)
EC_FP8_TARGET static int fp8_selftest_deep(u64 seed, int rounds) {
  if (!seed) seed = 0x853c49e6748fea9bULL;
  for (int r = 0; r < rounds; r++) {
    Fp va[8], vb[8];
    for (int i = 0; i < 8; i++) {
      Fp s;
      for (int j = 0; j < 6; j++) {
        seed ^= seed << 13; seed ^= seed >> 7; seed ^= seed << 17;
        s.l[j] = seed;
      }
      s.l[5] &= (1ULL << 60) - 1;
      fp_to_mont(va[i], s);
      for (int j = 0; j < 6; j++) {
        seed ^= seed << 13; seed ^= seed >> 7; seed ^= seed << 17;
        s.l[j] = seed;
      }
      s.l[5] &= (1ULL << 60) - 1;
      fp_to_mont(vb[i], s);
    }
    if (r == 0) { va[0] = FP_ZERO; vb[1] = FP_ZERO; va[2] = FP_ONE; }
    Fp8 a8, b8, r8;
    fp8_load(a8, va, 8);
    fp8_load(b8, vb, 8);
    Fp got[8], want;
    fp8_montmul(r8, a8, b8);
    fp8_store(got, r8, 8);
    for (int i = 0; i < 8; i++) {
      fp_mul(want, va[i], vb[i]);
      if (!fp_eq(got[i], want)) return 1;
    }
    fp8_add(r8, a8, b8);
    fp8_store(got, r8, 8);
    for (int i = 0; i < 8; i++) {
      fp_add(want, va[i], vb[i]);
      if (!fp_eq(got[i], want)) return 2;
    }
    fp8_sub(r8, a8, b8);
    fp8_store(got, r8, 8);
    for (int i = 0; i < 8; i++) {
      fp_sub(want, va[i], vb[i]);
      if (!fp_eq(got[i], want)) return 3;
    }
    // batched Fp2 sqrt agrees with the scalar routine, both on known
    // squares and on raw random candidates (~half non-squares)
    Fp2 roots[4], squares[4], outs[4];
    const Fp2* ptrs[4];
    for (int i = 0; i < 4; i++) {
      roots[i].c0 = va[i];
      roots[i].c1 = vb[i];
      fp2_sqr(squares[i], roots[i]);
      ptrs[i] = &squares[i];
    }
    u32 okb = fp2_sqrt_x8(outs, ptrs, 4);
    if (okb != 0xF) return 4;
    for (int i = 0; i < 4; i++) {
      Fp2 chk;
      fp2_sqr(chk, outs[i]);
      if (!fp2_eq(chk, squares[i])) return 5;
    }
    Fp2 rawin[4], rawout[4];
    const Fp2* rawptr[4];
    for (int i = 0; i < 4; i++) {
      rawin[i].c0 = va[4 + i];
      rawin[i].c1 = vb[4 + i];
      rawptr[i] = &rawin[i];
    }
    u32 gotmask = fp2_sqrt_x8(rawout, rawptr, 4);
    for (int i = 0; i < 4; i++) {
      Fp2 want2;
      bool want_ok = fp2_sqrt(want2, rawin[i]);
      if (((gotmask >> i) & 1) != (want_ok ? 1u : 0u)) return 6;
    }
  }
  return 0;
}
#endif  // EC_FP8_COMPILED

// called from ensure_init once the scalar Montgomery machinery is up
static void fp8_engine_init() {
  FP8_READY = false;
#ifdef EC_FP8_COMPILED
  if (!__builtin_cpu_supports("avx512ifma") ||
      !__builtin_cpu_supports("avx512f") ||
      !__builtin_cpu_supports("avx512dq") ||
      !__builtin_cpu_supports("avx512bw") ||
      !__builtin_cpu_supports("avx512vl"))
    return;
  limbs6_to_52(P52, P_RAW.l);
  P52_INV = FP_INV & MASK52;  // inverse mod 2^64 truncates to mod 2^52
  // powers of two mod p by doubling (canonical limbs)
  Fp acc = {{1, 0, 0, 0, 0, 0}};
  for (int i = 0; i < 384; i++) fp_add(acc, acc, acc);
  limbs6_to_52(X2_384_52, acc.l);
  for (int i = 384; i < 415; i++) fp_add(acc, acc, acc);
  limbs6_to_52(TWOINV_M52, acc.l);
  for (int i = 415; i < 448; i++) fp_add(acc, acc, acc);
  limbs6_to_52(X2_448_52, acc.l);
  for (int i = 448; i < 832; i++) fp_add(acc, acc, acc);
  limbs6_to_52(R52SQ_52, acc.l);
  FP8_READY = fp8_selfcheck();
#endif
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)
// ---------------------------------------------------------------------------

struct Fp6 { Fp2 a0, a1, a2; };
struct Fp12 { Fp6 c0, c1; };

static Fp6 FP6_ZERO, FP6_ONE;
static Fp12 FP12_ONE;
static Fp2 FROB_GAMMA1[6];  // xi^(i*(p-1)/6), i = 0..5

static inline bool fp6_is_zero(const Fp6& a) {
  return fp2_is_zero(a.a0) && fp2_is_zero(a.a1) && fp2_is_zero(a.a2);
}
static inline void fp6_add(Fp6& o, const Fp6& a, const Fp6& b) {
  fp2_add(o.a0, a.a0, b.a0); fp2_add(o.a1, a.a1, b.a1); fp2_add(o.a2, a.a2, b.a2);
}
static inline void fp6_sub(Fp6& o, const Fp6& a, const Fp6& b) {
  fp2_sub(o.a0, a.a0, b.a0); fp2_sub(o.a1, a.a1, b.a1); fp2_sub(o.a2, a.a2, b.a2);
}
static inline void fp6_neg(Fp6& o, const Fp6& a) {
  fp2_neg(o.a0, a.a0); fp2_neg(o.a1, a.a1); fp2_neg(o.a2, a.a2);
}

static void fp6_mul(Fp6& o, const Fp6& a, const Fp6& b) {
  Fp2 t0, t1, t2, s, u, x, y;
  fp2_mul(t0, a.a0, b.a0);
  fp2_mul(t1, a.a1, b.a1);
  fp2_mul(t2, a.a2, b.a2);
  // c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
  fp2_add(s, a.a1, a.a2);
  fp2_add(u, b.a1, b.a2);
  fp2_mul(x, s, u);
  fp2_sub(x, x, t1);
  fp2_sub(x, x, t2);
  fp2_mul_by_xi(y, x);
  Fp2 c0, c1, c2;
  fp2_add(c0, t0, y);
  // c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
  fp2_add(s, a.a0, a.a1);
  fp2_add(u, b.a0, b.a1);
  fp2_mul(x, s, u);
  fp2_sub(x, x, t0);
  fp2_sub(x, x, t1);
  fp2_mul_by_xi(y, t2);
  fp2_add(c1, x, y);
  // c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
  fp2_add(s, a.a0, a.a2);
  fp2_add(u, b.a0, b.a2);
  fp2_mul(x, s, u);
  fp2_sub(x, x, t0);
  fp2_sub(x, x, t2);
  fp2_add(c2, x, t1);
  o.a0 = c0; o.a1 = c1; o.a2 = c2;
}

static inline void fp6_sqr(Fp6& o, const Fp6& a) { fp6_mul(o, a, a); }

// multiply by v: (a0, a1, a2) -> (xi*a2, a0, a1)
static void fp6_mul_by_v(Fp6& o, const Fp6& a) {
  Fp2 t;
  fp2_mul_by_xi(t, a.a2);
  Fp2 old_a0 = a.a0, old_a1 = a.a1;
  o.a0 = t; o.a1 = old_a0; o.a2 = old_a1;
}

static void fp6_scalar_mul_fp2(Fp6& o, const Fp6& a, const Fp2& k) {
  fp2_mul(o.a0, a.a0, k); fp2_mul(o.a1, a.a1, k); fp2_mul(o.a2, a.a2, k);
}

static void fp6_inv(Fp6& o, const Fp6& a) {
  // c0 = a0^2 - xi*a1*a2 ; c1 = xi*a2^2 - a0*a1 ; c2 = a1^2 - a0*a2
  Fp2 c0, c1, c2, t, u;
  fp2_sqr(c0, a.a0);
  fp2_mul(t, a.a1, a.a2);
  fp2_mul_by_xi(u, t);
  fp2_sub(c0, c0, u);
  fp2_sqr(t, a.a2);
  fp2_mul_by_xi(u, t);
  fp2_mul(t, a.a0, a.a1);
  fp2_sub(c1, u, t);
  fp2_sqr(t, a.a1);
  fp2_mul(u, a.a0, a.a2);
  fp2_sub(c2, t, u);
  // t = a0*c0 + xi*(a2*c1 + a1*c2)
  Fp2 acc, x;
  fp2_mul(acc, a.a2, c1);
  fp2_mul(x, a.a1, c2);
  fp2_add(acc, acc, x);
  fp2_mul_by_xi(acc, acc);
  fp2_mul(x, a.a0, c0);
  fp2_add(acc, acc, x);
  Fp2 inv;
  fp2_inv(inv, acc);
  fp2_mul(o.a0, c0, inv);
  fp2_mul(o.a1, c1, inv);
  fp2_mul(o.a2, c2, inv);
}

static inline bool fp12_eq(const Fp12& a, const Fp12& b) {
  return fp2_eq(a.c0.a0, b.c0.a0) && fp2_eq(a.c0.a1, b.c0.a1) && fp2_eq(a.c0.a2, b.c0.a2) &&
         fp2_eq(a.c1.a0, b.c1.a0) && fp2_eq(a.c1.a1, b.c1.a1) && fp2_eq(a.c1.a2, b.c1.a2);
}

static void fp12_mul(Fp12& o, const Fp12& a, const Fp12& b) {
  Fp6 t0, t1, s0, s1, t2, vt;
  fp6_mul(t0, a.c0, b.c0);
  fp6_mul(t1, a.c1, b.c1);
  fp6_add(s0, a.c0, a.c1);
  fp6_add(s1, b.c0, b.c1);
  fp6_mul(t2, s0, s1);
  fp6_sub(t2, t2, t0);
  fp6_sub(t2, t2, t1);
  fp6_mul_by_v(vt, t1);
  fp6_add(o.c0, t0, vt);
  o.c1 = t2;
}

static void fp12_sqr(Fp12& o, const Fp12& a) {
  // c0 = A0^2 + v*A1^2 ; c1 = 2*A0*A1, karatsuba form
  Fp6 u, s, t, vt;
  fp6_mul(u, a.c0, a.c1);
  fp6_add(s, a.c0, a.c1);
  fp6_mul_by_v(vt, a.c1);
  fp6_add(t, a.c0, vt);
  fp6_mul(t, s, t);       // (A0+A1)(A0+v*A1) = A0^2 + v*A1^2 + (1+v)*A0*A1
  fp6_sub(t, t, u);
  fp6_mul_by_v(vt, u);
  fp6_sub(o.c0, t, vt);
  fp6_add(o.c1, u, u);
}

static inline void fp12_conj(Fp12& o, const Fp12& a) {
  o.c0 = a.c0;
  fp6_neg(o.c1, a.c1);
}

static void fp12_inv(Fp12& o, const Fp12& a) {
  Fp6 t0, t1, vt, inv;
  fp6_sqr(t0, a.c0);
  fp6_sqr(t1, a.c1);
  fp6_mul_by_v(vt, t1);
  fp6_sub(t0, t0, vt);
  fp6_inv(inv, t0);
  fp6_mul(o.c0, a.c0, inv);
  Fp6 t;
  fp6_mul(t, a.c1, inv);
  fp6_neg(o.c1, t);
}

// Frobenius x -> x^p. Basis powers of w: w^0..w^5 live at
// (c0.a0, c1.a0, c0.a1, c1.a1, c0.a2, c1.a2); b_i -> conj(b_i)*gamma1^i.
static void fp12_frob(Fp12& o, const Fp12& a) {
  Fp2 b[6] = {a.c0.a0, a.c1.a0, a.c0.a1, a.c1.a1, a.c0.a2, a.c1.a2};
  Fp2 r[6];
  for (int i = 0; i < 6; i++) {
    Fp2 c;
    fp2_conj(c, b[i]);
    fp2_mul(r[i], c, FROB_GAMMA1[i]);
  }
  o.c0.a0 = r[0]; o.c1.a0 = r[1]; o.c0.a1 = r[2];
  o.c1.a1 = r[3]; o.c0.a2 = r[4]; o.c1.a2 = r[5];
}

static void fp12_frob_n(Fp12& o, const Fp12& a, int n) {
  Fp12 t = a;
  for (int i = 0; i < n; i++) fp12_frob(t, t);
  o = t;
}

static bool fp12_is_one(const Fp12& a) { return fp12_eq(a, FP12_ONE); }

// ---------------------------------------------------------------------------
// Curve groups: Jacobian coordinates, templated over the field
// ---------------------------------------------------------------------------

struct FpOps {
  typedef Fp F;
  static void add(F& o, const F& a, const F& b) { fp_add(o, a, b); }
  static void sub(F& o, const F& a, const F& b) { fp_sub(o, a, b); }
  static void mul(F& o, const F& a, const F& b) { fp_mul(o, a, b); }
  static void sqr(F& o, const F& a) { fp_sqr(o, a); }
  static void neg(F& o, const F& a) { fp_neg(o, a); }
  static void inv(F& o, const F& a) { fp_inv(o, a); }
  static bool is_zero(const F& a) { return fp_is_zero(a); }
  static bool eq(const F& a, const F& b) { return fp_eq(a, b); }
  static F zero() { return FP_ZERO; }
  static F one() { return FP_ONE; }
};

struct Fp2Ops {
  typedef Fp2 F;
  static void add(F& o, const F& a, const F& b) { fp2_add(o, a, b); }
  static void sub(F& o, const F& a, const F& b) { fp2_sub(o, a, b); }
  static void mul(F& o, const F& a, const F& b) { fp2_mul(o, a, b); }
  static void sqr(F& o, const F& a) { fp2_sqr(o, a); }
  static void neg(F& o, const F& a) { fp2_neg(o, a); }
  static void inv(F& o, const F& a) { fp2_inv(o, a); }
  static bool is_zero(const F& a) { return fp2_is_zero(a); }
  static bool eq(const F& a, const F& b) { return fp2_eq(a, b); }
  static F zero() { return FP2_ZERO; }
  static F one() { return FP2_ONE; }
};

template <class Ops>
struct Point {
  typename Ops::F x, y, z;
  bool is_inf() const { return Ops::is_zero(z); }
};

typedef Point<FpOps> G1;
typedef Point<Fp2Ops> G2;

// fast-path subgroup membership (endomorphism criteria; defined with the
// psi machinery below, validated before first use)
static bool g1_in_subgroup(const G1& p);
static bool g2_in_subgroup(const G2& p);
static void validate_endomorphism_fast_paths();

static Fp G1_B;    // 4
static Fp2 G2_B;   // 4(u+1)
static G1 G1_GEN;
static G2 G2_GEN;

template <class Ops>
static Point<Ops> pt_infinity() {
  Point<Ops> p;
  p.x = Ops::one(); p.y = Ops::one(); p.z = Ops::zero();
  return p;
}

// dbl-2009-l, mirrors curves.py _JacobianPoint.double
template <class Ops>
static void pt_double(Point<Ops>& o, const Point<Ops>& p) {
  typedef typename Ops::F F;
  if (p.is_inf()) { o = p; return; }
  F a, b, c, d, e, f, t, x3, y3, z3;
  Ops::sqr(a, p.x);
  Ops::sqr(b, p.y);
  Ops::sqr(c, b);
  Ops::add(t, p.x, b);
  Ops::sqr(t, t);
  Ops::sub(t, t, a);
  Ops::sub(d, t, c);
  Ops::add(d, d, d);
  Ops::add(e, a, a);
  Ops::add(e, e, a);
  Ops::sqr(f, e);
  Ops::sub(x3, f, d);
  Ops::sub(x3, x3, d);
  F c8;
  Ops::add(c8, c, c);
  Ops::add(c8, c8, c8);
  Ops::add(c8, c8, c8);
  Ops::sub(t, d, x3);
  Ops::mul(y3, e, t);
  Ops::sub(y3, y3, c8);
  Ops::mul(z3, p.y, p.z);
  Ops::add(z3, z3, z3);
  o.x = x3; o.y = y3; o.z = z3;
}

// add-2007-bl, mirrors curves.py _JacobianPoint.__add__
template <class Ops>
static void pt_add(Point<Ops>& o, const Point<Ops>& p, const Point<Ops>& q) {
  typedef typename Ops::F F;
  if (p.is_inf()) { o = q; return; }
  if (q.is_inf()) { o = p; return; }
  F z1z1, z2z2, u1, u2, s1, s2, t;
  Ops::sqr(z1z1, p.z);
  Ops::sqr(z2z2, q.z);
  Ops::mul(u1, p.x, z2z2);
  Ops::mul(u2, q.x, z1z1);
  Ops::mul(t, p.y, q.z);
  Ops::mul(s1, t, z2z2);
  Ops::mul(t, q.y, p.z);
  Ops::mul(s2, t, z1z1);
  if (Ops::eq(u1, u2)) {
    if (Ops::eq(s1, s2)) { pt_double(o, p); return; }
    o = pt_infinity<Ops>();
    return;
  }
  F h, i, j, r, v, x3, y3, z3;
  Ops::sub(h, u2, u1);
  Ops::add(i, h, h);
  Ops::sqr(i, i);
  Ops::mul(j, h, i);
  Ops::sub(r, s2, s1);
  Ops::add(r, r, r);
  Ops::mul(v, u1, i);
  Ops::sqr(x3, r);
  Ops::sub(x3, x3, j);
  Ops::sub(x3, x3, v);
  Ops::sub(x3, x3, v);
  Ops::sub(t, v, x3);
  Ops::mul(y3, r, t);
  F sj;
  Ops::mul(sj, s1, j);
  Ops::sub(y3, y3, sj);
  Ops::sub(y3, y3, sj);
  Ops::mul(t, p.z, q.z);
  Ops::add(t, t, t);
  Ops::mul(z3, t, h);
  o.x = x3; o.y = y3; o.z = z3;
}

// madd-2007-bl: q affine (z = 1) — 7M+4S vs the general add's 11M+5S.
// The MSM bucket-accumulation hot path: base points arrive from raw
// affine bytes with z = 1.
template <class Ops>
static void pt_add_affine(Point<Ops>& o, const Point<Ops>& p,
                          const typename Ops::F& qx,
                          const typename Ops::F& qy) {
  typedef typename Ops::F F;
  if (p.is_inf()) {
    o.x = qx; o.y = qy; o.z = Ops::one();
    return;
  }
  F z1z1, u2, s2, t;
  Ops::sqr(z1z1, p.z);
  Ops::mul(u2, qx, z1z1);
  Ops::mul(t, qy, p.z);
  Ops::mul(s2, t, z1z1);
  if (Ops::eq(p.x, u2)) {
    if (Ops::eq(p.y, s2)) { pt_double(o, p); return; }
    o = pt_infinity<Ops>();
    return;
  }
  F h, hh, i, j, r, v, x3, y3, z3;
  Ops::sub(h, u2, p.x);
  Ops::sqr(hh, h);
  Ops::add(i, hh, hh);
  Ops::add(i, i, i);            // i = 4·hh
  Ops::mul(j, h, i);
  Ops::sub(r, s2, p.y);
  Ops::add(r, r, r);
  Ops::mul(v, p.x, i);
  Ops::sqr(x3, r);
  Ops::sub(x3, x3, j);
  Ops::sub(x3, x3, v);
  Ops::sub(x3, x3, v);
  Ops::sub(t, v, x3);
  Ops::mul(y3, r, t);
  F yj;
  Ops::mul(yj, p.y, j);
  Ops::sub(y3, y3, yj);
  Ops::sub(y3, y3, yj);
  Ops::add(t, p.z, h);          // z3 = (z1+h)² − z1z1 − hh
  Ops::sqr(t, t);
  Ops::sub(t, t, z1z1);
  Ops::sub(z3, t, hh);
  o.x = x3; o.y = y3; o.z = z3;
}

template <class Ops>
static void pt_neg(Point<Ops>& o, const Point<Ops>& p) {
  o.x = p.x;
  Ops::neg(o.y, p.y);
  o.z = p.z;
}

// scalar given as little-endian u64 limbs; width-4 NAF (digits in
// {0, ±1, ±3, ±5, ±7}), ~1/5 addition density vs 1/2 for double-and-add.
// Variable-time like the ladder it replaces (this backend verifies public
// data; the reference's blst wrapper is the hardened path for signing).
template <class Ops>
static void pt_mul(Point<Ops>& o, const Point<Ops>& p, const u64* scalar, int limbs) {
  if (p.is_inf() || limbs > 16) {  // limbs cap: largest caller is H_EFF (10)
    o = pt_infinity<Ops>();
    if (limbs <= 16) return;
    // oversized scalar: fall back to the plain ladder (unreachable today)
    Point<Ops> result = pt_infinity<Ops>();
    bool started = false;
    for (int i = limbs - 1; i >= 0; i--)
      for (int b = 63; b >= 0; b--) {
        if (started) pt_double(result, result);
        if ((scalar[i] >> b) & 1) {
          if (started) pt_add(result, result, p);
          else { result = p; started = true; }
        }
      }
    o = result;
    return;
  }
  u64 n[17];
  int L = limbs;
  for (int i = 0; i < L; i++) n[i] = scalar[i];
  n[L++] = 0;  // headroom for the +|d| carry in negative-digit recoding
  signed char digits[1089];
  int nd = 0;
  for (;;) {
    bool z = true;
    for (int i = 0; i < L; i++) if (n[i]) { z = false; break; }
    if (z) break;
    int d = 0;
    if (n[0] & 1) {
      d = (int)(n[0] & 15);
      if (d > 8) d -= 16;
      if (d > 0) {
        u64 borrow = (u64)d;
        for (int i = 0; i < L && borrow; i++) {
          u64 nv = n[i] - borrow;
          borrow = nv > n[i];
          n[i] = nv;
        }
      } else {
        u64 carry = (u64)(-d);
        for (int i = 0; i < L && carry; i++) {
          u64 nv = n[i] + carry;
          carry = nv < n[i];
          n[i] = nv;
        }
      }
    }
    digits[nd++] = (signed char)d;
    for (int i = 0; i < L - 1; i++) n[i] = (n[i] >> 1) | (n[i + 1] << 63);
    n[L - 1] >>= 1;
  }
  if (nd == 0) { o = pt_infinity<Ops>(); return; }
  Point<Ops> tbl[4];  // P, 3P, 5P, 7P
  tbl[0] = p;
  Point<Ops> p2;
  pt_double(p2, p);
  pt_add(tbl[1], tbl[0], p2);
  pt_add(tbl[2], tbl[1], p2);
  pt_add(tbl[3], tbl[2], p2);
  Point<Ops> result = pt_infinity<Ops>();
  for (int i = nd - 1; i >= 0; i--) {
    pt_double(result, result);
    int d = digits[i];
    if (d > 0) {
      pt_add(result, result, tbl[(d - 1) >> 1]);
    } else if (d < 0) {
      Point<Ops> m;
      pt_neg(m, tbl[((-d) - 1) >> 1]);
      pt_add(result, result, m);
    }
  }
  o = result;
}

template <class Ops>
static bool pt_in_subgroup(const Point<Ops>& p) {
  if (p.is_inf()) return true;
  Point<Ops> t;
  pt_mul(t, p, R_RAW, 4);
  return t.is_inf();
}

// affine (x, y); returns false for infinity
template <class Ops>
static bool pt_to_affine(typename Ops::F& ax, typename Ops::F& ay, const Point<Ops>& p) {
  typedef typename Ops::F F;
  if (p.is_inf()) return false;
  F zinv, z2, z3;
  Ops::inv(zinv, p.z);
  Ops::sqr(z2, zinv);
  Ops::mul(z3, z2, zinv);
  Ops::mul(ax, p.x, z2);
  Ops::mul(ay, p.y, z3);
  return true;
}

template <class Ops>
static Point<Ops> pt_from_affine(const typename Ops::F& ax, const typename Ops::F& ay) {
  Point<Ops> p;
  p.x = ax; p.y = ay; p.z = Ops::one();
  return p;
}

template <class Ops>
static bool pt_on_curve_affine(const typename Ops::F& ax, const typename Ops::F& ay,
                               const typename Ops::F& b) {
  typedef typename Ops::F F;
  F y2, x3, t;
  Ops::sqr(y2, ay);
  Ops::sqr(t, ax);
  Ops::mul(x3, t, ax);
  Ops::add(x3, x3, b);
  return Ops::eq(y2, x3);
}

// ---------------------------------------------------------------------------
// ZCash-format compressed serialization (mirrors curves.py)
// ---------------------------------------------------------------------------

enum DecodeErr {
  DEC_OK = 0,
  DEC_NOT_COMPRESSED = 2,
  DEC_BAD_INFINITY = 3,
  DEC_NOT_IN_FIELD = 4,
  DEC_NOT_ON_CURVE = 5,
  DEC_NOT_IN_SUBGROUP = 6,
};

static const u8 FLAG_COMPRESSED = 0x80;
static const u8 FLAG_INFINITY = 0x40;
static const u8 FLAG_SIGN = 0x20;

// decompress + full validation (curve + subgroup), infinity allowed
static int g1_decompress(G1& out, const u8 in[48], bool check_subgroup = true) {
  u8 flags = in[0];
  if (!(flags & FLAG_COMPRESSED)) return DEC_NOT_COMPRESSED;
  if (flags & FLAG_INFINITY) {
    if (flags & ~(FLAG_COMPRESSED | FLAG_INFINITY)) return DEC_BAD_INFINITY;
    for (int i = 1; i < 48; i++) if (in[i]) return DEC_BAD_INFINITY;
    out = pt_infinity<FpOps>();
    return DEC_OK;
  }
  u8 buf[48];
  memcpy(buf, in, 48);
  buf[0] = flags & 0x1F;
  Fp x;
  if (!fp_from_bytes(x, buf)) return DEC_NOT_IN_FIELD;
  Fp y2, t, y;
  fp_sqr(t, x);
  fp_mul(y2, t, x);
  fp_add(y2, y2, G1_B);
  if (!fp_sqrt(y, y2)) return DEC_NOT_ON_CURVE;
  if (fp_is_lex_largest(y) != !!(flags & FLAG_SIGN)) fp_neg(y, y);
  out = pt_from_affine<FpOps>(x, y);
  if (check_subgroup && !g1_in_subgroup(out)) return DEC_NOT_IN_SUBGROUP;
  return DEC_OK;
}

static int g2_decompress(G2& out, const u8 in[96], bool check_subgroup = true) {
  u8 flags = in[0];
  if (!(flags & FLAG_COMPRESSED)) return DEC_NOT_COMPRESSED;
  if (flags & FLAG_INFINITY) {
    if (flags & ~(FLAG_COMPRESSED | FLAG_INFINITY)) return DEC_BAD_INFINITY;
    for (int i = 1; i < 96; i++) if (in[i]) return DEC_BAD_INFINITY;
    out = pt_infinity<Fp2Ops>();
    return DEC_OK;
  }
  // layout: c1 (48, flags in MSB) || c0 (48)
  u8 buf[48];
  memcpy(buf, in, 48);
  buf[0] = flags & 0x1F;
  Fp2 x;
  if (!fp_from_bytes(x.c1, buf)) return DEC_NOT_IN_FIELD;
  if (!fp_from_bytes(x.c0, in + 48)) return DEC_NOT_IN_FIELD;
  Fp2 y2, t, y;
  fp2_sqr(t, x);
  fp2_mul(y2, t, x);
  fp2_add(y2, y2, G2_B);
  if (!fp2_sqrt(y, y2)) return DEC_NOT_ON_CURVE;
  if (fp2_is_lex_largest(y) != !!(flags & FLAG_SIGN)) fp2_neg(y, y);
  out = pt_from_affine<Fp2Ops>(x, y);
  if (check_subgroup && !g2_in_subgroup(out)) return DEC_NOT_IN_SUBGROUP;
  return DEC_OK;
}

static void g1_compress(u8 out[48], const G1& p) {
  if (p.is_inf()) {
    memset(out, 0, 48);
    out[0] = FLAG_COMPRESSED | FLAG_INFINITY;
    return;
  }
  Fp ax, ay;
  pt_to_affine<FpOps>(ax, ay, p);
  fp_to_bytes(out, ax);
  out[0] |= FLAG_COMPRESSED;
  if (fp_is_lex_largest(ay)) out[0] |= FLAG_SIGN;
}

static void g2_compress(u8 out[96], const G2& p) {
  if (p.is_inf()) {
    memset(out, 0, 96);
    out[0] = FLAG_COMPRESSED | FLAG_INFINITY;
    return;
  }
  Fp2 ax, ay;
  pt_to_affine<Fp2Ops>(ax, ay, p);
  fp_to_bytes(out, ax.c1);
  fp_to_bytes(out + 48, ax.c0);
  out[0] |= FLAG_COMPRESSED;
  if (fp2_is_lex_largest(ay)) out[0] |= FLAG_SIGN;
}

// ---------------------------------------------------------------------------
// Optimal ate pairing
//
// Miller loop over the M-twist with Jacobian accumulators and
// denominator-free line functions. Untwist: x = x'*xi^-1*v^2,
// y = y'*xi^-1*v*w (same map as crypto/pairing.py). Lines are scaled by
// Fq2 constants, which the final exponentiation kills (they lie in a
// proper subfield). Line slots in Fp12 (basis powers of w):
//   doubling, scale 2YZ^3:  c0.a0 = -xi*(2YZ^3 * yP)
//                           c1.a1 = 2Y^2 - 3X^3
//                           c1.a2 = (3X^2 Z^2) * xP
//   addition (T + Q, Q affine), scale lam_d = (X - xq Z^2) Z:
//     lam_n = Y - yq Z^3
//                           c0.a0 = -xi*(lam_d * yP)
//                           c1.a1 = yq*lam_d - lam_n*xq
//                           c1.a2 = lam_n * xP
// ---------------------------------------------------------------------------

struct MillerPair {
  Fp xp, yp;   // G1 affine
  Fp2 xq, yq;  // G2 affine (twist coords)
  G2 t;        // accumulator
};

// f *= line, exploiting the line's sparsity: line = A + B·w with
// A = (c00, 0, 0) and B = (0, c11, c12) in the Fp6[w]/(w²−v) tower.
// Karatsuba over the halves costs 3 + 6 + 6 = 15 fp2_mul vs the full
// fp12_mul's 18 — the saving lands on every Miller-loop step.
static void fp12_mul_by_line(Fp12& f, const Fp2& c00, const Fp2& c11, const Fp2& c12) {
  // t0 = f.c0 · A  (component scale by c00)
  Fp6 t0;
  fp2_mul(t0.a0, f.c0.a0, c00);
  fp2_mul(t0.a1, f.c0.a1, c00);
  fp2_mul(t0.a2, f.c0.a2, c00);
  // t1 = f.c1 · B:  (a0 + a1 v + a2 v²)(b v + c v²) with v³ = ξ
  //   = ξ(a1 c + a2 b) + (a0 b + ξ a2 c)·v + (a0 c + a1 b)·v²
  Fp6 t1;
  Fp2 u, w;
  fp2_mul(u, f.c1.a1, c12);
  fp2_mul(w, f.c1.a2, c11);
  fp2_add(u, u, w);
  fp2_mul_by_xi(t1.a0, u);
  fp2_mul(u, f.c1.a0, c11);
  fp2_mul(w, f.c1.a2, c12);
  fp2_mul_by_xi(w, w);
  fp2_add(t1.a1, u, w);
  fp2_mul(u, f.c1.a0, c12);
  fp2_mul(w, f.c1.a1, c11);
  fp2_add(t1.a2, u, w);
  // t2 = (f.c0 + f.c1) · (A + B); A + B = (c00, c11, c12) is dense
  Fp6 sum, ab, t2;
  fp6_add(sum, f.c0, f.c1);
  ab.a0 = c00; ab.a1 = c11; ab.a2 = c12;
  fp6_mul(t2, sum, ab);
  // o.c0 = t0 + v·t1 ; o.c1 = t2 − t0 − t1
  Fp6 vt;
  fp6_mul_by_v(vt, t1);
  fp6_add(f.c0, t0, vt);
  fp6_sub(t2, t2, t0);
  fp6_sub(f.c1, t2, t1);
}

// tangent line at pr.t evaluated at (xp, yp), multiplied into f, FUSED
// with the doubling T <- 2T (dbl-2009-l) so X², Y², Z², 3X² are computed
// once for both the line and the new point.
static void miller_double_step(Fp12& f, MillerPair& pr) {
  const Fp2 X = pr.t.x, Y = pr.t.y, Z = pr.t.z;
  Fp2 A, B, C, Z2, Z3c, L, X3c, E, c00, c11, c12, t, u;
  fp2_sqr(A, X);                     // X^2
  fp2_sqr(B, Y);                     // Y^2
  fp2_sqr(C, B);                     // Y^4
  fp2_sqr(Z2, Z);
  fp2_mul(Z3c, Z2, Z);               // Z^3
  // c00 = -xi * (2YZ^3 * yp)
  fp2_mul(L, Y, Z3c);
  fp2_dbl(L, L);
  fp2_scalar_mul(t, L, pr.yp);
  fp2_mul_by_xi(t, t);
  fp2_neg(c00, t);
  // c11 = 2Y^2 - 3X^3
  fp2_mul(X3c, A, X);
  fp2_dbl(c11, B);
  fp2_add(u, X3c, X3c);
  fp2_add(u, u, X3c);
  fp2_sub(c11, c11, u);
  // c12 = 3X^2 Z^2 * xp   (E = 3X^2 is also the doubling's slope term)
  fp2_add(E, A, A);
  fp2_add(E, E, A);
  fp2_mul(t, E, Z2);
  fp2_scalar_mul(c12, t, pr.xp);
  fp12_mul_by_line(f, c00, c11, c12);
  // T <- 2T reusing A, B, C, E (dbl-2009-l)
  Fp2 D, F, x3, y3, z3, c8;
  fp2_add(t, X, B);
  fp2_sqr(t, t);
  fp2_sub(t, t, A);
  fp2_sub(D, t, C);
  fp2_dbl(D, D);                     // 2((X+Y^2)^2 - X^2 - Y^4)
  fp2_sqr(F, E);
  fp2_sub(x3, F, D);
  fp2_sub(x3, x3, D);
  fp2_dbl(c8, C);
  fp2_dbl(c8, c8);
  fp2_dbl(c8, c8);                   // 8Y^4
  fp2_sub(t, D, x3);
  fp2_mul(y3, E, t);
  fp2_sub(y3, y3, c8);
  fp2_mul(z3, Y, Z);
  fp2_dbl(z3, z3);
  pr.t.x = x3; pr.t.y = y3; pr.t.z = z3;
}

// line through pr.t and affine (xq, yq) evaluated at (xp, yp), multiplied
// into f, FUSED with the mixed addition T <- T + Q (madd-2007-bl; Q has
// z = 1). T == ±Q never occurs inside the Miller loop: T = [k]Q with
// 1 < k < |x| << r, so the doubling/infinity arms of the generic add are
// unreachable and omitted.
static void miller_add_step(Fp12& f, MillerPair& pr) {
  const Fp2 X = pr.t.x, Y = pr.t.y, Z = pr.t.z;
  Fp2 Z2, Z3c, U2, S2, lam_n, lam_d, t, u, c00, c11, c12;
  fp2_sqr(Z2, Z);
  fp2_mul(Z3c, Z2, Z);
  fp2_mul(U2, pr.xq, Z2);            // xq Z^2
  fp2_mul(S2, pr.yq, Z3c);           // yq Z^3
  fp2_sub(lam_n, Y, S2);             // Y - yq Z^3
  fp2_sub(t, X, U2);
  fp2_mul(lam_d, t, Z);              // (X - xq Z^2) Z
  // c00 = -xi * (lam_d * yp)
  fp2_scalar_mul(u, lam_d, pr.yp);
  fp2_mul_by_xi(u, u);
  fp2_neg(c00, u);
  // c11 = yq*lam_d - lam_n*xq
  fp2_mul(t, pr.yq, lam_d);
  fp2_mul(u, lam_n, pr.xq);
  fp2_sub(c11, t, u);
  // c12 = lam_n * xp
  fp2_scalar_mul(c12, lam_n, pr.xp);
  fp12_mul_by_line(f, c00, c11, c12);
  // T <- T + Q, mixed addition reusing Z2, Z3c, U2, S2
  Fp2 H, HH, I, J, rr, V, x3, y3, z3;
  fp2_sub(H, U2, X);
  fp2_sqr(HH, H);
  fp2_dbl(I, HH);
  fp2_dbl(I, I);                     // 4 H^2
  fp2_mul(J, H, I);
  fp2_sub(rr, S2, Y);
  fp2_dbl(rr, rr);                   // 2(S2 - Y) = -2 lam_n
  fp2_mul(V, X, I);
  fp2_sqr(x3, rr);
  fp2_sub(x3, x3, J);
  fp2_sub(x3, x3, V);
  fp2_sub(x3, x3, V);
  fp2_sub(t, V, x3);
  fp2_mul(y3, rr, t);
  fp2_mul(u, Y, J);
  fp2_dbl(u, u);
  fp2_sub(y3, y3, u);
  fp2_add(z3, Z, H);
  fp2_sqr(z3, z3);
  fp2_sub(z3, z3, Z2);
  fp2_sub(z3, z3, HH);
  pr.t.x = x3; pr.t.y = y3; pr.t.z = z3;
}

// product of Miller loops, one shared squaring chain; pairs must be finite
static void multi_miller_loop(Fp12& f, MillerPair* pairs, size_t n) {
  f = FP12_ONE;
  if (n == 0) return;
  for (size_t k = 0; k < n; k++)
    pairs[k].t = pt_from_affine<Fp2Ops>(pairs[k].xq, pairs[k].yq);
  // bits of |x| MSB-first, top bit consumed by initialization
  int msb = 63;
  while (!((BLS_X_ABS >> msb) & 1)) msb--;
  for (int b = msb - 1; b >= 0; b--) {
    fp12_sqr(f, f);
    for (size_t k = 0; k < n; k++) miller_double_step(f, pairs[k]);
    if ((BLS_X_ABS >> b) & 1)
      for (size_t k = 0; k < n; k++) miller_add_step(f, pairs[k]);
  }
  // x negative: conjugate
  fp12_conj(f, f);
}

// Granger–Scott cyclotomic squaring: for elements of the cyclotomic
// subgroup (everything after the easy final-exp part), squaring costs
// three Fp4 squarings (9 fp2_sqr) instead of a generic fp12_sqr's 12
// fp2_mul — ~3x cheaper, and it dominates the exponentiation chains of
// the hard part. Validated once at init against fp12_sqr on a cyclotomic
// element (CYCLO_STATE); a mismatch demotes to the generic squaring.
static int CYCLO_STATE = -1;

// (a + b·s with s² = ξ): returns (a² + ξ·b², (a+b)² − a² − b²)
static void fp4_sqr(Fp2& out0, Fp2& out1, const Fp2& a, const Fp2& b) {
  Fp2 t0, t1, t2;
  fp2_sqr(t0, a);
  fp2_sqr(t1, b);
  fp2_mul_by_xi(out0, t1);
  fp2_add(out0, out0, t0);
  fp2_add(t2, a, b);
  fp2_sqr(t2, t2);
  fp2_sub(t2, t2, t0);
  fp2_sub(out1, t2, t1);
}

static void fp12_cyclo_sqr(Fp12& o, const Fp12& a) {
  // w-power basis components (see fp12_frob comment for the layout)
  Fp2 z0 = a.c0.a0, z4 = a.c0.a1, z3 = a.c0.a2;
  Fp2 z2 = a.c1.a0, z1 = a.c1.a1, z5 = a.c1.a2;
  Fp2 t0, t1, t2, t3, u;
  fp4_sqr(t0, t1, z0, z1);
  fp2_sub(u, t0, z0); fp2_dbl(u, u); fp2_add(z0, u, t0);   // 3t0 − 2z0
  fp2_add(u, t1, z1); fp2_dbl(u, u); fp2_add(z1, u, t1);   // 3t1 + 2z1
  fp4_sqr(t0, t1, z2, z3);
  fp4_sqr(t2, t3, z4, z5);
  fp2_sub(u, t0, z4); fp2_dbl(u, u); fp2_add(z4, u, t0);
  fp2_add(u, t1, z5); fp2_dbl(u, u); fp2_add(z5, u, t1);
  Fp2 xt3;
  fp2_mul_by_xi(xt3, t3);
  fp2_add(u, xt3, z2); fp2_dbl(u, u); fp2_add(z2, u, xt3);
  fp2_sub(u, t2, z3); fp2_dbl(u, u); fp2_add(z3, u, t2);
  o.c0.a0 = z0; o.c0.a1 = z4; o.c0.a2 = z3;
  o.c1.a0 = z2; o.c1.a1 = z1; o.c1.a2 = z5;
}

static inline void fp12_sqr_cyclotomic_input(Fp12& o, const Fp12& a) {
  if (CYCLO_STATE == 1) fp12_cyclo_sqr(o, a);
  else fp12_sqr(o, a);
}

// f^|x| then conjugate (x negative); input must be in cyclotomic subgroup
static void fp12_pow_neg_x(Fp12& o, const Fp12& a) {
  Fp12 result;
  bool started = false;
  for (int b = 63; b >= 0; b--) {
    if (started) fp12_sqr_cyclotomic_input(result, result);
    if ((BLS_X_ABS >> b) & 1) {
      if (started) fp12_mul(result, result, a);
      else { result = a; started = true; }
    }
  }
  fp12_conj(o, result);
}

// full final exponentiation up to a cube: f^(3*(p^12-1)/r).
// Hard part via (x-1)^2 (x+p) (x^2+p^2-1) + 3 == 3*(p^4-p^2+1)/r
// (verified numerically); the cube preserves the ==1 verdict since
// gcd(3, r) = 1. Only predicates are exposed, never raw pairing values.
static void final_exp_for_verdict(Fp12& o, const Fp12& f) {
  // easy: f^(p^6-1) = conj(f) * f^-1, then ^(p^2+1)
  Fp12 inv, f1, f2, t;
  fp12_inv(inv, f);
  fp12_conj(t, f);
  fp12_mul(f1, t, inv);
  fp12_frob_n(t, f1, 2);
  fp12_mul(f2, t, f1);
  // hard (cyclotomic subgroup: inverse == conjugate)
  Fp12 a, b, c, d, e;
  fp12_pow_neg_x(t, f2);
  fp12_conj(a, f2);
  fp12_mul(a, a, t);              // f2^(x-1)
  fp12_pow_neg_x(t, a);
  fp12_conj(b, a);
  fp12_mul(b, b, t);              // a^(x-1)
  fp12_pow_neg_x(t, b);
  fp12_frob(c, b);
  fp12_mul(c, c, t);              // b^(x+p)
  fp12_pow_neg_x(t, c);
  fp12_pow_neg_x(t, t);           // c^(x^2)
  fp12_frob_n(d, c, 2);
  fp12_mul(d, d, t);
  fp12_conj(e, c);
  fp12_mul(d, d, e);              // c^(x^2+p^2-1)
  // result = d * f2^3
  fp12_sqr(t, f2);
  fp12_mul(t, t, f2);
  fp12_mul(o, d, t);
}

// Π e(Pi, Qi) == 1, skipping infinite points (mirrors pairing.py)
// defined after the eight-lane tower below; false = engine unavailable,
// caller runs the scalar loop (identical Fp12 result — selftest-pinned)
static bool multi_miller_loop_x8_try(Fp12& f, MillerPair* pairs, size_t m);

static bool pairing_product_is_one(const G1* ps, const G2* qs, size_t n) {
  MillerPair pairs[129];
  MillerPair* heap_pairs = nullptr;
  MillerPair* use = pairs;
  if (n > 129) { heap_pairs = new MillerPair[n]; use = heap_pairs; }
  size_t m = 0;
  for (size_t i = 0; i < n; i++) {
    if (ps[i].is_inf() || qs[i].is_inf()) continue;
    // stash the Jacobian coords; the z inversions batch below (chunks of
    // 64 through one fp_inv each — Montgomery's trick)
    use[m].xp = ps[i].x;
    use[m].yp = ps[i].y;
    use[m].xq = qs[i].x;
    use[m].yq = qs[i].y;
    use[m].t.x = qs[i].z;  // temporary: G2 z parked in the accumulator slot
    use[m].t.z.c0 = ps[i].z;
    m++;
  }
  for (size_t base = 0; base < m; base += 64) {
    int c = (int)(m - base < 64 ? m - base : 64);
    Fp z1[64];
    Fp2 z2[64];
    for (int k = 0; k < c; k++) {
      z1[k] = use[base + k].t.z.c0;
      z2[k] = use[base + k].t.x;
    }
    fp_inv_batch(z1, c);
    fp2_inv_batch(z2, c);
    for (int k = 0; k < c; k++) {
      MillerPair& pr = use[base + k];
      Fp i2, i3;
      fp_sqr(i2, z1[k]);
      fp_mul(i3, i2, z1[k]);
      fp_mul(pr.xp, pr.xp, i2);
      fp_mul(pr.yp, pr.yp, i3);
      Fp2 j2, j3;
      fp2_sqr(j2, z2[k]);
      fp2_mul(j3, j2, z2[k]);
      fp2_mul(pr.xq, pr.xq, j2);
      fp2_mul(pr.yq, pr.yq, j3);
    }
  }
  Fp12 f, fe;
  if (!multi_miller_loop_x8_try(f, use, m)) multi_miller_loop(f, use, m);
  final_exp_for_verdict(fe, f);
  bool ok = fp12_is_one(fe);
  delete[] heap_pairs;
  return ok;
}

// ---------------------------------------------------------------------------
// init: derive every constant from p at load time
// ---------------------------------------------------------------------------

static Fp2 SSWU_A, SSWU_B, SSWU_Z, SSWU_NEG_B_OVER_A, SSWU_B_OVER_ZA;
static Fp2 ISO_XN[4], ISO_XD[3], ISO_YN[4], ISO_YD[4];

static void limbs_sub_small(u64* out, const u64* a, u64 small) {
  u64 borrow = 0;
  out[0] = sbb(a[0], small, borrow);
  for (int i = 1; i < 6; i++) out[i] = sbb(a[i], 0, borrow);
}

static void limbs_add_small(u64* out, const u64* a, u64 small) {
  u64 carry = 0;
  out[0] = adc(a[0], small, carry);
  for (int i = 1; i < 6; i++) out[i] = adc(a[i], 0, carry);
}

static void limbs_shr(u64* out, const u64* a, int k) {
  for (int i = 0; i < 6; i++) {
    u64 lo = a[i] >> k;
    u64 hi = (i + 1 < 6) ? (a[i + 1] << (64 - k)) : 0;
    out[i] = lo | hi;
  }
}

static void limbs_div3(u64* out, const u64* a) {
  u128 rem = 0;
  for (int i = 5; i >= 0; i--) {
    u128 cur = (rem << 64) | a[i];
    out[i] = (u64)(cur / 3);
    rem = cur % 3;
  }
}

static bool INITIALIZED = false;

static void ensure_init() {
  if (INITIALIZED) return;
  // -p^{-1} mod 2^64 by Newton iteration
  u64 inv = 1;
  for (int i = 0; i < 6; i++) inv *= 2 - P_RAW.l[0] * inv;
  FP_INV = (u64)0 - inv;
  // 2^768 mod p by doubling (fp_add reduces and needs no Montgomery state)
  Fp acc = {{1, 0, 0, 0, 0, 0}};
  for (int i = 0; i < 768; i++) fp_add(acc, acc, acc);
  FP_R2 = acc;
  Fp one_std = {{1, 0, 0, 0, 0, 0}};
  fp_mul(FP_ONE, one_std, FP_R2);
  // 2^{-1} = (p+1)/2 (p is odd, so (p+1)/2 * 2 = p + 1 ≡ 1)
  {
    u64 half[6];
    limbs_add_small(half, P_RAW.l, 1);
    limbs_shr(half, half, 1);
    Fp half_std;
    for (int i = 0; i < 6; i++) half_std.l[i] = half[i];
    fp_to_mont(FP_TWO_INV, half_std);
  }
  // exponents
  limbs_sub_small(EXP_P_MINUS_2, P_RAW.l, 2);
  u64 tmp[6];
  limbs_add_small(tmp, P_RAW.l, 1);
  limbs_shr(EXP_P_PLUS_1_DIV_4, tmp, 2);
  limbs_sub_small(tmp, P_RAW.l, 3);
  limbs_shr(EXP_P_MINUS_3_DIV_4, tmp, 2);
  limbs_sub_small(tmp, P_RAW.l, 1);
  limbs_shr(EXP_P_MINUS_1_DIV_2, tmp, 1);
  for (int i = 0; i < 6; i++) P_MINUS_1_DIV_2_STD[i] = EXP_P_MINUS_1_DIV_2[i];
  limbs_div3(EXP_P_MINUS_1_DIV_6, EXP_P_MINUS_1_DIV_2);
  // field constants
  FP2_ZERO.c0 = FP_ZERO; FP2_ZERO.c1 = FP_ZERO;
  FP2_ONE.c0 = FP_ONE; FP2_ONE.c1 = FP_ZERO;
  FP6_ZERO.a0 = FP2_ZERO; FP6_ZERO.a1 = FP2_ZERO; FP6_ZERO.a2 = FP2_ZERO;
  FP6_ONE.a0 = FP2_ONE; FP6_ONE.a1 = FP2_ZERO; FP6_ONE.a2 = FP2_ZERO;
  FP12_ONE.c0 = FP6_ONE; FP12_ONE.c1 = FP6_ZERO;
  // Frobenius gamma1^i = xi^(i*(p-1)/6)
  Fp2 xi;
  xi.c0 = FP_ONE; xi.c1 = FP_ONE;
  Fp2 g;
  fp2_pow(g, xi, EXP_P_MINUS_1_DIV_6, 6);
  FROB_GAMMA1[0] = FP2_ONE;
  for (int i = 1; i < 6; i++) fp2_mul(FROB_GAMMA1[i], FROB_GAMMA1[i - 1], g);
  // curve constants + generators
  fp_from_u64(G1_B, 4);
  fp_from_u64(G2_B.c0, 4);
  fp_from_u64(G2_B.c1, 4);
  Fp gx, gy;
  Fp g1x_std, g1y_std;
  for (int i = 0; i < 6; i++) { g1x_std.l[i] = G1_GEN_X.l[i]; g1y_std.l[i] = G1_GEN_Y.l[i]; }
  fp_to_mont(gx, g1x_std);
  fp_to_mont(gy, g1y_std);
  G1_GEN = pt_from_affine<FpOps>(gx, gy);
  Fp2 g2x, g2y;
  fp2_from_raw(g2x, G2_GEN_X);
  fp2_from_raw(g2y, G2_GEN_Y);
  G2_GEN = pt_from_affine<Fp2Ops>(g2x, g2y);
  // SSWU constants (RFC 9380 §8.8.2): A' = 240u, B' = 1012(1+u), Z = -(2+u)
  Fp f240, f1012, f2, f1;
  fp_from_u64(f240, 240);
  fp_from_u64(f1012, 1012);
  fp_from_u64(f2, 2);
  fp_from_u64(f1, 1);
  SSWU_A.c0 = FP_ZERO; SSWU_A.c1 = f240;
  SSWU_B.c0 = f1012; SSWU_B.c1 = f1012;
  fp_neg(SSWU_Z.c0, f2);
  fp_neg(SSWU_Z.c1, f1);
  Fp2 a_inv, t;
  fp2_inv(a_inv, SSWU_A);
  fp2_mul(t, SSWU_B, a_inv);
  fp2_neg(SSWU_NEG_B_OVER_A, t);
  Fp2 za, za_inv;
  fp2_mul(za, SSWU_Z, SSWU_A);
  fp2_inv(za_inv, za);
  fp2_mul(SSWU_B_OVER_ZA, SSWU_B, za_inv);
  // isogeny tables
  for (int i = 0; i < 4; i++) fp2_from_raw(ISO_XN[i], ISO_X_NUM[i]);
  for (int i = 0; i < 3; i++) fp2_from_raw(ISO_XD[i], ISO_X_DEN[i]);
  for (int i = 0; i < 4; i++) fp2_from_raw(ISO_YN[i], ISO_Y_NUM[i]);
  for (int i = 0; i < 4; i++) fp2_from_raw(ISO_YD[i], ISO_Y_DEN[i]);
  // validate + enable the endomorphism fast paths (psi cofactor clearing,
  // psi/GLV subgroup criteria) before any caller can race on their state
  validate_endomorphism_fast_paths();
  // eight-wide IFMA engine last: its self-check wants the exponent
  // tables and scalar field fully set up
  fp8_engine_init();
  INITIALIZED = true;
}

// ---------------------------------------------------------------------------
// SHA-256 (for expand_message_xmd); standard FIPS 180-4 constants
// ---------------------------------------------------------------------------

static const u32 SHA_K[64] = {
  0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
  0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
  0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
  0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
  0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
  0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
  0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
  0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
  0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
  0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
  0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

struct Sha256 {
  u32 h[8];
  u8 buf[64];
  u64 total;
  size_t fill;
};

static inline u32 rotr(u32 x, int n) { return (x >> n) | (x << (32 - n)); }

static void sha_init(Sha256& s) {
  static const u32 H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  memcpy(s.h, H0, sizeof(H0));
  s.total = 0;
  s.fill = 0;
}

static void sha_block(Sha256& s, const u8* p) {
  u32 w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((u32)p[4 * i] << 24) | ((u32)p[4 * i + 1] << 16) |
           ((u32)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  u32 a = s.h[0], b = s.h[1], c = s.h[2], d = s.h[3];
  u32 e = s.h[4], f = s.h[5], g = s.h[6], hh = s.h[7];
  for (int i = 0; i < 64; i++) {
    u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    u32 ch = (e & f) ^ (~e & g);
    u32 t1 = hh + S1 + ch + SHA_K[i] + w[i];
    u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    u32 maj = (a & b) ^ (a & c) ^ (b & c);
    u32 t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  s.h[0] += a; s.h[1] += b; s.h[2] += c; s.h[3] += d;
  s.h[4] += e; s.h[5] += f; s.h[6] += g; s.h[7] += hh;
}

static void sha_update(Sha256& s, const u8* data, size_t len) {
  s.total += len;
  while (len) {
    if (s.fill == 0 && len >= 64) {
      sha_block(s, data);
      data += 64;
      len -= 64;
      continue;
    }
    size_t take = 64 - s.fill;
    if (take > len) take = len;
    memcpy(s.buf + s.fill, data, take);
    s.fill += take;
    data += take;
    len -= take;
    if (s.fill == 64) { sha_block(s, s.buf); s.fill = 0; }
  }
}

static void sha_final(Sha256& s, u8 out[32]) {
  u64 bits = s.total * 8;
  u8 pad = 0x80;
  sha_update(s, &pad, 1);
  u8 z = 0;
  while (s.fill != 56) sha_update(s, &z, 1);
  u8 lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = (u8)(bits >> (56 - 8 * i));
  sha_update(s, lenb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (u8)(s.h[i] >> 24);
    out[4 * i + 1] = (u8)(s.h[i] >> 16);
    out[4 * i + 2] = (u8)(s.h[i] >> 8);
    out[4 * i + 3] = (u8)s.h[i];
  }
}

// ---------------------------------------------------------------------------
// hash_to_g2 (RFC 9380, BLS12381G2_XMD:SHA-256_SSWU_RO_), mirrors
// crypto/hash_to_curve.py
// ---------------------------------------------------------------------------

// len_in_bytes <= 256 covers count=2, m=2, L=64
static bool expand_message_xmd(u8* out, size_t len_in_bytes, const u8* msg,
                               size_t msg_len, const u8* dst, size_t dst_len) {
  const size_t B = 32, RB = 64;
  size_t ell = (len_in_bytes + B - 1) / B;
  if (ell > 255 || len_in_bytes > 65535 || dst_len > 255) return false;
  u8 dst_prime[256];
  memcpy(dst_prime, dst, dst_len);
  dst_prime[dst_len] = (u8)dst_len;
  size_t dp_len = dst_len + 1;
  u8 zpad[RB];
  memset(zpad, 0, RB);
  u8 lib[2] = {(u8)(len_in_bytes >> 8), (u8)len_in_bytes};
  u8 b0[32], bi[32];
  Sha256 s;
  sha_init(s);
  sha_update(s, zpad, RB);
  sha_update(s, msg, msg_len);
  sha_update(s, lib, 2);
  u8 zero = 0;
  sha_update(s, &zero, 1);
  sha_update(s, dst_prime, dp_len);
  sha_final(s, b0);
  sha_init(s);
  sha_update(s, b0, 32);
  u8 one = 1;
  sha_update(s, &one, 1);
  sha_update(s, dst_prime, dp_len);
  sha_final(s, bi);
  size_t off = 0;
  for (size_t i = 1;; i++) {
    size_t take = len_in_bytes - off < 32 ? len_in_bytes - off : 32;
    memcpy(out + off, bi, take);
    off += take;
    if (off >= len_in_bytes) break;
    u8 x[32];
    for (int j = 0; j < 32; j++) x[j] = b0[j] ^ bi[j];
    sha_init(s);
    sha_update(s, x, 32);
    u8 idx = (u8)(i + 1);
    sha_update(s, &idx, 1);
    sha_update(s, dst_prime, dp_len);
    sha_final(s, bi);
  }
  return true;
}

// 64-byte big-endian -> Fp via Horner in the field
static void fp_from_64_bytes(Fp& out, const u8 in[64]) {
  Fp b;  // 2^64 as a field element
  fp_from_u64(b, 0);  // placeholder; set below via doubling
  // 2^64 = (2^32)^2; build from u64 1<<32 squared to stay in range
  Fp t32;
  fp_from_u64(t32, (u64)1 << 32);
  fp_mul(b, t32, t32);
  Fp acc;
  fp_from_u64(acc, 0);
  for (int i = 0; i < 8; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[i * 8 + j];
    Fp lw;
    fp_from_u64(lw, w);
    fp_mul(acc, acc, b);
    fp_add(acc, acc, lw);
  }
  out = acc;
}

static void map_to_curve_sswu(Fp2& xo, Fp2& yo, const Fp2& u) {
  Fp2 u2, zu2, tv, x1, gx1, y1, t;
  fp2_sqr(u2, u);
  fp2_mul(zu2, SSWU_Z, u2);
  fp2_sqr(tv, zu2);
  fp2_add(tv, tv, zu2);
  if (fp2_is_zero(tv)) {
    x1 = SSWU_B_OVER_ZA;
  } else {
    Fp2 tv_inv;
    fp2_inv(tv_inv, tv);
    fp2_add(t, FP2_ONE, tv_inv);
    fp2_mul(x1, SSWU_NEG_B_OVER_A, t);
  }
  // g(x) = x^3 + A x + B
  Fp2 x3, ax;
  fp2_sqr(t, x1);
  fp2_mul(x3, t, x1);
  fp2_mul(ax, SSWU_A, x1);
  fp2_add(gx1, x3, ax);
  fp2_add(gx1, gx1, SSWU_B);
  Fp2 x, y;
  if (fp2_sqrt(y1, gx1)) {
    x = x1; y = y1;
  } else {
    Fp2 x2, gx2, y2;
    fp2_mul(x2, zu2, x1);
    fp2_sqr(t, x2);
    fp2_mul(x3, t, x2);
    fp2_mul(ax, SSWU_A, x2);
    fp2_add(gx2, x3, ax);
    fp2_add(gx2, gx2, SSWU_B);
    fp2_sqrt(y2, gx2);  // must be square when gx1 is not
    x = x2; y = y2;
  }
  if (fp2_sgn0(y) != fp2_sgn0(u)) fp2_neg(y, y);
  xo = x; yo = y;
}

static void horner_fp2(Fp2& out, const Fp2* coeffs, int n, const Fp2& v) {
  Fp2 acc = FP2_ZERO;
  for (int i = n - 1; i >= 0; i--) {
    Fp2 t;
    fp2_mul(t, acc, v);
    fp2_add(acc, t, coeffs[i]);
  }
  out = acc;
}

static void iso_map_to_g2(G2& out, const Fp2& x, const Fp2& y) {
  Fp2 xn, xd, yn, yd;
  horner_fp2(xn, ISO_XN, 4, x);
  horner_fp2(xd, ISO_XD, 3, x);
  horner_fp2(yn, ISO_YN, 4, x);
  horner_fp2(yd, ISO_YD, 4, x);
  if (fp2_is_zero(xd) || fp2_is_zero(yd)) {
    out = pt_infinity<Fp2Ops>();
    return;
  }
  Fp2 xd_inv, yd_inv, xo, yo, t;
  fp2_inv(xd_inv, xd);
  fp2_mul(xo, xn, xd_inv);
  fp2_inv(yd_inv, yd);
  fp2_mul(t, yn, yd_inv);
  fp2_mul(yo, y, t);
  out = pt_from_affine<Fp2Ops>(xo, yo);
}

// ---------------------------------------------------------------------------
// Fast G2 cofactor clearing via the untwist-Frobenius-twist endomorphism
// (Budroni–Pintore): [h_eff]P == [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P), where
// x is the (negative) BLS parameter. ψ(x, y) = (c_x·conj(x), c_y·conj(y))
// with c_x = 1/ξ^((p−1)/3), c_y = 1/ξ^((p−1)/2) — the inverses of the
// Frobenius gammas already computed for the pairing. Replaces the naive
// 640-bit H_EFF double-and-add (~950 group ops) with two 64-bit
// multiplications (~140 ops). The identity is cross-checked once per
// process against the H_EFF path on the first (pre-clearing, generic)
// mapped point; a mismatch demotes to the slow path permanently.
// ---------------------------------------------------------------------------

static Fp2 PSI_CX, PSI_CY;
static int PSI_STATE = -1;   // set by validate_endomorphism_fast_paths
static int G2_SUB_STATE = -1;
// BLS_X_ABS (|x|; x itself is negative) comes from bls12_381_constants.h

static void g2_psi(G2& o, const G2& p) {
  Fp2 cx, cy, cz;
  fp2_conj(cx, p.x);
  fp2_conj(cy, p.y);
  fp2_conj(cz, p.z);
  fp2_mul(o.x, cx, PSI_CX);
  fp2_mul(o.y, cy, PSI_CY);
  o.z = cz;
}

static void g2_mul_bls_x_neg(G2& o, const G2& p) {
  // [x]P = −[|x|]P
  G2 t;
  pt_mul(t, p, &BLS_X_ABS, 1);
  pt_neg(o, t);
}

template <class Ops>
static bool pt_eq_jacobian(const Point<Ops>& a, const Point<Ops>& b) {
  // X1·Z2² == X2·Z1²  and  Y1·Z2³ == Y2·Z1³ (Jacobian equality)
  typedef typename Ops::F F;
  bool ai = a.is_inf(), bi = b.is_inf();
  if (ai || bi) return ai == bi;
  F z1z1, z2z2, l, r;
  Ops::sqr(z1z1, a.z);
  Ops::sqr(z2z2, b.z);
  Ops::mul(l, a.x, z2z2);
  Ops::mul(r, b.x, z1z1);
  if (!Ops::eq(l, r)) return false;
  F z1c, z2c;
  Ops::mul(z1c, z1z1, a.z);
  Ops::mul(z2c, z2z2, b.z);
  Ops::mul(l, a.y, z2c);
  Ops::mul(r, b.y, z1c);
  return Ops::eq(l, r);
}

static bool g2_eq(const G2& a, const G2& b) { return pt_eq_jacobian<Fp2Ops>(a, b); }

// ---------------------------------------------------------------------------
// Fast G1 subgroup membership via the GLV endomorphism φ(x,y) = (βx, y)
// (β a primitive cube root of unity in Fp): on G1, φ acts as
// multiplication by λ = x²−1 (λ²+λ+1 ≡ 0 mod r), so
//   P ∈ G1  ⟺  φ(P) + P == [x²]P
// — two 64-bit multiplications instead of the 255-bit order mul. β and
// the criterion are validated at first use against the slow check on the
// generator (positive) and a synthesized off-subgroup curve point
// (negative); any disagreement demotes permanently.
// ---------------------------------------------------------------------------

static Fp G1_BETA;
static int G1_SUB_STATE = -1;  // set by validate_endomorphism_fast_paths

static bool g1_in_subgroup_fast(const G1& p) {
  if (p.is_inf()) return true;
  G1 l, r, t;
  l = p;
  fp_mul(l.x, p.x, G1_BETA);      // φ(P) — Jacobian x scales the same way
  pt_add(l, l, p);                // φ(P) + P
  pt_mul(t, p, &BLS_X_ABS, 1);
  pt_mul(r, t, &BLS_X_ABS, 1);    // [x²]P (sign of x is irrelevant squared)
  return pt_eq_jacobian<FpOps>(l, r);
}

static bool g1_validate_fast_subgroup() {
  // β = (2^((p−1)/6))² = 2^((p−1)/3); if it's 1, fall back (never for this p)
  Fp two, g;
  fp_from_u64(two, 2);
  fp_pow(g, two, EXP_P_MINUS_1_DIV_6, 6);
  fp_sqr(G1_BETA, g);
  if (FpOps::eq(G1_BETA, FP_ONE)) return false;
  // the GLV eigenvalue may correspond to β or β²; pick the one that fixes
  // the generator under the criterion
  if (!g1_in_subgroup_fast(G1_GEN)) {
    fp_sqr(G1_BETA, G1_BETA);
    if (!g1_in_subgroup_fast(G1_GEN)) return false;
  }
  if (!pt_in_subgroup(G1_GEN)) return false;
  // negative case: find a curve point (x=2,3,...) that the slow check
  // rejects (the cofactor is ~2^125, so the first few x all qualify)
  for (u64 xi = 2; xi < 40; xi++) {
    Fp x, y2, t, y;
    fp_from_u64(x, xi);
    fp_sqr(t, x);
    fp_mul(y2, t, x);
    fp_add(y2, y2, G1_B);
    if (!fp_sqrt(y, y2)) continue;
    G1 cand = pt_from_affine<FpOps>(x, y);
    if (pt_in_subgroup(cand)) continue;  // astronomically unlikely
    return !g1_in_subgroup_fast(cand);
  }
  return false;
}

static bool g1_in_subgroup(const G1& p) {
  if (G1_SUB_STATE == 1) return g1_in_subgroup_fast(p);
  return pt_in_subgroup(p);
}

static void g2_clear_cofactor_fast(G2& o, const G2& p) {
  G2 t1, t2, t3, t4, n;
  g2_mul_bls_x_neg(t1, p);          // [x]P
  g2_psi(t2, p);                    // ψ(P)
  pt_double(t3, p);
  g2_psi(t3, t3);
  g2_psi(t3, t3);                   // ψ²([2]P)
  pt_neg(n, t2);
  pt_add(t3, t3, n);                // ψ²(2P) − ψ(P)
  pt_add(t4, t1, t2);               // [x]P + ψ(P)
  g2_mul_bls_x_neg(t4, t4);         // [x²]P + [x]ψ(P)
  pt_add(t3, t3, t4);
  pt_neg(n, t1);
  pt_add(t3, t3, n);                // − [x]P
  pt_neg(n, p);
  pt_add(t3, t3, n);                // − P
  o = t3;
}

// ψ acts on G2 as multiplication by x (p ≡ x mod r for BLS curves), so
// P ∈ G2  ⟺  ψ(P) == [x]P (Scott's criterion) — a 64-bit mul + ψ instead
// of the 255-bit order multiplication.
static bool g2_in_subgroup_fast(const G2& p) {
  if (p.is_inf()) return true;
  G2 l, r;
  g2_psi(l, p);
  g2_mul_bls_x_neg(r, p);
  return g2_eq(l, r);
}

static bool g2_in_subgroup(const G2& p) {
  if (G2_SUB_STATE == 1) return g2_in_subgroup_fast(p);
  return pt_in_subgroup(p);
}

static void g2_clear_cofactor(G2& out, const G2& sum) {
  if (PSI_STATE == 1) {
    g2_clear_cofactor_fast(out, sum);
  } else {
    pt_mul(out, sum, H_EFF_G2_RAW, 10);
  }
}

// Runs once at the tail of ensure_init: derives the endomorphism
// constants, then validates every fast path against its slow reference on
// the generator (in-subgroup) and a synthesized generic curve point
// (off-subgroup, cofactors ≈ 2^125 / 2^507 make random curve points
// off-subgroup with overwhelming probability). Any disagreement leaves
// the corresponding path demoted to the slow, always-correct code.
static void validate_endomorphism_fast_paths() {
  // --- G1: GLV criterion ---
  G1_SUB_STATE = g1_validate_fast_subgroup() ? 1 : -1;

  // --- psi constants ---
  fp2_inv(PSI_CX, FROB_GAMMA1[2]);  // 1/xi^((p-1)/3)
  fp2_inv(PSI_CY, FROB_GAMMA1[3]);  // 1/xi^((p-1)/2)

  // synthesize a generic point on the twist: x = (a, 0), a = 1, 2, ...
  G2 cand;
  bool have_cand = false;
  for (u64 a = 1; a < 60 && !have_cand; a++) {
    Fp2 x, y2, t, y;
    fp_from_u64(x.c0, a);
    x.c1 = FP_ZERO;
    fp2_sqr(t, x);
    fp2_mul(y2, t, x);
    fp2_add(y2, y2, G2_B);
    if (!fp2_sqrt(y, y2)) continue;
    cand = pt_from_affine<Fp2Ops>(x, y);
    if (pt_in_subgroup(cand)) continue;  // astronomically unlikely
    have_cand = true;
  }
  if (!have_cand) {
    PSI_STATE = -1;
    G2_SUB_STATE = -1;
    return;
  }

  // cofactor clearing: fast == slow on the generic point
  G2 fast, slow;
  g2_clear_cofactor_fast(fast, cand);
  pt_mul(slow, cand, H_EFF_G2_RAW, 10);
  PSI_STATE = g2_eq(fast, slow) ? 1 : -1;

  // subgroup criterion: agree on the off-subgroup candidate (false) and
  // the cleared point + generator (true)
  if (PSI_STATE == 1) {
    bool neg_ok = !g2_in_subgroup_fast(cand);
    bool pos_ok = g2_in_subgroup_fast(slow) && pt_in_subgroup(slow) &&
                  g2_in_subgroup_fast(G2_GEN);
    G2_SUB_STATE = (neg_ok && pos_ok) ? 1 : -1;
  } else {
    G2_SUB_STATE = -1;
  }

  // --- cyclotomic squaring: build a cyclotomic-subgroup element the same
  // way the final exponentiation does (easy part of a Miller value),
  // then require fp12_cyclo_sqr == fp12_sqr on it ---
  {
    MillerPair mp;
    pt_to_affine<FpOps>(mp.xp, mp.yp, G1_GEN);
    pt_to_affine<Fp2Ops>(mp.xq, mp.yq, G2_GEN);
    Fp12 f, inv, c, f1, f2, t, a, b;
    multi_miller_loop(f, &mp, 1);
    fp12_inv(inv, f);
    fp12_conj(c, f);
    fp12_mul(f1, c, inv);           // f^(p^6 - 1)
    fp12_frob_n(t, f1, 2);
    fp12_mul(f2, t, f1);            // ^(p^2 + 1): cyclotomic
    fp12_sqr(a, f2);
    fp12_cyclo_sqr(b, f2);
    CYCLO_STATE = fp12_eq(a, b) ? 1 : -1;
  }
}

static bool hash_to_g2_point(G2& out, const u8* msg, size_t msg_len,
                             const u8* dst, size_t dst_len) {
  u8 uniform[256];
  if (!expand_message_xmd(uniform, 256, msg, msg_len, dst, dst_len)) {
    out = pt_infinity<Fp2Ops>();
    return false;
  }
  Fp2 u0, u1;
  fp_from_64_bytes(u0.c0, uniform);
  fp_from_64_bytes(u0.c1, uniform + 64);
  fp_from_64_bytes(u1.c0, uniform + 128);
  fp_from_64_bytes(u1.c1, uniform + 192);
  Fp2 x0, y0, x1, y1;
  map_to_curve_sswu(x0, y0, u0);
  map_to_curve_sswu(x1, y1, u1);
  G2 q0, q1, sum;
  iso_map_to_g2(q0, x0, y0);
  iso_map_to_g2(q1, x1, y1);
  pt_add(sum, q0, q1);
  g2_clear_cofactor(out, sum);
  return true;
}

// ---------------------------------------------------------------------------
// Eight-lane G2 point arithmetic on the IFMA engine. Straight-line
// Jacobian formulas (no per-lane branching): z == 0 IS the infinity
// representation, doubling preserves it (z3 = 2yz) and addition handles
// infinite operands by lane-blending, so the only genuinely exceptional
// case left is adding two EQUAL finite points — those lanes are flagged
// in an exception mask and recomputed scalar (cryptographically random
// inputs never hit this; correctness never depends on that).
// ---------------------------------------------------------------------------

#ifdef EC_FP8_COMPILED

EC_FP8_TARGET static void fp8_neg(Fp8& o, const Fp8& a) {
  Fp8 z;
  for (int j = 0; j < 8; j++) z.l[j] = _mm512_setzero_si512();
  fp8_sub(o, z, a);
}

struct Fp2x8 { Fp8 c0, c1; };

EC_FP8_TARGET static void fp2x8_add(Fp2x8& o, const Fp2x8& a, const Fp2x8& b) {
  fp8_add(o.c0, a.c0, b.c0);
  fp8_add(o.c1, a.c1, b.c1);
}
EC_FP8_TARGET static void fp2x8_sub(Fp2x8& o, const Fp2x8& a, const Fp2x8& b) {
  fp8_sub(o.c0, a.c0, b.c0);
  fp8_sub(o.c1, a.c1, b.c1);
}
EC_FP8_TARGET static void fp2x8_neg(Fp2x8& o, const Fp2x8& a) {
  fp8_neg(o.c0, a.c0);
  fp8_neg(o.c1, a.c1);
}
EC_FP8_TARGET static void fp2x8_conj(Fp2x8& o, const Fp2x8& a) {
  o.c0 = a.c0;
  fp8_neg(o.c1, a.c1);
}
// Karatsuba over i^2 = -1, the vector twin of fp2_mul
EC_FP8_TARGET static void fp2x8_mul(Fp2x8& o, const Fp2x8& a, const Fp2x8& b) {
  Fp8 t0, t1, sa, sb, m;
  fp8_montmul(t0, a.c0, b.c0);
  fp8_montmul(t1, a.c1, b.c1);
  fp8_add(sa, a.c0, a.c1);
  fp8_add(sb, b.c0, b.c1);
  fp8_montmul(m, sa, sb);
  fp8_sub(m, m, t0);
  fp8_sub(o.c1, m, t1);
  fp8_sub(o.c0, t0, t1);
}
EC_FP8_TARGET static void fp2x8_sqr(Fp2x8& o, const Fp2x8& a) {
  Fp8 s, d, m, t;
  fp8_add(s, a.c0, a.c1);
  fp8_sub(d, a.c0, a.c1);
  fp8_montmul(m, s, d);          // a0^2 - a1^2
  fp8_montmul(t, a.c0, a.c1);
  fp8_add(o.c1, t, t);
  o.c0 = m;
}
EC_FP8_TARGET static __mmask8 fp2x8_is_zero_mask(const Fp2x8& a) {
  return fp8_is_zero_mask(a.c0) & fp8_is_zero_mask(a.c1);
}
EC_FP8_TARGET static __mmask8 fp2x8_eq_mask(const Fp2x8& a, const Fp2x8& b) {
  return fp8_eq_mask(a.c0, b.c0) & fp8_eq_mask(a.c1, b.c1);
}
EC_FP8_TARGET static void fp8_blend(Fp8& o, __mmask8 take_b, const Fp8& a,
                                    const Fp8& b) {
  for (int j = 0; j < 8; j++)
    o.l[j] = _mm512_mask_blend_epi64(take_b, a.l[j], b.l[j]);
}
EC_FP8_TARGET static void fp2x8_blend(Fp2x8& o, __mmask8 take_b,
                                      const Fp2x8& a, const Fp2x8& b) {
  fp8_blend(o.c0, take_b, a.c0, b.c0);
  fp8_blend(o.c1, take_b, a.c1, b.c1);
}
// broadcast one scalar Fp2 into all lanes
EC_FP8_TARGET static void fp2x8_bcast_fp2(Fp2x8& o, const Fp2& v) {
  fp8_load(o.c0, &v.c0, 1);
  fp8_load(o.c1, &v.c1, 1);
}

struct G2x8 { Fp2x8 x, y, z; };

EC_FP8_TARGET static void g2x8_load(G2x8& o, const G2* pts, int n) {
  Fp xs0[8], xs1[8], ys0[8], ys1[8], zs0[8], zs1[8];
  for (int k = 0; k < 8; k++) {
    const G2& p = pts[k < n ? k : 0];
    xs0[k] = p.x.c0; xs1[k] = p.x.c1;
    ys0[k] = p.y.c0; ys1[k] = p.y.c1;
    zs0[k] = p.z.c0; zs1[k] = p.z.c1;
  }
  fp8_load(o.x.c0, xs0, 8); fp8_load(o.x.c1, xs1, 8);
  fp8_load(o.y.c0, ys0, 8); fp8_load(o.y.c1, ys1, 8);
  fp8_load(o.z.c0, zs0, 8); fp8_load(o.z.c1, zs1, 8);
}

EC_FP8_TARGET static void g2x8_store(G2* out, const G2x8& a, int n) {
  Fp xs0[8], xs1[8], ys0[8], ys1[8], zs0[8], zs1[8];
  fp8_store(xs0, a.x.c0, 8); fp8_store(xs1, a.x.c1, 8);
  fp8_store(ys0, a.y.c0, 8); fp8_store(ys1, a.y.c1, 8);
  fp8_store(zs0, a.z.c0, 8); fp8_store(zs1, a.z.c1, 8);
  for (int k = 0; k < n; k++) {
    out[k].x.c0 = xs0[k]; out[k].x.c1 = xs1[k];
    out[k].y.c0 = ys0[k]; out[k].y.c1 = ys1[k];
    out[k].z.c0 = zs0[k]; out[k].z.c1 = zs1[k];
  }
}

// dbl-2009-l, lane-complete: infinity (z=0) and y=0 both yield z3=0
EC_FP8_TARGET static void g2x8_dbl(G2x8& o, const G2x8& p) {
  Fp2x8 a, b, c, d, e, f, t, c8;
  fp2x8_sqr(a, p.x);
  fp2x8_sqr(b, p.y);
  fp2x8_sqr(c, b);
  fp2x8_add(t, p.x, b);
  fp2x8_sqr(t, t);
  fp2x8_sub(t, t, a);
  fp2x8_sub(d, t, c);
  fp2x8_add(d, d, d);
  fp2x8_add(e, a, a);
  fp2x8_add(e, e, a);
  fp2x8_sqr(f, e);
  Fp2x8 x3, y3, z3;
  fp2x8_sub(x3, f, d);
  fp2x8_sub(x3, x3, d);
  fp2x8_add(c8, c, c);
  fp2x8_add(c8, c8, c8);
  fp2x8_add(c8, c8, c8);
  fp2x8_sub(t, d, x3);
  fp2x8_mul(y3, e, t);
  fp2x8_sub(y3, y3, c8);
  fp2x8_mul(z3, p.y, p.z);
  fp2x8_add(z3, z3, z3);
  o.x = x3; o.y = y3; o.z = z3;
}

// add-2007-bl with infinity lane-blending; equal-finite-point lanes
// (the doubling case) are accumulated into *exc for scalar recomputation
EC_FP8_TARGET static void g2x8_add(G2x8& o, const G2x8& p, const G2x8& q,
                                  __mmask8& exc) {
  const __mmask8 pinf = fp2x8_is_zero_mask(p.z);
  const __mmask8 qinf = fp2x8_is_zero_mask(q.z);
  Fp2x8 z1z1, z2z2, u1, u2, s1, s2, t;
  fp2x8_sqr(z1z1, p.z);
  fp2x8_sqr(z2z2, q.z);
  fp2x8_mul(u1, p.x, z2z2);
  fp2x8_mul(u2, q.x, z1z1);
  fp2x8_mul(t, p.y, q.z);
  fp2x8_mul(s1, t, z2z2);
  fp2x8_mul(t, q.y, p.z);
  fp2x8_mul(s2, t, z1z1);
  const __mmask8 equ = fp2x8_eq_mask(u1, u2);
  const __mmask8 eqs = fp2x8_eq_mask(s1, s2);
  exc |= (__mmask8)(~pinf & ~qinf & equ & eqs);
  Fp2x8 h, i, j, r, v, x3, y3, z3;
  fp2x8_sub(h, u2, u1);            // h == 0 with s1 != s2: P = -Q, z3 = 0 below
  fp2x8_add(i, h, h);
  fp2x8_sqr(i, i);
  fp2x8_mul(j, h, i);
  fp2x8_sub(r, s2, s1);
  fp2x8_add(r, r, r);
  fp2x8_mul(v, u1, i);
  fp2x8_sqr(x3, r);
  fp2x8_sub(x3, x3, j);
  fp2x8_sub(x3, x3, v);
  fp2x8_sub(x3, x3, v);
  fp2x8_sub(t, v, x3);
  fp2x8_mul(y3, r, t);
  Fp2x8 sj;
  fp2x8_mul(sj, s1, j);
  fp2x8_sub(y3, y3, sj);
  fp2x8_sub(y3, y3, sj);
  fp2x8_mul(t, p.z, q.z);
  fp2x8_add(t, t, t);
  fp2x8_mul(z3, t, h);
  // infinite-operand lanes take the other operand verbatim
  fp2x8_blend(x3, pinf, x3, q.x);
  fp2x8_blend(y3, pinf, y3, q.y);
  fp2x8_blend(z3, pinf, z3, q.z);
  fp2x8_blend(x3, qinf, x3, p.x);
  fp2x8_blend(y3, qinf, y3, p.y);
  fp2x8_blend(z3, qinf, z3, p.z);
  o.x = x3; o.y = y3; o.z = z3;
}

EC_FP8_TARGET static void g2x8_neg(G2x8& o, const G2x8& p) {
  o.x = p.x;
  fp2x8_neg(o.y, p.y);
  o.z = p.z;
}

// vector twin of g2_psi: conjugate coordinates, scale x and y by the
// untwist-Frobenius-twist constants
EC_FP8_TARGET static void g2x8_psi(G2x8& o, const G2x8& p) {
  Fp2x8 cx, cy, cz, kx, ky;
  fp2x8_conj(cx, p.x);
  fp2x8_conj(cy, p.y);
  fp2x8_conj(cz, p.z);
  fp2x8_bcast_fp2(kx, PSI_CX);
  fp2x8_bcast_fp2(ky, PSI_CY);
  fp2x8_mul(o.x, cx, kx);
  fp2x8_mul(o.y, cy, ky);
  o.z = cz;
}

// [x]P = -[|x|]P over the sparse 64-bit |x|, shared schedule per lane
EC_FP8_TARGET static void g2x8_mul_bls_x_neg(G2x8& o, const G2x8& p,
                                             __mmask8& exc) {
  G2x8 acc = p;  // |x| has its top bit at 63
  for (int b = 62; b >= 0; b--) {
    g2x8_dbl(acc, acc);
    if ((BLS_X_ABS >> b) & 1) g2x8_add(acc, acc, p, exc);
  }
  g2x8_neg(o, acc);
}

// vector twin of g2_clear_cofactor_fast (Budroni-Pintore)
EC_FP8_TARGET static void g2x8_clear_cofactor(G2x8& o, const G2x8& p,
                                              __mmask8& exc) {
  G2x8 t1, t2, t3, t4, n;
  g2x8_mul_bls_x_neg(t1, p, exc);   // [x]P
  g2x8_psi(t2, p);                  // psi(P)
  g2x8_dbl(t3, p);
  g2x8_psi(t3, t3);
  g2x8_psi(t3, t3);                 // psi^2([2]P)
  g2x8_neg(n, t2);
  g2x8_add(t3, t3, n, exc);         // psi^2(2P) - psi(P)
  g2x8_add(t4, t1, t2, exc);        // [x]P + psi(P)
  g2x8_mul_bls_x_neg(t4, t4, exc);  // [x^2]P + [x]psi(P)
  g2x8_add(t3, t3, t4, exc);
  g2x8_neg(n, t1);
  g2x8_add(t3, t3, n, exc);         // - [x]P
  g2x8_neg(n, p);
  g2x8_add(t3, t3, n, exc);         // - P
  o = t3;
}

// Scott criterion psi(P) == [x]P per lane; lanes where either side is
// infinite (or the compare is otherwise degenerate) land in *exc
EC_FP8_TARGET static __mmask8 g2x8_in_subgroup_mask(const G2x8& p,
                                                    __mmask8& exc) {
  G2x8 l, r;
  g2x8_psi(l, p);
  g2x8_mul_bls_x_neg(r, p, exc);
  const __mmask8 linf = fp2x8_is_zero_mask(l.z);
  const __mmask8 rinf = fp2x8_is_zero_mask(r.z);
  exc |= (__mmask8)(linf | rinf);
  Fp2x8 z1z1, z2z2, a, b, z1c, z2c;
  fp2x8_sqr(z1z1, l.z);
  fp2x8_sqr(z2z2, r.z);
  fp2x8_mul(a, l.x, z2z2);
  fp2x8_mul(b, r.x, z1z1);
  const __mmask8 xeq = fp2x8_eq_mask(a, b);
  fp2x8_mul(z1c, z1z1, l.z);
  fp2x8_mul(z2c, z2z2, r.z);
  fp2x8_mul(a, l.y, z2c);
  fp2x8_mul(b, r.y, z1c);
  const __mmask8 yeq = fp2x8_eq_mask(a, b);
  return xeq & yeq;
}

// ---- G1x8: the same lane-complete Jacobian machinery over Fp ----

struct G1x8 { Fp8 x, y, z; };

EC_FP8_TARGET static void g1x8_load(G1x8& o, const G1* pts, int n) {
  Fp xs[8], ys[8], zs[8];
  for (int k = 0; k < 8; k++) {
    const G1& p = pts[k < n ? k : 0];
    xs[k] = p.x; ys[k] = p.y; zs[k] = p.z;
  }
  fp8_load(o.x, xs, 8);
  fp8_load(o.y, ys, 8);
  fp8_load(o.z, zs, 8);
}

EC_FP8_TARGET static void g1x8_store(G1* out, const G1x8& a, int n) {
  Fp xs[8], ys[8], zs[8];
  fp8_store(xs, a.x, 8);
  fp8_store(ys, a.y, 8);
  fp8_store(zs, a.z, 8);
  for (int k = 0; k < n; k++) {
    out[k].x = xs[k]; out[k].y = ys[k]; out[k].z = zs[k];
  }
}

EC_FP8_TARGET static void g1x8_dbl(G1x8& o, const G1x8& p) {
  Fp8 a, b, c, d, e, f, t, c8, x3, y3, z3;
  fp8_sqr(a, p.x);
  fp8_sqr(b, p.y);
  fp8_sqr(c, b);
  fp8_add(t, p.x, b);
  fp8_sqr(t, t);
  fp8_sub(t, t, a);
  fp8_sub(d, t, c);
  fp8_add(d, d, d);
  fp8_add(e, a, a);
  fp8_add(e, e, a);
  fp8_sqr(f, e);
  fp8_sub(x3, f, d);
  fp8_sub(x3, x3, d);
  fp8_add(c8, c, c);
  fp8_add(c8, c8, c8);
  fp8_add(c8, c8, c8);
  fp8_sub(t, d, x3);
  fp8_montmul(y3, e, t);
  fp8_sub(y3, y3, c8);
  fp8_montmul(z3, p.y, p.z);
  fp8_add(z3, z3, z3);
  o.x = x3; o.y = y3; o.z = z3;
}

EC_FP8_TARGET static void g1x8_add(G1x8& o, const G1x8& p, const G1x8& q,
                                  __mmask8& exc) {
  const __mmask8 pinf = fp8_is_zero_mask(p.z);
  const __mmask8 qinf = fp8_is_zero_mask(q.z);
  Fp8 z1z1, z2z2, u1, u2, s1, s2, t;
  fp8_sqr(z1z1, p.z);
  fp8_sqr(z2z2, q.z);
  fp8_montmul(u1, p.x, z2z2);
  fp8_montmul(u2, q.x, z1z1);
  fp8_montmul(t, p.y, q.z);
  fp8_montmul(s1, t, z2z2);
  fp8_montmul(t, q.y, p.z);
  fp8_montmul(s2, t, z1z1);
  const __mmask8 equ = fp8_eq_mask(u1, u2);
  const __mmask8 eqs = fp8_eq_mask(s1, s2);
  exc |= (__mmask8)(~pinf & ~qinf & equ & eqs);
  Fp8 h, i, j, r, v, x3, y3, z3, sj;
  fp8_sub(h, u2, u1);
  fp8_add(i, h, h);
  fp8_sqr(i, i);
  fp8_montmul(j, h, i);
  fp8_sub(r, s2, s1);
  fp8_add(r, r, r);
  fp8_montmul(v, u1, i);
  fp8_sqr(x3, r);
  fp8_sub(x3, x3, j);
  fp8_sub(x3, x3, v);
  fp8_sub(x3, x3, v);
  fp8_sub(t, v, x3);
  fp8_montmul(y3, r, t);
  fp8_montmul(sj, s1, j);
  fp8_sub(y3, y3, sj);
  fp8_sub(y3, y3, sj);
  fp8_montmul(t, p.z, q.z);
  fp8_add(t, t, t);
  fp8_montmul(z3, t, h);
  fp8_blend(x3, pinf, x3, q.x);
  fp8_blend(y3, pinf, y3, q.y);
  fp8_blend(z3, pinf, z3, q.z);
  fp8_blend(x3, qinf, x3, p.x);
  fp8_blend(y3, qinf, y3, p.y);
  fp8_blend(z3, qinf, z3, p.z);
  o.x = x3; o.y = y3; o.z = z3;
}

EC_FP8_TARGET static void g1x8_blend(G1x8& o, __mmask8 take_b, const G1x8& a,
                                     const G1x8& b) {
  fp8_blend(o.x, take_b, a.x, b.x);
  fp8_blend(o.y, take_b, a.y, b.y);
  fp8_blend(o.z, take_b, a.z, b.z);
}

// Eight independent 128-bit scalar multiplications with one shared 4-bit
// window schedule (the scalars differ per lane, so each window's table
// pick is a 16-way masked select). Used for the RLC blinder products
// r_i * aggpk_i in batch verification.
EC_FP8_TARGET static void g1x8_mul128(G1x8& o, const G1x8& p,
                                      const u64 (*r)[2], int n,
                                      __mmask8& exc) {
  G1x8 tbl[16];
  {
    Fp ones[8], zeros[8];
    for (int k = 0; k < 8; k++) { ones[k] = FP_ONE; zeros[k] = FP_ZERO; }
    fp8_load(tbl[0].x, ones, 8);
    fp8_load(tbl[0].y, ones, 8);
    fp8_load(tbl[0].z, zeros, 8);
  }
  tbl[1] = p;
  for (int d = 2; d < 16; d++) {
    if (d % 2 == 0) g1x8_dbl(tbl[d], tbl[d / 2]);
    else g1x8_add(tbl[d], tbl[d - 1], p, exc);  // (d-1)P + P, d-1 >= 2
  }
  G1x8 acc;
  bool started = false;
  for (int w = 124; w >= 0; w -= 4) {
    if (started) {
      g1x8_dbl(acc, acc);
      g1x8_dbl(acc, acc);
      g1x8_dbl(acc, acc);
      g1x8_dbl(acc, acc);
    }
    u8 digs[8];
    u8 any = 0;
    for (int k = 0; k < 8; k++) {
      const u64* rk = r[k < n ? k : 0];
      digs[k] = (u8)((rk[w >> 6] >> (w & 63)) & 15);
      any |= digs[k];
    }
    if (!started && !any) continue;
    G1x8 sel = tbl[0];
    for (int d = 1; d < 16; d++) {
      __mmask8 m = 0;
      for (int k = 0; k < 8; k++)
        if (digs[k] == d) m |= (__mmask8)(1u << k);
      if (m) g1x8_blend(sel, m, sel, tbl[d]);
    }
    if (!started) { acc = sel; started = true; }
    else g1x8_add(acc, acc, sel, exc);
  }
  if (!started) acc = tbl[0];
  o = acc;
}

// Batched blinder products out[i] = r_i * pts[i] (r 128-bit, nonzero);
// exception lanes redo the scalar ladder — mirrors pt_mul exactly
static void g1_mul128_batch(G1* out, const G1* pts, const u64 (*r)[2],
                            size_t n) {
  size_t base = 0;
  for (; FP8_READY && base < n; base += 8) {
    int c = (int)(n - base < 8 ? n - base : 8);
    G1x8 pv, ov;
    g1x8_load(pv, pts + base, c);
    __mmask8 exc = 0;
    g1x8_mul128(ov, pv, r + base, c, exc);
    g1x8_store(out + base, ov, c);
    for (int k = 0; k < c; k++)
      if ((exc >> k) & 1) {
        u64 sc[2] = {r[base + k][0], r[base + k][1]};
        pt_mul(out[base + k], pts[base + k], sc, 2);
      }
  }
  for (; base < n; base++) {
    u64 sc[2] = {r[base][0], r[base][1]};
    pt_mul(out[base], pts[base], sc, 2);
  }
}

// Eight-lane sum of n (>= 8) decompressed G2 points: running partial
// sums per lane, scalar combine; infinity operands blend through, the
// duplicate-point doubling corner patches scalar (result == serial chain)
EC_FP8_TARGET static void g2_sum_pts_x8(G2& out, const G2* pts, size_t n) {
  G2x8 accv;
  g2x8_load(accv, pts, 8);
  size_t i = 8;
  for (; i + 8 <= n; i += 8) {
    G2x8 inc;
    g2x8_load(inc, pts + i, 8);
    const G2x8 saved = accv;
    __mmask8 exc = 0;
    g2x8_add(accv, accv, inc, exc);
    if (exc) {
      G2 sv[8], nw[8];
      g2x8_store(sv, saved, 8);
      g2x8_store(nw, accv, 8);
      for (int g = 0; g < 8; g++)
        if ((exc >> g) & 1) pt_add(nw[g], sv[g], pts[i + g]);
      g2x8_load(accv, nw, 8);
    }
  }
  G2 fin[8];
  g2x8_store(fin, accv, 8);
  G2 acc = pt_infinity<Fp2Ops>();
  for (int g = 0; g < 8; g++) pt_add(acc, acc, fin[g]);
  for (; i < n; i++) pt_add(acc, acc, pts[i]);
  out = acc;
}

// ---- Fp6x8 / Fp12x8: lane-parallel tower for the eight-wide Miller loop ----

EC_FP8_TARGET static void fp2x8_mul_by_xi(Fp2x8& o, const Fp2x8& a) {
  Fp8 t0, t1;
  fp8_sub(t0, a.c0, a.c1);
  fp8_add(t1, a.c0, a.c1);
  o.c0 = t0; o.c1 = t1;
}
EC_FP8_TARGET static void fp2x8_scalar_mul(Fp2x8& o, const Fp2x8& a,
                                           const Fp8& k) {
  fp8_montmul(o.c0, a.c0, k);
  fp8_montmul(o.c1, a.c1, k);
}

struct Fp6x8 { Fp2x8 a0, a1, a2; };
struct Fp12x8 { Fp6x8 c0, c1; };

EC_FP8_TARGET static void fp6x8_add(Fp6x8& o, const Fp6x8& a, const Fp6x8& b) {
  fp2x8_add(o.a0, a.a0, b.a0);
  fp2x8_add(o.a1, a.a1, b.a1);
  fp2x8_add(o.a2, a.a2, b.a2);
}
EC_FP8_TARGET static void fp6x8_sub(Fp6x8& o, const Fp6x8& a, const Fp6x8& b) {
  fp2x8_sub(o.a0, a.a0, b.a0);
  fp2x8_sub(o.a1, a.a1, b.a1);
  fp2x8_sub(o.a2, a.a2, b.a2);
}
EC_FP8_TARGET static void fp6x8_neg(Fp6x8& o, const Fp6x8& a) {
  fp2x8_neg(o.a0, a.a0);
  fp2x8_neg(o.a1, a.a1);
  fp2x8_neg(o.a2, a.a2);
}
// vector twin of fp6_mul (Toom/Karatsuba layout kept identical)
EC_FP8_TARGET static void fp6x8_mul(Fp6x8& o, const Fp6x8& a, const Fp6x8& b) {
  Fp2x8 t0, t1, t2, s, u, x, y, c0, c1, c2;
  fp2x8_mul(t0, a.a0, b.a0);
  fp2x8_mul(t1, a.a1, b.a1);
  fp2x8_mul(t2, a.a2, b.a2);
  fp2x8_add(s, a.a1, a.a2);
  fp2x8_add(u, b.a1, b.a2);
  fp2x8_mul(x, s, u);
  fp2x8_sub(x, x, t1);
  fp2x8_sub(x, x, t2);
  fp2x8_mul_by_xi(y, x);
  fp2x8_add(c0, t0, y);
  fp2x8_add(s, a.a0, a.a1);
  fp2x8_add(u, b.a0, b.a1);
  fp2x8_mul(x, s, u);
  fp2x8_sub(x, x, t0);
  fp2x8_sub(x, x, t1);
  fp2x8_mul_by_xi(y, t2);
  fp2x8_add(c1, x, y);
  fp2x8_add(s, a.a0, a.a2);
  fp2x8_add(u, b.a0, b.a2);
  fp2x8_mul(x, s, u);
  fp2x8_sub(x, x, t0);
  fp2x8_sub(x, x, t2);
  fp2x8_add(c2, x, t1);
  o.a0 = c0; o.a1 = c1; o.a2 = c2;
}
EC_FP8_TARGET static void fp6x8_mul_by_v(Fp6x8& o, const Fp6x8& a) {
  Fp2x8 t, old_a0, old_a1;
  fp2x8_mul_by_xi(t, a.a2);
  old_a0 = a.a0;
  old_a1 = a.a1;
  o.a0 = t; o.a1 = old_a0; o.a2 = old_a1;
}
EC_FP8_TARGET static void fp12x8_sqr(Fp12x8& o, const Fp12x8& a) {
  Fp6x8 u, s, t, vt;
  fp6x8_mul(u, a.c0, a.c1);
  fp6x8_add(s, a.c0, a.c1);
  fp6x8_mul_by_v(vt, a.c1);
  fp6x8_add(t, a.c0, vt);
  fp6x8_mul(t, s, t);
  fp6x8_sub(t, t, u);
  fp6x8_mul_by_v(vt, u);
  fp6x8_sub(o.c0, t, vt);
  fp6x8_add(o.c1, u, u);
}
EC_FP8_TARGET static void fp12x8_conj(Fp12x8& o, const Fp12x8& a) {
  o.c0 = a.c0;
  fp6x8_neg(o.c1, a.c1);
}
// vector twin of fp12_mul_by_line (same sparse Karatsuba split)
EC_FP8_TARGET static void fp12x8_mul_by_line(Fp12x8& f, const Fp2x8& c00,
                                             const Fp2x8& c11,
                                             const Fp2x8& c12) {
  Fp6x8 t0;
  fp2x8_mul(t0.a0, f.c0.a0, c00);
  fp2x8_mul(t0.a1, f.c0.a1, c00);
  fp2x8_mul(t0.a2, f.c0.a2, c00);
  Fp6x8 t1;
  Fp2x8 u, w;
  fp2x8_mul(u, f.c1.a1, c12);
  fp2x8_mul(w, f.c1.a2, c11);
  fp2x8_add(u, u, w);
  fp2x8_mul_by_xi(t1.a0, u);
  fp2x8_mul(u, f.c1.a0, c11);
  fp2x8_mul(w, f.c1.a2, c12);
  fp2x8_mul_by_xi(w, w);
  fp2x8_add(t1.a1, u, w);
  fp2x8_mul(u, f.c1.a0, c12);
  fp2x8_mul(w, f.c1.a1, c11);
  fp2x8_add(t1.a2, u, w);
  Fp6x8 sum, ab, t2;
  fp6x8_add(sum, f.c0, f.c1);
  ab.a0 = c00; ab.a1 = c11; ab.a2 = c12;
  fp6x8_mul(t2, sum, ab);
  Fp6x8 vt;
  fp6x8_mul_by_v(vt, t1);
  fp6x8_add(f.c0, t0, vt);
  fp6x8_sub(t2, t2, t0);
  fp6x8_sub(f.c1, t2, t1);
}
EC_FP8_TARGET static void fp12x8_blend(Fp12x8& o, __mmask8 take_b,
                                       const Fp12x8& a, const Fp12x8& b) {
  fp2x8_blend(o.c0.a0, take_b, a.c0.a0, b.c0.a0);
  fp2x8_blend(o.c0.a1, take_b, a.c0.a1, b.c0.a1);
  fp2x8_blend(o.c0.a2, take_b, a.c0.a2, b.c0.a2);
  fp2x8_blend(o.c1.a0, take_b, a.c1.a0, b.c1.a0);
  fp2x8_blend(o.c1.a1, take_b, a.c1.a1, b.c1.a1);
  fp2x8_blend(o.c1.a2, take_b, a.c1.a2, b.c1.a2);
}
EC_FP8_TARGET static void fp12x8_store_lanes(Fp12* out, const Fp12x8& a,
                                             int n) {
  const Fp8* comps[12] = {
      &a.c0.a0.c0, &a.c0.a0.c1, &a.c0.a1.c0, &a.c0.a1.c1,
      &a.c0.a2.c0, &a.c0.a2.c1, &a.c1.a0.c0, &a.c1.a0.c1,
      &a.c1.a1.c0, &a.c1.a1.c1, &a.c1.a2.c0, &a.c1.a2.c1};
  Fp lanes[12][8];
  for (int c = 0; c < 12; c++) fp8_store(lanes[c], *comps[c], 8);
  for (int k = 0; k < n; k++) {
    out[k].c0.a0.c0 = lanes[0][k];  out[k].c0.a0.c1 = lanes[1][k];
    out[k].c0.a1.c0 = lanes[2][k];  out[k].c0.a1.c1 = lanes[3][k];
    out[k].c0.a2.c0 = lanes[4][k];  out[k].c0.a2.c1 = lanes[5][k];
    out[k].c1.a0.c0 = lanes[6][k];  out[k].c1.a0.c1 = lanes[7][k];
    out[k].c1.a1.c0 = lanes[8][k];  out[k].c1.a1.c1 = lanes[9][k];
    out[k].c1.a2.c0 = lanes[10][k]; out[k].c1.a2.c1 = lanes[11][k];
  }
}

// ---- eight-wide Miller loop: pairs round-robined over lanes ----
//
// The scalar multi_miller_loop shares ONE f-squaring chain across all
// pairs; here the pairs split into eight groups (pair i -> slot i/8,
// lane i%8), each lane accumulates its own group product through the
// same shared-squaring chain, and the eight group products multiply
// together scalar-side at the end — algebraically the identical Miller
// product, bit-for-bit (selftest-pinned against the scalar loop).

struct MillerPairX8 {
  Fp8 xp, yp;     // G1 affine lanes
  Fp2x8 xq, yq;   // G2 affine lanes (twist coords)
  G2x8 t;         // per-lane accumulator
};

EC_FP8_TARGET static void miller_double_step_x8(Fp12x8& f, MillerPairX8& pr) {
  const Fp2x8 X = pr.t.x, Y = pr.t.y, Z = pr.t.z;
  Fp2x8 A, B, C, Z2, Z3c, L, X3c, E, c00, c11, c12, t, u;
  fp2x8_sqr(A, X);
  fp2x8_sqr(B, Y);
  fp2x8_sqr(C, B);
  fp2x8_sqr(Z2, Z);
  fp2x8_mul(Z3c, Z2, Z);
  fp2x8_mul(L, Y, Z3c);
  fp2x8_add(L, L, L);
  fp2x8_scalar_mul(t, L, pr.yp);
  fp2x8_mul_by_xi(t, t);
  fp2x8_neg(c00, t);
  fp2x8_mul(X3c, A, X);
  fp2x8_add(c11, B, B);
  fp2x8_add(u, X3c, X3c);
  fp2x8_add(u, u, X3c);
  fp2x8_sub(c11, c11, u);
  fp2x8_add(E, A, A);
  fp2x8_add(E, E, A);
  fp2x8_mul(t, E, Z2);
  fp2x8_scalar_mul(c12, t, pr.xp);
  fp12x8_mul_by_line(f, c00, c11, c12);
  Fp2x8 D, F, x3, y3, z3, c8;
  fp2x8_add(t, X, B);
  fp2x8_sqr(t, t);
  fp2x8_sub(t, t, A);
  fp2x8_sub(D, t, C);
  fp2x8_add(D, D, D);
  fp2x8_sqr(F, E);
  fp2x8_sub(x3, F, D);
  fp2x8_sub(x3, x3, D);
  fp2x8_add(c8, C, C);
  fp2x8_add(c8, c8, c8);
  fp2x8_add(c8, c8, c8);
  fp2x8_sub(t, D, x3);
  fp2x8_mul(y3, E, t);
  fp2x8_sub(y3, y3, c8);
  fp2x8_mul(z3, Y, Z);
  fp2x8_add(z3, z3, z3);
  pr.t.x = x3; pr.t.y = y3; pr.t.z = z3;
}

EC_FP8_TARGET static void miller_add_step_x8(Fp12x8& f, MillerPairX8& pr) {
  const Fp2x8 X = pr.t.x, Y = pr.t.y, Z = pr.t.z;
  Fp2x8 Z2, Z3c, U2, S2, lam_n, lam_d, t, u, c00, c11, c12;
  fp2x8_sqr(Z2, Z);
  fp2x8_mul(Z3c, Z2, Z);
  fp2x8_mul(U2, pr.xq, Z2);
  fp2x8_mul(S2, pr.yq, Z3c);
  fp2x8_sub(lam_n, Y, S2);
  fp2x8_sub(t, X, U2);
  fp2x8_mul(lam_d, t, Z);
  fp2x8_scalar_mul(u, lam_d, pr.yp);
  fp2x8_mul_by_xi(u, u);
  fp2x8_neg(c00, u);
  fp2x8_mul(t, pr.yq, lam_d);
  fp2x8_mul(u, lam_n, pr.xq);
  fp2x8_sub(c11, t, u);
  fp2x8_scalar_mul(c12, lam_n, pr.xp);
  fp12x8_mul_by_line(f, c00, c11, c12);
  Fp2x8 H, HH, I, J, rr, V, x3, y3, z3;
  fp2x8_sub(H, U2, X);
  fp2x8_sqr(HH, H);
  fp2x8_add(I, HH, HH);
  fp2x8_add(I, I, I);
  fp2x8_mul(J, H, I);
  fp2x8_sub(rr, S2, Y);
  fp2x8_add(rr, rr, rr);
  fp2x8_mul(V, X, I);
  fp2x8_sqr(x3, rr);
  fp2x8_sub(x3, x3, J);
  fp2x8_sub(x3, x3, V);
  fp2x8_sub(x3, x3, V);
  fp2x8_sub(t, V, x3);
  fp2x8_mul(y3, rr, t);
  fp2x8_mul(u, Y, J);
  fp2x8_add(u, u, u);
  fp2x8_sub(y3, y3, u);
  fp2x8_add(z3, Z, H);
  fp2x8_sqr(z3, z3);
  fp2x8_sub(z3, z3, Z2);
  fp2x8_sub(z3, z3, HH);
  pr.t.x = x3; pr.t.y = y3; pr.t.z = z3;
}

EC_FP8_TARGET static void multi_miller_loop_x8_impl(Fp12& f_out,
                                                    MillerPair* pairs,
                                                    size_t m) {
  const size_t K = (m + 7) / 8;           // slots; pair i -> slot i/8, lane i%8
  // MillerPairX8 holds __m512i members (alignof 64). Plain new[] only
  // honors that from C++17's aligned-new on; under a C++14 toolchain the
  // 16-byte-aligned heap block GP-faults the first vmovdqa64. Align by
  // hand so the build is safe regardless of -std level.
  char* slots_raw = new char[K * sizeof(MillerPairX8) + 64];
  MillerPairX8* slots = reinterpret_cast<MillerPairX8*>(
      (reinterpret_cast<uintptr_t>(slots_raw) + 63) & ~uintptr_t(63));
  int acts[64];  // K <= 64 enforced by caller? no — heap-size acts
  int* act = (K > 64) ? new int[K] : acts;
  for (size_t k = 0; k < K; k++) {
    size_t lo = 8 * k;
    int c = (int)(m - lo < 8 ? m - lo : 8);
    act[k] = c;
    Fp xp[8], yp[8], xq0[8], xq1[8], yq0[8], yq1[8];
    for (int g = 0; g < 8; g++) {
      const MillerPair& p = pairs[lo + (g < c ? g : 0)];
      xp[g] = p.xp; yp[g] = p.yp;
      xq0[g] = p.xq.c0; xq1[g] = p.xq.c1;
      yq0[g] = p.yq.c0; yq1[g] = p.yq.c1;
    }
    fp8_load(slots[k].xp, xp, 8);
    fp8_load(slots[k].yp, yp, 8);
    fp8_load(slots[k].xq.c0, xq0, 8);
    fp8_load(slots[k].xq.c1, xq1, 8);
    fp8_load(slots[k].yq.c0, yq0, 8);
    fp8_load(slots[k].yq.c1, yq1, 8);
    slots[k].t.x = slots[k].xq;
    slots[k].t.y = slots[k].yq;
    // z = 1 in every lane
    Fp ones[8], zeros[8];
    for (int g = 0; g < 8; g++) { ones[g] = FP_ONE; zeros[g] = FP_ZERO; }
    fp8_load(slots[k].t.z.c0, ones, 8);
    fp8_load(slots[k].t.z.c1, zeros, 8);
  }
  // f = 1 in every lane
  Fp12x8 f;
  {
    Fp ones[8], zeros[8];
    for (int g = 0; g < 8; g++) { ones[g] = FP_ONE; zeros[g] = FP_ZERO; }
    Fp8 one8, zero8;
    fp8_load(one8, ones, 8);
    fp8_load(zero8, zeros, 8);
    f.c0.a0.c0 = one8;  f.c0.a0.c1 = zero8;
    f.c0.a1.c0 = zero8; f.c0.a1.c1 = zero8;
    f.c0.a2.c0 = zero8; f.c0.a2.c1 = zero8;
    f.c1.a0.c0 = zero8; f.c1.a0.c1 = zero8;
    f.c1.a1.c0 = zero8; f.c1.a1.c1 = zero8;
    f.c1.a2.c0 = zero8; f.c1.a2.c1 = zero8;
  }
  int msb = 63;
  while (!((BLS_X_ABS >> msb) & 1)) msb--;
  for (int b = msb - 1; b >= 0; b--) {
    fp12x8_sqr(f, f);
    for (size_t k = 0; k < K; k++) {
      if (act[k] == 8) {
        miller_double_step_x8(f, slots[k]);
      } else {
        // ragged slot: inactive lanes keep their f untouched
        Fp12x8 fsave = f;
        miller_double_step_x8(f, slots[k]);
        fp12x8_blend(f, (__mmask8)((1u << act[k]) - 1), fsave, f);
      }
    }
    if ((BLS_X_ABS >> b) & 1) {
      for (size_t k = 0; k < K; k++) {
        if (act[k] == 8) {
          miller_add_step_x8(f, slots[k]);
        } else {
          Fp12x8 fsave = f;
          miller_add_step_x8(f, slots[k]);
          fp12x8_blend(f, (__mmask8)((1u << act[k]) - 1), fsave, f);
        }
      }
    }
  }
  fp12x8_conj(f, f);  // x negative
  Fp12 lanes[8];
  fp12x8_store_lanes(lanes, f, 8);
  Fp12 total = lanes[0];
  for (int g = 1; g < 8; g++) fp12_mul(total, total, lanes[g]);
  f_out = total;
  if (act != acts) delete[] act;
  delete[] slots_raw;
}

// Batched cofactor clearing over n Jacobian sums (the hash-to-G2 tail):
// exception lanes redo the scalar chain; result identical to
// g2_clear_cofactor by construction
static void g2_clear_cofactor_batch(G2* out, const G2* in, size_t n) {
  if (!FP8_READY || PSI_STATE != 1) {
    for (size_t i = 0; i < n; i++) g2_clear_cofactor(out[i], in[i]);
    return;
  }
  for (size_t base = 0; base < n; base += 8) {
    int c = (int)(n - base < 8 ? n - base : 8);
    G2x8 pv, ov;
    g2x8_load(pv, in + base, c);
    __mmask8 exc = 0;
    g2x8_clear_cofactor(ov, pv, exc);
    g2x8_store(out + base, ov, c);
    for (int k = 0; k < c; k++)
      if ((exc >> k) & 1) g2_clear_cofactor(out[base + k], in[base + k]);
  }
}

// [|x|]P on G1 lanes, shared sparse schedule (no negate — used squared)
EC_FP8_TARGET static void g1x8_mul_bls_x_abs(G1x8& o, const G1x8& p,
                                             __mmask8& exc) {
  G1x8 acc = p;
  for (int b = 62; b >= 0; b--) {
    g1x8_dbl(acc, acc);
    if ((BLS_X_ABS >> b) & 1) g1x8_add(acc, acc, p, exc);
  }
  o = acc;
}

// GLV criterion phi(P) + P == [x^2]P per lane (vector twin of
// g1_in_subgroup_fast); degenerate lanes land in *exc
EC_FP8_TARGET static __mmask8 g1x8_in_subgroup_mask(const G1x8& p,
                                                    __mmask8& exc) {
  G1x8 l = p, r, t;
  Fp8 beta;
  fp8_load(beta, &G1_BETA, 1);
  fp8_montmul(l.x, p.x, beta);
  g1x8_add(l, l, p, exc);
  g1x8_mul_bls_x_abs(t, p, exc);
  g1x8_mul_bls_x_abs(r, t, exc);
  const __mmask8 linf = fp8_is_zero_mask(l.z);
  const __mmask8 rinf = fp8_is_zero_mask(r.z);
  exc |= (__mmask8)(linf | rinf);
  Fp8 z1z1, z2z2, a, b, z1c, z2c;
  fp8_sqr(z1z1, l.z);
  fp8_sqr(z2z2, r.z);
  fp8_montmul(a, l.x, z2z2);
  fp8_montmul(b, r.x, z1z1);
  const __mmask8 xeq = fp8_eq_mask(a, b);
  fp8_montmul(z1c, z1z1, l.z);
  fp8_montmul(z2c, z2z2, r.z);
  fp8_montmul(a, l.y, z2c);
  fp8_montmul(b, r.y, z1c);
  const __mmask8 yeq = fp8_eq_mask(a, b);
  return xeq & yeq;
}

// Batched subgroup membership for n points; mirrors g2_in_subgroup
static void g2_in_subgroup_batch(bool* ok, const G2* pts, size_t n) {
  if (!FP8_READY || G2_SUB_STATE != 1) {
    for (size_t i = 0; i < n; i++) ok[i] = g2_in_subgroup(pts[i]);
    return;
  }
  for (size_t base = 0; base < n; base += 8) {
    int c = (int)(n - base < 8 ? n - base : 8);
    G2x8 pv;
    g2x8_load(pv, pts + base, c);
    __mmask8 exc = 0;
    const __mmask8 in_sub = g2x8_in_subgroup_mask(pv, exc);
    for (int k = 0; k < c; k++) {
      if ((exc >> k) & 1) ok[base + k] = g2_in_subgroup(pts[base + k]);
      else ok[base + k] = (in_sub >> k) & 1;
    }
  }
}

// Batched G1 subgroup membership; mirrors g1_in_subgroup
static void g1_in_subgroup_batch(bool* ok, const G1* pts, size_t n) {
  if (!FP8_READY || G1_SUB_STATE != 1) {
    for (size_t i = 0; i < n; i++) ok[i] = g1_in_subgroup(pts[i]);
    return;
  }
  for (size_t base = 0; base < n; base += 8) {
    int c = (int)(n - base < 8 ? n - base : 8);
    G1x8 pv;
    g1x8_load(pv, pts + base, c);
    __mmask8 exc = 0;
    const __mmask8 in_sub = g1x8_in_subgroup_mask(pv, exc);
    for (int k = 0; k < c; k++) {
      if ((exc >> k) & 1) ok[base + k] = g1_in_subgroup(pts[base + k]);
      else ok[base + k] = (in_sub >> k) & 1;
    }
  }
}

// Eight-lane sum of n (>= 8) G1 points (the aggregate_public_keys tail)
EC_FP8_TARGET static void g1_sum_pts_x8(G1& out, const G1* pts, size_t n) {
  G1x8 accv;
  g1x8_load(accv, pts, 8);
  size_t i = 8;
  for (; i + 8 <= n; i += 8) {
    G1x8 inc;
    g1x8_load(inc, pts + i, 8);
    const G1x8 saved = accv;
    __mmask8 exc = 0;
    g1x8_add(accv, accv, inc, exc);
    if (exc) {
      G1 sv[8], nw[8];
      g1x8_store(sv, saved, 8);
      g1x8_store(nw, accv, 8);
      for (int g = 0; g < 8; g++)
        if ((exc >> g) & 1) pt_add(nw[g], sv[g], pts[i + g]);
      g1x8_load(accv, nw, 8);
    }
  }
  G1 fin[8];
  g1x8_store(fin, accv, 8);
  G1 acc = pt_infinity<FpOps>();
  for (int g = 0; g < 8; g++) pt_add(acc, acc, fin[g]);
  for (; i < n; i++) pt_add(acc, acc, pts[i]);
  out = acc;
}

#else  // !EC_FP8_COMPILED

static void g2_clear_cofactor_batch(G2* out, const G2* in, size_t n) {
  for (size_t i = 0; i < n; i++) g2_clear_cofactor(out[i], in[i]);
}
static void g2_in_subgroup_batch(bool* ok, const G2* pts, size_t n) {
  for (size_t i = 0; i < n; i++) ok[i] = g2_in_subgroup(pts[i]);
}
static void g1_in_subgroup_batch(bool* ok, const G1* pts, size_t n) {
  for (size_t i = 0; i < n; i++) ok[i] = g1_in_subgroup(pts[i]);
}
static void g1_mul128_batch(G1* out, const G1* pts, const u64 (*r)[2],
                            size_t n) {
  for (size_t i = 0; i < n; i++) {
    u64 sc[2] = {r[i][0], r[i][1]};
    pt_mul(out[i], pts[i], sc, 2);
  }
}

#endif  // EC_FP8_COMPILED

// Dispatch for the eight-wide Miller loop: worth the SoA conversion once
// enough pairs amortize the vector squaring chain. Measured crossover on
// the build machine: scalar wins at 2-3 pairs (1.8ms vs ~1.9ms), the
// lanes win from ~4 up (15 pairs: 7.0ms scalar vs ~2.5ms); single
// verifies (2 pairs) stay scalar.
static bool multi_miller_loop_x8_try(Fp12& f, MillerPair* pairs, size_t m) {
#ifdef EC_FP8_COMPILED
  if (FP8_READY && m >= 4) {
    multi_miller_loop_x8_impl(f, pairs, m);
    return true;
  }
#endif
  (void)f;
  (void)pairs;
  (void)m;
  return false;
}

// ---------------------------------------------------------------------------
// Batched hash-to-G2 / G2 decompression: the same algorithms as their
// scalar twins above, with the Fp2 sqrt chains routed through the
// eight-wide IFMA engine (fp2_sqrt_x8) and the scalar inversions through
// Montgomery batch inversion. Outputs are bit-identical to the scalar
// routines — SSWU canonicalizes the root's sign via sgn0 and
// decompression via the lex-largest flag, so WHICH square root the
// engine returns cannot matter — and fp2_sqrt_x8 verifies each root
// per-lane with scalar recomputation as the net.
// ---------------------------------------------------------------------------

// SSWU over n independent u values (n <= 32): scalar prologue with one
// batched inversion, batched gx1 sqrt chains, then a batched gx2 retry
// for lanes whose gx1 was a non-square (mirrors map_to_curve_sswu)
static void map_to_curve_sswu_batch(Fp2* xs, Fp2* ys, const Fp2* us, int n) {
  Fp2 zu2[32], x1[32], gx1[32], tv[32], y1[32];
  bool tv_zero[32];
  for (int i = 0; i < n; i++) {
    Fp2 u2;
    fp2_sqr(u2, us[i]);
    fp2_mul(zu2[i], SSWU_Z, u2);
    fp2_sqr(tv[i], zu2[i]);
    fp2_add(tv[i], tv[i], zu2[i]);
    tv_zero[i] = fp2_is_zero(tv[i]);
    if (tv_zero[i]) tv[i] = FP2_ONE;  // placeholder; lane uses B_OVER_ZA
  }
  fp2_inv_batch(tv, n);
  for (int i = 0; i < n; i++) {
    Fp2 t, x3, ax;
    if (tv_zero[i]) {
      x1[i] = SSWU_B_OVER_ZA;
    } else {
      fp2_add(t, FP2_ONE, tv[i]);
      fp2_mul(x1[i], SSWU_NEG_B_OVER_A, t);
    }
    fp2_sqr(t, x1[i]);
    fp2_mul(x3, t, x1[i]);
    fp2_mul(ax, SSWU_A, x1[i]);
    fp2_add(gx1[i], x3, ax);
    fp2_add(gx1[i], gx1[i], SSWU_B);
  }
  u32 ok = 0;
  for (int base = 0; base < n; base += 8) {
    int c = n - base < 8 ? n - base : 8;
    const Fp2* ptrs[8];
    for (int k = 0; k < c; k++) ptrs[k] = &gx1[base + k];
    ok |= fp2_sqrt_x8(y1 + base, ptrs, c) << base;
  }
  int fidx[32], nf = 0;
  Fp2 gx2[32], y2o[32];
  for (int i = 0; i < n; i++) {
    if ((ok >> i) & 1) {
      xs[i] = x1[i];
      ys[i] = y1[i];
      continue;
    }
    Fp2 x2, t, x3, ax;
    fp2_mul(x2, zu2[i], x1[i]);
    xs[i] = x2;
    fp2_sqr(t, x2);
    fp2_mul(x3, t, x2);
    fp2_mul(ax, SSWU_A, x2);
    fp2_add(gx2[nf], x3, ax);
    fp2_add(gx2[nf], gx2[nf], SSWU_B);
    fidx[nf++] = i;
  }
  for (int base = 0; base < nf; base += 8) {
    int c = nf - base < 8 ? nf - base : 8;
    const Fp2* ptrs[8];
    for (int k = 0; k < c; k++) ptrs[k] = &gx2[base + k];
    fp2_sqrt_x8(y2o + base, ptrs, c);  // must succeed when gx1 is not square
  }
  for (int k = 0; k < nf; k++) ys[fidx[k]] = y2o[k];
  for (int i = 0; i < n; i++)
    if (fp2_sgn0(ys[i]) != fp2_sgn0(us[i])) fp2_neg(ys[i], ys[i]);
}

// hash-to-G2 over n messages: expand_message_xmd stays scalar (SHA-256
// bound), SSWU sqrts batch eight-wide, the isogeny denominators share
// one inversion per chunk, cofactor clearing stays scalar point math
static bool hash_to_g2_batch(G2* out, const u8* msgs, const u32* msg_lens,
                             size_t n, const u8* dst, size_t dst_len) {
  const int CH = 16;  // messages per chunk -> 32 SSWU jobs
  size_t off = 0;
  for (size_t base = 0; base < n; base += CH) {
    int c = (int)(n - base < (size_t)CH ? n - base : CH);
    Fp2 us[32], xs[32], ys[32];
    for (int k = 0; k < c; k++) {
      u8 uniform[256];
      if (!expand_message_xmd(uniform, 256, msgs + off, msg_lens[base + k],
                              dst, dst_len))
        return false;
      off += msg_lens[base + k];
      fp_from_64_bytes(us[2 * k].c0, uniform);
      fp_from_64_bytes(us[2 * k].c1, uniform + 64);
      fp_from_64_bytes(us[2 * k + 1].c0, uniform + 128);
      fp_from_64_bytes(us[2 * k + 1].c1, uniform + 192);
    }
    map_to_curve_sswu_batch(xs, ys, us, 2 * c);
    // isogeny with batched denominator inversion (2 per SSWU output)
    Fp2 xn[32], yn[32], den[64];
    bool inf[32];
    for (int j = 0; j < 2 * c; j++) {
      Fp2 xd, yd;
      horner_fp2(xn[j], ISO_XN, 4, xs[j]);
      horner_fp2(xd, ISO_XD, 3, xs[j]);
      horner_fp2(yn[j], ISO_YN, 4, xs[j]);
      horner_fp2(yd, ISO_YD, 4, xs[j]);
      inf[j] = fp2_is_zero(xd) || fp2_is_zero(yd);
      den[2 * j] = inf[j] ? FP2_ONE : xd;
      den[2 * j + 1] = inf[j] ? FP2_ONE : yd;
    }
    fp2_inv_batch(den, 2 * c * 2);
    G2 sums[16];
    for (int k = 0; k < c; k++) {
      G2 q[2];
      for (int h = 0; h < 2; h++) {
        int j = 2 * k + h;
        if (inf[j]) {
          q[h] = pt_infinity<Fp2Ops>();
          continue;
        }
        Fp2 xo, yo, t;
        fp2_mul(xo, xn[j], den[2 * j]);
        fp2_mul(t, yn[j], den[2 * j + 1]);
        fp2_mul(yo, ys[j], t);
        q[h] = pt_from_affine<Fp2Ops>(xo, yo);
      }
      pt_add(sums[k], q[0], q[1]);
    }
    g2_clear_cofactor_batch(out + base, sums, c);
  }
  return true;
}

static void g1_in_subgroup_batch(bool* ok, const G1* pts, size_t n);

// n compressed G1 points with the sqrt chains batched eight-wide and the
// subgroup criterion eight-wide; per-point rc mirrors g1_decompress
// exactly. Serves pubkey-cache bulk fills and aggregate_public_keys.
static void g1_decompress_batch(G1* out, int* rcs, const u8* pks, size_t n,
                                bool check_subgroup) {
  Fp* xs = new Fp[n];
  Fp* y2s = new Fp[n];
  u8* sign_flags = new u8[n];
  for (size_t i = 0; i < n; i++) {
    const u8* in = pks + 48 * i;
    u8 flags = in[0];
    sign_flags[i] = flags & FLAG_SIGN;
    if (!(flags & FLAG_COMPRESSED)) {
      rcs[i] = DEC_NOT_COMPRESSED;
      continue;
    }
    if (flags & FLAG_INFINITY) {
      rcs[i] = DEC_BAD_INFINITY;
      if (!(flags & ~(FLAG_COMPRESSED | FLAG_INFINITY))) {
        bool zero = true;
        for (int b = 1; b < 48; b++)
          if (in[b]) { zero = false; break; }
        if (zero) {
          out[i] = pt_infinity<FpOps>();
          rcs[i] = DEC_OK;
        }
      }
      continue;
    }
    u8 buf[48];
    memcpy(buf, in, 48);
    buf[0] = flags & 0x1F;
    if (!fp_from_bytes(xs[i], buf)) {
      rcs[i] = DEC_NOT_IN_FIELD;
      continue;
    }
    Fp t;
    fp_sqr(t, xs[i]);
    fp_mul(y2s[i], t, xs[i]);
    fp_add(y2s[i], y2s[i], G1_B);
    rcs[i] = -1;  // sqrt pending
  }
  {
    int pend[8], m = 0;
    const Fp* ptrs[8];
    Fp roots[8];
    for (size_t k = 0; k <= n; k++) {
      if (k < n && rcs[k] == -1) pend[m++] = (int)k;
      if ((m == 8 || k == n) && m > 0) {
        for (int j = 0; j < m; j++) ptrs[j] = &y2s[pend[j]];
        u32 ok = fp_sqrt_x8(roots, ptrs, m);
        for (int j = 0; j < m; j++) {
          size_t idx = pend[j];
          if (!((ok >> j) & 1)) {
            rcs[idx] = DEC_NOT_ON_CURVE;
            continue;
          }
          Fp y = roots[j];
          if (fp_is_lex_largest(y) != !!sign_flags[idx]) fp_neg(y, y);
          out[idx] = pt_from_affine<FpOps>(xs[idx], y);
          rcs[idx] = DEC_OK;
        }
        m = 0;
      }
    }
  }
  if (check_subgroup) {
    G1 good[8];
    bool sub_ok[8];
    size_t gidx[8];
    int g = 0;
    for (size_t k = 0; k <= n; k++) {
      if (k < n && rcs[k] == DEC_OK && !out[k].is_inf()) {
        good[g] = out[k];
        gidx[g++] = k;
      }
      if ((g == 8 || k == n) && g > 0) {
        g1_in_subgroup_batch(sub_ok, good, g);
        for (int j = 0; j < g; j++)
          if (!sub_ok[j]) rcs[gidx[j]] = DEC_NOT_IN_SUBGROUP;
        g = 0;
      }
    }
  }
  delete[] xs;
  delete[] y2s;
  delete[] sign_flags;
}

// n compressed G2 points with the sqrt chains batched; per-point rc
// mirrors g2_decompress exactly (same codes, same order of checks)
static void g2_decompress_batch(G2* out, int* rcs, const u8* sigs, size_t n,
                                bool check_subgroup) {
  Fp2* xs = new Fp2[n];
  Fp2* y2s = new Fp2[n];
  u8* sign_flags = new u8[n];
  for (size_t i = 0; i < n; i++) {
    const u8* in = sigs + 96 * i;
    u8 flags = in[0];
    sign_flags[i] = flags & FLAG_SIGN;
    rcs[i] = DEC_OK;
    if (!(flags & FLAG_COMPRESSED)) {
      rcs[i] = DEC_NOT_COMPRESSED;
      continue;
    }
    if (flags & FLAG_INFINITY) {
      rcs[i] = DEC_BAD_INFINITY;
      if (!(flags & ~(FLAG_COMPRESSED | FLAG_INFINITY))) {
        bool zero = true;
        for (int b = 1; b < 96; b++)
          if (in[b]) { zero = false; break; }
        if (zero) {
          out[i] = pt_infinity<Fp2Ops>();
          rcs[i] = DEC_OK;
          continue;
        }
      }
      continue;
    }
    u8 buf[48];
    memcpy(buf, in, 48);
    buf[0] = flags & 0x1F;
    if (!fp_from_bytes(xs[i].c1, buf) || !fp_from_bytes(xs[i].c0, in + 48)) {
      rcs[i] = DEC_NOT_IN_FIELD;
      continue;
    }
    Fp2 t;
    fp2_sqr(t, xs[i]);
    fp2_mul(y2s[i], t, xs[i]);
    fp2_add(y2s[i], y2s[i], G2_B);
    rcs[i] = -1;  // marks "sqrt pending"
  }
  int pend[8];
  const Fp2* ptrs[8];
  Fp2 roots[8];
  {
    int m = 0;
    for (size_t k = 0; k <= n; k++) {
      if (k < n && rcs[k] == -1) pend[m++] = (int)k;
      if ((m == 8 || k == n) && m > 0) {
        for (int j = 0; j < m; j++) ptrs[j] = &y2s[pend[j]];
        u32 ok = fp2_sqrt_x8(roots, ptrs, m);
        for (int j = 0; j < m; j++) {
          size_t idx = pend[j];
          if (!((ok >> j) & 1)) {
            rcs[idx] = DEC_NOT_ON_CURVE;
            continue;
          }
          Fp2 y = roots[j];
          if (fp2_is_lex_largest(y) != !!sign_flags[idx]) fp2_neg(y, y);
          out[idx] = pt_from_affine<Fp2Ops>(xs[idx], y);
          rcs[idx] = DEC_OK;
        }
        m = 0;
      }
    }
  }
  if (check_subgroup) {
    // eight-wide psi criterion over the successfully decoded finite points
    G2 good[8];
    bool sub_ok[8];
    size_t gidx[8];
    int g = 0;
    for (size_t k = 0; k <= n; k++) {
      if (k < n && rcs[k] == DEC_OK && !out[k].is_inf()) {
        good[g] = out[k];
        gidx[g++] = k;
      }
      if ((g == 8 || k == n) && g > 0) {
        g2_in_subgroup_batch(sub_ok, good, g);
        for (int j = 0; j < g; j++)
          if (!sub_ok[j]) rcs[gidx[j]] = DEC_NOT_IN_SUBGROUP;
        g = 0;
      }
    }
  }
  delete[] xs;
  delete[] y2s;
  delete[] sign_flags;
}

// ---------------------------------------------------------------------------
// Pippenger multi-scalar multiplication
// ---------------------------------------------------------------------------

static void scalar_from_be32(u64 out[4], const u8 in[32]) {
  for (int i = 0; i < 4; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[i * 8 + j];
    out[3 - i] = w;
  }
}

static inline int scalar_window(const u64* limbs, int nlimbs, int bit, int c) {
  // c-bit digit starting at `bit` (LSB order), c <= 16
  int limb = bit >> 6, off = bit & 63;
  if (limb >= nlimbs) return 0;
  u64 v = limbs[limb] >> off;
  if (off + c > 64 && limb + 1 < nlimbs) v |= limbs[limb + 1] << (64 - off);
  return (int)(v & (((u64)1 << c) - 1));
}

static inline int msm_window_bits(size_t n) {
  return n < 4 ? 2 : n < 32 ? 4 : n < 256 ? 6 : n < 4096 ? 8 : 10;
}

template <class Ops>
static void pt_msm(Point<Ops>& out, const Point<Ops>* pts, const u64* scalars,
                   size_t n, int scalar_bits) {
  if (n == 0) { out = pt_infinity<Ops>(); return; }
  int c = msm_window_bits(n);
  int nbuckets = (1 << c) - 1;
  Point<Ops>* buckets = new Point<Ops>[nbuckets];
  Point<Ops> result = pt_infinity<Ops>();
  int windows = (scalar_bits + c - 1) / c;
  const typename Ops::F one = Ops::one();
  for (int win = windows - 1; win >= 0; win--) {
    for (int i = 0; i < c; i++) pt_double(result, result);
    for (int b = 0; b < nbuckets; b++) buckets[b] = pt_infinity<Ops>();
    for (size_t k = 0; k < n; k++) {
      int d = scalar_window(scalars + 4 * k, 4, win * c, c);
      if (!d) continue;
      // mixed add for affine inputs (z = 1, the raw-bytes common case)
      if (Ops::eq(pts[k].z, one)) {
        pt_add_affine(buckets[d - 1], buckets[d - 1], pts[k].x, pts[k].y);
      } else {
        pt_add(buckets[d - 1], buckets[d - 1], pts[k]);
      }
    }
    Point<Ops> running = pt_infinity<Ops>(), acc = pt_infinity<Ops>();
    for (int b = nbuckets - 1; b >= 0; b--) {
      pt_add(running, running, buckets[b]);
      pt_add(acc, acc, running);
    }
    pt_add(result, result, acc);
  }
  delete[] buckets;
  out = result;
}

// Batch-affine Pippenger: buckets live in AFFINE coordinates and each
// round's bucket additions share ONE field inversion (Montgomery's
// trick), so an accumulation add costs ~6M instead of the Jacobian
// mixed add's 11M+5S. Collisions (two adds into the same bucket in one
// round) defer to the next round; once a round's batch gets too small
// Scratch for the signed-digit batch-affine bucket pass; sized once per
// MSM (nbuckets buckets, up to `cap` entries).
template <class Ops>
struct MsmScratch {
  typedef typename Ops::F F;
  int nbuckets;
  u32 *cnt, *off, *pos, *sz;
  char* jstate;
  Point<Ops>* jshadow;
  F *ix, *iy;          // item values, grouped by bucket
  u32 *sel_p, *sel_q, *sel_tgt;
  char* sel_dbl;
  F *denom, *prefix, *rx, *ry;
  MsmScratch(int nb, size_t cap) : nbuckets(nb) {
    cnt = new u32[nb + 1]; off = new u32[nb + 1];
    pos = new u32[nb]; sz = new u32[nb];
    jstate = new char[nb];
    jshadow = new Point<Ops>[nb];
    ix = new F[cap]; iy = new F[cap];
    sel_p = new u32[cap / 2 + 1]; sel_q = new u32[cap / 2 + 1];
    sel_tgt = new u32[cap / 2 + 1];
    sel_dbl = new char[cap / 2 + 1];
    denom = new F[cap / 2 + 1]; prefix = new F[cap / 2 + 2];
    rx = new F[cap / 2 + 1]; ry = new F[cap / 2 + 1];
  }
  ~MsmScratch() {
    delete[] cnt; delete[] off; delete[] pos; delete[] sz;
    delete[] jstate; delete[] jshadow;
    delete[] ix; delete[] iy;
    delete[] sel_p; delete[] sel_q; delete[] sel_tgt; delete[] sel_dbl;
    delete[] denom; delete[] prefix; delete[] rx; delete[] ry;
  }
};

// One signed-digit bucket pass over `ne` entries: entry t contributes
// point e_k[t] (negated when e_d[t] < 0) to bucket |e_d[t]|-1. Items
// group by bucket (counting sort), then a PAIRING TREE folds each
// bucket: every round pairs its items two by two — all pairs are
// independent affine additions sharing ONE inversion (Montgomery's
// trick) — so a bucket of depth m collapses in log2(m) rounds
// regardless of multiplicity (the fix for fixed-base passes where every
// bucket holds dozens of entries). Doubling and annihilation pairs are
// classified exactly; once a round is too small to amortize the shared
// inversion, the leftovers fold through Jacobian shadows. Returns
// acc = sum_b (b+1) * bucket_b.
template <class Ops>
static void msm_bucket_pass(Point<Ops>& acc_out, const typename Ops::F* xs,
                            const typename Ops::F* ys,
                            const typename Ops::F* nys, const u32* e_k,
                            const int16_t* e_d, size_t ne,
                            MsmScratch<Ops>& S) {
  typedef typename Ops::F F;
  const size_t BATCH_MIN = 16;
  const int nbuckets = S.nbuckets;
  // group items by bucket
  std::memset(S.cnt, 0, sizeof(u32) * (nbuckets + 1));
  for (size_t t = 0; t < ne; t++) {
    int d = e_d[t];
    S.cnt[(d < 0 ? -d : d) - 1 + 1]++;
  }
  S.off[0] = 0;
  for (int b = 0; b < nbuckets; b++) S.off[b + 1] = S.off[b] + S.cnt[b + 1];
  std::memcpy(S.pos, S.off, sizeof(u32) * nbuckets);
  for (size_t t = 0; t < ne; t++) {
    int d = e_d[t];
    char s = d < 0;
    int b = (s ? -d : d) - 1;
    u32 slot = S.pos[b]++;
    S.ix[slot] = xs[e_k[t]];
    S.iy[slot] = (s ? nys : ys)[e_k[t]];
  }
  for (int b = 0; b < nbuckets; b++) {
    S.sz[b] = S.off[b + 1] - S.off[b];
    S.jstate[b] = 0;
  }
  // pairing-tree rounds
  for (;;) {
    // phase 1 — selection only (no item mutation, so a too-small round
    // can abort cleanly): pairs, per-bucket survivor moves, new sizes
    size_t m = 0;
    for (int b = 0; b < nbuckets; b++) {
      u32 s = S.sz[b];
      if (s < 2) continue;
      u32 base = S.off[b];
      u32 w = 0;
      u32 i = 0;
      for (; i + 1 < s; i += 2) {
        u32 p = base + i, q = base + i + 1;
        if (Ops::eq(S.ix[p], S.ix[q])) {
          if (Ops::eq(S.iy[p], S.iy[q])) {
            if (Ops::is_zero(S.iy[p])) continue;         // 2-torsion: 2P = ∞
            S.sel_dbl[m] = 1;
            Ops::add(S.denom[m], S.iy[p], S.iy[p]);      // 2y
          } else {
            continue;                                    // P + (−P) = ∞
          }
        } else {
          S.sel_dbl[m] = 0;
          Ops::sub(S.denom[m], S.ix[q], S.ix[p]);        // x2 − x1
        }
        S.sel_p[m] = p; S.sel_q[m] = q; S.sel_tgt[m] = base + w;
        w++; m++;
      }
      // odd survivor's pending move rides in cnt (srv target = base + w)
      S.cnt[b] = (i < s) ? (w + 1) : w;  // new size if the round commits
      S.pos[b] = (i < s) ? 1 : 0;        // survivor flag
    }
    if (m < BATCH_MIN) {
      // Too few pairs to amortize the shared inversion — including the
      // m == 0 case where every pair ANNIHILATED (a bucket can still
      // hold >= 2 items then; treating its first item as the bucket
      // value would drop the cancellation). Fold every multi-item
      // bucket's UNTOUCHED items through a guarded Jacobian shadow and
      // stop; a round with no pairs and no multi-item buckets folds
      // nothing and just terminates.
      for (int b = 0; b < nbuckets; b++) {
        u32 s = S.sz[b];
        if (s < 2) continue;
        u32 base = S.off[b];
        S.jshadow[b] = pt_infinity<Ops>();
        S.jstate[b] = 1;
        for (u32 i = 0; i < s; i++)
          pt_add_affine(S.jshadow[b], S.jshadow[b], S.ix[base + i],
                        S.iy[base + i]);
        S.sz[b] = 0;
      }
      break;
    }
    // one shared inversion for the whole round
    S.prefix[0] = Ops::one();
    for (size_t t = 0; t < m; t++)
      Ops::mul(S.prefix[t + 1], S.prefix[t], S.denom[t]);
    F invall;
    Ops::inv(invall, S.prefix[m]);
    for (size_t t = m; t-- > 0;) {
      F dinv, lam, t1, x3, y3;
      Ops::mul(dinv, S.prefix[t], invall);
      Ops::mul(invall, invall, S.denom[t]);
      u32 p = S.sel_p[t], q = S.sel_q[t];
      if (S.sel_dbl[t]) {
        Ops::sqr(t1, S.ix[p]);
        F t2;
        Ops::add(t2, t1, t1);
        Ops::add(t1, t2, t1);                            // 3x²
        Ops::mul(lam, t1, dinv);
      } else {
        Ops::sub(t1, S.iy[q], S.iy[p]);                  // y2 − y1
        Ops::mul(lam, t1, dinv);
      }
      Ops::sqr(x3, lam);
      Ops::sub(x3, x3, S.ix[p]);
      Ops::sub(x3, x3, S.ix[q]);
      Ops::sub(t1, S.ix[p], x3);
      Ops::mul(y3, lam, t1);
      Ops::sub(y3, y3, S.iy[p]);
      S.rx[t] = x3;
      S.ry[t] = y3;
    }
    // commit: scatter results, apply survivor moves, update sizes
    // (targets never collide with unread sources: tgt <= p < q within a
    // bucket, and every source was read into rx/ry above)
    for (size_t t = 0; t < m; t++) {
      S.ix[S.sel_tgt[t]] = S.rx[t];
      S.iy[S.sel_tgt[t]] = S.ry[t];
    }
    for (int b = 0; b < nbuckets; b++) {
      u32 s = S.sz[b];
      if (s < 2) continue;
      u32 base = S.off[b];
      u32 w = S.cnt[b];
      if (S.pos[b]) {  // odd survivor: slot s-1 -> compacted tail slot
        S.ix[base + w - 1] = S.ix[base + s - 1];
        S.iy[base + w - 1] = S.iy[base + s - 1];
      }
      S.sz[b] = w;
    }
  }
  // bucket reduction
  Point<Ops> running = pt_infinity<Ops>(), acc = pt_infinity<Ops>();
  for (int b = nbuckets - 1; b >= 0; b--) {
    if (S.sz[b]) pt_add_affine(running, running, S.ix[S.off[b]], S.iy[S.off[b]]);
    if (S.jstate[b]) pt_add(running, running, S.jshadow[b]);
    pt_add(acc, acc, running);
  }
  acc_out = acc;
}

// signed window digits for one scalar: d in (-2^(c-1), 2^(c-1)], one
// spill window absorbing the final carry
static void msm_signed_digits(int16_t* out, const u64* scalar, int c,
                              int windows) {
  const int half = 1 << (c - 1);
  int carry = 0;
  for (int win = 0; win < windows; win++) {
    int v = scalar_window(scalar, 4, win * c, c) + carry;
    if (v > half) {
      out[win] = (int16_t)(v - (1 << c));
      carry = 1;
    } else {
      out[win] = (int16_t)v;
      carry = 0;
    }
  }
}

template <class Ops>
static void pt_msm_batch_affine(Point<Ops>& out, const typename Ops::F* xs,
                                const typename Ops::F* ys,
                                const u64* scalars, size_t n,
                                int scalar_bits) {
  typedef typename Ops::F F;
  if (n == 0) { out = pt_infinity<Ops>(); return; }
  int c = msm_window_bits(n);
  // SIGNED digits: negating an affine point is free (flip y), so half
  // the buckets cover the same window — the bucket reduction (the other
  // half of Pippenger's cost) halves with it.
  int windows = (scalar_bits + c - 1) / c + 1;
  int16_t* digs = new int16_t[n * (size_t)windows];
  for (size_t k = 0; k < n; k++)
    msm_signed_digits(digs + k * windows, scalars + 4 * k, c, windows);
  // negated y per point, picked by digit sign at zero per-use cost
  F* nys = new F[n];
  for (size_t k = 0; k < n; k++) Ops::neg(nys[k], ys[k]);
  u32* e_k = new u32[n];
  int16_t* e_d = new int16_t[n];
  MsmScratch<Ops> S(1 << (c - 1), n);
  Point<Ops> result = pt_infinity<Ops>();
  for (int win = windows - 1; win >= 0; win--) {
    for (int i = 0; i < c; i++) pt_double(result, result);
    size_t ne = 0;
    for (size_t k = 0; k < n; k++) {
      int16_t d = digs[k * windows + win];
      if (d) { e_k[ne] = (u32)k; e_d[ne] = d; ne++; }
    }
    Point<Ops> acc;
    msm_bucket_pass<Ops>(acc, xs, ys, nys, e_k, e_d, ne, S);
    pt_add(result, result, acc);
  }
  delete[] digs; delete[] nys; delete[] e_k; delete[] e_d;
  out = result;
}

// ---------------------------------------------------------------------------
// Fixed-base prepared MSM: when the base points are static (the KZG
// Lagrange setup — kzg.rs wraps c-kzg over the same fixed ceremony),
// precompute each point's window shifts P_k * 2^(c*win) once so every
// later MSM is a SINGLE signed-digit bucket pass: the per-window bucket
// reductions (half of Pippenger's cost) collapse into one, and the
// window count stops constraining the bucket width.
// ---------------------------------------------------------------------------

template <class Ops>
struct MsmPrepared {
  typedef typename Ops::F F;
  size_t n;
  int c, windows;
  F* xs;    // entry (k, win) = point k shifted by 2^(c*win), affine x
  F* ys;
  F* nys;
  char* inf;  // infinity entries contribute nothing and are skipped
  ~MsmPrepared() { delete[] xs; delete[] ys; delete[] nys; delete[] inf; }
};

static inline void msm_inv_batch(Fp* vals, int n) { fp_inv_batch(vals, n); }
static inline void msm_inv_batch(Fp2* vals, int n) { fp2_inv_batch(vals, n); }

template <class Ops>
static MsmPrepared<Ops>* msm_prepare(const Point<Ops>* pts, size_t n, int c) {
  typedef typename Ops::F F;
  const int windows = (256 + c - 1) / c + 1;
  const size_t total = n * (size_t)windows;
  MsmPrepared<Ops>* h = new MsmPrepared<Ops>;
  h->n = n;
  h->c = c;
  h->windows = windows;
  h->xs = new F[total];
  h->ys = new F[total];
  h->nys = new F[total];
  h->inf = new char[total];
  Point<Ops>* jac = new Point<Ops>[total];
  for (size_t k = 0; k < n; k++) {
    Point<Ops> p = pts[k];
    for (int win = 0; win < windows; win++) {
      jac[k * windows + win] = p;
      if (win + 1 < windows)
        for (int i = 0; i < c; i++) pt_double(p, p);
    }
  }
  // batch-normalize to affine: chunks of shared inversions
  const size_t CH = 64;
  F zs[CH];
  for (size_t base = 0; base < total; base += CH) {
    size_t m = total - base < CH ? total - base : CH;
    for (size_t t = 0; t < m; t++) {
      h->inf[base + t] = jac[base + t].is_inf();
      zs[t] = h->inf[base + t] ? Ops::one() : jac[base + t].z;
    }
    // F == Fp or Fp2: route through the matching batch inverter
    msm_inv_batch(zs, (int)m);
    for (size_t t = 0; t < m; t++) {
      if (h->inf[base + t]) {
        h->xs[base + t] = Ops::zero();
        h->ys[base + t] = Ops::zero();
        h->nys[base + t] = Ops::zero();
        continue;
      }
      F zi2, zi3;
      Ops::sqr(zi2, zs[t]);
      Ops::mul(zi3, zi2, zs[t]);
      Ops::mul(h->xs[base + t], jac[base + t].x, zi2);
      Ops::mul(h->ys[base + t], jac[base + t].y, zi3);
      Ops::neg(h->nys[base + t], h->ys[base + t]);
    }
  }
  delete[] jac;
  return h;
}

template <class Ops>
static void msm_prepared_run(Point<Ops>& out, const MsmPrepared<Ops>* h,
                             const u64* scalars) {
  const size_t n = h->n;
  const int c = h->c, windows = h->windows;
  int16_t* digs = new int16_t[(size_t)windows];
  u32* e_k = new u32[n * (size_t)windows];
  int16_t* e_d = new int16_t[n * (size_t)windows];
  size_t ne = 0;
  for (size_t k = 0; k < n; k++) {
    msm_signed_digits(digs, scalars + 4 * k, c, windows);
    for (int win = 0; win < windows; win++) {
      size_t idx = k * (size_t)windows + win;
      if (digs[win] && !h->inf[idx]) {
        e_k[ne] = (u32)idx;
        e_d[ne] = digs[win];
        ne++;
      }
    }
  }
  MsmScratch<Ops> S(1 << (c - 1), ne ? ne : 1);
  msm_bucket_pass<Ops>(out, h->xs, h->ys, h->nys, e_k, e_d, ne, S);
  delete[] digs;
  delete[] e_k;
  delete[] e_d;
}

// ---------------------------------------------------------------------------
// Fr: the scalar field (4x64 Montgomery) — barycentric blob-polynomial
// evaluation and quotient construction, the EIP-4844 math of kzg.py's
// _evaluate_polynomial_in_evaluation_form / _compute_kzg_proof_impl
// (the role c-kzg's C polynomial code plays for crypto/kzg.rs). The
// Python big-int implementation stays as the cross-checked fallback.
// ---------------------------------------------------------------------------

struct Fr { u64 l[4]; };

static u64 FR_NINV;   // -r^{-1} mod 2^64
static Fr FR_R2;      // 2^512 mod r (canonical limbs)
static Fr FR_ONE;     // Montgomery 1
static bool FR_READY = false;

static inline bool fr_is_zero(const Fr& a) {
  return !(a.l[0] | a.l[1] | a.l[2] | a.l[3]);
}
static inline bool fr_eq(const Fr& a, const Fr& b) {
  return a.l[0] == b.l[0] && a.l[1] == b.l[1] && a.l[2] == b.l[2] &&
         a.l[3] == b.l[3];
}
static inline int fr_cmp_raw(const u64* a, const u64* b) {
  for (int i = 3; i >= 0; i--) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}
static void fr_add(Fr& o, const Fr& a, const Fr& b) {
  u64 carry = 0;
  for (int i = 0; i < 4; i++) o.l[i] = adc(a.l[i], b.l[i], carry);
  if (carry || fr_cmp_raw(o.l, R_RAW) >= 0) {
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) o.l[i] = sbb(o.l[i], R_RAW[i], borrow);
  }
}
static void fr_sub(Fr& o, const Fr& a, const Fr& b) {
  u64 borrow = 0;
  for (int i = 0; i < 4; i++) o.l[i] = sbb(a.l[i], b.l[i], borrow);
  if (borrow) {
    u64 carry = 0;
    for (int i = 0; i < 4; i++) o.l[i] = adc(o.l[i], R_RAW[i], carry);
  }
}
// CIOS Montgomery product, 4x64 (the scalar-field twin of fp_mul)
static void fr_mul(Fr& o, const Fr& a, const Fr& b) {
  u64 t[5] = {0, 0, 0, 0, 0};
  for (int i = 0; i < 4; i++) {
    u64 carry = 0, lo, hi;
    for (int j = 0; j < 4; j++) {
      madd2(a.l[j], b.l[i], t[j], carry, hi, lo);
      t[j] = lo;
      carry = hi;
    }
    u64 t4 = t[4] + carry;
    u64 m = t[0] * FR_NINV;
    madd1(m, R_RAW[0], t[0], hi, lo);
    carry = hi;
    for (int j = 1; j < 4; j++) {
      madd2(m, R_RAW[j], t[j], carry, hi, lo);
      t[j - 1] = lo;
      carry = hi;
    }
    u64 c2 = 0;
    t[3] = adc(t4, carry, c2);
    t[4] = c2;
  }
  for (int i = 0; i < 4; i++) o.l[i] = t[i];
  if (t[4] || fr_cmp_raw(o.l, R_RAW) >= 0) {
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) o.l[i] = sbb(o.l[i], R_RAW[i], borrow);
  }
}
static void fr_to_mont(Fr& o, const Fr& std_form) { fr_mul(o, std_form, FR_R2); }
static void fr_from_mont(Fr& o, const Fr& mont) {
  Fr one_std = {{1, 0, 0, 0}};
  fr_mul(o, mont, one_std);
}
static void fr_pow(Fr& out, const Fr& base, const u64* exp) {
  Fr result = FR_ONE;
  bool started = false;
  for (int bit = 255; bit >= 0; bit--) {
    if (started) fr_mul(result, result, result);
    if ((exp[bit >> 6] >> (bit & 63)) & 1) {
      if (started) fr_mul(result, result, base);
      else { result = base; started = true; }
    }
  }
  out = started ? result : FR_ONE;
}
static void fr_inv(Fr& out, const Fr& a) {
  u64 exp[4];
  u64 borrow = 0;
  exp[0] = sbb(R_RAW[0], 2, borrow);
  for (int i = 1; i < 4; i++) exp[i] = sbb(R_RAW[i], 0, borrow);
  fr_pow(out, a, exp);  // a^(r-2)
}
static void fr_batch_inv(Fr* vals, size_t n) {
  if (n == 0) return;
  Fr* pre = new Fr[n + 1];
  pre[0] = FR_ONE;
  for (size_t i = 0; i < n; i++) fr_mul(pre[i + 1], pre[i], vals[i]);
  Fr inv;
  fr_inv(inv, pre[n]);
  for (size_t i = n; i-- > 0;) {
    Fr v;
    fr_mul(v, inv, pre[i]);
    fr_mul(inv, inv, vals[i]);
    vals[i] = v;
  }
  delete[] pre;
}
static bool fr_from_bytes(Fr& o, const u8 in[32]) {
  Fr s;
  for (int i = 0; i < 4; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | in[i * 8 + j];
    s.l[3 - i] = w;
  }
  if (fr_cmp_raw(s.l, R_RAW) >= 0) return false;
  fr_to_mont(o, s);
  return true;
}
static void fr_to_bytes(u8 out[32], const Fr& mont) {
  Fr s;
  fr_from_mont(s, mont);
  for (int i = 0; i < 4; i++) {
    u64 w = s.l[3 - i];
    for (int j = 7; j >= 0; j--) { out[i * 8 + j] = (u8)w; w >>= 8; }
  }
}
static void fr_ensure_init() {
  if (FR_READY) return;
  u64 inv = 1;
  for (int i = 0; i < 6; i++) inv *= 2 - R_RAW[0] * inv;
  FR_NINV = (u64)0 - inv;
  Fr acc = {{1, 0, 0, 0}};
  for (int i = 0; i < 512; i++) fr_add(acc, acc, acc);
  FR_R2 = acc;
  Fr one_std = {{1, 0, 0, 0}};
  fr_mul(FR_ONE, one_std, FR_R2);
  FR_READY = true;
}

// Barycentric evaluation + (optionally) the quotient polynomial, shared
// scaffolding: p(z) = (z^n - 1)/n * sum_i e_i w_i / (z - w_i), with the
// in-domain short-circuit, and q(X) = (p(X) - y)/(X - z) in evaluation
// form (both branches of _compute_kzg_proof_impl).
static int fr_eval_quotient(const u8* evals32, const u8* roots32, size_t n,
                            const u8* z32, u8* y32, u8* q32 /* or null */) {
  fr_ensure_init();
  if (n == 0 || (n & (n - 1)) != 0) return -2;  // z^n below squares up
  Fr z;
  if (!fr_from_bytes(z, z32)) return -1;
  Fr* evals = new Fr[n];
  Fr* roots = new Fr[n];
  for (size_t i = 0; i < n; i++) {
    if (!fr_from_bytes(evals[i], evals32 + 32 * i) ||
        !fr_from_bytes(roots[i], roots32 + 32 * i)) {
      delete[] evals;
      delete[] roots;
      return -1;
    }
  }
  long m = -1;  // in-domain index
  for (size_t i = 0; i < n; i++)
    if (fr_eq(z, roots[i])) { m = (long)i; break; }
  Fr y;
  Fr* work = new Fr[n];
  if (m >= 0) {
    y = evals[m];
  } else {
    for (size_t i = 0; i < n; i++) fr_sub(work[i], z, roots[i]);
    fr_batch_inv(work, n);  // 1/(z - w_i)
    Fr total = {{0, 0, 0, 0}};
    for (size_t i = 0; i < n; i++) {
      Fr t;
      fr_mul(t, evals[i], roots[i]);
      fr_mul(t, t, work[i]);
      fr_add(total, total, t);
    }
    // zn1 = z^n - 1, n_inv = 1/n
    Fr zn = z;
    size_t nn = n;
    // n is a power of two for every preset; square up
    while (nn > 1) { fr_mul(zn, zn, zn); nn >>= 1; }
    Fr zn1;
    fr_sub(zn1, zn, FR_ONE);
    Fr n_fr = {{0, 0, 0, 0}}, n_std = {{(u64)n, 0, 0, 0}};
    fr_to_mont(n_fr, n_std);
    Fr n_inv;
    fr_inv(n_inv, n_fr);
    fr_mul(y, total, zn1);
    fr_mul(y, y, n_inv);
  }
  fr_to_bytes(y32, y);
  int rc = 0;
  if (q32) {
    if (m >= 0) {
      // z on the domain: the L'Hopital-style special column
      Fr* inv_wz = new Fr[n];
      Fr* inv_zzw = new Fr[n];
      for (size_t i = 0; i < n; i++) {
        if ((long)i == m) { inv_wz[i] = FR_ONE; inv_zzw[i] = FR_ONE; continue; }
        fr_sub(inv_wz[i], roots[i], z);
        Fr t;
        fr_sub(t, z, roots[i]);
        fr_mul(inv_zzw[i], z, t);
      }
      fr_batch_inv(inv_wz, n);
      fr_batch_inv(inv_zzw, n);
      Fr acc = {{0, 0, 0, 0}};
      for (size_t i = 0; i < n; i++) {
        if ((long)i == m) continue;
        Fr d, q;
        fr_sub(d, evals[i], y);
        fr_mul(q, d, inv_wz[i]);
        fr_to_bytes(q32 + 32 * i, q);
        Fr t;
        fr_mul(t, d, roots[i]);
        fr_mul(t, t, inv_zzw[i]);
        fr_add(acc, acc, t);
      }
      fr_to_bytes(q32 + 32 * (size_t)m, acc);
      delete[] inv_wz;
      delete[] inv_zzw;
    } else {
      // work[i] already holds 1/(z - w_i); 1/(w_i - z) = -that
      for (size_t i = 0; i < n; i++) {
        Fr d, neg, q;
        fr_sub(d, evals[i], y);
        Fr zero = {{0, 0, 0, 0}};
        fr_sub(neg, zero, work[i]);
        fr_mul(q, d, neg);
        fr_to_bytes(q32 + 32 * i, q);
      }
    }
  }
  delete[] evals;
  delete[] roots;
  delete[] work;
  return rc;
}

// ---------------------------------------------------------------------------
// raw affine IO (standard-form big-endian coordinates)
// g1 raw: x || y (96 bytes); g2 raw: x.c0 || x.c1 || y.c0 || y.c1 (192)
// ---------------------------------------------------------------------------

static void g1_to_raw(u8 out[96], const G1& p) {
  if (p.is_inf()) { memset(out, 0, 96); return; }
  Fp ax, ay;
  pt_to_affine<FpOps>(ax, ay, p);
  fp_to_bytes(out, ax);
  fp_to_bytes(out + 48, ay);
}

static bool g1_from_raw(G1& out, const u8 in[96], int is_inf) {
  if (is_inf) { out = pt_infinity<FpOps>(); return true; }
  Fp x, y;
  if (!fp_from_bytes(x, in) || !fp_from_bytes(y, in + 48)) return false;
  if (!pt_on_curve_affine<FpOps>(x, y, G1_B)) return false;
  out = pt_from_affine<FpOps>(x, y);
  return true;
}

#ifdef EC_FP8_COMPILED
// Parse eight raw affine G1 points straight into R52-Montgomery lanes
// (skipping the scalar-Montgomery detour g1_from_raw would pay), with
// the on-curve check run eight-wide. Out-of-field or off-curve lanes
// (incl. the all-zero "infinity" encoding, which is not on the curve)
// fail exactly like g1_from_raw.
EC_FP8_TARGET static bool g1x8_load_from_raw(G1x8& o, const u8* pks_raw) {
  u64 tx[8][8], ty[8][8];
  for (int k = 0; k < 8; k++) {
    const u8* in = pks_raw + 96 * k;
    u64 xs[6], ys[6];
    for (int i = 0; i < 6; i++) {
      u64 w = 0, w2 = 0;
      for (int j = 0; j < 8; j++) {
        w = (w << 8) | in[i * 8 + j];
        w2 = (w2 << 8) | in[48 + i * 8 + j];
      }
      xs[5 - i] = w;
      ys[5 - i] = w2;
    }
    if (fp_cmp_raw(xs, P_RAW.l) >= 0 || fp_cmp_raw(ys, P_RAW.l) >= 0)
      return false;
    limbs6_to_52(tx[k], xs);
    limbs6_to_52(ty[k], ys);
  }
  for (int j = 0; j < 8; j++) {
    o.x.l[j] = _mm512_setr_epi64(
        (long long)tx[0][j], (long long)tx[1][j], (long long)tx[2][j],
        (long long)tx[3][j], (long long)tx[4][j], (long long)tx[5][j],
        (long long)tx[6][j], (long long)tx[7][j]);
    o.y.l[j] = _mm512_setr_epi64(
        (long long)ty[0][j], (long long)ty[1][j], (long long)ty[2][j],
        (long long)ty[3][j], (long long)ty[4][j], (long long)ty[5][j],
        (long long)ty[6][j], (long long)ty[7][j]);
  }
  Fp8 r2;
  fp8_bcast(r2, R52SQ_52);
  fp8_montmul(o.x, o.x, r2);
  fp8_montmul(o.y, o.y, r2);
  static const u64 ONEP[8] = {1, 0, 0, 0, 0, 0, 0, 0};
  Fp8 onep;
  fp8_bcast(onep, ONEP);
  fp8_montmul(o.z, r2, onep);  // z = 1 in R52-Montgomery form
  Fp8 y2, x2, x3, b4;
  fp8_sqr(y2, o.y);
  fp8_sqr(x2, o.x);
  fp8_montmul(x3, x2, o.x);
  fp8_load(b4, &G1_B, 1);
  fp8_add(x3, x3, b4);
  return fp8_eq_mask(y2, x3) == 0xFF;
}

// Eight running partial pubkey sums + scalar combine — the
// fast_aggregate_verify aggregation loop (role of blst's pk aggregation
// in crypto/bls.rs:114,135) at SoA throughput. The rare add exception
// (a lane's partial sum equal to its incoming point) is patched with a
// scalar doubling-capable add, so the result always matches the serial
// pt_add chain; bad/infinity keys fail identically.
EC_FP8_TARGET static int g1_sum_raw_x8_impl(G1& out, const u8* pks_raw,
                                            size_t n) {
  G1x8 acc;
  if (!g1x8_load_from_raw(acc, pks_raw)) return 0;
  size_t i = 8;
  for (; i + 8 <= n; i += 8) {
    G1x8 inc;
    if (!g1x8_load_from_raw(inc, pks_raw + 96 * i)) return 0;
    const G1x8 saved = acc;
    __mmask8 exc = 0;
    g1x8_add(acc, acc, inc, exc);
    if (exc) {
      G1 sv[8], nw[8], pk;
      g1x8_store(sv, saved, 8);
      g1x8_store(nw, acc, 8);
      for (int g = 0; g < 8; g++)
        if ((exc >> g) & 1) {
          if (!g1_from_raw(pk, pks_raw + 96 * (i + g), 0) || pk.is_inf())
            return 0;
          pt_add(nw[g], sv[g], pk);
        }
      g1x8_load(acc, nw, 8);
    }
  }
  G1 fin[8];
  g1x8_store(fin, acc, 8);
  G1 total = pt_infinity<FpOps>();
  for (int g = 0; g < 8; g++) pt_add(total, total, fin[g]);
  for (; i < n; i++) {
    G1 pk;
    if (!g1_from_raw(pk, pks_raw + 96 * i, 0) || pk.is_inf()) return 0;
    pt_add(total, total, pk);
  }
  out = total;
  return 1;
}
#endif  // EC_FP8_COMPILED

// Sum n raw affine G1 points; false on any malformed/infinity key
// (mirrors the serial g1_from_raw + pt_add loop bit for bit)
static bool g1_sum_raw(G1& out, const u8* pks_raw, size_t n) {
#ifdef EC_FP8_COMPILED
  if (FP8_READY && n >= 32) return g1_sum_raw_x8_impl(out, pks_raw, n) != 0;
#endif
  G1 acc = pt_infinity<FpOps>();
  for (size_t i = 0; i < n; i++) {
    G1 pk;
    if (!g1_from_raw(pk, pks_raw + 96 * i, 0) || pk.is_inf()) return false;
    pt_add(acc, acc, pk);
  }
  out = acc;
  return true;
}

static void g2_to_raw(u8 out[192], const G2& p) {
  if (p.is_inf()) { memset(out, 0, 192); return; }
  Fp2 ax, ay;
  pt_to_affine<Fp2Ops>(ax, ay, p);
  fp_to_bytes(out, ax.c0);
  fp_to_bytes(out + 48, ax.c1);
  fp_to_bytes(out + 96, ay.c0);
  fp_to_bytes(out + 144, ay.c1);
}

static bool g2_from_raw(G2& out, const u8 in[192], int is_inf) {
  if (is_inf) { out = pt_infinity<Fp2Ops>(); return true; }
  Fp2 x, y;
  if (!fp_from_bytes(x.c0, in) || !fp_from_bytes(x.c1, in + 48) ||
      !fp_from_bytes(y.c0, in + 96) || !fp_from_bytes(y.c1, in + 144))
    return false;
  if (!pt_on_curve_affine<Fp2Ops>(x, y, G2_B)) return false;
  out = pt_from_affine<Fp2Ops>(x, y);
  return true;
}

// ---------------------------------------------------------------------------
// public C API
// error codes: 0 ok / verify-false, 1 verify-true; negative = parse errors
// (-2 not compressed, -3 bad infinity, -4 not in field, -5 not on curve,
//  -6 not in subgroup, -1 other)
// ---------------------------------------------------------------------------

extern "C" {

u64 ec_bls_version() { return 4; }

// 1 when the eight-wide IFMA field engine passed its init self-check and
// is serving the batched sqrt chains; 0 = scalar fallback in use
int ec_fp8_active() {
  ensure_init();
  return FP8_READY ? 1 : 0;
}

// Deep self-test of the IFMA engine against the scalar field: random
// mul/add/sub/sqrt cross-checks. 0 = ok (or engine inactive);
// a nonzero code identifies the first failing family.
int ec_fp8_selftest(u64 seed, int rounds) {
  ensure_init();
  if (!FP8_READY) return 0;
#ifdef EC_FP8_COMPILED
  int rc = fp8_selftest_deep(seed, rounds);
  if (rc) return rc;
  // end-to-end: batched hash-to-G2 == scalar hash-to-G2, message by
  // message (exercises SSWU batching, batched isogeny inversions, and
  // the eight-lane cofactor chain incl. partial final chunks)
  {
    const u8 dst[] = "EC_FP8_SELFTEST_DST_";
    u8 msgs[19 * 8];
    u32 lens[19];
    u64 s = seed ? seed : 0xa076bdf3u;
    for (int i = 0; i < 19 * 8; i++) {
      s ^= s << 13; s ^= s >> 7; s ^= s << 17;
      msgs[i] = (u8)s;
    }
    for (int i = 0; i < 19; i++) lens[i] = 8;
    G2 got[19], want;
    if (!hash_to_g2_batch(got, msgs, lens, 19, dst, sizeof(dst) - 1))
      return 7;
    for (int i = 0; i < 19; i++) {
      if (!hash_to_g2_point(want, msgs + 8 * i, 8, dst, sizeof(dst) - 1))
        return 7;
      if (!pt_eq_jacobian(got[i], want)) return 8;
    }
    // batched decompression (+ subgroup) == scalar decompression,
    // including corrupted encodings and the infinity encoding
    u8 enc[19 * 96];
    for (int i = 0; i < 19; i++) g2_compress(enc + 96 * i, got[i]);
    enc[96 * 3 + 17] ^= 0x40;               // corrupt one coordinate
    memset(enc + 96 * 5, 0, 96);            // infinity encoding
    enc[96 * 5] = 0xC0;
    enc[96 * 7] = (u8)(enc[96 * 7] ^ 0x20); // flip the sign flag (still valid)
    G2 dec[19];
    int rcs[19];
    g2_decompress_batch(dec, rcs, enc, 19, true);
    for (int i = 0; i < 19; i++) {
      G2 one;
      int want_rc = g2_decompress(one, enc + 96 * i, true);
      if (rcs[i] != want_rc) return 9;
      if (want_rc == DEC_OK && !pt_eq_jacobian(dec[i], one)) return 10;
    }
    // batched 128-bit G1 scalar mults == scalar pt_mul (odd count, so
    // the padded-lane path is exercised too)
    G1 pts[11], got1[11], want1;
    u64 rs[11][2];
    for (int i = 0; i < 11; i++) {
      u64 k[2] = {0, 0};
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; k[0] = s | 1;
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; k[1] = s;
      pt_mul(pts[i], G1_GEN, k, 2);
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; rs[i][0] = s | 1;
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; rs[i][1] = s;
    }
    g1_mul128_batch(got1, pts, rs, 11);
    for (int i = 0; i < 11; i++) {
      u64 sc[2] = {rs[i][0], rs[i][1]};
      pt_mul(want1, pts[i], sc, 2);
      if (!pt_eq_jacobian(got1[i], want1)) return 11;
    }
    // batched G1 decompression (+ subgroup) == scalar, incl. corruption,
    // the infinity encoding, and an off-subgroup point
    {
      u8 enc1[11 * 48];
      for (int i = 0; i < 11; i++) g1_compress(enc1 + 48 * i, pts[i]);
      enc1[48 * 2 + 9] ^= 0x10;
      memset(enc1 + 48 * 4, 0, 48);
      enc1[48 * 4] = 0xC0;  // infinity
      G1 dec[11];
      int rcs1[11];
      g1_decompress_batch(dec, rcs1, enc1, 11, true);
      for (int i = 0; i < 11; i++) {
        G1 one;
        int want_rc = g1_decompress(one, enc1 + 48 * i, true);
        if (rcs1[i] != want_rc) return 15;
        if (want_rc == DEC_OK && !pt_eq_jacobian(dec[i], one)) return 16;
      }
    }
    // eight-wide Miller loop == scalar Miller loop, bit for bit, on a
    // ragged pair count (19 pairs -> 3 slots, last slot 3 lanes active)
    MillerPair mp[19], mp2[19];
    for (int i = 0; i < 19; i++) {
      u64 k[2];
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; k[0] = s | 1;
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; k[1] = s >> 1;
      G1 gp;
      pt_mul(gp, G1_GEN, k, 2);
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; k[0] = s | 1;
      s ^= s << 13; s ^= s >> 7; s ^= s << 17; k[1] = s >> 1;
      G2 gq;
      pt_mul(gq, G2_GEN, k, 2);
      pt_to_affine<FpOps>(mp[i].xp, mp[i].yp, gp);
      pt_to_affine<Fp2Ops>(mp[i].xq, mp[i].yq, gq);
      mp2[i] = mp[i];
    }
    Fp12 fx8, fsc;
    if (!multi_miller_loop_x8_try(fx8, mp, 19)) return 0;  // engine off: done
    multi_miller_loop(fsc, mp2, 19);
    if (!fp12_eq(fx8, fsc)) return 12;
    // eight-lane pubkey aggregation == serial chain, on a duplicate-heavy
    // ragged list (41 points from 5 distinct values forces repeated adds)
    u8 raws[41 * 96];
    for (int i = 0; i < 41; i++) {
      u64 k[2];
      k[0] = (u64)(i % 5) + 2;
      k[1] = 0;
      G1 gp;
      pt_mul(gp, G1_GEN, k, 2);
      g1_to_raw(raws + 96 * i, gp);
    }
    G1 batch_sum, serial_sum = pt_infinity<FpOps>();
    if (!g1_sum_raw(batch_sum, raws, 41)) return 13;
    for (int i = 0; i < 41; i++) {
      G1 pk;
      if (!g1_from_raw(pk, raws + 96 * i, 0)) return 13;
      pt_add(serial_sum, serial_sum, pk);
    }
    if (!pt_eq_jacobian(batch_sum, serial_sum)) return 14;
  }
  return 0;
#else
  return 0;
#endif
}

int ec_g1_decompress(const u8* in, u8* out_raw, int* is_inf, int check_subgroup) {
  ensure_init();
  G1 p;
  int rc = g1_decompress(p, in, check_subgroup != 0);
  if (rc != DEC_OK) return -rc;
  *is_inf = p.is_inf() ? 1 : 0;
  g1_to_raw(out_raw, p);
  return 0;
}

int ec_g2_decompress(const u8* in, u8* out_raw, int* is_inf, int check_subgroup) {
  ensure_init();
  G2 p;
  int rc = g2_decompress(p, in, check_subgroup != 0);
  if (rc != DEC_OK) return -rc;
  *is_inf = p.is_inf() ? 1 : 0;
  g2_to_raw(out_raw, p);
  return 0;
}

int ec_g1_compress_raw(const u8* raw, int is_inf, u8* out) {
  ensure_init();
  G1 p;
  if (!g1_from_raw(p, raw, is_inf)) return -5;
  g1_compress(out, p);
  return 0;
}

int ec_g2_compress_raw(const u8* raw, int is_inf, u8* out) {
  ensure_init();
  G2 p;
  if (!g2_from_raw(p, raw, is_inf)) return -5;
  g2_compress(out, p);
  return 0;
}

int ec_g1_generator_raw(u8* out) { ensure_init(); g1_to_raw(out, G1_GEN); return 0; }
int ec_g2_generator_raw(u8* out) { ensure_init(); g2_to_raw(out, G2_GEN); return 0; }

// scalar must be 32-byte BE, 0 < scalar < r enforced by caller
int ec_bls_sk_to_pk(const u8* sk, u8* out) {
  ensure_init();
  u64 s[4];
  scalar_from_be32(s, sk);
  G1 p;
  pt_mul(p, G1_GEN, s, 4);
  g1_compress(out, p);
  return 0;
}

int ec_bls_hash_to_g2(const u8* msg, size_t msg_len, const u8* dst,
                      size_t dst_len, u8* out96) {
  ensure_init();
  G2 h;
  if (!hash_to_g2_point(h, msg, msg_len, dst, dst_len)) return -1;
  g2_compress(out96, h);
  return 0;
}

int ec_bls_sign(const u8* sk, const u8* msg, size_t msg_len, const u8* dst,
                size_t dst_len, u8* out96) {
  ensure_init();
  u64 s[4];
  scalar_from_be32(s, sk);
  G2 h, sig;
  if (!hash_to_g2_point(h, msg, msg_len, dst, dst_len)) return -1;
  pt_mul(sig, h, s, 4);
  g2_compress(out96, sig);
  return 0;
}

int ec_bls_verify(const u8* pk48, const u8* msg, size_t msg_len, const u8* dst,
                  size_t dst_len, const u8* sig96, int assume_valid) {
  ensure_init();
  G1 pk;
  int rc = g1_decompress(pk, pk48, assume_valid == 0);
  if (rc != DEC_OK) return -rc;
  G2 sig;
  rc = g2_decompress(sig, sig96, assume_valid == 0);
  if (rc != DEC_OK) return -rc;
  if (pk.is_inf() || sig.is_inf()) return 0;
  G2 h;
  if (!hash_to_g2_point(h, msg, msg_len, dst, dst_len)) return -1;
  G1 neg_gen;
  pt_neg(neg_gen, G1_GEN);
  G1 ps[2] = {pk, neg_gen};
  G2 qs[2] = {h, sig};
  return pairing_product_is_one(ps, qs, 2) ? 1 : 0;
}

int ec_bls_fast_aggregate_verify(const u8* pks, size_t n, const u8* msg,
                                 size_t msg_len, const u8* dst, size_t dst_len,
                                 const u8* sig96, int assume_valid) {
  ensure_init();
  if (n == 0) return 0;
  G1 acc = pt_infinity<FpOps>();
  for (size_t i = 0; i < n; i++) {
    G1 pk;
    int rc = g1_decompress(pk, pks + 48 * i, assume_valid == 0);
    if (rc != DEC_OK) return -rc;
    if (pk.is_inf()) return 0;  // PublicKey semantics: identity is invalid
    pt_add(acc, acc, pk);
  }
  G2 sig;
  int rc = g2_decompress(sig, sig96, assume_valid == 0);
  if (rc != DEC_OK) return -rc;
  if (acc.is_inf() || sig.is_inf()) return 0;
  G2 h;
  if (!hash_to_g2_point(h, msg, msg_len, dst, dst_len)) return -1;
  G1 neg_gen;
  pt_neg(neg_gen, G1_GEN);
  G1 ps[2] = {acc, neg_gen};
  G2 qs[2] = {h, sig};
  return pairing_product_is_one(ps, qs, 2) ? 1 : 0;
}

// fast_aggregate_verify from PRE-DECOMPRESSED raw affine pubkeys (the
// PublicKey cache) — skips the per-key sqrt that dominates large
// aggregates; on-curve is re-checked, subgroup was checked at parse.
int ec_bls_fast_aggregate_verify_raw(const u8* pks_raw, size_t n,
                                     const u8* msg, size_t msg_len,
                                     const u8* dst, size_t dst_len,
                                     const u8* sig96, int assume_valid) {
  ensure_init();
  if (n == 0) return 0;
  G1 acc;
  if (!g1_sum_raw(acc, pks_raw, n)) return -5;
  G2 sig;
  int rc = g2_decompress(sig, sig96, assume_valid == 0);
  if (rc != DEC_OK) return -rc;
  if (acc.is_inf() || sig.is_inf()) return 0;
  G2 h;
  if (!hash_to_g2_point(h, msg, msg_len, dst, dst_len)) return -1;
  G1 neg_gen;
  pt_neg(neg_gen, G1_GEN);
  G1 ps[2] = {acc, neg_gen};
  G2 qs[2] = {h, sig};
  return pairing_product_is_one(ps, qs, 2) ? 1 : 0;
}

int ec_bls_aggregate_verify(const u8* pks, size_t n, const u8* msgs,
                            const u32* msg_lens, const u8* dst, size_t dst_len,
                            const u8* sig96, int assume_valid) {
  ensure_init();
  if (n == 0) return 0;
  G2 sig;
  int rc = g2_decompress(sig, sig96, assume_valid == 0);
  if (rc != DEC_OK) return -rc;
  if (sig.is_inf()) return 0;
  G1* ps = new G1[n + 1];
  G2* qs = new G2[n + 1];
  for (size_t i = 0; i < n; i++) {
    G1 pk;
    rc = g1_decompress(pk, pks + 48 * i, assume_valid == 0);
    if (rc != DEC_OK) { delete[] ps; delete[] qs; return -rc; }
    if (pk.is_inf()) { delete[] ps; delete[] qs; return 0; }
    ps[i] = pk;
  }
  // distinct-message hashes batch eight-wide on the IFMA engine
  if (!hash_to_g2_batch(qs, msgs, msg_lens, n, dst, dst_len)) {
    delete[] ps;
    delete[] qs;
    return -1;
  }
  pt_neg(ps[n], G1_GEN);
  qs[n] = sig;
  bool ok = pairing_product_is_one(ps, qs, n + 1);
  delete[] ps;
  delete[] qs;
  return ok ? 1 : 0;
}

int ec_bls_aggregate_sigs(const u8* sigs, size_t n, u8* out96) {
  ensure_init();
  if (n == 0) return -1;
#ifdef EC_FP8_COMPILED
  if (FP8_READY && n >= 32) {
    // batched decompression (eight-wide sqrt chains + subgroup checks),
    // then eight running partial sums; duplicate-signature collisions
    // (the doubling corner) patch scalar — identical to the serial chain
    G2* pts = new G2[n];
    int* rcs = new int[n];
    g2_decompress_batch(pts, rcs, sigs, n, true);
    for (size_t i = 0; i < n; i++)
      if (rcs[i] != DEC_OK) {
        int rc = rcs[i];
        delete[] pts;
        delete[] rcs;
        return -rc;
      }
    G2 acc2;
    g2_sum_pts_x8(acc2, pts, n);
    delete[] pts;
    delete[] rcs;
    g2_compress(out96, acc2);
    return 0;
  }
#endif
  G2 acc = pt_infinity<Fp2Ops>();
  for (size_t i = 0; i < n; i++) {
    G2 s;
    int rc = g2_decompress(s, sigs + 96 * i, true);
    if (rc != DEC_OK) return -rc;
    pt_add(acc, acc, s);
  }
  g2_compress(out96, acc);
  return 0;
}

int ec_bls_aggregate_pubkeys(const u8* pks, size_t n, u8* out48) {
  ensure_init();
  if (n == 0) return -1;
#ifdef EC_FP8_COMPILED
  if (FP8_READY && n >= 32) {
    // eight-wide decompression (sqrt + subgroup chains) and lane sums
    G1* pts = new G1[n];
    int* rcs = new int[n];
    g1_decompress_batch(pts, rcs, pks, n, true);
    for (size_t i = 0; i < n; i++) {
      int rc = rcs[i] != DEC_OK ? -rcs[i]
               : pts[i].is_inf() ? -3  // each key must be a real point
                                 : 0;
      if (rc) {
        delete[] pts;
        delete[] rcs;
        return rc;
      }
    }
    G1 acc2;
    g1_sum_pts_x8(acc2, pts, n);
    delete[] pts;
    delete[] rcs;
    g1_compress(out48, acc2);
    return 0;
  }
#endif
  G1 acc = pt_infinity<FpOps>();
  for (size_t i = 0; i < n; i++) {
    G1 p;
    int rc = g1_decompress(p, pks + 48 * i, true);
    if (rc != DEC_OK) return -rc;
    if (p.is_inf()) return -3;  // eth_aggregate_public_keys validates each key
    pt_add(acc, acc, p);
  }
  g1_compress(out48, acc);
  return 0;
}

// Canonicality scan: every 32-byte big-endian scalar must be < r.
// 0 ok, -1 the first non-canonical element's complaint.
int ec_fr_validate(const u8* evals32, size_t n) {
  for (size_t i = 0; i < n; i++) {
    const u8* in = evals32 + 32 * i;
    u64 s[4];
    for (int k = 0; k < 4; k++) {
      u64 w = 0;
      for (int j = 0; j < 8; j++) w = (w << 8) | in[k * 8 + j];
      s[3 - k] = w;
    }
    if (fr_cmp_raw(s, R_RAW) >= 0) return -1;
  }
  return 0;
}

// Barycentric evaluation of a blob polynomial (evaluation form over the
// brp domain) at z; y32 gets the canonical 32-byte result. rc: 0 ok,
// -1 non-canonical input, -2 unsupported domain size.
int ec_fr_eval_poly(const u8* evals32, const u8* roots32, size_t n,
                    const u8* z32, u8* y32) {
  return fr_eval_quotient(evals32, roots32, n, z32, y32, nullptr);
}

// Same, plus the quotient polynomial q(X) = (p(X) - y)/(X - z) in
// evaluation form (both the on-domain and off-domain branches).
int ec_fr_eval_and_quotient(const u8* evals32, const u8* roots32, size_t n,
                            const u8* z32, u8* y32, u8* q32) {
  return fr_eval_quotient(evals32, roots32, n, z32, y32, q32);
}

// Prepared fixed-base G1 MSM over static points (the KZG Lagrange
// setup): precompute window shifts once, then every MSM is a single
// signed-digit bucket pass. The handle owns native-side Montgomery
// arrays; the caller frees it with ec_g1_msm_prepared_free.
void* ec_g1_msm_prepare(const u8* points_raw, size_t n, int window_bits) {
  ensure_init();
  if (n == 0 || window_bits < 2 || window_bits > 15) return nullptr;
  G1* pts = new G1[n];
  for (size_t i = 0; i < n; i++) {
    if (!g1_from_raw(pts[i], points_raw + 96 * i, 0)) {
      delete[] pts;
      return nullptr;
    }
  }
  MsmPrepared<FpOps>* h = msm_prepare<FpOps>(pts, n, window_bits);
  delete[] pts;
  return h;
}

int ec_g1_msm_prepared_run(void* handle, const u8* scalars32, size_t n,
                           u8* out_raw, int* out_inf) {
  ensure_init();
  MsmPrepared<FpOps>* h = (MsmPrepared<FpOps>*)handle;
  if (!h || h->n != n) return -1;
  u64* sc = new u64[4 * n];
  for (size_t i = 0; i < n; i++) scalar_from_be32(sc + 4 * i, scalars32 + 32 * i);
  G1 r;
  msm_prepared_run<FpOps>(r, h, sc);
  delete[] sc;
  *out_inf = r.is_inf() ? 1 : 0;
  g1_to_raw(out_raw, r);
  return 0;
}

void ec_g1_msm_prepared_free(void* handle) {
  delete (MsmPrepared<FpOps>*)handle;
}

// Bulk G1 decompression: n compressed keys -> n (rc, raw96, is_inf)
// triples with the sqrt and subgroup chains batched eight-wide. The
// Python pubkey cache uses this to warm a whole committee in one call.
int ec_g1_decompress_batch(const u8* in48s, size_t n, u8* out_raws,
                           int* rcs_out, int* infs, int check_subgroup) {
  ensure_init();
  G1* pts = new G1[n];
  int* rcs = new int[n];
  g1_decompress_batch(pts, rcs, in48s, n, check_subgroup != 0);
  for (size_t i = 0; i < n; i++) {
    rcs_out[i] = rcs[i] == DEC_OK ? 0 : -rcs[i];
    if (rcs[i] == DEC_OK) {
      infs[i] = pts[i].is_inf() ? 1 : 0;
      g1_to_raw(out_raws + 96 * i, pts[i]);
    } else {
      infs[i] = 0;
      memset(out_raws + 96 * i, 0, 96);
    }
  }
  delete[] pts;
  delete[] rcs;
  return 0;
}

// Random-linear-combination batch verification: every set must satisfy
// fast_aggregate_verify. scalars16: per-set 16-byte BE nonzero blinders
// (caller supplies; set 0 may be 1). Returns 1 all-valid, 0 otherwise.
int ec_bls_batch_verify(size_t n_sets, const u32* pk_counts, const u8* pks,
                        const u8* msgs, const u32* msg_lens, const u8* sigs,
                        const u8* dst, size_t dst_len, const u8* scalars16) {
  ensure_init();
  if (n_sets == 0) return 1;
  G1* ps = new G1[n_sets + 1];
  G2* qs = new G2[n_sets + 1];
  G2 sig_acc = pt_infinity<Fp2Ops>();
  size_t pk_off = 0, msg_off = 0;
  bool ok = true;
  for (size_t i = 0; i < n_sets && ok; i++) {
    u32 cnt = pk_counts[i];
    if (cnt == 0) { ok = false; break; }
    G1 agg = pt_infinity<FpOps>();
    for (u32 j = 0; j < cnt; j++) {
      G1 pk;
      if (g1_decompress(pk, pks + 48 * (pk_off + j), true) != DEC_OK ||
          pk.is_inf()) {
        ok = false;
        break;
      }
      pt_add(agg, agg, pk);
    }
    pk_off += cnt;
    if (!ok) break;
    G2 sig;
    if (g2_decompress(sig, sigs + 96 * i, true) != DEC_OK || sig.is_inf() ||
        agg.is_inf()) {
      ok = false;
      break;
    }
    u64 r[4] = {0, 0, 0, 0};
    for (int b = 0; b < 8; b++) r[1] = (r[1] << 8) | scalars16[16 * i + b];
    for (int b = 8; b < 16; b++) r[0] = (r[0] << 8) | scalars16[16 * i + b];
    if ((r[0] | r[1]) == 0) { ok = false; break; }
    G1 rp;
    pt_mul(rp, agg, r, 2);
    G2 rs;
    pt_mul(rs, sig, r, 2);
    pt_add(sig_acc, sig_acc, rs);
    ps[i] = rp;
    if (!hash_to_g2_point(qs[i], msgs + msg_off, msg_lens[i], dst, dst_len)) {
      ok = false;
      break;
    }
    msg_off += msg_lens[i];
  }
  if (ok) {
    pt_neg(ps[n_sets], G1_GEN);
    qs[n_sets] = sig_acc;
    ok = pairing_product_is_one(ps, qs, n_sets + 1);
  }
  delete[] ps;
  delete[] qs;
  return ok ? 1 : 0;
}

// Batch verify with PRE-DECOMPRESSED pubkeys (96-byte raw affine, already
// validated at parse time by the caller — on-curve is re-checked, the
// subgroup check was paid once when the key was first seen). Compared to
// ec_bls_batch_verify this removes the per-set per-key sqrt, and the
// blinded signature aggregation sum(r_i * sig_i) runs as one Pippenger
// MSM instead of n separate scalar mults.
int ec_bls_batch_verify_raw(size_t n_sets, const u32* pk_counts,
                            const u8* pks_raw, const u8* msgs,
                            const u32* msg_lens, const u8* sigs,
                            const u8* dst, size_t dst_len,
                            const u8* scalars16) {
  ensure_init();
  if (n_sets == 0) return 1;
  G1* ps = new G1[n_sets + 1];
  G2* qs = new G2[n_sets + 1];
  G2* sig_pts = new G2[n_sets];
  int* rcs = new int[n_sets];
  u64* sig_scalars = new u64[4 * n_sets];
  size_t pk_off = 0;
  bool ok = true;
  // phase 1: per-set pubkey aggregation (scalar adds), then all blinder
  // products r_i * aggpk_i as eight-lane batched scalar mults
  G1* aggs = new G1[n_sets];
  u64 (*blinders)[2] = new u64[n_sets][2];
  for (size_t i = 0; i < n_sets && ok; i++) {
    u32 cnt = pk_counts[i];
    if (cnt == 0) { ok = false; break; }
    G1 agg;
    if (!g1_sum_raw(agg, pks_raw + 96 * pk_off, cnt)) { ok = false; break; }
    pk_off += cnt;
    if (agg.is_inf()) { ok = false; break; }
    u64 r[4] = {0, 0, 0, 0};
    for (int b = 0; b < 8; b++) r[1] = (r[1] << 8) | scalars16[16 * i + b];
    for (int b = 8; b < 16; b++) r[0] = (r[0] << 8) | scalars16[16 * i + b];
    if ((r[0] | r[1]) == 0) { ok = false; break; }
    aggs[i] = agg;
    blinders[i][0] = r[0];
    blinders[i][1] = r[1];
    sig_scalars[4 * i] = r[0]; sig_scalars[4 * i + 1] = r[1];
    sig_scalars[4 * i + 2] = 0; sig_scalars[4 * i + 3] = 0;
  }
  if (ok) g1_mul128_batch(ps, aggs, blinders, n_sets);
  delete[] aggs;
  delete[] blinders;
  // phase 2: signature decompression, sqrt chains batched eight-wide
  if (ok) {
    g2_decompress_batch(sig_pts, rcs, sigs, n_sets, true);
    for (size_t i = 0; i < n_sets; i++)
      if (rcs[i] != DEC_OK || sig_pts[i].is_inf()) { ok = false; break; }
  }
  // phase 3: hash-to-G2, SSWU sqrt chains batched eight-wide
  if (ok) ok = hash_to_g2_batch(qs, msgs, msg_lens, n_sets, dst, dst_len);
  // phase 4: blinded-signature MSM + shared multi-pairing. Decompressed
  // signatures are affine (z = 1, infinity already rejected), so the
  // signed-digit batch-affine Pippenger applies directly.
  if (ok) {
    G2 sig_acc;
    Fp2* sxs = new Fp2[n_sets];
    Fp2* sys = new Fp2[n_sets];
    for (size_t i = 0; i < n_sets; i++) {
      sxs[i] = sig_pts[i].x;
      sys[i] = sig_pts[i].y;
    }
    pt_msm_batch_affine<Fp2Ops>(sig_acc, sxs, sys, sig_scalars, n_sets, 128);
    delete[] sxs;
    delete[] sys;
    pt_neg(ps[n_sets], G1_GEN);
    qs[n_sets] = sig_acc;
    ok = pairing_product_is_one(ps, qs, n_sets + 1);
  }
  delete[] ps;
  delete[] qs;
  delete[] sig_pts;
  delete[] rcs;
  delete[] sig_scalars;
  return ok ? 1 : 0;
}

int ec_g1_msm(const u8* points_raw, const u8* scalars32, size_t n, u8* out_raw,
              int* out_inf) {
  ensure_init();
  Fp* xs = new Fp[n];
  Fp* ys = new Fp[n];
  u64* sc = new u64[4 * n];
  for (size_t i = 0; i < n; i++) {
    G1 p;
    if (!g1_from_raw(p, points_raw + 96 * i, 0)) {
      delete[] xs; delete[] ys; delete[] sc;
      return -5;
    }
    xs[i] = p.x; ys[i] = p.y;   // pt_from_affine: z = 1
    scalar_from_be32(sc + 4 * i, scalars32 + 32 * i);
  }
  G1 r;
  pt_msm_batch_affine<FpOps>(r, xs, ys, sc, n, 256);
  *out_inf = r.is_inf() ? 1 : 0;
  g1_to_raw(out_raw, r);
  delete[] xs; delete[] ys;
  delete[] sc;
  return 0;
}

int ec_g2_msm(const u8* points_raw, const u8* scalars32, size_t n, u8* out_raw,
              int* out_inf) {
  ensure_init();
  Fp2* xs = new Fp2[n];
  Fp2* ys = new Fp2[n];
  u64* sc = new u64[4 * n];
  for (size_t i = 0; i < n; i++) {
    G2 p;
    if (!g2_from_raw(p, points_raw + 192 * i, 0)) {
      delete[] xs; delete[] ys; delete[] sc;
      return -5;
    }
    xs[i] = p.x; ys[i] = p.y;
    scalar_from_be32(sc + 4 * i, scalars32 + 32 * i);
  }
  G2 r;
  pt_msm_batch_affine<Fp2Ops>(r, xs, ys, sc, n, 256);
  *out_inf = r.is_inf() ? 1 : 0;
  g2_to_raw(out_raw, r);
  delete[] xs; delete[] ys;
  delete[] sc;
  return 0;
}

int ec_g1_mul_raw(const u8* point_raw, int is_inf, const u8* scalar32,
                  u8* out_raw, int* out_inf) {
  ensure_init();
  G1 p;
  if (!g1_from_raw(p, point_raw, is_inf)) return -5;
  u64 s[4];
  scalar_from_be32(s, scalar32);
  G1 r;
  pt_mul(r, p, s, 4);
  *out_inf = r.is_inf() ? 1 : 0;
  g1_to_raw(out_raw, r);
  return 0;
}

int ec_g1_add_raw(const u8* a_raw, int a_inf, const u8* b_raw, int b_inf,
                  u8* out_raw, int* out_inf) {
  ensure_init();
  G1 a, b;
  if (!g1_from_raw(a, a_raw, a_inf) || !g1_from_raw(b, b_raw, b_inf)) return -5;
  G1 r;
  pt_add(r, a, b);
  *out_inf = r.is_inf() ? 1 : 0;
  g1_to_raw(out_raw, r);
  return 0;
}

int ec_g1_subgroup_check_raw(const u8* raw) {
  ensure_init();
  G1 p;
  if (!g1_from_raw(p, raw, 0)) return -5;
  return pt_in_subgroup(p) ? 1 : 0;
}

int ec_g2_subgroup_check_raw(const u8* raw) {
  ensure_init();
  G2 p;
  if (!g2_from_raw(p, raw, 0)) return -5;
  return pt_in_subgroup(p) ? 1 : 0;
}

int ec_pairing_product_is_one_raw(const u8* g1_raw, const u8* g1_inf,
                                  const u8* g2_raw, const u8* g2_inf,
                                  size_t n) {
  ensure_init();
  G1* ps = new G1[n];
  G2* qs = new G2[n];
  for (size_t i = 0; i < n; i++) {
    if (!g1_from_raw(ps[i], g1_raw + 96 * i, g1_inf[i]) ||
        !g2_from_raw(qs[i], g2_raw + 192 * i, g2_inf[i])) {
      delete[] ps; delete[] qs;
      return -5;
    }
  }
  bool ok = pairing_product_is_one(ps, qs, n);
  delete[] ps;
  delete[] qs;
  return ok ? 1 : 0;
}

// --- Fq12 handoff for the device batched pairing (ops/pairing.py) ---------
// Raw layout: 12 coefficients, 48-byte big-endian standard form each, in
// (c0.a0.c0, c0.a0.c1, c0.a1.c0, c0.a1.c1, c0.a2.c0, c0.a2.c1,
//  c1.a0.c0, ..., c1.a2.c1) order — matching ops/fq12.fp12_to_ints.

static void fp12_to_raw576(u8* out, const Fp12& f) {
  const Fp2* comps[6] = {&f.c0.a0, &f.c0.a1, &f.c0.a2,
                         &f.c1.a0, &f.c1.a1, &f.c1.a2};
  for (int i = 0; i < 6; i++) {
    fp_to_bytes(out + 96 * i, comps[i]->c0);
    fp_to_bytes(out + 96 * i + 48, comps[i]->c1);
  }
}

static bool fp12_from_raw576(Fp12& f, const u8* in) {
  Fp2* comps[6] = {&f.c0.a0, &f.c0.a1, &f.c0.a2,
                   &f.c1.a0, &f.c1.a1, &f.c1.a2};
  for (int i = 0; i < 6; i++) {
    if (!fp_from_bytes(comps[i]->c0, in + 96 * i) ||
        !fp_from_bytes(comps[i]->c1, in + 96 * i + 48))
      return false;
  }
  return true;
}

// single-pair Miller loop, raw in/out — the device kernel's parity anchor
int ec_miller_loop_raw(const u8* g1_raw, const u8* g2_raw, u8* out576) {
  ensure_init();
  G1 p;
  G2 q;
  if (!g1_from_raw(p, g1_raw, 0) || !g2_from_raw(q, g2_raw, 0)) return -5;
  if (p.is_inf() || q.is_inf()) { fp12_to_raw576(out576, FP12_ONE); return 0; }
  MillerPair mp;
  pt_to_affine<FpOps>(mp.xp, mp.yp, p);
  pt_to_affine<Fp2Ops>(mp.xq, mp.yq, q);
  Fp12 f;
  multi_miller_loop(f, &mp, 1);
  fp12_to_raw576(out576, f);
  return 0;
}

// final-exponentiation verdict on a raw Fq12 (the device hands its
// tree-reduced Miller product here; only the predicate crosses back)
int ec_fp12_final_exp_is_one(const u8* f576) {
  ensure_init();
  Fp12 f;
  if (!fp12_from_raw576(f, f576)) return -4;
  Fp12 fe;
  final_exp_for_verdict(fe, f);
  return fp12_is_one(fe) ? 1 : 0;
}

}  // extern "C"
