"""Native BLS12-381 backend loader: builds bls12_381.cpp on first use and
exposes it via ctypes (same pattern as the SHA-256 merkle backend in
native/__init__.py — the role blst plays for the reference,
ethereum-consensus/src/crypto/bls.rs).

Every function here works on the wire formats (48-byte compressed G1,
96-byte compressed G2, 32-byte scalars); crypto/bls.py routes its
object-level API through these when the backend is available.

All argtypes are declared explicitly — size_t args beyond the register
slots otherwise pick up garbage upper halves on x86-64.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

__all__ = [
    "load",
    "available",
    "decode_error_message",
    "g1_decompress",
    "g2_decompress",
    "g1_compress_raw",
    "g2_compress_raw",
    "g1_generator_raw",
    "g2_generator_raw",
    "sk_to_pk",
    "sign",
    "hash_to_g2_compressed",
    "verify",
    "fast_aggregate_verify",
    "fast_aggregate_verify_raw",
    "aggregate_verify",
    "aggregate_signatures",
    "aggregate_public_keys",
    "batch_verify",
    "g1_msm",
    "g2_msm",
    "g1_mul_raw",
    "g1_add_raw",
    "pairing_product_is_one_raw",
]

_SOURCE = os.path.join(os.path.dirname(__file__), "bls12_381.cpp")
_HEADER = os.path.join(os.path.dirname(__file__), "bls12_381_constants.h")
_LIB = None
_TRIED = False

_c = ctypes
_u32p = _c.POINTER(_c.c_uint32)


class NativeBlsError(RuntimeError):
    """Unexpected native-backend failure (not a validation verdict)."""


# decompress/validation error codes (negated DecodeErr from the C side)
_DECODE_ERRORS = {
    -1: "internal error",
    -2: "uncompressed encodings are not supported",
    -3: "malformed infinity encoding",
    -4: "coordinate not in field",
    -5: "x coordinate not on curve",
    -6: "point not in the order-r subgroup",
}


def decode_error_message(rc: int) -> str:
    return _DECODE_ERRORS.get(rc, f"native error {rc}")


def _build_dir() -> str:
    path = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(path, exist_ok=True)
    return path


def _source_tag() -> str:
    digest = hashlib.sha256()
    for path in (_SOURCE, _HEADER):
        with open(path, "rb") as f:
            digest.update(f.read())
    return digest.hexdigest()[:16]


def _declare(lib) -> None:
    c = _c
    sz = c.c_size_t
    p8 = c.c_char_p
    i32 = c.c_int
    sigs = {
        "ec_bls_version": ([], c.c_uint64),
        "ec_g1_decompress": ([p8, p8, c.POINTER(i32), i32], i32),
        "ec_g2_decompress": ([p8, p8, c.POINTER(i32), i32], i32),
        "ec_g1_compress_raw": ([p8, i32, p8], i32),
        "ec_g2_compress_raw": ([p8, i32, p8], i32),
        "ec_g1_generator_raw": ([p8], i32),
        "ec_g2_generator_raw": ([p8], i32),
        "ec_bls_sk_to_pk": ([p8, p8], i32),
        "ec_bls_hash_to_g2": ([p8, sz, p8, sz, p8], i32),
        "ec_bls_sign": ([p8, p8, sz, p8, sz, p8], i32),
        "ec_bls_verify": ([p8, p8, sz, p8, sz, p8, i32], i32),
        "ec_bls_fast_aggregate_verify": ([p8, sz, p8, sz, p8, sz, p8, i32], i32),
        "ec_bls_fast_aggregate_verify_raw": ([p8, sz, p8, sz, p8, sz, p8, i32], i32),
        "ec_bls_aggregate_verify": ([p8, sz, p8, _u32p, p8, sz, p8, i32], i32),
        "ec_bls_aggregate_sigs": ([p8, sz, p8], i32),
        "ec_bls_aggregate_pubkeys": ([p8, sz, p8], i32),
        "ec_bls_batch_verify": ([sz, _u32p, p8, p8, _u32p, p8, p8, sz, p8], i32),
        "ec_bls_batch_verify_raw": ([sz, _u32p, p8, p8, _u32p, p8, p8, sz, p8], i32),
        "ec_miller_loop_raw": ([p8, p8, p8], i32),
        "ec_fp12_final_exp_is_one": ([p8], i32),
        "ec_g1_msm": ([p8, p8, sz, p8, c.POINTER(i32)], i32),
        "ec_g2_msm": ([p8, p8, sz, p8, c.POINTER(i32)], i32),
        "ec_g1_mul_raw": ([p8, i32, p8, p8, c.POINTER(i32)], i32),
        "ec_g1_add_raw": ([p8, i32, p8, i32, p8, c.POINTER(i32)], i32),
        "ec_g1_subgroup_check_raw": ([p8], i32),
        "ec_g2_subgroup_check_raw": ([p8], i32),
        "ec_pairing_product_is_one_raw": ([p8, p8, p8, p8, sz], i32),
        "ec_g1_decompress_batch": ([p8, sz, p8, c.POINTER(i32), c.POINTER(i32), i32], i32),
        "ec_fr_validate": ([p8, sz], i32),
        "ec_fr_eval_poly": ([p8, p8, sz, p8, p8], i32),
        "ec_fr_eval_and_quotient": ([p8, p8, sz, p8, p8, p8], i32),
        "ec_g1_msm_prepare": ([p8, sz, i32], c.c_void_p),
        "ec_g1_msm_prepared_run": ([c.c_void_p, p8, sz, p8, c.POINTER(i32)], i32),
        "ec_g1_msm_prepared_free": ([c.c_void_p], None),
        "ec_fp8_active": ([], i32),
        "ec_fp8_selftest": ([c.c_uint64, i32], i32),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype


def load():
    """Compile (once per source hash) + load the shared library, or None."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    lib_path = os.path.join(_build_dir(), f"bls12_381-{_source_tag()}.so")
    if not os.path.exists(lib_path):
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_build_dir())
            os.close(fd)
            subprocess.run(
                # -std=c++17 makes operator new honor over-aligned types
                # (the AVX-512 x8 structs); older toolchains default to
                # gnu++14 where a heap MillerPairX8 is only 16-byte
                # aligned and the first vmovdqa64 GP-faults
                ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
                 "-fPIC", _SOURCE, "-o", tmp],
                check=True,
                capture_output=True,
                timeout=300,
            )
            os.replace(tmp, lib_path)  # atomic under concurrent builders
            tmp = None
        except (OSError, subprocess.SubprocessError):
            return None
        finally:
            if tmp and os.path.exists(tmp):
                os.unlink(tmp)
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None
    _declare(lib)
    _LIB = lib
    return lib


def available() -> bool:
    return load() is not None


def _lib():
    lib = load()
    if lib is None:
        raise NativeBlsError("native BLS backend unavailable (no g++ toolchain)")
    return lib


# -- point codecs -----------------------------------------------------------


def g1_decompress(data: bytes, check_subgroup: bool = True) -> tuple[int, bytes, bool]:
    """(rc, raw96, is_infinity); rc == 0 on success, negative error code."""
    out = _c.create_string_buffer(96)
    inf = _c.c_int(0)
    rc = _lib().ec_g1_decompress(bytes(data), out, _c.byref(inf), int(check_subgroup))
    return rc, out.raw, bool(inf.value)


def g2_decompress(data: bytes, check_subgroup: bool = True) -> tuple[int, bytes, bool]:
    out = _c.create_string_buffer(192)
    inf = _c.c_int(0)
    rc = _lib().ec_g2_decompress(bytes(data), out, _c.byref(inf), int(check_subgroup))
    return rc, out.raw, bool(inf.value)


def g1_compress_raw(raw: bytes, is_inf: bool = False) -> bytes:
    out = _c.create_string_buffer(48)
    rc = _lib().ec_g1_compress_raw(bytes(raw), int(is_inf), out)
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw


def g2_compress_raw(raw: bytes, is_inf: bool = False) -> bytes:
    out = _c.create_string_buffer(96)
    rc = _lib().ec_g2_compress_raw(bytes(raw), int(is_inf), out)
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw


def g1_generator_raw() -> bytes:
    out = _c.create_string_buffer(96)
    _lib().ec_g1_generator_raw(out)
    return out.raw


def g2_generator_raw() -> bytes:
    out = _c.create_string_buffer(192)
    _lib().ec_g2_generator_raw(out)
    return out.raw


# -- signature scheme -------------------------------------------------------


def sk_to_pk(sk32: bytes) -> bytes:
    out = _c.create_string_buffer(48)
    rc = _lib().ec_bls_sk_to_pk(bytes(sk32), out)
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw


def sign(sk32: bytes, message: bytes, dst: bytes) -> bytes:
    out = _c.create_string_buffer(96)
    rc = _lib().ec_bls_sign(bytes(sk32), bytes(message), len(message), bytes(dst), len(dst), out)
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw


def hash_to_g2_compressed(message: bytes, dst: bytes) -> bytes:
    out = _c.create_string_buffer(96)
    rc = _lib().ec_bls_hash_to_g2(bytes(message), len(message), bytes(dst), len(dst), out)
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw


def verify(pk48: bytes, message: bytes, sig96: bytes, dst: bytes,
           assume_valid: bool = False) -> int:
    """1 valid, 0 invalid, negative = parse/validation error code."""
    return _lib().ec_bls_verify(
        bytes(pk48), bytes(message), len(message), bytes(dst), len(dst),
        bytes(sig96), int(assume_valid),
    )


def fast_aggregate_verify(pks: list[bytes], message: bytes, sig96: bytes,
                          dst: bytes, assume_valid: bool = False) -> int:
    cat = b"".join(bytes(pk) for pk in pks)
    return _lib().ec_bls_fast_aggregate_verify(
        cat, len(pks), bytes(message), len(message), bytes(dst), len(dst),
        bytes(sig96), int(assume_valid),
    )


def fast_aggregate_verify_raw(pk_raws: list[bytes], message: bytes,
                              sig96: bytes, dst: bytes,
                              assume_valid: bool = False) -> int:
    """fast_aggregate_verify from cached raw affine pubkeys (96 bytes
    each, subgroup-checked at parse) — no per-key decompression sqrt."""
    return _lib().ec_bls_fast_aggregate_verify_raw(
        b"".join(bytes(p) for p in pk_raws), len(pk_raws),
        bytes(message), len(message), bytes(dst), len(dst),
        bytes(sig96), int(assume_valid),
    )


def aggregate_verify(pks: list[bytes], messages: list[bytes], sig96: bytes,
                     dst: bytes, assume_valid: bool = False) -> int:
    cat = b"".join(bytes(pk) for pk in pks)
    msgs = b"".join(bytes(m) for m in messages)
    lens = (_c.c_uint32 * len(messages))(*[len(m) for m in messages])
    return _lib().ec_bls_aggregate_verify(
        cat, len(pks), msgs, lens, bytes(dst), len(dst), bytes(sig96),
        int(assume_valid),
    )


def aggregate_signatures(sigs: list[bytes]) -> tuple[int, bytes]:
    out = _c.create_string_buffer(96)
    rc = _lib().ec_bls_aggregate_sigs(b"".join(bytes(s) for s in sigs), len(sigs), out)
    return rc, out.raw


def aggregate_public_keys(pks: list[bytes]) -> tuple[int, bytes]:
    out = _c.create_string_buffer(48)
    rc = _lib().ec_bls_aggregate_pubkeys(b"".join(bytes(p) for p in pks), len(pks), out)
    return rc, out.raw


def batch_verify(sets: list[tuple[list[bytes], bytes, bytes]], dst: bytes,
                 scalars16: list[bytes]) -> bool:
    """Each set is (pubkeys, message, signature); scalars16 are per-set
    16-byte big-endian nonzero blinders (caller-supplied randomness).
    True only if every set satisfies fast_aggregate_verify."""
    n = len(sets)
    if n == 0:
        return True
    counts = (_c.c_uint32 * n)(*[len(s[0]) for s in sets])
    pks = b"".join(bytes(pk) for s in sets for pk in s[0])
    msgs = b"".join(bytes(s[1]) for s in sets)
    mlens = (_c.c_uint32 * n)(*[len(s[1]) for s in sets])
    sigs = b"".join(bytes(s[2]) for s in sets)
    rand = b"".join(scalars16)
    if len(rand) != 16 * n:
        raise NativeBlsError("need one 16-byte scalar per set")
    rc = _lib().ec_bls_batch_verify(
        n, counts, pks, msgs, mlens, sigs, bytes(dst), len(dst), rand,
    )
    return rc == 1


def batch_verify_raw(sets: list[tuple[list[bytes], bytes, bytes]], dst: bytes,
                     scalars16: list[bytes]) -> bool:
    """Like ``batch_verify`` but each set's pubkeys are 96-byte RAW AFFINE
    points (x||y big-endian) whose subgroup membership the caller already
    established (PublicKey caches them after its parse-time check) —
    skips the per-set decompression sqrt, and the blinded signature sum
    runs as one Pippenger MSM native-side."""
    n = len(sets)
    if n == 0:
        return True
    counts = (_c.c_uint32 * n)(*[len(s[0]) for s in sets])
    pks = b"".join(bytes(pk) for s in sets for pk in s[0])
    msgs = b"".join(bytes(s[1]) for s in sets)
    mlens = (_c.c_uint32 * n)(*[len(s[1]) for s in sets])
    sigs = b"".join(bytes(s[2]) for s in sets)
    rand = b"".join(scalars16)
    if len(rand) != 16 * n:
        raise NativeBlsError("need one 16-byte scalar per set")
    rc = _lib().ec_bls_batch_verify_raw(
        n, counts, pks, msgs, mlens, sigs, bytes(dst), len(dst), rand,
    )
    return rc == 1


# -- raw-point utilities (KZG / device interop) -----------------------------


def g1_msm(points_raw: bytes, scalars32: bytes, n: int) -> tuple[bytes, bool]:
    out = _c.create_string_buffer(96)
    inf = _c.c_int(0)
    rc = _lib().ec_g1_msm(bytes(points_raw), bytes(scalars32), n, out, _c.byref(inf))
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw, bool(inf.value)


def g2_msm(points_raw: bytes, scalars32: bytes, n: int) -> tuple[bytes, bool]:
    out = _c.create_string_buffer(192)
    inf = _c.c_int(0)
    rc = _lib().ec_g2_msm(bytes(points_raw), bytes(scalars32), n, out, _c.byref(inf))
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw, bool(inf.value)


def g1_mul_raw(point_raw: bytes, is_inf: bool, scalar32: bytes) -> tuple[bytes, bool]:
    out = _c.create_string_buffer(96)
    inf = _c.c_int(0)
    rc = _lib().ec_g1_mul_raw(bytes(point_raw), int(is_inf), bytes(scalar32), out, _c.byref(inf))
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw, bool(inf.value)


def g1_add_raw(a_raw: bytes, a_inf: bool, b_raw: bytes, b_inf: bool) -> tuple[bytes, bool]:
    out = _c.create_string_buffer(96)
    inf = _c.c_int(0)
    rc = _lib().ec_g1_add_raw(bytes(a_raw), int(a_inf), bytes(b_raw), int(b_inf), out, _c.byref(inf))
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw, bool(inf.value)


def pairing_product_is_one_raw(g1_raws: list[tuple[bytes, bool]],
                               g2_raws: list[tuple[bytes, bool]]) -> bool:
    n = len(g1_raws)
    if len(g2_raws) != n:
        raise NativeBlsError("pairing product needs equal-length point lists")
    g1b = b"".join(bytes(r) for r, _ in g1_raws)
    g2b = b"".join(bytes(r) for r, _ in g2_raws)
    i1 = bytes(1 if inf else 0 for _, inf in g1_raws)
    i2 = bytes(1 if inf else 0 for _, inf in g2_raws)
    rc = _lib().ec_pairing_product_is_one_raw(g1b, i1, g2b, i2, n)
    if rc < 0:
        raise NativeBlsError(decode_error_message(rc))
    return rc == 1


def miller_loop_raw(g1_raw: bytes, g2_raw: bytes) -> bytes:
    """Single-pair Miller value, 576-byte raw Fq12 (device parity anchor)."""
    out = _c.create_string_buffer(576)
    rc = _lib().ec_miller_loop_raw(bytes(g1_raw), bytes(g2_raw), out)
    if rc != 0:
        raise NativeBlsError(decode_error_message(rc))
    return out.raw


def fp12_final_exp_is_one(f576: bytes) -> bool:
    """Final-exponentiation verdict on a raw Fq12 Miller product."""
    rc = _lib().ec_fp12_final_exp_is_one(bytes(f576))
    if rc < 0:
        raise NativeBlsError(decode_error_message(rc))
    return rc == 1


def fp8_active() -> bool:
    """True when the eight-wide AVX-512 IFMA field engine passed its init
    self-check and serves the batched sqrt chains (hash-to-G2 / G2
    decompression inside batch verification); False = scalar fallback."""
    return _lib().ec_fp8_active() == 1


def fp8_selftest(seed: int = 0, rounds: int = 50) -> int:
    """Randomized engine-vs-scalar cross-check (mul/add/sub/sqrt families).

    Returns 0 when every family agrees (or the engine is inactive); a
    nonzero code identifies the first failing family."""
    return _lib().ec_fp8_selftest(seed, rounds)


def g1_decompress_batch(
    keys: "list[bytes]", check_subgroup: bool = True
) -> "list[tuple[int, bytes, bool]]":
    """Bulk G1 decompression with the sqrt and subgroup chains batched
    eight keys wide; per-key (rc, raw96, is_infinity) triples identical
    to calling g1_decompress on each."""
    n = len(keys)
    if n == 0:
        return []
    out = _c.create_string_buffer(96 * n)
    rcs = (_c.c_int * n)()
    infs = (_c.c_int * n)()
    _lib().ec_g1_decompress_batch(
        b"".join(bytes(k) for k in keys), n, out, rcs, infs,
        int(check_subgroup),
    )
    raw = out.raw
    return [
        (rcs[i], raw[96 * i : 96 * i + 96], bool(infs[i])) for i in range(n)
    ]


class PreparedMsm:
    """Fixed-base G1 MSM handle: window shifts of static points (the KZG
    Lagrange setup) precomputed native-side so each later MSM is a single
    signed-digit bucket pass. Frees the native memory on GC."""

    __slots__ = ("_handle", "_n")

    def __init__(self, points_raw: bytes, n: int, window_bits: int = 12):
        handle = _lib().ec_g1_msm_prepare(bytes(points_raw), n, window_bits)
        if not handle:
            raise NativeBlsError("msm precompute failed (bad points?)")
        self._handle = handle
        self._n = n

    def run(self, scalars32: bytes) -> "tuple[bytes, bool]":
        """(raw96, is_infinity) of sum scalars[i] * P_i."""
        out = _c.create_string_buffer(96)
        inf = _c.c_int(0)
        rc = _lib().ec_g1_msm_prepared_run(
            self._handle, bytes(scalars32), self._n, out, _c.byref(inf)
        )
        if rc != 0:
            raise NativeBlsError(f"prepared msm failed rc={rc}")
        return out.raw, bool(inf.value)

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and _LIB is not None:
            _LIB.ec_g1_msm_prepared_free(handle)
            self._handle = None


def fr_eval_poly(evals32: bytes, roots32: bytes, n: int, z32: bytes) -> bytes:
    """Barycentric blob-polynomial evaluation at z over the brp domain
    (EIP-4844); raises on non-canonical input or unsupported domain."""
    y = _c.create_string_buffer(32)
    rc = _lib().ec_fr_eval_poly(bytes(evals32), bytes(roots32), n, bytes(z32), y)
    if rc != 0:
        raise NativeBlsError(f"fr_eval_poly rc={rc}")
    return y.raw


def fr_eval_and_quotient(
    evals32: bytes, roots32: bytes, n: int, z32: bytes
) -> "tuple[bytes, bytes]":
    """(y, quotient-evals) for the KZG proof at z — both branches of the
    quotient construction (on-domain L'Hopital column and off-domain)."""
    y = _c.create_string_buffer(32)
    q = _c.create_string_buffer(32 * n)
    rc = _lib().ec_fr_eval_and_quotient(
        bytes(evals32), bytes(roots32), n, bytes(z32), y, q
    )
    if rc != 0:
        raise NativeBlsError(f"fr_eval_and_quotient rc={rc}")
    return y.raw, q.raw


def fr_validate(evals32: bytes, n: int) -> bool:
    """True when every 32-byte big-endian scalar is canonical (< r)."""
    return _lib().ec_fr_validate(bytes(evals32), n) == 0
