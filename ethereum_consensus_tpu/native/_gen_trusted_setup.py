"""Emit crypto/data/trusted_setup_affine.bin from trusted_setup.json.

First access to the embedded KZG ceremony used to cost seconds: 4096 G1
decompressions with subgroup checks (the price the reference pays inside
c-kzg's `load_trusted_setup`, crypto/kzg.rs:39). This build-time step pays
that price ONCE — the JSON (the checked-in source of truth, byte-identical
to the reference's ceremony artifact) is fully validated through
`KzgSettings.from_json` (curve + subgroup checks per point), then the
already-decompressed raw affine coordinates are written in a flat binary
whose sha256 is pinned in crypto/kzg.py. Runtime load = read + hash check
+ object construction (<0.1s).

Run from the repo root after any change to the JSON or the format:

    python -m ethereum_consensus_tpu.native._gen_trusted_setup

Layout (all integers little-endian):
    6s   magic  b"ECTS\\x01\\x00"
    u32  n_g1   (number of G1 Lagrange points)
    u32  n_g2   (number of G2 monomial points)
    n_g1 * 96 bytes   G1 affine (x||y, 48-byte big-endian each), BIT-
                      REVERSAL-PERMUTED order (the blob-native order
                      KzgSettings stores)
    n_g2 * 192 bytes  G2 affine (x.c0||x.c1||y.c0||y.c1), natural order
"""

from __future__ import annotations

import hashlib
import os
import struct

DATA_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "crypto",
    "data",
)
OUT = os.path.join(DATA_DIR, "trusted_setup_affine.bin")


def render() -> bytes:
    """Validate the JSON ceremony setup and render the binary form."""
    from ..crypto.kzg import CEREMONY_AFFINE_MAGIC, KzgSettings

    settings = KzgSettings.from_file(os.path.join(DATA_DIR, "trusted_setup.json"))
    parts = [
        CEREMONY_AFFINE_MAGIC,
        struct.pack("<II", settings.n, len(settings.g2_monomial)),
    ]
    for pt in settings.g1_lagrange_brp:
        x, y = pt.to_affine()
        parts.append(x.n.to_bytes(48, "big") + y.n.to_bytes(48, "big"))
    for pt in settings.g2_monomial:
        x, y = pt.to_affine()
        parts.append(
            x.c0.n.to_bytes(48, "big")
            + x.c1.n.to_bytes(48, "big")
            + y.c0.n.to_bytes(48, "big")
            + y.c1.n.to_bytes(48, "big")
        )
    return b"".join(parts)


def main() -> None:
    blob = render()
    with open(OUT, "wb") as f:
        f.write(blob)
    print(f"wrote {OUT} ({len(blob)} bytes)")
    print(f"sha256: {hashlib.sha256(blob).hexdigest()}")
    print("pin this digest as CEREMONY_AFFINE_SHA256 in crypto/kzg.py")


if __name__ == "__main__":
    main()
