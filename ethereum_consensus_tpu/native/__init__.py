"""Native C++ backend: builds and loads the SHA-256 merkle kernels.

The reference leans on native code for its crypto substrate (blst C/asm,
c-kzg, sha2 — SURVEY.md L0); this package is the equivalent native layer
here: a from-scratch C++ SHA-256 merkle library compiled on first use with
the system toolchain and loaded via ctypes (no pybind11 in this image).
Falls back cleanly to the pure-Python path when no compiler is available.

``install()`` registers the native hasher with ssz.hash so every
hash_tree_root below the device threshold runs native.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

from .. import _env

__all__ = [
    "load",
    "available",
    "hash_level_native",
    "merkle_root_native",
    "install",
]

_SOURCE = os.path.join(os.path.dirname(__file__), "sha256_merkle.cpp")
_LIB = None
_TRIED = False


def _build_dir() -> str:
    path = os.path.join(os.path.dirname(__file__), "_build")
    os.makedirs(path, exist_ok=True)
    return path


def _source_tag() -> str:
    with open(_SOURCE, "rb") as f:
        digest = hashlib.sha256(f.read())
    digest.update(_env.raw("EC_NATIVE_SHA_NI").encode())
    return digest.hexdigest()[:16]


def load():
    """Compile (once per source hash) + load the shared library, or None."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    lib_path = os.path.join(_build_dir(), f"sha256_merkle-{_source_tag()}.so")
    if not os.path.exists(lib_path):
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_build_dir())
            os.close(fd)
            flags = ["-O3", "-march=native", "-shared", "-fPIC"]
            # SHA-NI is opt-in: virtualized hosts may trap the sha
            # instructions (measured ~20x slower than scalar under
            # emulation in this image)
            if _env.raw("EC_NATIVE_SHA_NI"):
                flags.append("-DEC_USE_SHA_NI")
            subprocess.run(
                ["g++", *flags, _SOURCE, "-o", tmp],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, lib_path)  # atomic under concurrent builders
            tmp = None
        except (OSError, subprocess.SubprocessError):
            return None
        finally:
            if tmp and os.path.exists(tmp):
                os.unlink(tmp)
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None
    lib.ec_hash_level.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.ec_hash_level.restype = None
    lib.ec_merkle_root.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.ec_merkle_root.restype = None
    lib.ec_version.restype = ctypes.c_uint64
    _LIB = lib
    return lib


def available() -> bool:
    return load() is not None


def _require_lib():
    lib = load()
    if lib is None:
        raise RuntimeError(
            "native backend unavailable: no working C++ toolchain (g++) found"
        )
    return lib


def hash_level_native(nodes: bytes) -> bytes:
    """Native twin of ssz.hash.hash_level_host."""
    lib = _require_lib()
    n_pairs = len(nodes) // 64
    out = ctypes.create_string_buffer(n_pairs * 32)
    lib.ec_hash_level(nodes, out, n_pairs)
    return out.raw


def merkle_root_native(chunks: bytes, depth: int, zero_hashes: bytes) -> bytes:
    """Whole-tree reduction in one native call (``zero_hashes`` = depth+1
    concatenated 32-byte zero-subtree roots)."""
    lib = load()
    out = ctypes.create_string_buffer(32)
    lib.ec_merkle_root(chunks, len(chunks) // 32, depth, zero_hashes, out)
    return out.raw


def install() -> bool:
    """Register the native hasher with the SSZ hash dispatch; returns
    whether the native path is active."""
    if not available():
        return False
    from ..ssz import hash as hash_module

    hash_module.register_native_hasher(hash_level_native)
    return True
