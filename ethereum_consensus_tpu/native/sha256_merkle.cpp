// Native SHA-256 merkle kernels (the CPU-side hot path of SSZ
// hash_tree_root).
//
// Fills the role the reference fills with native crypto (sha2 crate /
// blst's C, SURVEY.md L0): a from-scratch C++ SHA-256 specialized for the
// 64-byte two-children message of binary merkleization, with whole-level
// and whole-tree entry points so the Python merkleizer can hand off entire
// reductions in one call.
//
// Build: g++ -O3 -march=native -shared -fPIC sha256_merkle.cpp -o ...
// ABI (ctypes):
//   void ec_hash_level(const uint8_t* in, uint8_t* out, size_t n_pairs);
//   void ec_merkle_root(const uint8_t* chunks, size_t count, uint32_t depth,
//                       const uint8_t* zero_hashes, uint8_t* out32);
//   uint64_t ec_version(void);

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(EC_USE_SHA_NI) && defined(__SHA__) && defined(__x86_64__)
#define EC_SHA_NI_ACTIVE 1
#include <immintrin.h>
#endif

// 8-way AVX2 multi-buffer path: the merkle level is 8+ independent
// 64-byte messages — the ideal multi-buffer case. Pure integer AVX2
// (no SHA-NI, which this image's hypervisor traps ~20x slower than
// scalar); measured ~6x over the scalar loop on the build machine.
#if !defined(EC_SHA_NI_ACTIVE) && defined(__AVX2__) && defined(__x86_64__)
#define EC_AVX2_ACTIVE 1
#endif

// the AVX-512 kernel below is compiled with target attributes on any
// x86-64 build (runtime-dispatched), so the intrinsics header is needed
// even when the baseline ISA has no AVX2
#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

constexpr uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline uint32_t load_be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v >> 24);
  p[1] = uint8_t(v >> 16);
  p[2] = uint8_t(v >> 8);
  p[3] = uint8_t(v);
}

inline void compress(uint32_t state[8], const uint32_t w_in[16]) {
  uint32_t w[64];
  std::memcpy(w, w_in, 16 * sizeof(uint32_t));
  for (int t = 16; t < 64; ++t) {
    uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
    uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int t = 0; t < 64; ++t) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[t] + w[t];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g;
    g = f;
    f = e;
    e = d + t1;
    d = c;
    c = b;
    b = a;
    a = t1 + t2;
  }
  state[0] += a;
  state[1] += b;
  state[2] += c;
  state[3] += d;
  state[4] += e;
  state[5] += f;
  state[6] += g;
  state[7] += h;
}

// the constant second block of a 64-byte message (0x80 pad + bit length 512)
constexpr uint32_t PAD_BLOCK[16] = {0x80000000, 0, 0, 0, 0, 0, 0, 0,
                                    0,          0, 0, 0, 0, 0, 0, 512};

// SHA-256 of exactly 64 bytes (one merkle pair) — two compressions, the
// second over a constant schedule.
inline void sha256_64(const uint8_t* in, uint8_t* out) {
  uint32_t state[8];
  std::memcpy(state, H0, sizeof(H0));
  uint32_t w[16];
  for (int i = 0; i < 16; ++i) w[i] = load_be32(in + 4 * i);
  compress(state, w);
  compress(state, PAD_BLOCK);
  for (int i = 0; i < 8; ++i) store_be32(out + 4 * i, state[i]);
}

#ifdef EC_SHA_NI_ACTIVE
// SHA-NI two-compression digest of a 64-byte message. State is carried in
// the (ABEF, CDGH) register layout the sha256rnds2 instruction expects.
inline void sha256_64_ni(const uint8_t* in, uint8_t* out) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  // H0 in ABEF/CDGH layout
  __m128i abef = _mm_set_epi32(0x6a09e667, 0xbb67ae85, 0x510e527f, 0x9b05688c);
  __m128i cdgh = _mm_set_epi32(0x3c6ef372, 0xa54ff53a, 0x1f83d9ab, 0x5be0cd19);

  for (int block = 0; block < 2; ++block) {
    __m128i msg0, msg1, msg2, msg3;
    if (block == 0) {
      msg0 = _mm_shuffle_epi8(
          _mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 0)), MASK);
      msg1 = _mm_shuffle_epi8(
          _mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 16)), MASK);
      msg2 = _mm_shuffle_epi8(
          _mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 32)), MASK);
      msg3 = _mm_shuffle_epi8(
          _mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 48)), MASK);
    } else {
      // constant pad block: 0x80 then zeros, length 512 bits
      msg0 = _mm_set_epi32(0, 0, 0, int(0x80000000));
      msg1 = _mm_setzero_si128();
      msg2 = _mm_setzero_si128();
      msg3 = _mm_set_epi32(512, 0, 0, 0);
    }
    const __m128i save_abef = abef;
    const __m128i save_cdgh = cdgh;
    __m128i msg;

#define ROUNDS4(m, k_hi, k_lo)                                         \
  msg = _mm_add_epi32(m, _mm_set_epi64x(k_hi, k_lo));                  \
  cdgh = _mm_sha256rnds2_epu32(cdgh, abef, msg);                       \
  msg = _mm_shuffle_epi32(msg, 0x0E);                                  \
  abef = _mm_sha256rnds2_epu32(abef, cdgh, msg);

#define SCHED(m0, m1, m2, m3)                                          \
  m0 = _mm_sha256msg1_epu32(m0, m1);                                   \
  m0 = _mm_add_epi32(m0, _mm_alignr_epi8(m3, m2, 4));                  \
  m0 = _mm_sha256msg2_epu32(m0, m3);

    ROUNDS4(msg0, 0xe9b5dba5b5c0fbcfULL, 0x71374491428a2f98ULL)
    ROUNDS4(msg1, 0xab1c5ed5923f82a4ULL, 0x59f111f13956c25bULL)
    ROUNDS4(msg2, 0x550c7dc3243185beULL, 0x12835b01d807aa98ULL)
    ROUNDS4(msg3, 0xc19bf1749bdc06a7ULL, 0x80deb1fe72be5d74ULL)
    SCHED(msg0, msg1, msg2, msg3)
    ROUNDS4(msg0, 0x240ca1cc0fc19dc6ULL, 0xefbe4786e49b69c1ULL)
    SCHED(msg1, msg2, msg3, msg0)
    ROUNDS4(msg1, 0x76f988da5cb0a9dcULL, 0x4a7484aa2de92c6fULL)
    SCHED(msg2, msg3, msg0, msg1)
    ROUNDS4(msg2, 0xbf597fc7b00327c8ULL, 0xa831c66d983e5152ULL)
    SCHED(msg3, msg0, msg1, msg2)
    ROUNDS4(msg3, 0x1429296706ca6351ULL, 0xd5a79147c6e00bf3ULL)
    SCHED(msg0, msg1, msg2, msg3)
    ROUNDS4(msg0, 0x53380d134d2c6dfcULL, 0x2e1b213827b70a85ULL)
    SCHED(msg1, msg2, msg3, msg0)
    ROUNDS4(msg1, 0x92722c8581c2c92eULL, 0x766a0abb650a7354ULL)
    SCHED(msg2, msg3, msg0, msg1)
    ROUNDS4(msg2, 0xc76c51a3c24b8b70ULL, 0xa81a664ba2bfe8a1ULL)
    SCHED(msg3, msg0, msg1, msg2)
    ROUNDS4(msg3, 0x106aa070f40e3585ULL, 0xd6990624d192e819ULL)
    SCHED(msg0, msg1, msg2, msg3)
    ROUNDS4(msg0, 0x34b0bcb52748774cULL, 0x1e376c0819a4c116ULL)
    SCHED(msg1, msg2, msg3, msg0)
    ROUNDS4(msg1, 0x682e6ff35b9cca4fULL, 0x4ed8aa4a391c0cb3ULL)
    SCHED(msg2, msg3, msg0, msg1)
    ROUNDS4(msg2, 0x8cc7020884c87814ULL, 0x78a5636f748f82eeULL)
    SCHED(msg3, msg0, msg1, msg2)
    ROUNDS4(msg3, 0xc67178f2bef9a3f7ULL, 0xa4506ceb90befffaULL)

#undef ROUNDS4
#undef SCHED

    abef = _mm_add_epi32(abef, save_abef);
    cdgh = _mm_add_epi32(cdgh, save_cdgh);
  }

  // unpack ABEF/CDGH → big-endian digest
  uint32_t a = uint32_t(_mm_extract_epi32(abef, 3));
  uint32_t b = uint32_t(_mm_extract_epi32(abef, 2));
  uint32_t e = uint32_t(_mm_extract_epi32(abef, 1));
  uint32_t f = uint32_t(_mm_extract_epi32(abef, 0));
  uint32_t c = uint32_t(_mm_extract_epi32(cdgh, 3));
  uint32_t d = uint32_t(_mm_extract_epi32(cdgh, 2));
  uint32_t g = uint32_t(_mm_extract_epi32(cdgh, 1));
  uint32_t h = uint32_t(_mm_extract_epi32(cdgh, 0));
  store_be32(out + 0, a);
  store_be32(out + 4, b);
  store_be32(out + 8, c);
  store_be32(out + 12, d);
  store_be32(out + 16, e);
  store_be32(out + 20, f);
  store_be32(out + 24, g);
  store_be32(out + 28, h);
}
#endif  // EC_SHA_NI_ACTIVE

// message schedule of the constant pad block, computed once (shared by
// the AVX2 and AVX-512 multi-buffer kernels)
struct PadSchedule {
  uint32_t w[64];
  PadSchedule() {
    std::memcpy(w, PAD_BLOCK, 16 * sizeof(uint32_t));
    for (int t = 16; t < 64; ++t) {
      uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
      uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
      w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
  }
};
const PadSchedule PAD_SCHED;

#ifdef EC_AVX2_ACTIVE

inline __m256i rotr8(__m256i x, int n) {
  return _mm256_or_si256(_mm256_srli_epi32(x, n),
                         _mm256_slli_epi32(x, 32 - n));
}

#define EC_ROUND8(wt)                                                        \
  do {                                                                       \
    __m256i S1 = _mm256_xor_si256(_mm256_xor_si256(rotr8(e, 6), rotr8(e, 11)),\
                                  rotr8(e, 25));                             \
    __m256i ch = _mm256_xor_si256(_mm256_and_si256(e, f),                    \
                                  _mm256_andnot_si256(e, g));                \
    __m256i t1 = _mm256_add_epi32(                                           \
        _mm256_add_epi32(_mm256_add_epi32(h, S1), ch),                       \
        _mm256_add_epi32(_mm256_set1_epi32(int(K[t])), (wt)));               \
    __m256i S0 = _mm256_xor_si256(_mm256_xor_si256(rotr8(a, 2), rotr8(a, 13)),\
                                  rotr8(a, 22));                             \
    __m256i maj = _mm256_xor_si256(                                          \
        _mm256_xor_si256(_mm256_and_si256(a, b), _mm256_and_si256(a, c)),    \
        _mm256_and_si256(b, c));                                             \
    __m256i t2 = _mm256_add_epi32(S0, maj);                                  \
    h = g; g = f; f = e; e = _mm256_add_epi32(d, t1);                        \
    d = c; c = b; b = a; a = _mm256_add_epi32(t1, t2);                       \
  } while (0)

// eight independent 64-byte messages -> eight 32-byte digests, lanes
// transposed across one ymm register per word
inline void sha256_64_x8(const uint8_t* in, uint8_t* out) {
  __m256i a = _mm256_set1_epi32(int(H0[0]));
  __m256i b = _mm256_set1_epi32(int(H0[1]));
  __m256i c = _mm256_set1_epi32(int(H0[2]));
  __m256i d = _mm256_set1_epi32(int(H0[3]));
  __m256i e = _mm256_set1_epi32(int(H0[4]));
  __m256i f = _mm256_set1_epi32(int(H0[5]));
  __m256i g = _mm256_set1_epi32(int(H0[6]));
  __m256i h = _mm256_set1_epi32(int(H0[7]));

  // block 1: the data block, schedule extended in a 16-entry ring
  __m256i w[16];
  for (int t = 0; t < 16; ++t) {
    w[t] = _mm256_set_epi32(
        int(load_be32(in + 7 * 64 + 4 * t)), int(load_be32(in + 6 * 64 + 4 * t)),
        int(load_be32(in + 5 * 64 + 4 * t)), int(load_be32(in + 4 * 64 + 4 * t)),
        int(load_be32(in + 3 * 64 + 4 * t)), int(load_be32(in + 2 * 64 + 4 * t)),
        int(load_be32(in + 1 * 64 + 4 * t)), int(load_be32(in + 0 * 64 + 4 * t)));
  }
  for (int t = 0; t < 64; ++t) {
    if (t >= 16) {
      __m256i w15 = w[(t - 15) & 15], w2 = w[(t - 2) & 15];
      __m256i s0 = _mm256_xor_si256(
          _mm256_xor_si256(rotr8(w15, 7), rotr8(w15, 18)),
          _mm256_srli_epi32(w15, 3));
      __m256i s1 = _mm256_xor_si256(
          _mm256_xor_si256(rotr8(w2, 17), rotr8(w2, 19)),
          _mm256_srli_epi32(w2, 10));
      w[t & 15] = _mm256_add_epi32(
          _mm256_add_epi32(w[t & 15], s0),
          _mm256_add_epi32(w[(t - 7) & 15], s1));
    }
    EC_ROUND8(w[t & 15]);
  }
  __m256i sa = _mm256_add_epi32(a, _mm256_set1_epi32(int(H0[0])));
  __m256i sb = _mm256_add_epi32(b, _mm256_set1_epi32(int(H0[1])));
  __m256i sc = _mm256_add_epi32(c, _mm256_set1_epi32(int(H0[2])));
  __m256i sd = _mm256_add_epi32(d, _mm256_set1_epi32(int(H0[3])));
  __m256i se = _mm256_add_epi32(e, _mm256_set1_epi32(int(H0[4])));
  __m256i sf = _mm256_add_epi32(f, _mm256_set1_epi32(int(H0[5])));
  __m256i sg = _mm256_add_epi32(g, _mm256_set1_epi32(int(H0[6])));
  __m256i sh = _mm256_add_epi32(h, _mm256_set1_epi32(int(H0[7])));

  // block 2: constant schedule, no extension work
  a = sa; b = sb; c = sc; d = sd; e = se; f = sf; g = sg; h = sh;
  for (int t = 0; t < 64; ++t) {
    EC_ROUND8(_mm256_set1_epi32(int(PAD_SCHED.w[t])));
  }
  a = _mm256_add_epi32(a, sa);
  b = _mm256_add_epi32(b, sb);
  c = _mm256_add_epi32(c, sc);
  d = _mm256_add_epi32(d, sd);
  e = _mm256_add_epi32(e, se);
  f = _mm256_add_epi32(f, sf);
  g = _mm256_add_epi32(g, sg);
  h = _mm256_add_epi32(h, sh);

  alignas(32) uint32_t lanes[8][8];
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[0]), a);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[1]), b);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[2]), c);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[3]), d);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[4]), e);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[5]), f);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[6]), g);
  _mm256_store_si256(reinterpret_cast<__m256i*>(lanes[7]), h);
  for (int lane = 0; lane < 8; ++lane) {
    for (int i = 0; i < 8; ++i) {
      store_be32(out + 32 * lane + 4 * i, lanes[i][lane]);
    }
  }
}

#undef EC_ROUND8

#endif  // EC_AVX2_ACTIVE

#if defined(__x86_64__)
#define EC_AVX512_COMPILED 1
#define EC_SHA512_TARGET \
  __attribute__((target("avx512f,avx512bw,avx512dq,avx512vl")))

// 16-way AVX-512 multi-buffer path: same transposed-lane scheme as the
// AVX2 kernel, but with the ISA doing real work per instruction — native
// 32-bit rotates (vprord) replace the shift/shift/or triple, and
// vpternlogd fuses ch, maj, and each three-way xor into single ops.
// Runtime-dispatched (the .so is built per machine, but the check stays
// dynamic so a cached binary can never fault on a non-AVX-512 host).

#define EC_ROUND16(wt)                                                       \
  do {                                                                       \
    __m512i S1 = _mm512_ternarylogic_epi32(                                  \
        _mm512_ror_epi32(e, 6), _mm512_ror_epi32(e, 11),                     \
        _mm512_ror_epi32(e, 25), 0x96);                                      \
    __m512i ch = _mm512_ternarylogic_epi32(e, f, g, 0xCA);                   \
    __m512i t1 = _mm512_add_epi32(                                           \
        _mm512_add_epi32(_mm512_add_epi32(h, S1), ch),                       \
        _mm512_add_epi32(_mm512_set1_epi32(int(K[t])), (wt)));               \
    __m512i S0 = _mm512_ternarylogic_epi32(                                  \
        _mm512_ror_epi32(a, 2), _mm512_ror_epi32(a, 13),                     \
        _mm512_ror_epi32(a, 22), 0x96);                                      \
    __m512i maj = _mm512_ternarylogic_epi32(a, b, c, 0xE8);                  \
    __m512i t2 = _mm512_add_epi32(S0, maj);                                  \
    h = g; g = f; f = e; e = _mm512_add_epi32(d, t1);                        \
    d = c; c = b; b = a; a = _mm512_add_epi32(t1, t2);                       \
  } while (0)

// sixteen independent 64-byte messages -> sixteen 32-byte digests
EC_SHA512_TARGET inline void sha256_64_x16(const uint8_t* in, uint8_t* out) {
  __m512i a = _mm512_set1_epi32(int(H0[0]));
  __m512i b = _mm512_set1_epi32(int(H0[1]));
  __m512i c = _mm512_set1_epi32(int(H0[2]));
  __m512i d = _mm512_set1_epi32(int(H0[3]));
  __m512i e = _mm512_set1_epi32(int(H0[4]));
  __m512i f = _mm512_set1_epi32(int(H0[5]));
  __m512i g = _mm512_set1_epi32(int(H0[6]));
  __m512i h = _mm512_set1_epi32(int(H0[7]));

  __m512i w[16];
  for (int t = 0; t < 16; ++t) {
    alignas(64) uint32_t lanes[16];
    for (int lane = 0; lane < 16; ++lane)
      lanes[lane] = load_be32(in + lane * 64 + 4 * t);
    w[t] = _mm512_load_si512(reinterpret_cast<const __m512i*>(lanes));
  }
  for (int t = 0; t < 64; ++t) {
    if (t >= 16) {
      __m512i w15 = w[(t - 15) & 15], w2 = w[(t - 2) & 15];
      __m512i s0 = _mm512_ternarylogic_epi32(
          _mm512_ror_epi32(w15, 7), _mm512_ror_epi32(w15, 18),
          _mm512_srli_epi32(w15, 3), 0x96);
      __m512i s1 = _mm512_ternarylogic_epi32(
          _mm512_ror_epi32(w2, 17), _mm512_ror_epi32(w2, 19),
          _mm512_srli_epi32(w2, 10), 0x96);
      w[t & 15] = _mm512_add_epi32(
          _mm512_add_epi32(w[t & 15], s0),
          _mm512_add_epi32(w[(t - 7) & 15], s1));
    }
    EC_ROUND16(w[t & 15]);
  }
  __m512i sa = _mm512_add_epi32(a, _mm512_set1_epi32(int(H0[0])));
  __m512i sb = _mm512_add_epi32(b, _mm512_set1_epi32(int(H0[1])));
  __m512i sc = _mm512_add_epi32(c, _mm512_set1_epi32(int(H0[2])));
  __m512i sd = _mm512_add_epi32(d, _mm512_set1_epi32(int(H0[3])));
  __m512i se = _mm512_add_epi32(e, _mm512_set1_epi32(int(H0[4])));
  __m512i sf = _mm512_add_epi32(f, _mm512_set1_epi32(int(H0[5])));
  __m512i sg = _mm512_add_epi32(g, _mm512_set1_epi32(int(H0[6])));
  __m512i sh = _mm512_add_epi32(h, _mm512_set1_epi32(int(H0[7])));

  a = sa; b = sb; c = sc; d = sd; e = se; f = sf; g = sg; h = sh;
  for (int t = 0; t < 64; ++t) {
    EC_ROUND16(_mm512_set1_epi32(int(PAD_SCHED.w[t])));
  }
  a = _mm512_add_epi32(a, sa);
  b = _mm512_add_epi32(b, sb);
  c = _mm512_add_epi32(c, sc);
  d = _mm512_add_epi32(d, sd);
  e = _mm512_add_epi32(e, se);
  f = _mm512_add_epi32(f, sf);
  g = _mm512_add_epi32(g, sg);
  h = _mm512_add_epi32(h, sh);

  alignas(64) uint32_t lanes[8][16];
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[0]), a);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[1]), b);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[2]), c);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[3]), d);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[4]), e);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[5]), f);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[6]), g);
  _mm512_store_si512(reinterpret_cast<__m512i*>(lanes[7]), h);
  for (int lane = 0; lane < 16; ++lane) {
    for (int i = 0; i < 8; ++i) {
      store_be32(out + 32 * lane + 4 * i, lanes[i][lane]);
    }
  }
}

#undef EC_ROUND16

EC_SHA512_TARGET inline void hash_level_x16(const uint8_t* in, uint8_t* out,
                                            size_t n16) {
  for (size_t i = 0; i < n16; ++i) {
    sha256_64_x16(in + 64 * 16 * i, out + 32 * 16 * i);
  }
}

inline bool avx512_available() {
  static const bool ok = __builtin_cpu_supports("avx512f") &&
                         __builtin_cpu_supports("avx512bw") &&
                         __builtin_cpu_supports("avx512dq") &&
                         __builtin_cpu_supports("avx512vl");
  return ok;
}
#endif  // __x86_64__

}  // namespace

extern "C" {

// Hash one merkle level: in = n_pairs 64-byte messages, out = n_pairs
// 32-byte digests. in/out may not alias.
void ec_hash_level(const uint8_t* in, uint8_t* out, size_t n_pairs) {
#ifdef EC_SHA_NI_ACTIVE
  for (size_t i = 0; i < n_pairs; ++i) {
    sha256_64_ni(in + 64 * i, out + 32 * i);
  }
#else
  size_t i = 0;
#ifdef EC_AVX512_COMPILED
  if (avx512_available() && n_pairs >= 16) {
    size_t n16 = n_pairs / 16;
    hash_level_x16(in, out, n16);
    i = 16 * n16;
  }
#endif
#ifdef EC_AVX2_ACTIVE
  for (; i + 8 <= n_pairs; i += 8) {
    sha256_64_x8(in + 64 * i, out + 32 * i);
  }
#endif
  for (; i < n_pairs; ++i) {
    sha256_64(in + 64 * i, out + 32 * i);
  }
#endif
}

// Full tree reduction: `chunks` holds `count` populated 32-byte leaves of a
// depth-`depth` virtual tree; `zero_hashes` is depth+1 cached zero-subtree
// roots (32 bytes each). Writes the 32-byte root to `out32`. Matches the
// Python merkleizer bit-for-bit (zero-padding odd levels with the level's
// zero hash).
void ec_merkle_root(const uint8_t* chunks, size_t count, uint32_t depth,
                    const uint8_t* zero_hashes, uint8_t* out32) {
  if (count == 0) {
    std::memcpy(out32, zero_hashes + 32 * size_t(depth), 32);
    return;
  }
  std::vector<uint8_t> nodes(chunks, chunks + 32 * count);
  std::vector<uint8_t> next;
  for (uint32_t level = 0; level < depth; ++level) {
    size_t n = nodes.size() / 32;
    if (n % 2 == 1) {
      nodes.insert(nodes.end(), zero_hashes + 32 * size_t(level),
                   zero_hashes + 32 * size_t(level) + 32);
      ++n;
    }
    next.resize(32 * (n / 2));
    ec_hash_level(nodes.data(), next.data(), n / 2);
    nodes.swap(next);
  }
  std::memcpy(out32, nodes.data(), 32);
}

uint64_t ec_version(void) { return 1; }

}  // extern "C"
