"""Structured error taxonomy for the state-transition function.

Reference parity: ethereum-consensus/src/error.rs (Error, InvalidBlock,
InvalidOperation and per-operation invalidity enums, error.rs:15-275).

In Python these are exception classes: spec functions raise the most specific
subtype; callers (the Executor, the conformance harness) catch
``StateTransitionError`` to observe "transition must fail" vectors.
"""

from __future__ import annotations


class Error(Exception):
    """Root of the library's error hierarchy (error.rs:15)."""


class DeserializationError(Error):
    pass


class SerializationError(Error):
    pass


class MerkleizationError(Error):
    pass


class OverflowError_(Error):
    """u64 arithmetic overflow (error.rs:41-44)."""


class UnderflowError(Error):
    """u64 arithmetic underflow."""


class OutOfBoundsError(Error):
    """Index out of bounds for a bounded collection."""


class CollectionError(Error):
    """Bounded collection over/underflow (push beyond limit)."""


class UnknownForkError(Error):
    def __init__(self, version_or_slot):
        super().__init__(f"unknown fork for {version_or_slot!r}")


class IncompatibleForksError(Error):
    def __init__(self, block_fork, state_fork):
        super().__init__(
            f"block fork {block_fork} incompatible with state fork {state_fork}"
        )
        self.block_fork = block_fork
        self.state_fork = state_fork


class CryptoError(Error):
    pass


class InvalidSignatureError(CryptoError):
    pass


class InvalidPublicKeyError(CryptoError):
    pass


class InvalidSecretKeyError(CryptoError):
    pass


class KzgError(CryptoError):
    pass


class StateTransitionError(Error):
    """Any failure of the state-transition function (invalid block/operation).
    error.rs:69+ (InvalidBlock and below)."""


class InvalidBlock(StateTransitionError):
    pass


class InvalidBeaconBlockHeader(InvalidBlock):
    pass


class InvalidStateRoot(InvalidBlock):
    def __init__(self, expected: bytes, got: bytes):
        super().__init__(
            f"state root mismatch: block {expected.hex()} != computed {got.hex()}"
        )


class InvalidOperation(InvalidBlock):
    pass


class InvalidAttestation(InvalidOperation):
    pass


class InvalidIndexedAttestation(InvalidOperation):
    pass


class InvalidDeposit(InvalidOperation):
    pass


class InvalidRandao(InvalidOperation):
    pass


class InvalidProposerSlashing(InvalidOperation):
    pass


class InvalidAttesterSlashing(InvalidOperation):
    pass


class InvalidVoluntaryExit(InvalidOperation):
    pass


class InvalidSyncAggregate(InvalidOperation):
    pass


class InvalidExecutionPayload(InvalidOperation):
    pass


class InvalidWithdrawals(InvalidOperation):
    pass


class InvalidBlsToExecutionChange(InvalidOperation):
    pass


class InvalidDepositRequest(InvalidOperation):
    pass


class InvalidWithdrawalRequest(InvalidOperation):
    pass


class InvalidConsolidation(InvalidOperation):
    pass


class InvalidBlobData(InvalidOperation):
    pass


class ExecutionEngineError(StateTransitionError):
    """The (mock) execution engine rejected a payload
    (execution_engine.rs:20 failure path)."""


# -- checked u64 arithmetic helpers -----------------------------------------

U64_MAX = 2**64 - 1


def checked_add(a: int, b: int) -> int:
    c = a + b
    if c > U64_MAX:
        raise OverflowError_(f"u64 overflow: {a} + {b}")
    return c


def checked_sub(a: int, b: int) -> int:
    if b > a:
        raise UnderflowError(f"u64 underflow: {a} - {b}")
    return a - b


def checked_mul(a: int, b: int) -> int:
    c = a * b
    if c > U64_MAX:
        raise OverflowError_(f"u64 overflow: {a} * {b}")
    return c


def saturating_add(a: int, b: int) -> int:
    return min(a + b, U64_MAX)


def saturating_sub(a: int, b: int) -> int:
    return max(a - b, 0)
