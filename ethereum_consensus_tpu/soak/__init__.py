"""Production soak subsystem (docs/SOAK.md).

One sustained mixed-load run composing every hostility the scenario
harness can generate — fork-boundary pipeline replay, invalid-block
storms, injected infrastructure AND mesh-route faults, reader swarms,
SSE subscribers, pool ingestion spam, equivocation traffic — for
thousands of flush windows, asserting three hard gates: p99 latency
SLOs off the reservoir histograms (with /healthz pinned to ``ok``),
flat RSS via the leak sentinel, and end-of-run bit-identity (state
root, blame, equivocation ledger). ``bench.py soak`` reports the
sustained blocks/s + queries/s pair the north star asks for.

Host-only by construction: importing this package never imports jax
(the mesh fault lane engages only when ``ECT_MESH`` is on).
"""

from .runner import SoakConfig, SoakRunner, run_soak
from .sentinel import LeakSentinel, rss_mb

__all__ = [
    "SoakConfig",
    "SoakRunner",
    "run_soak",
    "LeakSentinel",
    "rss_mb",
]
