"""Leak sentinel: the production soak's flat-RSS gate (docs/SOAK.md).

The PR 9 copy-on-write test bounded ONE structure (four snapshot
bundles' shared columns) for one operation. A sustained run leaks
through any of half a dozen other retainers — serving snapshots pinned
past the head-store horizon, flight-ring records that stopped
evicting, pool aggregate matrices that never prune, mesh staging
buffers kept alive by a stale closure — and a per-structure test can't
see a leak it didn't anticipate. The sentinel watches the one number
every leak eventually moves — process RSS — across the soak's cycles,
plus an explicit census of the bounded structures so a tripped gate
names its suspect instead of just "memory grew".

Since ISSUE 15 the census itself lives in the memory observatory
(``telemetry/memory.py``) — ONE census implementation for the soak
gate, the ``/memory`` endpoint, and the bench ``mem`` evidence blocks.
``watch_owner(name, bound)`` reads a registered owner's entry count
from the observatory registry; the plain ``watch(name, fn, bound)``
seam stays for run-local structures (and the trip tests), and the
sentinel keeps its trip/fail-closed verdict semantics unchanged: a
failing or unknown owner probe reports -1, which the bound check
rejects — a broken census can never pass silently.

Gate semantics (``LeakSentinel.gate``):

* samples during the ``warmup`` cycles are recorded but EXCLUDED from
  the verdict — caches (chain bundles, jit executables, pubkey FIFO,
  committee memos) legitimately fill early;
* after warmup, ``growth_mb`` = last sample − first post-warmup sample
  must stay within ``budget_mb`` (``max_growth_mb`` is reported too —
  a sawtooth that returns to baseline passes, a ratchet fails);
* every watched census (``watch(name, fn, bound)``) must satisfy its
  declared bound at the final sample — a structure that silently grew
  past its design capacity trips the gate even before RSS notices.

The gate is deliberately trip-ABLE: ``tests/test_soak.py`` runs a
deliberately-leaky snapshot retainer through it and asserts the
verdict comes back False — a sentinel that cannot fail is not a gate.
"""

from __future__ import annotations

import threading

from ..telemetry import memory as _memory

__all__ = ["LeakSentinel", "rss_mb"]


def rss_mb() -> float:
    """Current process resident set in MiB — the memory observatory's
    reader (one implementation; /proc statm on Linux, ru_maxrss peak as
    the degraded fallback elsewhere — the gate still bounds growth,
    just of the high-watermark)."""
    return _memory.rss_mb()


class LeakSentinel:
    """RSS + structure-census sampler with a flat-memory verdict.

    Lock discipline: samples and watches are written from the soak
    driver thread and read by ``gate()`` on the same thread in
    production, but the instance lock guards every mutation anyway so a
    background sampler (a future periodic thread) can share it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: list = []  # (label, rss_mb, {census name: value})
        self._watches: list = []  # (name, fn, bound)

    def watch(self, name: str, fn, bound: "int | None" = None) -> "LeakSentinel":
        """Record ``fn()`` (an int census — ring length, snapshots held,
        pool rows, cache size) at every sample; when ``bound`` is given,
        the final census must be ``<= bound`` or the gate trips."""
        with self._lock:
            self._watches.append((name, fn, bound))
        return self

    def watch_owner(self, name: str, bound: "int | None" = None,
                    owner: "str | None" = None) -> "LeakSentinel":
        """Watch a memory-observatory owner's ENTRY count (the one
        census implementation — telemetry/memory.py): ``owner`` is the
        registry name (defaults to ``name``). An unknown owner or a
        failing probe reads -1, which a bound check rejects — the
        fail-closed contract."""
        owner_name = owner or name
        return self.watch(
            name, lambda: _memory.OBSERVATORY.owner_entries(owner_name),
            bound,
        )

    def sample(self, label) -> float:
        """Take one sample; returns the RSS read (MiB)."""
        census = {}
        with self._lock:
            watches = list(self._watches)
        for name, fn, _bound in watches:
            try:
                census[name] = int(fn())
            except Exception:  # noqa: BLE001 — a census must not kill the run
                census[name] = -1
        rss = rss_mb()
        with self._lock:
            self._samples.append((label, rss, census))
        return rss

    def samples(self) -> list:
        with self._lock:
            return list(self._samples)

    def gate(self, budget_mb: float, warmup: int = 2,
             ceiling_mb: "float | None" = None) -> dict:
        """The flat-RSS verdict over the recorded samples (see module
        docstring for semantics). Returns a JSON-ready dict with ``ok``
        plus the evidence a tripped gate needs to be debugged.
        ``ceiling_mb`` (per-deployment profile, docs/SOAK.md) bounds
        the ABSOLUTE process high-water mark on top of the growth
        budget — a deployment that knows its envelope can assert it."""
        with self._lock:
            samples = list(self._samples)
            watches = list(self._watches)
        verdict: dict = {
            "budget_mb": float(budget_mb),
            "warmup_samples": int(warmup),
            "samples": len(samples),
        }
        if len(samples) <= warmup + 1:
            # nothing measurable after warmup: vacuous passes are worse
            # than loud ones — fail closed
            verdict.update(ok=False, error="too few post-warmup samples")
            return verdict
        post = samples[warmup:]
        rss_series = [s[1] for s in post]
        baseline = rss_series[0]
        final = rss_series[-1]
        growth = final - baseline
        max_growth = max(rss_series) - baseline
        census_ok = True
        census_verdicts = {}
        final_census = post[-1][2]
        for name, _fn, bound in watches:
            value = final_census.get(name)
            bounded = bound is None or (value is not None and 0 <= value <= bound)
            census_verdicts[name] = {
                "final": value,
                "bound": bound,
                "ok": bounded,
            }
            census_ok = census_ok and bounded
        ceiling_ok = True
        peak_mb = _memory.peak_rss_mb()
        if ceiling_mb is not None:
            ceiling_ok = peak_mb <= float(ceiling_mb)
            verdict.update(
                ceiling_mb=float(ceiling_mb), peak_mb=round(peak_mb, 1),
                ceiling_ok=ceiling_ok,
            )
        verdict.update(
            ok=bool(growth <= budget_mb and census_ok and ceiling_ok),
            baseline_mb=round(baseline, 1),
            final_mb=round(final, 1),
            growth_mb=round(growth, 1),
            max_growth_mb=round(max_growth, 1),
            census=census_verdicts,
        )
        return verdict
