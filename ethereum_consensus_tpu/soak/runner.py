"""Production soak: every hostility at once, sustained, with hard gates.

The scenario families (scenarios/families.py) each prove one hostility
in isolation; production is all of them CONCURRENTLY for hours. One
``SoakRunner.run()`` composes everything PRs 6-12 landed into a single
sustained mixed load:

* **pipeline replay across fork boundaries** — the full phase0→electra
  upgrade chain (tests/chain_utils.produce_full_upgrade_chain), cycled
  for thousands of flush windows under the bounded two-stage pipeline;
* **an invalid-block storm** — ``storm_fraction`` of each cycle's blocks
  corrupted by the mutator library, recovered with exact blame and the
  honest twin resumed (scenarios/mutators.py);
* **fault injection** — rotating ``FaultInjector`` plans: transient
  flush faults (retried), delayed flushes (inside the settle bound),
  and — when the mesh runtime is switched on — injected DEVICE faults
  on the sharded pairing/epoch routes (``fail_mesh``), recovered by the
  host fallback with the decline journaled;
* **read traffic** — a ``ReaderSwarm`` hammering the Beacon-API data
  plane and SSE subscribers on ``/events`` for the whole run, verified
  against the scalar oracle at the end (no torn reads, no rolled-back
  state served);
* **pool ingestion** — a ``PoolSpammer`` feeding hostile gossip through
  ``admit_attestation`` against the rotating heads (accounting
  contract: no silent drops), plus a DETERMINISTIC equivocation feed
  through ``admit_attestation_batch`` whose double AND surround votes
  must surface slashings that EXECUTE in soak-produced blocks.

Three hard gates fold into ``report["ok"]`` (docs/SOAK.md):

1. **SLOs** — p99 ``pipeline.verify_s`` / ``pipeline.settle_s`` /
   ``serving.gather_s`` bounded straight off the reservoir histograms
   (telemetry/metrics.py), and ``/healthz`` answering ``ok`` at every
   cycle's sample — which is why the soak's fault mix deliberately
   excludes worker-death (that lane legitimately latches the
   ``degraded`` gauge and belongs to the faults family, not a
   steady-state soak);
2. **flat RSS** — the ``LeakSentinel`` (sentinel.py): post-warmup RSS
   growth within budget and every watched structure census inside its
   declared bound;
3. **bit-identity** — every cycle's committed head equals the scalar
   oracle's root, every corruption blamed exactly, and the equivocation
   ledger + surfaced slashings of the live run identical to a clean
   refeed of the recorded admission schedule.

The run additionally executes with the causal trace plane ACTIVE
(telemetry/spans.py): a fourth ``trace`` gate folds into ``ok`` —
every SLO histogram's worst-N exemplar table must name at least one
trace_id that resolves into a connected admission→settle span tree,
settled windows must actually have linked (``trace.windows_linked``),
and an SLO breach or sentinel trip names its exemplar/slow trace ids
so the tail is a ``/trace`` lookup away, not a re-run.
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from ..error import Error
from ..executor import Executor
from ..pipeline import ChainPipeline, FaultInjector, FlushPolicy
from ..scenarios.harness import (
    PoolSpammer,
    ReaderSwarm,
    _advance_to_slot,
    forced_columnar,
    oracle_replay,
)
from ..scenarios.mutators import MUTATORS, MutationEnv, by_name, plan_storm
from ..telemetry import flight as _flight
from ..telemetry import memory as _memory
from ..telemetry import metrics as _metrics
from ..telemetry import spans as _spans
from ..utils import trace
from .sentinel import LeakSentinel

__all__ = ["SoakConfig", "SoakRunner", "run_soak", "load_profile",
           "DEFAULT_PROFILE_PATH"]

# the shipped per-deployment profile (ROADMAP soak residue → ISSUE 15):
# the catastrophe-catcher defaults, as a FILE a deployment can copy and
# tighten — p99 SLO bounds, RSS budget/ceiling, and the bench epoch
# configs' memory ceilings all live here (docs/SOAK.md)
DEFAULT_PROFILE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "profiles", "default.json"
)

# the p99 SLO histograms (gate 1) — also the histograms whose exemplar
# tables the trace gate resolves into connected causal trees
_SLO_HISTOGRAMS = ("pipeline.verify_s", "pipeline.settle_s",
                   "serving.gather_s")


def _parse_flat_toml(text: str) -> dict:
    """A minimal TOML subset parser (``[section]`` + ``key = value``
    with ints/floats/bools/quoted strings) for py3.10 boxes without
    ``tomllib`` — exactly the shape a soak profile needs, nothing
    more. Full TOML goes through ``tomllib`` when available."""
    out: dict = {}
    section = out
    for raw_line in text.splitlines():
        line = raw_line.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            section = out.setdefault(line[1:-1].strip(), {})
            continue
        if "=" not in line:
            raise ValueError(f"unparsable profile line: {raw_line!r}")
        key, value = (part.strip() for part in line.split("=", 1))
        if value.lower() in ("true", "false"):
            section[key] = value.lower() == "true"
        elif value.startswith(('"', "'")) and value.endswith(value[0]):
            section[key] = value[1:-1]
        else:
            try:
                section[key] = int(value)
            except ValueError:
                section[key] = float(value)
    return out


def load_profile(path: "str | None" = None) -> dict:
    """The deployment profile document: JSON or TOML by extension
    (``tomllib`` when the interpreter has it, the flat-subset parser
    otherwise). ``None`` loads the shipped default profile."""
    path = path or DEFAULT_PROFILE_PATH
    with open(path, "rb") as f:
        raw = f.read()
    if path.endswith(".toml"):
        try:
            import tomllib  # py3.11+

            return tomllib.loads(raw.decode("utf-8"))
        except ModuleNotFoundError:
            return _parse_flat_toml(raw.decode("utf-8"))
    return json.loads(raw)


class SoakConfig:
    """One soak's shape. Defaults are the ``make soak-smoke`` scale; the
    bench config (``bench.py soak``) raises cycles/deadline/spam to the
    sustained shape."""

    __slots__ = (
        "validator_count", "atts_per_block", "cycles", "deadline_s",
        "min_windows", "storm_fraction", "policy", "readers",
        "sse_subscribers", "pool_spam_rounds", "equivocate_every",
        "rss_budget_mb", "rss_warmup_cycles", "rss_ceiling_mb",
        "retainers", "seed",
        "slo_verify_p99_s", "slo_settle_p99_s", "slo_gather_p99_s",
        "mesh_faults", "check_columns_every", "memory_ceilings",
    )

    def __init__(self, validator_count: int = 64, atts_per_block: int = 2,
                 cycles: int = 8, deadline_s: float = 300.0,
                 min_windows: int = 40, storm_fraction: float = 0.10,
                 policy: "FlushPolicy | None" = None, readers: int = 2,
                 sse_subscribers: int = 1, pool_spam_rounds: int = 40,
                 equivocate_every: int = 2, rss_budget_mb: float = 96.0,
                 rss_warmup_cycles: int = 2, retainers=(), seed: int = 0x50AC,
                 slo_verify_p99_s: float = 2.0,
                 slo_settle_p99_s: float = 10.0,
                 slo_gather_p99_s: float = 0.25,
                 mesh_faults: "bool | None" = None,
                 check_columns_every: int = 4,
                 rss_ceiling_mb: "float | None" = None,
                 memory_ceilings: "dict | None" = None):
        self.validator_count = int(validator_count)
        self.atts_per_block = int(atts_per_block)
        self.cycles = int(cycles)
        self.deadline_s = float(deadline_s)
        self.min_windows = int(min_windows)
        self.storm_fraction = float(storm_fraction)
        # the soak default IS the auto-sized lane policy (ROADMAP PR 12
        # residue): verify_lanes unset resolves to min(cores, devices)
        self.policy = policy or FlushPolicy(
            window_size=2, max_in_flight=2, checkpoint_interval=2,
            settle_timeout_s=60.0, flush_retries=2, retry_backoff_s=0.01,
        )
        self.readers = int(readers)
        self.sse_subscribers = int(sse_subscribers)
        self.pool_spam_rounds = int(pool_spam_rounds)
        self.equivocate_every = max(1, int(equivocate_every))
        self.rss_budget_mb = float(rss_budget_mb)
        self.rss_warmup_cycles = int(rss_warmup_cycles)
        self.retainers = tuple(retainers)  # (cycle, state) callables
        self.seed = int(seed)
        self.slo_verify_p99_s = float(slo_verify_p99_s)
        self.slo_settle_p99_s = float(slo_settle_p99_s)
        self.slo_gather_p99_s = float(slo_gather_p99_s)
        # None = follow the runtime (inject device faults exactly when
        # ECT_MESH is switched on); True/False force it for tests
        self.mesh_faults = mesh_faults
        self.check_columns_every = max(1, int(check_columns_every))
        # per-deployment memory envelope (ISSUE 15): an ABSOLUTE peak
        # ceiling the flat-RSS gate additionally asserts (None = growth
        # budget only — the shipped catastrophe-catcher default), plus
        # the bench epoch configs' ceiling table the profile carries
        # through (bench.py reads it via load_profile)
        self.rss_ceiling_mb = (
            None if rss_ceiling_mb is None else float(rss_ceiling_mb)
        )
        self.memory_ceilings = dict(memory_ceilings or {})

    @classmethod
    def from_file(cls, path: "str | None" = None,
                  **overrides) -> "SoakConfig":
        """Build a config from a deployment profile (TOML or JSON —
        ``load_profile``): ``[slo]`` p99 bounds, ``[rss]``
        budget/warmup/ceiling, ``[load]`` traffic shape, and the
        ``[memory_ceilings]`` table. Unknown keys raise (a typo'd bound
        must not silently keep the catastrophe-catcher default);
        keyword ``overrides`` win over the file."""
        doc = load_profile(path)
        kwargs: dict = {}
        slo = doc.get("slo", {})
        for key, kw in (("verify_p99_s", "slo_verify_p99_s"),
                        ("settle_p99_s", "slo_settle_p99_s"),
                        ("gather_p99_s", "slo_gather_p99_s")):
            if key in slo:
                kwargs[kw] = float(slo[key])
        rss = doc.get("rss", {})
        for key, kw in (("budget_mb", "rss_budget_mb"),
                        ("warmup_cycles", "rss_warmup_cycles"),
                        ("ceiling_mb", "rss_ceiling_mb")):
            if key in rss and rss[key] is not None:
                kwargs[kw] = rss[key]
        load = doc.get("load", {})
        allowed_load = {
            "validator_count", "atts_per_block", "cycles", "deadline_s",
            "min_windows", "storm_fraction", "readers", "sse_subscribers",
            "pool_spam_rounds", "equivocate_every", "seed",
            "check_columns_every",
        }
        unknown = set(load) - allowed_load
        if unknown:
            raise ValueError(
                f"unknown [load] profile keys: {sorted(unknown)}"
            )
        kwargs.update(load)
        if "memory_ceilings" in doc:
            kwargs["memory_ceilings"] = dict(doc["memory_ceilings"])
        unknown_sections = set(doc) - {"slo", "rss", "load",
                                       "memory_ceilings", "name", "notes"}
        if unknown_sections:
            raise ValueError(
                f"unknown profile sections: {sorted(unknown_sections)}"
            )
        kwargs.update(overrides)
        return cls(**kwargs)


class _SSESubscriber:
    """One /events SSE client counting events per kind for the run (a
    long-lived subscriber is itself soak load: the per-client queue and
    keepalive path run for the whole duration)."""

    def __init__(self, base_url: str, name: str):
        import threading

        self._lock = threading.Lock()
        self._stop = False
        self._response = None
        self.counts: dict = {}
        self.errors: list = []
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix=name)
        self._future = self._pool.submit(self._loop, base_url)

    def _should_stop(self) -> bool:
        with self._lock:
            return self._stop

    def _loop(self, base_url: str) -> None:
        try:
            response = urllib.request.urlopen(
                base_url + "/events?kinds=head,commit,rollback,broken",
                timeout=30,
            )
        except OSError as exc:
            with self._lock:
                self.errors.append(repr(exc))
            return
        with self._lock:
            self._response = response
        try:
            for raw in response:
                if self._should_stop():
                    break
                line = raw.decode("utf-8", "replace").strip()
                if line.startswith("event:"):
                    kind = line.split(":", 1)[1].strip()
                    with self._lock:
                        self.counts[kind] = self.counts.get(kind, 0) + 1
        except (OSError, ValueError):
            # closed under us by stop(): normal shutdown
            pass

    def stop(self) -> dict:
        with self._lock:
            self._stop = True
            response = self._response
        if response is not None:
            try:
                response.close()
            except OSError:
                pass
        self._future.result(timeout=30)
        self._pool.shutdown(wait=True)
        with self._lock:
            return dict(self.counts)


class SoakRunner:
    """Drives one soak (see module docstring); ``run()`` returns the
    JSON-ready report with the three gates folded into ``ok``."""

    def __init__(self, config: "SoakConfig | None" = None):
        self.config = config or SoakConfig()
        self._oracle_root_memo: "bytes | None" = None

    # -- pieces ---------------------------------------------------------------
    def _chain(self):
        from ..scenarios.families import _chain_utils

        cu = _chain_utils()
        state, ctx, blocks = cu.produce_full_upgrade_chain(
            self.config.validator_count, self.config.atts_per_block
        )
        return cu, state, ctx, blocks

    def _corrupt(self, cu, ctx, blocks, plan, prefixes) -> list:
        """The cycle's corrupted stream off the PRE-COMPUTED oracle
        prefixes (harness.build_corrupted_stream re-runs the oracle per
        call; a thousand-cycle soak amortizes it to once)."""
        stream = list(blocks)
        for i, mutator in plan.items():
            env = MutationEnv(
                ctx,
                donor=blocks[(i + 1) % len(blocks)],
                pre_state=(
                    _advance_to_slot(
                        prefixes[i], int(blocks[i].message.slot), ctx
                    )
                    if mutator.needs_sign
                    else None
                ),
                sign=cu.sign_block,
            )
            stream[i] = mutator(blocks[i], env)
        return stream

    def _injector_for(self, cycle: int, n_windows_est: int,
                      mesh_on: bool) -> "tuple[FaultInjector | None, bool]":
        """The cycle's rotating fault plan: none / transient / delayed /
        mesh. Worker-death is deliberately absent — it latches the
        ``degraded`` gauge, and this run's /healthz gate pins ``ok``
        (the faults family owns that lane). Returns (injector,
        mesh_installed)."""
        lane = cycle % 4
        if lane == 0:
            return None, False
        inj = FaultInjector()
        seq = cycle % max(1, n_windows_est)
        if lane == 1:
            inj.fail_flush(seq, times=1)
        elif lane == 2:
            inj.delay_flush(seq, seconds=0.05)
        elif lane == 3:
            if mesh_on:
                inj.fail_mesh("pairing", 1).fail_mesh("epoch", 1)
                inj.install_mesh()
                return inj, True
            inj.fail_flush(seq, times=2)
        return inj, False

    def _surround_slots(self, ctx, head_state) -> "tuple | None":
        """Pick (outer_slot, inner_slot) for the surround pair such that
        (a) both slots clear the admission inclusion window, (b) the two
        slots' committees share at least one validator (every validator
        attests once per epoch, so a cross-epoch overlap always exists —
        but a BLIND slot pair can miss it), and (c) the outer slot is
        not the double-vote slot (epoch-``E`` committees partition the
        active set, so distinct slots keep the two slashings' attester
        intersections DISJOINT — drain order can't starve either of
        slashable validators)."""
        from ..models.phase0 import helpers as h

        spe = int(ctx.SLOTS_PER_EPOCH)
        head_slot = int(head_state.slot)
        epoch = head_slot // spe
        if epoch < 3:
            return None

        def slot_members(slot: int) -> set:
            count = h.get_committee_count_per_slot(
                head_state, slot // spe, ctx
            )
            members: set = set()
            for index in range(count):
                members.update(
                    int(v)
                    for v in h.get_beacon_committee(head_state, slot, index,
                                                    ctx)
                )
            return members

        double_slot = head_slot - 1
        inner_lo = max((epoch - 1) * spe, head_slot - spe)
        for inner_slot in range(epoch * spe - 1, inner_lo - 1, -1):
            inner_members = slot_members(inner_slot)
            for outer_slot in range(epoch * spe, head_slot + 1):
                if outer_slot == double_slot:
                    continue
                if inner_members & slot_members(outer_slot):
                    return outer_slot, inner_slot
        return None

    def _equivocation_traffic(self, cu, ctx, head_state) -> "list":
        """The deterministic double + surround feed for one cycle,
        derived from the head (the same committed position every cycle,
        so the end-of-run refeed replays the identical schedule).
        Returns the attestation containers in feed order."""
        import importlib

        spe = int(ctx.SLOTS_PER_EPOCH)
        head_slot = int(head_state.slot)
        epoch = head_slot // spe
        fork_name = cu.full_upgrade_fork_at_slot(head_slot, ctx)
        electra = fork_name == "electra"
        ns = importlib.import_module(
            f"ethereum_consensus_tpu.models.{fork_name}"
        ).build(ctx.preset)

        def make(slot, **kwargs):
            if electra:
                return cu.make_attestation_electra(head_state, slot, ctx,
                                                   **kwargs)
            return cu.make_attestation(head_state, slot, 0, ctx, **kwargs)

        out = []
        # double vote: honest head vote + a properly-signed contradictory
        # vote at the same slot (same target epoch, different data)
        double_slot = head_slot - 1
        out.append(make(double_slot))
        out.append(make(double_slot, beacon_block_root=b"\x66" * 32))
        # surround pair: the later-epoch vote's (source, target) span
        # strictly contains the earlier-epoch vote's — slots picked so
        # the pair's attester intersection is provably non-empty
        pair = self._surround_slots(ctx, head_state)
        if pair is not None:
            outer_slot, inner_slot = pair
            inner = make(
                inner_slot,
                source=ns.Checkpoint(epoch=epoch - 2, root=b"\x21" * 32),
            )
            outer = make(
                outer_slot,
                source=ns.Checkpoint(epoch=epoch - 3, root=b"\x21" * 32),
            )
            out.extend((inner, outer))
        return out

    def _healthz(self, server) -> "dict | None":
        try:
            with urllib.request.urlopen(
                server.url("/healthz"), timeout=10
            ) as response:
                return json.loads(response.read())
        except OSError as exc:
            return {"status": f"unreachable: {exc!r}"}

    # -- the run --------------------------------------------------------------
    def run(self) -> dict:
        with forced_columnar():
            return self._run()

    def _run(self) -> dict:
        from ..pool import AdmissionEngine, OperationPool, produce_block
        from ..serving import BeaconDataPlane, HeadStore
        from ..telemetry.server import IntrospectionServer

        config = self.config
        cu, pre_state, ctx, blocks = self._chain()
        n_blocks = len(blocks)
        rng = random.Random(config.seed)

        # the scalar oracle, once: per-index prefixes feed the mutators'
        # re-signing AND the reader-verification map; the final state is
        # the bit-identity target of every cycle
        oracle_ex, prefixes = oracle_replay(
            pre_state, ctx, blocks, capture_at=range(n_blocks)
        )
        oracle_raw = getattr(oracle_ex.state, "data", oracle_ex.state)
        oracle_root = type(oracle_raw).hash_tree_root(oracle_raw)
        self._oracle_root_memo = bytes(oracle_root)
        states_by_root = {}
        for state in list(prefixes.values()) + [oracle_ex.state]:
            raw = getattr(state, "data", state)
            states_by_root[
                "0x" + type(raw).hash_tree_root(raw).hex()
            ] = state

        mesh_on = config.mesh_faults
        if mesh_on is None:
            from ..models.epoch_vector import _mesh_requested

            mesh_on = _mesh_requested()

        # causal tracing is ON for the whole soak: the trace gate below
        # must resolve every SLO histogram's exemplars into connected
        # admission→settle trees. A fresh recording clears the span
        # ring, and resetting the SLO exemplar tables drops any ids
        # minted by earlier runs in this process — every exemplar this
        # run reports resolves against this run's recording.
        trace_started = not _spans.is_recording()
        if trace_started:
            _spans.start_recording()
        for hist_name in _SLO_HISTOGRAMS:
            _metrics.histogram(hist_name).reset_exemplars()

        sentinel = LeakSentinel()
        store = HeadStore().attach()
        server = IntrospectionServer(port=0, sse_keepalive_s=1.0).start()
        server.mount(BeaconDataPlane(store))
        swarm = (
            # bounded retention: the swarm verifies a 4096-sample
            # reservoir offline and counts the rest — unbounded response
            # retention would read as a leak to the sentinel below
            ReaderSwarm(server.url(), n_readers=config.readers,
                        max_samples=4096)
            if config.readers
            else None
        )
        subscribers = [
            _SSESubscriber(server.url(), f"soak-sse-{i}")
            for i in range(config.sse_subscribers)
        ]
        spammer = (
            PoolSpammer(store, ctx, blocks, config.pool_spam_rounds)
            if config.pool_spam_rounds
            else None
        )
        eq_pool = OperationPool()
        eq_engine = AdmissionEngine(eq_pool, store, ctx, window_size=8)
        eq_schedule: list = []

        # the census reads come from the memory observatory's registry —
        # ONE census implementation (ISSUE 15): the process-wide owners
        # for the ring and the serving history, plus a run-local owner
        # for this soak's equivocation pool (registered here, dropped in
        # the finally — the process-wide "pool.store" owner would also
        # count the spammer's hostile-gossip pool)
        _memory.register_owner(
            "soak.eq_pool", lambda: eq_pool.memory_census()
        )
        _memory.register_owner(
            "soak.headstore", lambda: store.memory_census()
        )
        sentinel.watch_owner("flight_ring",
                             bound=_flight.RECORDER.capacity,
                             owner="flight.ring")
        sentinel.watch_owner("serving_snapshots", bound=64,
                             owner="soak.headstore")
        sentinel.watch_owner("pool_rows", bound=4096, owner="soak.eq_pool")

        metrics_base = _metrics.snapshot()
        report: dict = {"config": {
            "validators": config.validator_count,
            "chain_blocks": n_blocks,
            "cycles_planned": config.cycles,
            "storm_fraction": config.storm_fraction,
            "readers": config.readers,
            "sse_subscribers": config.sse_subscribers,
            "pool_spam_rounds": config.pool_spam_rounds,
            "verify_lanes": self.config.policy.verify_lanes,
            "mesh_faults": bool(mesh_on),
        }}
        healthz_samples = 0
        healthz_ok = True
        last_health = None
        cycles_run = 0
        failures = 0
        blame_ok = True
        roots_ok = True
        columns_ok = True
        faults: dict = {}
        final_state = None
        t0 = time.perf_counter()
        try:
            with trace.span("soak.run", cycles=config.cycles):
                for cycle in range(config.cycles):
                    if time.perf_counter() - t0 > config.deadline_s:
                        break
                    outcome = self._cycle(
                        cu, ctx, pre_state, blocks, prefixes, plan_rng=rng,
                        cycle=cycle, mesh_on=mesh_on,
                    )
                    cycles_run += 1
                    failures += outcome["failures"]
                    blame_ok = blame_ok and outcome["blame_ok"]
                    roots_ok = roots_ok and outcome["root_ok"]
                    columns_ok = columns_ok and outcome["columns_ok"]
                    for kind, count in outcome["faults"].items():
                        faults[kind] = faults.get(kind, 0) + count
                    final_state = outcome["state"]
                    _metrics.counter("soak.cycles").inc()

                    health = self._healthz(server)
                    healthz_samples += 1
                    last_health = health
                    healthz_ok = healthz_ok and (
                        health is not None and health.get("status") == "ok"
                    )
                    if cycle % config.equivocate_every == 0:
                        head_raw = getattr(final_state, "data", final_state)
                        traffic = self._equivocation_traffic(
                            cu, ctx, head_raw
                        )
                        eq_schedule.extend(a.copy() for a in traffic)
                        eq_engine.admit_attestation_batch(traffic)
                        eq_engine.settle()
                    for retainer in config.retainers:
                        retainer(cycle, final_state)
                    sentinel.sample(cycle)
        finally:
            spam_summary = spammer.stop() if spammer is not None else None
            sse_counts: dict = {}
            for subscriber in subscribers:
                for kind, count in subscriber.stop().items():
                    sse_counts[kind] = sse_counts.get(kind, 0) + count
            reader_samples = reader_roots = 0
            reader_error = None
            if swarm is not None:
                swarm.stop()
                try:
                    reader_roots = swarm.verify(states_by_root, ctx)
                    reader_samples = swarm.samples_seen
                except AssertionError as exc:
                    reader_error = str(exc)[:300]
            # detach/stop here so an exception mid-cycle can't leave the
            # process-wide commit hook subscribed or the server running
            store.detach()
            server.stop()
            # the run-local census owners die with the run (samples
            # already recorded their values; the gate reads samples)
            _memory.OBSERVATORY.unregister_owner("soak.eq_pool")
            _memory.OBSERVATORY.unregister_owner("soak.headstore")

        wall_s = time.perf_counter() - t0
        delta = _metrics.delta(metrics_base)

        # -- gate 3: bit-identity (roots + blame + ledger) --------------------
        ledger = self._ledger_identity(
            cu, ctx, eq_pool, eq_schedule, final_state, produce_block,
        )
        identity = {
            "cycle_roots_ok": roots_ok,
            "blame_ok": blame_ok,
            "columns_ok": columns_ok,
            "final_root": "0x" + bytes(oracle_root).hex(),
            "ledger": ledger,
            "ok": bool(
                roots_ok and blame_ok and columns_ok and ledger["ok"]
            ),
        }

        # -- gate 1: SLOs off the reservoir histograms ------------------------
        slo = self._slo_gate(healthz_ok, healthz_samples, last_health)

        # -- gate 2: flat RSS -------------------------------------------------
        rss = sentinel.gate(config.rss_budget_mb,
                            warmup=config.rss_warmup_cycles,
                            ceiling_mb=config.rss_ceiling_mb)
        if not rss["ok"]:
            # a sentinel trip names the run's worst traces: the windows
            # most likely to have been live while memory ratcheted
            rss["slow_trace_ids"] = [
                entry["trace_id"]
                for entry in _spans.RECORDER.slow_traces()[:8]
            ]

        # -- trace gate: exemplars resolve into connected causal trees --------
        trace_gate = self._trace_gate(delta)
        if trace_started:
            _spans.stop_recording()

        windows = delta.get("pipeline.flushes", 0)
        blocks_committed = delta.get("pipeline.blocks_committed", 0)
        queries = delta.get("serving.requests", 0)
        spam_ok = spam_summary is None or (
            spam_summary["admitted"] + sum(spam_summary["rejected"].values())
            == spam_summary["fed"]
        )
        readers_ok = reader_error is None
        report.update(
            cycles=cycles_run,
            windows=windows,
            blocks_committed=blocks_committed,
            wall_s=round(wall_s, 2),
            blocks_per_s=round(blocks_committed / wall_s, 2) if wall_s else 0,
            queries_served=queries,
            queries_per_s=round(queries / wall_s, 2) if wall_s else 0,
            storm_failures=failures,
            faults_injected=faults,
            gates={"slo": slo, "rss": rss, "identity": identity,
                   "trace": trace_gate},
            pool_spam=spam_summary,
            pool_spam_ok=spam_ok,
            readers={"samples": reader_samples, "roots": reader_roots,
                     "connection_errors": (
                         swarm.connection_errors if swarm is not None else 0
                     ),
                     "ok": readers_ok, "error": reader_error},
            sse_events=sse_counts,
            ok=bool(
                slo["ok"] and rss["ok"] and identity["ok"]
                and trace_gate["ok"] and spam_ok
                and readers_ok and windows >= config.min_windows
                and cycles_run > 0
            ),
            min_windows=config.min_windows,
        )
        return report

    def _cycle(self, cu, ctx, pre_state, blocks, prefixes, plan_rng,
               cycle: int, mesh_on: bool) -> dict:
        """One storm replay over the fixed chain: corrupt, replay with
        rollback+resume, verify blame and the committed root."""
        from ..scenarios.harness import assert_column_consistency

        config = self.config
        n_blocks = len(blocks)
        plan = plan_storm(n_blocks, config.storm_fraction, plan_rng,
                          MUTATORS)
        for index, mutator in list(plan.items()):
            # an attestation mutator drawn for an attestation-less block
            # (early upgrade-chain slots) re-rolls to the proposer-sig
            # corruption — same rollback path, no content requirement
            if mutator.name == "bad_attestation_sig" and not len(
                blocks[index].message.body.attestations
            ):
                plan[index] = by_name("bad_proposer_sig")
        stream = self._corrupt(cu, ctx, blocks, plan, prefixes)
        est_windows = max(1, n_blocks // config.policy.window_size)
        injector, mesh_installed = self._injector_for(
            cycle, est_windows, mesh_on
        )
        remaining = sorted(plan)
        blame_ok = True
        failures = 0
        ex = Executor(pre_state.copy(), ctx)
        pipe = ChainPipeline(ex, policy=config.policy,
                             fault_injector=injector)
        i = 0
        try:
            while True:
                try:
                    if i < len(stream):
                        pipe.submit(stream[i])
                        i += 1
                        continue
                    pipe.close()
                    break
                except Error as exc:
                    failures += 1
                    if not remaining:
                        blame_ok = False
                        break
                    f = remaining.pop(0)
                    if not plan[f].matches(exc):
                        blame_ok = False
                    pipe = ChainPipeline(ex, policy=config.policy,
                                         fault_injector=injector)
                    stream[f] = blocks[f]
                    i = f
                    _metrics.counter("soak.recoveries").inc()
        finally:
            if mesh_installed:
                injector.uninstall_mesh()
        blame_ok = blame_ok and not remaining
        raw = getattr(ex.state, "data", ex.state)
        columns_ok = True
        # committed head vs the scalar oracle: root compare every cycle
        # (cheap — the incremental-HTR memo makes it a cached read);
        # column consistency on its sampling interval
        root_ok = bytes(type(raw).hash_tree_root(raw)) == bytes(
            self._oracle_root(ctx, pre_state, blocks)
        )
        if cycle % config.check_columns_every == 0:
            try:
                assert_column_consistency(ex.state, where=f"cycle {cycle}")
            except AssertionError:
                columns_ok = False
        faults = {}
        if injector is not None:
            for _seq, _attempt, kind in injector.injected:
                faults[kind] = faults.get(kind, 0) + 1
        return {
            "failures": failures,
            "blame_ok": blame_ok,
            "root_ok": root_ok,
            "columns_ok": columns_ok,
            "faults": faults,
            "state": ex.state,
        }

    def _oracle_root(self, ctx, pre_state, blocks) -> bytes:
        """The honest chain's final root, computed once per runner (one
        fixed chain per run)."""
        if self._oracle_root_memo is None:
            oracle_ex, _ = oracle_replay(pre_state, ctx, blocks)
            raw = getattr(oracle_ex.state, "data", oracle_ex.state)
            self._oracle_root_memo = bytes(type(raw).hash_tree_root(raw))
        return self._oracle_root_memo

    def _slo_gate(self, healthz_ok: bool, healthz_samples: int,
                  last_health) -> dict:
        config = self.config
        quantiles = {}
        verdicts = {}
        for name, bound in zip(_SLO_HISTOGRAMS,
                               (config.slo_verify_p99_s,
                                config.slo_settle_p99_s,
                                config.slo_gather_p99_s)):
            hist = _metrics.histogram(name)
            qs = hist.quantiles((0.5, 0.9, 0.99))
            p99 = qs.get(0.99)
            quantiles[name] = {
                "p50": qs.get(0.5), "p90": qs.get(0.9), "p99": p99,
                "count": hist.summary()["count"], "bound_p99": bound,
                # the causal trace plane: which windows WERE the tail —
                # a breach names the traces to pull from /trace
                "exemplar_trace_ids": [
                    e["trace_id"] for e in hist.exemplars()
                ],
            }
            verdicts[name] = p99 is not None and p99 <= bound
        return {
            "quantiles": quantiles,
            "healthz_samples": healthz_samples,
            "healthz_all_ok": healthz_ok,
            "healthz_last": last_health,
            "ok": bool(all(verdicts.values()) and healthz_ok
                       and healthz_samples > 0),
        }

    def _trace_gate(self, delta: dict) -> dict:
        """The causal-trace verdict: every SLO histogram's exemplar
        table must hold at least one trace_id that resolves — against
        the run's own span recording — into a CONNECTED causal tree
        (one root, zero orphans), and the pipeline/pool settle paths
        must have linked windows (``trace.windows_linked`` moved).
        Whole-buffer orphans gate only while nothing was evicted — a
        ring that dropped its oldest spans can legitimately strand
        children, and that loss is already counted, not silent."""
        recorder = _spans.RECORDER
        audit = recorder.audit()
        exemplars = {}
        resolved_ok = True
        for name in _SLO_HISTOGRAMS:
            ids = [
                e["trace_id"]
                for e in _metrics.histogram(name).exemplars()
            ]
            connected = [
                t for t in ids if recorder.trace_tree(t)["connected"]
            ]
            exemplars[name] = {
                "trace_ids": ids,
                "connected": len(connected),
            }
            resolved_ok = resolved_ok and bool(connected)
        windows_linked = delta.get("trace.windows_linked", 0)
        orphans_ok = audit["dropped"] > 0 or audit["orphans"] == 0
        return {
            "windows_linked": windows_linked,
            "audit": audit,
            "exemplars": exemplars,
            "slow_traces": recorder.slow_traces()[:8],
            "ok": bool(
                resolved_ok and orphans_ok and windows_linked > 0
            ),
        }

    def _ledger_identity(self, cu, ctx, eq_pool, eq_schedule,
                         final_state, produce_block) -> dict:
        """End-of-run equivocation-ledger identity + slashing execution:
        a clean refeed of the recorded admission schedule into a fresh
        engine over the SAME final head must reproduce the ledger and
        the surfaced slashings bit-for-bit, and draining the live pool
        into produced blocks must actually slash the equivocators."""
        from ..pool import AdmissionEngine, OperationPool
        from ..serving import HeadStore

        out: dict = {"schedule": len(eq_schedule)}
        if final_state is None or not eq_schedule:
            out.update(ok=False, error="no completed cycle / empty schedule")
            return out

        live_roots = sorted(
            bytes(type(s).hash_tree_root(s)).hex()
            for s in eq_pool.attester_slashings()
        )
        live_digest = eq_pool.vote_ledger_digest()

        refeed_store = HeadStore()
        refeed_store.publish(final_state.copy(), ctx)
        refeed_pool = OperationPool()
        refeed_engine = AdmissionEngine(refeed_pool, refeed_store, ctx,
                                        window_size=8)
        refeed_engine.admit_attestation_batch(
            [a.copy() for a in eq_schedule]
        )
        refeed_engine.settle()
        refeed_roots = sorted(
            bytes(type(s).hash_tree_root(s)).hex()
            for s in refeed_pool.attester_slashings()
        )
        ledger_identical = (
            live_roots == refeed_roots
            and live_digest == refeed_pool.vote_ledger_digest()
        )

        # the surfaced slashings EXECUTE in soak-produced blocks: drain
        # the live pool block by block on top of the committed head and
        # apply each produced block through the full sequential path.
        # The feed keeps the double and surround intersections DISJOINT
        # (distinct epoch-E slots partition the active set), so drain
        # order cannot leave either slashing without a slashable index.
        surfaced = eq_pool.attester_slashings()
        surround_surfaced = any(
            int(s.attestation_1.data.target.epoch)
            != int(s.attestation_2.data.target.epoch)
            for s in surfaced
        )
        drain_ex = Executor(final_state.copy(), ctx)
        drain_store = HeadStore()
        packed: list = []
        produced_blocks = 0
        error = None

        def extras(state, slot, context):
            fork = cu.full_upgrade_fork_at_slot(int(slot), context)
            body: dict = {}
            if fork not in ("phase0", "altair"):
                body["execution_payload"] = cu.make_execution_payload_fork(
                    fork, state, context, block_number=int(slot)
                )
            if fork != "phase0":
                body["sync_aggregate"] = cu.make_sync_aggregate(
                    state, context
                )
            return body

        try:
            while eq_pool.attester_slashings() and produced_blocks < 4:
                snap = drain_store.publish(drain_ex.state.copy(), ctx)
                produced = produce_block(
                    snap, eq_pool, ctx, randao=cu.make_randao_reveal,
                    sign=cu.sign_block, body_extras=extras,
                )
                produced_blocks += 1
                packed.extend(produced.message.body.attester_slashings)
                drain_ex.apply_block(produced)
                eq_pool.prune_included(produced.message.body)
        except Exception as exc:  # noqa: BLE001 — the gate reports, never hides
            error = f"{type(exc).__name__}: {str(exc)[:200]}"
        final_raw = getattr(drain_ex.state, "data", drain_ex.state)
        slashed = {
            i for i, v in enumerate(final_raw.validators) if bool(v.slashed)
        }
        expected_slashed: set = set()
        surround_packed = False
        for slashing in packed:
            expected_slashed |= set(
                int(i) for i in slashing.attestation_1.attesting_indices
            ) & set(int(i) for i in slashing.attestation_2.attesting_indices)
            if int(slashing.attestation_1.data.target.epoch) != int(
                slashing.attestation_2.data.target.epoch
            ):
                surround_packed = True
        executed = bool(
            packed
            and expected_slashed
            and expected_slashed <= slashed
            and (surround_packed or not surround_surfaced)
        )
        out.update(
            ledger_identical=bool(ledger_identical),
            slashings_surfaced=len(live_roots),
            surround_surfaced=bool(surround_surfaced),
            surround_packed=bool(surround_packed),
            slashings_packed=len(packed),
            produced_blocks=produced_blocks,
            equivocators=sorted(expected_slashed),
            equivocators_slashed=bool(executed),
            error=error,
            ok=bool(ledger_identical and executed and error is None),
        )
        return out


def run_soak(config: "SoakConfig | None" = None) -> dict:
    """One full soak; returns the report (``report["ok"]`` folds the
    three gates — docs/SOAK.md)."""
    return SoakRunner(config).run()
