"""Columnar resolution for the Beacon-API read plane (docs/SERVING.md).

Every batched registry read an endpoint serves reduces to the same
shape: resolve the request's validator indices, perform ONE vectorized
gather over the snapshot's frozen column bundle (``ops_vector.
gather_rows`` — numpy fancy-index, no per-validator Python), apply any
status filter as a vectorized mask, and only then assemble the JSON
rows for the (already narrowed) result set. The scalar twin of every
computation lives in ``serving/oracle.py`` and is both the fallback
(no numpy / exotic values) and the differential oracle
(tests/test_serving.py asserts bit-identical documents).

Status taxonomy: the standard Beacon-API validator status machine
(api/types.py ``ValidatorStatus``), computed once per snapshot as a
uint8 code column over the whole registry — after that, a request's
status is one gathered byte.
"""

from __future__ import annotations

import time

from ..models import ops_vector
from ..primitives import FAR_FUTURE_EPOCH
from ..telemetry import metrics as _metrics
from ..utils import trace

__all__ = [
    "STATUS_NAMES",
    "STATUS_AGGREGATES",
    "status_code_column",
    "snapshot_bundle",
    "parse_statuses",
    "gather",
    "resolve_validators",
    "rewards_summary_columnar",
]

# index-aligned with the code column below; the order encodes the
# precedence of the standard status machine (oracle.validator_status is
# the scalar twin — keep them in lockstep)
STATUS_NAMES = (
    "pending_initialized",   # 0
    "pending_queued",        # 1
    "active_ongoing",        # 2
    "active_exiting",        # 3
    "active_slashed",        # 4
    "exited_unslashed",      # 5
    "exited_slashed",        # 6
    "withdrawal_possible",   # 7
    "withdrawal_done",       # 8
)

# the aggregate filter classes the ?status= parameter also accepts
STATUS_AGGREGATES = {
    "pending": (0, 1),
    "active": (2, 3, 4),
    "exited": (5, 6),
    "withdrawal": (7, 8),
}


def _np():
    return ops_vector._np()


def status_code_column(bundle: dict, epoch: int):
    """uint8 status codes over the whole registry, vectorized — the
    scalar twin is ``oracle.validator_status`` (differentially tested)."""
    np = _np()
    far = np.uint64(FAR_FUTURE_EPOCH)
    e = np.uint64(epoch)
    act = bundle["activation_epoch"]
    ex = bundle["exit_epoch"]
    wd = bundle["withdrawable_epoch"]
    elig = bundle["activation_eligibility_epoch"]
    slashed = bundle["slashed"]
    bal = bundle["balances"]
    codes = np.zeros(act.shape[0], dtype=np.uint8)
    pending = e < act
    active = (act <= e) & (e < ex)
    exited = (ex <= e) & (e < wd)
    withdrawable = wd <= e
    codes[pending] = np.where(
        elig[pending] == far, np.uint8(0), np.uint8(1)
    )
    codes[active] = np.where(
        slashed[active],
        np.uint8(4),
        np.where(ex[active] != far, np.uint8(3), np.uint8(2)),
    )
    codes[exited] = np.where(slashed[exited], np.uint8(6), np.uint8(5))
    codes[withdrawable] = np.where(
        bal[withdrawable] != 0, np.uint8(7), np.uint8(8)
    )
    return codes


def snapshot_bundle(snapshot) -> "dict | None":
    """The snapshot's frozen column bundle extended (once, memoized on
    the snapshot) with the status-code column at the snapshot's current
    epoch. None → scalar fallback."""
    base = snapshot.bundle()
    if base is None:
        return None

    def build():
        epoch = int(snapshot.raw.slot) // int(
            snapshot.context.SLOTS_PER_EPOCH
        )
        out = dict(base)
        out["status_codes"] = status_code_column(base, epoch)
        out["epoch"] = epoch
        return out

    return snapshot.memo(("bundle+status",), build)


def parse_statuses(raw_statuses) -> "set[int] | None":
    """?status= values → allowed status-code set (None = no filter).
    Raises ValueError on an unknown status name (the handler's 400)."""
    if not raw_statuses:
        return None
    allowed: set = set()
    for name in raw_statuses:
        if name in STATUS_AGGREGATES:
            allowed.update(STATUS_AGGREGATES[name])
        elif name in STATUS_NAMES:
            allowed.add(STATUS_NAMES.index(name))
        else:
            raise ValueError(f"unknown validator status {name!r}")
    return allowed


def gather(bundle: dict, indices, fields):
    """The data plane's one-columnar-gather-per-batch unit: a single
    ``ops_vector.gather_rows`` pass over the requested fields, counted
    (``serving.gathers``) and timed (``serving.gather_s``) so the bench
    can assert exactly one per batched read. Under tracing the gather
    runs in its own span and the observation carries its trace_id, so
    the p99 ``serving.gather_s`` gate can exemplar the tail request."""
    t0 = time.perf_counter()
    with trace.span("serving.gather", rows=len(indices)):
        out = ops_vector.gather_rows(bundle, indices, fields)
        ctx = trace.context()
    _metrics.counter("serving.gathers").inc()
    _metrics.histogram("serving.gather_s").observe(
        time.perf_counter() - t0,
        trace_id=ctx.trace_id if ctx is not None else None,
    )
    return out


def resolve_validators(bundle: dict, indices, allowed_codes=None):
    """(kept_indices, balances, codes) for the requested registry rows:
    one gather + one vectorized status mask. ``indices`` None means the
    whole registry (no fancy-index needed — still one logical gather).
    The returned arrays are position-aligned and owned by the caller."""
    np = _np()
    if indices is None:
        idx = np.arange(bundle["balances"].shape[0], dtype=np.int64)
        balances = bundle["balances"]
        codes = bundle["status_codes"]
        _metrics.counter("serving.gathers").inc()
    else:
        idx = np.asarray(indices, dtype=np.int64)
        rows = gather(bundle, idx, ("balances", "status_codes"))
        balances = rows["balances"]
        codes = rows["status_codes"]
    if allowed_codes is not None:
        mask = np.isin(codes, np.asarray(sorted(allowed_codes), np.uint8))
        idx, balances, codes = idx[mask], balances[mask], codes[mask]
    return idx, balances, codes


def rewards_summary_columnar(snapshot) -> "dict | None":
    """The epoch-rewards summary from one ``pack_registry_cached`` pass:
    previous-epoch participation flag balances as vectorized mask sums.
    None → scalar fallback (phase0 or columns unavailable); the scalar
    twin is ``oracle.rewards_summary_data``."""
    np = _np()
    state = snapshot.raw
    context = snapshot.context
    if np is None or ops_vector._disabled():
        return None
    if getattr(state, "previous_epoch_participation", None) is None:
        return None  # phase0: no participation flags to summarize
    current_epoch = int(state.slot) // int(context.SLOTS_PER_EPOCH)
    previous_epoch = max(0, current_epoch - 1)
    packed = ops_vector.pack_registry_cached(state, previous_epoch)
    eff = packed["effective_balance"]
    if not isinstance(eff, np.ndarray):
        return None  # the cached pack degraded to a scalar shape
    increment = int(context.EFFECTIVE_BALANCE_INCREMENT)
    active = packed["active_previous"]
    unslashed = active & ~packed["slashed"]
    participation = packed["previous_participation"]

    def total(mask) -> int:
        # u64 sum is exact while total stake < 2^64 gwei (mainnet is
        # ~2^55); the scalar oracle computes the same python int
        return max(increment, int(eff[mask].sum(dtype=np.uint64)))

    from ..models.altair.constants import (
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_SOURCE_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
    )
    from ..models.altair.helpers import get_base_reward_per_increment

    flags = {}
    for name, flag_index in (
        ("timely_source", TIMELY_SOURCE_FLAG_INDEX),
        ("timely_target", TIMELY_TARGET_FLAG_INDEX),
        ("timely_head", TIMELY_HEAD_FLAG_INDEX),
    ):
        has = (participation & np.uint8(1 << flag_index)) != 0
        flags[name] = str(total(unslashed & has))
    return {
        "epoch": str(previous_epoch),
        "active_validators": str(int(active.sum())),
        "eligible_validators": str(int(packed["eligible"].sum())),
        "total_active_balance": str(total(active)),
        "base_reward_per_increment": str(
            int(get_base_reward_per_increment(state, context))
        ),
        "participation": flags,
    }
