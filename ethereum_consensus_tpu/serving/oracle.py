"""Scalar oracle for the Beacon-API read plane (docs/SERVING.md).

Pure per-validator Python over the SSZ containers — no numpy, no column
caches. Every function here produces the EXACT document its columnar
twin in ``serving/views.py``/``serving/handlers.py`` serves; the
differential tests (tests/test_serving.py) and the ``serving_queries``
bench both diff the two byte-for-byte. It is also the live fallback
when the columnar engine is unavailable (``serving.fallback`` counts).

Committees, duties, and sync committees have no columnar twin — the
spec helpers (cached shuffles, proposer sampling) ARE the single
implementation — so this module is their one source of truth too; the
handlers call straight in here for those documents.
"""

from __future__ import annotations

from hashlib import sha256

from ..domains import DomainType
from ..models.altair.block_processing import _registry_pubkey_index
from ..models.phase0 import helpers as h
from ..primitives import FAR_FUTURE_EPOCH

__all__ = [
    "validator_status",
    "validator_row",
    "validators_data",
    "balances_data",
    "committees_data",
    "sync_committees_data",
    "attester_duty_map",
    "attester_duties_data",
    "proposer_duties_data",
    "rewards_summary_data",
    "resolve_validator_ids",
]

# spec constant (not in the preset tables): sync committee subnets
SYNC_COMMITTEE_SUBNET_COUNT = 4


class BadRequest(ValueError):
    """Maps to HTTP 400 in the handler layer."""


def validator_status(validator, balance: int, epoch: int) -> str:
    """The standard Beacon-API status machine — the scalar twin of
    ``views.status_code_column`` (kept in lockstep, differentially
    tested)."""
    activation = int(validator.activation_epoch)
    exit_epoch = int(validator.exit_epoch)
    if epoch < activation:
        if int(validator.activation_eligibility_epoch) == FAR_FUTURE_EPOCH:
            return "pending_initialized"
        return "pending_queued"
    if epoch < exit_epoch:
        if bool(validator.slashed):
            return "active_slashed"
        if exit_epoch != FAR_FUTURE_EPOCH:
            return "active_exiting"
        return "active_ongoing"
    if epoch < int(validator.withdrawable_epoch):
        return "exited_slashed" if bool(validator.slashed) else "exited_unslashed"
    return "withdrawal_possible" if int(balance) != 0 else "withdrawal_done"


def validator_row(state, index: int, epoch: int, status=None) -> dict:
    """One wire row of the validators endpoint. The ``validator`` object
    is the container's own JSON codec — both paths emit it, so the row
    is identical columnar or scalar by construction except for
    balance/status, which the tests diff."""
    validator = state.validators[index]
    balance = int(state.balances[index])
    return {
        "index": str(index),
        "balance": str(balance),
        "status": (
            status
            if status is not None
            else validator_status(validator, balance, epoch)
        ),
        "validator": type(validator).to_json(validator),
    }


def current_epoch(state, context) -> int:
    return int(state.slot) // int(context.SLOTS_PER_EPOCH)


def resolve_validator_ids(state, ids) -> "list[int]":
    """``?id=`` values (decimal indices and/or 0x-pubkeys) → registry
    indices. Unknown pubkeys and out-of-range indices are dropped (the
    standard list-endpoint behavior); a malformed value raises
    ``BadRequest``. Order and duplicates are preserved — the response
    mirrors the request."""
    n = len(state.validators)
    out: list = []
    pubkey_index = None
    for value in ids:
        value = value.strip()
        if value.startswith("0x"):
            try:
                key = bytes.fromhex(value[2:])
            except ValueError:
                raise BadRequest(f"malformed validator id {value!r}") from None
            if len(key) != 48:
                raise BadRequest(f"validator pubkey must be 48 bytes: {value!r}")
            if pubkey_index is None:
                pubkey_index = _registry_pubkey_index(state)
            hit = pubkey_index.get(key)
            if hit is not None:
                out.append(hit)
        elif value.isdigit():
            index = int(value)
            if index < n:
                out.append(index)
        else:
            raise BadRequest(f"malformed validator id {value!r}")
    return out


def validators_data(state, context, indices=None, statuses=None) -> list:
    """The scalar validators document: a full per-validator walk —
    exactly the cost model the columnar gather replaces (and the bench's
    ≥10× comparison baseline)."""
    epoch = current_epoch(state, context)
    rows = []
    index_iter = (
        range(len(state.validators)) if indices is None else indices
    )
    for index in index_iter:
        validator = state.validators[index]
        balance = int(state.balances[index])
        status = validator_status(validator, balance, epoch)
        if statuses is not None and status not in statuses:
            continue
        rows.append(
            {
                "index": str(index),
                "balance": str(balance),
                "status": status,
                "validator": type(validator).to_json(validator),
            }
        )
    return rows


def balances_data(state, indices=None) -> list:
    index_iter = (
        range(len(state.balances)) if indices is None else indices
    )
    return [
        {"index": str(index), "balance": str(int(state.balances[index]))}
        for index in index_iter
    ]


def _validate_epoch_window(state, context, epoch: int, what: str) -> None:
    cur = current_epoch(state, context)
    if not (max(0, cur - 1) <= epoch <= cur + 1):
        raise BadRequest(
            f"{what} epoch {epoch} outside the served window "
            f"[{max(0, cur - 1)}, {cur + 1}] of the state at slot "
            f"{int(state.slot)}"
        )


def committees_data(state, context, epoch=None, index=None, slot=None) -> list:
    """Every (slot, committee) row of ``epoch`` (default: the state's
    current epoch), optionally narrowed by ``?index=``/``?slot=`` — the
    spec committee machinery (cached shuffles) is the single source."""
    spe = int(context.SLOTS_PER_EPOCH)
    if slot is not None and epoch is not None and slot // spe != epoch:
        raise BadRequest(f"slot {slot} is not in epoch {epoch}")
    if epoch is None:
        epoch = (
            slot // spe if slot is not None else current_epoch(state, context)
        )
    _validate_epoch_window(state, context, epoch, "committees")
    slots = (slot,) if slot is not None else range(epoch * spe, (epoch + 1) * spe)
    per_slot = h.get_committee_count_per_slot(state, epoch, context)
    if index is not None and index >= per_slot:
        raise BadRequest(
            f"committee index {index} out of range ({per_slot} per slot)"
        )
    rows = []
    for s in slots:
        for committee_index in (index,) if index is not None else range(per_slot):
            committee = h.get_beacon_committee(state, s, committee_index, context)
            rows.append(
                {
                    "index": str(committee_index),
                    "slot": str(s),
                    "validators": [str(v) for v in committee],
                }
            )
    return rows


def sync_committees_data(state, context, epoch=None) -> dict:
    """current/next sync committee pubkeys resolved to registry indices
    (plus the per-subnet aggregates). 400 outside the two stored
    periods or on a pre-altair state."""
    committee = getattr(state, "current_sync_committee", None)
    if committee is None:
        raise BadRequest("state has no sync committees (phase0)")
    period_epochs = int(context.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    cur = current_epoch(state, context)
    if epoch is not None:
        delta = epoch // period_epochs - cur // period_epochs
        if delta == 1:
            committee = state.next_sync_committee
        elif delta != 0:
            raise BadRequest(
                f"epoch {epoch} outside the stored sync-committee periods "
                f"of the state at epoch {cur}"
            )
    pubkey_index = _registry_pubkey_index(state)
    indices = []
    for key in committee.public_keys:
        hit = pubkey_index.get(bytes(key))
        if hit is None:  # impossible for a spec-built committee
            raise BadRequest("sync committee member not in the registry")
        indices.append(hit)
    per_subnet = max(1, len(indices) // SYNC_COMMITTEE_SUBNET_COUNT)
    return {
        "validators": [str(i) for i in indices],
        "validator_aggregates": [
            [str(i) for i in indices[at : at + per_subnet]]
            for at in range(0, len(indices), per_subnet)
        ],
    }


def attester_duty_map(state, context, epoch: int) -> dict:
    """validator index → (slot, committee_index, committee_length,
    committees_at_slot, position) over every committee of ``epoch`` —
    built once per (snapshot, epoch), then a duties request is one dict
    lookup per requested validator."""
    _validate_epoch_window(state, context, epoch, "attester duties")
    spe = int(context.SLOTS_PER_EPOCH)
    per_slot = h.get_committee_count_per_slot(state, epoch, context)
    duty_map: dict = {}
    for s in range(epoch * spe, (epoch + 1) * spe):
        for committee_index in range(per_slot):
            committee = h.get_beacon_committee(state, s, committee_index, context)
            length = len(committee)
            for position, validator in enumerate(committee):
                duty_map[validator] = (
                    s, committee_index, length, per_slot, position,
                )
    return duty_map


def attester_duties_data(state, duty_map: dict, indices) -> list:
    rows = []
    for index in indices:
        duty = duty_map.get(index)
        if duty is None:  # not active in the epoch: omitted, per spec
            continue
        slot, committee_index, length, per_slot, position = duty
        rows.append(
            {
                "pubkey": "0x" + bytes(
                    state.validators[index].public_key
                ).hex(),
                "validator_index": str(index),
                "committee_index": str(committee_index),
                "committee_length": str(length),
                "committees_at_slot": str(per_slot),
                "validator_committee_index": str(position),
                "slot": str(slot),
            }
        )
    return rows


def proposer_duties_data(state, context, epoch: int) -> list:
    """One proposer per slot of ``epoch`` — the spec sampling
    (``compute_proposer_index``) with the per-slot seed derived exactly
    as ``get_beacon_proposer_index`` derives it, without mutating the
    snapshot's slot."""
    cur = current_epoch(state, context)
    if epoch != cur:
        raise BadRequest(
            f"proposer duties are served for the state's current epoch "
            f"{cur} only (requested {epoch})"
        )
    spe = int(context.SLOTS_PER_EPOCH)
    indices = list(h.get_active_validator_indices(state, epoch))
    seed_base = h.get_seed(state, epoch, DomainType.BEACON_PROPOSER, context)
    rows = []
    for s in range(epoch * spe, (epoch + 1) * spe):
        seed = sha256(seed_base + s.to_bytes(8, "little")).digest()
        proposer = h.compute_proposer_index(state, indices, seed, context)
        rows.append(
            {
                "pubkey": "0x" + bytes(
                    state.validators[proposer].public_key
                ).hex(),
                "validator_index": str(proposer),
                "slot": str(s),
            }
        )
    return rows


def head_block_root(state) -> bytes:
    """The head BLOCK's hash_tree_root derived from the state alone:
    ``latest_block_header`` with its ``state_root`` filled the way
    ``process_slot`` fills it. Identical to the pipeline's claimed block
    root for the same head (test-asserted), so pipeline-less publishes
    index the same way."""
    from ..models.phase0.containers import BeaconBlockHeader

    header = state.latest_block_header.copy()
    if bytes(header.state_root) == b"\x00" * 32:
        header.state_root = type(state).hash_tree_root(state)
    return BeaconBlockHeader.hash_tree_root(header)


def dependent_root(state, context, epoch: int, duty: str,
                   head_root: "bytes | None" = None) -> bytes:
    """The REAL ``dependent_root`` of a duties response (PR 8 residue —
    this used to be a state-root placeholder): the block root the duty
    assignment is derived from, i.e. the last block before the epoch the
    shuffling seed reads.

    * proposer duties for ``epoch`` → block root at
      ``start_slot(epoch) - 1``;
    * attester duties for ``epoch`` → block root at
      ``start_slot(epoch - 1) - 1``;
    * a dependent slot before genesis → the genesis block root; at or
      past the state's slot → the head block root (``head_root`` when
      the caller has the pipeline's claimed one, else derived)."""
    spe = int(context.SLOTS_PER_EPOCH)
    if duty == "proposer":
        dep_slot = epoch * spe - 1
    else:
        dep_slot = max(0, epoch - 1) * spe - 1
    if 0 <= dep_slot < int(state.slot):
        return h.get_block_root_at_slot(state, dep_slot)
    if head_root is not None:
        return bytes(head_root)
    return head_block_root(state)


def rewards_summary_data(state, context) -> dict:
    """Scalar twin of ``views.rewards_summary_columnar`` — exact python
    ints over the literal containers."""
    from ..models.altair.constants import (
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_SOURCE_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
    )
    from ..models.altair.helpers import get_base_reward_per_increment

    participation = getattr(state, "previous_epoch_participation", None)
    if participation is None:
        raise BadRequest("state has no participation flags (phase0)")
    cur = current_epoch(state, context)
    previous_epoch = max(0, cur - 1)
    increment = int(context.EFFECTIVE_BALANCE_INCREMENT)
    active_count = eligible_count = 0
    active_balance = 0
    flag_balances = {"timely_source": 0, "timely_target": 0, "timely_head": 0}
    flag_bits = (
        ("timely_source", 1 << TIMELY_SOURCE_FLAG_INDEX),
        ("timely_target", 1 << TIMELY_TARGET_FLAG_INDEX),
        ("timely_head", 1 << TIMELY_HEAD_FLAG_INDEX),
    )
    for index, validator in enumerate(state.validators):
        active = h.is_active_validator(validator, previous_epoch)
        slashed = bool(validator.slashed)
        if active:
            active_count += 1
            active_balance += int(validator.effective_balance)
        if active or (
            slashed and previous_epoch + 1 < int(validator.withdrawable_epoch)
        ):
            eligible_count += 1
        if active and not slashed:
            flags = int(participation[index])
            for name, bit in flag_bits:
                if flags & bit:
                    flag_balances[name] += int(validator.effective_balance)
    return {
        "epoch": str(previous_epoch),
        "active_validators": str(active_count),
        "eligible_validators": str(eligible_count),
        "total_active_balance": str(max(increment, active_balance)),
        "base_reward_per_increment": str(
            int(get_base_reward_per_increment(state, context))
        ),
        "participation": {
            name: str(max(increment, balance))
            for name, balance in flag_balances.items()
        },
    }
