"""Beacon-API read handlers — the data plane mounted on the PR 7
introspection server (docs/SERVING.md).

``BeaconDataPlane`` is a tiny WSGI-shaped app the telemetry server
routes ``/eth/...`` requests into: ``handle(method, path, params, body)
→ (status, document)``. Every request resolves exactly ONE ``HeadStore``
snapshot at entry and serves entirely from it — the snapshot-isolation
contract the reader-chaos scenario hammers — and every batched registry
read is one columnar gather (``serving/views.py``) with the scalar
oracle (``serving/oracle.py``) as fallback and differential twin.

Wire format: the standard Beacon-API envelopes (``data`` payloads,
string-encoded integers, 0x-hex bytes), chosen so the repo's own
``api/client.py`` round-trips every endpoint; responses additionally
carry ``snapshot_root`` (the served snapshot's state root) so a chaos
reader can pin each response to the exact committed state it came from.

Endpoint catalog: see ``ROUTES`` below / docs/SERVING.md.
"""

from __future__ import annotations

import time

from ..telemetry import metrics as _metrics
from . import oracle, views

__all__ = ["BeaconDataPlane"]


def _error(status: int, message: str):
    _metrics.counter("serving.errors").inc()
    return status, {"code": status, "message": message}


class BeaconDataPlane:
    """The mountable read plane over a ``HeadStore``.

    Stateless beyond the store reference: all request-scoped work lives
    on the resolved snapshot (bundle, memoized documents), so concurrent
    handler threads share nothing mutable here — speclint's concurrency
    scope covers the module to keep it that way."""

    prefix = "/eth/"

    ROUTES = (
        "GET  /eth/v1/beacon/genesis",
        "GET  /eth/v1/beacon/states/{state_id}/root",
        "GET  /eth/v1/beacon/states/{state_id}/fork",
        "GET  /eth/v1/beacon/states/{state_id}/finality_checkpoints",
        "GET  /eth/v1/beacon/states/{state_id}/randao?epoch=",
        "GET  /eth/v1/beacon/states/{state_id}/validators?id=&status=",
        "GET  /eth/v1/beacon/states/{state_id}/validators/{validator_id}",
        "GET  /eth/v1/beacon/states/{state_id}/validator_balances?id=",
        "GET  /eth/v1/beacon/states/{state_id}/committees?epoch=&index=&slot=",
        "GET  /eth/v1/beacon/states/{state_id}/sync_committees?epoch=",
        "GET  /eth/v1/beacon/states/{state_id}/epoch_rewards",
        "GET  /eth/v1/beacon/states/{state_id}/proof?gindex=",
        "GET  /eth/v1/beacon/light_client/bootstrap/{block_root}",
        "GET  /eth/v1/beacon/light_client/updates?start_period=&count=",
        "GET  /eth/v1/beacon/light_client/finality_update",
        "GET  /eth/v1/beacon/light_client/optimistic_update",
        "POST /eth/v1/validator/duties/attester/{epoch}",
        "GET  /eth/v1/validator/duties/proposer/{epoch}",
    )

    def __init__(self, store):
        self.store = store

    # -- plumbing ------------------------------------------------------------
    def _param(self, params: dict, key: str):
        values = params.get(key)
        return values[0] if values else None

    def _list_param(self, params: dict, key: str) -> list:
        out: list = []
        for chunk in params.get(key, ()):
            out.extend(v for v in chunk.split(",") if v)
        return out

    def _resolve(self, state_id: str):
        snap = self.store.resolve(state_id)
        if snap is None:
            raise _NotFound(
                f"state {state_id!r} is not retained "
                f"({len(self.store)} snapshots held)"
            )
        return snap

    def _envelope(self, snap, data, extra=None) -> dict:
        doc = {
            "execution_optimistic": False,
            "finalized": False,
            "snapshot_root": snap.root_hex(),
            "data": data,
        }
        if extra:
            doc.update(extra)
        return doc

    # -- dispatch ------------------------------------------------------------
    def handle(self, method: str, path: str, params: dict, body):
        """(status, JSON document) for one request; never raises — the
        server thread must always get a response to write."""
        t0 = time.perf_counter()
        route = "?"
        try:
            route, response = self._dispatch(method, path, params, body)
            return response
        except _NotFound as exc:
            return _error(404, str(exc))
        except (oracle.BadRequest, ValueError) as exc:
            return _error(400, str(exc))
        except Exception as exc:  # noqa: BLE001 — a reader must get a reply
            return _error(500, f"{type(exc).__name__}: {exc}")
        finally:
            _metrics.counter("serving.requests").inc()
            _metrics.counter(f"serving.requests.{route}").inc()
            _metrics.histogram("serving.request_s").observe(
                time.perf_counter() - t0
            )

    def _dispatch(self, method: str, path: str, params: dict, body):
        parts = [p for p in path.split("/") if p]
        # parts[0] == "eth" guaranteed by the mount prefix
        if parts[1:3] == ["v1", "beacon"]:
            if parts[3:] == ["genesis"] and method == "GET":
                return "genesis", self._genesis()
            if len(parts) >= 6 and parts[3] == "states":
                return self._dispatch_state(method, parts[4], parts[5:], params)
            if parts[3] == "light_client" and method == "GET":
                return self._dispatch_light_client(parts[4:], params)
        if parts[1:4] == ["v1", "validator", "duties"] and len(parts) == 6:
            if parts[4] == "attester" and method == "POST":
                return "duties_attester", self._attester_duties(
                    int(parts[5]), body
                )
            if parts[4] == "proposer" and method == "GET":
                return "duties_proposer", self._proposer_duties(int(parts[5]))
        raise _NotFound(f"no data-plane route {method} {path}")

    def _dispatch_state(self, method, state_id, rest, params):
        if method != "GET":
            raise _NotFound(f"no data-plane route {method} for states")
        if rest == ["root"]:
            return "root", self._root(state_id)
        if rest == ["fork"]:
            return "fork", self._fork(state_id)
        if rest == ["finality_checkpoints"]:
            return "finality", self._finality(state_id)
        if rest == ["randao"]:
            return "randao", self._randao(state_id, params)
        if rest == ["validators"]:
            return "validators", self._validators(state_id, params)
        if len(rest) == 2 and rest[0] == "validators":
            return "validator", self._one_validator(state_id, rest[1])
        if rest == ["validator_balances"]:
            return "balances", self._balances(state_id, params)
        if rest == ["committees"]:
            return "committees", self._committees(state_id, params)
        if rest == ["sync_committees"]:
            return "sync_committees", self._sync_committees(state_id, params)
        if rest == ["epoch_rewards"]:
            return "rewards", self._epoch_rewards(state_id)
        if rest == ["proof"]:
            return "proof", self._state_proof(state_id, params)
        raise _NotFound(f"no data-plane route GET states/{'/'.join(rest)}")

    def _dispatch_light_client(self, rest, params):
        if len(rest) == 2 and rest[0] == "bootstrap":
            return "lc_bootstrap", self._lc_bootstrap(rest[1])
        if rest == ["updates"]:
            return "lc_updates", self._lc_updates(params)
        if rest == ["finality_update"]:
            return "lc_finality", self._lc_finality_update()
        if rest == ["optimistic_update"]:
            return "lc_optimistic", self._lc_optimistic_update()
        raise _NotFound(
            f"no data-plane route GET light_client/{'/'.join(rest)}"
        )

    # -- scalar-metadata endpoints -------------------------------------------
    def _genesis(self):
        snap = self._resolve("head")
        state = snap.raw
        return 200, self._envelope(
            snap,
            {
                "genesis_time": str(int(state.genesis_time)),
                "genesis_validators_root": "0x"
                + bytes(state.genesis_validators_root).hex(),
                "genesis_fork_version": "0x"
                + bytes(snap.context.genesis_fork_version).hex(),
            },
        )

    def _root(self, state_id):
        snap = self._resolve(state_id)
        return 200, self._envelope(snap, {"root": snap.root_hex()})

    def _fork(self, state_id):
        snap = self._resolve(state_id)
        fork = snap.raw.fork
        return 200, self._envelope(snap, type(fork).to_json(fork))

    def _finality(self, state_id):
        snap = self._resolve(state_id)
        state = snap.raw
        return 200, self._envelope(
            snap,
            {
                name: type(cp).to_json(cp)
                for name, cp in (
                    ("previous_justified", state.previous_justified_checkpoint),
                    ("current_justified", state.current_justified_checkpoint),
                    ("finalized", state.finalized_checkpoint),
                )
            },
        )

    def _randao(self, state_id, params):
        snap = self._resolve(state_id)
        from ..models.phase0.helpers import get_randao_mix

        epoch_raw = self._param(params, "epoch")
        epoch = (
            int(epoch_raw)
            if epoch_raw is not None
            else oracle.current_epoch(snap.raw, snap.context)
        )
        mix = snap.memo(
            ("randao", epoch), lambda: get_randao_mix(snap.raw, epoch)
        )
        return 200, self._envelope(snap, {"randao": "0x" + bytes(mix).hex()})

    # -- columnar registry endpoints -----------------------------------------
    def _validators(self, state_id, params):
        snap = self._resolve(state_id)
        ids = self._list_param(params, "id")
        statuses = self._list_param(params, "status")
        allowed = views.parse_statuses(statuses)
        indices = (
            oracle.resolve_validator_ids(snap.raw, ids) if ids else None
        )
        bundle = views.snapshot_bundle(snap)
        if bundle is None:
            _metrics.counter("serving.fallback").inc()
            rows = oracle.validators_data(
                snap.raw,
                snap.context,
                indices,
                None
                if allowed is None
                else {views.STATUS_NAMES[c] for c in allowed},
            )
        else:
            idx, balances, codes = views.resolve_validators(
                bundle, indices, allowed
            )
            vals = snap.raw.validators
            rows = [
                {
                    "index": str(i),
                    "balance": str(int(b)),
                    "status": views.STATUS_NAMES[c],
                    "validator": type(vals[i]).to_json(vals[i]),
                }
                for i, b, c in zip(idx.tolist(), balances.tolist(), codes.tolist())
            ]
        return 200, self._envelope(snap, rows)

    def _one_validator(self, state_id, validator_id):
        snap = self._resolve(state_id)
        indices = oracle.resolve_validator_ids(snap.raw, [validator_id])
        if not indices:
            raise _NotFound(f"validator {validator_id!r} not found")
        index = indices[0]
        bundle = views.snapshot_bundle(snap)
        if bundle is None:
            _metrics.counter("serving.fallback").inc()
            row = oracle.validators_data(snap.raw, snap.context, [index])[0]
        else:
            idx, balances, codes = views.resolve_validators(bundle, [index])
            validator = snap.raw.validators[index]
            row = {
                "index": str(index),
                "balance": str(int(balances[0])),
                "status": views.STATUS_NAMES[int(codes[0])],
                "validator": type(validator).to_json(validator),
            }
        return 200, self._envelope(snap, row)

    def _balances(self, state_id, params):
        snap = self._resolve(state_id)
        ids = self._list_param(params, "id")
        indices = (
            oracle.resolve_validator_ids(snap.raw, ids) if ids else None
        )
        bundle = views.snapshot_bundle(snap)
        if bundle is None:
            _metrics.counter("serving.fallback").inc()
            rows = oracle.balances_data(snap.raw, indices)
        else:
            if indices is None:
                balances = bundle["balances"]
                index_list = range(balances.shape[0])
                _metrics.counter("serving.gathers").inc()
            else:
                gathered = views.gather(bundle, indices, ("balances",))
                balances = gathered["balances"]
                index_list = indices
            rows = [
                {"index": str(i), "balance": str(int(b))}
                for i, b in zip(index_list, balances.tolist())
            ]
        return 200, self._envelope(snap, rows)

    # -- committee machinery endpoints ---------------------------------------
    def _committees(self, state_id, params):
        snap = self._resolve(state_id)
        epoch = self._param(params, "epoch")
        index = self._param(params, "index")
        slot = self._param(params, "slot")
        key = ("committees", epoch, index, slot)
        rows = snap.memo(
            key,
            lambda: oracle.committees_data(
                snap.raw,
                snap.context,
                epoch=None if epoch is None else int(epoch),
                index=None if index is None else int(index),
                slot=None if slot is None else int(slot),
            ),
        )
        return 200, self._envelope(snap, rows)

    def _sync_committees(self, state_id, params):
        snap = self._resolve(state_id)
        epoch = self._param(params, "epoch")
        doc = snap.memo(
            ("sync_committees", epoch),
            lambda: oracle.sync_committees_data(
                snap.raw,
                snap.context,
                epoch=None if epoch is None else int(epoch),
            ),
        )
        return 200, self._envelope(snap, doc)

    def _attester_duties(self, epoch: int, body):
        if not isinstance(body, list):
            raise oracle.BadRequest(
                "attester duties take a JSON list of validator indices"
            )
        snap = self._resolve("head")
        indices = oracle.resolve_validator_ids(
            snap.raw, [str(v) for v in body]
        )
        duty_map = snap.memo(
            ("duty_map", epoch),
            lambda: oracle.attester_duty_map(snap.raw, snap.context, epoch),
        )
        rows = oracle.attester_duties_data(snap.raw, duty_map, indices)
        dep = snap.memo(
            ("dependent_root", "attester", epoch),
            lambda: oracle.dependent_root(
                snap.raw, snap.context, epoch, "attester",
                head_root=snap.block_root,
            ),
        )
        return 200, self._envelope(
            snap, rows, extra={"dependent_root": "0x" + dep.hex()}
        )

    def _proposer_duties(self, epoch: int):
        snap = self._resolve("head")
        rows = snap.memo(
            ("proposer_duties", epoch),
            lambda: oracle.proposer_duties_data(snap.raw, snap.context, epoch),
        )
        dep = snap.memo(
            ("dependent_root", "proposer", epoch),
            lambda: oracle.dependent_root(
                snap.raw, snap.context, epoch, "proposer",
                head_root=snap.block_root,
            ),
        )
        return 200, self._envelope(
            snap, rows, extra={"dependent_root": "0x" + dep.hex()}
        )

    def _epoch_rewards(self, state_id):
        snap = self._resolve(state_id)

        def build():
            doc = views.rewards_summary_columnar(snap)
            if doc is None:
                _metrics.counter("serving.fallback").inc()
                doc = oracle.rewards_summary_data(snap.raw, snap.context)
            return doc

        return 200, self._envelope(snap, snap.memo(("rewards",), build))

    # -- proof & light-client plane (docs/PROOFS.md) -------------------------
    def _proof_ctx(self, snap):
        """One warm walker per snapshot: the settle inside ProofContext is
        a no-op once the snapshot's root has been computed, and the memo
        makes every proof request off this snapshot share the lazily
        built layer providers."""
        from ..proofs import ProofContext

        return snap.memo(
            ("proof_ctx",), lambda: ProofContext(type(snap.raw), snap.raw)
        )

    def _state_proof(self, state_id, params):
        snap = self._resolve(state_id)
        raw = self._list_param(params, "gindex")
        if not raw:
            raise oracle.BadRequest("proof requires at least one gindex=")
        try:
            gindices = sorted({int(g) for g in raw})
        except ValueError:
            raise oracle.BadRequest(f"gindex must be integers, got {raw!r}")
        if any(g < 1 for g in gindices):
            raise oracle.BadRequest("gindex must be >= 1")

        # fetched OUTSIDE the proof-document memo below: snap.memo's
        # lock is not reentrant, so the nested ("proof_ctx",) memo must
        # resolve first, not from inside build()
        ctx = self._proof_ctx(snap)

        def build():
            if len(gindices) == 1:
                gi = gindices[0]
                return {
                    "gindex": str(gi),
                    "leaf": "0x" + ctx.node_at(gi).hex(),
                    "proof": ["0x" + node.hex() for node in ctx.proof(gi)],
                }
            from ..proofs import extract_multiproof

            mp = extract_multiproof(ctx, gindices=gindices)
            return {
                "gindices": [str(g) for g in mp.gindices],
                "leaves": ["0x" + leaf.hex() for leaf in mp.leaves],
                "proof": ["0x" + node.hex() for node in mp.proof],
            }

        doc = snap.memo(("proof", tuple(gindices)), build)
        return 200, self._envelope(snap, doc)

    def _lc_bootstrap(self, block_root):
        from ..proofs import light_client as lc

        snap = self._resolve(block_root)
        doc, fork = snap.memo(
            ("lc_bootstrap",), lambda: lc.light_client_bootstrap(snap)
        )
        return 200, self._envelope(
            snap, type(doc).to_json(doc), extra={"version": fork}
        )

    def _lc_updates(self, params):
        from ..proofs import light_client as lc

        start = self._param(params, "start_period")
        count = self._param(params, "count")
        if start is None or count is None:
            raise oracle.BadRequest(
                "updates requires start_period= and count="
            )
        pairs = lc.light_client_updates(self.store, int(start), int(count))
        # spec wire shape: a bare list of {version, data} — no envelope
        return 200, [
            {"version": fork, "data": type(doc).to_json(doc)}
            for doc, fork in pairs
        ]

    def _lc_finality_update(self):
        from ..proofs import light_client as lc

        snap = self._resolve("head")
        try:
            doc, fork = snap.memo(
                ("lc_finality",),
                lambda: lc.light_client_finality_update(self.store, snap),
            )
        except LookupError as exc:
            raise _NotFound(str(exc))
        return 200, self._envelope(
            snap, type(doc).to_json(doc), extra={"version": fork}
        )

    def _lc_optimistic_update(self):
        from ..proofs import light_client as lc

        snap = self._resolve("head")
        try:
            doc, fork = snap.memo(
                ("lc_optimistic",),
                lambda: lc.light_client_optimistic_update(self.store, snap),
            )
        except LookupError as exc:
            raise _NotFound(str(exc))
        return 200, self._envelope(
            snap, type(doc).to_json(doc), extra={"version": fork}
        )


class _NotFound(Exception):
    """Maps to HTTP 404 in ``handle``."""
