"""HeadStore — immutable per-commit snapshots for the Beacon-API read
plane (docs/SERVING.md).

The pipeline engine copies the post-window state at dispatch (while the
live state IS it) and publishes the copy on the commit hook's STATE
channel when the window's verdicts come back clean
(``telemetry/flight.py``). This module is the subscriber: a bounded
history of ``Snapshot`` objects — committed state handle, its
``RegistryColumns`` read-only bundle, slot/root/fork metadata — with
``state_id`` resolution (head / slot / root / finalized / justified).

Isolation contract: a snapshot's state is a structural copy that
NOTHING mutates after publication. The copy-on-write column travel
across ``state.copy()`` (docs/OPS_VECTOR.md) means the columns the live
pipeline keeps warm arrive for free; the first reader-side sync clones
before refreshing any residual dirty rows, so the live state's later
writes can never tear a response — a reader resolves exactly one
snapshot per request and serves entirely from it. Rolled-back states
are structurally unservable: the engine publishes only at commit
boundaries, after the window's signatures proved.

Locking (speclint concurrency + lockorder scope): store mutations hold
``HeadStore._lock``; per-snapshot lazy builds (column bundle, duty
maps, memoized documents) hold ``Snapshot._lock``. Neither lock is ever
held while calling into the other, and resolution returns plain
references, so readers gather lock-free once a bundle exists.
"""

from __future__ import annotations

import threading
import time
import weakref

from ..models import ops_vector
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics

__all__ = ["Snapshot", "HeadStore", "DEFAULT_CAPACITY",
           "registered_stores"]

# every live HeadStore, for the memory observatory's
# ``serving.snapshots`` owner census (telemetry/memory.py): snapshot
# counts + frozen-bundle bytes across the process. WeakValueDictionary
# keyed by id (stores die, the census must not pin them).
_STORES: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def registered_stores() -> list:
    """Live HeadStore instances (census snapshot, GC-safe)."""
    return [s for s in (r() for r in _STORES.valuerefs()) if s is not None]

DEFAULT_CAPACITY = 64

# per-snapshot memoized-document cap: a pathological query mix clears
# the memo rather than growing it without bound (snapshots are already
# bounded by the store's history, this bounds each one's footprint)
_MEMO_CAP = 256


class Snapshot:
    """One committed state frozen for readers.

    ``state`` may be the executor's polymorphic ``BeaconState`` wrapper
    or a bare fork container; ``raw`` is always the container (what the
    spec helpers and the columnar engine take). ``root`` is the state's
    hash_tree_root as bytes — for pipeline-published snapshots it is the
    block's claimed (and stage-A-verified) post-state root, a free field
    read. ``block_root`` is the head BLOCK's hash_tree_root (the
    flight-lineage claimed block root for pipeline publishes, derived
    from ``latest_block_header`` otherwise) — the duties endpoints'
    ``dependent_root`` anchor and the store's block-root index key."""

    __slots__ = (
        "state",
        "raw",
        "context",
        "slot",
        "root",
        "block_root",
        "block",
        "fork",
        "seq",
        "published_at",
        "_lock",
        "_bundle",
        "_bundle_built",
        "_memo",
    )

    def __init__(self, state, context, slot: int, root: bytes, seq=None,
                 block_root: "bytes | None" = None, block=None):
        self.state = state
        self.raw = getattr(state, "data", state)
        self.context = context
        self.slot = int(slot)
        self.root = bytes(root)
        if block_root is None:
            from . import oracle as _oracle

            block_root = _oracle.head_block_root(self.raw)
        self.block_root = bytes(block_root)
        # the committed SignedBeaconBlock (pipeline publishes carry it
        # since the proof plane landed; None for pipeline-less publishes
        # that don't pass one): the light-client endpoints read its
        # sync_aggregate and prove execution_branch over its body
        self.block = block
        version = getattr(state, "version", None)
        self.fork = version().name.lower() if version is not None else None
        self.seq = seq
        self.published_at = time.time()
        self._lock = threading.Lock()
        self._bundle = None
        self._bundle_built = False
        self._memo: dict = {}

    # -- columnar bundle -----------------------------------------------------
    def bundle(self) -> "dict | None":
        """The frozen ``registry_snapshot`` column bundle (read-only
        views), built once under the snapshot lock — the column sync
        machinery mutates list-resident cache records, so the build must
        not race; afterwards readers share the views lock-free. None →
        scalar fallback (no numpy / exotic values / engine off)."""
        if self._bundle_built:  # benign race: build is idempotent
            return self._bundle
        with self._lock:
            if not self._bundle_built:
                cols = ops_vector.columns_for(self.raw)
                self._bundle = (
                    cols.registry_snapshot(self.raw)
                    if cols is not None
                    else None
                )
                self._bundle_built = True
        return self._bundle

    # -- per-snapshot document memo ------------------------------------------
    def memo(self, key, build):
        """Memoize an immutable response document per snapshot (duty
        maps, committee tables, rewards summaries — all pure functions
        of this frozen state). The builder runs under the snapshot lock:
        the spec helpers it calls keep ``state.__dict__`` memo caches
        that must not be rebuilt concurrently."""
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        with self._lock:
            hit = self._memo.get(key)
            if hit is None:
                hit = build()
                if len(self._memo) >= _MEMO_CAP:
                    self._memo = {}
                self._memo[key] = hit
        return hit

    def root_hex(self) -> str:
        return "0x" + self.root.hex()

    def __repr__(self) -> str:
        return (
            f"Snapshot(slot={self.slot}, fork={self.fork}, "
            f"root=0x{self.root.hex()[:12]}…)"
        )


class HeadStore:
    """Bounded history of committed snapshots + ``state_id`` resolution.

    ``attach()`` subscribes the store to the process-wide commit hook's
    state channel — from then on every pipeline commit publishes a new
    head here (and flips the engine's ``state_active`` guard, paying one
    state copy per flush window). ``publish()`` feeds the store directly
    for pipeline-less serving (tests, benches, a warm state put up for
    reads)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = max(1, int(capacity))
        self._history: list = []  # oldest → newest
        self._by_root: dict = {}
        self._by_block_root: dict = {}  # PR 8 residue: the block-root index
        self._attached = False
        _STORES[id(self)] = self  # memory-observatory census membership

    # -- lifecycle -----------------------------------------------------------
    def attach(self) -> "HeadStore":
        with self._lock:
            if not self._attached:
                self._attached = True
                _flight.HOOK.subscribe_states(self.handle_state)
        return self

    def detach(self) -> None:
        with self._lock:
            attached, self._attached = self._attached, False
        if attached:
            _flight.HOOK.unsubscribe_states(self.handle_state)

    def __enter__(self) -> "HeadStore":
        return self.attach()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.detach()

    # -- publication ---------------------------------------------------------
    def handle_state(self, payload: dict) -> None:
        """Commit-hook state-channel subscriber (must never raise into
        the pipeline — the hook counts and swallows if we do)."""
        root = payload["root"]
        block_root = payload.get("block_root")
        if block_root is not None:
            block_root = bytes.fromhex(
                block_root[2:] if block_root.startswith("0x") else block_root
            )
        self._install(
            Snapshot(
                payload["state"],
                payload["context"],
                payload["slot"],
                bytes.fromhex(root[2:] if root.startswith("0x") else root),
                seq=payload.get("seq"),
                block_root=block_root,
                block=payload.get("block"),
            )
        )

    def publish(self, state, context, slot=None, root=None, seq=None,
                block_root=None, block=None):
        """Directly publish ``state`` (NOT copied — hand the store a
        state nothing else will mutate). Root/slot/block root computed
        from the state when omitted; pass ``block`` (the committed
        SignedBeaconBlock) to enable the light-client endpoints that
        need a sync aggregate or an execution branch."""
        raw = getattr(state, "data", state)
        if root is None:
            root = type(raw).hash_tree_root(raw)
        if slot is None:
            slot = int(raw.slot)
        snap = Snapshot(state, context, slot, root, seq=seq,
                        block_root=block_root, block=block)
        self._install(snap)
        return snap

    def _install(self, snap: Snapshot) -> None:
        with self._lock:
            self._history.append(snap)
            self._by_root[snap.root] = snap
            self._by_block_root[snap.block_root] = snap
            while len(self._history) > self._capacity:
                old = self._history.pop(0)
                if self._by_root.get(old.root) is old:
                    del self._by_root[old.root]
                if self._by_block_root.get(old.block_root) is old:
                    del self._by_block_root[old.block_root]
                _metrics.counter("serving.snapshots.evicted").inc()
        _metrics.counter("serving.snapshots.published").inc()
        _metrics.gauge("serving.head_slot").set(snap.slot)

    def clear(self) -> None:
        with self._lock:
            self._history = []
            self._by_root = {}
            self._by_block_root = {}

    def memory_census(self) -> "tuple[int, int]":
        """(resident bytes, retained snapshots) for the memory
        observatory: the frozen column bundles' array bytes (deduped —
        copy-on-write travel can share buffers across snapshots). The
        state handles themselves are attributed through the SSZ list
        census (their lists are tracked), not double-counted here."""
        nbytes = 0
        seen: set = set()
        snaps = self.snapshots()
        for snap in snaps:
            bundle = snap._bundle
            if isinstance(bundle, dict):
                for arr in bundle.values():
                    if id(arr) not in seen:
                        seen.add(id(arr))
                        nbytes += int(getattr(arr, "nbytes", 0))
        return nbytes, len(snaps)

    # -- resolution ----------------------------------------------------------
    @property
    def head(self) -> "Snapshot | None":
        with self._lock:
            return self._history[-1] if self._history else None

    def __len__(self) -> int:
        return len(self._history)

    def snapshots(self) -> "list[Snapshot]":
        """Every retained snapshot, oldest first (consistent copy)."""
        with self._lock:
            return list(self._history)

    def resolve(self, state_id) -> "Snapshot | None":
        """``head`` / slot number / ``0x``-root / ``finalized`` /
        ``justified`` → the matching retained snapshot, or None (the
        handler's 404). ``genesis`` resolves only while a slot-0
        snapshot is retained. Slot resolution is exact-match newest-
        first: snapshots exist per commit, not per slot. A 0x-root
        resolves against the state-root index first, then the
        block-root index (PR 8 residue: dependent_root pinning)."""
        value = getattr(state_id, "value", state_id)
        if isinstance(value, str):
            if value == "head":
                return self.head
            if value in ("finalized", "justified"):
                return self._checkpoint_snapshot(value)
            if value == "genesis":
                return self._newest(lambda s: s.slot == 0)
            if value.startswith("0x"):
                try:
                    value = bytes.fromhex(value[2:])
                except ValueError:
                    return None
            elif value.isdigit():
                value = int(value)
            else:
                return None
        if isinstance(value, bytes):
            with self._lock:
                hit = self._by_root.get(bytes(value))
                if hit is None:
                    # the block-root index: duties clients pin follow-up
                    # reads to dependent_root, which is a BLOCK root
                    hit = self._by_block_root.get(bytes(value))
                return hit
        if isinstance(value, int):
            return self._newest(lambda s: s.slot == value)
        return None

    def _newest(self, predicate) -> "Snapshot | None":
        with self._lock:
            for snap in reversed(self._history):
                if predicate(snap):
                    return snap
        return None

    def _checkpoint_snapshot(self, which: str) -> "Snapshot | None":
        head = self.head
        if head is None:
            return None
        field = (
            "finalized_checkpoint"
            if which == "finalized"
            else "current_justified_checkpoint"
        )
        checkpoint = getattr(head.raw, field, None)
        if checkpoint is None:
            return None
        boundary = int(checkpoint.epoch) * int(
            head.context.SLOTS_PER_EPOCH
        )
        return self._newest(lambda s: s.slot <= boundary)

    def __repr__(self) -> str:
        head = self.head
        return (
            f"HeadStore({len(self._history)}/{self._capacity} snapshots, "
            f"head={head!r})"
        )
