"""serving — the hot-state Beacon-API read data plane (docs/SERVING.md).

The ROADMAP's "heavy traffic" axis: Beacon-API READ endpoints served
straight from columnar snapshots of pipeline-committed states, mounted
on the PR 7 introspection server.

* ``headstore``  — ``HeadStore``/``Snapshot``: bounded history of
  immutable per-commit state snapshots off the pipeline commit hook's
  state channel, with ``state_id`` (head/slot/root/finalized/justified)
  resolution and copy-on-write isolation from the live pipeline.
* ``views``      — columnar resolution: status codes, batch gathers,
  status-filter masks, the vectorized rewards summary. One columnar
  gather per request batch.
* ``oracle``     — the scalar per-validator twin of every document:
  fallback path AND differential oracle (tests/test_serving.py).
* ``handlers``   — ``BeaconDataPlane``: the mountable route table
  (validators, balances, committees, sync committees, duties, rewards,
  root/fork/finality/randao/genesis) in standard Beacon-API wire
  format, round-tripped by the repo's own ``api/client.py``.

Quickstart::

    store = HeadStore().attach()            # feed from pipeline commits
    server = IntrospectionServer(port=8799).start()
    server.mount(BeaconDataPlane(store))
    ... pipeline replay ...                 # every commit publishes
    Client(server.url()).get_validators("head", indices=[1, 2, 3])

or ``make serve-data`` / ``run_storm(serve_port=0, readers=4)``.
"""

from .handlers import BeaconDataPlane
from .headstore import HeadStore, Snapshot

__all__ = ["BeaconDataPlane", "HeadStore", "Snapshot"]
