"""BLS12-381 field tower: Fq, Fq2, Fq6, Fq12, and the scalar field Fr.

This is the arithmetic substrate for the BLS signature scheme and KZG
commitments — the role the `blst` C/assembly library plays for the reference
(wrapped at ethereum-consensus/src/crypto/bls.rs). Implemented from the
curve parameters (BLS12-381: p, r, non-residues) as a pure-Python oracle;
the batched device paths in ops/ are checked against this.

Tower construction (standard for BLS12-381):
    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - (u + 1))
    Fq12 = Fq6[w] / (w^2 - v)
"""

from __future__ import annotations

__all__ = ["P", "R", "Fq", "Fq2", "Fq6", "Fq12", "Fr", "frobenius_coeffs_c1"]

# Base field modulus (381 bits).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Scalar field modulus (curve order, 255 bits).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS parameter x (negative: x = -0xd201000000010000).
BLS_X = 0xD201000000010000
BLS_X_IS_NEGATIVE = True


class Fq:
    """Prime field element mod P."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    def __add__(self, other: "Fq") -> "Fq":
        return Fq(self.n + other.n)

    def __sub__(self, other: "Fq") -> "Fq":
        return Fq(self.n - other.n)

    def __mul__(self, other: "Fq") -> "Fq":
        return Fq(self.n * other.n)

    def __neg__(self) -> "Fq":
        return Fq(-self.n)

    def __eq__(self, other) -> bool:
        return isinstance(other, Fq) and self.n == other.n

    def __hash__(self):
        return hash(("Fq", self.n))

    def square(self) -> "Fq":
        return Fq(self.n * self.n)

    def inverse(self) -> "Fq":
        if self.n == 0:
            raise ZeroDivisionError("Fq inverse of zero")
        return Fq(pow(self.n, P - 2, P))

    def pow(self, e: int) -> "Fq":
        return Fq(pow(self.n, e, P))

    def sqrt(self) -> "Fq | None":
        # P ≡ 3 (mod 4): candidate = self^((P+1)/4)
        cand = Fq(pow(self.n, (P + 1) // 4, P))
        return cand if cand.square() == self else None

    def is_zero(self) -> bool:
        return self.n == 0

    def sgn0(self) -> int:
        return self.n & 1

    @classmethod
    def zero(cls) -> "Fq":
        return cls(0)

    @classmethod
    def one(cls) -> "Fq":
        return cls(1)

    def __repr__(self) -> str:
        return f"Fq(0x{self.n:x})"


class Fq2:
    """Fq[u]/(u^2+1): c0 + c1*u."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq, c1: Fq):
        self.c0 = c0
        self.c1 = c1

    @classmethod
    def from_ints(cls, a: int, b: int) -> "Fq2":
        return cls(Fq(a), Fq(b))

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        # Karatsuba: (a0+a1u)(b0+b1u) = a0b0 - a1b1 + ((a0+a1)(b0+b1)-a0b0-a1b1)u
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fq2(t0 - t1, t2 - t0 - t1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash(("Fq2", self.c0.n, self.c1.n))

    def square(self) -> "Fq2":
        # (a+bu)^2 = (a+b)(a-b) + 2ab·u
        a, b = self.c0, self.c1
        t0 = (a + b) * (a - b)
        t1 = a * b
        return Fq2(t0, t1 + t1)

    def scalar_mul(self, k: Fq) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def mul_by_nonresidue(self) -> "Fq2":
        # ξ = u + 1: (a+bu)(1+u) = (a-b) + (a+b)u
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def inverse(self) -> "Fq2":
        # 1/(a+bu) = (a-bu)/(a^2+b^2)
        norm = self.c0.square() + self.c1.square()
        inv = norm.inverse()
        return Fq2(self.c0 * inv, -(self.c1 * inv))

    def pow(self, e: int) -> "Fq2":
        result = Fq2.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def frobenius(self) -> "Fq2":
        # x -> x^p = conjugate in Fq2
        return self.conjugate()

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def sgn0(self) -> int:
        # RFC 9380 sgn0 for m=2: sign of c0 unless c0 == 0, then c1
        s0 = self.c0.n & 1
        z0 = 1 if self.c0.n == 0 else 0
        s1 = self.c1.n & 1
        return s0 | (z0 & s1)

    def sqrt(self) -> "Fq2 | None":
        """Square root in Fq2 (p ≡ 3 mod 4 algorithm)."""
        if self.is_zero():
            return self
        # a1 = self^((p-3)/4); alpha = a1^2 * self; x0 = a1*self
        a1 = self.pow((P - 3) // 4)
        alpha = a1.square() * self
        x0 = a1 * self
        if alpha == Fq2(Fq(P - 1), Fq.zero()):  # alpha == -1
            return Fq2(-x0.c1, x0.c0)  # i * x0
        b = (alpha + Fq2.one()).pow((P - 1) // 2)
        cand = b * x0
        return cand if cand.square() == self else None

    @classmethod
    def zero(cls) -> "Fq2":
        return cls(Fq.zero(), Fq.zero())

    @classmethod
    def one(cls) -> "Fq2":
        return cls(Fq.one(), Fq.zero())

    def __repr__(self) -> str:
        return f"Fq2(0x{self.c0.n:x}, 0x{self.c1.n:x})"


# Frobenius coefficients for Fq6/Fq12: ξ^((p^i - 1)/k) precomputed lazily.
_XI = Fq2.from_ints(1, 1)


def _xi_pow(exp_num: int, exp_den: int, power_of_p: int) -> Fq2:
    """ξ^((p^power_of_p - 1) * exp_num / exp_den)."""
    e = (pow(P, power_of_p) - 1) * exp_num // exp_den
    return _XI.pow(e)


class _FrobeniusTables:
    """Lazily computed Frobenius twist coefficients."""

    def __init__(self):
        self._c1_6: list[Fq2] | None = None  # for Fq6 c1 coefficients
        self._c2_6: list[Fq2] | None = None  # for Fq6 c2 coefficients
        self._c1_12: list[Fq2] | None = None  # for Fq12

    @property
    def fq6_c1(self) -> list[Fq2]:
        if self._c1_6 is None:
            self._c1_6 = [_XI.pow((pow(P, i) - 1) // 3) for i in range(6)]
        return self._c1_6

    @property
    def fq6_c2(self) -> list[Fq2]:
        if self._c2_6 is None:
            self._c2_6 = [_XI.pow(2 * (pow(P, i) - 1) // 3) for i in range(6)]
        return self._c2_6

    @property
    def fq12_c1(self) -> list[Fq2]:
        if self._c1_12 is None:
            self._c1_12 = [_XI.pow((pow(P, i) - 1) // 6) for i in range(12)]
        return self._c1_12


_FROB = _FrobeniusTables()


def frobenius_coeffs_c1(i: int) -> Fq2:
    return _FROB.fq12_c1[i % 12]


class Fq6:
    """Fq2[v]/(v^3 - ξ): c0 + c1*v + c2*v^2."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __eq__(self, o) -> bool:
        return (
            isinstance(o, Fq6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __hash__(self):
        return hash(("Fq6", self.c0, self.c1, self.c2))

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_nonresidue() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_nonresidue()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def square(self) -> "Fq6":
        return self * self

    def mul_by_nonresidue(self) -> "Fq6":
        # v * (c0 + c1 v + c2 v^2) = ξ·c2 + c0 v + c1 v^2
        return Fq6(self.c2.mul_by_nonresidue(), self.c0, self.c1)

    def scalar_mul2(self, k: Fq2) -> "Fq6":
        return Fq6(self.c0 * k, self.c1 * k, self.c2 * k)

    def inverse(self) -> "Fq6":
        a, b, c = self.c0, self.c1, self.c2
        t0 = a.square() - (b * c).mul_by_nonresidue()
        t1 = c.square().mul_by_nonresidue() - a * b
        t2 = b.square() - a * c
        denom = (a * t0 + (c * t1 + b * t2).mul_by_nonresidue()).inverse()
        return Fq6(t0 * denom, t1 * denom, t2 * denom)

    def frobenius(self) -> "Fq6":
        return Fq6(
            self.c0.frobenius(),
            self.c1.frobenius() * _FROB.fq6_c1[1],
            self.c2.frobenius() * _FROB.fq6_c2[1],
        )

    def frobenius_n(self, n: int) -> "Fq6":
        out = self
        for _ in range(n):
            out = out.frobenius()
        return out

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    @classmethod
    def zero(cls) -> "Fq6":
        return cls(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @classmethod
    def one(cls) -> "Fq6":
        return cls(Fq2.one(), Fq2.zero(), Fq2.zero())


class Fq12:
    """Fq6[w]/(w^2 - v): c0 + c1*w."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0 = c0
        self.c1 = c1

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash(("Fq12", self.c0, self.c1))

    def __mul__(self, o: "Fq12") -> "Fq12":
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        c0 = t0 + t1.mul_by_nonresidue()
        c1 = (self.c0 + self.c1) * (o.c0 + o.c1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        # (a + bw)^2 = a^2 + v b^2 + 2abw
        a, b = self.c0, self.c1
        t0 = a * b
        c0 = (a + b) * (a + b.mul_by_nonresidue()) - t0 - t0.mul_by_nonresidue()
        return Fq12(c0, t0 + t0)

    def conjugate(self) -> "Fq12":
        return Fq12(self.c0, -self.c1)

    def inverse(self) -> "Fq12":
        denom = (self.c0.square() - self.c1.square().mul_by_nonresidue()).inverse()
        return Fq12(self.c0 * denom, -(self.c1 * denom))

    def pow(self, e: int) -> "Fq12":
        result = Fq12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def frobenius(self) -> "Fq12":
        c0 = self.c0.frobenius()
        c1f = self.c1.frobenius()
        coeff = _FROB.fq12_c1[1]
        c1 = Fq6(c1f.c0 * coeff, c1f.c1 * coeff, c1f.c2 * coeff)
        return Fq12(c0, c1)

    def frobenius_n(self, n: int) -> "Fq12":
        out = self
        for _ in range(n % 12):
            out = out.frobenius()
        return out

    def is_one(self) -> bool:
        return self == Fq12.one()

    @classmethod
    def zero(cls) -> "Fq12":
        return cls(Fq6.zero(), Fq6.zero())

    @classmethod
    def one(cls) -> "Fq12":
        return cls(Fq6.one(), Fq6.zero())


class Fr:
    """Scalar field element mod R (the curve order) — used by KZG polynomial
    math; plain ints are used for scalars elsewhere."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % R

    def __add__(self, o: "Fr") -> "Fr":
        return Fr(self.n + o.n)

    def __sub__(self, o: "Fr") -> "Fr":
        return Fr(self.n - o.n)

    def __mul__(self, o: "Fr") -> "Fr":
        return Fr(self.n * o.n)

    def __neg__(self) -> "Fr":
        return Fr(-self.n)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fr) and self.n == o.n

    def __hash__(self):
        return hash(("Fr", self.n))

    def inverse(self) -> "Fr":
        if self.n == 0:
            raise ZeroDivisionError("Fr inverse of zero")
        return Fr(pow(self.n, R - 2, R))

    def pow(self, e: int) -> "Fr":
        return Fr(pow(self.n, e, R))

    def is_zero(self) -> bool:
        return self.n == 0

    @classmethod
    def zero(cls) -> "Fr":
        return cls(0)

    @classmethod
    def one(cls) -> "Fr":
        return cls(1)

    def __repr__(self) -> str:
        return f"Fr(0x{self.n:x})"
