"""Cryptography subsystem: BLS12-381 signatures + KZG/EIP-4844.

Replaces the reference's blst (C/asm) and c-kzg (C) dependencies
(ethereum-consensus/src/crypto/{mod,bls,kzg}.rs) with a from-scratch field/
curve/pairing stack; batched device acceleration hooks in via ops/.
"""

from . import bls, curves, fields, hash_to_curve, pairing  # noqa: F401
from .bls import (  # noqa: F401
    PublicKey,
    SecretKey,
    Signature,
    aggregate,
    aggregate_verify,
    eth_aggregate_public_keys,
    eth_fast_aggregate_verify,
    fast_aggregate_verify,
    hash,
    verify_signature,
)
