"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

Jacobian-coordinate arithmetic, scalar multiplication, subgroup checks,
cofactor clearing, and the ZCash serialization format (48-byte compressed
G1 / 96-byte compressed G2 with compression/infinity/sign flag bits) that
the reference's `blst` wrapper exposes
(ethereum-consensus/src/crypto/bls.rs:{PublicKey,Signature}).

Curve equations:  E : y^2 = x^3 + 4 over Fq
                  E': y^2 = x^3 + 4(u+1) over Fq2 (the sextic twist)
"""

from __future__ import annotations

from .fields import Fq, Fq2, P, R

__all__ = [
    "G1Point",
    "G2Point",
    "G1_GENERATOR",
    "G2_GENERATOR",
    "H_EFF_G2",
    "InvalidPointError",
]

# Standard generators (from the BLS12-381 specification).
_G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
_G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

_G2_X0 = 0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8
_G2_X1 = 0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E
_G2_Y0 = 0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801
_G2_Y1 = 0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE

# Effective cofactor for G2 cofactor clearing (h_eff, RFC 9380 §8.8.2).
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551

# G1 cofactor (not needed for clearing via the map, kept for reference).
H_G1 = 0x396C8C005555E1568C00AAAB0000AAAB


class InvalidPointError(ValueError):
    """Encoding does not describe a valid curve point."""


class _JacobianPoint:
    """Shared Jacobian-coordinate arithmetic. Field ops are duck-typed over
    Fq / Fq2; subclasses fix the field, the curve constant b, and codec."""

    __slots__ = ("x", "y", "z")

    # subclasses set these
    FIELD = None
    B = None

    def __init__(self, x, y, z):
        self.x = x
        self.y = y
        self.z = z

    # -- constructors -------------------------------------------------------
    @classmethod
    def infinity(cls):
        f = cls.FIELD
        return cls(f.one(), f.one(), f.zero())

    @classmethod
    def from_affine(cls, x, y):
        return cls(x, y, cls.FIELD.one())

    def is_infinity(self) -> bool:
        return self.z.is_zero()

    def to_affine(self):
        """Returns (x, y) or None for the point at infinity."""
        if self.is_infinity():
            return None
        zinv = self.z.inverse()
        z2 = zinv.square()
        return (self.x * z2, self.y * z2 * zinv)

    # -- group law ----------------------------------------------------------
    def double(self):
        if self.is_infinity():
            return self
        x, y, z = self.x, self.y, self.z
        a = x.square()
        b = y.square()
        c = b.square()
        d = (x + b).square() - a - c
        d = d + d
        e = a + a + a
        f = e.square()
        x3 = f - d - d
        c8 = c + c
        c8 = c8 + c8
        c8 = c8 + c8
        y3 = e * (d - x3) - c8
        z3 = (y * z) + (y * z)
        return type(self)(x3, y3, z3)

    def __add__(self, other):
        if self.is_infinity():
            return other
        if other.is_infinity():
            return self
        x1, y1, z1 = self.x, self.y, self.z
        x2, y2, z2 = other.x, other.y, other.z
        z1z1 = z1.square()
        z2z2 = z2.square()
        u1 = x1 * z2z2
        u2 = x2 * z1z1
        s1 = y1 * z2 * z2z2
        s2 = y2 * z1 * z1z1
        if u1 == u2:
            if s1 == s2:
                return self.double()
            return type(self).infinity()
        h = u2 - u1
        i = (h + h).square()
        j = h * i
        r = s2 - s1
        r = r + r
        v = u1 * i
        x3 = r.square() - j - v - v
        y3 = r * (v - x3) - (s1 * j) - (s1 * j)
        z3 = ((z1 * z2) + (z1 * z2)) * h
        return type(self)(x3, y3, z3)

    def __neg__(self):
        return type(self)(self.x, -self.y, self.z)

    def __sub__(self, other):
        return self + (-other)

    def __eq__(self, other) -> bool:
        if type(self) is not type(other):
            return NotImplemented
        # cross-multiply to compare projective classes
        if self.is_infinity() or other.is_infinity():
            return self.is_infinity() and other.is_infinity()
        z1z1 = self.z.square()
        z2z2 = other.z.square()
        if self.x * z2z2 != other.x * z1z1:
            return False
        return self.y * z2z2 * other.z == other.y * z1z1 * self.z

    def __hash__(self):
        aff = self.to_affine()
        return hash((type(self).__name__, None if aff is None else (aff[0], aff[1])))

    def __mul__(self, scalar: int):
        """Scalar multiplication (double-and-add, MSB-first)."""
        if scalar < 0:
            return (-self) * (-scalar)
        result = type(self).infinity()
        if scalar == 0 or self.is_infinity():
            return result
        addend = self
        for bit in bin(scalar)[2:]:
            result = result.double()
            if bit == "1":
                result = result + addend
        return result

    __rmul__ = __mul__

    # -- validation ---------------------------------------------------------
    def is_on_curve(self) -> bool:
        if self.is_infinity():
            return True
        x, y = self.to_affine()
        return y.square() == x.square() * x + self.B

    def in_subgroup(self) -> bool:
        """Order-r subgroup membership (scalar-mul check; the oracle favors
        clarity over the endomorphism fast path)."""
        return (self * R).is_infinity()

    def __repr__(self) -> str:
        aff = self.to_affine()
        if aff is None:
            return f"{type(self).__name__}(infinity)"
        return f"{type(self).__name__}({aff[0]!r}, {aff[1]!r})"


# -- serialization flag bits (ZCash BLS12-381 format) ------------------------
# In the most significant byte of the encoding:
_COMPRESSED_FLAG = 0x80
_INFINITY_FLAG = 0x40
_SIGN_FLAG = 0x20


def _fq_is_lexicographically_largest(y: Fq) -> bool:
    return y.n > (P - 1) // 2


def _fq2_is_lexicographically_largest(y: Fq2) -> bool:
    # compare c1 first, then c0 (ZCash convention)
    if y.c1.n != 0:
        return y.c1.n > (P - 1) // 2
    return y.c0.n > (P - 1) // 2


class G1Point(_JacobianPoint):
    FIELD = Fq
    B = Fq(4)

    def serialize(self) -> bytes:
        """48-byte compressed encoding."""
        if self.is_infinity():
            out = bytearray(48)
            out[0] = _COMPRESSED_FLAG | _INFINITY_FLAG
            return bytes(out)
        x, y = self.to_affine()
        out = bytearray(x.n.to_bytes(48, "big"))
        out[0] |= _COMPRESSED_FLAG
        if _fq_is_lexicographically_largest(y):
            out[0] |= _SIGN_FLAG
        return bytes(out)

    @classmethod
    def deserialize(cls, data: bytes) -> "G1Point":
        """Decode 48-byte compressed encoding; validates curve membership
        and subgroup (matching blst's `key_validate`-adjacent behavior)."""
        if len(data) != 48:
            raise InvalidPointError(f"G1 compressed encoding must be 48 bytes, got {len(data)}")
        flags = data[0]
        if not flags & _COMPRESSED_FLAG:
            raise InvalidPointError("uncompressed G1 encodings are not supported")
        if flags & _INFINITY_FLAG:
            if any(data[1:]) or flags & ~(_COMPRESSED_FLAG | _INFINITY_FLAG):
                raise InvalidPointError("malformed G1 infinity encoding")
            return cls.infinity()
        xn = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
        if xn >= P:
            raise InvalidPointError("G1 x coordinate not in field")
        x = Fq(xn)
        y2 = x.square() * x + cls.B
        y = y2.sqrt()
        if y is None:
            raise InvalidPointError("G1 x coordinate not on curve")
        if _fq_is_lexicographically_largest(y) != bool(flags & _SIGN_FLAG):
            y = -y
        point = cls.from_affine(x, y)
        if not point.in_subgroup():
            raise InvalidPointError("G1 point not in the order-r subgroup")
        return point


class G2Point(_JacobianPoint):
    FIELD = Fq2
    B = Fq2(Fq(4), Fq(4))  # 4(u+1)

    def serialize(self) -> bytes:
        """96-byte compressed encoding (c1 || c0 big-endian)."""
        if self.is_infinity():
            out = bytearray(96)
            out[0] = _COMPRESSED_FLAG | _INFINITY_FLAG
            return bytes(out)
        x, y = self.to_affine()
        out = bytearray(x.c1.n.to_bytes(48, "big") + x.c0.n.to_bytes(48, "big"))
        out[0] |= _COMPRESSED_FLAG
        if _fq2_is_lexicographically_largest(y):
            out[0] |= _SIGN_FLAG
        return bytes(out)

    @classmethod
    def deserialize(cls, data: bytes) -> "G2Point":
        if len(data) != 96:
            raise InvalidPointError(f"G2 compressed encoding must be 96 bytes, got {len(data)}")
        flags = data[0]
        if not flags & _COMPRESSED_FLAG:
            raise InvalidPointError("uncompressed G2 encodings are not supported")
        if flags & _INFINITY_FLAG:
            if any(data[1:]) or flags & ~(_COMPRESSED_FLAG | _INFINITY_FLAG):
                raise InvalidPointError("malformed G2 infinity encoding")
            return cls.infinity()
        x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:96], "big")
        if x0 >= P or x1 >= P:
            raise InvalidPointError("G2 x coordinate not in field")
        x = Fq2(Fq(x0), Fq(x1))
        y2 = x.square() * x + cls.B
        y = y2.sqrt()
        if y is None:
            raise InvalidPointError("G2 x coordinate not on curve")
        if _fq2_is_lexicographically_largest(y) != bool(flags & _SIGN_FLAG):
            y = -y
        point = cls.from_affine(x, y)
        if not point.in_subgroup():
            raise InvalidPointError("G2 point not in the order-r subgroup")
        return point

    def clear_cofactor(self) -> "G2Point":
        """Map onto the order-r subgroup via the effective cofactor."""
        return self * H_EFF_G2

    def psi(self) -> "G2Point":
        """The untwist-Frobenius-twist endomorphism (for future fast subgroup
        checks); not used by the oracle paths yet."""
        raise NotImplementedError


G1_GENERATOR = G1Point.from_affine(Fq(_G1_X), Fq(_G1_Y))
G2_GENERATOR = G2Point.from_affine(
    Fq2(Fq(_G2_X0), Fq(_G2_X1)), Fq2(Fq(_G2_Y0), Fq(_G2_Y1))
)
