"""Optimal ate pairing for BLS12-381.

The verification core of the BLS signature scheme — the role blst's pairing
engine plays for the reference (ethereum-consensus/src/crypto/bls.rs
verify/aggregate_verify paths).

Design: G2 points are untwisted into E(Fq12) and the Miller loop runs with
affine line functions over Fq12. This trades speed for transparency — the
oracle must be obviously correct; batched/device acceleration lives a level
up (multi-pairing products share one final exponentiation).

Untwist (tower Fq12 = Fq6[w]/(w²-v), Fq6 = Fq2[v]/(v³-ξ), ξ = u+1):
    ψ(x', y') = (x'·v²/ξ, y'·v·w/ξ)
which maps E'(Fq2): y² = x³ + 4ξ onto E(Fq12): y² = x³ + 4.
"""

from __future__ import annotations

from .curves import G1Point, G2Point
from .fields import BLS_X, Fq2, Fq6, Fq12, P, R

__all__ = ["pairing", "miller_loop", "multi_miller_loop", "final_exponentiation"]


_XI_INV = Fq2.from_ints(1, 1).inverse()


def _untwist(q: G2Point) -> tuple[Fq12, Fq12]:
    """Affine G2 point → affine coordinates in E(Fq12)."""
    xq, yq = q.to_affine()
    x12 = Fq12(Fq6(Fq2.zero(), Fq2.zero(), xq * _XI_INV), Fq6.zero())
    y12 = Fq12(Fq6.zero(), Fq6(Fq2.zero(), yq * _XI_INV, Fq2.zero()))
    return x12, y12


def _embed_g1(p: G1Point) -> tuple[Fq12, Fq12]:
    xp, yp = p.to_affine()
    def lift(a):
        return Fq12(Fq6(Fq2(a, a.__class__(0)), Fq2.zero(), Fq2.zero()), Fq6.zero())
    return lift(xp), lift(yp)


def _line(x1: Fq12, y1: Fq12, x2: Fq12, y2: Fq12, xt: Fq12, yt: Fq12) -> Fq12:
    """Evaluate the line through (x1,y1),(x2,y2) at (xt,yt).

    Doubling when the points coincide; vertical line when x1==x2, y1!=y2.
    """
    if x1 == x2 and y1 == y2:
        # tangent: m = 3x²/(2y)
        num = x1.square()
        num = num + num + num
        den = y1 + y1
        m = num * den.inverse()
        return m * (xt - x1) - (yt - y1)
    if x1 == x2:
        return xt - x1
    m = (y2 - y1) * (x2 - x1).inverse()
    return m * (xt - x1) - (yt - y1)


def _point_add(a, b):
    """Affine addition on E(Fq12). For the order-r inputs the Miller loop
    feeds in, intermediate multiples [k]Q with 0 < k ≤ |x| ≪ r can never be
    the identity or each other's negatives, so no infinity handling is
    needed (asserted for defense in depth)."""
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        assert y1 == y2, "Miller loop hit P + (-P); inputs not in the r-subgroup"
        num = x1.square()
        num = num + num + num
        den = y1 + y1
        m = num * den.inverse()
    else:
        m = (y2 - y1) * (x2 - x1).inverse()
    x3 = m.square() - x1 - x2
    y3 = m * (x1 - x3) - y1
    return (x3, y3)


def miller_loop(q: G2Point, p: G1Point) -> Fq12:
    """f_{|x|,Q}(P) for the BLS parameter, conjugated for the negative x."""
    if q.is_infinity() or p.is_infinity():
        return Fq12.one()
    xq, yq = _untwist(q)
    xp, yp = _embed_g1(p)

    f = Fq12.one()
    rx, ry = xq, yq
    for bit in bin(BLS_X)[3:]:  # MSB already consumed by initializing R = Q
        f = f.square() * _line(rx, ry, rx, ry, xp, yp)
        rx, ry = _point_add((rx, ry), (rx, ry))
        if bit == "1":
            f = f * _line(rx, ry, xq, yq, xp, yp)
            rx, ry = _point_add((rx, ry), (xq, yq))
    # BLS parameter x is negative: f ← conj(f) (p^6-power Frobenius).
    return f.conjugate()


def multi_miller_loop(pairs: list[tuple[G1Point, G2Point]]) -> Fq12:
    """Product of Miller loops — shares the (expensive) final exponentiation
    across all pairs; this is the shape batched verification wants."""
    f = Fq12.one()
    for p, q in pairs:
        if p.is_infinity() or q.is_infinity():
            continue
        f = f * miller_loop(q, p)
    return f


def pairing_product_is_one(pairs: list[tuple[G1Point, G2Point]]) -> bool:
    """Π e(Pi, Qi) == 1 with one shared final exponentiation — the single
    verification primitive every BLS/KZG check reduces to."""
    return final_exponentiation(multi_miller_loop(pairs)).is_one()


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p^12 - 1)/r).

    Easy part via Frobenius/conjugation; the hard part uses a plain square-
    and-multiply over (p^4 - p^2 + 1)/r (clarity over the Karabina cyclotomic
    decomposition — the oracle is not the hot path).
    """
    # easy: f^(p^6 - 1) = conj(f) * f^-1 ; then ^(p^2 + 1)
    f1 = f.conjugate() * f.inverse()
    f2 = f1.frobenius_n(2) * f1
    # hard: ^((p^4 - p^2 + 1) / r)
    hard = (P**4 - P**2 + 1) // R
    return f2.pow(hard)


def pairing(p: G1Point, q: G2Point) -> Fq12:
    """e(P, Q) for P ∈ G1, Q ∈ G2."""
    return final_exponentiation(miller_loop(q, p))
