"""Hash-to-curve for G2: BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380).

This is how messages become signable G2 points in the min_pk BLS scheme —
the role blst's `hash_to_g2` plays for the reference
(ethereum-consensus/src/crypto/bls.rs sign/verify paths, which pass the
Ethereum ciphersuite DST).

Pipeline: expand_message_xmd(SHA-256) → hash_to_field (two Fq2 elements) →
simplified SWU onto the 3-isogenous curve E'' → derived 3-isogeny onto the
G2 twist E' (constants in g2_isogeny.py, re-derived by Vélu's formulas in
_isogeny_derive.py) → point addition → cofactor clearing by h_eff.
"""

from __future__ import annotations

import hashlib

from .curves import G2Point
from .fields import Fq, Fq2, P
from . import g2_isogeny as iso

__all__ = [
    "ETH_DST",
    "expand_message_xmd",
    "hash_to_field_fq2",
    "map_to_curve_sswu",
    "iso_map_to_g2_curve",
    "hash_to_g2",
]

# Ethereum 2.0 BLS ciphersuite domain separation tag.
ETH_DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_B_IN_BYTES = 32  # SHA-256 output
_R_IN_BYTES = 64  # SHA-256 block
_L = 64  # bytes per field-element component (ceil((381 + 128)/8))

# SSWU curve E'': y² = x³ + A'x + B', and Z (RFC 9380 §8.8.2)
_A = Fq2(Fq(0), Fq(240))
_B = Fq2(Fq(1012), Fq(1012))
_Z = Fq2(Fq(P - 2), Fq(P - 1))  # -(2 + u)
_NEG_B_OVER_A = -(_B * _A.inverse())
_B_OVER_ZA = _B * (_Z * _A).inverse()


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter overflow")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        blocks.append(hashlib.sha256(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(blocks)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = ETH_DST) -> list[Fq2]:
    """RFC 9380 §5.2 hash_to_field for m=2, L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        comps = []
        for j in range(2):
            offset = _L * (j + i * 2)
            tv = uniform[offset : offset + _L]
            comps.append(Fq(int.from_bytes(tv, "big")))
        out.append(Fq2(comps[0], comps[1]))
    return out


def map_to_curve_sswu(u: Fq2) -> tuple[Fq2, Fq2]:
    """Simplified SWU map onto E'' (RFC 9380 §6.6.2), returning affine (x, y)."""
    zu2 = _Z * u.square()  # Z·u²
    tv = zu2.square() + zu2  # Z²u⁴ + Zu²
    if tv.is_zero():
        # exceptional case: x1 = B / (Z·A)
        x1 = _B_OVER_ZA
    else:
        x1 = _NEG_B_OVER_A * (Fq2.one() + tv.inverse())
    gx1 = x1.square() * x1 + _A * x1 + _B
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = zu2 * x1
        gx2 = x2.square() * x2 + _A * x2 + _B
        y2 = gx2.sqrt()
        if y2 is None:
            raise AssertionError("SSWU: neither g(x1) nor g(x2) is square")
        x, y = x2, y2
    if y.sgn0() != u.sgn0():
        y = -y
    return x, y


def iso_map_to_g2_curve(x: Fq2, y: Fq2) -> G2Point:
    """Apply the derived 3-isogeny E'' → E' to an affine E'' point."""

    def horner(coeffs: list[Fq2], v: Fq2) -> Fq2:
        acc = Fq2.zero()
        for c in reversed(coeffs):
            acc = acc * v + c
        return acc

    x_num = horner(iso.X_NUM, x)
    x_den = horner(iso.X_DEN, x)
    y_num = horner(iso.Y_NUM, x)
    y_den = horner(iso.Y_DEN, x)
    # x == kernel x0 maps to the identity; SSWU outputs are uniformly random
    # so this is cryptographically unreachable, but guard anyway.
    if x_den.is_zero() or y_den.is_zero():
        return G2Point.infinity()
    xo = x_num * x_den.inverse()
    yo = y * y_num * y_den.inverse()
    return G2Point.from_affine(xo, yo)


def hash_to_g2(msg: bytes, dst: bytes = ETH_DST) -> G2Point:
    """Full RFC 9380 hash_to_curve for the G2 ciphersuite."""
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = iso_map_to_g2_curve(*map_to_curve_sswu(u0))
    q1 = iso_map_to_g2_curve(*map_to_curve_sswu(u1))
    return (q0 + q1).clear_cofactor()
