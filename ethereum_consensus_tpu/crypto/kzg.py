"""KZG polynomial commitments for EIP-4844 blobs (deneb).

Reference parity: ethereum-consensus/src/crypto/kzg.rs — KzgSettings +
trusted-setup loading (:39), blob_to_kzg_commitment (:60),
compute_kzg_proof (:71), compute_blob_kzg_proof (:88), verify_kzg_proof
(:101), verify_blob_kzg_proof (:124), verify_blob_kzg_proof_batch (:139).
The reference wraps the c-kzg C library; here the polynomial math runs on
the from-scratch BLS12-381 stack (fields/curves/pairing), in the evaluation
(Lagrange, bit-reversal-permuted) form the EIP-4844 spec prescribes.

Trusted setups:
  - ``KzgSettings.from_json`` loads the standard c-kzg JSON layout
    (``g1_lagrange``/``g2_monomial``, or legacy ``setup_G1_lagrange``/
    ``setup_G2``) — use this with the published mainnet ceremony output.
  - ``KzgSettings.insecure_dev_setup(tau, n)`` derives a mathematically
    valid setup from a KNOWN secret — test-only by construction, and also
    the only way to get a small-domain setup for fast tests.
"""

from __future__ import annotations

import hashlib
import json

from ..error import KzgError
from ..native import bls as native_bls
from .curves import G1Point, G2Point, G1_GENERATOR, G2_GENERATOR, InvalidPointError
from .fields import R


def _native_on() -> bool:
    """KZG follows the BLS backend selection (EC_BLS_BACKEND)."""
    from . import bls as _bls

    return _bls.backend_name() == "native"


def _batch_inv(values: list[int]) -> list[int]:
    """Montgomery's trick: n field inversions for one modexp + 3n mults."""
    n = len(values)
    prefix = [1] * (n + 1)
    for i, v in enumerate(values):
        if v % R == 0:
            raise KzgError("batch inversion of zero")
        prefix[i + 1] = prefix[i] * v % R
    inv_all = pow(prefix[n], R - 2, R)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % R
        inv_all = inv_all * values[i] % R
    return out

__all__ = [
    "FIELD_ELEMENTS_PER_BLOB",
    "BYTES_PER_FIELD_ELEMENT",
    "BYTES_PER_BLOB",
    "KzgCommitment",
    "KzgProof",
    "KzgSettings",
    "blob_to_kzg_commitment",
    "compute_kzg_proof",
    "compute_blob_kzg_proof",
    "verify_kzg_proof",
    "verify_blob_kzg_proof",
    "verify_blob_kzg_proof_batch",
]

FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_FIELD_ELEMENT = 32
BYTES_PER_BLOB = FIELD_ELEMENTS_PER_BLOB * BYTES_PER_FIELD_ELEMENT

# Fiat-Shamir domains (EIP-4844 polynomial-commitments spec).
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"

# Fr multiplicative generator and 2-adicity for roots of unity.
_FR_GENERATOR = 7
_FR_TWO_ADICITY = 32

_CEREMONY = None  # process-wide cache of the embedded ceremony setup

# Pin of the pre-decompressed ceremony binary (native/_gen_trusted_setup.py);
# a mismatch falls back to the validated-JSON slow path, never to trust.
CEREMONY_AFFINE_MAGIC = b"ECTS\x01\x00"
CEREMONY_AFFINE_SHA256 = (
    "92199542ef523b03dbbbd1071709e21801a220161fb8374ebfeda64ed4b168c5"
)


def _roots_of_unity(order: int) -> list[int]:
    """The order-``order`` subgroup of Fr*, in natural order."""
    if order & (order - 1):
        raise KzgError("domain order must be a power of two")
    if order > 1 << _FR_TWO_ADICITY:
        raise KzgError("domain order exceeds Fr two-adicity")
    root = pow(_FR_GENERATOR, (R - 1) // order, R)
    out = [1]
    for _ in range(order - 1):
        out.append(out[-1] * root % R)
    return out


def _bit_reversal_permutation(values: list) -> list:
    n = len(values)
    bits = n.bit_length() - 1
    return [values[int(format(i, f"0{bits}b")[::-1], 2)] if bits else values[i] for i in range(n)]


class KzgCommitment(bytes):
    """48-byte compressed G1 commitment."""

    def __new__(cls, data: bytes):
        if len(data) != 48:
            raise KzgError("KZG commitment must be 48 bytes")
        return super().__new__(cls, data)


class KzgProof(bytes):
    """48-byte compressed G1 proof."""

    def __new__(cls, data: bytes):
        if len(data) != 48:
            raise KzgError("KZG proof must be 48 bytes")
        return super().__new__(cls, data)


class KzgSettings:
    """Trusted setup in the blob-native form: G1 points of the Lagrange
    basis over the bit-reversal-permuted evaluation domain, plus [1]_2 and
    [τ]_2."""

    def __init__(self, g1_lagrange_brp: list[G1Point], g2_monomial: list[G2Point]):
        n = len(g1_lagrange_brp)
        if n & (n - 1):
            raise KzgError("setup size must be a power of two")
        if len(g2_monomial) < 2:
            raise KzgError("setup needs at least [1]_2 and [tau]_2")
        self.g1_lagrange_brp = g1_lagrange_brp
        self.g2_monomial = g2_monomial
        self.n = n
        self.roots_brp = _bit_reversal_permutation(_roots_of_unity(n))
        self._g1_raw: bytes | None = None   # 96n-byte affine cache (native)
        self._g2_raw: list[bytes] | None = None

    def g1_raw(self) -> bytes:
        """Concatenated 96-byte raw affine setup points (native MSM input)."""
        if self._g1_raw is None:
            parts = []
            for pt in self.g1_lagrange_brp:
                rc, raw, is_inf = native_bls.g1_decompress(
                    pt.serialize(), check_subgroup=False
                )
                if rc != 0 or is_inf:
                    raise KzgError("setup point unusable for MSM")
                parts.append(raw)
            self._g1_raw = b"".join(parts)
        return self._g1_raw

    def g2_raw(self) -> list[bytes]:
        """Raw affine [1]_2 and [tau]_2 (native pairing input)."""
        if self._g2_raw is None:
            out = []
            for pt in self.g2_monomial[:2]:
                rc, raw, is_inf = native_bls.g2_decompress(
                    pt.serialize(), check_subgroup=False
                )
                if rc != 0 or is_inf:
                    raise KzgError("setup G2 point unusable for pairing")
                out.append(raw)
            self._g2_raw = out
        return self._g2_raw

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_json(cls, text: str) -> "KzgSettings":
        """Load the c-kzg JSON trusted-setup layout.

        Ceremony files list the Lagrange points in NATURAL domain order;
        the blob convention is bit-reversal-permuted, so the permutation is
        applied here (matching c-kzg's load-time behavior)."""
        obj = json.loads(text)
        g1 = obj.get("g1_lagrange") or obj.get("setup_G1_lagrange") or obj.get("setup_G1")
        g2 = obj.get("g2_monomial") or obj.get("setup_G2")
        if g1 is None or g2 is None:
            raise KzgError("unrecognized trusted setup JSON layout")

        if _native_on():
            # native decompress validates (curve + subgroup) and yields the
            # affine coordinates without a Python-side sqrt per point
            from .fields import Fq

            g1_points, g1_raws = [], []
            for h in g1:
                rc, raw, is_inf = native_bls.g1_decompress(
                    bytes.fromhex(h.removeprefix("0x")), check_subgroup=True
                )
                if rc != 0 or is_inf:
                    raise KzgError(
                        f"invalid point in trusted setup: "
                        f"{native_bls.decode_error_message(rc)}"
                    )
                g1_raws.append(raw)
                g1_points.append(G1Point.from_affine(
                    Fq(int.from_bytes(raw[:48], "big")),
                    Fq(int.from_bytes(raw[48:], "big")),
                ))
            try:
                g2_points = [
                    G2Point.deserialize(bytes.fromhex(h.removeprefix("0x")))
                    for h in g2
                ]
            except InvalidPointError as exc:
                raise KzgError(f"invalid point in trusted setup: {exc}") from exc
            settings = cls(
                _bit_reversal_permutation(g1_points), g2_points
            )
            settings._g1_raw = b"".join(_bit_reversal_permutation(g1_raws))
            return settings

        def parse_g1(h: str) -> G1Point:
            return G1Point.deserialize(bytes.fromhex(h.removeprefix("0x")))

        def parse_g2(h: str) -> G2Point:
            return G2Point.deserialize(bytes.fromhex(h.removeprefix("0x")))

        try:
            g1_points = [parse_g1(h) for h in g1]
            g2_points = [parse_g2(h) for h in g2]
        except InvalidPointError as exc:
            raise KzgError(f"invalid point in trusted setup: {exc}") from exc
        return cls(_bit_reversal_permutation(g1_points), g2_points)

    def to_json(self) -> str:
        """Dump in the c-kzg layout (natural domain order — inverse brp)."""
        natural = _bit_reversal_permutation(self.g1_lagrange_brp)  # involution
        return json.dumps(
            {
                "g1_lagrange": ["0x" + p.serialize().hex() for p in natural],
                "g2_monomial": ["0x" + p.serialize().hex() for p in self.g2_monomial],
            }
        )

    @classmethod
    def from_file(cls, path: str) -> "KzgSettings":
        with open(path) as f:
            return cls.from_json(f.read())

    @classmethod
    def _from_affine_bin(cls, blob: bytes) -> "KzgSettings":
        """Construct from the pre-decompressed binary rendered at build
        time by native/_gen_trusted_setup.py (see its docstring for the
        layout). No per-point validation — the caller pins the blob's
        sha256, and the blob was derived from the fully validated JSON."""
        import struct

        from .fields import Fq, Fq2

        if blob[:6] != CEREMONY_AFFINE_MAGIC:
            raise KzgError("bad trusted_setup_affine.bin magic")
        if len(blob) < 14:
            raise KzgError("truncated trusted_setup_affine.bin")
        n_g1, n_g2 = struct.unpack_from("<II", blob, 6)
        off = 14
        if len(blob) != off + 96 * n_g1 + 192 * n_g2:
            raise KzgError("truncated trusted_setup_affine.bin")
        g1_points = []
        for _ in range(n_g1):
            g1_points.append(G1Point.from_affine(
                Fq(int.from_bytes(blob[off:off + 48], "big")),
                Fq(int.from_bytes(blob[off + 48:off + 96], "big")),
            ))
            off += 96
        g1_raw = blob[14:off]
        g2_points, g2_raws = [], []
        for _ in range(n_g2):
            c = [int.from_bytes(blob[off + 48 * i:off + 48 * (i + 1)], "big")
                 for i in range(4)]
            g2_points.append(G2Point.from_affine(
                Fq2(Fq(c[0]), Fq(c[1])), Fq2(Fq(c[2]), Fq(c[3]))
            ))
            if len(g2_raws) < 2:  # g2_raw() only needs [1]_2 and [tau]_2
                g2_raws.append(blob[off:off + 192])
            off += 192
        # points arrive already bit-reversal-permuted — __init__ expects
        # exactly that order (it never re-permutes), so construct normally
        # and attach the raw-affine caches
        settings = cls(g1_points, g2_points)
        settings._g1_raw = g1_raw
        settings._g2_raw = g2_raws
        return settings

    @classmethod
    def ceremony(cls) -> "KzgSettings":
        """The published mainnet ceremony setup, embedded with the package
        (same artifact the reference embeds:
        ethereum-consensus/src/deneb/presets/trusted_setup.json, loaded at
        deneb/presets/mod.rs:10 / context.rs:206). Cached per process.

        Fast path: the build-time pre-decompressed binary (sha256-pinned,
        rendered from the JSON by native/_gen_trusted_setup.py) loads in
        tens of ms; the JSON + 4096 subgroup checks (seconds) is only the
        fallback when the binary is missing or does not match its pin."""
        global _CEREMONY
        if _CEREMONY is None:
            import os

            data_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
            bin_path = os.path.join(data_dir, "trusted_setup_affine.bin")
            if os.path.exists(bin_path):
                with open(bin_path, "rb") as f:
                    blob = f.read()
                if hashlib.sha256(blob).hexdigest() == CEREMONY_AFFINE_SHA256:
                    _CEREMONY = cls._from_affine_bin(blob)
                    return _CEREMONY
            _CEREMONY = cls.from_file(os.path.join(data_dir, "trusted_setup.json"))
        return _CEREMONY

    @classmethod
    def insecure_dev_setup(cls, tau: int = 0x107A5, n: int = FIELD_ELEMENTS_PER_BLOB) -> "KzgSettings":
        """Derive a setup from the KNOWN secret ``tau`` — INSECURE, test-only.

        With tau known, the Lagrange values l_j(τ) are plain field scalars:
            l_j(τ) = w_j·(τ^n − 1) / (n·(τ − w_j))
        so the setup costs one scalar-mult per point instead of an MSM."""
        roots = _roots_of_unity(n)
        tau %= R
        if tau in roots or tau == 0:
            raise KzgError("pathological dev tau")
        tn1 = (pow(tau, n, R) - 1) % R
        n_inv = pow(n, R - 2, R)
        denom_inv = _batch_inv([(tau - w) % R for w in roots])
        lags = [w * tn1 % R * dinv % R * n_inv % R
                for w, dinv in zip(roots, denom_inv)]
        if _native_on():
            from .fields import Fq

            gen_raw = native_bls.g1_generator_raw()
            g1, raws = [], []
            for lj in lags:
                raw, is_inf = native_bls.g1_mul_raw(
                    gen_raw, False, lj.to_bytes(32, "big")
                )
                if is_inf:
                    raise KzgError("pathological dev tau")
                raws.append(raw)
                g1.append(G1Point.from_affine(
                    Fq(int.from_bytes(raw[:48], "big")),
                    Fq(int.from_bytes(raw[48:], "big")),
                ))
            settings = cls(
                _bit_reversal_permutation(g1),
                [G2_GENERATOR, G2_GENERATOR * tau],
            )
            settings._g1_raw = b"".join(_bit_reversal_permutation(raws))
            return settings
        g1 = [G1_GENERATOR * lj for lj in lags]
        g1_brp = _bit_reversal_permutation(g1)
        g2 = [G2_GENERATOR, G2_GENERATOR * tau]
        return cls(g1_brp, g2)


# ---------------------------------------------------------------------------
# field-element / blob codecs
# ---------------------------------------------------------------------------


def _fr_from_bytes(data: bytes) -> int:
    """Big-endian 32-byte scalar, must be canonical (< r)."""
    if len(data) != BYTES_PER_FIELD_ELEMENT:
        raise KzgError("field element must be 32 bytes")
    v = int.from_bytes(data, "big")
    if v >= R:
        raise KzgError("field element not canonical")
    return v


def _fr_to_bytes(v: int) -> bytes:
    return (v % R).to_bytes(BYTES_PER_FIELD_ELEMENT, "big")


def _blob_to_polynomial(blob: bytes, settings: KzgSettings) -> list[int]:
    expected = settings.n * BYTES_PER_FIELD_ELEMENT
    if len(blob) != expected:
        raise KzgError(f"blob must be {expected} bytes, got {len(blob)}")
    return [
        _fr_from_bytes(blob[i * 32 : (i + 1) * 32]) for i in range(settings.n)
    ]


def _hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest(), "big") % R


# ---------------------------------------------------------------------------
# polynomial math (evaluation form over the brp domain)
# ---------------------------------------------------------------------------


# keyed by id() but ALSO holding the settings object: an entry must pin
# its owner alive, or a recycled id() could serve another setup's data.
# Bounded at a few slots (not cleared on each new setup) so a process
# alternating between two live setups — mainnet + minimal presets in one
# pytest session — doesn't rebuild the ~0.5s MSM tables on every switch.
_SETUP_CACHE_SLOTS = 4
_ROOTS_RAW: "dict[int, tuple]" = {}


def _roots_raw(settings: KzgSettings) -> bytes:
    hit = _ROOTS_RAW.get(id(settings))
    if hit is not None and hit[0] is settings:
        return hit[1]
    raw = b"".join(w.to_bytes(32, "big") for w in settings.roots_brp)
    if len(_ROOTS_RAW) >= _SETUP_CACHE_SLOTS:
        _ROOTS_RAW.pop(next(iter(_ROOTS_RAW)))  # FIFO evict oldest
    _ROOTS_RAW[id(settings)] = (settings, raw)
    return raw


def _evaluate_polynomial_in_evaluation_form(
    evals: list[int], z: int, settings: KzgSettings
) -> int:
    """Barycentric evaluation at z over the brp domain:
        p(z) = (z^n − 1)/n · Σ_i e_i·w_i/(z − w_i)
    with the in-domain short-circuit. Native Fr fast path when available
    (~25x over Python big ints at blob size); this Python body doubles
    as the cross-checked fallback."""
    n = settings.n
    z %= R
    if _native_on():
        try:
            y = native_bls.fr_eval_poly(
                b"".join(e.to_bytes(32, "big") for e in evals),
                _roots_raw(settings), n, z.to_bytes(32, "big"),
            )
            return int.from_bytes(y, "big")
        except native_bls.NativeBlsError:
            pass  # e.g. a non-power-of-two custom domain: Python below
    roots = settings.roots_brp
    for i, w in enumerate(roots):
        if z == w:
            return evals[i]
    inv_zw = _batch_inv([(z - w) % R for w in roots])
    total = 0
    for e, w, inv in zip(evals, roots, inv_zw):
        total = (total + e * w % R * inv) % R
    zn1 = (pow(z, n, R) - 1) % R
    n_inv = pow(n, R - 2, R)
    return total * zn1 % R * n_inv % R


def _g1_lincomb(points: list[G1Point], scalars: list[int]) -> G1Point:
    """Σ s_i·P_i (naive; the native Pippenger MSM replaces this when on)."""
    acc = G1Point.infinity()
    for p, s in zip(points, scalars):
        s %= R
        if s == 0:
            continue
        acc = acc + p * s
    return acc


_MSM_PREPARED: "dict[int, object]" = {}


def _setup_lincomb(settings: KzgSettings, scalars: list[int]) -> bytes:
    """Σ s_i·L_i over the setup's Lagrange points, as compressed G1 bytes —
    the MSM hot path. The setup is FIXED, so the first call precomputes
    window-shifted copies of every Lagrange point native-side and each
    later commitment/proof is a single signed-digit bucket pass (~1.6x
    over windowed Pippenger at blob size)."""
    if _native_on():
        return _setup_lincomb_raw(
            settings, b"".join((s % R).to_bytes(32, "big") for s in scalars)
        )
    return _g1_lincomb(settings.g1_lagrange_brp, scalars).serialize()


def _setup_lincomb_raw(settings: KzgSettings, sc: bytes) -> bytes:
    """Native-only variant taking pre-serialized 32-byte scalars (the
    native quotient builder emits exactly this layout)."""
    hit = _MSM_PREPARED.get(id(settings))
    if hit is not None and hit[0] is settings:
        pre = hit[1]
    else:
        try:
            pre = native_bls.PreparedMsm(settings.g1_raw(), settings.n)
        except native_bls.NativeBlsError:
            pre = False  # precompute unavailable: plain Pippenger
        if len(_MSM_PREPARED) >= _SETUP_CACHE_SLOTS:
            _MSM_PREPARED.pop(next(iter(_MSM_PREPARED)))  # FIFO evict
        _MSM_PREPARED[id(settings)] = (settings, pre)
    if pre:
        raw, is_inf = pre.run(sc)
    else:
        raw, is_inf = native_bls.g1_msm(settings.g1_raw(), sc, settings.n)
    return native_bls.g1_compress_raw(raw, is_inf)


def _g1_raw_neg(raw: bytes) -> bytes:
    from .fields import P as _P

    y = int.from_bytes(raw[48:], "big")
    return raw[:48] + ((_P - y) % _P).to_bytes(48, "big")


def _decompress_or_kzg_error(data: bytes, what: str) -> tuple[bytes, bool]:
    rc, raw, is_inf = native_bls.g1_decompress(bytes(data), check_subgroup=True)
    if rc != 0:
        raise KzgError(f"invalid {what}: {native_bls.decode_error_message(rc)}")
    return raw, is_inf


# ---------------------------------------------------------------------------
# public KZG operations (EIP-4844 semantics)
# ---------------------------------------------------------------------------


def _check_blob(blob: bytes, settings: KzgSettings) -> bytes:
    """Length + canonicality gate for the native blob-direct fast paths
    (blob bytes ARE the evaluation-form scalars — no int round-trip)."""
    blob = bytes(blob)
    expected = settings.n * BYTES_PER_FIELD_ELEMENT
    if len(blob) != expected:
        raise KzgError(f"blob must be {expected} bytes, got {len(blob)}")
    if not native_bls.fr_validate(blob, settings.n):
        raise KzgError("field element not canonical")
    return blob


def blob_to_kzg_commitment(blob: bytes, settings: KzgSettings) -> KzgCommitment:
    if _native_on():
        return KzgCommitment(_setup_lincomb_raw(settings, _check_blob(blob, settings)))
    evals = _blob_to_polynomial(blob, settings)
    return KzgCommitment(_setup_lincomb(settings, evals))


def compute_kzg_proof(blob: bytes, z_bytes: bytes, settings: KzgSettings) -> tuple[KzgProof, bytes]:
    """Returns (proof, y_bytes) for evaluation at z (kzg.rs:71)."""
    z = _fr_from_bytes(z_bytes)
    if _native_on():
        blob_proof = _compute_kzg_proof_from_blob(blob, z, settings)
        if blob_proof is not None:
            proof, y_b = blob_proof
            return proof, y_b
    evals = _blob_to_polynomial(blob, settings)
    proof, y = _compute_kzg_proof_impl(evals, z, settings)
    return proof, _fr_to_bytes(y)


def _compute_kzg_proof_from_blob(
    blob: bytes, z: int, settings: KzgSettings
) -> "tuple[KzgProof, bytes] | None":
    """Native blob-direct proof: the quotient scalars come back in MSM
    wire layout, untouched by Python ints. None = fall back (e.g. a
    non-power-of-two custom domain)."""
    blob = _check_blob(blob, settings)
    try:
        y_b, q_b = native_bls.fr_eval_and_quotient(
            blob, _roots_raw(settings), settings.n, (z % R).to_bytes(32, "big")
        )
    except native_bls.NativeBlsError:
        return None
    return KzgProof(_setup_lincomb_raw(settings, q_b)), y_b


def _compute_kzg_proof_impl(
    evals: list[int], z: int, settings: KzgSettings
) -> tuple[KzgProof, int]:
    n = settings.n
    if _native_on():
        try:
            y_b, q_b = native_bls.fr_eval_and_quotient(
                b"".join(e.to_bytes(32, "big") for e in evals),
                _roots_raw(settings), n, (z % R).to_bytes(32, "big"),
            )
            return (
                KzgProof(_setup_lincomb_raw(settings, q_b)),
                int.from_bytes(y_b, "big"),
            )
        except native_bls.NativeBlsError:
            pass  # non-power-of-two custom domain: Python path below
    roots = settings.roots_brp
    y = _evaluate_polynomial_in_evaluation_form(evals, z, settings)

    # quotient q(X) = (p(X) − y)/(X − z) in evaluation form
    q = [0] * n
    if z in roots:
        # z on the domain: use the L'Hôpital-style special column
        m = roots.index(z)
        others = [i for i in range(n) if i != m]
        inv_wz = _batch_inv([(roots[i] - z) % R for i in others])
        inv_zzw = _batch_inv([z * (z - roots[i]) % R for i in others])
        acc = 0
        for i, iwz, izzw in zip(others, inv_wz, inv_zzw):
            q[i] = (evals[i] - y) % R * iwz % R
            # q_m = Σ_{i≠m} (e_i − y)·w_i / (z·(z − w_i))
            acc = (acc + (evals[i] - y) % R * roots[i] % R * izzw) % R
        q[m] = acc
    else:
        inv_wz = _batch_inv([(w - z) % R for w in roots])
        for i in range(n):
            q[i] = (evals[i] - y) % R * inv_wz[i] % R

    return KzgProof(_setup_lincomb(settings, q)), y


def verify_kzg_proof(
    commitment: bytes, z_bytes: bytes, y_bytes: bytes, proof: bytes, settings: KzgSettings
) -> bool:
    """Pairing check e(P − y·g1, g2) == e(proof, [τ]_2 − z·g2) (kzg.rs:101)."""
    z = _fr_from_bytes(z_bytes)
    y = _fr_from_bytes(y_bytes)
    return _verify_kzg_proof_bytes(bytes(commitment), z, y, bytes(proof), settings)


def _verify_kzg_proof_bytes(
    commitment: bytes, z: int, y: int, proof: bytes, settings: KzgSettings
) -> bool:
    if _native_on():
        c_raw, c_inf = _decompress_or_kzg_error(commitment, "commitment")
        p_raw, p_inf = _decompress_or_kzg_error(proof, "proof")
        # p_minus_y = C + (−y)·g1
        yg, yg_inf = native_bls.g1_mul_raw(
            native_bls.g1_generator_raw(), False, ((-y) % R).to_bytes(32, "big")
        )
        pm, pm_inf = native_bls.g1_add_raw(c_raw, c_inf, yg, yg_inf)
        # x_minus_z = [τ]_2 + (−z)·[1]_2
        g2r = settings.g2_raw()
        xz, xz_inf = native_bls.g2_msm(
            g2r[1] + g2r[0],
            (1).to_bytes(32, "big") + ((-z) % R).to_bytes(32, "big"),
            2,
        )
        neg_pm = pm if pm_inf else _g1_raw_neg(pm)
        return native_bls.pairing_product_is_one_raw(
            [(neg_pm, pm_inf), (p_raw, p_inf)],
            [(g2r[0], False), (xz, xz_inf)],
        )
    try:
        c = G1Point.deserialize(commitment)
        pi = G1Point.deserialize(proof)
    except InvalidPointError as exc:
        raise KzgError(str(exc)) from exc
    return _verify_kzg_proof_impl(c, z, y, pi, settings)


def _verify_kzg_proof_impl(
    commitment: G1Point, z: int, y: int, proof: G1Point, settings: KzgSettings
) -> bool:
    from .pairing import pairing_product_is_one

    g2 = settings.g2_monomial[0]
    tau_g2 = settings.g2_monomial[1]
    p_minus_y = commitment - G1_GENERATOR * y
    x_minus_z = tau_g2 - g2 * z
    # e(P − y, −g2) · e(proof, [τ−z]_2) == 1
    return pairing_product_is_one([(-p_minus_y, g2), (proof, x_minus_z)])


def _compute_challenge(blob: bytes, commitment: bytes, settings: KzgSettings) -> int:
    """Fiat-Shamir challenge binding blob+commitment (spec compute_challenge)."""
    degree_poly = settings.n.to_bytes(16, "big")
    return _hash_to_bls_field(
        FIAT_SHAMIR_PROTOCOL_DOMAIN + degree_poly + blob + bytes(commitment)
    )


def compute_blob_kzg_proof(
    blob: bytes, commitment: bytes, settings: KzgSettings
) -> KzgProof:
    if _native_on():
        _decompress_or_kzg_error(bytes(commitment), "commitment")
    else:
        try:
            G1Point.deserialize(bytes(commitment))  # validate before transcript
        except InvalidPointError as exc:
            raise KzgError(f"invalid commitment: {exc}") from exc
    z = _compute_challenge(blob, commitment, settings)
    if _native_on():
        blob_proof = _compute_kzg_proof_from_blob(blob, z, settings)
        if blob_proof is not None:
            return blob_proof[0]
    evals = _blob_to_polynomial(blob, settings)
    proof, _ = _compute_kzg_proof_impl(evals, z, settings)
    return proof


def _evaluate_blob_at(blob: bytes, z: int, settings: KzgSettings) -> int:
    """p(z) from the raw blob bytes: native blob-direct when available,
    Python int path otherwise (identical semantics and errors)."""
    if _native_on():
        try:
            y = native_bls.fr_eval_poly(
                _check_blob(blob, settings), _roots_raw(settings),
                settings.n, (z % R).to_bytes(32, "big"),
            )
            return int.from_bytes(y, "big")
        except native_bls.NativeBlsError:
            pass  # non-power-of-two custom domain
    evals = _blob_to_polynomial(blob, settings)
    return _evaluate_polynomial_in_evaluation_form(evals, z, settings)


def verify_blob_kzg_proof(
    blob: bytes, commitment: bytes, proof: bytes, settings: KzgSettings
) -> bool:
    z = _compute_challenge(blob, commitment, settings)
    y = _evaluate_blob_at(blob, z, settings)
    return _verify_kzg_proof_bytes(bytes(commitment), z, y, bytes(proof), settings)


def verify_blob_kzg_proof_batch(
    blobs: list[bytes],
    commitments: list[bytes],
    proofs: list[bytes],
    settings: KzgSettings,
) -> bool:
    """Random-linear-combination batch verification (kzg.rs:139): one
    two-pairing check regardless of batch size."""
    if not (len(blobs) == len(commitments) == len(proofs)):
        raise KzgError("batch length mismatch")
    if not blobs:
        return True
    if len(blobs) == 1:
        return verify_blob_kzg_proof(blobs[0], commitments[0], proofs[0], settings)

    cs = pis = None
    if _native_on():
        c_raws = [_decompress_or_kzg_error(bytes(c), "commitment") for c in commitments]
        p_raws = [_decompress_or_kzg_error(bytes(p), "proof") for p in proofs]
    else:
        try:
            cs = [G1Point.deserialize(bytes(c)) for c in commitments]
            pis = [G1Point.deserialize(bytes(p)) for p in proofs]
        except InvalidPointError as exc:
            raise KzgError(str(exc)) from exc

    zs, ys = [], []
    for blob, commitment in zip(blobs, commitments):
        z = _compute_challenge(blob, commitment, settings)
        zs.append(z)
        ys.append(_evaluate_blob_at(blob, z, settings))

    # r-powers from a transcript binding every (commitment, z, y, proof)
    data = RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
    data += settings.n.to_bytes(8, "big")
    data += len(blobs).to_bytes(8, "big")
    for c, z, y, p in zip(commitments, zs, ys, proofs):
        data += bytes(c) + _fr_to_bytes(z) + _fr_to_bytes(y) + bytes(p)
    r = _hash_to_bls_field(data)
    r_powers = [1]
    for _ in range(len(blobs) - 1):
        r_powers.append(r_powers[-1] * r % R)

    if _native_on():
        # Σ r_i(C_i − y_i·g1) = Σ r_i·C_i − (Σ r_i·y_i)·g1; all finite inputs
        # (decompress above rejects nothing silently; infinity C/π handled
        # by padding the MSM input with zero scalars)
        def msm(raws_inf, scalars):
            finite = [(raw, s) for (raw, inf), s in zip(raws_inf, scalars) if not inf]
            if not finite:
                return bytes(96), True
            return native_bls.g1_msm(
                b"".join(r for r, _ in finite),
                b"".join((s % R).to_bytes(32, "big") for _, s in finite),
                len(finite),
            )

        proof_l, proof_l_inf = msm(p_raws, r_powers)
        proof_z_l, proof_z_l_inf = msm(
            p_raws, [rp * z % R for rp, z in zip(r_powers, zs)]
        )
        c_l, c_l_inf = msm(c_raws, r_powers)
        sum_ry = sum(rp * y % R for rp, y in zip(r_powers, ys)) % R
        yg, yg_inf = native_bls.g1_mul_raw(
            native_bls.g1_generator_raw(), False, ((-sum_ry) % R).to_bytes(32, "big")
        )
        cy_l, cy_l_inf = native_bls.g1_add_raw(c_l, c_l_inf, yg, yg_inf)
        lhs, lhs_inf = native_bls.g1_add_raw(cy_l, cy_l_inf, proof_z_l, proof_z_l_inf)
        neg_lhs = lhs if lhs_inf else _g1_raw_neg(lhs)
        g2r = settings.g2_raw()
        return native_bls.pairing_product_is_one_raw(
            [(neg_lhs, lhs_inf), (proof_l, proof_l_inf)],
            [(g2r[0], False), (g2r[1], False)],
        )

    proof_lincomb = _g1_lincomb(pis, r_powers)
    proof_z_lincomb = _g1_lincomb(
        pis, [rp * z % R for rp, z in zip(r_powers, zs)]
    )
    c_minus_y = [c - G1_GENERATOR * y for c, y in zip(cs, ys)]
    c_minus_y_lincomb = _g1_lincomb(c_minus_y, r_powers)

    from .pairing import pairing_product_is_one

    g2 = settings.g2_monomial[0]
    tau_g2 = settings.g2_monomial[1]
    lhs = c_minus_y_lincomb + proof_z_lincomb
    return pairing_product_is_one([(-lhs, g2), (proof_lincomb, tau_g2)])
