"""Derive the 3-isogeny used by the G2 simplified-SWU map (RFC 9380 §8.8.2).

The RFC publishes the isogeny's rational-map coefficients as constants
(Appendix E.3); offline, we re-derive them from first principles:

  1. The SSWU map targets the isogenous curve
         E'': y² = x³ + A'x + B',  A' = 240u,  B' = 1012(1+u)  over Fq2.
  2. A rational 3-isogeny φ: E'' → E' (the G2 twist, y² = x³ + 4(1+u))
     has kernel {O, (x0, ±y0)} where x0 ∈ Fq2 is a root of the 3-division
     polynomial ψ₃(x) = 3x⁴ + 6A'x² + 12B'x − A'².
  3. Vélu's formulas give the rational maps and codomain; the root whose
     codomain is exactly E' identifies the kernel the RFC chose.

Run as a module to (re)generate ``g2_isogeny.py``; the test suite re-runs
the derivation and checks the stored constants (and that mapped points land
on E' and the map is a group homomorphism).
"""

from __future__ import annotations

from .fields import Fq, Fq2, P

# SSWU target curve E'' parameters (RFC 9380 §8.8.2).
ISO_A = Fq2.from_ints(0, 240)
ISO_B = Fq2.from_ints(1012, 1012)
SSWU_Z = Fq2(Fq(P - 2), Fq(P - 1))  # -(2 + u)

# E' (the G2 twist) coefficients.
E2_A = Fq2.zero()
E2_B = Fq2.from_ints(4, 4)


# -- minimal polynomial arithmetic over Fq2 ---------------------------------
# polynomials are coefficient lists, low degree first


def _poly_trim(a: list[Fq2]) -> list[Fq2]:
    while a and a[-1].is_zero():
        a.pop()
    return a


def _poly_mul(a: list[Fq2], b: list[Fq2]) -> list[Fq2]:
    out = [Fq2.zero()] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai.is_zero():
            continue
        for j, bj in enumerate(b):
            out[i + j] = out[i + j] + ai * bj
    return _poly_trim(out)


def _poly_mod(a: list[Fq2], m: list[Fq2]) -> list[Fq2]:
    a = list(a)
    inv_lead = m[-1].inverse()
    while len(a) >= len(m):
        coef = a[-1] * inv_lead
        shift = len(a) - len(m)
        for i, mi in enumerate(m):
            a[shift + i] = a[shift + i] - coef * mi
        _poly_trim(a)
        if not a:
            break
    return a


def _poly_pow_mod(base: list[Fq2], e: int, m: list[Fq2]) -> list[Fq2]:
    result = [Fq2.one()]
    base = _poly_mod(base, m)
    while e:
        if e & 1:
            result = _poly_mod(_poly_mul(result, base), m)
        base = _poly_mod(_poly_mul(base, base), m)
        e >>= 1
    return result


def _poly_gcd(a: list[Fq2], b: list[Fq2]) -> list[Fq2]:
    a, b = list(a), list(b)
    while b:
        a, b = b, _poly_mod(a, b)
    # monic
    inv = a[-1].inverse()
    return [c * inv for c in a]


def _poly_eval(a: list[Fq2], x: Fq2) -> Fq2:
    acc = Fq2.zero()
    for c in reversed(a):
        acc = acc * x + c
    return acc


def _quartic_roots_in_fq2(poly: list[Fq2]) -> list[Fq2]:
    """Roots of ``poly`` (≤ quartic) lying in Fq2, via gcd with x^(p²) − x."""
    q = P * P
    xq = _poly_pow_mod([Fq2.zero(), Fq2.one()], q, poly)  # x^q mod poly
    xq_minus_x = _poly_trim(
        [xq[i] if i != 1 else xq[i] - Fq2.one() for i in range(len(xq))]
        if len(xq) > 1
        else [xq[0] if xq else Fq2.zero(), -Fq2.one()]
    )
    split = _poly_gcd(poly, xq_minus_x)
    # extract roots of the (low-degree) split factor by degree cases
    roots: list[Fq2] = []
    deg = len(split) - 1
    if deg == 0:
        return roots
    if deg == 1:
        roots.append(-(split[0] * split[1].inverse()))
        return roots
    if deg == 2:
        c, b, a = split[0], split[1], split[2]
        disc = b * b - Fq2.from_ints(4, 0) * a * c
        s = disc.sqrt()
        if s is not None:
            inv2a = (a + a).inverse()
            roots.append((-b + s) * inv2a)
            roots.append((-b - s) * inv2a)
        return roots
    # deg 3/4: find one root by trying linear gcds with random shifts —
    # fall back to exhaustive factor peeling via repeated quadratic solves
    raise NotImplementedError(f"unexpected split degree {deg}")


def derive() -> dict:
    """Derive the isogeny kernel and the scaling onto E'.

    Velu's codomain for the rational kernel root is y² = x³ + 2916(1+u) =
    x³ + 4·3⁶(1+u); composing with the isomorphism (x,y) → (x/9, y/27)
    (scaling c = 1/3, c⁴a = 0, c⁶b = b/729) lands exactly on E'. The
    composed coefficients reproduce the RFC 9380 Appendix E.3 constants
    (k_(1,0) = 0x5c759507…97d6·(1+u) etc.)."""
    A, B = ISO_A, ISO_B
    three = Fq2.from_ints(3, 0)
    six = Fq2.from_ints(6, 0)
    twelve = Fq2.from_ints(12, 0)
    # ψ₃(x) = 3x⁴ + 6Ax² + 12Bx − A²
    psi3 = _poly_trim([-(A * A), twelve * B, six * A, Fq2.zero(), three])
    roots = _quartic_roots_in_fq2(psi3)
    if not roots:
        raise RuntimeError("no rational 3-torsion x-coordinate found")

    nine = Fq2.from_ints(9, 0)
    for x0 in roots:
        # Vélu sums for the kernel {(x0, ±y0)} (one representative):
        gx = three * x0 * x0 + A
        t = gx + gx                       # 2(3x0² + A)
        u4y2 = (x0 * x0 * x0 + A * x0 + B)
        u = Fq2.from_ints(4, 0) * u4y2    # 4y0² (rational in x0)
        w = u + x0 * t
        a_new = A - Fq2.from_ints(5, 0) * t
        b_new = B - Fq2.from_ints(7, 0) * w
        # accept codomains reachable from E' by the scaling (x,y)→(c²x,c³y)
        if a_new == E2_A and b_new == E2_B * nine * nine * nine:
            # b_new = 729·b' → c = 1/3
            return {"x0": x0, "t": t, "u": u}
        if a_new == E2_A and b_new == E2_B:
            return {"x0": x0, "t": t, "u": u}
    raise RuntimeError(
        "no kernel root maps E'' onto (a scaling of) E': "
        + ", ".join(repr(r) for r in roots)
    )


def rational_maps(consts: dict):
    """Composed rational maps (Vélu ∘ scaling) as coefficient lists
    (low-first) over Fq2, in the RFC's monic-denominator normal form:

        X(x) = x_num(x) / x_den(x),   x_den = (x − x0)²       (monic, deg 2)
        Y(x,y) = y · y_num(x) / y_den(x),  y_den = (x − x0)³  (monic, deg 3)

    Vélu: x_num = x(x−x0)² + t(x−x0) + u,  y_num = (x−x0)³ − t(x−x0) − 2u;
    scaling c = −1/3 divides x_num by c² = 1/9 and y_num by c³ = −1/27.
    (Both ±1/3 satisfy c⁶ = 1/729; the RFC's constants correspond to −1/3 —
    with +1/3 every mapped point comes out negated, which is self-consistent
    but not interoperable. Anchored by the k_(3,3) constant check in tests.)
    """
    x0, t, u = consts["x0"], consts["t"], consts["u"]
    one = Fq2.one()
    # (x - x0)^2 and ^3
    d1 = [-x0, one]
    d2 = _poly_mul(d1, d1)
    d3 = _poly_mul(d2, d1)
    # x_num = x·(x−x0)² + t·(x−x0) + u
    x_num = [Fq2.zero()] + d2
    x_num = [
        x_num[0] + t * d1[0] + u,
        x_num[1] + t * d1[1],
        x_num[2],
        x_num[3],
    ]
    y_num = [
        d3[0] - t * d1[0] - (u + u),
        d3[1] - t * d1[1],
        d3[2],
        d3[3],
    ]
    inv9 = Fq2.from_ints(9, 0).inverse()
    neg_inv27 = -(Fq2.from_ints(27, 0).inverse())
    x_num = [c * inv9 for c in x_num]
    y_num = [c * neg_inv27 for c in y_num]
    return {"x_num": x_num, "x_den": d2, "y_num": y_num, "y_den": d3}


def _fq2_literal(v: Fq2) -> str:
    return f"Fq2(Fq(0x{v.c0.n:x}), Fq(0x{v.c1.n:x}))"


def generate_module() -> str:
    consts = derive()
    maps = rational_maps(consts)
    lines = [
        '"""G2 SSWU 3-isogeny constants — GENERATED by _isogeny_derive.py.',
        "",
        "Derived via Velu's formulas from the RFC 9380 §8.8.2 curve parameters;",
        "the derivation is re-run and cross-checked by tests/test_bls.py.",
        '"""',
        "",
        "from .fields import Fq, Fq2",
        "",
        f"KERNEL_X0 = {_fq2_literal(consts['x0'])}",
        "",
    ]
    for name in ("x_num", "x_den", "y_num", "y_den"):
        lines.append(f"{name.upper()} = [")
        for c in maps[name]:
            lines.append(f"    {_fq2_literal(c)},")
        lines.append("]")
        lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    import pathlib

    out = pathlib.Path(__file__).parent / "g2_isogeny.py"
    out.write_text(generate_module())
    print(f"wrote {out}")
