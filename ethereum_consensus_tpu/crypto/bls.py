"""BLS signatures over BLS12-381 (min_pk: public keys in G1, signatures in
G2), with the Ethereum consensus-layer semantics.

Reference parity: ethereum-consensus/src/crypto/bls.rs — SecretKey/PublicKey/
Signature types, sign, verify_signature (:64-112), aggregate,
aggregate_verify, fast_aggregate_verify (:114), eth_aggregate_public_keys
(:135), eth_fast_aggregate_verify (:150, the infinity-signature rule), and
the SHA-256 `hash` helper (:12). The reference wraps the blst C/assembly
library; here the pure-Python oracle (fields/curves/pairing/hash_to_curve)
provides exact semantics, and batched device paths hook in above the
multi-pairing product.
"""

from __future__ import annotations

import hashlib
import secrets

from ..error import (
    InvalidPublicKeyError,
    InvalidSecretKeyError,
    InvalidSignatureError,
)
from .curves import (
    G1_GENERATOR,
    G1Point,
    G2Point,
    InvalidPointError,
)
from .fields import R
from .hash_to_curve import ETH_DST, hash_to_g2
from .pairing import pairing_product_is_one

__all__ = [
    "SecretKey",
    "PublicKey",
    "Signature",
    "hash",
    "aggregate",
    "aggregate_verify",
    "fast_aggregate_verify",
    "eth_aggregate_public_keys",
    "eth_fast_aggregate_verify",
    "SECRET_KEY_SIZE",
    "PUBLIC_KEY_SIZE",
    "SIGNATURE_SIZE",
]

SECRET_KEY_SIZE = 32
PUBLIC_KEY_SIZE = 48
SIGNATURE_SIZE = 96


def hash(data: bytes) -> bytes:  # noqa: A001 - mirrors crypto::hash
    """SHA-256 (crypto/bls.rs:12-20)."""
    return hashlib.sha256(data).digest()


class SecretKey:
    """Scalar in [1, r-1]. (bls.rs SecretKey)"""

    __slots__ = ("_scalar",)

    def __init__(self, scalar: int):
        if not 0 < scalar < R:
            raise InvalidSecretKeyError("secret key scalar out of range")
        self._scalar = scalar

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_SIZE:
            raise InvalidSecretKeyError(
                f"secret key must be {SECRET_KEY_SIZE} bytes, got {len(data)}"
            )
        return cls(int.from_bytes(data, "big"))

    @classmethod
    def random(cls) -> "SecretKey":
        # 384-bit draw reduced mod r: bias < 2^-129 (the RFC 9380
        # hash_to_field approach), unlike a 255-bit draw which skews
        # low scalars by 1.5x.
        while True:
            candidate = int.from_bytes(secrets.token_bytes(48), "big") % R
            if candidate != 0:
                return cls(candidate)

    def to_bytes(self) -> bytes:
        return self._scalar.to_bytes(SECRET_KEY_SIZE, "big")

    def public_key(self) -> "PublicKey":
        return PublicKey(G1_GENERATOR * self._scalar)

    def sign(self, message: bytes, dst: bytes = ETH_DST) -> "Signature":
        return Signature(hash_to_g2(message, dst) * self._scalar)

    def __repr__(self) -> str:
        return "SecretKey(...)"  # never print key material

    def __eq__(self, other) -> bool:
        return isinstance(other, SecretKey) and self._scalar == other._scalar

    __hash__ = None


class PublicKey:
    """G1 point, 48-byte compressed. Infinity is rejected (blst
    key_validate semantics: a pubkey must be a valid non-identity subgroup
    point)."""

    __slots__ = ("point",)

    def __init__(self, point: G1Point):
        self.point = point

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        try:
            point = G1Point.deserialize(bytes(data))
        except InvalidPointError as exc:
            raise InvalidPublicKeyError(str(exc)) from exc
        if point.is_infinity():
            raise InvalidPublicKeyError("public key cannot be the identity")
        return cls(point)

    def to_bytes(self) -> bytes:
        return self.point.serialize()

    def validate(self) -> None:
        if self.point.is_infinity():
            raise InvalidPublicKeyError("public key cannot be the identity")
        if not self.point.is_on_curve() or not self.point.in_subgroup():
            raise InvalidPublicKeyError("public key not in G1 subgroup")

    def __eq__(self, other) -> bool:
        return isinstance(other, PublicKey) and self.point == other.point

    def __hash__(self):
        # NB: bare `hash` in this module is the SHA-256 helper
        return self.to_bytes().__hash__()

    def __repr__(self) -> str:
        return f"PublicKey(0x{self.to_bytes().hex()})"


class Signature:
    """G2 point, 96-byte compressed. The identity encoding is accepted at
    parse time (it is needed for the eth_fast_aggregate_verify rule) but
    never verifies against a real message/pubkey pair."""

    __slots__ = ("point",)

    def __init__(self, point: G2Point):
        self.point = point

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        try:
            return cls(G2Point.deserialize(bytes(data)))
        except InvalidPointError as exc:
            raise InvalidSignatureError(str(exc)) from exc

    def to_bytes(self) -> bytes:
        return self.point.serialize()

    def is_infinity(self) -> bool:
        return self.point.is_infinity()

    def __eq__(self, other) -> bool:
        return isinstance(other, Signature) and self.point == other.point

    def __hash__(self):
        # NB: bare `hash` in this module is the SHA-256 helper
        return self.to_bytes().__hash__()

    def __repr__(self) -> str:
        return f"Signature(0x{self.to_bytes().hex()})"


# ---------------------------------------------------------------------------
# Verification primitives
# ---------------------------------------------------------------------------


def verify_signature(
    public_key: PublicKey, message: bytes, signature: Signature, dst: bytes = ETH_DST
) -> bool:
    """e(pk, H(m)) == e(g1, sig)  (bls.rs verify_signature)."""
    if signature.is_infinity() or public_key.point.is_infinity():
        return False
    h = hash_to_g2(message, dst)
    return pairing_product_is_one(
        [(public_key.point, h), (-G1_GENERATOR, signature.point)]
    )


def aggregate(signatures: list[Signature]) -> Signature:
    """Sum of signature points; errors on empty input (bls.rs aggregate)."""
    if not signatures:
        raise InvalidSignatureError("cannot aggregate zero signatures")
    acc = G2Point.infinity()
    for sig in signatures:
        acc = acc + sig.point
    return Signature(acc)


def aggregate_verify(
    public_keys: list[PublicKey],
    messages: list[bytes],
    signature: Signature,
    dst: bytes = ETH_DST,
) -> bool:
    """Π e(pk_i, H(m_i)) == e(g1, sig) (bls.rs aggregate_verify)."""
    if len(public_keys) != len(messages) or not public_keys:
        return False
    if signature.is_infinity():
        return False
    if any(pk.point.is_infinity() for pk in public_keys):
        return False
    pairs: list[tuple[G1Point, G2Point]] = [
        (pk.point, hash_to_g2(msg, dst))
        for pk, msg in zip(public_keys, messages)
    ]
    pairs.append((-G1_GENERATOR, signature.point))
    return pairing_product_is_one(pairs)


def fast_aggregate_verify(
    public_keys: list[PublicKey],
    message: bytes,
    signature: Signature,
    dst: bytes = ETH_DST,
) -> bool:
    """All keys sign the same message: aggregate the pubkeys, verify once
    (bls.rs fast_aggregate_verify:114)."""
    if not public_keys:
        return False
    acc = G1Point.infinity()
    for pk in public_keys:
        acc = acc + pk.point
    return verify_signature(PublicKey(acc), message, signature, dst)


def eth_aggregate_public_keys(public_keys: list[PublicKey]) -> PublicKey:
    """Spec `eth_aggregate_pubkeys` (bls.rs eth_aggregate_public_keys:135):
    errors on empty input or invalid keys; the aggregate may legitimately be
    used for sync-committee processing."""
    if not public_keys:
        raise InvalidPublicKeyError("cannot aggregate zero public keys")
    acc = G1Point.infinity()
    for pk in public_keys:
        pk.validate()
        acc = acc + pk.point
    return PublicKey(acc)


def eth_fast_aggregate_verify(
    public_keys: list[PublicKey],
    message: bytes,
    signature: Signature,
    dst: bytes = ETH_DST,
) -> bool:
    """Spec `eth_fast_aggregate_verify` (bls.rs:150): returns True for an
    empty key list when the signature is the G2 identity encoding (the
    sync-aggregate "no participants" rule), otherwise defers to
    fast_aggregate_verify."""
    if not public_keys and signature.is_infinity():
        return True
    return fast_aggregate_verify(public_keys, message, signature, dst)
