"""BLS signatures over BLS12-381 (min_pk: public keys in G1, signatures in
G2), with the Ethereum consensus-layer semantics.

Reference parity: ethereum-consensus/src/crypto/bls.rs — SecretKey/PublicKey/
Signature types, sign, verify_signature (:64-112), aggregate,
aggregate_verify, fast_aggregate_verify (:114), eth_aggregate_public_keys
(:135), eth_fast_aggregate_verify (:150, the infinity-signature rule), and
the SHA-256 `hash` helper (:12).

Two backends, same semantics:
  * native — the from-scratch C++ library (native/bls12_381.cpp), playing
    exactly blst's role for the reference (Cargo.toml:22). Default when a
    toolchain is present; ~300x the oracle per verify.
  * python — the pure-Python oracle (fields/curves/pairing/hash_to_curve),
    kept as the transparent correctness reference.
Select with EC_BLS_BACKEND={auto,native,python}; tests cross-check both.

Batched verification: `verify_signature_sets` checks N independent
(pubkeys, message, signature) sets with one random-linear-combination
multi-pairing (N+1 Miller loops, ONE final exponentiation) and falls back
to per-set verification only to attribute failures.
"""

from __future__ import annotations

import hashlib
import secrets
import threading

from .. import _device_flags, _env
from ..error import (
    InvalidPublicKeyError,
    InvalidSecretKeyError,
    InvalidSignatureError,
)
from ..native import bls as native_bls
from ..telemetry import device as _device_obs
from ..telemetry import metrics as _metrics
from ..utils import trace
from .curves import (
    G1_GENERATOR,
    G1Point,
    G2Point,
    InvalidPointError,
)
from .fields import R
from .hash_to_curve import ETH_DST, hash_to_g2
from .pairing import pairing_product_is_one

__all__ = [
    "SecretKey",
    "PublicKey",
    "Signature",
    "SignatureSet",
    "hash",
    "aggregate",
    "aggregate_verify",
    "fast_aggregate_verify",
    "eth_aggregate_public_keys",
    "eth_fast_aggregate_verify",
    "verify_signature",
    "verify_signature_sets",
    "verify_signature_sets_async",
    "warm_pubkey_cache",
    "warm_raw_keys",
    "backend_name",
    "SECRET_KEY_SIZE",
    "PUBLIC_KEY_SIZE",
    "SIGNATURE_SIZE",
]

SECRET_KEY_SIZE = 32
PUBLIC_KEY_SIZE = 48
SIGNATURE_SIZE = 96

_INFINITY_FLAG = 0x40

_BACKEND: str | None = None
# guards the one-time backend resolution: the chain pipeline's stage A
# and the background verifier can both hit a cold _native() first; the
# computation is idempotent but the double-checked lock keeps the
# resolve-once contract explicit (and speclint-clean). Reads stay
# lock-free — after the first store the value never changes.
_BACKEND_LOCK = threading.Lock()


def backend_name() -> str:
    """Active backend: "native" or "python" (EC_BLS_BACKEND to override)."""
    global _BACKEND
    if _BACKEND is None:
        with _BACKEND_LOCK:
            if _BACKEND is None:
                mode = _env.raw("EC_BLS_BACKEND", "auto")
                if mode == "python":
                    _BACKEND = "python"
                else:
                    _BACKEND = "native" if native_bls.available() else "python"
    return _BACKEND


def _native() -> bool:
    return backend_name() == "native"


def hash(data: bytes) -> bytes:  # noqa: A001 - mirrors crypto::hash
    """SHA-256 (crypto/bls.rs:12-20)."""
    return hashlib.sha256(data).digest()


class SecretKey:
    """Scalar in [1, r-1]. (bls.rs SecretKey)"""

    __slots__ = ("_scalar",)

    def __init__(self, scalar: int):
        if not 0 < scalar < R:
            raise InvalidSecretKeyError("secret key scalar out of range")
        self._scalar = scalar

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_SIZE:
            raise InvalidSecretKeyError(
                f"secret key must be {SECRET_KEY_SIZE} bytes, got {len(data)}"
            )
        return cls(int.from_bytes(data, "big"))

    @classmethod
    def random(cls) -> "SecretKey":
        # 384-bit draw reduced mod r: bias < 2^-129 (the RFC 9380
        # hash_to_field approach), unlike a 255-bit draw which skews
        # low scalars by 1.5x.
        while True:
            candidate = int.from_bytes(secrets.token_bytes(48), "big") % R
            if candidate != 0:
                return cls(candidate)

    def to_bytes(self) -> bytes:
        return self._scalar.to_bytes(SECRET_KEY_SIZE, "big")

    def public_key(self) -> "PublicKey":
        if _native():
            return PublicKey._from_valid_bytes(native_bls.sk_to_pk(self.to_bytes()))
        return PublicKey(G1_GENERATOR * self._scalar)

    def sign(self, message: bytes, dst: bytes = ETH_DST) -> "Signature":
        if _native():
            return Signature._from_valid_bytes(
                native_bls.sign(self.to_bytes(), message, dst)
            )
        return Signature(hash_to_g2(message, dst) * self._scalar)

    def __repr__(self) -> str:
        return "SecretKey(...)"  # never print key material

    def __eq__(self, other) -> bool:
        return isinstance(other, SecretKey) and self._scalar == other._scalar

    __hash__ = None


# process-wide decompressed-pubkey cache (FIFO eviction): compressed48 →
# affine raw96 of a VALID key. Entries enter ONLY from from_bytes'
# subgroup-checked, identity-rejecting decompression, so a hit proves
# validity; raw_uncompressed (which skips the subgroup check and accepts
# identity aggregates) reads but never writes it. ~15MB at capacity.
_RAW_PK_CACHE: "dict[bytes, bytes]" = {}
_RAW_PK_CACHE_MAX = 1 << 16
# inserts/evictions serialize: the chain pipeline fills this cache from
# the background verifier thread while the application thread reads and
# fills it too, and an unlocked FIFO evict (pop of the first iter key)
# races into KeyError. Reads stay lock-free — dict get is atomic.
_PK_CACHE_LOCK = threading.Lock()

# registry counters (docs/OBSERVABILITY.md): a cache "hit" is a raw-form
# lookup satisfied by _RAW_PK_CACHE, a "miss" is a lookup that fell
# through to an actual per-key decompression (deferred registry parses
# that stay cold are neither — their decompression is counted by the
# warm_raw_keys bulk counters when it happens eight-wide).
_CACHE_HITS = _metrics.counter("bls.pubkey_cache.hits")
_CACHE_MISSES = _metrics.counter("bls.pubkey_cache.misses")
_CACHE_INSERTS = _metrics.counter("bls.pubkey_cache.inserts")
_CACHE_EVICTIONS = _metrics.counter("bls.pubkey_cache.evictions")
_WARM_CALLS = _metrics.counter("bls.warm_raw_keys.calls")
_WARM_KEYS = _metrics.counter("bls.warm_raw_keys.keys")
_ROUTE_DEVICE = _metrics.counter("bls.pairing_route.device")
_ROUTE_HOST = _metrics.counter("bls.pairing_route.host")

# which route proved the most recent batched verification on THIS thread
# ("device" / "host" / None before any batch) — the flight recorder's
# per-flush-window verify_route source (pipeline/scheduler.py stamps it
# onto the window right after the worker's verify returns; the verifier
# is a single thread, so thread-locality is exactly window-locality)
_ROUTE_TL = threading.local()


def _note_pairing_route(choice: str, reason: str, n_sets: int) -> None:
    """Record one batch verification's route: the thread-local stamp
    (always — two writes), and the device observatory's routing journal
    with the threshold inputs (only while observing)."""
    _ROUTE_TL.route = choice
    if _device_obs.OBSERVATORY.active:
        _device_obs.route(
            "pairing",
            choice,
            reason,
            sets=n_sets,
            threshold=_device_flags.PAIRING_MIN_SETS,
        )


def last_batch_route() -> "str | None":
    """The route ("device"/"host") of the newest batched verification
    on the calling thread, or None if none ran (short batches and the
    per-set fallback verify host-side without the RLC batch)."""
    return getattr(_ROUTE_TL, "route", None)


# one-shot state for _device_decline: last exception type per decline
# kind, so a CHANGED failure cause re-arms the trace event (the mesh
# runtime's decline idiom) instead of the first cause masking the rest
_DECLINE_LOCK = threading.Lock()
_DECLINE_LAST: "dict[str, str]" = {}


def _device_decline(kind: str, exc: BaseException) -> None:
    """Journal one device-route decline: counter + routing journal +
    one-shot trace event (re-armed when the exception type changes).
    The device path swallowing an exception MUST NOT change verdicts —
    but it must not go dark either: a soak where every batch quietly
    falls back to the host pairing would otherwise read as healthy."""
    _metrics.counter(f"bls.device_decline.{kind}").inc()
    cause = type(exc).__name__
    if _device_obs.OBSERVATORY.active:
        _device_obs.route("bls_device", "host", kind, cause=cause)
    with _DECLINE_LOCK:
        armed = _DECLINE_LAST.get(kind) != cause
        _DECLINE_LAST[kind] = cause
    if armed:
        trace.event("bls.device_decline", kind=kind, cause=cause)


def _pk_cache_put(data: bytes, raw: bytes) -> None:
    with _PK_CACHE_LOCK:
        evicted = 0
        while len(_RAW_PK_CACHE) >= _RAW_PK_CACHE_MAX:
            try:
                _RAW_PK_CACHE.pop(next(iter(_RAW_PK_CACHE)))
                evicted += 1
            except (KeyError, StopIteration):  # pragma: no cover - defensive
                break
        _RAW_PK_CACHE[data] = raw
    _CACHE_INSERTS.inc()
    if evicted:
        _CACHE_EVICTIONS.inc(evicted)


def warm_pubkey_cache(keys) -> None:
    """Bulk-fill the decompressed-pubkey cache: every uncached key in
    ``keys`` (48-byte compressed) decompresses through the native
    eight-wide sqrt + subgroup chains in one call, so a following stream
    of PublicKey.from_bytes calls — a committee's attesters, a sync
    committee — is all cache hits. Invalid or identity keys are simply
    not cached; from_bytes raises the precise error when the key is
    actually used. No-op on the pure-Python backend."""
    if not _native():
        return
    todo = list(dict.fromkeys(
        bytes(k) for k in keys if bytes(k) not in _RAW_PK_CACHE
    ))
    if len(todo) < 8:  # below the lane width there is nothing to win
        return
    for rc_raw_inf, key in zip(
        native_bls.g1_decompress_batch(todo, check_subgroup=True), todo
    ):
        rc, raw, is_inf = rc_raw_inf
        if rc == 0 and not is_inf:
            _pk_cache_put(key, raw)


class PublicKey:
    """G1 point, 48-byte compressed. Infinity is rejected at parse time
    (blst key_validate semantics); an *aggregate* of valid keys may still
    be the identity (it then never verifies).

    Holds either a decoded G1Point, validated compressed bytes, or both;
    the point decodes lazily so the native fast path never pays for it.
    The decompressed affine form (``raw_uncompressed``) is cached after
    first use — decompression costs a field sqrt + subgroup check, and the
    chain workload re-verifies the same validator keys every block."""

    __slots__ = ("_point", "_bytes", "_raw")

    def __init__(self, point: G1Point):
        self._point = point
        self._bytes = None
        self._raw = None

    @classmethod
    def _from_valid_bytes(cls, data: bytes) -> "PublicKey":
        self = cls.__new__(cls)
        self._point = None
        self._bytes = bytes(data)
        self._raw = None
        return self

    def raw_uncompressed(self) -> bytes:
        """Affine x||y (96 bytes, big-endian), decompressed once and
        cached — on the instance, consulting the process-wide
        FIFO-evicted cache keyed by compressed bytes, because the chain
        workload rebuilds PublicKey objects from state bytes every block
        for the SAME validators. Native backend only (callers gate on
        it)."""
        if self._raw is None:
            data = self.to_bytes()
            hit = _RAW_PK_CACHE.get(data)
            if hit is not None:
                _CACHE_HITS.inc()
                self._raw = hit
                return hit
            _CACHE_MISSES.inc()
            rc, raw, is_inf = native_bls.g1_decompress(
                data, check_subgroup=False
            )
            if rc != 0:
                raise InvalidPublicKeyError(native_bls.decode_error_message(rc))
            self._raw = b"\x00" * 96 if is_inf else raw
            # deliberately NOT inserted into _RAW_PK_CACHE: this path
            # skips the subgroup check and accepts identity (aggregate
            # results are legitimately reachable here), so its entries
            # must never satisfy from_bytes' validation
        return self._raw

    @classmethod
    def from_validated_bytes(cls, data: bytes) -> "PublicKey":
        """Trusted parse for keys from a source that only admits valid
        keys — the beacon registry: a deposit whose pubkey is not a
        valid subgroup point cannot carry a valid deposit signature, so
        it never joins, and validator pubkeys are immutable afterwards.

        Skips the eager native decompression ``from_bytes`` pays; the
        affine form materializes lazily at verification time
        (``raw_uncompressed`` — stage B of the chain pipeline), where
        uncached keys go through the eight-wide bulk decompression
        (``warm_raw_keys``) instead of a per-key sqrt at collection
        time. Length and the infinity encoding are still rejected here
        (flag-byte check), so a corrupted registry fails loudly at the
        call site."""
        data = bytes(data)
        if len(data) != PUBLIC_KEY_SIZE:
            raise InvalidPublicKeyError(
                f"public key must be {PUBLIC_KEY_SIZE} bytes, got {len(data)}"
            )
        if data[0] & _INFINITY_FLAG:
            raise InvalidPublicKeyError("public key cannot be the identity")
        if not _native():
            return cls.from_bytes(data)  # no lazy raw path in the oracle
        self = cls._from_valid_bytes(data)
        self._raw = _RAW_PK_CACHE.get(data)
        if self._raw is not None:
            _CACHE_HITS.inc()
        return self

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        data = bytes(data)
        if len(data) != PUBLIC_KEY_SIZE:
            raise InvalidPublicKeyError(
                f"public key must be {PUBLIC_KEY_SIZE} bytes, got {len(data)}"
            )
        if _native():
            cached_raw = _RAW_PK_CACHE.get(data)
            if cached_raw is not None:
                # a cache hit was subgroup-checked when it entered
                _CACHE_HITS.inc()
                self = cls._from_valid_bytes(data)
                self._raw = cached_raw
                return self
            _CACHE_MISSES.inc()
            rc, raw, is_inf = native_bls.g1_decompress(data, check_subgroup=True)
            if rc != 0:
                raise InvalidPublicKeyError(native_bls.decode_error_message(rc))
            if is_inf:
                raise InvalidPublicKeyError("public key cannot be the identity")
            self = cls._from_valid_bytes(data)
            self._raw = raw
            _pk_cache_put(data, raw)
            return self
        try:
            point = G1Point.deserialize(data)
        except InvalidPointError as exc:
            raise InvalidPublicKeyError(str(exc)) from exc
        if point.is_infinity():
            raise InvalidPublicKeyError("public key cannot be the identity")
        return cls(point)

    @property
    def point(self) -> G1Point:
        if self._point is None:
            self._point = G1Point.deserialize(self._bytes)
        return self._point

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = self._point.serialize()
        return self._bytes

    def is_infinity(self) -> bool:
        if self._bytes is not None:
            return bool(self._bytes[0] & _INFINITY_FLAG)
        return self._point.is_infinity()

    def validate(self) -> None:
        if self.is_infinity():
            raise InvalidPublicKeyError("public key cannot be the identity")
        if self._point is not None:
            if not self._point.is_on_curve() or not self._point.in_subgroup():
                raise InvalidPublicKeyError("public key not in G1 subgroup")
        # bytes-only keys were subgroup-checked when parsed/constructed

    def __eq__(self, other) -> bool:
        return isinstance(other, PublicKey) and self.to_bytes() == other.to_bytes()

    def __hash__(self):
        # NB: bare `hash` in this module is the SHA-256 helper
        return self.to_bytes().__hash__()

    def __repr__(self) -> str:
        return f"PublicKey(0x{self.to_bytes().hex()})"


class Signature:
    """G2 point, 96-byte compressed. The identity encoding is accepted at
    parse time (it is needed for the eth_fast_aggregate_verify rule) but
    never verifies against a real message/pubkey pair."""

    __slots__ = ("_point", "_bytes", "_raw")

    def __init__(self, point: G2Point):
        self._point = point
        self._bytes = None
        self._raw = None

    @classmethod
    def _from_valid_bytes(cls, data: bytes) -> "Signature":
        self = cls.__new__(cls)
        self._point = None
        self._bytes = bytes(data)
        self._raw = None
        return self

    def raw_uncompressed(self) -> bytes:
        """Affine x.c0||x.c1||y.c0||y.c1 (192 bytes, big-endian), cached.
        Subgroup membership was established at parse time; all-zero for
        the identity. Native backend only (callers gate on it)."""
        if self._raw is None:
            rc, raw, is_inf = native_bls.g2_decompress(
                self.to_bytes(), check_subgroup=False
            )
            if rc != 0:
                raise InvalidSignatureError(native_bls.decode_error_message(rc))
            self._raw = b"\x00" * 192 if is_inf else raw
        return self._raw

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        data = bytes(data)
        if len(data) != SIGNATURE_SIZE:
            raise InvalidSignatureError(
                f"signature must be {SIGNATURE_SIZE} bytes, got {len(data)}"
            )
        if _native():
            rc, _raw, _is_inf = native_bls.g2_decompress(data, check_subgroup=True)
            if rc != 0:
                raise InvalidSignatureError(native_bls.decode_error_message(rc))
            return cls._from_valid_bytes(data)
        try:
            return cls(G2Point.deserialize(data))
        except InvalidPointError as exc:
            raise InvalidSignatureError(str(exc)) from exc

    @property
    def point(self) -> G2Point:
        if self._point is None:
            self._point = G2Point.deserialize(self._bytes)
        return self._point

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = self._point.serialize()
        return self._bytes

    def is_infinity(self) -> bool:
        if self._bytes is not None:
            return bool(self._bytes[0] & _INFINITY_FLAG)
        return self._point.is_infinity()

    def __eq__(self, other) -> bool:
        return isinstance(other, Signature) and self.to_bytes() == other.to_bytes()

    def __hash__(self):
        # NB: bare `hash` in this module is the SHA-256 helper
        return self.to_bytes().__hash__()

    def __repr__(self) -> str:
        return f"Signature(0x{self.to_bytes().hex()})"


# ---------------------------------------------------------------------------
# Verification primitives
# ---------------------------------------------------------------------------


def warm_raw_keys(public_keys) -> None:
    """Eight-wide bulk decompression for any keys whose affine form is
    not yet materialized — the verification-time complement of the
    deferred ``from_validated_bytes`` parse.

    Deliberately does NOT route through the process-wide cache: in the
    replay workload each attester key verifies once per epoch, so at
    registry scale the FIFO cache evicts a block's keys before they are
    ever reused — pure churn. The results land directly on the
    ``PublicKey`` instances instead. The subgroup check is skipped under
    the same contract as ``raw_uncompressed`` (these keys' membership is
    established by their source — the registry's deposit rule, or an
    earlier subgroup-checked parse); a key the batch cannot decompress is
    simply left cold, and the per-key path raises its precise error."""
    if not _native():
        return
    todo: "dict[bytes, list[PublicKey]]" = {}
    for pk in public_keys:
        if pk._raw is not None or pk._bytes is None:
            continue
        hit = _RAW_PK_CACHE.get(pk._bytes)
        if hit is not None:
            _CACHE_HITS.inc()
            pk._raw = hit
            continue
        todo.setdefault(pk._bytes, []).append(pk)
    if len(todo) < 8:  # below the lane width there is nothing to win
        return
    keys = list(todo)
    _WARM_CALLS.inc()
    _WARM_KEYS.inc(len(keys))
    for rc_raw_inf, key in zip(
        native_bls.g1_decompress_batch(keys, check_subgroup=False), keys
    ):
        rc, raw, is_inf = rc_raw_inf
        if rc == 0:
            raw = b"\x00" * 96 if is_inf else raw
            for pk in todo[key]:
                pk._raw = raw


def verify_signature(
    public_key: PublicKey, message: bytes, signature: Signature, dst: bytes = ETH_DST
) -> bool:
    """e(pk, H(m)) == e(g1, sig)  (bls.rs verify_signature)."""
    if _native():
        rc = native_bls.verify(
            public_key.to_bytes(), message, signature.to_bytes(), dst
        )
        if rc >= 0:
            return rc == 1
        # unparseable object (cannot happen for validated inputs): fall
        # through to the oracle for a defensive second opinion
    if signature.is_infinity() or public_key.is_infinity():
        return False
    h = hash_to_g2(message, dst)
    return pairing_product_is_one(
        [(public_key.point, h), (-G1_GENERATOR, signature.point)]
    )


def aggregate(signatures: list[Signature]) -> Signature:
    """Sum of signature points; errors on empty input (bls.rs aggregate)."""
    if not signatures:
        raise InvalidSignatureError("cannot aggregate zero signatures")
    if _native():
        rc, out = native_bls.aggregate_signatures([s.to_bytes() for s in signatures])
        if rc == 0:
            return Signature._from_valid_bytes(out)
        raise InvalidSignatureError(native_bls.decode_error_message(rc))
    acc = G2Point.infinity()
    for sig in signatures:
        acc = acc + sig.point
    return Signature(acc)


def aggregate_verify(
    public_keys: list[PublicKey],
    messages: list[bytes],
    signature: Signature,
    dst: bytes = ETH_DST,
) -> bool:
    """Π e(pk_i, H(m_i)) == e(g1, sig) (bls.rs aggregate_verify)."""
    if len(public_keys) != len(messages) or not public_keys:
        return False
    if _native():
        rc = native_bls.aggregate_verify(
            [pk.to_bytes() for pk in public_keys], messages,
            signature.to_bytes(), dst,
        )
        if rc >= 0:
            return rc == 1
    if signature.is_infinity():
        return False
    if any(pk.is_infinity() for pk in public_keys):
        return False
    pairs: list[tuple[G1Point, G2Point]] = [
        (pk.point, hash_to_g2(msg, dst))
        for pk, msg in zip(public_keys, messages)
    ]
    pairs.append((-G1_GENERATOR, signature.point))
    return pairing_product_is_one(pairs)


def fast_aggregate_verify(
    public_keys: list[PublicKey],
    message: bytes,
    signature: Signature,
    dst: bytes = ETH_DST,
) -> bool:
    """All keys sign the same message: aggregate the pubkeys, verify once
    (bls.rs fast_aggregate_verify:114).

    Large batches route the aggregation through the device G1 kernel
    (ops/g1.py log-depth limb fold) when installed — the O(N) piece; the
    single pairing stays native."""
    if not public_keys:
        return False
    if _native():
        if _device_flags.bls_agg_enabled(len(public_keys)):
            try:
                agg = _aggregate_on_device(public_keys)
            except Exception as exc:  # noqa: BLE001 — device trouble must not change verdicts
                _device_decline("fast_aggregate", exc)
                # fall through to the native path
            else:
                if agg is None:
                    return False  # identity aggregate never verifies
                return verify_signature(agg, message, signature, dst)
        # an identity pubkey in the list never verifies (PublicKey
        # semantics, bls.rs:114) — checked here because the raw path's
        # all-zero encoding would otherwise surface as a parse error
        if any(pk.is_infinity() for pk in public_keys):
            return False
        # cached raw affine keys skip the per-key decompression sqrt
        # (subgroup membership was established at parse time); deferred
        # registry parses bulk-decompress eight-wide here instead of
        # one-by-one below
        warm_raw_keys(public_keys)
        rc = native_bls.fast_aggregate_verify_raw(
            [pk.raw_uncompressed() for pk in public_keys], message,
            signature.to_bytes(), dst,
        )
        if rc >= 0:
            return rc == 1
    acc = G1Point.infinity()
    for pk in public_keys:
        acc = acc + pk.point
    return verify_signature(PublicKey(acc), message, signature, dst)


def _aggregate_on_device(public_keys: list[PublicKey]) -> "PublicKey | None":
    """Device pubkey aggregation; None when the sum is the identity (which
    can never verify) or the device path is unusable."""
    from ..ops import g1 as device_g1

    raws = [pk.raw_uncompressed() for pk in public_keys]
    raw_sum, is_inf = device_g1.aggregate_pubkeys_device(raws)
    if is_inf:
        return None
    agg = PublicKey._from_valid_bytes(native_bls.g1_compress_raw(raw_sum))
    agg._raw = raw_sum
    return agg


def eth_aggregate_public_keys(public_keys: list[PublicKey]) -> PublicKey:
    """Spec `eth_aggregate_pubkeys` (bls.rs eth_aggregate_public_keys:135):
    errors on empty input or invalid keys; the aggregate may legitimately be
    used for sync-committee processing."""
    if not public_keys:
        raise InvalidPublicKeyError("cannot aggregate zero public keys")
    if _native():
        rc, out = native_bls.aggregate_public_keys(
            [pk.to_bytes() for pk in public_keys]
        )
        if rc == 0:
            return PublicKey._from_valid_bytes(out)
        raise InvalidPublicKeyError(native_bls.decode_error_message(rc))
    acc = G1Point.infinity()
    for pk in public_keys:
        pk.validate()
        acc = acc + pk.point
    return PublicKey(acc)


def eth_fast_aggregate_verify(
    public_keys: list[PublicKey],
    message: bytes,
    signature: Signature,
    dst: bytes = ETH_DST,
) -> bool:
    """Spec `eth_fast_aggregate_verify` (bls.rs:150): returns True for an
    empty key list when the signature is the G2 identity encoding (the
    sync-aggregate "no participants" rule), otherwise defers to
    fast_aggregate_verify."""
    if not public_keys and signature.is_infinity():
        return True
    return fast_aggregate_verify(public_keys, message, signature, dst)


# ---------------------------------------------------------------------------
# Batched verification (the device/batch boundary: SURVEY.md §2.5, §7)
# ---------------------------------------------------------------------------


class SignatureSet:
    """One verification claim: `signature` is a valid aggregate signature by
    `public_keys` over `message` (fast_aggregate_verify semantics). The unit
    the state transition batches — proposer/randao/attestations/sync sets
    from one block become one multi-pairing."""

    __slots__ = ("public_keys", "message", "signature")

    def __init__(self, public_keys: list[PublicKey], message: bytes,
                 signature: Signature):
        self.public_keys = list(public_keys)
        self.message = bytes(message)
        self.signature = signature

    def verify(self, dst: bytes = ETH_DST) -> bool:
        return fast_aggregate_verify(
            self.public_keys, self.message, self.signature, dst
        )


def _batch_all_valid(sets: list[SignatureSet], dst: bytes) -> bool:
    """One RLC multi-pairing over every set (native backend only).

    When the device G1 kernels are installed and the batch carries enough
    keys, every set's pubkey aggregation runs as ONE segmented device fold
    (ops/g1.py) and the native multi-pairing sees single-key sets — the
    device owns the O(total keys) work, the host the O(sets) pairings."""
    # deferred registry parses (from_validated_bytes) materialize here,
    # through the eight-wide bulk path — in the chain pipeline this is
    # stage B, off the block-application critical path
    warm_raw_keys(pk for s in sets for pk in s.public_keys)
    total_keys = sum(len(s.public_keys) for s in sets)
    if _device_flags.bls_agg_enabled(total_keys):
        try:
            from ..ops import g1 as device_g1

            agg = device_g1.aggregate_pubkey_sets_device(
                [[pk.raw_uncompressed() for pk in s.public_keys] for s in sets]
            )
        except Exception:  # noqa: BLE001 — device trouble must not change verdicts
            agg = None
        if agg is not None:
            if any(is_inf for _, is_inf in agg):
                return False  # an identity aggregate never verifies
            new_sets = []
            for (raw, _), s in zip(agg, sets):
                pk = PublicKey._from_valid_bytes(native_bls.g1_compress_raw(raw))
                pk._raw = raw  # already affine — don't re-pay the sqrt
                new_sets.append(SignatureSet([pk], s.message, s.signature))
            sets = new_sets
    scalars = [(1).to_bytes(16, "big")]
    for _ in range(len(sets) - 1):
        while True:
            s = secrets.token_bytes(16)
            if any(s):
                break
        scalars.append(s)
    device_declined = False
    if _device_flags.pairing_enabled(len(sets)):
        verdict = _batch_device_pairing(sets, dst, scalars)
        if verdict is not None:
            _ROUTE_DEVICE.inc()
            _note_pairing_route("device", "routed", len(sets))
            return verdict
        device_declined = True
    # raw-affine pubkeys: decompressed once per key (cached on the
    # PublicKey — subgroup-checked at parse time), so repeat verifiers
    # (the same validators every block) never pay the sqrt again
    _ROUTE_HOST.inc()
    _note_pairing_route(
        "host",
        (
            "device_unusable"
            if device_declined
            else (
                "not_installed"
                if _device_flags.PAIRING_MIN_SETS is None
                else "below_threshold"
            )
        ),
        len(sets),
    )
    return native_bls.batch_verify_raw(
        [([pk.raw_uncompressed() for pk in s.public_keys], s.message,
          s.signature.to_bytes()) for s in sets],
        dst,
        scalars,
    )


def _batch_device_pairing(
    sets: list[SignatureSet], dst: bytes, scalars: list[bytes]
) -> "bool | None":
    """The device pairing route for the RLC batch: per-set pubkey
    aggregation as ONE segmented device fold (ops/g1.py), native
    hash_to_g2 per message, then blinder mults + N+1 Miller loops + the
    Fq12 product on device (ops/pairing.py) with the native final-exp
    verdict. None = device unusable, caller falls back; False verdicts
    are exact (same RLC soundness as the native batch)."""
    try:
        from ..ops import pairing as device_pairing
    except Exception:  # noqa: BLE001 — no jax, no device route
        return None
    try:
        pk_raws = []
        if any(len(s.public_keys) > 1 for s in sets):
            # multi-key sets: ONE segmented device fold aggregates every
            # set at once (ops/g1.py) — the device owns the O(total keys)
            # work; a serial host add loop here would cost O(keys) point
            # adds before the device saw anything (512 for a sync
            # aggregate, altair/block_processing.rs:192-243)
            from ..ops import g1 as device_g1

            agg = device_g1.aggregate_pubkey_sets_device(
                [[pk.raw_uncompressed() for pk in s.public_keys]
                 for s in sets]
            )
            if any(is_inf for _, is_inf in agg):
                return False  # an identity aggregate never verifies
            pk_raws = [raw for raw, _ in agg]
        else:
            pk_raws = [s.public_keys[0].raw_uncompressed() for s in sets]
        h_raws = []
        for s in sets:
            h_c = native_bls.hash_to_g2_compressed(s.message, dst)
            rc, raw, _ = native_bls.g2_decompress(h_c, check_subgroup=False)
            if rc != 0:
                return None
            h_raws.append(raw)
        sig_raws = []
        for s in sets:
            if s.signature.is_infinity():
                return False  # an identity signature never verifies
            sig_raws.append(s.signature.raw_uncompressed())
        blinders = [int.from_bytes(sc, "big") for sc in scalars]
        import jax

        from ..parallel import runtime as _mesh_runtime

        # the provisioned ECT_MESH mesh owns the sharded route (with its
        # engage/decline journal); without one, any multi-device backend
        # still shards over the default mesh (the dryrun_multichip shape)
        mesh = _mesh_runtime.pairing_mesh(len(sets))
        if mesh is None and len(jax.devices()) > 1:
            # multi-chip: the set axis shards over the mesh (SURVEY §2.5)
            from ..parallel.mesh import default_device_mesh

            mesh = default_device_mesh()
        if mesh is not None:
            from ..parallel.pairing import batch_verify_sharded

            return batch_verify_sharded(
                pk_raws, h_raws, sig_raws, blinders, mesh=mesh
            )
        return device_pairing.batch_verify_device(
            pk_raws, h_raws, sig_raws, blinders
        )
    except Exception as exc:  # noqa: BLE001 — device trouble must not change verdicts
        _device_decline("pairing", exc)
        return None


def verify_signature_sets(
    sets: list[SignatureSet], dst: bytes = ETH_DST
) -> list[bool]:
    """Verdicts for N independent signature sets.

    Native path: one random-linear-combination multi-pairing proves all N
    at once (N+1 Miller loops, one shared final exponentiation). On
    failure, blame is attributed by verifying each set directly —
    ``SignatureSet.verify`` already aggregates multi-key sets in one
    native pass (and rejects identity pubkeys/empty keysets cleanly), so
    no pre-aggregation here can save work. Bisection-style batch probing
    was tried and measured a wash-to-loss here: a probe over m sets pays
    the same per-set hash_to_g2 + Miller work a direct verify pays, so
    the only sharing is the final exponentiation, which the probe ladder
    re-spends on overlapping ranges. A forged set passes the blinded
    batch with probability <= 2^-128."""
    if not sets:
        return []
    # each batched verification re-stamps the thread-local route below;
    # clearing first means "no RLC batch ran" is distinguishable (the
    # single-set and blame-attribution paths verify host-side per set)
    _ROUTE_TL.route = None
    if _native() and len(sets) > 1 and _batch_all_valid(sets, dst):
        return [True] * len(sets)
    return [s.verify(dst) for s in sets]


# ---------------------------------------------------------------------------
# Async dispatch (the chain pipeline's stage-B hook, pipeline/scheduler.py)
# ---------------------------------------------------------------------------

_VERIFY_POOLS: dict = {}
# double-checked creation: two racing first-dispatchers would otherwise
# build TWO single-thread pools for one lane — and the pipeline's
# windows-settle-FIFO guarantee (per lane) only holds when every dispatch
# to a lane queues behind the SAME worker
_VERIFY_POOL_LOCK = threading.Lock()


def _verify_pool(lane: int = 0):
    """One process-wide single-thread verifier PER LANE. One worker per
    lane on purpose: dispatches within a lane complete FIFO, and the
    pairing engines underneath (native ctypes — which releases the GIL
    for the whole multi-pairing — or the device route) each already own
    their parallelism. Lane 0 is the historical single verifier (the
    pool's flushes and unconfigured pipelines land there); the pipeline
    scheduler fans windows over N lanes deterministically
    (``seq % verify_lanes``, pipeline/scheduler.py) so a multi-core host
    proves N windows CONCURRENTLY — the GIL-released native pairing
    makes that real parallelism — while the engine's settle-oldest order
    keeps commits in chain order regardless of which lane finishes
    first."""
    pool = _VERIFY_POOLS.get(lane)
    if pool is None:
        with _VERIFY_POOL_LOCK:
            pool = _VERIFY_POOLS.get(lane)
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix=f"bls-verify-{lane}"
                )
                _VERIFY_POOLS[lane] = pool
    return pool


def verify_signature_sets_async(
    sets: list[SignatureSet], dst: bytes = ETH_DST, timer=None, pre=None,
    route_sink=None, lane: int = 0, trace_ctx=None,
):
    """Dispatch one batched verification to the background verifier thread;
    returns a ``concurrent.futures.Future[list[bool]]``.

    The host thread keeps mutating state (SSZ writes, incremental HTR)
    while the multi-pairing runs: the native batch call is a single ctypes
    entry that releases the GIL for its whole duration, so the overlap is
    real CPU parallelism, not just interleaving. ``timer``, if given, is
    called on the worker with the verification's duration in seconds —
    the pipeline's stage-occupancy probe. ``pre``, if given, runs on the
    worker immediately before verification (the pipeline's fault-injection
    seam, pipeline/faults.py); anything it raises surfaces through the
    future exactly as a real worker fault would. ``route_sink``, if
    given, is called on the worker after verification with the batch's
    pairing route ("device"/"host"/None — ``last_batch_route``), the
    flight recorder's per-window verify_route feed. ``lane`` picks the
    single-thread verifier worker (default 0 — the historical shared
    worker); batches dispatched to different lanes verify CONCURRENTLY,
    batches on one lane stay FIFO. ``trace_ctx``, if given, is the
    caller's causal handoff token (utils/trace TraceContext): the worker
    adopts it so the verify span parents under the dispatching window's
    trace across the thread seam (a cross-lane flow arrow in the Chrome
    trace) instead of rooting its own tree."""
    sets = list(sets)

    def run() -> list[bool]:
        import time as _time

        t0 = _time.perf_counter()
        try:
            if pre is not None:
                pre()
            # the span lands on the verifier thread's lane, so a recorded
            # pipeline run shows stage B as its own Perfetto track —
            # linked under trace_ctx's trace when the caller passed one
            with trace.adopt(trace_ctx):
                with trace.span("pipeline.flush.verify", sets=len(sets)):
                    verdicts = verify_signature_sets(sets, dst)
            if route_sink is not None:
                route_sink(last_batch_route())
            return verdicts
        finally:
            if timer is not None:
                timer(_time.perf_counter() - t0)

    return _verify_pool(lane).submit(run)
