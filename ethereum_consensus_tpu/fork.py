"""Fork tag used throughout the polymorphic layers.

Reference parity: ethereum-consensus/src/fork.rs:6-13.
"""

from __future__ import annotations

from enum import IntEnum


class Fork(IntEnum):
    PHASE0 = 0
    ALTAIR = 1
    BELLATRIX = 2
    CAPELLA = 3
    DENEB = 4
    ELECTRA = 5

    def __str__(self) -> str:
        return self.name.lower()

    @classmethod
    def from_str(cls, name: str) -> "Fork":
        return cls[name.upper()]

    @property
    def previous(self) -> "Fork | None":
        return None if self is Fork.PHASE0 else Fork(self.value - 1)
